"""Abstract lowering of the composed train step — shared by the analyzers.

`jax.jit(...).lower()` on ShapeDtypeStructs traces and lowers the exact
program a real run would execute, without materializing a single array or
touching an accelerator: the same recipe tools/memcheck.py uses for memory
estimates, here reused to hand the collective-schedule and hazard analyzers
the StableHLO text plus the abstract (state, batch) the arg list refers to.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LoweredStep(NamedTuple):
    step_fn: object      # the jitted step (for eval_shape-level checks)
    lowered: object      # jax Lowered
    text: str            # StableHLO module text
    state: object        # abstract TrainState
    batch: tuple         # abstract (ids, targets)
    jaxpr: object = None  # ClosedJaxpr pre-lowering (provenance analysis);
    #                       None when this JAX lacks jit(...).trace


def abstract_batch(cfg, menv):
    t = cfg.training
    b = (t.micro_batch_size * cfg.distributed.dp_size
         * cfg.distributed.ep_size)
    ids = jax.ShapeDtypeStruct(
        (t.gradient_accumulation_steps, b, t.seq_length), jnp.int32,
        sharding=menv.batch_sharding())
    return (ids, ids)


def lower_train_step(cfg, menv=None) -> LoweredStep:
    """Build + lower the config's train step on an abstract mesh. Requires
    enough local (simulated) devices for cfg's world size — the CLI forces
    a host-device count first, exactly like tools/memcheck.py."""
    import dataclasses

    from picotron_tpu.config import PipelineConfig
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step

    cfg.validate()
    if cfg.pipeline.executor == "mpmd":
        # The MPMD executor is a host-side schedule walker over per-stage
        # programs — there is no single jit to lower. Trace-level checks
        # (collectives, provenance, donation, stability) run on its SPMD
        # twin: same math, one program. The per-stage compile-once claim
        # is proven separately by variants.prove_mpmd_stages.
        cfg = dataclasses.replace(cfg, pipeline=PipelineConfig())
    menv = menv if menv is not None else MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0), abstract=True)
    step = make_train_step(cfg, menv)
    batch = abstract_batch(cfg, menv)
    # one trace serves both consumers: the jaxpr (sharding-dataflow
    # provenance, analysis/dataflow.py) and the lowering (HLO-text checks)
    jaxpr = None
    if hasattr(step, "trace"):
        traced = step.trace(state, batch)
        jaxpr = traced.jaxpr
        lowered = traced.lower()
    else:  # older JAX: no Traced stage — lower directly, skip provenance
        lowered = step.lower(state, batch)
    return LoweredStep(step, lowered, lowered.as_text(), state, batch,
                       jaxpr)
