"""ICI-topology communication cost model — price a layout's step on CPU.

The collective-schedule audit (analysis/collectives.py) says *which*
collectives a dp×tp×pp×cp×ep layout emits; this module says *what they
cost*, so layouts can be ranked by predicted step time without touching
hardware — the ATP (arxiv 2301.08658) / TASP (arxiv 2509.26541) approach:
a static per-axis topology model is enough to order layouts, which turns
"which layout for model X on slice Y?" into a CPU query.

Three parts:

- **Topology** (`IciGeneration`, `place_axes`): per-TPU-generation link
  bandwidth, physical torus dimensionality, and wraparound rule. Mesh axes
  are placed innermost-first (tp, cp, ep, pp, dp) onto physical ICI axes —
  the same contract mesh.py's `_topology_grid` encodes — so tp gets a
  dedicated ring and outer axes fold (modeled as a bandwidth divide by the
  neighbor stride). An axis big enough for wraparound is a **ring**
  (bidirectional, diameter n//2); smaller slices are a **line** (no wrap,
  diameter n-1) — the v5e-vs-v5p distinction the hop-count tests pin.
- **Per-collective formulas** (`collective_secs`): bandwidth-term costs of
  the standard ring algorithms (all-reduce 2·(n-1)/n·V, all-gather /
  reduce-scatter (n-1)/n·V, all-to-all n/8·V per direction, neighbor
  ppermute V) plus an α·hops latency term, per axis placement. `price_ops`
  applies them to the `CollectiveOp` list parsed off a traced schedule.
- **Step model** (`CostModel.predict`): the analytic whole-step time —
  compute (calibrated dense/attention efficiencies), the executor-dependent
  pipeline bubble (spmd lockstep 2(pp-1)/ga; mpmd (pp-1)/(v·ga) plus
  host-dispatch), optimizer-offload PCIe streaming, and the per-class
  comm terms with exposed-fraction weights (a grad all-reduce overlaps the
  backward; an in-layer TP psum does not). Constants live in `Calibration`
  and are fitted against the measured SWEEP/BENCH rows on disk by
  analysis/calibration.py — the model's job is *ranking*, and the fitted
  defaults reproduce the measured per-round sweep orderings (Spearman ≥
  0.9, pinned in tests/test_cost_model.py).

Everything here is pure arithmetic on a Config — no jax device calls — so
it runs in a preflight, a report CLI, or a 300-point planner sweep in
milliseconds.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Optional

from picotron_tpu.config import (
    Config, num_params, parse_tp_strategy, resolved_cp_flavor,
    resolved_cp_mesh, resolved_tp_mesh,
)
from picotron_tpu.utils import flops_per_token

# ---------------------------------------------------------------------------
# TPU generations — ICI topology + link/HBM/peak constants.
#
# Bandwidths are per-link per-direction, derived from the published
# aggregate ICI figures (v5e 1600 Gb/s over 4 links; v5p 4800 Gb/s over 6;
# v4 2400 Gb/s over 6) de-rated ~10% for protocol overhead. wrap_min is
# the smallest axis size modeled with wraparound links: v5e sub-slices of
# its 16x16 2D torus are meshes (lines) until a full 16-ring; v5p/v4 3D
# slices get wraparound from a full side of 4. HBM is per chip.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class IciGeneration:
    name: str
    phys_axes: int          # independent torus dims a logical axis can own
    link_bandwidth: float   # bytes/s per link per direction
    wrap_min: int           # smallest axis size that closes into a ring
    hbm_gib: float          # per-chip HBM capacity
    peak_flops: float       # per-chip bf16 peak FLOP/s
    pcie_bandwidth: float   # host<->device streaming bw (offload); see
                            # Calibration — fitted, this is the fallback
    # -- the dcn tier (multi-slice scale-out) -----------------------------
    # Per-slice-exit DCN bandwidth per direction. Slices connect through
    # the data-center network at per-host NIC rates aggregated across the
    # slice boundary — order 50-100 Gb/s per host vs 360-800 Gb/s per
    # chip of ICI. Analytic defaults (derated published figures) awaiting
    # on-TPU multi-slice validation; PERF.md round 16 has the protocol.
    dcn_bandwidth: float = 6.25e9   # bytes/s across the cut per direction
    dcn_alpha_s: float = 2.0e-5     # per-transfer DCN latency (vs 1 µs ICI)


GENERATIONS: dict[str, IciGeneration] = {
    "v4": IciGeneration("v4", 3, 45e9, 4, 32.0, 275e12, 7e9,
                        6.25e9, 2.0e-5),
    "v5e": IciGeneration("v5e", 2, 45e9, 16, 16.0, 197e12, 7e9,
                         6.25e9, 2.0e-5),
    "v5p": IciGeneration("v5p", 3, 90e9, 4, 95.0, 459e12, 7e9,
                         12.5e9, 2.0e-5),
    "v6e": IciGeneration("v6e", 2, 100e9, 16, 32.0, 918e12, 7e9,
                         12.5e9, 2.0e-5),
}


def resolve_generation(name_or_kind: str) -> IciGeneration:
    """Generation from a config string ('v5e') or a jax device_kind
    ('TPU v5 lite', 'TPU v5p'); unknown kinds (the CPU test platform)
    default to v5e, matching utils.device_peak_flops."""
    k = name_or_kind.lower()
    if k in GENERATIONS:
        return GENERATIONS[k]
    if "v6" in k or "trillium" in k:
        return GENERATIONS["v6e"]
    if "v5 lite" in k or "v5lite" in k or "v5e" in k:
        return GENERATIONS["v5e"]
    if "v5" in k:
        return GENERATIONS["v5p"]
    if "v4" in k:
        return GENERATIONS["v4"]
    return GENERATIONS["v5e"]


# ---------------------------------------------------------------------------
# Hop counts + axis placement
# ---------------------------------------------------------------------------


def ring_diameter(n: int) -> int:
    """Max hop distance on a bidirectional ring of n chips."""
    return n // 2


def line_diameter(n: int) -> int:
    """Max hop distance on a line (torus slice without wraparound)."""
    return max(n - 1, 0)


@dataclass(frozen=True)
class AxisLink:
    """One mesh axis' modeled ICI placement."""

    axis: str
    size: int
    kind: str          # "ring" | "line"
    bandwidth: float   # effective bytes/s per direction for this axis
    stride: int        # physical hops between logical neighbors (folding)

    @property
    def diameter(self) -> int:
        d = (ring_diameter(self.size) if self.kind == "ring"
             else line_diameter(self.size))
        return d * self.stride

    @property
    def directions(self) -> int:
        # a ring algorithm can stream both ways; a line effectively one
        return 2 if self.kind == "ring" else 1


# placement priority: innermost (most comm-hungry) first — mirrors the
# AXES = (dp, pp, ep, cp, tp) ordering contract in mesh.py, reversed
PLACEMENT_ORDER = ("tp", "cp", "ep", "pp", "dp")


def place_axes(axis_sizes: dict, gen: IciGeneration) -> dict[str, AxisLink]:
    """Model the logical→physical axis assignment: the first `phys_axes`
    non-trivial axes (innermost first) each own a torus dimension at full
    link bandwidth; later axes fold over already-used dimensions, paying a
    neighbor stride equal to the product of the sizes sharing their
    dimension (a folded neighbor hop traverses that many links)."""
    out: dict[str, AxisLink] = {}
    nontrivial = [a for a in PLACEMENT_ORDER if axis_sizes.get(a, 1) > 1]
    dim_load = [1] * max(gen.phys_axes, 1)
    for i, ax in enumerate(nontrivial):
        n = axis_sizes[ax]
        dim = i % len(dim_load)
        stride = dim_load[dim] if i >= len(dim_load) else 1
        dim_load[dim] *= n
        kind = "ring" if n >= gen.wrap_min else "line"
        out[ax] = AxisLink(ax, n, kind,
                           gen.link_bandwidth / max(stride, 1), stride)
    return out


def split_cp_link(link: AxisLink, cp_x: int, cp_y: int,
                  gen: IciGeneration) -> tuple[AxisLink, AxisLink]:
    """Factor one placed cp AxisLink into the mesh flavor's 2D submesh:
    (outer cp_x row-ring link, inner cp_y head-scatter link).

    The inner sub-axis is a contiguous slice of the physical placement, so
    its logical-neighbor stride is the parent's and it closes into a ring
    by the generation's own wrap rule (a cp_y-slice of a v5e 16-torus side
    is a line; a full side is a ring). The outer sub-axis hops cp_y
    physical neighbors per logical step — and all cp_y row rings shift
    concurrently over the same links, so each pair sees 1/cp_y of the
    parent bandwidth — but it inherits the parent's wraparound: if the
    full cp axis closes, the stride-cp_y cycle closes with it. This is the
    TASP-style observation that makes mesh win on wrap-less slices: the
    ring leg shrinks from cp-1 line hops to cp_x-1, while the a2a leg
    stays inside a short contiguous subgroup."""
    inner_kind = "ring" if cp_y >= gen.wrap_min else "line"
    inner = AxisLink(link.axis, cp_y, inner_kind, link.bandwidth, link.stride)
    outer_kind = link.kind if cp_x > 1 else "line"
    outer = AxisLink(link.axis, cp_x, outer_kind,
                     link.bandwidth / max(cp_y, 1), link.stride * cp_y)
    return outer, inner


def split_slice_link(link: AxisLink, n_slices: int,
                     gen: IciGeneration) -> tuple[AxisLink, AxisLink]:
    """Factor one placed DCN-crossing axis into its hierarchical tiers:
    (intra-slice ICI sub-link of size n/slices, inter-slice DCN link of
    size slices). The intra leg keeps the parent's bandwidth/stride and
    re-derives its wrap rule from the shrunk size; the DCN leg is modeled
    as a bidirectional ring of slices at the generation's dcn_bandwidth
    (slice interconnects are switched, so a ring is the conservative
    shape). Mirrors split_cp_link's role for the mesh cp flavor — the
    slice-boundary analogue of the TASP follow-the-network split."""
    m = max(link.size // max(n_slices, 1), 1)
    intra = AxisLink(link.axis, m,
                     "ring" if m >= gen.wrap_min else "line",
                     link.bandwidth, link.stride)
    dcn = AxisLink(f"{link.axis}@dcn", n_slices, "ring",
                   gen.dcn_bandwidth, 1)
    return intra, dcn


# ---------------------------------------------------------------------------
# Calibration constants
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Calibration:
    """Constants the measured rows on disk pin down (analysis/calibration.py
    fits eff_max / h_half / eff_attn / pcie_bandwidth against the
    SWEEP_r03–r05 + BENCH step times; the defaults below ARE that fit).
    The exposure fractions and link latency are analytic defaults awaiting
    on-TPU validation — PERF.md documents the protocol."""

    # dense-matmul efficiency saturates with hidden size:
    #   eff_dense(h) = min(eff_max * h / (h + h_half), eff_cap)
    eff_max: float = 1.07
    h_half: float = 1280.0
    eff_cap: float = 0.92
    # flash-attention FLOPs run below the matmul peak (softmax/mask
    # overhead, shorter arithmetic chains)
    eff_attn: float = 0.40
    # achieved host<->device streaming bandwidth for optimizer offload
    # (fitted: the r05 offload rows' residual over their compute term)
    pcie_bandwidth: float = 5.6e9
    # per-link-hop latency (collective setup + hop): the α in α + V/B
    alpha_link_s: float = 1.0e-6
    # fraction of each comm class NOT hidden under compute
    expose_grad: float = 0.35   # grad all-reduce overlaps the backward
    expose_pp: float = 0.5      # boundary ppermute overlaps the 1f1b scan
    # MPMD executor: host-side cost of dispatching one per-stage program
    # (schedule-table walk + jit cache hit + device_put enqueue). Replaces
    # the SPMD scan's full-priced idle tick — the r4 intercept said an
    # SPMD idle tick costs ~a traced unit (~64.7 ms); a host dispatch is
    # ~0.2 ms. Analytic default awaiting --pp-tick-sweep calibration.
    host_dispatch_s: float = 2.0e-4
    expose_layer: float = 1.0   # in-layer tp/sp/cp/ep collectives serialize
    # deferred tp_sync (parallel/tp_strategies.py): the reduce-scatter at a
    # block's exit still serializes, but its gather half is hoisted to the
    # NEXT block's entry where it overlaps that block's norm + qkv/gate
    # matmul issue window — only this fraction of the all-gather stays
    # exposed. Analytic default awaiting on-TPU validation (PERF.md r15).
    expose_deferred: float = 0.55
    # step-FLOPs multiplier per remat policy (recompute overhead), relative
    # to "dots" whose overhead the efficiency fit absorbs
    remat_flops: tuple = (("full", 1.30), ("dots", 1.0),
                          ("dots_attn", 1.07), ("dots_lean", 1.12),
                          ("dots_norms", 0.98), ("dots_offload", 1.07))

    def remat_multiplier(self, policy: str, remat: bool) -> float:
        if not remat:
            return 1.0
        return dict(self.remat_flops).get(policy, 1.0)


DEFAULT_CALIBRATION = Calibration()

_DTYPE_BYTES = {"bfloat16": 2, "float16": 2, "float32": 4}


# ---------------------------------------------------------------------------
# Cost terms
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CommTerm:
    """One class of collective traffic in a step's schedule."""

    name: str          # e.g. "grad_sync", "tp_psum", "cp_ring"
    kind: str          # a collectives.KINDS member
    axes: tuple        # mesh axes the op spans
    count: int         # ops per step
    bytes_each: float  # payload bytes per op (full logical tensor)
    secs_each: float   # predicted seconds per op
    exposed_frac: float

    @property
    def secs_total(self) -> float:
        return self.secs_each * self.count

    @property
    def secs_exposed(self) -> float:
        return self.secs_total * self.exposed_frac


@dataclass(frozen=True)
class StepCost:
    """Predicted decomposition of one optimizer step."""

    config_label: str
    generation: str
    n_chips: int
    tokens_per_step: int
    compute_s: float
    bubble_s: float      # pipeline bubble: spmd 2(pp-1)/ga of compute;
    #                      mpmd (pp-1)/(v*ga) + host dispatch
    offload_s: float     # optimizer-offload PCIe streaming
    comm: tuple          # CommTerm, ...

    @property
    def comm_s(self) -> float:
        return sum(t.secs_total for t in self.comm)

    @property
    def exposed_comm_s(self) -> float:
        return sum(t.secs_exposed for t in self.comm)

    @property
    def total_s(self) -> float:
        return (self.compute_s + self.bubble_s + self.offload_s
                + self.exposed_comm_s)

    @property
    def tokens_per_sec(self) -> float:
        return self.tokens_per_step / self.total_s

    @property
    def tokens_per_sec_per_chip(self) -> float:
        return self.tokens_per_sec / self.n_chips

    def as_dict(self) -> dict:
        return {
            "config": self.config_label,
            "generation": self.generation,
            "n_chips": self.n_chips,
            "tokens_per_step": self.tokens_per_step,
            "predicted_step_ms": round(self.total_s * 1e3, 3),
            "compute_ms": round(self.compute_s * 1e3, 3),
            "bubble_ms": round(self.bubble_s * 1e3, 3),
            "offload_ms": round(self.offload_s * 1e3, 3),
            "comm_ms": round(self.comm_s * 1e3, 3),
            "exposed_comm_ms": round(self.exposed_comm_s * 1e3, 3),
            "tokens_per_sec": round(self.tokens_per_sec, 1),
            "tokens_per_sec_per_chip": round(self.tokens_per_sec_per_chip,
                                             1),
            "comm_terms": {t.name: round(t.secs_total * 1e3, 3)
                           for t in self.comm},
        }


# ---------------------------------------------------------------------------
# The model
# ---------------------------------------------------------------------------


class CostModel:
    """Price collectives and whole steps for one TPU generation."""

    def __init__(self, generation="v5e",
                 calibration: Calibration = DEFAULT_CALIBRATION):
        self.gen = (generation if isinstance(generation, IciGeneration)
                    else resolve_generation(generation))
        self.calib = calibration

    # -- per-collective ----------------------------------------------------

    def collective_secs(self, kind: str, nbytes: float,
                        link: AxisLink, alpha: float = None) -> float:
        """Seconds for one collective of `kind` moving `nbytes` (the full
        logical tensor for group collectives; the per-device payload for a
        ppermute shift) over one placed axis. `alpha` overrides the
        per-hop latency (the dcn tier's is ~20x the ICI default)."""
        n, bw = link.size, link.bandwidth
        if n <= 1 or nbytes <= 0:
            return 0.0
        dirs = link.directions
        if alpha is None:
            alpha = self.calib.alpha_link_s
        if kind == "all_gather" or kind == "reduce_scatter":
            return nbytes * (n - 1) / n / (dirs * bw) + alpha * (n - 1)
        if kind == "all_reduce":
            return 2 * nbytes * (n - 1) / n / (dirs * bw) + alpha * (n - 1)
        if kind == "all_to_all":
            # mean pair distance n/4 on a ring (n/2 on a line) x per-pair
            # V/n payloads crossing shared links
            return nbytes * n / (4 * dirs * bw) + alpha * (n - 1)
        if kind == "collective_permute":
            # neighbor shift: every link carries one payload; on a line
            # the wraparound message re-crosses the whole slice
            hops = 1 if link.kind == "ring" else max(n - 1, 1)
            return nbytes * hops / bw + alpha * hops
        raise ValueError(f"unknown collective kind {kind!r}")

    def axes_for(self, cfg: Config) -> dict[str, AxisLink]:
        d = cfg.distributed
        return place_axes({"dp": d.dp_size, "pp": d.pp_size,
                           "ep": d.ep_size, "cp": d.cp_size,
                           "tp": d.tp_size}, self.gen)

    # -- the dcn tier -----------------------------------------------------

    def dcn_link(self, n_slices: int) -> AxisLink:
        """The inter-slice DCN 'axis': a ring of slices at the
        generation's dcn_bandwidth."""
        return AxisLink("dcn", n_slices, "ring", self.gen.dcn_bandwidth, 1)

    def dcn_secs(self, kind: str, nbytes: float, n_slices: int) -> float:
        """Seconds for one collective leg crossing the slice cut — same
        ring formulas as ICI, at the dcn tier's bandwidth and latency."""
        return self.collective_secs(kind, nbytes, self.dcn_link(n_slices),
                                    alpha=self.gen.dcn_alpha_s)

    def slice_tiers(self, cfg: Config, n_slices: int, axis: str) -> dict:
        """Price the predicted step comm under a slice cut on `axis`
        (one of the DCN-tolerant axes, dp or pp): comm terms spanning the
        axis are re-priced hierarchically — wide legs on the intra-slice
        ICI sub-link, a shard-per-slice leg on the dcn tier — and
        everything else stays on its placed ICI link. Returns the per-tier
        split the planner renders: which axis should absorb the slice
        granules falls out of comparing these rows."""
        cost = self.predict(cfg)
        links = self.axes_for(cfg)
        d = cfg.distributed
        axis_size = {"dp": d.dp_size, "pp": d.pp_size}.get(axis, 1)
        ici_s = dcn_s = 0.0
        dcn_bytes = 0.0
        crossing = []
        for t in cost.comm:
            if axis not in t.axes or axis not in links:
                ici_s += t.secs_total
                continue
            crossing.append(t.name)
            intra, dcn = split_slice_link(links[axis], n_slices, self.gen)
            other_s = sum(self.collective_secs(t.kind, t.bytes_each,
                                               links[a])
                          for a in t.axes if a != axis and a in links)
            if t.kind == "collective_permute":
                # the boundary pairs at the cut cross DCN point-to-point;
                # in-slice pairs keep the ICI price
                ici_s += t.count * (other_s + self.collective_secs(
                    t.kind, t.bytes_each, intra))
                dcn_leg = (t.bytes_each / self.gen.dcn_bandwidth
                           + self.gen.dcn_alpha_s)
                dcn_s += t.count * dcn_leg
                dcn_bytes += t.count * t.bytes_each
            else:
                m = max(axis_size // n_slices, 1)
                ici_s += t.count * (other_s + self.collective_secs(
                    t.kind, t.bytes_each, intra))
                shard = t.bytes_each / m
                dcn_s += t.count * self.dcn_secs(t.kind, shard, n_slices)
                dcn_bytes += t.count * shard * (
                    2 if t.kind == "all_reduce" else 1) * (
                    n_slices - 1) / n_slices
        return {
            "axis": axis, "slices": n_slices,
            "generation": self.gen.name,
            "crossing_terms": crossing,
            "dcn_bytes": int(dcn_bytes),
            "dcn_ms": round(dcn_s * 1e3, 4),
            "ici_ms": round(ici_s * 1e3, 4),
            "total_comm_ms": round((ici_s + dcn_s) * 1e3, 4),
        }

    # -- traced-schedule pricing ------------------------------------------

    def price_ops(self, cfg: Config, ops) -> list[dict]:
        """Price a parsed `CollectiveOp` list (analysis/collectives.py)
        against the config's axis placement. Each op's replica-group size
        is matched to a mesh axis (or, for the fused-data-axes grad
        all-reduce, to the (dp, ep, cp) product, priced hierarchically as
        one pass per constituent axis). Ops whose group no axis explains
        are priced on the worst (slowest) placed axis, flagged
        `axis_guess`."""
        links = self.axes_for(cfg)
        d = cfg.distributed
        sizes = {"dp": d.dp_size, "pp": d.pp_size, "ep": d.ep_size,
                 "cp": d.cp_size, "tp": d.tp_size}
        priced = []
        for op in ops:
            if not op.effective:
                continue
            nbytes = op.nbytes or 0
            axes = self._match_axes(op, sizes)
            if axes:
                secs = sum(
                    self.collective_secs(op.kind, nbytes, links[a])
                    for a in axes if a in links)
                guess = False
            else:
                worst = min(links.values(), key=lambda l: l.bandwidth,
                            default=None)
                secs = (self.collective_secs(op.kind, nbytes, worst)
                        if worst else 0.0)
                guess = True
            priced.append({"kind": op.kind, "line": op.line,
                           "bytes": nbytes, "axes": axes,
                           "secs": secs, "axis_guess": guess})
        return priced

    def price_reshards(self, cfg: Config, reshards) -> tuple:
        """(secs, bytes) for predicted boundary reshards
        (analysis/dataflow.py BoundaryReshard). GSPMD materializes a spec
        mismatch as an all-gather of the full logical tensor; the static
        prediction cannot know which axis the partitioner routes it over,
        so budget the slowest placed axis — the conservative bound the
        planner should price unintended traffic at."""
        links = [l for l in self.axes_for(cfg).values() if l.size > 1]
        worst = min(links, key=lambda l: l.bandwidth, default=None)
        if worst is None:
            return 0.0, sum(r.nbytes for r in reshards)
        secs = sum(self.collective_secs("all_gather", r.nbytes, worst)
                   for r in reshards)
        return secs, sum(r.nbytes for r in reshards)

    def price_kv_handoff(self, model_cfg, serve_cfg=None, *,
                         n_tokens: Optional[int] = None,
                         hops: int = 1) -> tuple:
        """(secs, bytes) for ONE prefill->decode KV-block handoff in the
        disaggregated serving engine (serve/disagg.py): the K and V
        blocks of one finished prefix cross the pool boundary as a
        point-to-point `device_put` over `hops` ICI links (1 = adjacent
        chips, the intended placement; a torus detour raises it).

        Payload = 2 tensors x L x blocks x block_size x Hkv x Dh at the
        serve compute dtype, with `blocks` rounded UP from `n_tokens`
        (default: the full serve.max_model_len prefix — the conservative
        per-request bound admission should budget). The transfer is
        point-to-point, so it prices like a single ppermute hop:
        nbytes * hops / link_bw + alpha * hops. Decode-side stall only
        occurs if the handoff is scheduled synchronously with a decode
        dispatch — the engine interleaves it between dispatches, so this
        number is the budget the scheduler's handoff rate must stay
        under, not a per-token tax."""
        from picotron_tpu.config import ServeConfig

        scfg = serve_cfg or ServeConfig()
        max_len = (scfg.max_model_len
                   or model_cfg.max_position_embeddings)
        if n_tokens is None:
            n_tokens = max_len
        blocks = -(-n_tokens // scfg.block_size)
        kv_bytes = _DTYPE_BYTES.get(model_cfg.dtype, 2)
        nbytes = (2 * model_cfg.num_hidden_layers * blocks
                  * scfg.block_size * model_cfg.num_key_value_heads
                  * model_cfg.head_dim * kv_bytes)
        secs = (nbytes * hops / self.gen.link_bandwidth
                + self.calib.alpha_link_s * hops)
        return secs, nbytes

    @staticmethod
    def _match_axes(op, sizes: dict) -> tuple:
        """Mesh axes a parsed op most plausibly spans."""
        if op.kind == "collective_permute":
            # ppermutes carry pairs, not groups: cp rings issue far more
            # of them than pp boundaries — prefer cp when present
            for a in ("cp", "pp", "dp"):
                if sizes[a] > 1:
                    return (a,)
            return ()
        g = op.group_size or 0
        if g <= 1:
            return ()
        # fused data axes (the grad sync) first, then single axes by
        # comm-frequency priority
        fused = sizes["dp"] * sizes["ep"] * sizes["cp"]
        if g == fused and fused > 1:
            return tuple(a for a in ("dp", "ep", "cp") if sizes[a] > 1)
        prefer = (("ep", "cp", "tp", "dp", "pp")
                  if op.kind == "all_to_all"
                  else ("tp", "cp", "ep", "dp", "pp"))
        for a in prefer:
            if sizes[a] == g:
                return (a,)
        return ()

    def priced_schedule(self, cfg: Config, text: Optional[str] = None):
        """(priced ops, total comm seconds) from a traced schedule —
        lowers the train step when `text` is not given (requires enough
        simulated devices, same contract as analysis/trace.py)."""
        if text is None:
            from picotron_tpu.analysis.trace import lower_train_step

            text = lower_train_step(cfg).text
        from picotron_tpu.analysis.collectives import parse_collectives

        priced = self.price_ops(cfg, parse_collectives(text))
        return priced, sum(p["secs"] for p in priced)

    # -- analytic whole-step prediction -----------------------------------

    def predict(self, cfg: Config, label: Optional[str] = None) -> StepCost:
        """Analytic step-time decomposition for `cfg` on this generation.
        The schedule is derived from the config (the same per-axis
        presence rules audit_collectives enforces on traces), so this
        needs no devices and prices a 64-chip layout in microseconds."""
        c = self.calib
        m, d, t = cfg.model, cfg.distributed, cfg.training
        world = d.world_size
        s, h = t.seq_length, m.hidden_size
        ga, mbs = t.gradient_accumulation_steps, t.micro_batch_size
        act_bytes = _DTYPE_BYTES.get(m.dtype, 2)
        tokens = cfg.tokens_per_step

        # compute: split the 6N+attn formula into dense / attention parts
        f_tok = flops_per_token(m, s)
        f_attn_tok = 12.0 * m.num_hidden_layers * h * s
        f_dense_tok = f_tok - f_attn_tok
        eff_d = min(c.eff_max * h / (h + c.h_half), c.eff_cap)
        mult = c.remat_multiplier(t.remat_policy, t.remat)
        compute_s = (tokens * mult
                     * (f_dense_tok / eff_d + f_attn_tok / c.eff_attn)
                     / (world * self.gen.peak_flops))

        # Non-megatron TP strategies (parallel/tp_strategies.py). The 2d
        # row-side matmuls (o/down) contract a tp_y-times larger slab —
        # weight rows are gathered within the inner subgroup so the
        # contraction replicates tp_y-fold across it. Fold the extra FLOPs
        # into compute_s so the bubble and overlap terms see the true
        # critical path; the comm terms below price the collectives.
        tp_strat = None
        tp_x = tp_y = 1
        if d.tp_size > 1:
            from picotron_tpu.config import resolved_tp_strategy

            tp_strat = resolved_tp_strategy(cfg, generation=self.gen.name)
            if "2d" in tp_strat.values():
                tp_x, tp_y = resolved_tp_mesh(cfg)
                extra_tok = 0.0
                if tp_strat["o"] == "2d":
                    extra_tok += 2.0 * h * h
                if tp_strat["down"] == "2d":
                    extra_tok += 2.0 * h * m.intermediate_size
                compute_s += (tokens * mult * m.num_hidden_layers
                              * extra_tok * (tp_y - 1)
                              / (eff_d * world * self.gen.peak_flops))

        # Pipeline bubble — executor-dependent (parallel/mpmd.py):
        # - spmd: the lockstep scan runs n + 2(pp-1) ticks and EVERY tick
        #   costs a full traced unit on every device (PERF.md r4: idle
        #   ticks are not free), so bubble = compute * 2(pp-1)/ga.
        # - mpmd: idle ticks dispatch nothing. What remains is the
        #   schedule's fill/drain — (pp-1)/ga of compute for 1f1b/gpipe,
        #   divided by the interleave factor v for the interleaved
        #   schedule — plus the per-dispatch host cost of walking the
        #   table (2 programs per microbatch per virtual stage).
        bubble_s = 0.0
        if d.pp_size > 1:
            pl = cfg.pipeline
            if pl.executor == "spmd":
                bubble_s = compute_s * 2 * (d.pp_size - 1) / ga
            else:
                v = pl.interleave if pl.schedule == "interleaved" else 1
                bubble_s = (compute_s * (d.pp_size - 1) / (v * ga)
                            + 2 * ga * d.pp_size * v * c.host_dispatch_s)

        # optimizer offload: master + both moments stream host->device and
        # the refreshed values stream back, once per step, sharded like the
        # params (tp*pp; experts additionally over ep; zero1 over dp)
        offload_s = 0.0
        if t.optimizer_offload:
            n_total = num_params(m)
            n_local = n_total / (d.tp_size * d.pp_size)
            if m.num_experts and d.ep_size > 1:
                bank = (m.num_hidden_layers * m.num_experts
                        * 3 * h * m.expert_ffn_size)
                n_local -= bank / d.tp_size / d.pp_size * (1 - 1 / d.ep_size)
            if d.zero1:
                n_local /= d.dp_size
            mom_b = 2 if t.adam_moments_dtype == "bfloat16" else 4
            per_param = 2 * (4 + 2 * mom_b)  # round trip: master + m + v
            offload_s = n_local * per_param / c.pcie_bandwidth

        links = self.axes_for(cfg)
        terms: list[CommTerm] = []

        def add(name, kind, axes, count, nbytes, exposed):
            axes = tuple(a for a in axes if a in links)
            if not axes or count <= 0 or nbytes <= 0:
                return
            secs = sum(self.collective_secs(kind, nbytes, links[a])
                       for a in axes)
            terms.append(CommTerm(name, kind, axes, int(count), nbytes,
                                  secs, exposed))

        layers_stage = max(m.num_hidden_layers // d.pp_size, 1)
        v_act = mbs * (s // d.cp_size) * h * act_bytes  # one microbatch

        # grad sync over the fused data axes, fp32, once per step
        n_grad_local = num_params(m) / (d.tp_size * d.pp_size)
        add("grad_sync",
            "reduce_scatter" if d.zero1 else "all_reduce",
            ("dp", "ep", "cp"), 1, 4 * n_grad_local, c.expose_grad)
        if d.zero1:
            # the matching param all-gather of the refreshed shards
            add("zero1_gather", "all_gather", ("dp",), 1,
                act_bytes * n_grad_local, c.expose_grad)

        # TP: 2 fwd + 2 bwd boundary collectives per layer per microbatch
        # on the megatron col/row pairing; Megatron-SP replaces each psum
        # with an all-gather/reduce-scatter pair of the same volume, and
        # tp_sync=deferred keeps the SP pair but hoists the gather into the
        # next block's entry (only expose_deferred of it stays exposed).
        # The row-first pairing moves the psum to the block ENTRY (over the
        # full projection width — wider than hidden) and exits with a
        # feature all-gather; the 2d pairing splits tp into tp_x x tp_y
        # subgroups: an activation + weight-rows all-gather over the inner
        # tp_y link and a psum shrunk to the outer tp_x link.
        if d.tp_size > 1 and tp_strat is not None:
            deferred = d.tp_sync == "deferred"
            pair_kinds = (("attn", tp_strat["qkv"]), ("mlp", tp_strat["up"]))
            n_pair = 2 * layers_stage * ga   # fwd + bwd, per pair per micro
            n_boundary = sum(n_pair for _, k in pair_kinds if k == "col")
            if n_boundary:
                if deferred:
                    add("tp_defer_gather", "all_gather", ("tp",),
                        n_boundary, v_act, c.expose_deferred)
                    add("tp_defer_scatter", "reduce_scatter", ("tp",),
                        n_boundary, v_act, c.expose_layer)
                elif d.sequence_parallel:
                    add("sp_gather", "all_gather", ("tp",), n_boundary,
                        v_act, c.expose_layer)
                    add("sp_scatter", "reduce_scatter", ("tp",), n_boundary,
                        v_act, c.expose_layer)
                else:
                    add("tp_psum", "all_reduce", ("tp",), n_boundary,
                        v_act, c.expose_layer)
            tok_mb = mbs * (s // d.cp_size)
            p_bytes = _DTYPE_BYTES.get(m.dtype, 2)
            attn_w = m.num_attention_heads * m.head_dim
            proj = {"attn": attn_w + 2 * m.num_key_value_heads * m.head_dim,
                    "mlp": 2 * m.intermediate_size}
            gath = {"attn": proj["attn"], "mlp": m.intermediate_size}
            wrows = {"attn": attn_w, "mlp": m.intermediate_size}
            for pair, kind in pair_kinds:
                if kind == "row":
                    add(f"tp_row_psum_{pair}", "all_reduce", ("tp",),
                        n_pair, tok_mb * proj[pair] * act_bytes,
                        c.expose_layer)
                    add(f"tp_row_gather_{pair}", "all_gather", ("tp",),
                        n_pair, v_act, c.expose_layer)
                elif kind == "2d" and "tp" in links:
                    outer, inner = split_cp_link(links["tp"], tp_x, tp_y,
                                                 self.gen)
                    if tp_y > 1:
                        v_g = tok_mb * gath[pair] // tp_x * act_bytes
                        terms.append(CommTerm(
                            f"tp2d_gather_{pair}", "all_gather", ("tp",),
                            n_pair, v_g,
                            self.collective_secs("all_gather", v_g, inner),
                            c.expose_layer))
                        v_w = wrows[pair] * h // tp_x * p_bytes
                        terms.append(CommTerm(
                            f"tp2d_wgather_{pair}", "all_gather", ("tp",),
                            n_pair, v_w,
                            self.collective_secs("all_gather", v_w, inner),
                            c.expose_layer))
                    if tp_x > 1:
                        terms.append(CommTerm(
                            f"tp2d_psum_{pair}", "all_reduce", ("tp",),
                            n_pair, v_act,
                            self.collective_secs("all_reduce", v_act,
                                                 outer),
                            c.expose_layer))

        # CP: ring (K/V shift chain fwd, K/V + dK/dV bwd), the Ulysses
        # seq<->head all_to_all pair each way, or the mesh flavor's 2D
        # split — head scatter over the inner cp_y subgroup plus a K/V
        # ring over the outer cp_x rows. The mesh row-block payload
        # (cp_y-times-longer sequence on 1/cp_y of the KV heads) equals
        # the 1D ring's per-hop v_kv exactly; what changes is the hop
        # count (cp_x-1 vs cp-1) and the sub-link each leg runs on.
        if d.cp_size > 1:
            flavor = resolved_cp_flavor(cfg)
            kv_dim = m.num_key_value_heads * m.head_dim
            v_kv = 2 * mbs * (s // d.cp_size) * kv_dim * act_bytes
            if flavor == "ulysses":
                add("ulysses_a2a", "all_to_all", ("cp",),
                    4 * layers_stage * ga, v_act, c.expose_layer)
            elif flavor == "mesh" and "cp" in links:
                cp_x, cp_y = resolved_cp_mesh(cfg)
                outer, inner = split_cp_link(links["cp"], cp_x, cp_y,
                                             self.gen)
                if cp_y > 1:
                    secs = self.collective_secs("all_to_all", v_act, inner)
                    terms.append(CommTerm(
                        "mesh_a2a", "all_to_all", ("cp",),
                        4 * layers_stage * ga, v_act, secs,
                        c.expose_layer))
                if cp_x > 1:
                    secs = self.collective_secs("collective_permute",
                                                v_kv, outer)
                    terms.append(CommTerm(
                        "mesh_ring", "collective_permute", ("cp",),
                        3 * (cp_x - 1) * layers_stage * ga, v_kv, secs,
                        c.expose_layer))
            else:
                add("cp_ring", "collective_permute", ("cp",),
                    3 * (d.cp_size - 1) * layers_stage * ga, v_kv,
                    c.expose_layer)

        # EP: dispatch + combine all_to_all, forward and backward
        if d.ep_size > 1 and m.num_experts:
            v_disp = v_act * m.num_experts_per_token * m.capacity_factor
            add("ep_dispatch", "all_to_all", ("ep",),
                4 * layers_stage * ga, v_disp, c.expose_layer)

        # PP boundary: activation fwd + grad bwd per microbatch
        if d.pp_size > 1:
            v_bound = v_act / (d.tp_size if d.sequence_parallel else 1)
            add("pp_boundary", "collective_permute", ("pp",), 2 * ga,
                v_bound, c.expose_pp)

        return StepCost(
            config_label=label or layout_label(cfg),
            generation=self.gen.name, n_chips=world,
            tokens_per_step=tokens, compute_s=compute_s,
            bubble_s=bubble_s, offload_s=offload_s, comm=tuple(terms))


def layout_label(cfg: Config) -> str:
    d, t = cfg.distributed, cfg.training
    bits = [f"dp{d.dp_size}", f"tp{d.tp_size}", f"pp{d.pp_size}",
            f"cp{d.cp_size}", f"ep{d.ep_size}"]
    flags = []
    if d.cp_size > 1 and d.cp_flavor:
        flags.append(d.cp_flavor + (f"-{d.cp_mesh}"
                                    if d.cp_flavor == "mesh" else ""))
    if d.sequence_parallel:
        flags.append("sp")
    if d.tp_size > 1 and d.tp_strategy != "megatron":
        if d.tp_strategy == "2d":
            tp_x, tp_y = resolved_tp_mesh(cfg)
            flags.append(f"tp2d-{tp_x}x{tp_y}")
        elif d.tp_strategy in ("row", "adaptive"):
            flags.append("tp" + d.tp_strategy)
        else:
            flags.append("tpmix")
    if d.tp_sync == "deferred":
        flags.append("deferred")
    if d.zero1:
        flags.append("zero1")
    if t.optimizer_offload:
        flags.append("offload")
    pl = getattr(cfg, "pipeline", None)
    if pl is not None and pl.executor == "mpmd":
        tag = "mpmd-" + pl.schedule
        if pl.schedule == "interleaved":
            tag += f"-v{pl.interleave}"
        flags.append(tag)
    return "x".join(bits) + (("+" + "+".join(flags)) if flags else "")


# ---------------------------------------------------------------------------
# CP-flavor crossover prediction
# ---------------------------------------------------------------------------


def _tp_local_heads(cfg: Config) -> tuple[int, int]:
    m, tp = cfg.model, cfg.distributed.tp_size
    return m.num_attention_heads // tp, m.num_key_value_heads // tp


def feasible_cp_meshes(cfg: Config, cp: Optional[int] = None) -> list:
    """True-2D (cp_x, cp_y) factorizations of the cp degree — both factors
    > 1 (degenerates ARE ring/ulysses, not a distinct flavor) and cp_y
    dividing the tp-local query AND kv head counts."""
    cp = cp or cfg.distributed.cp_size
    hq, hkv = _tp_local_heads(cfg)
    return [(cp // y, y) for y in range(2, cp)
            if cp % y == 0 and cp // y > 1
            and hq % y == 0 and hkv % y == 0]


def cp_flavor_costs(model: CostModel, cfg: Config) -> dict:
    """Price each feasible cp flavor for cfg's cp degree: 'ring' always,
    'ulysses' when the tp-local heads divide by cp, and 'mesh' as the best
    true-2D factorization (None entries mark infeasible flavors). Mesh
    values are (StepCost, (cp_x, cp_y))."""
    d = cfg.distributed
    out = {"ring": None, "ulysses": None, "mesh": None}
    ring_cfg = replace(cfg, distributed=replace(
        d, cp_flavor="ring", cp_mesh=""))
    out["ring"] = model.predict(ring_cfg)
    hq, hkv = _tp_local_heads(cfg)
    if hq % d.cp_size == 0 and hkv % d.cp_size == 0:
        out["ulysses"] = model.predict(replace(cfg, distributed=replace(
            d, cp_flavor="ulysses", cp_mesh="")))
    best = None
    for cp_x, cp_y in feasible_cp_meshes(cfg):
        cost = model.predict(replace(cfg, distributed=replace(
            d, cp_flavor="mesh", cp_mesh=f"{cp_x}x{cp_y}")))
        if best is None or cost.total_s < best[0].total_s:
            best = (cost, (cp_x, cp_y))
    out["mesh"] = best
    return out


def cp_crossover_table(model: CostModel, base: Config,
                       cp_degrees=(2, 4, 8, 16, 32)) -> list[dict]:
    """Sweep cp degree for `base`'s model/batch on `model`'s generation and
    report, per degree, each flavor's predicted step time and the winner —
    the table `tools/layout_planner.py --cp-crossover` prints. Degrees the
    sequence length cannot shard (zigzag needs 2*cp | seq) are skipped."""
    rows = []
    for cp in cp_degrees:
        if base.training.seq_length % (2 * cp) or cp < 2:
            continue
        cfg = replace(base, distributed=replace(
            base.distributed, cp_size=cp, cp_flavor="", cp_mesh=""))
        costs = cp_flavor_costs(model, cfg)
        row = {"cp": cp, "generation": model.gen.name}
        times = {}
        for flavor in ("ring", "ulysses", "mesh"):
            v = costs[flavor]
            if flavor == "mesh" and v is not None:
                cost, (cp_x, cp_y) = v
                row["mesh_factorization"] = f"{cp_x}x{cp_y}"
                v = cost
            row[f"{flavor}_ms"] = (round(v.total_s * 1e3, 3)
                                   if v is not None else None)
            if v is not None:
                times[flavor] = v.total_s
        row["winner"] = min(times, key=times.get) if times else None
        rows.append(row)
    return rows


def cp_crossover(model: CostModel, base: Config,
                 cp_degrees=(2, 4, 8, 16, 32)) -> Optional[int]:
    """Smallest swept cp degree where the mesh flavor's best factorization
    beats ring AND ulysses — None if mesh never wins. On wrap-less slices
    (v5e/v6e lines) the 1D ring pays cp-1 full-diameter wrap penalties and
    mesh wins early; on wrapped v4/v5p rings the crossover moves out."""
    for row in cp_crossover_table(model, base, cp_degrees):
        if row["winner"] == "mesh":
            return row["cp"]
    return None


# ---------------------------------------------------------------------------
# TP-strategy pricing + adaptive selection
# ---------------------------------------------------------------------------


def feasible_tp_meshes(cfg: Config, tp: Optional[int] = None) -> list:
    """True-2D (tp_x, tp_y) factorizations of the tp degree — both factors
    > 1 (degenerates ARE megatron: tp_y=1 has no inner gather and tp_x=1
    no outer psum shrink) and tp_x dividing the q AND kv head counts (the
    2d attention runs heads/tp_x, tp_y-replicated)."""
    m = cfg.model
    tp = tp or cfg.distributed.tp_size
    return [(tp // y, y) for y in range(2, tp)
            if tp % y == 0 and tp // y > 1
            and m.num_attention_heads % (tp // y) == 0
            and m.num_key_value_heads % (tp // y) == 0]


def price_tp_strategy(model: CostModel, cfg: Config, strategy: str,
                      sync: str = "sync", tp_mesh: str = "") -> StepCost:
    """Price `cfg` with its TP strategy/sync knobs forced — the one-call
    query behind `choose_tp_strategy` and the `--tp-strategy-table` CLI.
    No validation is re-run: this is a pricing probe, so the caller owns
    eligibility (the planner only probes eligible configs)."""
    return model.predict(replace(cfg, distributed=replace(
        cfg.distributed, tp_strategy=strategy, tp_sync=sync,
        tp_mesh=tp_mesh)))


def _pair_spec(attn_kind: str, mlp_kind: str) -> str:
    """Explicit per-class spec string for a (attn-pair, mlp-pair) choice,
    respecting the legal (entry, exit) pairings config.parse_tp_strategy
    enforces: col pairs with row, row with col, 2d with 2d."""
    exit_of = {"col": "row", "row": "col", "2d": "2d"}
    return (f"qkv={attn_kind},o={exit_of[attn_kind]},"
            f"up={mlp_kind},down={exit_of[mlp_kind]},head=col")


def choose_tp_strategy(cfg: Config, generation: str = "v5e") -> dict:
    """Resolve tp_strategy='adaptive': per-class argmin over the legal
    pair partitionings, priced on `generation`'s ICI descriptor (the ATP
    selection loop, arxiv 2301.08658, collapsed to the three partitionings
    this runtime implements). Deterministic: candidates are enumerated in
    a fixed order with a strict < comparison, so megatron (first) wins
    ties — tp degrees where no alternative strictly helps keep the
    reference layout. Pure arithmetic; resolves in microseconds."""
    model = CostModel(generation)
    d = cfg.distributed
    tp_x, tp_y = resolved_tp_mesh(cfg)
    kinds = ["col", "row"] + (["2d"] if tp_x > 1 and tp_y > 1 else [])
    best_s, best_spec = None, _pair_spec("col", "col")
    for ak in kinds:
        for mk in kinds:
            spec = _pair_spec(ak, mk)
            cost = price_tp_strategy(model, cfg, spec, sync=d.tp_sync,
                                     tp_mesh=d.tp_mesh)
            if best_s is None or cost.total_s < best_s:
                best_s, best_spec = cost.total_s, spec
    return parse_tp_strategy(best_spec)


def tp_strategy_table(model: CostModel, base: Config,
                      tp_degrees=(2, 4, 8, 16)) -> list[dict]:
    """Sweep tp degree for `base`'s model/batch on `model`'s generation
    and report, per degree, each strategy x sync-mode's predicted step
    time and exposed-comm time, the best 2d factorization, the adaptive
    resolution, and the winner — the table
    `tools/layout_planner.py --tp-strategy-table` prints. Degrees the
    model cannot shard (head/kv/vocab divisibility) are skipped."""
    m = base.model
    rows = []
    for tp in tp_degrees:
        if (tp < 2 or m.num_attention_heads % tp
                or m.num_key_value_heads % tp or m.vocab_size % tp):
            continue
        cfg = replace(base, distributed=replace(
            base.distributed, tp_size=tp, tp_strategy="megatron",
            tp_sync="sync", tp_mesh=""))
        variants: dict[str, StepCost] = {
            "megatron": model.predict(cfg),
            "deferred": price_tp_strategy(model, cfg, "megatron",
                                          sync="deferred"),
            "row": price_tp_strategy(model, cfg, "row"),
        }
        row = {"tp": tp, "generation": model.gen.name}
        best2d = None
        for tp_mx, tp_my in feasible_tp_meshes(cfg, tp):
            cost = price_tp_strategy(model, cfg, "2d",
                                     tp_mesh=f"{tp_mx}x{tp_my}")
            if best2d is None or cost.total_s < best2d[0].total_s:
                best2d = (cost, f"{tp_mx}x{tp_my}")
        if best2d is not None:
            variants["2d"] = best2d[0]
            row["mesh_factorization"] = best2d[1]
        base_exposed = variants["megatron"].exposed_comm_s
        for name, cost in variants.items():
            row[f"{name}_ms"] = round(cost.total_s * 1e3, 3)
            row[f"{name}_exposed_ms"] = round(cost.exposed_comm_s * 1e3, 3)
            row[f"{name}_exposed_delta_ms"] = round(
                (cost.exposed_comm_s - base_exposed) * 1e3, 3)
        adaptive = choose_tp_strategy(replace(cfg, distributed=replace(
            cfg.distributed, tp_strategy="adaptive")),
            generation=model.gen.name)
        row["adaptive"] = ",".join(
            f"{k}={adaptive[k]}" for k in ("qkv", "o", "up", "down"))
        row["winner"] = min(variants, key=lambda k: variants[k].total_s)
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# Rank statistics (calibration / validation)
# ---------------------------------------------------------------------------


def spearman(xs, ys) -> float:
    """Spearman rank correlation (mean-rank ties)."""
    if len(xs) != len(ys) or len(xs) < 2:
        raise ValueError("spearman needs two equal-length series, n >= 2")

    def ranks(vs):
        order = sorted(range(len(vs)), key=lambda i: vs[i])
        r = [0.0] * len(vs)
        i = 0
        while i < len(order):
            j = i
            while j + 1 < len(order) and vs[order[j + 1]] == vs[order[i]]:
                j += 1
            mean_rank = (i + j) / 2.0
            for k in range(i, j + 1):
                r[order[k]] = mean_rank
            i = j + 1
        return r

    rx, ry = ranks(list(xs)), ranks(list(ys))
    mx = sum(rx) / len(rx)
    my = sum(ry) / len(ry)
    num = sum((a - mx) * (b - my) for a, b in zip(rx, ry))
    den = math.sqrt(sum((a - mx) ** 2 for a in rx)
                    * sum((b - my) ** 2 for b in ry))
    return num / den if den else 0.0


def with_calibration(model: "CostModel", **changes) -> "CostModel":
    """A CostModel with some calibration constants replaced."""
    return CostModel(model.gen, replace(model.calib, **changes))
