"""4D device mesh — the TPU-native replacement for the reference's
process-group singleton (ref: picotron/process_group_manager.py).

The reference builds a rank grid `arange(world).view(dp, pp, cp, tp)` with TP
fastest-varying (ref: process_group_manager.py:13) and derives 6 communicator
subgroups from it. On TPU the grid *is* a `jax.sharding.Mesh` with named axes
``('dp', 'pp', 'cp', 'tp')``; every communicator the reference creates becomes
a named-axis collective:

- tp group      -> `lax.psum(..., 'tp')` / `lax.all_gather(..., 'tp')`
- cp ring       -> `lax.ppermute(..., 'cp', ...)`
- pp p2p        -> `lax.ppermute(..., 'pp', ...)`
- cp_dp group   -> `lax.pmean(..., ('cp', 'dp'))` (gradient sync, ref:
                   data_parallel.py:83)
- pp_dp group   -> axis tuple ('pp', 'dp')

TP is innermost so it maps to the fastest ICI axis, same ordering rationale as
the reference's grid. Axis order here is (dp, pp, cp, tp) — identical to the
reference's view order.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names, outermost to innermost. 'ep' (expert parallelism,
# beyond the reference's 4D: SURVEY §2.2 marks EP absent) acts as an extra
# data axis for everything except expert weights, which shard their expert
# dim over it; MoE dispatch rides `lax.all_to_all(..., 'ep')`.
AXES = ("dp", "pp", "ep", "cp", "tp")


def force_host_device_count(n: int) -> None:
    """Request `n` simulated host (CPU) devices. Must run before JAX backends
    initialize — the test conftest and the multichip dry-run use this
    (the TPU analogue of the reference's gloo/CPU path, ref: train.py:83).

    Raises if the flag is already pinned to a different count (a silent skip
    would surface later as a confusing mesh-oversubscription error).
    """
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m:
        have = int(m.group(1))
        if have < n:
            raise RuntimeError(
                f"XLA_FLAGS already pins host device count to {have} < requested {n}; "
                "restart the process with the larger count"
            )
        return
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()


@dataclass(frozen=True)
class MeshEnv:
    """Owns the 4D mesh and the sharding vocabulary built on it."""

    mesh: Mesh

    # -- construction ------------------------------------------------------

    @staticmethod
    def create(
        dp: int = 1,
        pp: int = 1,
        cp: int = 1,
        tp: int = 1,
        ep: int = 1,
        devices: Optional[Sequence[jax.Device]] = None,
    ) -> "MeshEnv":
        devices = list(devices if devices is not None else jax.devices())
        world = dp * pp * ep * cp * tp
        if world > len(devices):
            raise ValueError(
                f"dp*pp*ep*cp*tp = {world} exceeds available devices "
                f"({len(devices)}). (ref parity: train.py:86 asserts "
                "world_size == dp*pp*cp*tp)"
            )
        grid = np.array(devices[:world]).reshape(dp, pp, ep, cp, tp)
        return MeshEnv(Mesh(grid, AXES))

    @staticmethod
    def from_config(cfg) -> "MeshEnv":
        d = cfg.distributed
        return MeshEnv.create(dp=d.dp_size, pp=d.pp_size, cp=d.cp_size,
                              tp=d.tp_size, ep=getattr(d, "ep_size", 1))

    # -- axis sizes --------------------------------------------------------

    @property
    def dp(self) -> int:
        return self.mesh.shape["dp"]

    @property
    def pp(self) -> int:
        return self.mesh.shape["pp"]

    @property
    def cp(self) -> int:
        return self.mesh.shape["cp"]

    @property
    def tp(self) -> int:
        return self.mesh.shape["tp"]

    @property
    def ep(self) -> int:
        return self.mesh.shape["ep"]

    @property
    def world_size(self) -> int:
        return self.dp * self.pp * self.ep * self.cp * self.tp

    # -- sharding vocabulary ----------------------------------------------

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self) -> NamedSharding:
        """Sharding for a [micro, batch, seq] token block: batch over the
        fused (dp, ep) data axes, sequence over cp. The contiguous
        per-cp-rank sequence slice the reference does by hand in its collate
        fn (ref: data.py:105-109) falls out of sharding the sequence
        dimension."""
        return self.sharding(None, ("dp", "ep"), "cp")


def multihost_initialize() -> None:
    """Initialize the JAX distributed runtime for multi-host pods.

    One process per host over ICI/DCN replaces the reference's
    one-process-per-GPU torchrun + NCCL rendezvous (ref: base_job.slurm:64,
    train.py:94). `jax.distributed.initialize()` auto-detects Cloud TPU pod
    metadata, SLURM, and MPI cluster environments; we attempt it whenever any
    such environment is plausible and fail loudly if detection half-works.
    """
    # Must not touch any backend-initializing jax API before initialize();
    # consult the distributed global state directly instead.
    from jax._src import distributed as _jdist

    if _jdist.global_state.client is not None:
        return  # already initialized
    if _cluster_env_detected(os.environ):
        jax.distributed.initialize()


def _cluster_env_detected(env) -> bool:
    """True when a multi-host cluster environment is plausibly present:
    an explicit coordinator address, a SLURM/OpenMPI job, or a Cloud TPU
    pod worker list with more than one host. Single-host runs (including
    a TPU_WORKER_HOSTNAMES containing just this host) stay local."""
    if env.get("COORDINATOR_ADDRESS") or env.get("JAX_COORDINATOR_ADDRESS"):
        return True
    if env.get("SLURM_JOB_ID") or env.get("OMPI_COMM_WORLD_SIZE"):
        return True
    hosts = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",")
             if h.strip()]
    return len(hosts) > 1
