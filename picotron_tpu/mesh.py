"""4D device mesh — the TPU-native replacement for the reference's
process-group singleton (ref: picotron/process_group_manager.py).

The reference builds a rank grid `arange(world).view(dp, pp, cp, tp)` with TP
fastest-varying (ref: process_group_manager.py:13) and derives 6 communicator
subgroups from it. On TPU the grid *is* a `jax.sharding.Mesh` with named axes
``('dp', 'pp', 'cp', 'tp')``; every communicator the reference creates becomes
a named-axis collective:

- tp group      -> `lax.psum(..., 'tp')` / `lax.all_gather(..., 'tp')`
- cp ring       -> `lax.ppermute(..., 'cp', ...)`
- pp p2p        -> `lax.ppermute(..., 'pp', ...)`
- cp_dp group   -> `lax.pmean(..., ('cp', 'dp'))` (gradient sync, ref:
                   data_parallel.py:83)
- pp_dp group   -> axis tuple ('pp', 'dp')

TP is innermost so it maps to the fastest ICI axis, same ordering rationale as
the reference's grid. Axis order here is (dp, pp, cp, tp) — identical to the
reference's view order.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names, outermost to innermost. 'ep' (expert parallelism,
# beyond the reference's 4D: SURVEY §2.2 marks EP absent) acts as an extra
# data axis for everything except expert weights, which shard their expert
# dim over it; MoE dispatch rides `lax.all_to_all(..., 'ep')`.
AXES = ("dp", "pp", "ep", "cp", "tp")


def force_host_device_count(n: int, exact: bool = False) -> None:
    """Request `n` simulated host (CPU) devices. Must run before JAX backends
    initialize — the test conftest and the multichip dry-run use this
    (the TPU analogue of the reference's gloo/CPU path, ref: train.py:83).

    Raises if the flag is already pinned to a smaller count (a silent skip
    would surface later as a confusing mesh-oversubscription error). With
    `exact=True` any pinned mismatch raises: in a multi-process launch each
    process must provision exactly its share of the world, and a stale
    inherited XLA_FLAGS (e.g. exported for an earlier single-process run)
    would make every process bring the full count — the global device list
    then holds n_proc times the world and the mesh lands entirely on
    process 0's devices, failing far from the cause.
    """
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m:
        have = int(m.group(1))
        if have < n or (exact and have != n):
            raise RuntimeError(
                f"XLA_FLAGS already pins host device count to {have}, but "
                f"{'exactly ' if exact else 'at least '}{n} per process "
                f"is required; unset XLA_FLAGS or restart with the right "
                f"count"
            )
        return
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()


@dataclass(frozen=True)
class MeshEnv:
    """Owns the 4D mesh and the sharding vocabulary built on it."""

    mesh: Mesh

    # -- construction ------------------------------------------------------

    @staticmethod
    def create(
        dp: int = 1,
        pp: int = 1,
        cp: int = 1,
        tp: int = 1,
        ep: int = 1,
        devices: Optional[Sequence[jax.Device]] = None,
    ) -> "MeshEnv":
        devices = list(devices if devices is not None else jax.devices())
        world = dp * pp * ep * cp * tp
        if world > len(devices):
            raise ValueError(
                f"dp*pp*ep*cp*tp = {world} exceeds available devices "
                f"({len(devices)}). (ref parity: train.py:86 asserts "
                "world_size == dp*pp*cp*tp)"
            )
        grid = _topology_grid((dp, pp, ep, cp, tp), devices[:world])
        return MeshEnv(Mesh(grid, AXES))

    @staticmethod
    def from_config(cfg) -> "MeshEnv":
        d = cfg.distributed
        return MeshEnv.create(dp=d.dp_size, pp=d.pp_size, cp=d.cp_size,
                              tp=d.tp_size, ep=getattr(d, "ep_size", 1))

    # -- axis sizes --------------------------------------------------------

    @property
    def dp(self) -> int:
        return self.mesh.shape["dp"]

    @property
    def pp(self) -> int:
        return self.mesh.shape["pp"]

    @property
    def cp(self) -> int:
        return self.mesh.shape["cp"]

    @property
    def tp(self) -> int:
        return self.mesh.shape["tp"]

    @property
    def ep(self) -> int:
        return self.mesh.shape["ep"]

    @property
    def world_size(self) -> int:
        return self.dp * self.pp * self.ep * self.cp * self.tp

    # -- sharding vocabulary ----------------------------------------------

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self) -> NamedSharding:
        """Sharding for a [micro, batch, seq] token block: batch over the
        fused (dp, ep) data axes, sequence over cp. The contiguous
        per-cp-rank sequence slice the reference does by hand in its collate
        fn (ref: data.py:105-109) falls out of sharding the sequence
        dimension."""
        return self.sharding(None, ("dp", "ep"), "cp")


def _topology_grid(shape: tuple, devices: list) -> np.ndarray:
    """Device grid for `Mesh(grid, AXES)` that respects the physical
    network topology.

    The reference's whole reason for its rank-grid ordering is mapping TP
    onto the fastest links (ref: process_group_manager.py:13-23 — TP
    fastest-varying onto NVLink). A naive `reshape(jax.devices())` encodes
    that ordering over the *enumeration* order, which on a real pod slice
    has no relation to the ICI torus. `mesh_utils.create_device_mesh`
    assigns logical axes to physical torus axes so that later (more
    network-intensive) mesh axes land on better-connected device groups —
    AXES is ordered (dp, pp, ep, cp, tp) for exactly this contract. For
    DCN-spanning jobs (multiple pod slices), `create_hybrid_device_mesh`
    keeps ICI-hungry axes inside a slice and routes the outermost axes
    (dp first, then pp) over DCN.

    Non-TPU devices (the simulated CPU meshes tests use) reduce to the
    plain reshape inside mesh_utils, keeping single-host behavior and
    device order unchanged. Any mesh_utils failure (e.g. a shape the torus
    mapper cannot satisfy for a partial-host device subset) falls back to
    the naive reshape with a warning rather than refusing to run.
    """
    if len(devices) == 1:
        return np.array(devices).reshape(shape)
    from jax.experimental import mesh_utils

    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    if len(slice_ids) > 1:
        # An unsatisfiable slice/axis split is a layout error the user must
        # fix — raised OUTSIDE the try below, which only downgrades
        # topology-*optimization* failures to a warning.
        dcn_shape, per_slice_shape = _split_axes_over_dcn(
            shape, len(slice_ids))
    try:
        if len(slice_ids) > 1:
            return mesh_utils.create_hybrid_device_mesh(
                per_slice_shape, dcn_shape, devices=devices,
                allow_split_physical_axes=True)
        return mesh_utils.create_device_mesh(
            shape, devices=devices, allow_split_physical_axes=True)
    except Exception as e:  # noqa: BLE001 — topology optimization only
        import warnings

        warnings.warn(
            f"topology-aware mesh construction failed ({e}); falling back "
            f"to enumeration-order reshape — collective performance may "
            f"suffer on multi-chip hardware", stacklevel=2)
        return np.array(devices).reshape(shape)


def _split_axes_over_dcn(shape: tuple, n_slices: int) -> tuple[tuple, tuple]:
    """Factor the logical mesh shape into (dcn_shape, per_slice_shape) for
    `create_hybrid_device_mesh`: the n_slices DCN granules are absorbed by
    the outermost axes first (dp, then pp, ...), since gradient all-reduce
    over dp (once per step, overlappable) and pipeline boundary ppermute
    over pp (point-to-point) tolerate DCN latency, while cp/tp collectives
    must stay on ICI."""
    import math

    N_DCN_TOLERANT_AXES = 2  # dp, pp only — never ep/cp/tp over DCN
    dcn = [1] * len(shape)
    per_slice = list(shape)
    rem = n_slices
    for i in range(N_DCN_TOLERANT_AXES):
        g = math.gcd(per_slice[i], rem)
        dcn[i] = g
        per_slice[i] //= g
        rem //= g
        if rem == 1:
            break
    if rem != 1:
        raise ValueError(
            f"cannot distribute {n_slices} DCN slices over mesh axes "
            f"{dict(zip(AXES, shape))}: the slice count must divide the "
            f"product of the DCN-tolerant axis sizes (dp * pp = "
            f"{shape[0] * shape[1]}) — ep/cp/tp collectives must stay on "
            f"ICI. Rebalance the layout so dp*pp absorbs the slice count.")
    return tuple(dcn), tuple(per_slice)


def multihost_initialize() -> None:
    """Initialize the JAX distributed runtime for multi-host pods.

    One process per host over ICI/DCN replaces the reference's
    one-process-per-GPU torchrun + NCCL rendezvous (ref: base_job.slurm:64,
    train.py:94). Two entry paths:

    - **Explicit contract** — `PICOTRON_COORDINATOR` / `_NUM_PROCESSES` /
      `_PROCESS_ID` env vars (the framework's own launcher contract, the
      analogue of torchrun's MASTER_ADDR/RANK/WORLD_SIZE). This is what the
      multi-process integration test and any non-auto-detected cluster use.
      On the CPU platform this also selects gloo cross-process collectives
      (the role the reference's gloo backend plays, ref: train.py:83) —
      which must happen before the first backend client exists.
    - **Auto-detect** — `jax.distributed.initialize()` sniffs Cloud TPU pod
      metadata, SLURM, and MPI environments; attempted whenever such an
      environment is plausibly multi-host (see `_cluster_env_detected`).
    """
    # Must not touch any backend-initializing jax API before initialize();
    # consult the distributed global state directly instead.
    from jax._src import distributed as _jdist

    if _jdist.global_state.client is not None:
        return  # already initialized
    contract = launcher_contract()
    if contract is not None:
        coord, num_processes, process_id = contract
        if num_processes > 1 and jax.config.jax_platforms == "cpu":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return
    if _cluster_env_detected(os.environ):
        jax.distributed.initialize()


def launcher_contract() -> Optional[tuple[str, int, int]]:
    """The explicit PICOTRON_* launcher contract, validated as a unit:
    (coordinator, num_processes, process_id), or None when unset. All three
    vars must appear together — a partial contract (e.g. a stale
    PICOTRON_NUM_PROCESSES without a coordinator) would otherwise make
    different components disagree about the process count and fail far from
    the cause."""
    names = ("PICOTRON_COORDINATOR", "PICOTRON_NUM_PROCESSES",
             "PICOTRON_PROCESS_ID")
    present = [n for n in names if os.environ.get(n)]
    if not present:
        return None
    missing = [n for n in names if not os.environ.get(n)]
    if missing:
        raise ValueError(
            f"partial PICOTRON launcher contract: {present} set but "
            f"{missing} missing — set all three or none")
    return (os.environ["PICOTRON_COORDINATOR"],
            int(os.environ["PICOTRON_NUM_PROCESSES"]),
            int(os.environ["PICOTRON_PROCESS_ID"]))


def _cluster_env_detected(env) -> bool:
    """True when a multi-host cluster environment is plausibly present:
    an explicit coordinator address, a SLURM/OpenMPI job spanning more than
    one task, or a Cloud TPU pod worker list with more than one host.
    Single-host runs (including a TPU_WORKER_HOSTNAMES containing just this
    host, a 1-task `mpirun -n 1`, or a single-node SLURM interactive shell)
    stay local — jax.distributed.initialize() there would hang waiting for
    a coordinator that never comes (ADVICE r2)."""
    if env.get("COORDINATOR_ADDRESS") or env.get("JAX_COORDINATOR_ADDRESS"):
        return True

    def _int(name: str) -> int:
        try:
            return int(env.get(name, "") or 0)
        except ValueError:
            return 0

    if _int("OMPI_COMM_WORLD_SIZE") > 1:
        return True
    if env.get("SLURM_JOB_ID") and (
            _int("SLURM_NTASKS") > 1 or _int("SLURM_JOB_NUM_NODES") > 1):
        return True
    hosts = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",")
             if h.strip()]
    return len(hosts) > 1
