"""Optimizer: AdamW over fp32 master params.

The reference uses `torch.optim.AdamW(fused=True)` (ref: train.py:204-209) —
a CUDA kernel. On TPU, optax's adamw update is a handful of elementwise ops
that XLA fuses into one kernel per bucket automatically; no custom kernel is
needed (SURVEY.md §2.3 row `fused AdamW`).
"""

from __future__ import annotations

import optax

from picotron_tpu.config import TrainingConfig


def make_optimizer(t: TrainingConfig) -> optax.GradientTransformation:
    steps = [] if t.grad_clip_norm <= 0 else [optax.clip_by_global_norm(t.grad_clip_norm)]
    steps.append(
        optax.adamw(
            learning_rate=t.learning_rate,
            b1=t.adam_beta1,
            b2=t.adam_beta2,
            eps=t.adam_eps,
            weight_decay=t.weight_decay,
        )
    )
    return optax.chain(*steps)
