"""Optimizer: AdamW over fp32 master params.

The reference uses `torch.optim.AdamW(fused=True)` (ref: train.py:204-209) —
a CUDA kernel. On TPU, optax's adamw update is a handful of elementwise ops
that XLA fuses into one kernel per bucket automatically; no custom kernel is
needed (SURVEY.md §2.3 row `fused AdamW`).

`adam_moments_dtype: "bfloat16"` stores both Adam moments in bf16 (compute
still fp32): moment memory halves, which is what lets full-depth
SmolLM-1.7B's optimizer state fit a single 16G v5e chip. The reference has
no low-precision optimizer option; this is a TPU-memory-driven extension.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from picotron_tpu.config import TrainingConfig


def scale_by_adam_low_moments(b1: float, b2: float, eps: float,
                              moments_dtype) -> optax.GradientTransformation:
    """scale_by_adam with BOTH moments stored in `moments_dtype` (optax's
    mu_dtype covers only the first moment). The update math runs in fp32;
    only the carried state is rounded."""

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, dtype=moments_dtype)  # noqa: E731
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(zeros, params),
            nu=jax.tree.map(zeros, params),
        )

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        mu32 = jax.tree.map(
            lambda g, m: b1 * m.astype(jnp.float32)
            + (1 - b1) * g.astype(jnp.float32),
            updates, state.mu)
        nu32 = jax.tree.map(
            lambda g, n: b2 * n.astype(jnp.float32)
            + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            updates, state.nu)
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)
        out = jax.tree.map(
            lambda m, n: (m / c1) / (jnp.sqrt(n / c2) + eps), mu32, nu32)
        new_state = optax.ScaleByAdamState(
            count=count,
            mu=jax.tree.map(lambda m: m.astype(moments_dtype), mu32),
            nu=jax.tree.map(lambda n: n.astype(moments_dtype), nu32),
        )
        return out, new_state

    return optax.GradientTransformation(init, update)


def make_lr(t: TrainingConfig):
    """Learning-rate schedule (a float or an optax schedule fn). The
    reference trains at constant LR (ref: train.py:209); warmup + cosine /
    linear decay are the standard pretraining extensions. Schedules are a
    pure function of the optimizer step count, which lives in the restored
    optimizer state — resume continues the schedule where it left off."""
    if t.lr_schedule == "constant" and t.lr_warmup_steps == 0:
        return t.learning_rate
    peak, floor = t.learning_rate, t.learning_rate * t.lr_min_ratio
    decay_steps = max(1, t.total_train_steps - t.lr_warmup_steps)
    if t.lr_schedule == "cosine":
        decay = optax.cosine_decay_schedule(peak, decay_steps,
                                            alpha=t.lr_min_ratio)
    elif t.lr_schedule == "linear":
        decay = optax.linear_schedule(peak, floor, decay_steps)
    else:  # constant with warmup
        decay = optax.constant_schedule(peak)
    if t.lr_warmup_steps == 0:
        return decay
    return optax.join_schedules(
        [optax.linear_schedule(0.0, peak, t.lr_warmup_steps), decay],
        boundaries=[t.lr_warmup_steps])


# fp32-master bytes per streamed-update slice: big enough that the h2d/d2h
# DMAs run near PCIe peak (measured ~5 GB/s aggregate at 64-128 MB on v5e),
# small enough that double-buffered slices cost < 1 GB of HBM.
_OFFLOAD_SLICE_BYTES = 128 * 2 ** 20


class OffloadAdamState(NamedTuple):
    """Optimizer state for `training.optimizer_offload`: the fp32 master
    params and both Adam moments live in pinned HOST memory (their leaves
    carry `memory_kind='pinned_host'` shardings); only the step counter is a
    device scalar. TrainState.params is then the bf16 device compute copy —
    the master moves INTO the optimizer state, which is where it
    conceptually belongs (it exists only for the update)."""

    count: jnp.ndarray  # int32 scalar, device
    master: Any         # fp32 pytree, pinned_host
    mu: Any             # adam_moments_dtype pytree, pinned_host
    nu: Any             # adam_moments_dtype pytree, pinned_host


def _lr_at(t: TrainingConfig, count):
    lr = make_lr(t)
    return lr(count) if callable(lr) else jnp.asarray(lr, jnp.float32)


def offload_adam_update(grads, state: OffloadAdamState, t: TrainingConfig,
                        shardings, compute_dtype,
                        memory_kind: str | None = "pinned_host",
                        grad_scale=None):
    """One AdamW step streamed through the device, leaf by leaf.

    grads: fp32 device pytree (already data-axis-averaged).
    shardings: per-param-leaf NamedShardings (the params' PartitionSpecs —
    a leaf's master and moments shard exactly like it; the host and device
    memory-kind variants are derived here). memory_kind None (CPU tests)
    runs the identical update without placement transfers. grad_scale (a
    traced scalar, e.g. 1/token_count) is folded into the per-slice math so
    the caller never materializes a divided copy of the grad tree — that
    second 6.75 GB fp32 tree is what OOMed full-depth SmolLM-1.7B.

    Returns (new_params_compute_dtype_device, new_state). The math is
    bit-identical to the on-device `scale_by_adam_low_moments` +
    `add_decayed_weights` + `scale_by_learning_rate` chain (and to
    optax.adamw for fp32 moments): offload changes WHERE state lives, not
    what the update computes — that is the whole point of keeping an fp32
    master. Each leaf's chain is h2d DMA -> fused elementwise -> d2h DMA;
    XLA's latency-hiding scheduler overlaps the DMAs of different leaves
    with each other and with neighboring compute."""
    b1, b2, eps = t.adam_beta1, t.adam_beta2, t.adam_eps
    wd = t.weight_decay
    mdt = jnp.bfloat16 if t.adam_moments_dtype == "bfloat16" else jnp.float32

    count = state.count + 1
    # optax evaluates the LR schedule at the PRE-increment count (the number
    # of updates already applied) while Adam's bias correction uses the
    # incremented count — mirror both exactly so the parity test holds.
    lr = _lr_at(t, state.count)
    c1 = 1 - b1 ** count.astype(jnp.float32)
    c2 = 1 - b2 ** count.astype(jnp.float32)

    # One combined scalar multiplier on g, applied inside the slice math:
    # the token-mean 1/count (grad_scale) and the global-norm clip. The
    # clip threshold compares against the SCALED grad norm — identical to
    # clipping after division, since ||s*g|| = s*||g||.
    scale = (jnp.asarray(1.0, jnp.float32) if grad_scale is None
             else jnp.asarray(grad_scale, jnp.float32))
    if t.grad_clip_norm > 0:
        gn = optax.global_norm(grads) * scale
        scale = scale * jnp.where(gn < t.grad_clip_norm, 1.0,
                                  t.grad_clip_norm / gn)

    def math(p, m, n, g):
        g = g.astype(jnp.float32) * scale
        m2 = b1 * m + (1 - b1) * g
        n2 = b2 * n + (1 - b2) * jnp.square(g)
        upd = (m2 / c1) / (jnp.sqrt(n2 / c2) + eps) + wd * p
        return p - lr * upd, m2, n2

    def leaf_plain(g, p_h, m_h, n_h):
        p2, m2, n2 = math(p_h, m_h.astype(jnp.float32),
                          n_h.astype(jnp.float32), g)
        return (p2, m2.astype(mdt), n2.astype(mdt),
                p2.astype(compute_dtype))

    def leaf_whole(g, p_h, m_h, n_h, s, token):
        dev = jax.sharding.NamedSharding(s.mesh, s.spec,
                                         memory_kind="device")
        host = jax.sharding.NamedSharding(s.mesh, s.spec,
                                          memory_kind=memory_kind)
        # Sequence this leaf's h2d DMAs after the previous leaf's update
        # compute: without the barrier XLA hoists every leaf's master +
        # moment transfers to the front of the update, and ~15 GB of fp32
        # state is live on device at once (measured: 17.6 GB peak, OOM).
        p_h, m_h, n_h, token = lax.optimization_barrier(
            (p_h, m_h, n_h, token))
        p = jax.device_put(p_h, dev)
        m = jax.device_put(m_h, dev).astype(jnp.float32)
        n = jax.device_put(n_h, dev).astype(jnp.float32)
        p2, m2, n2 = math(p, m, n, g)
        token, p2 = lax.optimization_barrier((token, p2))
        return (jax.device_put(p2, host),
                jax.device_put(m2.astype(mdt), host),
                jax.device_put(n2.astype(mdt), host),
                p2.astype(compute_dtype)), token

    def leaf_scanned(g, p_h, m_h, n_h, s, token, n_iters):
        # Stream the leaf through the device in n_iters slices along axis 0:
        # lax.scan's per-iteration dynamic-slice reads directly from the
        # pinned-host buffer (one h2d DMA per slice) and the stacked outputs
        # dynamic-update-slice back into a pinned-host result, so at most
        # ~two ~128 MB slices of fp32 state are device-resident at any
        # point. The reshape on the host operand is a bitcast (contiguous).
        shape = p_h.shape
        folded = (n_iters, shape[0] // n_iters) + shape[1:]
        entries = tuple(s.spec) + (None,) * (len(shape) - len(s.spec))
        slice_spec = jax.sharding.PartitionSpec(*entries)
        dev = jax.sharding.NamedSharding(s.mesh, slice_spec,
                                         memory_kind="device")
        host = jax.sharding.NamedSharding(s.mesh, slice_spec,
                                          memory_kind=memory_kind)

        def body(tok, xs):
            p_sl, m_sl, n_sl, g_sl = xs
            # the token must DATA-DEPEND on each slice's work — a pass-
            # through carry would be forwarded to the scan's init by the
            # while-loop simplifier, severing the inter-leaf ordering chain
            # (code review r4) and re-opening the transfer-hoisting OOM
            # leaf_whole guards against
            p_sl, tok = lax.optimization_barrier((p_sl, tok))
            p = jax.device_put(p_sl, dev)
            m = jax.device_put(m_sl, dev).astype(jnp.float32)
            n = jax.device_put(n_sl, dev).astype(jnp.float32)
            p2, m2, n2 = math(p, m, n, g_sl)
            tok, p2 = lax.optimization_barrier((tok, p2))
            return tok, (jax.device_put(p2, host),
                         jax.device_put(m2.astype(mdt), host),
                         jax.device_put(n2.astype(mdt), host),
                         p2.astype(compute_dtype))

        token, (p2, m2, n2, pb) = lax.scan(
            body, token,
            (p_h.reshape(folded), m_h.reshape(folded), n_h.reshape(folded),
             g.reshape(folded)))
        return (p2.reshape(shape), m2.reshape(shape), n2.reshape(shape),
                pb.reshape(shape)), token

    def n_scan_iters(p_h, s) -> int:
        """Slices to stream a leaf in (1 = whole-leaf). Only leaves whose
        axis 0 is effectively unsharded stream sliced — slicing a genuinely
        sharded axis under GSPMD would insert gathers. (A dim "sharded"
        over size-1 mesh axes is unsharded.)"""
        shape = p_h.shape
        if len(shape) < 2 or shape[0] <= 1:
            return 1
        entries = tuple(s.spec) + (None,) * (len(shape) - len(s.spec))
        e0 = entries[0]
        if e0 is not None:
            axes = e0 if isinstance(e0, (tuple, list)) else (e0,)
            size = 1
            for a in axes:
                size *= s.mesh.shape[a]
            if size > 1:
                return 1
        want = max(1, round(p_h.nbytes / _OFFLOAD_SLICE_BYTES))
        n = min(want, shape[0])
        while shape[0] % n:
            n -= 1
        return n

    token = jnp.zeros((), jnp.float32)
    g_leaves, treedef = jax.tree.flatten(grads)
    p_leaves = treedef.flatten_up_to(state.master)
    m_leaves = treedef.flatten_up_to(state.mu)
    n_leaves = treedef.flatten_up_to(state.nu)
    s_leaves = treedef.flatten_up_to(shardings)
    out = []
    for g, p_h, m_h, n_h, s in zip(g_leaves, p_leaves, m_leaves, n_leaves,
                                   s_leaves):
        if memory_kind is None:
            out.append(leaf_plain(g, p_h, m_h, n_h))
            continue
        n_iters = n_scan_iters(p_h, s)
        if n_iters == 1:
            o, token = leaf_whole(g, p_h, m_h, n_h, s, token)
        else:
            o, token = leaf_scanned(g, p_h, m_h, n_h, s, token, n_iters)
        out.append(o)
    pick = lambda i: jax.tree.unflatten(  # noqa: E731
        treedef, [o[i] for o in out])
    new_state = OffloadAdamState(count=count, master=pick(0), mu=pick(1),
                                 nu=pick(2))
    return pick(3), new_state


def make_optimizer(t: TrainingConfig) -> optax.GradientTransformation:
    lr = make_lr(t)
    steps = [] if t.grad_clip_norm <= 0 else [optax.clip_by_global_norm(t.grad_clip_norm)]
    if t.adam_moments_dtype == "bfloat16":
        steps += [
            scale_by_adam_low_moments(t.adam_beta1, t.adam_beta2, t.adam_eps,
                                      jnp.bfloat16),
            optax.add_decayed_weights(t.weight_decay),
            optax.scale_by_learning_rate(lr),
        ]
    else:
        steps.append(
            optax.adamw(
                learning_rate=lr,
                b1=t.adam_beta1,
                b2=t.adam_beta2,
                eps=t.adam_eps,
                weight_decay=t.weight_decay,
            )
        )
    return optax.chain(*steps)
