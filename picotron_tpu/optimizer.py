"""Optimizer: AdamW over fp32 master params.

The reference uses `torch.optim.AdamW(fused=True)` (ref: train.py:204-209) —
a CUDA kernel. On TPU, optax's adamw update is a handful of elementwise ops
that XLA fuses into one kernel per bucket automatically; no custom kernel is
needed (SURVEY.md §2.3 row `fused AdamW`).

`adam_moments_dtype: "bfloat16"` stores both Adam moments in bf16 (compute
still fp32): moment memory halves, which is what lets full-depth
SmolLM-1.7B's optimizer state fit a single 16G v5e chip. The reference has
no low-precision optimizer option; this is a TPU-memory-driven extension.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from picotron_tpu.config import TrainingConfig


def scale_by_adam_low_moments(b1: float, b2: float, eps: float,
                              moments_dtype) -> optax.GradientTransformation:
    """scale_by_adam with BOTH moments stored in `moments_dtype` (optax's
    mu_dtype covers only the first moment). The update math runs in fp32;
    only the carried state is rounded."""

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, dtype=moments_dtype)  # noqa: E731
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(zeros, params),
            nu=jax.tree.map(zeros, params),
        )

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        mu32 = jax.tree.map(
            lambda g, m: b1 * m.astype(jnp.float32)
            + (1 - b1) * g.astype(jnp.float32),
            updates, state.mu)
        nu32 = jax.tree.map(
            lambda g, n: b2 * n.astype(jnp.float32)
            + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            updates, state.nu)
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)
        out = jax.tree.map(
            lambda m, n: (m / c1) / (jnp.sqrt(n / c2) + eps), mu32, nu32)
        new_state = optax.ScaleByAdamState(
            count=count,
            mu=jax.tree.map(lambda m: m.astype(moments_dtype), mu32),
            nu=jax.tree.map(lambda n: n.astype(moments_dtype), nu32),
        )
        return out, new_state

    return optax.GradientTransformation(init, update)


def make_lr(t: TrainingConfig):
    """Learning-rate schedule (a float or an optax schedule fn). The
    reference trains at constant LR (ref: train.py:209); warmup + cosine /
    linear decay are the standard pretraining extensions. Schedules are a
    pure function of the optimizer step count, which lives in the restored
    optimizer state — resume continues the schedule where it left off."""
    if t.lr_schedule == "constant" and t.lr_warmup_steps == 0:
        return t.learning_rate
    peak, floor = t.learning_rate, t.learning_rate * t.lr_min_ratio
    decay_steps = max(1, t.total_train_steps - t.lr_warmup_steps)
    if t.lr_schedule == "cosine":
        decay = optax.cosine_decay_schedule(peak, decay_steps,
                                            alpha=t.lr_min_ratio)
    elif t.lr_schedule == "linear":
        decay = optax.linear_schedule(peak, floor, decay_steps)
    else:  # constant with warmup
        decay = optax.constant_schedule(peak)
    if t.lr_warmup_steps == 0:
        return decay
    return optax.join_schedules(
        [optax.linear_schedule(0.0, peak, t.lr_warmup_steps), decay],
        boundaries=[t.lr_warmup_steps])


def make_optimizer(t: TrainingConfig) -> optax.GradientTransformation:
    lr = make_lr(t)
    steps = [] if t.grad_clip_norm <= 0 else [optax.clip_by_global_norm(t.grad_clip_norm)]
    if t.adam_moments_dtype == "bfloat16":
        steps += [
            scale_by_adam_low_moments(t.adam_beta1, t.adam_beta2, t.adam_eps,
                                      jnp.bfloat16),
            optax.add_decayed_weights(t.weight_decay),
            optax.scale_by_learning_rate(lr),
        ]
    else:
        steps.append(
            optax.adamw(
                learning_rate=lr,
                b1=t.adam_beta1,
                b2=t.adam_beta2,
                eps=t.adam_eps,
                weight_decay=t.weight_decay,
            )
        )
    return optax.chain(*steps)
