"""Optimizer: AdamW over fp32 master params.

The reference uses `torch.optim.AdamW(fused=True)` (ref: train.py:204-209) —
a CUDA kernel. On TPU, optax's adamw update is a handful of elementwise ops
that XLA fuses into one kernel per bucket automatically; no custom kernel is
needed (SURVEY.md §2.3 row `fused AdamW`).

`adam_moments_dtype: "bfloat16"` stores both Adam moments in bf16 (compute
still fp32): moment memory halves, which is what lets full-depth
SmolLM-1.7B's optimizer state fit a single 16G v5e chip. The reference has
no low-precision optimizer option; this is a TPU-memory-driven extension.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from picotron_tpu.config import TrainingConfig


def scale_by_adam_low_moments(b1: float, b2: float, eps: float,
                              moments_dtype) -> optax.GradientTransformation:
    """scale_by_adam with BOTH moments stored in `moments_dtype` (optax's
    mu_dtype covers only the first moment). The update math runs in fp32;
    only the carried state is rounded."""

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, dtype=moments_dtype)  # noqa: E731
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(zeros, params),
            nu=jax.tree.map(zeros, params),
        )

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        mu32 = jax.tree.map(
            lambda g, m: b1 * m.astype(jnp.float32)
            + (1 - b1) * g.astype(jnp.float32),
            updates, state.mu)
        nu32 = jax.tree.map(
            lambda g, n: b2 * n.astype(jnp.float32)
            + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            updates, state.nu)
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)
        out = jax.tree.map(
            lambda m, n: (m / c1) / (jnp.sqrt(n / c2) + eps), mu32, nu32)
        new_state = optax.ScaleByAdamState(
            count=count,
            mu=jax.tree.map(lambda m: m.astype(moments_dtype), mu32),
            nu=jax.tree.map(lambda n: n.astype(moments_dtype), nu32),
        )
        return out, new_state

    return optax.GradientTransformation(init, update)


def make_lr(t: TrainingConfig):
    """Learning-rate schedule (a float or an optax schedule fn). The
    reference trains at constant LR (ref: train.py:209); warmup + cosine /
    linear decay are the standard pretraining extensions. Schedules are a
    pure function of the optimizer step count, which lives in the restored
    optimizer state — resume continues the schedule where it left off."""
    if t.lr_schedule == "constant" and t.lr_warmup_steps == 0:
        return t.learning_rate
    peak, floor = t.learning_rate, t.learning_rate * t.lr_min_ratio
    decay_steps = max(1, t.total_train_steps - t.lr_warmup_steps)
    if t.lr_schedule == "cosine":
        decay = optax.cosine_decay_schedule(peak, decay_steps,
                                            alpha=t.lr_min_ratio)
    elif t.lr_schedule == "linear":
        decay = optax.linear_schedule(peak, floor, decay_steps)
    else:  # constant with warmup
        decay = optax.constant_schedule(peak)
    if t.lr_warmup_steps == 0:
        return decay
    return optax.join_schedules(
        [optax.linear_schedule(0.0, peak, t.lr_warmup_steps), decay],
        boundaries=[t.lr_warmup_steps])


# Minimum fp32-master bytes per streamed-update slice for axis-0 scanning
# to beat a whole-leaf transfer: ~16 MB slices already run ~4 GB/s on v5e
# (measured; the per-iteration latency floor dominates below that), and
# tiny leaves (norms) go whole-leaf through the barrier chain instead.
_OFFLOAD_MIN_SLICE_BYTES = 4 * 2 ** 20
# Target fp32-master bytes per ROW GROUP when streaming big-axis-0 leaves
# (embedding/lm_head): ~32 MB groups measured 4.0 GB/s via
# dynamic_slice_in_dim on the pinned-host buffer.
_OFFLOAD_ROW_GROUP_BYTES = 32 * 2 ** 20


class OffloadAdamState(NamedTuple):
    """Optimizer state for `training.optimizer_offload`: the fp32 master
    params and both Adam moments live in pinned HOST memory (their leaves
    carry `memory_kind='pinned_host'` shardings); only the step counter is a
    device scalar. TrainState.params is then the bf16 device compute copy —
    the master moves INTO the optimizer state, which is where it
    conceptually belongs (it exists only for the update)."""

    count: jnp.ndarray  # int32 scalar, device
    master: Any         # fp32 pytree, pinned_host
    mu: Any             # adam_moments_dtype pytree, pinned_host
    nu: Any             # adam_moments_dtype pytree, pinned_host


def _lr_at(t: TrainingConfig, count):
    lr = make_lr(t)
    return lr(count) if callable(lr) else jnp.asarray(lr, jnp.float32)


def _global_sq_norm(grads, clip_specs):
    """Global grad norm under shard_map: per-leaf local sum-of-squares,
    psum'd over the mesh axes the leaf is SHARDED over (its PartitionSpec
    axes — distinct shards sum to the global total; replicated leaves need
    no collective and must not double-count). clip_specs None = local norm
    (outside shard_map / single device)."""
    total = jnp.zeros((), jnp.float32)
    if clip_specs is None:
        for g in jax.tree.leaves(grads):
            total += jnp.sum(jnp.square(g.astype(jnp.float32)))
        return jnp.sqrt(total)
    from jax.sharding import PartitionSpec as P

    g_leaves, treedef = jax.tree.flatten(grads)
    s_leaves = jax.tree.leaves(clip_specs,
                               is_leaf=lambda x: isinstance(x, P))
    for g, spec in zip(g_leaves, s_leaves):
        s = jnp.sum(jnp.square(g.astype(jnp.float32)))
        axes = tuple(a for part in spec if part is not None
                     for a in (part if isinstance(part, (tuple, list))
                               else (part,)))
        # scalar psums (one fp32 each): latency-only, per-leaf axis sets
        # differ so they cannot batch into one op
        total += lax.psum(s, axes) if axes else s  # shardcheck: ok
    return jnp.sqrt(total)


def offload_adam_update(grads, state: OffloadAdamState, t: TrainingConfig,
                        compute_dtype, *, transfer: bool = True,
                        clip_specs=None, grad_scale=None, zero1_info=None):
    """One AdamW step streamed through the device, leaf by leaf — written
    in PER-DEVICE terms so it runs INSIDE the train step's shard_map body:
    every operand is this device's local shard, and host<->device movement
    uses memory-space-only transfers (`jax.device_put(x, MemorySpace)`),
    which carry no resharding semantics. Fusing the update into the grad
    shard_map is load-bearing for memory: grads leaving a shard_map as
    outputs cost a SECOND full fp32 tree (the while-loop grad carry cannot
    alias a boundary output — measured 6-7 GB of waste at SmolLM-1.7B
    scale, PERF.md r4).

    grads: fp32 local grad shards (data-axis-psum'd, NOT yet divided).
    transfer False (CPU test meshes) runs the identical math without
    placement transfers. clip_specs: the params' PartitionSpec tree, for
    the cross-shard grad-norm psum (None = local norm). grad_scale (e.g.
    1/token_count) is folded into the per-slice math so the caller never
    materializes a divided copy of the grad tree. zero1_info (from
    api.offload_zero1_info): per-flattened-leaf (dim, axes, axis_sizes)
    ZeRO-1 placements — the host state arrives sharded over the fused
    data axes, so each process slices its shard out of the (replicated)
    grads, updates 1/dp of the state, and all-gathers the refreshed
    compute-dtype params back to full size at the end. The math per
    element is unchanged; zero1 changes WHICH process updates it.

    Returns (new_params_compute_dtype, new_state). The math is
    bit-identical to the on-device `scale_by_adam_low_moments` +
    `add_decayed_weights` + `scale_by_learning_rate` chain (and to
    optax.adamw for fp32 moments): offload changes WHERE state lives, not
    what the update computes."""
    if transfer:
        from picotron_tpu.compat import memory_space_puts

    b1, b2, eps = t.adam_beta1, t.adam_beta2, t.adam_eps
    wd = t.weight_decay
    mdt = jnp.bfloat16 if t.adam_moments_dtype == "bfloat16" else jnp.float32

    count = state.count + 1
    # optax evaluates the LR schedule at the PRE-increment count (the number
    # of updates already applied) while Adam's bias correction uses the
    # incremented count — mirror both exactly so the parity test holds.
    lr = _lr_at(t, state.count)
    c1 = 1 - b1 ** count.astype(jnp.float32)
    c2 = 1 - b2 ** count.astype(jnp.float32)

    # One combined scalar multiplier on g, applied inside the slice math:
    # the token-mean 1/count (grad_scale) and the global-norm clip. The
    # clip threshold compares against the SCALED grad norm — identical to
    # clipping after division, since ||s*g|| = s*||g||.
    scale = (jnp.asarray(1.0, jnp.float32) if grad_scale is None
             else jnp.asarray(grad_scale, jnp.float32))
    if t.grad_clip_norm > 0:
        gn = _global_sq_norm(grads, clip_specs) * scale
        scale = scale * jnp.where(gn < t.grad_clip_norm, 1.0,
                                  t.grad_clip_norm / gn)

    if transfer:
        to_dev, to_host = memory_space_puts()
    else:
        to_dev = to_host = lambda x: x

    def math(p, m, n, g):
        g = g.astype(jnp.float32) * scale
        m2 = b1 * m + (1 - b1) * g
        n2 = b2 * n + (1 - b2) * jnp.square(g)
        upd = (m2 / c1) / (jnp.sqrt(n2 / c2) + eps) + wd * p
        return p - lr * upd, m2, n2

    def leaf_plain(g, p_h, m_h, n_h):
        p2, m2, n2 = math(p_h, m_h.astype(jnp.float32),
                          n_h.astype(jnp.float32), g)
        return (p2, m2.astype(mdt), n2.astype(mdt),
                p2.astype(compute_dtype))

    def leaf_whole(g, p_h, m_h, n_h, token):
        # Sequence this leaf's h2d DMAs after the previous leaf's update
        # compute: without the barrier XLA hoists every leaf's master +
        # moment transfers to the front of the update, and ~15 GB of fp32
        # state is live on device at once (measured: 17.6 GB peak, OOM).
        p_h, m_h, n_h, token = lax.optimization_barrier(
            (p_h, m_h, n_h, token))
        p = to_dev(p_h)
        m = to_dev(m_h).astype(jnp.float32)
        n = to_dev(n_h).astype(jnp.float32)
        p2, m2, n2 = math(p, m, n, g)
        token, p2 = lax.optimization_barrier((token, p2))
        return (to_host(p2),
                to_host(m2.astype(mdt)),
                to_host(n2.astype(mdt)),
                p2.astype(compute_dtype)), token

    def group_scanned(members, token):
        # Stream a GROUP of equal-depth stacked leaves through the device
        # one axis-0 slice (= one layer of each local stacked-tree shard)
        # at a time: lax.scan's per-iteration dynamic-slices read directly
        # from the pinned-host buffers (one h2d DMA per leaf per slice)
        # and the stacked outputs dynamic-update-slice back into
        # pinned-host results, so at most ~two layers' worth of fp32 state
        # is device-resident at any point. Fusing every same-depth leaf
        # into ONE scan (instead of one scan per leaf, r4) lets the DMA
        # engines pipeline all the leaves' slice transfers within an
        # iteration — leaf-serial scans measured 21 GB/s aggregate on the
        # 64 MB-slice MLP leaves vs 43 GB/s on the smaller qkv slices; the
        # fused scan keeps every engine fed (PERF.md r5). Slicing MUST be
        # each leaf's own leading axis: reshaping the host operand to fold
        # layers into bigger chunks drops the async-DMA fast path
        # (measured 4.8 -> 1.7 GB/s, PERF.md r4).
        def body(tok, xs):
            p2s, outs = [], []
            for p_sl, m_sl, n_sl, g_sl in xs:
                p = to_dev(p_sl)
                m = to_dev(m_sl).astype(jnp.float32)
                n = to_dev(n_sl).astype(jnp.float32)
                p2, m2, n2 = math(p, m, n, g_sl)
                p2s.append(p2)
                outs.append((m2, n2))
            # the token must DATA-DEPEND on the slice work — a pass-through
            # carry would be forwarded to the scan's init by the while-loop
            # simplifier, severing the inter-leaf ordering chain that
            # leaf_whole's barriers hang off (code review r4). Output-side
            # only: an input-side barrier too was measured ~10% slower
            # (it serializes the h2d against the previous iteration). One
            # barrier over the whole group: intra-group transfers stay
            # unordered (that is the parallelism), inter-iteration memory
            # stays bounded.
            bar = lax.optimization_barrier(tuple(p2s) + (tok,))
            p2s, tok = bar[:-1], bar[-1]
            return tok, tuple(
                (to_host(p2), to_host(m2.astype(mdt)),
                 to_host(n2.astype(mdt)), p2.astype(compute_dtype))
                for p2, (m2, n2) in zip(p2s, outs))

        xs = tuple((p_leaves[i], m_leaves[i], n_leaves[i], g_leaves[i])
                   for i in members)
        token, outs = lax.scan(body, token, xs)
        return outs, token

    def leaf_scanned_rows(g, p_h, m_h, n_h, token, group):
        # Row-group streaming for leaves whose axis 0 is a big vocab/
        # feature dim (embedding, lm_head): explicit dynamic_slice_in_dim
        # with a computed offset keeps the async host-DMA fast path
        # (measured 4.0 GB/s — a host RESHAPE to fold rows would drop it
        # to 1.7) while capping the device-resident transient at one
        # ~32 MB group instead of the whole 400 MB leaf chain.
        n = p_h.shape[0] // group

        def body(tok, i):
            def sl(x):
                return lax.dynamic_slice_in_dim(x, i * group, group, 0)

            p = to_dev(sl(p_h))
            m = to_dev(sl(m_h)).astype(jnp.float32)
            nn = to_dev(sl(n_h)).astype(jnp.float32)
            p2, m2, n2 = math(p, m, nn, sl(g))
            tok, p2 = lax.optimization_barrier((tok, p2))
            return tok, (to_host(p2),
                         to_host(m2.astype(mdt)),
                         to_host(n2.astype(mdt)),
                         p2.astype(compute_dtype))

        token, ys = lax.scan(body, token, jnp.arange(n))
        shape = p_h.shape
        out = tuple(y.reshape(shape) for y in ys)
        return out, token

    def row_group(p_h) -> int:
        """Group size for leaf_scanned_rows (0 = not applicable): a
        divisor of axis 0 whose group stays near _OFFLOAD_ROW_GROUP_BYTES.
        Searches below the target first, then up to 4x above it, so vocab
        sizes without a divisor right at the target still stream (e.g.
        49152/151936/128256 all do). A genuinely prime-ish axis 0 (GPT-2's
        50257) has no usable divisor and falls back to the whole-leaf
        path — acceptable: its transient is one leaf, and scan slices
        must be uniform."""
        shape = p_h.shape
        if len(shape) < 2 or shape[0] <= 1024:
            return 0
        row_bytes = p_h.nbytes // shape[0]
        target = max(1, _OFFLOAD_ROW_GROUP_BYTES // max(row_bytes, 1))
        gsz = min(target, shape[0])
        while gsz > 1 and shape[0] % gsz:
            gsz -= 1
        if gsz > 1 and gsz * row_bytes >= _OFFLOAD_MIN_SLICE_BYTES \
                and gsz < shape[0]:
            return gsz
        # nothing usable at-or-below the target: take the smallest divisor
        # above it (bounded, so the transient stays within ~4x the target)
        for cand in range(target + 1, min(4 * target, shape[0] - 1) + 1):
            if shape[0] % cand == 0:
                return cand
        return 0

    def scannable(p_h) -> bool:
        """Stream sliced along axis 0 (one slice per stacked layer of the
        LOCAL shard — inside shard_map the leading axis is always safe to
        slice)? Short enough to be a layer stack rather than a
        vocab/feature dim, big enough per slice for the DMA to run near
        peak."""
        shape = p_h.shape
        if len(shape) < 2 or not 2 <= shape[0] <= 1024:
            return False
        return p_h.nbytes // shape[0] >= _OFFLOAD_MIN_SLICE_BYTES

    # One ordering token PER VMA CLASS (the set of mesh axes a leaf varies
    # over inside shard_map): the optimization_barrier chain joins the
    # varying-axes type of everything it groups, so a single token would
    # leak e.g. the embedding's {tp} onto the replicated norms' outputs and
    # fail the out_specs vma check. Leaves of the same class (in practice:
    # all the big tp-sharded matrices) still chain — which is where the
    # DMA-hoisting memory bound matters; the off-class leaves are the KB-
    # sized norms. Outside shard_map every vma is empty and this is one
    # global token, exactly the old behavior.
    tokens: dict = {}

    def token_for(leaf):
        from picotron_tpu import compat

        key = compat.vma(leaf)
        if key not in tokens:
            tok = jnp.zeros((), jnp.float32)
            if key:  # only ever non-empty when the vma types exist
                tok = lax.pvary(tok, tuple(sorted(key)))
            tokens[key] = tok
        return key, tokens[key]

    g_leaves, treedef = jax.tree.flatten(grads)
    p_leaves = treedef.flatten_up_to(state.master)
    m_leaves = treedef.flatten_up_to(state.mu)
    n_leaves = treedef.flatten_up_to(state.nu)
    # ZeRO-1: slice each leaf's (replicated) grads down to this process's
    # state shard. The global-norm clip above already consumed the FULL
    # grad tree, so the clip scale is identical on every shard.
    if zero1_info is not None:
        def z1_slice(g, place):
            if place is None:
                return g
            dim, axes, sizes = place
            idx = jnp.zeros((), jnp.int32)
            for a, s in zip(axes, sizes):
                idx = idx * s + lax.axis_index(a)
            n_shards = 1
            for s in sizes:
                n_shards *= s
            shard = g.shape[dim] // n_shards
            return lax.dynamic_slice_in_dim(g, idx * shard, shard, dim)

        g_leaves = [z1_slice(g, pl)
                    for g, pl in zip(g_leaves, zero1_info)]
    # Squeeze leading unit dims so single-layer stacks still stream: a
    # 1-layer model's stacked expert bank is [1, E, H, I] — axis 0 of
    # size 1 would fall through to leaf_whole and put the entire
    # multi-GB master in flight at once (measured: the Mixtral-8x7B-1L
    # row OOM'd by 2.6 GB, PERF.md r5). Dropping the unit dim is a
    # layout-preserving view (unlike the dim-folding reshapes that kill
    # the async-DMA fast path), so the bank streams along its expert
    # axis; outputs reshape back below.
    lead1 = [p.ndim >= 3 and p.shape[0] == 1 for p in p_leaves]
    if transfer:
        sq = lambda t: t.reshape(t.shape[1:])  # noqa: E731
        p_leaves = [sq(p) if s else p for p, s in zip(p_leaves, lead1)]
        m_leaves = [sq(m) if s else m for m, s in zip(m_leaves, lead1)]
        n_leaves = [sq(n) if s else n for n, s in zip(n_leaves, lead1)]
        g_leaves = [sq(g) if s else g for g, s in zip(g_leaves, lead1)]
    # collect the scannable leaves into same-(vma, depth) groups so each
    # group streams as one fused scan (group_scanned)
    groups: dict = {}
    if transfer:
        for i, p_h in enumerate(p_leaves):
            if scannable(p_h):
                key, _ = token_for(p_h)
                groups.setdefault((key, p_h.shape[0]), []).append(i)
    out: list = [None] * len(g_leaves)
    for i, (g, p_h, m_h, n_h) in enumerate(
            zip(g_leaves, p_leaves, m_leaves, n_leaves)):
        if out[i] is not None:
            continue  # filled by an earlier member's fused group scan
        if not transfer:
            out[i] = leaf_plain(g, p_h, m_h, n_h)
            continue
        key, token = token_for(p_h)
        if scannable(p_h):
            members = groups[(key, p_h.shape[0])]
            os_, tokens[key] = group_scanned(members, token)
            for j, o in zip(members, os_):
                out[j] = o
        elif (grp := row_group(p_h)):
            o, tokens[key] = leaf_scanned_rows(g, p_h, m_h, n_h, token, grp)
            out[i] = o
        else:
            o, tokens[key] = leaf_whole(g, p_h, m_h, n_h, token)
            out[i] = o
    if transfer and any(lead1):
        out = [tuple(t.reshape((1,) + t.shape) for t in o) if s else o
               for o, s in zip(out, lead1)]
    # Under zero1 the compute-dtype params leave this function still
    # SHARDED over the zero1 axes (each process computed only its 1/dp);
    # the caller re-gathers them with a GSPMD sharding constraint outside
    # the shard_map — shard_map's varying-axes checker cannot statically
    # see that an all_gather of per-shard updates is replicated, while
    # the SPMD partitioner's resharding is invariant by construction.
    pick = lambda i: jax.tree.unflatten(  # noqa: E731
        treedef, [o[i] for o in out])
    new_state = OffloadAdamState(count=count, master=pick(0), mu=pick(1),
                                 nu=pick(2))
    return pick(3), new_state


def make_optimizer(t: TrainingConfig) -> optax.GradientTransformation:
    lr = make_lr(t)
    steps = [] if t.grad_clip_norm <= 0 else [optax.clip_by_global_norm(t.grad_clip_norm)]
    if t.adam_moments_dtype == "bfloat16":
        steps += [
            scale_by_adam_low_moments(t.adam_beta1, t.adam_beta2, t.adam_eps,
                                      jnp.bfloat16),
            optax.add_decayed_weights(t.weight_decay),
            optax.scale_by_learning_rate(lr),
        ]
    else:
        steps.append(
            optax.adamw(
                learning_rate=lr,
                b1=t.adam_beta1,
                b2=t.adam_beta2,
                eps=t.adam_eps,
                weight_decay=t.weight_decay,
            )
        )
    return optax.chain(*steps)
