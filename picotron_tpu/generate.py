"""Autoregressive generation with a KV cache (beyond the reference, which
is training-only — a framework needs a decode path to inspect what it
trained).

TPU-first decode design:

- **Static shapes throughout**: the cache is allocated at `max_length` up
  front; prefill writes the prompt's K/V in one batched pass, and each
  decode step updates one slot via `lax.dynamic_update_slice` inside a
  `lax.scan` — one compiled program for any prompt/generation length up to
  the cap, no retracing per token.
- **Attention against the cache is plain jnp** (fp32 softmax over
  [B, Hq, s, S_max]): decode is a GEMV-shaped, HBM-bound workload where a
  flash kernel buys nothing; XLA fuses the mask/softmax fine. GQA stays
  unexpanded in the cache (Hkv heads) and queries are grouped at score
  time, so cache memory is Hkv/Hq of the naive layout.
- **Weight-compatible with training**: same param pytree (train ->
  generate without conversion), same RoPE/RMSNorm helpers, and the MLP /
  MoE blocks are the training ones (a Mixtral checkpoint decodes through
  the same capacity-bounded expert dispatch it trained with).

Decode at target scale (VERDICT r3 weak #6 — a trained Llama-2-7B's fp32
master cannot be sampled on one 16 GB chip):

- **bf16 load**: `tools/generate.py --load-dtype bfloat16` restores the
  checkpoint straight into bf16 (Orbax casts during restore — the fp32
  tree never materializes): 7B params = 13.5 GB, which fits one v5e chip
  with the KV cache for short contexts. Decode compute is bf16 either way,
  so sampling output is unchanged.
- **tp-sharded decode**: `place_for_decode(params, cfg, tp=N)` re-places
  the same param tree into the training TP shardings (column/row/vocab
  parallel, parallel/sharding.py) over an N-chip mesh; `generate` is pure
  GSPMD, so XLA propagates the shardings through the cache and inserts the
  TP collectives itself — no shard_map, no second decode path, greedy
  parity with single-device pinned by test.

Sampling: greedy (temperature=0), temperature, and top-k.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.config import ModelConfig
from picotron_tpu.models.llama import (
    DEFAULT_CTX, _mlp_block, _moe_block, compute_dtype, final_hidden,
    head_weight, model_rope_tables, qkv_proj, rms_norm,
)
from picotron_tpu.ops.rope import apply_rope


class KVCache(NamedTuple):
    """Per-layer contiguous key/value cache, [L, B, S_max, Hkv, D] each.

    One of the two cache implementations `_decode_layers` runs against
    (the other is `serve.paged_cache.PagedKVCache`); both expose the same
    interface — `num_layers`, `write(li, k, v, q_pos)`,
    `layer_view(li)` — so the layer loop is cache-agnostic and greedy
    parity between the two is a test invariant, not an accident."""

    k: jnp.ndarray
    v: jnp.ndarray

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    def write(self, li, k_new, v_new, q_pos) -> "KVCache":
        """Write this segment's K/V [B, s, Hkv, D] into slots
        q_pos[0]..q_pos[-1] of layer li. Contiguous slots only: needs the
        batch-shared [s] positions form (every sequence at the same
        offset — the offline `generate` arrangement)."""
        start = q_pos[0]
        ck = lax.dynamic_update_slice(self.k, k_new[None],
                                      (li, 0, start, 0, 0))
        cv = lax.dynamic_update_slice(self.v, v_new[None],
                                      (li, 0, start, 0, 0))
        return KVCache(ck, cv)

    def layer_view(self, li):
        """([B, S_max, Hkv, D], same) view of layer li, slot j holding
        the token at position j."""
        return (lax.dynamic_index_in_dim(self.k, li, 0, keepdims=False),
                lax.dynamic_index_in_dim(self.v, li, 0, keepdims=False))


def init_cache(cfg: ModelConfig, batch: int, max_length: int) -> KVCache:
    shape = (cfg.num_hidden_layers, batch, max_length,
             cfg.num_key_value_heads, cfg.head_dim)
    dt = compute_dtype(cfg)
    return KVCache(jnp.zeros(shape, dt), jnp.zeros(shape, dt))


def _rope(x, cos, sin, q_pos):
    """apply_rope over either positions form: [s] (batch-shared — the
    offline path) or [B, s] (per-sequence — continuous batching, where
    every slot sits at its own depth). Negative positions (chunk padding
    in the serving prefill) rotate by position 0; their K/V never lands
    in a cache (sentinel-dropped) and their outputs are discarded."""
    if q_pos.ndim == 1:
        return apply_rope(x, cos, sin, jnp.maximum(q_pos, 0))
    c = cos[jnp.maximum(q_pos, 0)][:, :, None, :]  # [B, s, 1, D/2]
    s_ = sin[jnp.maximum(q_pos, 0)][:, :, None, :]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * c - x2 * s_, x2 * c + x1 * s_],
                           axis=-1).astype(x.dtype)


def _cached_attention(q, ck, cv, q_pos):
    """q: [B, s, Hq, D] at global positions q_pos ([s] batch-shared or
    [B, s] per-sequence); ck/cv: [B, S_max, Hkv, D] with slot j holding
    the token at position j (zeros/stale beyond the filled length —
    masked out by causality, since every filled slot index <= max(q_pos);
    exact zeros under softmax leave the valid rows bit-identical for any
    S_max). Returns [B, s, Hq, D]."""
    b, s, hq, d = q.shape
    s_max, hkv = ck.shape[1], ck.shape[2]
    group = hq // hkv
    qg = q.reshape(b, s, hkv, group, d)
    # [B, Hkv, G, s, S_max]
    scores = jnp.einsum("bshgd,bthd->bhgst", qg, ck).astype(jnp.float32)
    scores = scores / (d ** 0.5)
    # negative q_pos (serving chunk padding) clamps to 0 so the row stays
    # finite (an all-masked row softmaxes to NaN and poisons the residual
    # stream for positions whose output IS discarded, but which still
    # flows through later layers)
    mask = jnp.arange(s_max) <= jnp.maximum(q_pos, 0)[..., None]
    if mask.ndim == 2:          # [s, S_max] batch-shared
        mask = mask[None]
    mask = mask[:, None, None]  # [B|1, 1, 1, s, S_max]
    scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgst,bthd->bshgd", p, cv)
    return out.reshape(b, s, hq, d)


def _decode_layers(params, x, cache, q_pos, cfg: ModelConfig, cos, sin):
    """Run every layer over x [B, s, H] (prefill: s = prompt length,
    decode: s = 1), writing this segment's K/V into the cache at positions
    q_pos. Cache-agnostic: `cache` is any object with num_layers /
    write / layer_view (contiguous KVCache here, PagedKVCache in
    picotron_tpu/serve). Returns (hidden, cache)."""
    dt = x.dtype
    d = cfg.head_dim

    # The cache rides the scan CARRY with per-layer in-place writes of
    # only the new token slots. Feeding it through as xs/ys instead (r4
    # structure) made every decode step rewrite the full cache — the scan
    # stacks fresh ys buffers — and the token-loop carry copy doubled it:
    # profiled at 2x 2.75 ms of pure cache copies per token at
    # SmolLM-1.7B batch 8 (~half the decode step; PERF.md r5).
    def body(carry, inputs):
        x, cache = carry
        lp, li = inputs
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        b, s, _ = h.shape
        q, k, v = qkv_proj(h, lp, d)
        q = _rope(q, cos, sin, q_pos)
        k = _rope(k, cos, sin, q_pos)
        cache = cache.write(li, k, v, q_pos)
        ck_l, cv_l = cache.layer_view(li)
        out = _cached_attention(q, ck_l, cv_l, q_pos)
        out = out.reshape(b, s, -1) @ lp["o"].astype(dt)
        x = x + out
        if cfg.num_experts:
            mlp_out, _ = _moe_block(x, lp, cfg, DEFAULT_CTX)
        else:
            mlp_out = _mlp_block(x, lp, cfg, DEFAULT_CTX)
        return (x + mlp_out, cache), None

    (x, cache), _ = lax.scan(
        body, (x, cache),
        (params["layers"], jnp.arange(cache.num_layers)))
    return x, cache


def _logits_last(params, x, cfg: ModelConfig):
    """Logits of the LAST position only: [B, V] fp32."""
    hf = final_hidden(params, x[:, -1:], cfg)
    return (hf @ head_weight(params).astype(hf.dtype))[:, 0].astype(jnp.float32)


def _sample(logits, temperature: float, top_k: int, key):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature",
                                   "top_k", "eos_token_id"))
def _generate_jit(params, prompt_ids, cfg: ModelConfig,
                  max_new_tokens: int, temperature: float, top_k: int,
                  eos_token_id: Optional[int], key):
    b, p_len = prompt_ids.shape
    max_len = p_len + max_new_tokens
    # Tables sized to the positions actually indexed (max_len), not the
    # preset's max_position_embeddings — Llama-3.1's 131072-position limit
    # would bake ~64 MB of cos/sin constants into every compiled variant.
    cos, sin = model_rope_tables(cfg, max_len=max_len)
    cache = init_cache(cfg, b, max_len)

    # prefill: one batched pass over the prompt
    x = params["embedding"][prompt_ids].astype(compute_dtype(cfg))
    x, cache = _decode_layers(params, x, cache, jnp.arange(p_len), cfg,
                              cos, sin)
    logits = _logits_last(params, x, cfg)
    key, sub = jax.random.split(key)
    tok = _sample(logits, temperature, top_k, sub)
    done = (jnp.full((b,), False) if eos_token_id is None
            else tok == eos_token_id)

    def decode_one(tok, cache, key, i):
        # iteration i feeds the token SAMPLED at step i-1, which sits at
        # sequence position p_len + i - 1 (an off-by-one here rotates RoPE
        # wrong, writes K/V one slot late, and attends a never-written
        # zero slot — caught by code review r3 + the greedy parity test)
        pos = p_len + i - 1
        x = params["embedding"][tok[:, None]].astype(compute_dtype(cfg))
        x, cache = _decode_layers(params, x, cache, pos[None], cfg, cos, sin)
        logits = _logits_last(params, x, cfg)
        key, sub = jax.random.split(key)
        nxt = _sample(logits, temperature, top_k, sub)
        return nxt, cache, key

    if eos_token_id is None:
        # no EOS: every step decodes — a fixed-trip scan
        def step(carry, i):
            tok, cache, key = carry
            nxt, cache, key = decode_one(tok, cache, key, i)
            return (nxt, cache, key), tok

        (last, _, _), toks = lax.scan(
            step, (tok, cache, key), jnp.arange(1, max_new_tokens))
        # toks stacks the PREVIOUS token per step; append the final one
        out = jnp.concatenate([toks.T, last[:, None]], axis=1)  # [B, N]
    else:
        # EOS given: a while_loop that stops as soon as EVERY row has
        # emitted EOS, instead of burning max_new_tokens decode steps on
        # finished sequences. The output buffer starts EOS-filled, so an
        # early exit leaves exactly the padding the scan path would have
        # produced (finished rows are forced to EOS either way) — token
        # parity between the two paths is pinned by test.
        out = jnp.full((b, max_new_tokens), eos_token_id, jnp.int32)
        out = out.at[:, 0].set(tok)

        def cond(carry):
            i, tok, cache, done, key, out = carry
            return (i < max_new_tokens) & ~done.all()

        def body(carry):
            i, tok, cache, done, key, out = carry
            nxt, cache, key = decode_one(tok, cache, key, i)
            nxt = jnp.where(done, eos_token_id, nxt)
            done = done | (nxt == eos_token_id)
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, i))
            return (i + 1, nxt, cache, done, key, out)

        (_, _, _, _, _, out) = lax.while_loop(
            cond, body, (jnp.asarray(1), tok, cache, done, key, out))
    return jnp.concatenate([prompt_ids, out], axis=1)


def place_for_decode(params, model_cfg: ModelConfig, tp: int = 1,
                     devices=None):
    """Re-place a param tree for tp-parallel decode: the training TP
    shardings (column/row/vocab parallel) over a tp-chip mesh. Returns the
    sharded tree; pass it to `generate` unchanged — jit picks the shardings
    up from the arrays and GSPMD inserts the collectives. tp=1 places on
    one device (the single-chip path)."""
    from picotron_tpu.config import Config, DistributedConfig, TrainingConfig
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.sharding import param_shardings

    devices = list(devices if devices is not None else jax.devices())
    # the training section is irrelevant to decode; seq_length=1 keeps
    # validate() focused on what matters here (head/vocab % tp)
    cfg = Config(distributed=DistributedConfig(tp_size=tp),
                 model=model_cfg,
                 training=TrainingConfig(seq_length=1))
    cfg.validate()
    menv = MeshEnv.create(tp=tp, devices=devices[:tp])
    return jax.tree.map(jax.device_put, params,
                        param_shardings(cfg, menv.mesh))


def generate(params, cfg: ModelConfig, prompt_ids, max_new_tokens: int,
             *, temperature: float = 0.0, top_k: int = 0,
             eos_token_id: Optional[int] = None,
             key: Optional[jax.Array] = None) -> jnp.ndarray:
    """prompt_ids [B, P] int32 -> [B, P + max_new_tokens] (tokens after an
    EOS are padded with EOS when eos_token_id is given). One compile per
    (shape, sampling-config); greedy when temperature == 0."""
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if key is None:
        key = jax.random.key(0)
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    return _generate_jit(params, prompt_ids, cfg, max_new_tokens,
                         float(temperature), int(top_k), eos_token_id, key)
