"""Pipeline parallelism: microbatch pipelining over the 'pp' mesh axis.

TPU-native equivalent of the reference's pipeline stack
(ref: picotron/pipeline_parallel/pipeline_parallel.py +
pp_communications.py). The mapping:

- **Stage slicing** — the reference keeps a contiguous block of decoder
  layers per stage, embedding on the first stage, norm+head on the last
  (ref: pipeline_parallel.py:13-51). Here the stacked layer pytree is
  *sharded* over 'pp' on its leading layer axis (parallel/sharding.py), so
  inside shard_map each device's `params['layers']` IS its stage slice.
- **Activation transport** — the reference's batched isend/irecv pairs with
  hard cuda synchronization and `CUDA_DEVICE_MAX_CONNECTIONS=1` ordering
  (ref: pp_communications.py:8-46, base_job.slurm:53) become one
  `lax.ppermute` per pipeline tick; XLA orders and overlaps it.

Both engines share one stage unit (`_make_stage_fn`): at a given tick, stage
s applies its layer block to microbatch m, where stage 0 ingests `embed(m)`
(masked-uniform) and the last stage scores m against the targets via a
collective-free `lax.cond` branch (the head matmul runs ONLY on the last
stage — see _make_stage_fn).

**"afab"** (all-forward-all-backward, ref: pipeline_parallel.py:77-118):
one `lax.scan` over n_micro + pp - 1 ticks; at tick t stage s forwards
microbatch t - s. Differentiating through the scan yields the reverse
schedule with transposed ppermutes — the reference's manual
`torch.autograd.backward` choreography + grad send/recv is derived, not
written. Memory: scan AD stores per-tick residuals, i.e. O(n_micro) —
bounded by the tick-level `jax.checkpoint` (which honors the configured
remat policy) to one boundary activation per tick plus policy-saved values.

**"1f1b"** (ref: pipeline_parallel.py:122-215 warmup/steady/cooldown): a
synchronous schedule-table scan with *manual* VJP — no AD through the scan.
Microbatch m's forward runs at stage s on tick m + s; its backward at tick
m + 2(pp-1) - s — each steady-state tick executes one active forward AND
one active backward per stage, finishing in n_micro + 2(pp-1) ticks (see
pipeline_1f1b_grads for the schedule/memory analysis). Activation
cotangents ride a reverse ppermute; parameter gradients accumulate in the
scan carry; live boundary inputs sit in a min(n_micro, 2(pp-1))-slot ring,
*independent of n_micro* (AFAB's live set grows with n_micro). 1f1b is the
default engine: ~AFAB speed with O(pp) instead of O(n_micro) boundary-
activation memory.

**Why no Megatron interleaved (virtual-stage) schedule UNDER THIS
EXECUTOR** (`pipeline.executor: spmd`, the default): with v chunks per
device the pipeline deepens to V = v*pp virtual stages, and in a
masked-uniform SPMD tick model every tick must trace each device's v
forward + v backward units whether active or not — so fill/drain cost
grows with V while per-tick cost grows with v, making interleaving
STRICTLY worse here (efficiency n/(n + 2(V-1)) vs this schedule's
n/(n + 2(pp-1))). Interleaving wins on per-rank imperative runtimes
because idle warmup slots cost nothing; under jit they cost a full traced
unit (PERF.md r4 measured ~one traced unit per idle tick). Gating the
units with lax.cond (the head-scoring trick) cannot recover it either: a
skipped unit still occupies its tick slot in the schedule. Under the scan
the lever for bubble fraction is more microbatches (n), amortized at
2(pp-1)/n.

`pipeline.executor: mpmd` (parallel/mpmd.py) is the executor where that
premise does not hold: per-stage programs driven by a host-side schedule
table make idle ticks ~free, so the interleaved schedule is supported
there (and measured winning, PERF.md r10). This module stays the SPMD
reference twin the MPMD executor is parity-pinned against.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu import compat
from picotron_tpu.config import Config
from picotron_tpu.models.llama import (
    ParallelCtx, compute_dtype, embed, final_hidden, head_weight,
    model_rope_tables, remat_policy_for, run_layers,
)
from picotron_tpu.ops.losses import IGNORE_INDEX, cross_entropy_sum_count


def _vary_over(x, want):
    """Promote x to vary over the mesh axes in `want` (no-op for axes it
    already varies over). Sound in the safe direction only: it forgets
    replication knowledge, never asserts it."""
    have = compat.vma(x)
    missing = tuple(a for a in ("dp", "pp", "ep", "cp", "tp")
                    if a in want and a not in have)
    return compat.pcast(x, missing, to="varying") if missing else x


def _cast_varying_like(x, target):
    return _vary_over(x, set(compat.vma(target)))


def _boundary_axes(ctx) -> tuple:
    """Mesh axes the pipeline's activation boundary buffers vary over. A
    seq-sharded residual stream (sequence parallelism) is tp-VARYING; the
    nll/count scalars never are (head_ce psums over tp)."""
    return ("dp", "ep", "cp", "pp") + (("tp",) if ctx.seq_shard > 1 else ())


def _make_stage_fn(ids, tgt, m, ctx: ParallelCtx, cos, sin, s_idx, pp):
    """One stage-forward unit, shared by both engines.

    Returns stage_fn(params, x_buf, m_idx, valid) ->
    ((y, nll_sum), (count, dropw)): stage 0 consumes embed(ids[m_idx])
    (zero-masked when not `valid`), other stages consume the rotated-in
    activation `x_buf`; the last stage's nll_sum scores microbatch m_idx.
    Differentiable in params and x_buf ((count, dropw) is aux).

    The vocab-head scoring is gated with `lax.cond` on the stage index, not
    masked: a masked-uniform program would pay the full [B*S, H] x [H, V/tp]
    head matmul (and the fp32 exp over the logits) on EVERY stage every tick
    — at pp=4, tp=1 that is ~pp x redundant head FLOPs riding every tick
    (VERDICT r2 weak #2; the reference runs the head only on the last stage,
    ref: pipeline_parallel.py:53-63). Constraint: the branches must contain
    no cross-device collectives — a collective whose replica group spans
    devices that take different branches leaves the in-branch members
    waiting on peers that never arrive (observed as a rendezvous deadlock
    on the CPU backend). Hence the cond computes only this tp shard's local
    softmax stats (vocab_parallel_ce_local_stats; zero FLOPs off the last
    stage) and the [B, S]-sized pmax/psum merge runs uniformly on every
    stage. Under sequence parallelism the scoring needs a seq
    all_gather that cannot be split that way, so the engines fall back to
    r2's uniform masked scoring there (no regression — SP already divides
    the head by tp). The embed stays masked-uniform for the same reason
    (its psum is the dominant cost and cannot leave a branch cheaply);
    its gather FLOPs are negligible.

    The token count needs no head output (it is just the non-ignored-target
    count) and is computed outside the cond because the MoE aux-loss fold
    weights by it on every stage.
    """
    dtype = compute_dtype(m)
    gated = ctx.head_ce_local is not None and ctx.seq_shard == 1

    def stage_fn(params, x_buf, m_idx, valid):
        mb_ids = lax.dynamic_index_in_dim(ids, m_idx, 0, keepdims=False)
        mb_tgt = lax.dynamic_index_in_dim(tgt, m_idx, 0, keepdims=False)
        # Zero-mask invalid ingest so garbage never enters the pipe (all
        # bubble compute then runs on zeros, which every op here keeps
        # finite — no NaNs can poison the masked accumulators' grads).
        x0 = embed(params, mb_ids, m, ctx) * valid.astype(dtype)
        x_in = jnp.where(s_idx == 0, x0, x_buf)
        y, aux = run_layers(params["layers"], x_in, m, ctx, cos, sin)
        count = jnp.sum(mb_tgt != IGNORE_INDEX)

        # Two rules keep the branches collective-free through the BACKWARD
        # cond as well (verified against the optimized HLO — violations
        # deadlock the CPU runtime's order-matched rendezvous):
        # 1. No lax.pcast inside a branch: pcast-to-varying transposes to a
        #    psum. The neutral branch instead anchors its constants on
        #    zero-weighted elements of exactly the arrays the scoring
        #    branch consumes — same varying type by construction, and the
        #    transpose of `* 0` is `* 0`.
        # 2. Every float array a branch consumes must ALREADY vary over the
        #    branch result's axes: consuming a pp-replicated param (head,
        #    final norm) inside the branch makes shard_map insert the
        #    pvary there implicitly, whose transpose is again an in-branch
        #    psum — so promote them out here, where the psum is uniform.
        y_vma = set(compat.vma(y))
        # the head weight source is lm_head, or the embedding when tied
        # (Qwen2-style) — promote whichever the scoring branch will read
        head_key = "lm_head" if "lm_head" in params else "embedding"
        head_v = _vary_over(params[head_key], y_vma)
        norm_v = _vary_over(params["final_norm"], y_vma)
        params_v = {**params, head_key: head_v, "final_norm": norm_v}

        def _anchor(args):
            y_sc, params_sc = args
            return (y_sc.ravel()[0].astype(jnp.float32)
                    + params_sc[head_key].ravel()[0].astype(jnp.float32)) * 0.0

        if gated:
            # neutral branch merges to logz = log(tp_size) — finite garbage
            # (never inf/nan: a nan would poison the masked accumulators'
            # gradients through 0*nan), masked by the contrib select below

            def score(args):
                y_sc, params_sc = args
                hf = final_hidden(params_sc, y_sc, m)
                return ctx.head_ce_local(hf, head_weight(params_sc), mb_tgt)

            def no_score(args):
                a = _anchor(args)
                zero = jnp.zeros(mb_tgt.shape, jnp.float32) + a
                return (zero, zero + 1.0, zero)  # max=0, sumexp=1, label=0

            stats = lax.cond(s_idx == pp - 1, score, no_score, (y, params_v))
            total = ctx.head_ce_merge(stats, mb_tgt)
        elif ctx.head_ce is not None:
            hf = final_hidden(params, y, m)
            total, _ = ctx.head_ce(hf, head_weight(params), mb_tgt)
        else:
            # no TP head hook (plain unsharded head): the whole scoring is
            # already collective-free, so the cond can return the total

            def score_full(args):
                y_sc, params_sc = args
                hf = final_hidden(params_sc, y_sc, m)
                logits = hf @ head_weight(params_sc).astype(hf.dtype)
                total, _ = cross_entropy_sum_count(logits, mb_tgt)
                return total

            total = lax.cond(s_idx == pp - 1, score_full, _anchor,
                             (y, params_v))
        # `contrib` is stage-additive: the CE sum counts only on the last
        # stage (masked HERE, so the engines accumulate on every active
        # tick), while each stage contributes its own layers' (pre-weighted)
        # MoE router loss, scaled by the microbatch token count
        # (llama.loss_sum_count's folding rule) — psum over 'pp' then
        # assembles the full total. dropw is the same-scaled capacity drop
        # observability sum (aux[1] == 0 for dense models).
        contrib = jnp.where(s_idx == pp - 1, total, 0.0)
        if m.num_experts:
            contrib = contrib + aux[0] * count
        dropw = aux[1] * count
        return (y, contrib), (count, dropw)

    return stage_fn


def pipeline_loss_sum_count(params, ids, tgt, cfg: Config, ctx: ParallelCtx):
    """AFAB engine: (nll_sum, valid_count, drop_weighted_sum) for the full
    microbatch stream, pipelined over 'pp'. Must run inside shard_map with
    'pp' (and 'dp','cp','tp') in scope; differentiate through it for
    gradients (the counts are non-differentiable pass-throughs).

    ids/tgt: [n_micro, mbs_local, s_local] (this device's dp/cp shard,
    replicated over pp — every stage sees the token stream, matching the
    reference's dataloader feeding all ranks, ref: pipeline_parallel.py:145-155).

    Outputs are replicated over 'pp' (psum-broadcast from the last stage).
    """
    m = cfg.model
    pp = lax.psum(1, "pp")
    s_idx = lax.axis_index("pp")
    n_micro, mbs, s_local = ids.shape
    n_ticks = n_micro + pp - 1

    cos, sin = model_rope_tables(m)
    dtype = compute_dtype(m)
    # Remat is applied at tick granularity below (so the policy governs what
    # the scan's AD saves per tick); disable the inner per-layer checkpoint
    # to avoid nesting two remat regions.
    ctx_inner = dataclasses.replace(ctx, remat=False)
    stage_fn = _make_stage_fn(ids, tgt, m, ctx_inner, cos, sin, s_idx, pp)
    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def tick(carry, t):
        x_buf, nll_acc, cnt_acc, drop_acc = carry
        d = t - s_idx  # microbatch index this stage works on at tick t
        on = (d >= 0) & (d < n_micro)
        m_f = jnp.clip(d, 0, n_micro - 1)
        (y, contrib), (cnt, dropw) = stage_fn(params, x_buf, m_f, on)
        # contrib is pre-masked to the last stage's CE (+ this stage's MoE
        # aux) inside stage_fn — accumulate wherever the stage was active.
        # dropw is this stage's layers' contribution: every active tick.
        nll_acc = nll_acc + jnp.where(on, contrib, 0.0)
        cnt_acc = cnt_acc + jnp.where(on & (s_idx == pp - 1), cnt, 0)
        drop_acc = drop_acc + jnp.where(on, dropw, 0.0)
        y_next = lax.ppermute(y * on.astype(y.dtype), "pp", fwd_perm)
        return (y_next, nll_acc, cnt_acc, drop_acc), None

    body = tick
    if ctx.remat:
        body = jax.checkpoint(body, policy=remat_policy_for(ctx.remat_policy))

    # Boundary buffers carry the residual stream, which sequence parallelism
    # shards to s_local / seq_shard (tp x less ppermute traffic per tick).
    x0_buf = compat.pcast(
        jnp.zeros((mbs, s_local // ctx.seq_shard, m.hidden_size), dtype),
        _boundary_axes(ctx), to="varying")
    init = (x0_buf,) + compat.pcast(
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32),
         jnp.zeros((), jnp.float32)),
        ("dp", "ep", "cp", "pp"), to="varying")
    (x_last, nll_sum, cnt, dropw), _ = lax.scan(body, init,
                                                jnp.arange(n_ticks))

    # Broadcast the last stage's totals to every stage (masked elsewhere, so
    # psum == select; the drop sum is genuinely pp-partial — each stage
    # holds its own layers' share — and the same psum assembles it;
    # ref: utils.py:93-98 averages loss on the last PP stage then
    # broadcasts via the wandb-rank convention).
    nll_sum = lax.psum(nll_sum, "pp")
    cnt = lax.psum(cnt, "pp")
    dropw = lax.psum(dropw, "pp")
    return nll_sum, cnt, dropw


def pp_1f1b_ticks(n_micro: int, pp: int) -> int:
    """Tick count of the 1F1B schedule: n_micro + 2(pp-1). Exposed so tests
    can pin the schedule length (VERDICT r3: a tick-count assertion)."""
    return n_micro + 2 * (pp - 1)


def pp_1f1b_ring_slots(n_micro: int, pp: int) -> int:
    """Boundary-input ring size: min(n_micro, 2(pp-1)), at least 1."""
    return max(1, min(n_micro, 2 * (pp - 1)))


def pipeline_1f1b_grads(params, ids, tgt, cfg: Config, ctx: ParallelCtx):
    """1F1B engine: (grads, nll_sum, valid_count, drop_weighted_sum),
    pipelined over 'pp'.

    Unlike the AFAB engine this computes gradients *itself* (manual VJP per
    tick) — do not differentiate through it. Full-rate schedule (the
    synchronous analogue of ref: pipeline_parallel.py:122-215):

        forward  of microbatch m at stage s: tick m + s
        backward of microbatch m at stage s: tick m + 2(pp-1) - s

    Every steady-state tick runs ONE active forward and ONE active backward
    on every stage (warmup: stage s forwards 2(pp-1-s) microbatches before
    its first backward; cooldown mirrors it), completing in
    n_micro + 2(pp-1) ticks — within pp-1 ticks of AFAB's forward-pass
    length, vs the 2*n_micro + 2(pp-1) - 1 of the previous half-rate
    schedule, which idled every stage on alternating ticks and cost ~2x
    AFAB's pipeline FLOPs (VERDICT r2 weak #1).

    Memory: stage s holds up to min(n_micro, 2(pp-1-s)) boundary *inputs*
    live — the ring holds only [mbs, S_local, H] stage inputs (the backward
    unit recomputes the stage interior under jax.vjp, honoring the remat
    policy), so the bound is 2x Megatron's per-stage pp-s activations but
    counts only boundary tensors, negligible against weights at realistic
    shapes. The 2x is fundamental to full rate: microbatch m's grad returns
    to stage s exactly 2(pp-1-s) ticks after its forward (one stage per
    tick each way), during which a full-rate stage forwards 2(pp-1-s) more
    microbatches. Halving the in-flight set requires halving the forward
    rate — the previous schedule — never a win on TPU, where HBM spent on
    2pp boundary buffers is cheap and idle MXU ticks are not.

    Ring-slot safety (R = min(n_micro, 2(pp-1)) slots, slot = m mod R):
    the load of microbatch m's input at tick m + 2(pp-1) - s happens before
    the store of microbatch m + R at tick m + R + s in tick order for every
    s > 0; at s = 0 with R = 2(pp-1) they land on the same tick, so the
    tick body LOADS the backward input before the forward unit stores. At
    the last stage backward and forward of the same microbatch share a tick
    (b == f) and the backward consumes the live x_buf directly, not the
    ring.

    Grads of pp-replicated params (embedding / final norm / head) come out
    nonzero only on the stage that uses them — pass through
    sync_pp_replicated_grads like the AFAB path's.
    """
    m = cfg.model
    pp = lax.psum(1, "pp")
    s_idx = lax.axis_index("pp")
    n_micro, mbs, s_local = ids.shape
    n_ticks = pp_1f1b_ticks(n_micro, pp)
    ring_slots = pp_1f1b_ring_slots(n_micro, pp)

    cos, sin = model_rope_tables(m)
    dtype = compute_dtype(m)
    stage_fn = _make_stage_fn(ids, tgt, m, ctx, cos, sin, s_idx, pp)
    fwd_perm = [(i, i + 1) for i in range(pp - 1)]
    bwd_perm = [(i + 1, i) for i in range(pp - 1)]

    def tick(carry, t):
        ring, x_buf, g_buf, g_acc, nll_acc, cnt_acc, drop_acc = carry

        # ---- backward ring load FIRST: at stage 0 with a full ring the
        # slot being loaded is re-stored by this tick's forward unit ----
        db = t - 2 * (pp - 1) + s_idx
        b_on = (db >= 0) & (db < n_micro)
        m_b = jnp.clip(db, 0, n_micro - 1)
        x_ring = lax.dynamic_index_in_dim(ring, m_b % ring_slots, 0,
                                          keepdims=False)

        # ---- forward unit: microbatch m_f advances one stage ----
        df = t - s_idx
        f_on = (df >= 0) & (df < n_micro)
        m_f = jnp.clip(df, 0, n_micro - 1)
        (y, contrib), (cnt, dropw) = stage_fn(params, x_buf, m_f, f_on)
        # contrib pre-masks the CE to the last stage (stage_fn); MoE aux
        # contributions ride it on every stage, as does this stage's
        # layers' capacity-drop observability sum.
        nll_acc = nll_acc + jnp.where(f_on, contrib, 0.0)
        cnt_acc = cnt_acc + jnp.where(f_on & (s_idx == pp - 1), cnt, 0)
        drop_acc = drop_acc + jnp.where(f_on, dropw, 0.0)
        # Save this stage's *input* for the backward recompute. Guard the
        # store: on non-forward ticks m_f aliases a possibly-live slot.
        ring_new = lax.dynamic_update_index_in_dim(
            ring, x_buf, m_f % ring_slots, 0)
        ring = jnp.where(f_on, ring_new, ring)
        y_send = lax.ppermute(y * f_on.astype(y.dtype), "pp", fwd_perm)

        # ---- backward unit: microbatch m_b retreats one stage ----
        # Last stage: b(m) == f(m), the input is this tick's live x_buf.
        x_saved = jnp.where(s_idx == pp - 1, x_buf, x_ring)
        _, vjp_fn, _ = jax.vjp(
            lambda p, xb: stage_fn(p, xb, m_b, b_on), params, x_saved,
            has_aux=True)
        # Cotangents: g_buf arrived from stage s+1 (zeros at the last stage
        # by ppermute's edge semantics — its y has no downstream consumer);
        # the contrib cotangent is 1 on EVERY stage that ran m_b — contrib
        # masks the CE to the last stage internally, and the per-stage MoE
        # aux term needs its gradient from every stage. On non-backward
        # ticks both cotangents are zero, so the VJP outputs are zero and
        # need no masking.
        g_nll = _vary_over(jnp.where(b_on, 1.0, 0.0),
                           {"dp", "ep", "cp", "pp"})
        g_params, g_x = vjp_fn((g_buf, g_nll))
        g_acc = jax.tree.map(
            lambda a, g: jnp.add(a, _cast_varying_like(g, a)), g_acc, g_params)
        g_send = lax.ppermute(g_x, "pp", bwd_perm)

        return (ring, y_send, g_send, g_acc, nll_acc, cnt_acc, drop_acc), None

    x0 = jnp.zeros((mbs, s_local // ctx.seq_shard, m.hidden_size), dtype)
    bufs = compat.pcast(
        (jnp.zeros((ring_slots,) + x0.shape, dtype), x0, x0),
        _boundary_axes(ctx), to="varying"
    ) + compat.pcast(
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32),
         jnp.zeros((), jnp.float32)),
        ("dp", "ep", "cp", "pp"), to="varying")
    # Each grad-accumulator leaf varies over the data axes plus whatever its
    # param already varies over (tp/pp shardings) — matching what the VJP
    # emits each tick, so the scan carry type is stable. Under sequence
    # parallelism the per-tick VJP grads of tp-replicated params (norms)
    # are per-rank partials over this rank's seq shard, hence tp-varying;
    # sync_sp_partial_grads completes them with a tp psum after the scan.
    # fp32 accumulation regardless of the param dtype: with
    # optimizer_offload the params (and hence the per-tick VJP grads) are
    # bf16, and summing n_micro bf16 grads in bf16 would lose the low bits
    # the fp32 master exists to keep (jnp.add promotes bf16 + fp32 -> fp32).
    g_zero = jax.tree.map(
        lambda p: _vary_over(jnp.zeros(p.shape, jnp.float32),
                             set(_boundary_axes(ctx))
                             | set(compat.vma(p))),
        params)
    init = (bufs[0], bufs[1], bufs[2], g_zero, bufs[3], bufs[4], bufs[5])
    (_, _, _, grads, nll_sum, cnt, dropw), _ = lax.scan(
        tick, init, jnp.arange(n_ticks))

    nll_sum = lax.psum(nll_sum, "pp")
    cnt = lax.psum(cnt, "pp")
    dropw = lax.psum(dropw, "pp")
    return grads, nll_sum, cnt, dropw


def sync_pp_replicated_grads(grads, specs):
    """psum over 'pp' the grads of params replicated across pipeline stages
    (embedding / final norm / lm_head): each is used by one stage, so its
    per-stage grads are disjoint and the sum assembles the true total.
    Layer params are sharded over 'pp' (leading axis) and need no collective.
    """
    from jax.sharding import PartitionSpec as P

    def fix(g, spec):
        flat = []
        for part in spec:
            if isinstance(part, (tuple, list)):
                flat.extend(part)
            elif part is not None:
                flat.append(part)
        if "pp" in flat:
            return g
        return lax.psum(g, "pp")

    return jax.tree.map(fix, grads, specs,
                        is_leaf=lambda x: isinstance(x, P))


def sync_sp_partial_grads(grads, params):
    """Under sequence parallelism, complete the grads of tp-replicated
    params (the norm weights): each tp rank accumulated the partial over its
    sequence shard (tp-varying leaf), and the psum assembles the full sum.
    tp-sharded params (vma already contains 'tp') are genuine shards, not
    partials — left untouched. No-op tree-wide when nothing is tp-varying
    beyond its param (the automatic pvary-transpose psum already ran, e.g.
    the AFAB jax.grad path)."""
    # Which leaves are tp-PARTIAL (vs genuine tp shards) is read off the
    # vma types — without them this sync cannot distinguish the two and
    # would either drop or double-count the norm grads, so fail loudly
    # rather than return silently-wrong gradients (compat module).
    compat.require_vma("sequence_parallel gradient sync under pipeline "
                       "parallelism (sync_sp_partial_grads)")

    def fix(g, p):
        if "tp" in compat.vma(g) and "tp" not in compat.vma(p):
            return lax.psum(g, "tp")
        return g

    return jax.tree.map(fix, grads, params)
