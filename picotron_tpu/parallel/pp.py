"""Pipeline parallelism: microbatch pipelining over the 'pp' mesh axis.

TPU-native equivalent of the reference's pipeline stack
(ref: picotron/pipeline_parallel/pipeline_parallel.py +
pp_communications.py). The mapping:

- **Stage slicing** — the reference keeps a contiguous block of decoder
  layers per stage, embedding on the first stage, norm+head on the last
  (ref: pipeline_parallel.py:13-51). Here the stacked layer pytree is
  *sharded* over 'pp' on its leading layer axis (parallel/sharding.py), so
  inside shard_map each device's `params['layers']` IS its stage slice; the
  even `distribute_layers` split (ref: pipeline_parallel.py:42-51) is the
  sharding rule (layers % pp == 0 enforced at config validation).
- **Activation transport** — the reference's batched isend/irecv pairs with
  hard cuda synchronization and `CUDA_DEVICE_MAX_CONNECTIONS=1` ordering
  (ref: pp_communications.py:8-46, base_job.slurm:53) become one
  `lax.ppermute` per pipeline tick; XLA orders and overlaps it.
- **Schedule** — one `lax.scan` over `n_micro + pp - 1` ticks. At tick t,
  stage s processes microbatch `t - s`: stage 0 ingests embedded microbatch
  t, every stage runs its layer block, the last stage accumulates a masked
  loss, activations rotate one stage forward. Differentiating through the
  scan yields the reverse schedule with transposed ppermutes — the manual
  `torch.autograd.backward` choreography + grad send/recv of the reference
  (ref: pipeline_parallel.py:65-75, 94-118) is derived, not written.
- **Grad-sync deferral** — `require_backward_grad_sync` gating on the last
  microbatch (ref: pipeline_parallel.py:179-199) falls out of psum-ing once,
  after the scan (see parallel/api.py).

Schedule semantics per engine (ref: train.py:225-227 dispatch):
- "afab": exactly this scan — all forwards then all backwards, activations
  retained per tick (the reference's AFAB stores input/output per microbatch,
  ref: pipeline_parallel.py:94-118; the scan carry plays that role).
- "1f1b": currently runs the same scan. True 1F1B's only delta is peak
  activation memory (<= pp in-flight microbatches instead of n_micro);
  with per-tick rematerialization the scan already bounds stored state to
  one carry per tick. An explicit interleaved-vjp schedule is planned.

SPMD uniformity note: every stage traces the same program, so embed and the
loss head are *computed* on every stage and masked where inapplicable. The
head matmul is the only nontrivial overhead; under TP it is vocab-sharded
(tp.vocab_parallel_ce_sum_count), which divides that waste by tp_size.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.config import Config
from picotron_tpu.models.llama import (
    ParallelCtx, compute_dtype, embed, final_hidden, run_layers,
)
from picotron_tpu.ops.losses import cross_entropy_sum_count
from picotron_tpu.ops.rope import rope_tables


def pipeline_loss_sum_count(params, ids, tgt, cfg: Config, ctx: ParallelCtx):
    """(nll_sum, valid_count) for the full microbatch stream, pipelined over
    'pp'. Must run inside shard_map with 'pp' (and 'dp','cp','tp') in scope.

    ids/tgt: [n_micro, mbs_local, s_local] (this device's dp/cp shard,
    replicated over pp — every stage sees the token stream; stage 0 reads
    ids, the last stage reads tgt, matching the reference's dataloader
    feeding all ranks, ref: pipeline_parallel.py:145-155).

    Outputs are replicated over 'pp' (psum-broadcast from the last stage).
    """
    m = cfg.model
    pp = lax.psum(1, "pp")
    s_idx = lax.axis_index("pp")
    n_micro, mbs, s_local = ids.shape
    n_ticks = n_micro + pp - 1

    cos, sin = rope_tables(m.max_position_embeddings, m.head_dim, m.rope_theta)
    dtype = compute_dtype(m)

    # Pad the ingest stream to n_ticks; shift the target stream so that at
    # tick t the last stage scores the microbatch it is finishing (t-(pp-1)).
    ids_p = jnp.pad(ids, ((0, pp - 1), (0, 0), (0, 0)))
    tgt_p = jnp.pad(tgt, ((pp - 1, 0), (0, 0), (0, 0)))
    ticks = jnp.arange(n_ticks)
    in_valid = ticks < n_micro
    out_valid = ticks >= pp - 1

    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def tick(carry, xs):
        x_buf, nll_acc, cnt_acc = carry
        mb_ids, mb_tgt, v_in, v_out = xs

        # Stage 0 ingests a fresh microbatch; others take the rotated-in
        # activations. Zero-mask padded ingest ticks so garbage never enters
        # the pipe (it would reach the last stage as a masked tick anyway,
        # but non-finite values would poison grads through the mask).
        x0 = embed(params, mb_ids, m, ctx) * v_in.astype(dtype)
        x_in = jnp.where(s_idx == 0, x0, x_buf)

        y = run_layers(params["layers"], x_in, m, ctx, cos, sin)

        # Last stage: norm + head + CE on the microbatch leaving the pipe.
        hf = final_hidden(params, y, m)
        if ctx.head_ce is not None:
            total, count = ctx.head_ce(hf, params["lm_head"], mb_tgt)
        else:
            logits = hf @ params["lm_head"].astype(hf.dtype)
            total, count = cross_entropy_sum_count(logits, mb_tgt)
        take = (s_idx == pp - 1) & v_out
        nll_acc = nll_acc + jnp.where(take, total, 0.0)
        cnt_acc = cnt_acc + jnp.where(take, count, 0)

        y_next = lax.ppermute(y, "pp", fwd_perm)
        return (y_next, nll_acc, cnt_acc), None

    x0_buf = jnp.zeros((mbs, s_local, m.hidden_size), dtype)
    init = lax.pcast(
        (x0_buf, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        ("dp", "cp", "pp"), to="varying")
    body = tick
    if ctx.remat:
        body = jax.checkpoint(body)
    (x_last, nll_sum, cnt), _ = lax.scan(
        body, init, (ids_p, tgt_p, in_valid, out_valid))

    # Broadcast the last stage's totals to every stage (masked elsewhere, so
    # psum == select; ref: utils.py:93-98 averages loss on the last PP stage
    # then broadcasts via the wandb-rank convention).
    nll_sum = lax.psum(nll_sum, "pp")
    cnt = lax.psum(cnt, "pp")
    return nll_sum, cnt


def sync_pp_replicated_grads(grads, specs):
    """psum over 'pp' the grads of params replicated across pipeline stages
    (embedding / final norm / lm_head): each is used by one stage, so its
    per-stage grads are disjoint and the sum assembles the true total.
    Layer params are sharded over 'pp' (leading axis) and need no collective.
    """
    from jax.sharding import PartitionSpec as P

    def fix(g, spec):
        flat = []
        for part in spec:
            if isinstance(part, (tuple, list)):
                flat.extend(part)
            elif part is not None:
                flat.append(part)
        if "pp" in flat:
            return g
        return lax.psum(g, "pp")

    return jax.tree.map(fix, grads, specs,
                        is_leaf=lambda x: isinstance(x, P))
