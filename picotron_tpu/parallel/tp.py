"""Tensor parallelism: vocab-parallel embedding, sharded cross-entropy, and
the TP hooks for ParallelCtx.

Megatron-style 1D TP (capability parity with ref: picotron/tensor_parallel/):

- Column-parallel linears (q/k/v/gate/up) shard the output features over
  'tp'; row-parallel linears (o/down) shard the input features and psum the
  partial outputs (ref: tensor_parallel.py:54-189). In this framework the
  *sharding specs* (parallel/sharding.py) put the weights on the mesh and the
  only explicit collective needed in the forward is the row-parallel exit
  psum — the backward psum of the column-parallel entry
  (ref: tp_communications.py:19-33, the `f` function) is inserted
  automatically when JAX transposes the psum/pvary pair under shard_map.

- The vocab-parallel embedding masks out-of-shard tokens and psums
  (ref: tensor_parallel.py:191-271 does the same with an explicit mask +
  all-reduce).

- `vocab_parallel_ce` improves on the reference, which all-gathers full-vocab
  logits on every rank before cross-entropy (ref: tensor_parallel.py:50
  `gather_output=True` + train.py:49): we compute the softmax statistics with
  a pmax/psum pair and never materialize the gathered [B, S, V] tensor —
  at SmolLM's 49k vocab this saves tp x the logit memory and an all-gather
  per microbatch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.ops.losses import IGNORE_INDEX


def vocab_parallel_embed(w_shard: jnp.ndarray, ids: jnp.ndarray,
                         axis: str = "tp",
                         scatter_seq: bool = False) -> jnp.ndarray:
    """Embedding lookup with the vocab dimension sharded over `axis`.

    w_shard: [vocab/tp, hidden] local shard; ids replicated.
    Out-of-shard ids contribute zero; psum over tp assembles the full row.
    With `scatter_seq` (sequence parallelism) the psum becomes a
    psum_scatter over the sequence dim, handing each tp rank its
    [*, S/tp, H] slice of the residual stream.
    """
    vshard = w_shard.shape[0]
    lo = lax.axis_index(axis) * vshard
    rel = ids - lo
    ok = (rel >= 0) & (rel < vshard)
    rel = jnp.clip(rel, 0, vshard - 1)
    x = w_shard[rel] * ok[..., None].astype(w_shard.dtype)
    if scatter_seq:
        return lax.psum_scatter(x, axis, scatter_dimension=1, tiled=True)
    return lax.psum(x, axis)


# -- sequence parallelism (SP) hooks ----------------------------------------
# Megatron-SP's g/ḡ pair (Korthikanti et al. 2022): with the residual
# stream seq-sharded over tp, the column-parallel entry gathers the
# sequence (backward: reduce-scatter of the grad — JAX's transpose of a
# tiled all_gather) and the row-parallel exit reduce-scatters the partial
# sums (backward: all_gather). Same total bytes as the psum pair they
# replace; tp x less activation memory between blocks.
#
# These transposes are load-bearing beyond AD: the fused grad engine
# (parallel/fused_bwd.py) reaches both hooks through jax.vjp over segment
# closures, so its manual backward scan emits the SAME all_gather/
# reduce-scatter pair per layer as the AD engine — the schedule
# picotron_tpu/analysis/collectives.py's SP presence rule audits on both
# engines.


def sp_gather_seq(x: jnp.ndarray, axis: str = "tp") -> jnp.ndarray:
    """[*, S/tp, H] -> [*, S, H]; the SP column-parallel entry (`f`)."""
    return lax.all_gather(x, axis, axis=1, tiled=True)


def sp_scatter_seq(x: jnp.ndarray, axis: str = "tp") -> jnp.ndarray:
    """partial [*, S, H] -> reduced [*, S/tp, H]; the SP row-parallel
    exit (`g`)."""
    return lax.psum_scatter(x, axis, scatter_dimension=1, tiled=True)


def vocab_parallel_ce_sum_count(hidden: jnp.ndarray, head_shard: jnp.ndarray,
                                targets: jnp.ndarray, axis: str = "tp",
                                chunk_size: int = 0):
    """(sum of per-token NLL, valid-token count) against a vocab-sharded LM
    head — the reduction pieces, so dp/cp shards can psum both and divide once.

    hidden: [B, S, H] (replicated over tp); head_shard: [H, vocab/tp];
    targets: [B, S] with IGNORE_INDEX allowed. Both outputs are replicated
    over tp. Matches ops.losses.cross_entropy_sum_count numerically.
    """
    # One implementation, two entry points: this delegates to the
    # local-stats/merge split the pipeline engines use, so the fused and
    # gated scoring paths cannot numerically diverge (code review r3).
    stats = vocab_parallel_ce_local_stats(hidden, head_shard, targets, axis,
                                          chunk_size=chunk_size)
    total = vocab_parallel_ce_merge(stats, targets, axis)
    return total, jnp.sum(targets != IGNORE_INDEX)


def vocab_parallel_ce_local_stats(hidden: jnp.ndarray,
                                  head_shard: jnp.ndarray,
                                  targets: jnp.ndarray, axis: str = "tp",
                                  chunk_size: int = 0):
    """The collective-free half of `vocab_parallel_ce_sum_count`: this
    shard's softmax statistics, (local_max, local_sumexp, local_label), each
    [B, S] fp32. Pair with `vocab_parallel_ce_merge` for the cross-shard
    reduction.

    The split exists for the pipeline engines: the expensive part (the
    [B*S, H] x [H, V/tp] head matmul and the exp) runs inside a `lax.cond`
    taken only by the last pp stage, which therefore must contain no
    cross-device collectives — a collective whose replica group spans
    devices that take different branches leaves the in-branch members
    waiting on peers that never arrive (a rendezvous deadlock on the CPU
    backend; here the risk is the pvary-transpose psums over 'pp' that
    implicit varying-type promotion would insert into the backward cond).
    The [B, S]-sized pmax/psum merge runs unconditionally on every stage —
    three tiny uniform collectives per tick.
    """
    vshard = head_shard.shape[-1]
    lo = lax.axis_index(axis) * vshard
    valid = targets != IGNORE_INDEX
    rel = jnp.where(valid, targets, 0) - lo

    if chunk_size and chunk_size < vshard and vshard % chunk_size == 0:
        return _chunked_local_stats(hidden, head_shard, rel, chunk_size)

    logits = (hidden @ head_shard.astype(hidden.dtype)).astype(jnp.float32)
    m_loc = jax.lax.stop_gradient(jnp.max(logits, axis=-1))  # [B, S]
    sumexp_loc = jnp.sum(jnp.exp(logits - m_loc[..., None]), axis=-1)
    ok = (rel >= 0) & (rel < vshard)
    relc = jnp.clip(rel, 0, vshard - 1)
    label_loc = (jnp.take_along_axis(logits, relc[..., None], axis=-1)
                 .squeeze(-1) * ok.astype(jnp.float32))
    return m_loc, sumexp_loc, label_loc


def _chunked_local_stats(hidden, head_shard, rel, chunk_size: int):
    """Streaming form of the local CE stats: scan vocab chunks, keeping a
    running (max, sumexp, label) merge, so the [N, V_local] logits tensor
    never materializes — neither in forward nor as a saved residual (the
    chunk body is jax.checkpoint'd, so backward recomputes each chunk's
    logits from hidden/head instead of loading ~N*V saved values). At
    SmolLM shapes ([10240, 49152] fp32 stats path) that trades one extra
    chunk matmul in backward for ~1 GB of saved-residual HBM — the memory
    that caps the micro-batch size (see PERF.md). Numerics match the fused
    path: the running max-merge is the same logsumexp shift, stop_gradient
    on every max."""
    vshard = head_shard.shape[-1]
    b_shape = rel.shape

    def body(carry, off):
        m_acc, se_acc, lab_acc = carry
        wc = lax.dynamic_slice_in_dim(head_shard, off, chunk_size, axis=1)
        logits = (hidden @ wc.astype(hidden.dtype)).astype(jnp.float32)
        m_c = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
        m_new = jnp.maximum(m_acc, m_c)
        se = (se_acc * jnp.exp(m_acc - m_new)
              + jnp.sum(jnp.exp(logits - m_new[..., None]), axis=-1))
        rc = rel - off
        ok = (rc >= 0) & (rc < chunk_size)
        rcc = jnp.clip(rc, 0, chunk_size - 1)
        lab = (jnp.take_along_axis(logits, rcc[..., None], axis=-1)
               .squeeze(-1) * ok.astype(jnp.float32))
        return (m_new, se, lab_acc + lab), None

    # The scan carry must already hold the varying type the body produces
    # (tp via head/rel, data axes via hidden). Anchored with zero-weighted
    # operand elements, NOT lax.pcast: this function also runs inside the
    # pipeline's last-stage scoring cond, where a pcast's transpose would
    # put a psum inside the divergent backward branch (parallel/pp.py's
    # branch rules).
    anchor = (hidden.ravel()[0].astype(jnp.float32)
              + head_shard.ravel()[0].astype(jnp.float32)
              + rel.ravel()[0].astype(jnp.float32)) * 0.0
    init = (jnp.full(b_shape, -jnp.inf, jnp.float32) + anchor,
            jnp.zeros(b_shape, jnp.float32) + anchor,
            jnp.zeros(b_shape, jnp.float32) + anchor)
    # exp(m_acc - m_new) with m_acc = -inf on the first chunk: m_new = m_c
    # is finite (real logits), so the factor is exp(-inf) = 0, scaling the
    # zero se_acc — no nan path.
    offsets = jnp.arange(0, vshard, chunk_size)
    (m_loc, sumexp_loc, label_loc), _ = lax.scan(
        jax.checkpoint(body), init, offsets)
    return m_loc, sumexp_loc, label_loc


def vocab_parallel_ce_merge(stats, targets: jnp.ndarray, axis: str = "tp"):
    """Cross-shard merge of `vocab_parallel_ce_local_stats` -> NLL sum.
    Numerically identical to `vocab_parallel_ce_sum_count`'s fused path:
    psum_r[exp(m_r - m) * sum_v exp(l_rv - m_r)] == psum over the full
    vocab of exp(l - m)."""
    m_loc, sumexp_loc, label_loc = stats
    # m is a pure shift constant (its gradient contribution cancels exactly
    # — the standard logsumexp trick); stop_gradient here also covers the
    # pipeline's cond-anchored neutral stats, whose m_loc arrives with a
    # (zero-valued but non-symbolic) tangent that pmax cannot differentiate.
    m_loc = jax.lax.stop_gradient(m_loc)
    m = lax.pmax(m_loc, axis)
    sumexp = lax.psum(sumexp_loc * jnp.exp(m_loc - m), axis)
    logz = m + jnp.log(sumexp)
    label = lax.psum(label_loc, axis)
    valid = targets != IGNORE_INDEX
    nll = jnp.where(valid, logz - label, 0.0)
    return jnp.sum(nll)


def vocab_parallel_ce(hidden: jnp.ndarray, head_shard: jnp.ndarray,
                      targets: jnp.ndarray, axis: str = "tp") -> jnp.ndarray:
    """Token-mean cross-entropy against a vocab-sharded LM head."""
    total, count = vocab_parallel_ce_sum_count(hidden, head_shard, targets, axis)
    return total / jnp.maximum(count, 1)


def gather_logits(logits: jnp.ndarray, axis: str = "tp") -> jnp.ndarray:
    """all-gather vocab-sharded logits to full vocab on the last dim (the
    eval/debug path; ref: tp_communications.py:51-64 GatherFromModelParallel)."""
    return lax.all_gather(logits, axis, axis=logits.ndim - 1, tiled=True)
