"""The composed parallel train step — one SPMD program over the 4D mesh.

This is the TPU-native replacement for the reference's entire L4/L5 wiring
(apply_tensor_parallel -> PipelineParallel -> apply_context_parallel ->
DataParallelBucket -> train_step dispatch, ref: train.py:174-231):

- gradients: differentiate through `lax.pmean(loss, ('dp','cp'))` — the
  transpose machinery emits exactly the grad all-reduce over the fused cp_dp
  group that the reference implements with bucketed autograd hooks
  (ref: data_parallel.py:83, bucket.py:25-31). XLA's all-reduce combiner
  plays the role of the 25MB bucket manager, and its latency-hiding
  scheduler overlaps the reduction with remaining backward compute.
- the optimizer update runs *outside* shard_map in plain GSPMD land, so
  optax transforms (incl. global-norm clipping) see global arrays and
  gradient-norm reductions span all shards automatically.
- one uniform code path for every (dp, pp, cp, tp) size — collectives over
  size-1 axes compile away, so there are no `if tp > 1` forks in the traced
  program (the reference dispatches between four wrapper stacks).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from picotron_tpu.config import Config
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.models.llama import ParallelCtx, init_params, loss_fn
from picotron_tpu.optimizer import make_optimizer
from picotron_tpu.parallel.sharding import batch_spec, param_specs
from picotron_tpu.parallel.tp import (
    gather_logits,
    vocab_parallel_ce,
    vocab_parallel_embed,
)
from picotron_tpu.train_step import TrainState


def make_parallel_ctx(cfg: Config) -> ParallelCtx:
    """Build the ParallelCtx used *inside* the shard_map body.

    Must be called under an active ('dp','pp','cp','tp') mesh context since
    positions use axis_index. Uniform across axis sizes: tp hooks and cp
    position arithmetic are identities when the axis has size 1.
    """
    d = cfg.distributed
    s_local = cfg.training.seq_length // d.cp_size
    positions = lax.axis_index("cp") * s_local + jnp.arange(s_local)

    if d.cp_size > 1:
        from picotron_tpu.ops.ring_attention import ring_attention

        def attn(q, k, v, pos):
            return ring_attention(q, k, v, axis="cp")
    else:
        from picotron_tpu.ops.attention import sdpa_attention

        def attn(q, k, v, pos):
            return sdpa_attention(q, k, v, causal=True,
                                  q_positions=pos, kv_positions=pos)

    return ParallelCtx(
        attn=attn,
        g=lambda x: lax.psum(x, "tp"),
        embed_lookup=partial(vocab_parallel_embed, axis="tp"),
        head_ce=partial(vocab_parallel_ce, axis="tp"),
        gather_logits=partial(gather_logits, axis="tp"),
        positions=positions,
        remat=cfg.training.remat,
    )


def _device_grads(params, batch, cfg: Config):
    """Per-device grad computation: scan microbatches accumulating fp32
    grads (ref: train.py:29-55 loop + require_backward_grad_sync gating),
    then one pmean over the data axes."""
    ctx = make_parallel_ctx(cfg)
    ids, tgt = batch  # [n_micro, mbs_local, s_local]
    n_micro = ids.shape[0]

    def micro_step(carry, mb):
        g_acc, l_acc = carry
        mb_ids, mb_tgt = mb
        loss, grads = jax.value_and_grad(loss_fn)(params, mb_ids, mb_tgt,
                                                  cfg.model, ctx)
        return (jax.tree.map(jnp.add, g_acc, grads), l_acc + loss), None

    # The grad/loss accumulators become dp/cp-varying inside the scan (the
    # loss depends on this device's batch shard), so the initial carry must
    # carry the same varying type.
    zeros = jax.tree.map(jnp.zeros_like, params)
    init_carry = lax.pcast((zeros, jnp.zeros((), jnp.float32)),
                           ("dp", "cp"), to="varying")
    (grads, loss_sum), _ = lax.scan(micro_step, init_carry, (ids, tgt))
    scale = 1.0 / n_micro
    grads = jax.tree.map(lambda g: g * scale, grads)
    # gradient + loss sync over the fused data axes (the reference's cp_dp
    # group semantics: ref process_group_manager.py:22, utils.py:93-98)
    grads = lax.pmean(grads, ("dp", "cp"))
    loss = lax.pmean(loss_sum * scale, ("dp", "cp"))
    return grads, loss


def make_train_step(cfg: Config, menv: MeshEnv):
    """Build the jitted (TrainState, batch) -> (TrainState, loss) step over
    the 4D mesh. batch = (input_ids, targets), each [n_micro, global_b, seq]
    sharded P(None, 'dp', 'cp')."""
    cfg.validate()
    mesh = menv.mesh
    pspecs = param_specs(cfg)
    bspec = batch_spec()
    opt = make_optimizer(cfg.training)

    grad_fn = jax.shard_map(
        partial(_device_grads, cfg=cfg),
        mesh=mesh,
        in_specs=(pspecs, (bspec, bspec)),
        out_specs=(pspecs, P()),
    )

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, batch):
        grads, loss = grad_fn(state.params, batch)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return TrainState(new_params, opt_state, state.step + 1), loss

    return step


def init_sharded_state(cfg: Config, menv: MeshEnv, key: jax.Array) -> TrainState:
    """Initialize params directly into their mesh shardings (each device
    materializes only its shard — the role of the reference's meta-device
    init + per-rank materialization, ref: checkpoint.py:15-102, minus the
    safetensors shape-template dance)."""
    cfg.validate()
    mesh = menv.mesh
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P),
    )
    params = jax.jit(
        partial(init_params, cfg.model), out_shardings=shardings
    )(key)
    opt = make_optimizer(cfg.training)
    opt_state = jax.jit(opt.init)(params)
    step0 = jnp.zeros((), jnp.int32)
    return TrainState(params=params, opt_state=opt_state, step=step0)
