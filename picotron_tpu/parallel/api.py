"""The composed parallel train step — one SPMD program over the 5D mesh.

This is the TPU-native replacement for the reference's entire L4/L5 wiring
(apply_tensor_parallel -> PipelineParallel -> apply_context_parallel ->
DataParallelBucket -> train_step dispatch, ref: train.py:174-231):

- gradients: differentiate through `lax.pmean(loss, ('dp','cp'))` — the
  transpose machinery emits exactly the grad all-reduce over the fused cp_dp
  group that the reference implements with bucketed autograd hooks
  (ref: data_parallel.py:83, bucket.py:25-31). XLA's all-reduce combiner
  plays the role of the 25MB bucket manager, and its latency-hiding
  scheduler overlaps the reduction with remaining backward compute.
- the standard (on-device) optimizer update runs *outside* shard_map in
  plain GSPMD land, so optax transforms (incl. global-norm clipping) see
  global arrays and gradient-norm reductions span all shards
  automatically. Under `optimizer_offload` the update instead runs
  INSIDE the same shard_map body as the gradients (grads crossing the
  boundary as outputs cost a second full fp32 grad tree — PERF.md r4);
  there the hand-rolled streamed AdamW (optimizer.offload_adam_update)
  reproduces the optax math per shard, with an explicit per-leaf psum
  over each param's sharded axes for the global grad norm.
- one uniform code path for every (dp, pp, cp, tp) size — collectives over
  size-1 axes compile away, so there are no `if tp > 1` forks in the traced
  program (the reference dispatches between four wrapper stacks).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from picotron_tpu import compat
from picotron_tpu.config import (
    Config, resolved_cp_flavor, resolved_cp_mesh,
)
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.models.llama import (
    ParallelCtx, init_params, loss_sum_count, pad_layers_for_pp,
)
from picotron_tpu.optimizer import (
    OffloadAdamState, make_optimizer, offload_adam_update,
)
from picotron_tpu.parallel.sharding import batch_spec, param_shardings, param_specs
from picotron_tpu.parallel.tp import (
    gather_logits,
    sp_gather_seq,
    sp_scatter_seq,
    vocab_parallel_ce_local_stats,
    vocab_parallel_ce_merge,
    vocab_parallel_ce_sum_count,
    vocab_parallel_embed,
)
from picotron_tpu.train_step import TrainState, guard_nonfinite


def make_parallel_ctx(cfg: Config) -> ParallelCtx:
    """Build the ParallelCtx used *inside* the shard_map body.

    Must be called under an active ('dp','pp','cp','tp') mesh context since
    positions use axis_index. Uniform across axis sizes: tp hooks and cp
    position arithmetic are identities when the axis has size 1.
    """
    d = cfg.distributed
    s_local = cfg.training.seq_length // d.cp_size
    idx = lax.axis_index("cp")
    if d.cp_size == 1:
        # contiguous 0..S-1 — encode as None (ParallelCtx's documented
        # meaning) so the flash kernels take the static-causal fast path
        # (program-id block classes + DMA-free skipped tiles; PERF.md r5)
        positions = None
    elif d.cp_layout == "zigzag":
        # Must mirror data.cp_sequence_permutation: shard r holds chunks
        # (r, 2cp-1-r) of 2cp chunks — its tokens' global positions.
        half = s_local // 2
        lo = idx * half
        hi = (2 * d.cp_size - 1 - idx) * half
        positions = jnp.concatenate([lo + jnp.arange(half),
                                     hi + jnp.arange(half)])
    else:
        positions = idx * s_local + jnp.arange(s_local)

    # Attention implementation dispatch (the reference routes via the
    # FLASH_ATTEN / CONTEXT_PARALLEL env vars, ref: model.py:148-158):
    # flash = the Pallas kernel on TPU (jnp twin elsewhere), reference = the
    # plain jnp softmax path, ring = require context parallelism.
    if cfg.model.attn_impl in ("ring", "ulysses", "mesh") and d.cp_size == 1:
        raise ValueError(
            f"attn_impl={cfg.model.attn_impl!r} requires cp_size > 1 (it is "
            "a context-parallel schedule; ref: context_parallel.py:10-12)"
        )
    use_flash = cfg.model.attn_impl in ("auto", "flash", "ring", "ulysses",
                                        "mesh")
    if use_flash:
        from picotron_tpu.ops.flash_attention import flash_attention as attn_fn
    else:
        from picotron_tpu.ops.attention import sdpa_attention as attn_fn

    cp_flavor = resolved_cp_flavor(cfg)
    if d.cp_size > 1 and cp_flavor == "ulysses":
        from picotron_tpu.ops.ulysses import (
            ulysses_attention, ulysses_static_layout,
        )

        # the gathered sequence's global positions are exactly the
        # dataloader's layout permutation (arange when contiguous) — known
        # at trace time, so no runtime position all_gather is needed, and a
        # static argsort restores a monotone sequence so the kernel's
        # causal fast paths fire. Derived by ulysses_static_layout — the
        # same source the fused grad engine's backward uses, so the two
        # sides cannot disagree about the gathered order.
        full_pos, seq_sort = ulysses_static_layout(cfg)

        def attn(q, k, v, pos, rope):
            # one all_to_all pair trades the seq shard for a head shard;
            # the flash kernel (fused RoPE, position-masked causal) then
            # runs full-sequence on this device's head subset (ops/ulysses)
            return ulysses_attention(q, k, v, axis="cp", q_positions=pos,
                                     attn_fn=attn_fn, rope=rope,
                                     seq_sort=seq_sort,
                                     full_positions=full_pos,
                                     # full_pos is built from the config
                                     # right here — a trace-time constant
                                     positions_static=True)
    elif d.cp_size > 1 and cp_flavor == "mesh":
        from picotron_tpu.ops.mesh_attention import mesh_attention
        from picotron_tpu.ops.rope import apply_rope

        cp_mesh = resolved_cp_mesh(cfg)
        blockwise = partial(attn_fn, return_lse=True)

        def attn(q, k, v, pos, rope):
            # same pre-rotation contract as the ring (rotation commutes
            # with the head split, so positions stay single-sourced here);
            # the 2D schedule factors cp into a cp_y head scatter and a
            # cp_x row ring (ops/mesh_attention.py)
            q = apply_rope(q, *rope, pos)
            k = apply_rope(k, *rope, pos)
            return mesh_attention(q, k, v, axis="cp", cp_mesh=cp_mesh,
                                  q_positions=pos, attn_block=blockwise)
    elif d.cp_size > 1:
        from picotron_tpu.ops.ring_attention import ring_attention
        from picotron_tpu.ops.rope import apply_rope

        blockwise = partial(attn_fn, return_lse=True)

        def attn(q, k, v, pos, rope):
            # positions are single-sourced here: RoPE and the ring's causal
            # masking must see the same sequence layout (zigzag ordering
            # changes `positions` in exactly one place). K/V are rotated
            # BEFORE entering the ring so each block travels pre-rotated
            # with its positions (ref: context_parallel.py:189-195).
            q = apply_rope(q, *rope, pos)
            k = apply_rope(k, *rope, pos)
            return ring_attention(q, k, v, axis="cp", q_positions=pos,
                                  attn_block=blockwise)
    elif use_flash:

        def attn(q, k, v, pos, rope):
            # RoPE fused into the Pallas kernels (rotation + un-rotation in
            # VMEM) — XLA's rotate-half concat/slice chain profiled at ~7%
            # of a train step.
            return attn_fn(q, k, v, causal=True, rope=rope,
                           q_positions=pos, kv_positions=pos)
    else:
        from picotron_tpu.ops.rope import apply_rope

        def attn(q, k, v, pos, rope):
            q = apply_rope(q, *rope, pos)
            k = apply_rope(k, *rope, pos)
            return attn_fn(q, k, v, causal=True,
                           q_positions=pos, kv_positions=pos)

    ce_chunk = cfg.training.ce_chunk_size
    ce = partial(vocab_parallel_ce_sum_count, axis="tp", chunk_size=ce_chunk)
    hooks = dict(
        g=lambda x: lax.psum(x, "tp"),
        embed_lookup=partial(vocab_parallel_embed, axis="tp"),
        head_ce=ce,
        # the split form lets the PP engines run the head matmul only on
        # the last stage (collective-free branch + tiny uniform merge)
        head_ce_local=partial(vocab_parallel_ce_local_stats, axis="tp",
                              chunk_size=ce_chunk),
        head_ce_merge=partial(vocab_parallel_ce_merge, axis="tp"),
    )
    if d.sequence_parallel:
        # Megatron-SP (parallel/tp.py): residual stream seq-sharded over tp,
        # f/g become all_gather / reduce-scatter. head_ce and the eval logits
        # path re-gather the sequence before the head matmul (a seq-sharded
        # hidden against a vocab-sharded head would yield diagonal blocks of
        # the logits, which cannot be assembled).
        hooks = dict(
            f=sp_gather_seq,
            g=sp_scatter_seq,
            embed_lookup=partial(vocab_parallel_embed, axis="tp",
                                 scatter_seq=True),
            head_ce=lambda x, head, tgt: ce(sp_gather_seq(x), head, tgt),
            seq_shard=d.tp_size,
            # all tp ranks compute the same aux from the gathered tokens;
            # pmean re-marks it tp-invariant for the loss fold
            moe_aux_sync=lambda a: lax.pmean(a, "tp"),
        )

    # Non-megatron TP strategies and deferred activation sync install their
    # hook overrides on top (parallel/tp_strategies.py); {} on the plain
    # megatron/SP sync paths, so those stay byte-identical.
    from picotron_tpu.parallel.tp_strategies import tp_strategy_hooks

    hooks.update(tp_strategy_hooks(cfg, ce=ce))

    # Uneven-PP padding: mask the aux statistics of pad slots from the
    # STATIC placement rule (pp_layer_placement puts each stage's real
    # layers in its leading slots; remainder to early stages) rather than
    # sniffing router weights (ADVICE r3).
    L, pp = cfg.model.num_hidden_layers, d.pp_size
    layer_is_real = None
    if pp > 1 and L % pp != 0:
        def layer_is_real(n_slots):
            cnt = L // pp + (lax.axis_index("pp") < L % pp).astype(jnp.int32)
            return (jnp.arange(n_slots) < cnt).astype(jnp.float32)

    return ParallelCtx(
        attn=attn,
        gather_logits=partial(gather_logits, axis="tp"),
        positions=positions,
        layer_is_real=layer_is_real,
        moe_ep_axis="ep",
        # layout-exact router statistics: pmean f/P/z over the data axes so
        # the aux losses describe the global batch (config.router_aux_global)
        moe_stat_axes=(("dp", "ep", "cp")
                       if cfg.model.router_aux_global else None),
        remat=cfg.training.remat,
        remat_policy=cfg.training.remat_policy,
        **hooks,
    )


def _data_axes_psum(grads, cfg: Config):
    """Sum grads over the data axes. 'ep' is a data axis for every param
    EXCEPT the expert banks sharded over it — their per-device grads already
    integrate every peer's tokens via the dispatch all_to_all, so an ep psum
    would multiply them by ep_size.

    This is the one seam BOTH grad engines exit through (the AD and fused
    paths below, and the pp scan path) — so it is also where the multi-slice
    layouts swap the flat psum for the hierarchical DCN schedule
    (parallel/hier_reduce.py): reduce-scatter inside the slice, a
    shard-per-slice all-reduce across DCN, all-gather back."""
    from picotron_tpu.parallel.hier_reduce import hier_axes_psum, use_hier_dp

    specs = param_specs(cfg)
    hier = use_hier_dp(cfg)

    def red(g, spec):
        flat = [a for part in spec if part is not None
                for a in (part if isinstance(part, (tuple, list)) else (part,))]
        axes = ("dp", "cp") if "ep" in flat else ("dp", "ep", "cp")
        if hier:
            return hier_axes_psum(g, axes, cfg)
        return lax.psum(g, axes)

    return jax.tree.map(red, grads, specs, is_leaf=lambda x: isinstance(x, P))


def _normalize_extras(dropw, count, cfg: Config) -> dict:
    """Turn the token-weighted capacity-drop sum into the global fraction:
    dropw accumulates sum_micro(count_micro * sum_layers(drop_frac)), so
    dividing by count_total * L gives the token-weighted mean per-layer
    drop fraction. Empty for dense models (no silent dict keys)."""
    if not cfg.model.num_experts:
        return {}
    return {"moe_drop_frac":
            dropw / (count * cfg.model.num_hidden_layers)}


def _device_grads(params, batch, cfg: Config):
    """Per-device grad computation: scan microbatches accumulating fp32
    NLL-sum grads and valid-token counts (ref: train.py:29-55 loop +
    require_backward_grad_sync gating), then one psum over the data axes and
    a single division — a per-shard token mean followed by an unweighted
    pmean would mis-weight shards whose IGNORE_INDEX counts differ.

    Returns (grads, loss, extras) — extras is a dict of normalized
    observability scalars ({"moe_drop_frac"} for MoE runs, {} otherwise)
    that the step surfaces in its metrics."""
    ctx = make_parallel_ctx(cfg)
    ids, tgt = batch  # [n_micro, mbs_local, s_local]

    if cfg.distributed.pp_size > 1:
        # The pipeline scan subsumes the microbatch loop: grad accumulation
        # across microbatches IS the schedule (ref: train.py:225-227
        # dispatches to the pipeline engines the same way).
        from picotron_tpu.parallel.pp import (
            pipeline_1f1b_grads, pipeline_loss_sum_count,
            sync_pp_replicated_grads, sync_sp_partial_grads,
        )

        if cfg.distributed.pp_engine == "1f1b":
            # Manual-VJP schedule: grads come out of the scan directly.
            grads, nll_total, count, dropw = pipeline_1f1b_grads(
                params, ids, tgt, cfg, ctx)
        else:  # "afab": differentiate through the forward scan

            def pp_nll(params):
                total, count, dropw = pipeline_loss_sum_count(
                    params, ids, tgt, cfg, ctx)
                return total, (count, dropw)

            (nll_total, (count, dropw)), grads = jax.value_and_grad(
                pp_nll, has_aux=True)(params)
        grads = sync_pp_replicated_grads(grads, param_specs(cfg))
        if cfg.distributed.sequence_parallel:
            grads = sync_sp_partial_grads(grads, params)
        grads = _data_axes_psum(grads, cfg)
        nll_total = lax.psum(nll_total, ("dp", "ep", "cp"))
        dropw = lax.psum(dropw, ("dp", "ep", "cp"))
        count = jnp.maximum(lax.psum(count, ("dp", "ep", "cp")), 1)
        return _finish_grads(grads, nll_total, count, dropw, cfg)

    from picotron_tpu.parallel.fused_bwd import (
        fused_bwd_supported, fused_micro_grads,
    )

    t = cfg.training
    use_fused = (t.grad_engine == "fused"
                 or (t.grad_engine == "auto"
                     and t.gradient_accumulation_steps > 1
                     and fused_bwd_supported(cfg)))

    def nll_sum(params, mb_ids, mb_tgt):
        total, count, extras = loss_sum_count(params, mb_ids, mb_tgt,
                                              cfg.model, ctx)
        return total, (count, extras.get("moe_drop_weighted",
                                         jnp.zeros((), jnp.float32)))

    def micro_step(carry, mb):
        g_acc, l_acc, c_acc, d_acc = carry
        mb_ids, mb_tgt = mb
        if use_fused:
            # manual backward layer scan accumulating dW in-scan: no
            # per-microbatch grad tree, no whole-tree adds (fused_bwd.py)
            g_acc, total, count, dropw = fused_micro_grads(
                params, mb_ids, mb_tgt, g_acc, cfg, ctx)
            return (g_acc, l_acc + total, c_acc + count,
                    d_acc + dropw), None
        (total, (count, dropw)), grads = jax.value_and_grad(
            nll_sum, has_aux=True)(params, mb_ids, mb_tgt)
        return (jax.tree.map(jnp.add, g_acc, grads), l_acc + total,
                c_acc + count, d_acc + dropw), None

    d = cfg.distributed
    if ids.shape[0] == 1 and not use_fused:
        # Single-microbatch fast path: differentiate directly — the
        # accumulation scan's fp32 zeros carry + per-microbatch grad temp
        # would hold TWO full grad trees for zero numerical effect
        # (add(0.0f32, bf16 g) is an exact promotion). At MoE scale the
        # double tree is the difference between fitting and OOM: the
        # Mixtral-8x7B single-chip row needs this path (PERF.md r5).
        # (An explicit grad_engine='fused' still takes the scan path —
        # silently swapping engines under the user would invalidate any
        # ga=1 A/B measurement; code review r5.)
        (nll_total, (count, dropw)), grads = jax.value_and_grad(
            nll_sum, has_aux=True)(params, ids[0], tgt[0])
        if (not cfg.training.optimizer_offload
                or d.dp_size * d.ep_size * d.cp_size > 1):
            # fp32 BEFORE the data-axes psum: under offload the bf16
            # params yield bf16 grads, and a multi-shard all-reduce in
            # bf16 would drop exactly the low bits the fp32 master keeps
            # (the accumulation path promotes via its fp32 carry; code
            # review r5). Single-shard offload keeps the bf16 tree — the
            # psum is an identity there and the streamed update casts
            # per slice, which is what lets Mixtral-1L fit.
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32), grads)
    else:
        # The accumulators become dp/ep/cp-varying inside the scan (they
        # depend on this device's batch shard), so the initial carry must
        # carry the same varying type. Promote per leaf, skipping axes a
        # leaf already varies over (expert banks arrive ep-varying from
        # their sharding).
        from picotron_tpu.parallel.pp import _vary_over

        # fp32 accumulation regardless of the param dtype: with
        # optimizer_offload the params (hence per-microbatch grads) are
        # bf16; summing grad-acc microbatches in bf16 would lose exactly
        # the low bits the fp32 master exists to keep (jnp.add promotes
        # bf16 + fp32 -> fp32).
        zeros = jax.tree.map(
            lambda p: _vary_over(jnp.zeros(p.shape, jnp.float32),
                                 {"dp", "ep", "cp"} | set(compat.vma(p))),
            params)
        init_carry = (zeros,) + compat.pcast(
            (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32),
             jnp.zeros((), jnp.float32)),
            ("dp", "ep", "cp"), to="varying")
        (grads, nll_total, count, dropw), _ = lax.scan(
            micro_step, init_carry, (ids, tgt))
    # gradient + loss sync over the fused data axes (the reference's cp_dp
    # group semantics: ref process_group_manager.py:22, utils.py:93-98)
    grads = _data_axes_psum(grads, cfg)
    nll_total = lax.psum(nll_total, ("dp", "ep", "cp"))
    dropw = lax.psum(dropw, ("dp", "ep", "cp"))
    count = jnp.maximum(lax.psum(count, ("dp", "ep", "cp")), 1)
    return _finish_grads(grads, nll_total, count, dropw, cfg)


def _finish_grads(grads, nll_total, count, dropw, cfg: Config):
    """Final token-mean normalization. Under optimizer_offload the grads are
    returned UN-divided with the 1/count scale riding in extras: the
    elementwise division would materialize a second 6.75 GB fp32 grad tree
    (it cannot fuse across the while-loop boundary into the streamed update
    scan) — measured as ~6 GB of "fragmentation" that OOMed full-depth
    SmolLM-1.7B. offload_adam_update folds the scale into its slice math
    instead."""
    extras = _normalize_extras(dropw, count, cfg)
    if cfg.training.optimizer_offload:
        extras["_grad_scale"] = 1.0 / count.astype(jnp.float32)
        return grads, nll_total / count, extras
    return (jax.tree.map(lambda g: g / count, grads), nll_total / count,
            extras)


def make_train_step(cfg: Config, menv: MeshEnv, inject_nan: bool = False):
    """Build the jitted (TrainState, batch) -> (TrainState, metrics) step
    over the mesh. batch = (input_ids, targets), each
    [n_micro, global_b, seq] sharded P(None, ('dp', 'ep'), 'cp').

    metrics is a dict with at least {"loss"}; MoE runs additionally carry
    {"moe_drop_frac"} (the capacity-drop observability scalar — VERDICT r2
    weak #4: drops used to be silent in training logs). With
    resilience.guard_policy != "off" it also carries {"grad_norm",
    "nonfinite"} — the divergence guard's inputs — and under policy
    "skip" a non-finite loss/grad step keeps params and optimizer state
    unchanged (train_step.guard_nonfinite; the step counter still
    advances).

    `inject_nan=True` poisons every step's gradients and loss — the
    chaos harness's nan_grad event (the driver routes only the injected
    steps through this variant). Injection must live inside the compiled
    step: it is the only way the in-jit skip path sees a genuinely
    non-finite gradient tree."""
    cfg.validate()
    if cfg.pipeline.executor == "mpmd":
        # Per-stage programs + host-side schedule (parallel/mpmd.py) —
        # same (state, batch) -> (state, metrics) contract, so callers
        # (train.py, chaos harness) never see the executor swap. Lazy
        # import: mpmd.py imports this module at its top level.
        from picotron_tpu.parallel.mpmd import make_mpmd_train_step

        return make_mpmd_train_step(cfg, menv, inject_nan=inject_nan)
    mesh = menv.mesh
    pspecs = param_specs(cfg)
    bspec = batch_spec()
    guards_on = cfg.resilience.guard_policy != "off"
    guard_skip = cfg.resilience.guard_policy == "skip"

    def _poison(grads, loss):
        nan = jnp.float32(jnp.nan)
        grads = jax.tree.map(lambda g: g + nan.astype(g.dtype), grads)
        return grads, loss + nan

    grad_fn = compat.shard_map(
        partial(_device_grads, cfg=cfg),
        mesh=mesh,
        in_specs=(pspecs, (bspec, bspec)),
        out_specs=(pspecs, P(), P()),  # P() prefixes the extras dict
    )

    if cfg.training.optimizer_offload:
        from picotron_tpu.models.llama import compute_dtype

        cdt = compute_dtype(cfg.model)
        transfer = offload_memory_kind(mesh) is not None

        # The update runs INSIDE the shard_map body, fused with the grad
        # computation: grads crossing the shard_map boundary as outputs
        # cost a SECOND full fp32 grad tree (the grad-accumulation while
        # carry cannot alias a boundary output — measured 6-7 GB of pure
        # waste at SmolLM-1.7B scale). Inside, every leaf is this device's
        # local shard and the host<->device moves are memory-space-only
        # transfers, so the same body is correct on any mesh (each process
        # streams exactly its own host-resident state shards).
        # ZeRO-1 composition (VERDICT r4 #3): the host master/moments
        # shard over the fused data axes; each process streams 1/dp of
        # the state and the update all-gathers the refreshed bf16 params
        # over dp at the end.
        z1_info = None
        mspecs = pspecs
        if cfg.distributed.zero1:
            abs_master = abstract_master(cfg)
            z1_info = offload_zero1_info(cfg, abs_master)
            sizes = _zero1_sizes(cfg)
            mspecs = jax.tree.map(
                lambda s, a: _zero1_spec(s, a.shape, sizes),
                pspecs, abs_master, is_leaf=lambda x: isinstance(x, P))

        def _device_step(params, batch, opt_state):
            grads, loss, extras = _device_grads(params, batch, cfg)
            if inject_nan:
                grads, loss = _poison(grads, loss)
            grad_scale = extras.pop("_grad_scale")
            new_params, new_opt = offload_adam_update(
                grads, opt_state, cfg.training, cdt, transfer=transfer,
                clip_specs=pspecs, grad_scale=grad_scale,
                zero1_info=z1_info)
            return new_params, new_opt, loss, extras

        opt_specs = OffloadAdamState(count=P(), master=mspecs, mu=mspecs,
                                     nu=mspecs)
        # Under zero1 the refreshed bf16 params leave the shard_map still
        # sharded over the zero1 axes (out spec = mspecs); the GSPMD
        # constraint below re-gathers them to the full param layout — the
        # ZeRO-1 update all-gather, expressed as a resharding.
        fused = compat.shard_map(
            _device_step, mesh=mesh,
            in_specs=(pspecs, (bspec, bspec), opt_specs),
            out_specs=(mspecs, opt_specs, P(), P()))
        full_shardings = param_shardings(cfg, mesh)

        @partial(jax.jit, donate_argnums=(0,))
        def step(state: TrainState, batch):
            new_params, new_opt, loss, extras = fused(
                state.params, batch, state.opt_state)
            if cfg.distributed.zero1:
                new_params = jax.lax.with_sharding_constraint(
                    new_params, full_shardings)
            metrics = {"loss": loss, **extras}
            if guards_on:
                # Offload guards key on the (already psum'd) loss only: a
                # per-shard global grad norm would need the clip_specs
                # psum machinery for no policy benefit — 'skip' is
                # rejected for offload at config time, and rollback/abort
                # both trigger off the loss.
                metrics["nonfinite"] = (
                    1.0 - jnp.isfinite(loss).astype(jnp.float32))
            return TrainState(new_params, new_opt, state.step + 1), metrics

        return step

    opt = make_optimizer(cfg.training)

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, batch):
        grads, loss, extras = grad_fn(state.params, batch)
        if inject_nan:
            grads, loss = _poison(grads, loss)
        if guards_on:
            # One global norm covers the whole tree: any NaN/Inf leaf
            # poisons it, so non-finite detection is a single scalar
            # check instead of a per-leaf isfinite sweep. Surfaced as a
            # metric either way — grad-norm curves are standard
            # divergence forensics.
            gnorm = optax.global_norm(grads)
            ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)
            extras = {**extras, "grad_norm": gnorm,
                      "nonfinite": 1.0 - ok.astype(jnp.float32)}
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if guards_on and guard_skip:
            new_params = guard_nonfinite(ok, new_params, state.params)
            opt_state = guard_nonfinite(ok, opt_state, state.opt_state)
        metrics = {"loss": loss, **extras}
        return TrainState(new_params, opt_state, state.step + 1), metrics

    return step


def make_eval_step(cfg: Config, menv: MeshEnv):
    """Jitted forward-only (params, batch) -> loss over the mesh — the
    validation half of the train step: same sharded loss computation
    (pipeline engines included, via the AFAB loss path), no grads, no
    optimizer, no donation (params are reused across eval batches)."""
    cfg.validate()
    pspecs = param_specs(cfg)
    bspec = batch_spec()

    def _device_loss(params, batch):
        ctx = make_parallel_ctx(cfg)
        ids, tgt = batch
        if cfg.distributed.pp_size > 1:
            from picotron_tpu.parallel.pp import pipeline_loss_sum_count

            total, count, _ = pipeline_loss_sum_count(params, ids, tgt,
                                                      cfg, ctx)
        else:
            def body(carry, mb):
                l_acc, c_acc = carry
                total, count, _ = loss_sum_count(params, mb[0], mb[1],
                                                 cfg.model, ctx)
                return (l_acc + total, c_acc + count), None

            init = compat.pcast(
                (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
                ("dp", "ep", "cp"), to="varying")
            (total, count), _ = lax.scan(body, init, (ids, tgt))
        total = lax.psum(total, ("dp", "ep", "cp"))
        count = jnp.maximum(lax.psum(count, ("dp", "ep", "cp")), 1)
        return total / count

    loss_fn_sharded = compat.shard_map(
        _device_loss, mesh=menv.mesh,
        in_specs=(pspecs, (bspec, bspec)), out_specs=P())
    return jax.jit(loss_fn_sharded)


def init_sharded_state(cfg: Config, menv: MeshEnv, key: jax.Array,
                       abstract: bool = False) -> TrainState:
    """Initialize params directly into their mesh shardings (each device
    materializes only its shard — the role of the reference's meta-device
    init + per-rank materialization, ref: checkpoint.py:15-102, minus the
    safetensors shape-template dance).

    `abstract=True` returns sharding-annotated ShapeDtypeStructs instead of
    real arrays — zero memory, same shardings — for AOT uses like
    tools/memcheck.py's compile-only analysis (materializing a 7B model's
    fp32 master + moments just to call .lower() would need ~84 GB of host
    RAM)."""
    cfg.validate()
    mesh = menv.mesh
    shardings = param_shardings(cfg, mesh)

    def init(key):
        # Pad the layer stack for uneven PP splits (identity zero-layers);
        # real layers keep exactly the single-device init values.
        return pad_layers_for_pp(init_params(cfg.model, key),
                                 cfg.model.num_hidden_layers,
                                 cfg.distributed.pp_size)

    if cfg.training.optimizer_offload:
        return _init_offload_state(cfg, menv, key, init, shardings, abstract)

    if abstract:
        params = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            jax.eval_shape(init, key), shardings)
    else:
        params = jax.jit(init, out_shardings=shardings)(key)
    opt = make_optimizer(cfg.training)
    # Optimizer moments must mirror the param shardings (Adam mu/nu live
    # wherever their param lives — the reference gets this implicitly from
    # per-rank optimizer instances, ref: train.py:209); scalar counters are
    # replicated. Without explicit out_shardings, jit can leave the whole
    # opt state on one device, which breaks the first step after a
    # checkpoint restore. Moment subtrees are recognized structurally (any
    # opt-state subtree with the params' treedef takes the params'
    # shardings leaf-for-leaf) — matching by leaf shape would collide for
    # same-shape/different-spec params like q [h, h] and o [h, h].
    replicated = NamedSharding(mesh, P())
    params_treedef = jax.tree.structure(params)
    param_leaf_shardings = [p.sharding for p in jax.tree.leaves(params)]

    if cfg.distributed.zero1:
        # ZeRO-1 (beyond the reference; SURVEY §2.2 marks ZeRO absent): the
        # Adam moments additionally shard over the data axes — GSPMD then
        # partitions the elementwise optimizer update per shard and inserts
        # the update all-gather, i.e. the ZeRO-1 schedule falls out of a
        # sharding annotation instead of a hand-written partitioner.
        sizes = _zero1_sizes(cfg)
        param_leaf_shardings = [
            NamedSharding(mesh, _zero1_spec(s.spec, p.shape, sizes))
            for p, s in zip(jax.tree.leaves(params), param_leaf_shardings)]

    def opt_subtree_shardings(subtree):
        if jax.tree.structure(subtree) == params_treedef:
            return jax.tree.unflatten(params_treedef, param_leaf_shardings)
        return jax.tree.map(lambda _: replicated, subtree)

    abstract_opt = jax.eval_shape(opt.init, params)
    opt_shardings = jax.tree.map(
        opt_subtree_shardings, abstract_opt,
        is_leaf=lambda x: jax.tree.structure(x) == params_treedef)
    if abstract:
        opt_state = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract_opt, opt_shardings)
        step0 = jax.ShapeDtypeStruct((), jnp.int32, sharding=replicated)
    else:
        opt_state = jax.jit(opt.init, out_shardings=opt_shardings)(params)
        step0 = jax.device_put(jnp.zeros((), jnp.int32), replicated)
    return TrainState(params=params, opt_state=opt_state, step=step0)


def offload_memory_kind(mesh) -> str | None:
    """'pinned_host' on TPU, None elsewhere. On the CPU backend "device"
    memory IS host RAM, and XLA:CPU's pinned_host plumbing cannot round-trip
    donated buffers through jit outputs — so the simulated-mesh tests run
    the offload code path placement-free (same math, same state layout)
    while real chips get genuine host placement."""
    return ("pinned_host"
            if mesh.devices.flat[0].platform == "tpu" else None)


def _init_offload_state(cfg: Config, menv: MeshEnv, key, init,
                        dev_shardings, abstract: bool) -> TrainState:
    """optimizer_offload state layout: fp32 master + Adam moments in pinned
    host memory (sharded exactly like their params), bf16 compute copy + an
    int32 step counter on device. See OffloadAdamState."""
    from picotron_tpu.models.llama import compute_dtype

    mesh = menv.mesh
    abs_master = abstract_master(cfg)
    host_shardings = _offload_host_shardings(cfg, mesh, abs_master)
    cdt = compute_dtype(cfg.model)
    mdt = (jnp.bfloat16 if cfg.training.adam_moments_dtype == "bfloat16"
           else jnp.float32)
    replicated = NamedSharding(mesh, P())

    if abstract:
        sds = lambda a, dt, s: jax.ShapeDtypeStruct(  # noqa: E731
            a.shape, dt, sharding=s)
        master = jax.tree.map(lambda a, s: sds(a, a.dtype, s),
                              abs_master, host_shardings)
        params = jax.tree.map(lambda a, s: sds(a, cdt, s),
                              abs_master, dev_shardings)
        mu = jax.tree.map(lambda a, s: sds(a, mdt, s),
                          abs_master, host_shardings)
        nu = jax.tree.map(lambda a, s: sds(a, mdt, s),
                          abs_master, host_shardings)
        count = jax.ShapeDtypeStruct((), jnp.int32, sharding=replicated)
        step0 = jax.ShapeDtypeStruct((), jnp.int32, sharding=replicated)
    else:
        # Stage through device shardings and device_put to host OUTSIDE jit:
        # XLA's SPMD partitioner rejects host-memory-kind out_shardings on a
        # multi-device mesh ("side-effect HLO must have sharding"), while
        # plain device_put transfers (and device_put inside jit, which the
        # train step uses) partition fine.
        master_dev = jax.jit(init, out_shardings=dev_shardings)(key)
        params = jax.jit(
            lambda mp: jax.tree.map(lambda x: x.astype(cdt), mp),
            out_shardings=dev_shardings)(master_dev)
        master = jax.device_put(master_dev, host_shardings)
        zeros = jax.jit(
            lambda: jax.tree.map(lambda a: jnp.zeros(a.shape, mdt),
                                 abs_master),
            out_shardings=dev_shardings)
        mu = jax.device_put(zeros(), host_shardings)
        nu = jax.device_put(zeros(), host_shardings)
        count = jax.device_put(jnp.zeros((), jnp.int32), replicated)
        step0 = jax.device_put(jnp.zeros((), jnp.int32), replicated)
    opt_state = OffloadAdamState(count=count, master=master, mu=mu, nu=nu)
    return TrainState(params=params, opt_state=opt_state, step=step0)


def install_params(cfg: Config, menv: MeshEnv, state: TrainState,
                   params) -> TrainState:
    """Install externally produced fp32 params (HF import, params-only
    restore) into `state`, respecting the optimizer-state layout: under
    optimizer_offload they become the pinned-host master AND the bf16
    device compute copy; otherwise they simply replace state.params."""
    from picotron_tpu.models.llama import compute_dtype

    if not cfg.training.optimizer_offload:
        shardings = param_shardings(cfg, menv.mesh)
        return state._replace(
            params=jax.tree.map(jax.device_put, params, shardings))
    dev_shardings = param_shardings(cfg, menv.mesh)
    host_shardings = _offload_host_shardings(
        cfg, menv.mesh, jax.eval_shape(lambda t: t, params))
    master = jax.tree.map(
        lambda p, s: jax.device_put(jnp.asarray(p, jnp.float32), s),
        params, host_shardings)
    compute = jax.jit(
        lambda mp: jax.tree.map(
            lambda x: x.astype(compute_dtype(cfg.model)), mp),
        out_shardings=dev_shardings)(master)
    return state._replace(params=compute,
                          opt_state=state.opt_state._replace(master=master))


def _zero1_placement(spec: P, shape, data_axis_sizes: dict):
    """(dim, axes) of the ZeRO-1 shard extension for this leaf, or None
    when none qualifies: the first unsharded dimension divisible by the
    product of the applicable fused data axes ('dp','ep'). Axes the param
    already shards over (the ep of expert banks) are excluded, matching
    _data_axes_psum's view of which axes are data axes per leaf."""
    used = {a for part in spec if part is not None
            for a in (part if isinstance(part, (tuple, list)) else (part,))}
    axes = tuple(a for a in ("dp", "ep")
                 if data_axis_sizes.get(a, 1) > 1 and a not in used)
    if not axes:
        return None
    factor = 1
    for a in axes:
        factor *= data_axis_sizes[a]
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, (entry, dim) in enumerate(zip(entries, shape)):
        if entry is None and dim % factor == 0:
            return i, axes
    return None


def _zero1_spec(spec: P, shape, data_axis_sizes: dict) -> P:
    """Extend a param's PartitionSpec per `_zero1_placement` (identity when
    no dimension qualifies — tiny tensors just stay replicated)."""
    place = _zero1_placement(spec, shape, data_axis_sizes)
    if place is None:
        return spec
    dim, axes = place
    entries = list(spec) + [None] * (len(shape) - len(spec))
    entries[dim] = axes if len(axes) > 1 else axes[0]
    return P(*entries)


def _zero1_sizes(cfg: Config) -> dict:
    return {"dp": cfg.distributed.dp_size, "ep": cfg.distributed.ep_size}


def abstract_master(cfg: Config):
    """ShapeDtypeStructs of the fp32 master param pytree — the single
    source of the param tree structure wherever specs must align with the
    real state leaf-for-leaf (zero1 placements, host shardings,
    checkpoint templates). Every consumer derives from here so the init
    expression cannot silently diverge between sites (code review r5)."""
    return jax.eval_shape(lambda: pad_layers_for_pp(
        init_params(cfg.model, jax.random.key(0)),
        cfg.model.num_hidden_layers, cfg.distributed.pp_size))


def offload_zero1_info(cfg: Config, abs_master) -> list | None:
    """Flattened-leaf-aligned list of (dim, axes, axis_sizes) ZeRO-1
    placements (None per leaf when unsharded) for the offload x zero1
    composition, or None when zero1 is off. Static — consumed at trace
    time by optimizer.offload_adam_update for the grad slice / param
    all-gather."""
    if not cfg.distributed.zero1:
        return None
    sizes = _zero1_sizes(cfg)
    specs = param_specs(cfg)
    s_leaves = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    a_leaves = jax.tree.leaves(abs_master)
    out = []
    for s, a in zip(s_leaves, a_leaves):
        place = _zero1_placement(s, a.shape, sizes)
        out.append(None if place is None else
                   (place[0], place[1],
                    tuple(sizes[ax] for ax in place[1])))
    return out


def _offload_host_shardings(cfg: Config, mesh, abs_master):
    """Host-memory shardings for the offload master/moments. Under zero1
    they additionally shard over the fused data axes (VERDICT r4 #3 —
    each process keeps and streams only 1/dp of the host state; the
    update all-gathers the refreshed bf16 params over dp afterwards)."""
    kind = offload_memory_kind(mesh)
    if not cfg.distributed.zero1:
        return param_shardings(cfg, mesh, memory_kind=kind)
    kw = {} if kind is None else {"memory_kind": kind}
    sizes = _zero1_sizes(cfg)
    return jax.tree.map(
        lambda spec, a: NamedSharding(
            mesh, _zero1_spec(spec, a.shape, sizes), **kw),
        param_specs(cfg), abs_master,
        is_leaf=lambda x: isinstance(x, P))
