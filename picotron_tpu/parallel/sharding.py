"""Parameter and batch PartitionSpecs over the (dp, pp, cp, tp) mesh.

This module is the declarative heart of DP/TP/PP: where the reference
surgically replaces nn.Linear modules with Column/Row/VocabParallel classes
(ref: tensor_parallel.py:9-52) and slices layer stacks per pipeline rank
(ref: pipeline_parallel.py:13-51), here one pytree of PartitionSpecs says
where every parameter lives and GSPMD materializes exactly that shard per
device:

- column-parallel (q/k/v/gate/up): output features on 'tp'
- row-parallel (o/down): input features on 'tp'
- vocab-parallel (embedding, lm_head): vocab dim on 'tp'
- stacked decoder layers: leading layer axis on 'pp' (the reference's
  contiguous stage slices, ref: pipeline_parallel.py:42-51, as a sharding)
- norms: replicated over tp (sequence-parallel sharding is a future option)
- everything: replicated over dp and cp (they are data axes; ZeRO-style
  param sharding over dp is a deliberate non-goal for parity — SURVEY.md
  §2.2 marks FSDP absent in the reference)
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from picotron_tpu.config import Config


def param_specs(cfg: Config) -> dict[str, Any]:
    """PartitionSpec pytree matching models.llama.init_params' structure.

    Non-megatron TP strategies (config.resolved_tp_strategy) only re-point
    which tensor dim carries 'tp': "row" flips a class to input-feature
    (qkv/up) or output-feature (o/down) shards; "2d" keeps the megatron
    1D shards — its tp_x x tp_y layout is expressed purely as subgroup
    collectives over those shards (parallel/tp_strategies.py), so the
    stored layout (and every checkpoint) is strategy-invariant except for
    the explicit "row" flip."""
    from picotron_tpu.config import resolved_tp_strategy

    # layers % pp divisibility is enforced by Config.validate().
    pp = "pp" if cfg.distributed.pp_size > 1 else None
    strat = resolved_tp_strategy(cfg)

    def pair(cls):
        # (entry, exit) specs for a col/row-paired class: megatron and 2d
        # store column shards for the entry and row shards for the exit;
        # "row" flips both.
        if strat[cls] == "row":
            return P(pp, "tp", None), P(pp, None, "tp")
        return P(pp, None, "tp"), P(pp, "tp", None)

    qkv_spec, o_spec = pair("qkv")
    up_spec, down_spec = pair("up")
    layers = {
        "input_norm": P(pp, None),
        "q": qkv_spec,
        "k": qkv_spec,
        "v": qkv_spec,
        "o": o_spec,
        "post_norm": P(pp, None),
    }
    if cfg.model.attention_bias:
        # qkv biases shard over tp with their output features
        layers.update({
            "b_q": P(pp, "tp"),
            "b_k": P(pp, "tp"),
            "b_v": P(pp, "tp"),
        })
    if cfg.model.num_experts:
        # expert banks [L, E, ...]: expert dim over 'ep', ffn dim over 'tp'
        # (column-parallel gate/up, row-parallel down — same as the dense
        # MLP); the router is small and replicated.
        layers.update({
            "router": P(pp, None, None),
            "w_gate": P(pp, "ep", None, "tp"),
            "w_up": P(pp, "ep", None, "tp"),
            "w_down": P(pp, "ep", "tp", None),
        })
    else:
        layers.update({
            "gate": up_spec,
            "up": up_spec,
            "down": down_spec,
        })
    specs = {
        "embedding": P("tp", None),
        "layers": layers,
        "final_norm": P(),
    }
    if not cfg.model.tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def batch_spec() -> P:
    """[n_micro, batch, seq] token blocks: batch over dp, sequence over cp
    (the contiguous CP split, ref: data.py:105-109, as a sharding)."""
    return P(None, ("dp", "ep"), "cp")


def param_shardings(cfg: Config, mesh,
                    memory_kind: str | None = None) -> dict[str, Any]:
    """NamedShardings for every param leaf. `memory_kind='pinned_host'`
    places the same shards in host RAM — the optimizer-offload home for the
    fp32 master and Adam moments (each shards exactly like its param, so a
    multi-chip topology splits the host-resident state across hosts too)."""
    kw = {} if memory_kind is None else {"memory_kind": memory_kind}
    return jax.tree.map(lambda s: NamedSharding(mesh, s, **kw),
                        param_specs(cfg),
                        is_leaf=lambda x: isinstance(x, P))
