"""MPMD pipeline executor: per-stage jitted programs + a host-side schedule.

The SPMD 1f1b engine (parallel/pp.py) runs the whole pipeline as ONE jitted
lockstep scan: every device executes every tick's traced unit whether its
schedule slot is active or not, so an IDLE tick costs a full forward+backward
unit (PERF.md r4 measured 64.7 ms/tick with an implied bubble of 7.0 ticks at
pp=4). This module is the fix from "Scaling Deep Learning Training with MPMD
Pipeline Parallelism" (arxiv 2412.14374): compile one program per pipeline
stage (each tracing ONLY its own layer block) and drive them from a host-side
schedule table — an idle tick dispatches nothing and costs ~0, which is what
makes interleaved (and zero-bubble-style) schedules profitable at all.

Architecture (selected by `pipeline.executor: mpmd`; the SPMD scan stays as
the reference twin under `spmd`):

- **Schedule tables** (`build_schedule`) — a greedy dependency-driven tick
  simulator generalizing pp.py's closed-form 1f1b table (fwd of microbatch m
  at stage s on tick m+s, bwd on tick m+2(pp-1)-s — the greedy simulator
  with backward-priority reproduces exactly that makespan) to gpipe,
  interleaved (v virtual layer chunks per device group) and zero-bubble
  (ZB-H1-style split-backward, accounting only) schedules, and to the edge
  shapes (n_micro < pp, n_micro == 1, pp == 1 passthrough) the closed form
  never met.
- **Per-stage programs** — each virtual stage j (layer block j of V = pp*v)
  gets a forward and a backward `jit(shard_map)` over its device group's
  submesh (axes dp/ep/cp/tp — no 'pp' axis: stage identity is baked in, so
  the head matmul is traced only into the last stage's program and pp.py's
  lax.cond gating disappears). The backward recomputes the stage interior
  from the saved stage *input* under `jax.vjp` — the same manual-VJP math as
  the SPMD 1f1b engine, honoring the configured remat policy — and adds
  per-microbatch grads (psummed over the data axes) into a donated fp32
  accumulator, so every program is compile-once by construction
  (analysis/variants.py proves it).
- **Ring buffers** — boundary activations/cotangents move between stage
  submeshes via explicit `jax.device_put` (committed shardings end to end),
  so a step is `jax.transfer_guard("disallow")`-clean: nothing implicit
  crosses hosts or devices.
- **Finish program** — one jitted step-tail over the FULL mesh: concatenate
  the per-chunk layer grads back into the P('pp')-sharded global tree, sum
  multi-owner leaves (a tied embedding earns grads on both the first and the
  last stage), divide by the token count, and run the optax update + guard
  logic of the SPMD step, donating the TrainState.

Known costs, accepted for this revision and recorded in PERF.md: per-step
param re-slicing + chunk grads crossing to the full mesh replicate boundary
tensors over 'pp' (aliasing the chunk shards into the global arrays is a
future optimization), and per-microbatch grads pay their data-axes psum per
backward call instead of once per step (ga x more collective launches, each
1/ga the payload).
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from picotron_tpu import compat
from picotron_tpu.config import Config
from picotron_tpu.resilience import chaos, watchdog
from picotron_tpu.telemetry import bus as telemetry_bus
from picotron_tpu.telemetry.flightdeck.tracer import TID_PP_BASE
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.models.llama import (
    compute_dtype, embed, final_hidden, head_weight, model_rope_tables,
    pp_layer_placement, run_layers,
)
from picotron_tpu.optimizer import make_optimizer
from picotron_tpu.parallel.api import make_parallel_ctx
from picotron_tpu.parallel.pp import _cast_varying_like, _vary_over
from picotron_tpu.parallel.sharding import batch_spec, param_shardings, param_specs
from picotron_tpu.train_step import TrainState, guard_nonfinite

# Submesh axes of one stage's device group: the full mesh minus 'pp'.
SUB_AXES = ("dp", "ep", "cp", "tp")

# Executable schedules ("zb" is accounting-only: the split-backward programs
# it needs are not built; config.validate() rejects it as a pipeline.schedule
# value, bench --pp-tick-sweep reports its tick accounting).
SCHEDULES = ("1f1b", "gpipe", "interleaved", "zb")

# Hook for per-stage tick timing (telemetry): when set, a sampled step calls
# it with ({group: [op_seconds, ...]}, python_step_index) after its schedule
# walk. train.py installs the telemetry emitter; sampling cadence comes from
# PICOTRON_PP_TICK_SAMPLE (0 = never; N = every Nth step), so the
# block_until_ready the timing needs never rides an unsampled step.
on_stage_times = None


# ---------------------------------------------------------------------------
# Schedule tables
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TickOp:
    """One scheduled unit: device group `group` runs `op` for microbatch
    `mb` of virtual stage `vstage` at host tick `tick`. Ops: "F" forward,
    "B" backward (combined), "BX"/"BW" the zero-bubble split (input-grad /
    weight-grad halves)."""

    tick: int
    group: int
    op: str
    mb: int
    vstage: int


def build_schedule(kind: str, n_micro: int, pp: int,
                   interleave: int = 1) -> list[TickOp]:
    """Greedy dependency-driven schedule table, sorted by (tick, group).

    Model: V = pp * interleave virtual stages; virtual stage j runs on
    device group j % pp (Megatron's round-robin chunk assignment); each
    group executes at most one op per tick and every op costs one tick.
    Dependencies: F(m, j) needs F(m, j-1); B(m, j) needs F(m, j) and
    B(m, j+1); the zero-bubble split relaxes the weight half — BX carries
    the B dependencies, BW needs only BX(m, j) and fills bubbles at the
    lowest priority (ZB-H1's observation).

    Priorities: "gpipe" runs any ready forward first (the AFAB dependency
    shape); everything else runs ready backwards first — which reproduces
    the canonical 1f1b warmup/steady/cooldown (stage s forwards pp-1-s
    extra microbatches before its first backward falls ready) and its
    2n + 2(pp-1) tick makespan, without hand-writing the three phases.
    Edge shapes fall out of the dependency rules: n_micro < pp and
    n_micro == 1 just drain early, pp == 1 degenerates to an alternating
    F/B stream (or all-F-then-all-B for gpipe) with zero bubble.
    """
    if kind not in SCHEDULES:
        raise ValueError(f"unknown schedule kind {kind!r}; one of {SCHEDULES}")
    if n_micro < 1 or pp < 1:
        raise ValueError(
            f"need n_micro >= 1 and pp >= 1, got {n_micro}/{pp}")
    v = interleave if kind == "interleaved" else 1
    if interleave != 1 and kind != "interleaved":
        raise ValueError(
            f"interleave={interleave} only applies to the 'interleaved' "
            f"schedule, got kind={kind!r}")
    if v < 1:
        raise ValueError(f"interleave must be >= 1, got {interleave}")
    V = pp * v
    split_b = kind == "zb"

    f_done: dict = {}   # (mb, vstage) -> first tick the result is usable
    b_done: dict = {}   # combined B, or BX under the zb split
    w_done: dict = {}   # BW under the zb split
    ops: list[TickOp] = []
    total = n_micro * V * (3 if split_b else 2)
    t = 0
    max_ticks = 8 * total + 16  # generous; greedy always progresses
    while len(ops) < total and t < max_ticks:
        for g in range(pp):
            stages = range(g, V, pp)
            ready_f = [(m, j) for j in stages for m in range(n_micro)
                       if (m, j) not in f_done
                       and (j == 0 or f_done.get((m, j - 1), t + 1) <= t)]
            ready_b = [(m, j) for j in stages for m in range(n_micro)
                       if (m, j) not in b_done
                       and f_done.get((m, j), t + 1) <= t
                       and (j == V - 1 or b_done.get((m, j + 1), t + 1) <= t)]
            ready_w = [(m, j) for j in stages for m in range(n_micro)
                       if split_b and (m, j) not in w_done
                       and b_done.get((m, j), t + 1) <= t]
            # F tie-break: deepest virtual stage first under interleaving
            # (advance in-flight microbatches to completion so backwards
            # fall ready early); plain schedules have one vstage per group.
            f_key = (lambda o: (-o[1], o[0])) if v > 1 else (
                lambda o: (o[0], o[1]))
            b_key = lambda o: (o[0], -o[1])  # noqa: E731 — FIFO microbatches
            pick = None
            if kind == "gpipe":
                if ready_f:
                    pick, kop = min(ready_f, key=f_key), "F"
                elif ready_b:
                    pick, kop = min(ready_b, key=b_key), "B"
            else:
                if ready_b:
                    pick, kop = min(ready_b, key=b_key), "BX" if split_b else "B"
                elif ready_f:
                    pick, kop = min(ready_f, key=f_key), "F"
                elif ready_w:
                    pick, kop = min(ready_w, key=b_key), "BW"
            if pick is None:
                continue
            m, j = pick
            ops.append(TickOp(tick=t, group=g, op=kop, mb=m, vstage=j))
            done = {"F": f_done, "B": b_done, "BX": b_done, "BW": w_done}[kop]
            done[(m, j)] = t + 1
        t += 1
    if len(ops) < total:
        raise RuntimeError(
            f"schedule simulator stalled at {len(ops)}/{total} ops "
            f"(kind={kind}, n={n_micro}, pp={pp}, v={interleave})")
    problems = lint_schedule(ops, n_micro, pp, interleave, kind=kind)
    if problems:
        raise ScheduleBufferError(
            f"schedule table fails the static lint (kind={kind}, "
            f"n={n_micro}, pp={pp}, v={interleave}): "
            f"{'; '.join(problems)}")
    return ops


def lint_schedule(table: list, n_micro: int, pp: int,
                  interleave: int = 1, kind: str = None) -> list[str]:
    """Static schedule-table lint: walk the table with the exact
    produce/consume rules `_run_schedule` applies at runtime and return
    every problem as a string — the `ScheduleBufferError` contract proven
    BEFORE any schedule runs, instead of after a wasted walk.

    Three rule families:

    - **consume-before-produce**: an op that pops an activation /
      cotangent / saved-input buffer no earlier op filled would KeyError
      mid-walk at runtime (a dependency-broken table);
    - **balanced produce/consume**: the end-of-walk live set must be
      empty per (vstage, mb) buffer key — leftovers are orphaned tensors
      some dispatched op produced and nothing consumed (a truncated
      table), exactly what the runtime assert at the end of
      `_run_schedule` reports today;
    - **bounded live set**: the peak number of saved stage inputs per
      virtual stage must not exceed the schedule's in-flight budget —
      n_micro for gpipe (all-forward-then-all-backward legitimately
      saves everything), min(n_micro, 2*pp*v) per vstage otherwise.
      The greedy backward-first simulator's warmup depth at early
      stages reaches 2*pp - 3 (measured across pp up to 16), so the
      bound tracks twice the pipeline depth, widened by the interleave
      factor. A table over budget would OOM activations on hardware
      even though it drains cleanly.

    The zb split's BX carries B's buffer rules and BW is buffer-neutral
    (weight-grad only). Exposed through `shardcheck --variants`
    (analysis/variants.py) so a schedule bug is a static finding."""
    V = pp * (interleave if interleave > 1 else 1)
    if V < 2:
        return []
    problems: list[str] = []
    names = {"x": "activation", "s": "saved-input", "g": "cotangent"}
    live: dict = {}            # ("x"|"s"|"g", vstage, mb) -> True
    peak_saved: dict = {}      # vstage -> peak live saved-inputs
    n_saved: dict = {}

    def produce(b, j, m):
        live[(b, j, m)] = True
        if b == "s":
            n_saved[j] = n_saved.get(j, 0) + 1
            peak_saved[j] = max(peak_saved.get(j, 0), n_saved[j])

    def consume(b, j, m, op):
        if not live.pop((b, j, m), None):
            problems.append(
                f"{op.op}@tick{op.tick} (vstage={op.vstage}, mb={op.mb}) "
                f"consumes {names[b]} (vstage={j}, mb={m}) never produced")
        elif b == "s":
            n_saved[j] -= 1

    for op in sorted(table, key=lambda o: (o.tick, o.group)):
        j, m = op.vstage, op.mb
        if op.op == "F":
            if j == 0:
                produce("x", j + 1, m)
            elif j == V - 1:
                consume("x", j, m, op)
                produce("s", j, m)
            else:
                consume("x", j, m, op)
                produce("s", j, m)
                produce("x", j + 1, m)
        elif op.op in ("B", "BX"):
            if j == V - 1:
                consume("s", j, m, op)
                produce("g", j - 1, m)
            elif j == 0:
                consume("g", j, m, op)
            else:
                consume("s", j, m, op)
                consume("g", j, m, op)
                produce("g", j - 1, m)
        # BW: weight-grad half, touches no boundary buffers
    leftover = sorted(live)
    if leftover:
        keys = "; ".join(f"{names[b]} (vstage={j}, mb={m})"
                         for b, j, m in leftover)
        problems.append(
            f"{len(leftover)} live boundary buffer(s) at end of walk — "
            f"produced but never consumed: {keys}")
    v = interleave if interleave > 1 else 1
    budget = n_micro if kind == "gpipe" else min(n_micro, 2 * pp * v)
    for j, peak in sorted(peak_saved.items()):
        if peak > budget:
            problems.append(
                f"vstage {j} holds {peak} saved inputs at peak, over the "
                f"schedule's in-flight budget of {budget} — the table "
                f"defers backwards past the {kind or 'schedule'} "
                f"in-flight depth (activation OOM on hardware)")
    return problems


def schedule_stats(kind: str, n_micro: int, pp: int,
                   interleave: int = 1) -> dict:
    """Tick accounting for a schedule, in full units (1 unit = one stage's
    forward + backward for one microbatch — the SPMD scan's per-tick cost).

    kind="spmd" prices the lockstep scan twin closed-form: n + 2(pp-1)
    ticks, EVERY tick a full unit on every device, so bubble = 2(pp-1)
    units. MPMD schedules are priced off the simulated table: makespan
    ticks / ticks-per-unit, where a full unit spans 2v chunk-ops (3v under
    the zb split, whose halves each cost ~a forward — the ZB-H1
    assumption). busy is always n_micro units; the bubble is the rest.
    """
    if kind == "spmd":
        makespan = float(n_micro + 2 * (pp - 1))
        return {
            "kind": kind, "n_micro": n_micro, "pp": pp, "interleave": 1,
            "ticks": n_micro + 2 * (pp - 1), "makespan_units": makespan,
            "busy_units": float(n_micro),
            "bubble_units": float(2 * (pp - 1)),
            "bubble_fraction": 2 * (pp - 1) / makespan if makespan else 0.0,
        }
    table = build_schedule(kind, n_micro, pp, interleave)
    v = interleave if kind == "interleaved" else 1
    ticks = max(op.tick for op in table) + 1
    per_unit = (3 if kind == "zb" else 2) * v
    makespan = ticks / per_unit
    bubble = makespan - n_micro
    return {
        "kind": kind, "n_micro": n_micro, "pp": pp, "interleave": interleave,
        "ticks": ticks, "makespan_units": makespan,
        "busy_units": float(n_micro), "bubble_units": bubble,
        "bubble_fraction": bubble / makespan if makespan else 0.0,
    }


def pipeline_bubble_fraction(cfg: Config) -> float:
    """Static schedule-derived idle fraction of a step for this config (0.0
    when pp == 1) — what telemetry books under the 'pp_bubble' goodput
    category. For the SPMD executor this is the lockstep scan's full-price
    accounting; for MPMD it comes off the simulated table."""
    pp = cfg.distributed.pp_size
    if pp <= 1:
        return 0.0
    n = cfg.training.gradient_accumulation_steps
    kind = ("spmd" if cfg.pipeline.executor == "spmd"
            else cfg.pipeline.schedule)
    return schedule_stats(kind, n, pp, cfg.pipeline.interleave)[
        "bubble_fraction"]


# ---------------------------------------------------------------------------
# Stage decomposition
# ---------------------------------------------------------------------------


def _stage_blocks(cfg: Config) -> list[tuple[int, int, np.ndarray | None]]:
    """Per virtual stage j: (row_lo, row_hi, real_mask_or_None) into the
    padded global layer stack. Block j is the j-th contiguous chunk of
    padded rows; its real-slot mask comes from the same static placement
    rule as pp_layer_placement (group k's real layers fill the leading
    counts[k] of its `per` rows). For dense models the mask is only
    documentation — pad layers are exact identities with zero grads — but
    it keeps the chunk programs aligned with the SPMD layout."""
    L, pp = cfg.model.num_hidden_layers, cfg.distributed.pp_size
    v = cfg.pipeline.interleave
    padded, _ = pp_layer_placement(L, pp)
    per = padded // pp
    V = pp * v
    if padded % V != 0:
        raise ValueError(
            f"interleave {v} does not divide the per-stage slot count "
            f"{per} (padded stack {padded}, pp {pp})")
    Lv = padded // V
    counts = np.asarray([L // pp + (1 if k < L % pp else 0)
                         for k in range(pp)])
    blocks = []
    for j in range(V):
        rows = np.arange(j * Lv, (j + 1) * Lv)
        mask = (rows % per) < counts[rows // per]
        blocks.append((j * Lv, (j + 1) * Lv,
                       None if mask.all() else mask.astype(np.float32)))
    return blocks


def _stage_meshes(menv: MeshEnv) -> list[Mesh]:
    """One submesh per device group: the full mesh's pp=g slice, re-meshed
    over (dp, ep, cp, tp)."""
    dev = menv.mesh.devices  # (dp, pp, ep, cp, tp)
    return [Mesh(dev[:, g], SUB_AXES) for g in range(dev.shape[1])]


def _strip_pp(spec: P) -> P:
    return P(*[None if part == "pp" else part for part in spec])


def _chunk_param_specs(cfg: Config, j: int, V: int) -> dict:
    """PartitionSpec tree of virtual stage j's parameter chunk on its
    submesh: the layer-block slice (leading 'pp' dropped — the block lives
    whole on the group), plus the embedding on the first stage and the
    final norm + head on the last (the tied-embedding case puts the
    embedding on BOTH end stages; the finish program sums their grads)."""
    full = param_specs(cfg)
    layers = jax.tree.map(_strip_pp, full["layers"],
                          is_leaf=lambda x: isinstance(x, P))
    specs: dict = {"layers": layers}
    tied = "lm_head" not in full
    if j == 0:
        specs["embedding"] = full["embedding"]
    if j == V - 1:
        specs["final_norm"] = full["final_norm"]
        if tied:
            specs["embedding"] = full["embedding"]
        else:
            specs["lm_head"] = full["lm_head"]
    return specs


def _sub_data_psum(grads, cfg: Config):
    """Per-microbatch grad reduction over the submesh's data axes. No
    per-leaf exceptions: MoE (the expert-bank case _data_axes_psum special-
    cases) is rejected for the MPMD executor at config time. When dp
    carries a slice granule (dcn_axes includes dp at slices > 1) the flat
    psum becomes the hierarchical DCN schedule — the submesh's dp axis
    spans the same global dp coordinates as the SPMD mesh's, so the
    intra/cross slice groups of parallel/hier_reduce.py apply unchanged."""
    from picotron_tpu.parallel.hier_reduce import hier_axes_psum, use_hier_dp

    if use_hier_dp(cfg):
        return jax.tree.map(
            lambda g: hier_axes_psum(g, ("dp", "ep", "cp"), cfg), grads)
    return jax.tree.map(lambda g: lax.psum(g, ("dp", "ep", "cp")), grads)


def _accumulate(acc, g_params):
    return jax.tree.map(
        lambda a, g: jnp.add(a, _cast_varying_like(g.astype(jnp.float32), a)),
        acc, g_params)


# ---------------------------------------------------------------------------
# Per-stage programs
# ---------------------------------------------------------------------------


class _StagePrograms:
    """Compiled surface of one virtual stage: fwd / bwd / zeros jits plus
    the committed shardings its feeds must carry. Built once per train-step
    construction; every call site feeds identical abstract signatures, so
    each jit mints exactly one executable (proven by analysis/variants.py).
    """

    def __init__(self, cfg: Config, submesh: Mesh, j: int, V: int,
                 block, global_mesh: Mesh):
        lo, hi, mask = block
        m = cfg.model
        self.j, self.V = j, V
        self.first, self.last = j == 0, j == V - 1
        first, last = self.first, self.last
        pspecs = _chunk_param_specs(cfg, j, V)
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(submesh, s), pspecs,
            is_leaf=lambda x: isinstance(x, P))
        xspec = P(("dp", "ep"), "cp", None)
        bspec = batch_spec()
        self.x_sharding = NamedSharding(submesh, xspec)
        self.batch_sharding = NamedSharding(submesh, bspec)
        self.scalar_sharding = NamedSharding(submesh, P())
        tied = "lm_head" not in param_specs(cfg)
        self.tied = tied

        def ctx_for():
            ctx = make_parallel_ctx(cfg)
            # The composed ctx's layer_is_real reads lax.axis_index('pp'),
            # which does not exist on the submesh — replace it with this
            # chunk's STATIC mask (None when every slot is real; dense pad
            # slots are exact identities either way).
            lir = (None if mask is None
                   else (lambda n_slots: jnp.asarray(mask)))
            return dataclasses.replace(ctx, layer_is_real=lir)

        def run_chunk(params, x):
            ctx = ctx_for()
            cos, sin = model_rope_tables(m)
            y, _ = run_layers(params["layers"], x, m, ctx, cos, sin)
            return y

        def embed_chunk(params, mb_ids):
            ctx = ctx_for()
            cos, sin = model_rope_tables(m)
            x = embed(params, mb_ids, m, ctx)
            y, _ = run_layers(params["layers"], x, m, ctx, cos, sin)
            return y

        def chunk_loss(params, x, mb_tgt):
            ctx = ctx_for()
            cos, sin = model_rope_tables(m)
            y, _ = run_layers(params["layers"], x, m, ctx, cos, sin)
            hf = final_hidden(params, y, m)
            total, count = ctx.head_ce(hf, head_weight(params), mb_tgt)
            return total, count

        sm = partial(compat.shard_map, mesh=submesh)
        P_ = P()

        if first:

            def fwd_body(params, ids, idx):
                mb = lax.dynamic_index_in_dim(ids, idx, 0, keepdims=False)
                return embed_chunk(params, mb)

            self.fwd = jax.jit(sm(fwd_body,
                                  in_specs=(pspecs, bspec, P_),
                                  out_specs=xspec))

            def bwd_body(params, ids, idx, g_in, acc):
                mb = lax.dynamic_index_in_dim(ids, idx, 0, keepdims=False)
                y, vjp_fn = jax.vjp(lambda p: embed_chunk(p, mb), params)
                (g_params,) = vjp_fn(_cast_varying_like(g_in, y))
                return _accumulate(acc, _sub_data_psum(g_params, cfg))

            self.bwd = jax.jit(
                sm(bwd_body,
                   in_specs=(pspecs, bspec, P_, xspec, pspecs),
                   out_specs=pspecs),
                donate_argnums=(4,))
        elif last:

            def fwd_body(params, x_in, tgt, idx, nll_acc, cnt_acc):
                mb_tgt = lax.dynamic_index_in_dim(tgt, idx, 0,
                                                  keepdims=False)
                total, count = chunk_loss(params, x_in, mb_tgt)
                total = lax.psum(total, ("dp", "ep", "cp"))
                count = lax.psum(count, ("dp", "ep", "cp"))
                return total, count, nll_acc + total, cnt_acc + count

            self.fwd = jax.jit(
                sm(fwd_body,
                   in_specs=(pspecs, xspec, bspec, P_, P_, P_),
                   out_specs=(P_, P_, P_, P_)),
                donate_argnums=(4, 5))

            def bwd_body(params, x_saved, tgt, idx, acc):
                mb_tgt = lax.dynamic_index_in_dim(tgt, idx, 0,
                                                  keepdims=False)

                def f(p, x):
                    total, _ = chunk_loss(p, x, mb_tgt)
                    return total
                total, vjp_fn = jax.vjp(f, params, x_saved)
                one = _vary_over(jnp.ones((), jnp.float32),
                                 set(compat.vma(total)))
                g_params, g_x = vjp_fn(one)
                return _accumulate(acc, _sub_data_psum(g_params, cfg)), g_x

            self.bwd = jax.jit(
                sm(bwd_body,
                   in_specs=(pspecs, xspec, bspec, P_, pspecs),
                   out_specs=(pspecs, xspec)),
                donate_argnums=(4,))
        else:

            def fwd_body(params, x_in):
                return run_chunk(params, x_in)

            self.fwd = jax.jit(sm(fwd_body,
                                  in_specs=(pspecs, xspec),
                                  out_specs=xspec))

            def bwd_body(params, x_saved, g_in, acc):
                y, vjp_fn = jax.vjp(run_chunk, params, x_saved)
                g_params, g_x = vjp_fn(_cast_varying_like(g_in, y))
                return _accumulate(acc, _sub_data_psum(g_params, cfg)), g_x

            self.bwd = jax.jit(
                sm(bwd_body,
                   in_specs=(pspecs, xspec, xspec, pspecs),
                   out_specs=(pspecs, xspec)),
                donate_argnums=(3,))

        # Grad-accumulator factory: fresh fp32 zeros each step (the previous
        # step's accumulators were donated into their last bwd call).
        abs_chunk = jax.tree.map(
            lambda s: None, pspecs, is_leaf=lambda x: isinstance(x, P))
        del abs_chunk  # structure documented via pspecs; zeros built below
        self._slicer = _make_slicer(cfg, lo, hi, first, last, tied)
        abs_params = _abstract_global_params(cfg)
        abs_chunk = jax.eval_shape(self._slicer, abs_params)
        self.abstract_params = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abs_chunk, self.param_shardings)
        self.zeros = jax.jit(
            lambda: jax.tree.map(
                lambda a: jnp.zeros(a.shape, jnp.float32), abs_chunk),
            out_shardings=self.param_shardings)

    def slice_params(self, global_params):
        """Chunk this stage's params off the global tree (a compile-once
        global-mesh jit) and commit them onto the stage submesh via an
        explicit device_put."""
        return jax.device_put(self._slicer(global_params),
                              self.param_shardings)


def _abstract_global_params(cfg: Config):
    from picotron_tpu.parallel.api import abstract_master

    return abstract_master(cfg)


def _make_slicer(cfg: Config, lo: int, hi: int, first: bool, last: bool,
                 tied: bool):
    def slicer(params):
        out = {"layers": jax.tree.map(
            lambda x: lax.slice_in_dim(x, lo, hi, axis=0),
            params["layers"])}
        if first or (last and tied):
            out["embedding"] = params["embedding"]
        if last:
            out["final_norm"] = params["final_norm"]
            if not tied:
                out["lm_head"] = params["lm_head"]
        return out
    return jax.jit(slicer)


# ---------------------------------------------------------------------------
# The executor
# ---------------------------------------------------------------------------


def _build_stages(cfg: Config, menv: MeshEnv):
    pp, v = cfg.distributed.pp_size, cfg.pipeline.interleave
    V = pp * v
    if cfg.distributed.slices > 1:
        # every stage program must live whole on one slice when pp alone
        # carries the cut (see check_stage_slice_placement) — asserted at
        # build time so a grid regression fails before a step runs
        check_stage_slice_placement(cfg)
    blocks = _stage_blocks(cfg)
    meshes = _stage_meshes(menv)
    return [_StagePrograms(cfg, meshes[j % pp], j, V, blocks[j], menv.mesh)
            for j in range(V)]


class ScheduleBufferError(RuntimeError):
    """The schedule walk finished with live boundary buffers: some
    dispatched op produced an activation/cotangent/saved-input that no
    later op consumed. Always a schedule-table bug (truncated table,
    broken dependency edge) — named so the diagnostic lists exactly
    which (vstage, mb) keys were orphaned instead of a bare assert."""


def _index_arrays(n_micro: int, sharding: NamedSharding):
    """The microbatch index feed, staged ONCE: n committed int32 scalars on
    the stage submesh. Re-minting them per step would be a host-to-device
    transfer inside the schedule walk (transfer_guard-dirty) for values
    that never change."""
    return [jax.device_put(np.int32(i), sharding) for i in range(n_micro)]


def _run_schedule(stages, table, chunk_params, accs, state_scalars,
                  ids_s, tgt_s, idx_first, idx_last, timings=None,
                  step=None):
    """Walk the schedule table in (tick, group) order, dispatching stage
    programs and moving boundary tensors with explicit device_put. Returns
    (accs, nll_acc, cnt_acc, per_microbatch_nll, per_microbatch_cnt).

    Mid-schedule fault surface: each dispatched op heartbeats the
    watchdog with the live (stage, tick, op, mb) — a stall inside the
    walk is reported as that op, not a bare stack dump — and calls the
    `schedule_tick` chaos point so a `#TICK` event can deliver a
    SIGTERM/hang at a named op. A SIGTERM landing mid-walk only sets the
    preemption flag (the handler runs no consequential Python), so the
    walk always drains to the step boundary: the emergency checkpoint
    the driver then writes only ever contains fully-accumulated state,
    never a half-walked schedule's partial grads."""
    V = len(stages)
    nll_acc, cnt_acc = state_scalars
    # flightdeck span tracer (telemetry/flightdeck): one fetch per walk,
    # then a None check per op. When tracing, each op is synced like the
    # sampled-timings path so span durations are real tick times (an
    # opt-in perturbation, same as PICOTRON_PP_TICK_SAMPLE).
    _tel = telemetry_bus.active()
    tracer = getattr(_tel, "tracer", None) if _tel is not None else None
    xbuf: dict = {}    # (vstage, mb) -> inbound activation
    xsave: dict = {}   # (vstage, mb) -> saved stage input for the backward
    gbuf: dict = {}    # (vstage, mb) -> inbound cotangent
    mb_nll: dict = {}
    mb_cnt: dict = {}
    for op in table:
        j, mb = op.vstage, op.mb
        st = stages[j]
        if watchdog.active():
            watchdog.touch(f"pp_schedule stage={j} tick={op.tick} "
                           f"op={op.op} mb={mb}", step)
        if step is not None:
            chaos.fire("schedule_tick", step=step,
                       tick=op.tick, stage=j, op=op.op, mb=mb)
        t0 = (time.perf_counter()
              if (timings is not None or tracer is not None) else 0.0)
        if op.op == "F":
            if st.first:
                y = st.fwd(chunk_params[j], ids_s, idx_first[mb])
                xbuf[(j + 1, mb)] = jax.device_put(
                    y, stages[j + 1].x_sharding)
            elif st.last:
                x_in = xbuf.pop((j, mb))
                xsave[(j, mb)] = x_in
                nll_mb, cnt_mb, nll_acc, cnt_acc = st.fwd(
                    chunk_params[j], x_in, tgt_s, idx_last[mb],
                    nll_acc, cnt_acc)
                mb_nll[mb], mb_cnt[mb] = nll_mb, cnt_mb
            else:
                x_in = xbuf.pop((j, mb))
                xsave[(j, mb)] = x_in
                y = st.fwd(chunk_params[j], x_in)
                xbuf[(j + 1, mb)] = jax.device_put(
                    y, stages[j + 1].x_sharding)
        elif op.op == "B":
            if st.last:
                accs[j], g_x = st.bwd(chunk_params[j], xsave.pop((j, mb)),
                                      tgt_s, idx_last[mb], accs[j])
                gbuf[(j - 1, mb)] = jax.device_put(
                    g_x, stages[j - 1].x_sharding)
            elif st.first:
                accs[j] = st.bwd(chunk_params[j], ids_s, idx_first[mb],
                                 gbuf.pop((j, mb)), accs[j])
            else:
                accs[j], g_x = st.bwd(chunk_params[j], xsave.pop((j, mb)),
                                      gbuf.pop((j, mb)), accs[j])
                gbuf[(j - 1, mb)] = jax.device_put(
                    g_x, stages[j - 1].x_sharding)
        else:  # pragma: no cover — zb tables are accounting-only
            raise RuntimeError(
                f"op {op.op!r} has no executable stage program")
        if timings is not None or tracer is not None:
            jax.block_until_ready(accs[j] if op.op == "B" else
                                  (nll_acc if st.last else
                                   xbuf.get((j + 1, mb))))
            dt = time.perf_counter() - t0
            if timings is not None:
                timings.setdefault(op.group, []).append(dt)
            if tracer is not None:
                # One span per dispatched op on the owning device
                # group's lane, named with the same stage/tick/op/mb
                # coordinates the watchdog's last-touch string uses.
                tracer.complete(
                    f"stage{j}/tick{op.tick}/{op.op}/mb{mb}",
                    tid=TID_PP_BASE + op.group, dur_s=dt,
                    stage=j, tick=op.tick, op=op.op, mb=mb,
                    step=step)
    leftover = ([f"activation (vstage={j}, mb={m})" for j, m in sorted(xbuf)]
                + [f"cotangent (vstage={j}, mb={m})" for j, m in sorted(gbuf)]
                + [f"saved-input (vstage={j}, mb={m})"
                   for j, m in sorted(xsave)])
    if leftover:
        raise ScheduleBufferError(
            f"schedule walk left {len(leftover)} live boundary buffer(s) "
            f"— the table dispatched ops that produced tensors no later "
            f"op consumed (a truncated or dependency-broken table): "
            f"{'; '.join(leftover)}")
    return accs, nll_acc, cnt_acc, mb_nll, mb_cnt


def make_mpmd_train_step(cfg: Config, menv: MeshEnv,
                         inject_nan: bool = False):
    """Build the MPMD (state, batch) -> (state, metrics) step: a host
    function (NOT a jit) whose schedule walk dispatches the per-stage
    programs and whose tail runs the jitted global finish/update. Same
    contract as the SPMD `make_train_step` — train.py cannot tell them
    apart (that is the point of the executor knob)."""
    cfg.validate()
    if cfg.pipeline.executor != "mpmd":
        raise ValueError("make_mpmd_train_step needs pipeline.executor='mpmd'")
    n_micro = cfg.training.gradient_accumulation_steps
    pp, v = cfg.distributed.pp_size, cfg.pipeline.interleave
    table = build_schedule(cfg.pipeline.schedule, n_micro, pp, v)
    stages = _build_stages(cfg, menv)
    V = len(stages)

    ids_sharding = stages[0].batch_sharding
    tgt_sharding = stages[V - 1].batch_sharding
    idx_first = _index_arrays(n_micro, stages[0].scalar_sharding)
    idx_last = _index_arrays(n_micro, stages[V - 1].scalar_sharding)
    zero_scalars = jax.jit(
        lambda: (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        out_shardings=(stages[V - 1].scalar_sharding,
                       stages[V - 1].scalar_sharding))
    finish = _make_finish(cfg, menv, inject_nan)
    global_chunk_shardings = [
        jax.tree.map(lambda s: NamedSharding(menv.mesh, s),
                     _chunk_param_specs(cfg, j, V),
                     is_leaf=lambda x: isinstance(x, P))
        for j in range(V)]
    replicated = NamedSharding(menv.mesh, P())
    sample = int(os.environ.get("PICOTRON_PP_TICK_SAMPLE", "0") or 0)
    host_step = [0]

    def step(state: TrainState, batch):
        ids, tgt = batch
        chunk_params = [stages[j].slice_params(state.params)
                        for j in range(V)]
        accs = [stages[j].zeros() for j in range(V)]
        ids_s = jax.device_put(ids, ids_sharding)
        tgt_s = jax.device_put(tgt, tgt_sharding)
        host_step[0] += 1
        step_no = host_step[0]
        if chaos.controller().has_tick_events():
            # #TICK chaos keys on the TRAINING step number (identical on
            # every process / across resumes); resolve it exactly via a
            # host sync this path otherwise avoids. Without tick events
            # the process-local invocation index is plenty for the
            # watchdog's diagnostic beats.
            step_no = int(jax.device_get(state.step)) + 1
        timings = ({} if on_stage_times is not None and sample > 0
                   and host_step[0] % sample == 0 else None)
        accs, nll_acc, cnt_acc, _, _ = _run_schedule(
            stages, table, chunk_params, accs, zero_scalars(),
            ids_s, tgt_s, idx_first, idx_last, timings=timings,
            step=step_no)
        if timings is not None and on_stage_times is not None:
            on_stage_times(timings, host_step[0])
        grads = tuple(
            jax.device_put(accs[j], global_chunk_shardings[j])
            for j in range(V))
        nll_g = jax.device_put(nll_acc, replicated)
        cnt_g = jax.device_put(cnt_acc, replicated)
        return finish(state, grads, nll_g, cnt_g)

    return step


def mpmd_microbatch_losses(cfg: Config, menv: MeshEnv, params, batch):
    """Forward-only probe: per-microbatch (nll_sum, count) through the
    per-stage programs — what the parity tests pin against the SPMD twin's
    per-microbatch reference. Returns (nll[n_micro], count[n_micro]) as
    numpy arrays."""
    cfg.validate()
    n_micro = cfg.training.gradient_accumulation_steps
    pp, v = cfg.distributed.pp_size, cfg.pipeline.interleave
    table = [op for op in build_schedule(
        cfg.pipeline.schedule if cfg.pipeline.executor == "mpmd" else "1f1b",
        n_micro, pp, v) if op.op == "F"]
    stages = _build_stages(cfg, menv)
    V = len(stages)
    idx_first = _index_arrays(n_micro, stages[0].scalar_sharding)
    idx_last = _index_arrays(n_micro, stages[V - 1].scalar_sharding)
    ids, tgt = batch
    ids_s = jax.device_put(ids, stages[0].batch_sharding)
    tgt_s = jax.device_put(tgt, stages[V - 1].batch_sharding)
    chunk_params = [stages[j].slice_params(params) for j in range(V)]
    nll_acc, cnt_acc = jax.jit(
        lambda: (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        out_shardings=(stages[V - 1].scalar_sharding,
                       stages[V - 1].scalar_sharding))()
    xbuf: dict = {}
    mb_nll = [None] * n_micro
    mb_cnt = [None] * n_micro
    for op in table:
        j, mb = op.vstage, op.mb
        st = stages[j]
        if st.first:
            y = st.fwd(chunk_params[j], ids_s, idx_first[mb])
            xbuf[(j + 1, mb)] = jax.device_put(y, stages[j + 1].x_sharding)
        elif st.last:
            nll_mb, cnt_mb, nll_acc, cnt_acc = st.fwd(
                chunk_params[j], xbuf.pop((j, mb)), tgt_s, idx_last[mb],
                nll_acc, cnt_acc)
            mb_nll[mb], mb_cnt[mb] = nll_mb, cnt_mb
        else:
            y = st.fwd(chunk_params[j], xbuf.pop((j, mb)))
            xbuf[(j + 1, mb)] = jax.device_put(y, stages[j + 1].x_sharding)
    return (np.asarray([float(x) for x in mb_nll]),
            np.asarray([int(x) for x in mb_cnt]))


def _make_finish(cfg: Config, menv: MeshEnv, inject_nan: bool):
    """The jitted step tail on the FULL mesh: reassemble the global grad
    tree from the per-chunk accumulators, normalize by the token count, and
    run the same optax update + divergence-guard logic as the SPMD step
    (api.make_train_step's standard branch), donating the TrainState."""
    mesh = menv.mesh
    layer_shardings = param_shardings(cfg, mesh)["layers"]
    opt = make_optimizer(cfg.training)
    guards_on = cfg.resilience.guard_policy != "off"
    guard_skip = cfg.resilience.guard_policy == "skip"
    tied = cfg.model.tie_word_embeddings

    def _assemble(sh, *xs):
        # Rebuild the P('pp')-sharded layer stack by dynamic_update_slice
        # into a constrained zeros buffer, NOT jnp.concatenate: this XLA's
        # SPMD partitioner double-counts replicated inputs when a concat's
        # result is resharded along the concat axis (each dp replica's copy
        # lands as a contribution instead of a copy — values scale by
        # dp_size). DUS of a replicated update into a sharded operand
        # lowers correctly.
        rows = sum(x.shape[0] for x in xs)
        y = jax.lax.with_sharding_constraint(
            jnp.zeros((rows,) + xs[0].shape[1:], xs[0].dtype), sh)
        off = 0
        for x in xs:
            y = jax.lax.with_sharding_constraint(
                lax.dynamic_update_slice(y, x, (off,) + (0,) * (x.ndim - 1)),
                sh)
            off += x.shape[0]
        return y

    @partial(jax.jit, donate_argnums=(0,))
    def finish(state: TrainState, chunk_grads, nll_total, count):
        layers = jax.tree.map(_assemble, layer_shardings,
                              *[g["layers"] for g in chunk_grads])
        grads = {"layers": layers,
                 "final_norm": chunk_grads[-1]["final_norm"]}
        if tied:
            # the embedding earns grads on BOTH end stages (lookup on the
            # first, head matmul on the last) — disjoint contributions sum
            grads["embedding"] = (chunk_grads[0]["embedding"]
                                  + chunk_grads[-1]["embedding"])
        else:
            grads["embedding"] = chunk_grads[0]["embedding"]
            grads["lm_head"] = chunk_grads[-1]["lm_head"]
        count = jnp.maximum(count, 1)
        grads = jax.tree.map(lambda g: g / count, grads)
        loss = nll_total / count
        if inject_nan:
            nan = jnp.float32(jnp.nan)
            grads = jax.tree.map(lambda g: g + nan.astype(g.dtype), grads)
            loss = loss + nan
        metrics = {"loss": loss}
        if guards_on:
            gnorm = optax.global_norm(grads)
            ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)
            metrics["grad_norm"] = gnorm
            metrics["nonfinite"] = 1.0 - ok.astype(jnp.float32)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if guards_on and guard_skip:
            new_params = guard_nonfinite(ok, new_params, state.params)
            opt_state = guard_nonfinite(ok, opt_state, state.opt_state)
        return TrainState(new_params, opt_state, state.step + 1), metrics

    return finish


# ---------------------------------------------------------------------------
# Variant-prover surface (analysis/variants.py / tools/shardcheck.py)
# ---------------------------------------------------------------------------


def mpmd_entry_feeds(cfg: Config, menv: MeshEnv) -> dict:
    """{entry_name: [abstract argument tuple per scheduled call]} for every
    per-stage program of this config's schedule — what the variant prover
    audits to certify each stage program compiles exactly once. Every feed
    is a committed ShapeDtypeStruct tree (shardings included), enumerated
    per call the schedule actually makes, so a stage whose calls disagree
    in abstract signature (a second executable) is caught, not assumed."""
    cfg.validate()
    n_micro = cfg.training.gradient_accumulation_steps
    pp, v = cfg.distributed.pp_size, cfg.pipeline.interleave
    table = build_schedule(cfg.pipeline.schedule, n_micro, pp, v)
    stages = _build_stages(cfg, menv)
    V = len(stages)
    m = cfg.model
    mbs = cfg.training.micro_batch_size
    d = cfg.distributed
    batch_shape = (n_micro, mbs * d.dp_size * d.ep_size,
                   cfg.training.seq_length)

    def sds(shape, dtype, sharding):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    feeds: dict[str, list] = {}
    for j in range(V):
        st = stages[j]
        p_abs = st.abstract_params
        acc_abs = jax.tree.map(
            lambda a, s: sds(a.shape, jnp.float32, s),
            p_abs, st.param_shardings)
        x_abs = sds((mbs * d.dp_size * d.ep_size,
                     cfg.training.seq_length, m.hidden_size),
                    compute_dtype(m), st.x_sharding)
        ids_abs = sds(batch_shape, jnp.int32, st.batch_sharding)
        idx_abs = sds((), jnp.int32, st.scalar_sharding)
        s_f32 = sds((), jnp.float32, st.scalar_sharding)
        s_i32 = sds((), jnp.int32, st.scalar_sharding)
        fkey, bkey = f"mpmd_stage{j}_fwd", f"mpmd_stage{j}_bwd"
        feeds[fkey], feeds[bkey] = [], []
        for op in table:
            if op.vstage != j:
                continue
            if op.op == "F":
                if st.first:
                    feeds[fkey].append((p_abs, ids_abs, idx_abs))
                elif st.last:
                    feeds[fkey].append(
                        (p_abs, x_abs, ids_abs, idx_abs, s_f32, s_i32))
                else:
                    feeds[fkey].append((p_abs, x_abs))
            else:
                if st.first:
                    feeds[bkey].append(
                        (p_abs, ids_abs, idx_abs, x_abs, acc_abs))
                elif st.last:
                    feeds[bkey].append(
                        (p_abs, x_abs, ids_abs, idx_abs, acc_abs))
                else:
                    feeds[bkey].append((p_abs, x_abs, x_abs, acc_abs))
    return feeds


# ---------------------------------------------------------------------------
# Multi-slice placement (the boundary auditor's runtime counterpart)
# ---------------------------------------------------------------------------


def stage_slice_placement(cfg: Config) -> list:
    """Slice index each pp device group lives on — None for a group that
    spans slices. Derived from the row-major (dp, pp, ep, cp, tp) grid the
    Mesh contract fixes (analysis/boundary.py SliceTopology), so it needs
    no live devices. When pp alone carries the slice granule, every group
    is per-slice BY CONSTRUCTION (_stage_meshes re-meshes the full mesh's
    pp=g column): the boundary device_put ring buffers become the only
    DCN traffic (arxiv 2412.14374's placement), which `make_mpmd_train_step`
    asserts and `boundary_dcn_traffic` prices."""
    from picotron_tpu.analysis.boundary import SliceTopology

    topo = SliceTopology.from_config(cfg)
    d = cfg.distributed
    grid = np.arange(topo.world).reshape(topo.grid)
    out = []
    for g in range(d.pp_size):
        slices = {topo.slice_of(int(i)) for i in grid[:, g].ravel()}
        out.append(slices.pop() if len(slices) == 1 else None)
    return out


def check_stage_slice_placement(cfg: Config) -> list:
    """Raise unless every pp device group sits whole on one slice when pp
    alone carries the slice cut — the invariant that makes the schedule
    walk's device_put transfers the ONLY inter-slice traffic. A dp-cut
    layout legitimately spans every group across slices (the hierarchical
    dp reduction inside the stage programs handles that cut), so the check
    applies only to the pure-pp cut. Returns the placement list."""
    from picotron_tpu.analysis.boundary import SliceTopology

    placement = stage_slice_placement(cfg)
    topo = SliceTopology.from_config(cfg)
    if topo.n_slices > 1 and topo.cut_axes == ("pp",):
        bad = [g for g, s in enumerate(placement) if s is None]
        if bad:
            raise RuntimeError(
                f"mpmd stage placement violates the slice cut: device "
                f"group(s) {bad} span multiple slices although pp alone "
                f"carries the {topo.n_slices}-slice granule — stage "
                f"programs would run ICI collectives over DCN. The mesh "
                f"grid no longer matches mesh._split_axes_over_dcn's "
                f"house rule; this is a bug, not a layout choice.")
    return placement


def boundary_dcn_traffic(cfg: Config, cost_model=None) -> dict:
    """Per-step DCN traffic of the schedule walk's boundary ring buffers:
    which stage-to-stage device_put transfers cross the slice cut, their
    bytes, and (with a cost model) seconds at the dcn tier — the
    collective_permute pricing of CostModel.dcn_secs, since a boundary
    transfer is a point-to-point neighbor shift, not a group collective."""
    from picotron_tpu.analysis.boundary import SliceTopology

    d = cfg.distributed
    topo = SliceTopology.from_config(cfg)
    placement = stage_slice_placement(cfg)
    n_micro = cfg.training.gradient_accumulation_steps
    pp, v = d.pp_size, cfg.pipeline.interleave
    table = build_schedule(cfg.pipeline.schedule, n_micro, pp, v)
    V = pp * v
    m = cfg.model
    itemsize = jnp.dtype(compute_dtype(m)).itemsize
    per_transfer = (cfg.training.micro_batch_size * d.dp_size * d.ep_size
                    * cfg.training.seq_length * m.hidden_size * itemsize)

    def crosses(j_from: int, j_to: int) -> bool:
        a, b = placement[j_from % pp], placement[j_to % pp]
        return a is None or b is None or a != b

    transfers = crossing = 0
    for op in table:
        j = op.vstage
        if op.op == "F" and j < V - 1:
            transfers += 1
            crossing += crosses(j, j + 1)
        elif op.op == "B" and j > 0:
            transfers += 1
            crossing += crosses(j, j - 1)
    out = {
        "slices": topo.n_slices,
        "placement": placement,
        "transfers": transfers,
        "crossing": crossing,
        "bytes_per_transfer": per_transfer,
        "dcn_bytes": crossing * per_transfer,
    }
    if cost_model is not None and topo.n_slices > 1:
        out["dcn_secs"] = crossing * cost_model.dcn_secs(
            "collective_permute", per_transfer, topo.n_slices)
        out["dcn_generation"] = cost_model.gen.name
    return out
