"""Parallelism layers: TP collectives, sharding specs, PP schedules, CP ring,
and the composed 4D train step.

Where the reference implements each parallelism as a model-surgery wrapper
plus hand-written autograd collectives (SURVEY.md §2 rows 4-11), here:

- DP/TP are *declarative*: parameter PartitionSpecs + named-axis collectives
  inside one `shard_map`; gradient synchronization is just differentiating
  through `lax.pmean(loss, ('dp', 'cp'))` — JAX's varying-manual-axes
  machinery transposes the collectives, which is what the reference builds by
  hand as CopyTo/ReduceFrom/GatherFrom autograd Functions
  (ref: tp_communications.py) and bucketed gradient hooks
  (ref: data_parallel.py, bucket.py).
- PP/CP are *choreographed*: ppermute schedules over the 'pp'/'cp' axes
  (parallel/pp.py, ops/ring_attention.py).
"""

from picotron_tpu.parallel.sharding import param_specs, batch_spec  # noqa: F401
from picotron_tpu.parallel.api import (  # noqa: F401
    init_sharded_state,
    make_parallel_ctx,
    make_train_step,
)
from picotron_tpu.parallel.pp import pipeline_loss_sum_count  # noqa: F401
