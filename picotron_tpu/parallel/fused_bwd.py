"""Fused-accumulation grad engine: a manual VJP over the decoder-layer scan
that adds each layer's weight gradients into the fp32 accumulator IN-SCAN.

Why this exists (PERF.md r5): under gradient accumulation the AD path
materializes every microbatch's full stacked-layer grad tree (the backward
scan's ys output, ~6.5 GB fp32 at SmolLM-1.7B) and then runs whole-tree
`g_acc + grads` adds — measured at 26 ms per microbatch of pure serialized
HBM traffic between the backward and the next forward scan (1.7 s of a 36 s
step at grad-acc 64, all at roofline, none of it overlappable: TPU cores
run one op at a time, and the adds depend on the completed backward-scan
output buffer). This engine instead carries the fp32 accumulator through a
manual backward layer scan and updates one layer's slices per iteration
(`dynamic-update-slice(acc, acc[k] + dW_k)`), so the microbatch grad tree
never exists — the temp write AND the separate add pass disappear.

The backward mirrors exactly the `dots_attn` remat policy's save set
(models/llama.py remat_policy_for): the forward scan saves per layer the
layer input x plus the attention impl's residuals (q/k/v flat "qkv_out",
out flat "attn_out", and the saved softmax statistics "attn_lse"); the
backward recomputes the norms, the o-projection input, and the whole
MLP/MoE block, and reaches the attention backward through a
`*_bwd_from_saved` entry — never re-running the forward kernel. Segment
VJPs (`jax.vjp` over the same llama.py building blocks — qkv_proj,
_mlp_block/_moe_block, the ctx.f/g hooks) derive every other transpose, so
TP/SP/EP collectives and activation functions cannot diverge from the AD
engine; parity is pinned by tests/test_fused_bwd.py.

Per-axis structure (the north-star layouts; VERDICT r5):

- **TP / sequence parallelism**: the ctx.f/g hooks live inside the segment
  VJPs, so Megatron-SP's all-gather / reduce-scatter pair appears in both
  directions of the fused layer scan for free (forward as written;
  backward as JAX's transposes: tiled all_gather <-> psum_scatter). The
  residual stream and its saved layer inputs stay seq-sharded [B, S/tp, H];
  the saved q/k/v/out are the full-sequence post-gather tensors, exactly
  as under the AD engine's dots_attn policy.
- **Context parallelism**: both cp schedules save their per-block softmax
  statistics and re-enter the backward through a from-saved twin — the
  ring via `ring_attention_bwd_from_saved` (a second forward ppermute ring
  carrying dK/dV accumulators with their blocks; globally-normalized
  per-block grads from the merged LSE), Ulysses via
  `ulysses_attention_bwd_from_saved` (the same all_to_all pair in both
  directions around the flash backward kernel). RoPE for the ring is
  applied outside the ring exactly as in the forward wiring
  (parallel/api.py), with the rotation's transpose recovered by jax.vjp.
- **Multi-slice / DCN**: the in-scan accumulator is a purely per-device
  fp32 tree — no collective touches it until the engine seam
  (api._data_axes_psum) reduces it ONCE over the data axes after the last
  microbatch. That single exit point is exactly where multi-slice layouts
  swap the flat dp all-reduce for the hierarchical DCN schedule
  (parallel/hier_reduce.py: intra-slice reduce-scatter, shard-per-slice
  all-reduce over DCN, intra-slice all-gather), so the fused engine emits
  the same slice-boundary schedule as the AD engine by construction —
  pinned by the `tiny-dp-cross-fused` shardcheck preset's boundary audit.
- **MoE (Mixtral expert block)**: the expert MLP is recomputed in backward
  by a segment VJP over `_moe_block` — routing (router logits, top-k,
  slot cumsum) recomputes deterministically from the saved layer input,
  so the forward-scan save set stays exactly dots_attn's (no [E, C, H]
  dispatch buffers saved). The router aux loss re-folds inside the
  segment (`aux * count`, the loss_sum_count convention) so balance/z
  gradients flow with the same cotangent the AD engine sees; the capacity
  drop statistic rides the forward scan only (observability, no grad).

Eligibility (see `fused_bwd_supported`): every single-pipeline-stage
layout — dp/tp/SP/cp (ring and Ulysses)/ep/MoE — under remat with the
dots_attn policy. Only pp > 1 and other remat policies keep the AD engine
(the 1F1B engine is itself a manual-VJP schedule; see parallel/pp.py).
The reference gets in-place accumulation for free on every layout from
per-rank autograd hooks (ref: bucket.py:25-31 — an imperative luxury an
SPMD program has to earn back with scan structure); with the three axes
above, the SPMD port is no longer single-chip-only.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.config import Config
from picotron_tpu.models.llama import (
    ParallelCtx, _mlp_block, _moe_block, compute_dtype, head_weight,
    model_rope_tables, qkv_proj,
)
from picotron_tpu.ops.flash_attention import (
    flash_attention, flash_attention_bwd_from_saved,
)
from picotron_tpu.ops.rmsnorm import rms_norm


def fused_bwd_supported(cfg: Config) -> bool:
    """True when the fused grad engine covers this config: any
    single-pipeline-stage layout (dp/tp/SP/cp ring|ulysses|mesh/ep/MoE)
    under remat with the dots_attn policy — the save set this engine's
    manual backward is derived from. pp > 1 keeps the AD/1F1B engines (the
    pipeline scan subsumes the microbatch loop), and other remat policies
    keep the AD engine (their save sets differ from the manual backward's
    recompute plan)."""
    d, t = cfg.distributed, cfg.training
    return (d.pp_size == 1
            and t.remat and t.remat_policy == "dots_attn")


def _vary_like(x, ref):
    from picotron_tpu import compat
    from picotron_tpu.parallel.pp import _vary_over

    return _vary_over(x, set(compat.vma(ref)))


def _o_exit(ctx: ParallelCtx, outf, w_o, dt):
    """The o-projection + TP block exit, dispatched through the strategy
    hook exactly as models/llama.py's _attention_block does — the single
    definition both the forward scan and the backward segment VJPs close
    over, so the fused engine emits whatever collectives the strategy
    chose (megatron psum, SP/deferred reduce-scatter, 2d subgroup psum,
    row-first feature gather)."""
    if ctx.o_mm is not None:
        return ctx.o_mm(outf, w_o)
    return ctx.g(outf @ w_o.astype(dt))


def _attn_paths(cfg: Config, ctx: ParallelCtx, cos, sin):
    """(attn_fwd, attn_bwd) closures for this config's attention schedule,
    mirroring parallel/api.py's dispatch exactly:

      attn_fwd(q, k, v) -> (out, lse)          q/k UNROTATED [B, S, H, D]
      attn_bwd(q, k, v, out, lse, dout) -> (dq, dk, dv)   same domains

    The lse is whatever statistic the schedule's `*_bwd_from_saved` twin
    consumes: the kernel LSE (cp=1), the globally merged ring LSE, or the
    inner-domain Ulysses LSE."""
    from picotron_tpu.config import resolved_cp_flavor, resolved_cp_mesh

    d, m = cfg.distributed, cfg.model
    pos = ctx.positions
    use_flash = m.attn_impl in ("auto", "flash", "ring", "ulysses", "mesh")
    cp_flavor = resolved_cp_flavor(cfg)

    if d.cp_size > 1 and cp_flavor == "ulysses":
        from picotron_tpu.ops.ulysses import (
            ulysses_attention, ulysses_attention_bwd_from_saved,
            ulysses_static_layout,
        )

        full_pos, seq_sort = ulysses_static_layout(cfg)
        uly_kw = dict(axis="cp", q_positions=pos, rope=(cos, sin),
                      seq_sort=seq_sort, full_positions=full_pos,
                      positions_static=True)

        def attn_fwd(q, k, v):
            return ulysses_attention(q, k, v, attn_fn=flash_attention,
                                     return_lse=True, **uly_kw)

        def attn_bwd(q, k, v, out, lse, dout):
            return ulysses_attention_bwd_from_saved(q, k, v, out, lse,
                                                    dout, **uly_kw)

        return attn_fwd, attn_bwd

    if d.cp_size > 1 and cp_flavor == "mesh":
        from picotron_tpu.ops.attention import (
            sdpa_attention, sdpa_attention_bwd_from_saved,
        )
        from picotron_tpu.ops.mesh_attention import (
            mesh_attention, mesh_attention_bwd_from_saved,
        )
        from picotron_tpu.ops.rope import apply_rope

        cp_mesh = resolved_cp_mesh(cfg)
        blockwise = partial(
            (flash_attention if use_flash else sdpa_attention),
            return_lse=True)
        block_bwd = (flash_attention_bwd_from_saved if use_flash
                     else sdpa_attention_bwd_from_saved)

        def rot_pair(q, k):
            # pre-rotation, same single-sourcing as the ring branch below
            return jax.vjp(
                lambda q_, k_: (apply_rope(q_, cos, sin, pos),
                                apply_rope(k_, cos, sin, pos)), q, k)

        def attn_fwd(q, k, v):
            (qr, kr), _ = rot_pair(q, k)
            return mesh_attention(qr, kr, v, axis="cp", cp_mesh=cp_mesh,
                                  q_positions=pos, attn_block=blockwise,
                                  return_lse=True)

        def attn_bwd(q, k, v, out, lse, dout):
            (qr, kr), rot_vjp = rot_pair(q, k)
            dqr, dkr, dv = mesh_attention_bwd_from_saved(
                qr, kr, v, out, lse, dout, axis="cp", cp_mesh=cp_mesh,
                q_positions=pos, block_bwd=block_bwd)
            dq, dk = rot_vjp((dqr, dkr))
            return dq, dk, dv

        return attn_fwd, attn_bwd

    if d.cp_size > 1:
        from picotron_tpu.ops.attention import (
            sdpa_attention, sdpa_attention_bwd_from_saved,
        )
        from picotron_tpu.ops.ring_attention import (
            ring_attention, ring_attention_bwd_from_saved,
        )
        from picotron_tpu.ops.rope import apply_rope

        blockwise = partial(
            (flash_attention if use_flash else sdpa_attention),
            return_lse=True)
        block_bwd = (flash_attention_bwd_from_saved if use_flash
                     else sdpa_attention_bwd_from_saved)

        def rot_pair(q, k):
            # K is rotated BEFORE entering the ring so each block travels
            # pre-rotated with its positions (same single-sourcing as the
            # forward wiring, parallel/api.py); jax.vjp over the rotation
            # is its exact transpose for the backward.
            return jax.vjp(
                lambda q_, k_: (apply_rope(q_, cos, sin, pos),
                                apply_rope(k_, cos, sin, pos)), q, k)

        def attn_fwd(q, k, v):
            (qr, kr), _ = rot_pair(q, k)
            return ring_attention(qr, kr, v, axis="cp", q_positions=pos,
                                  attn_block=blockwise, return_lse=True)

        def attn_bwd(q, k, v, out, lse, dout):
            (qr, kr), rot_vjp = rot_pair(q, k)
            dqr, dkr, dv = ring_attention_bwd_from_saved(
                qr, kr, v, out, lse, dout, axis="cp", q_positions=pos,
                block_bwd=block_bwd)
            dq, dk = rot_vjp((dqr, dkr))
            return dq, dk, dv

        return attn_fwd, attn_bwd

    if use_flash:
        def attn_fwd(q, k, v):
            return flash_attention(q, k, v, causal=True, rope=(cos, sin),
                                   q_positions=pos, kv_positions=pos,
                                   return_lse=True)

        def attn_bwd(q, k, v, out, lse, dout):
            return flash_attention_bwd_from_saved(
                q, k, v, out, lse, dout, causal=True, q_positions=pos,
                kv_positions=pos, rope=(cos, sin))

        return attn_fwd, attn_bwd

    from picotron_tpu.ops.attention import (
        sdpa_attention, sdpa_attention_bwd_from_saved,
    )
    from picotron_tpu.ops.rope import apply_rope

    def rot_pair(q, k):
        return jax.vjp(
            lambda q_, k_: (apply_rope(q_, cos, sin, pos),
                            apply_rope(k_, cos, sin, pos)), q, k)

    def attn_fwd(q, k, v):
        (qr, kr), _ = rot_pair(q, k)
        return sdpa_attention(qr, kr, v, causal=True, q_positions=pos,
                              kv_positions=pos, return_lse=True)

    def attn_bwd(q, k, v, out, lse, dout):
        (qr, kr), rot_vjp = rot_pair(q, k)
        dqr, dkr, dv = sdpa_attention_bwd_from_saved(
            qr, kr, v, out, lse, dout, causal=True, q_positions=pos,
            kv_positions=pos)
        dq, dk = rot_vjp((dqr, dkr))
        return dq, dk, dv

    return attn_fwd, attn_bwd


def fused_micro_grads(params, ids, tgt, g_acc, cfg: Config,
                      ctx: ParallelCtx):
    """One microbatch: returns (g_acc', nll_sum, valid_count, dropw) with
    grads accumulated into g_acc (layer leaves in-scan, non-layer leaves by
    one small add). Per-device semantics — runs inside the train step's
    shard_map body like the AD engine it replaces. Numerics match the AD
    engine: per-layer dW emerges in the bf16 param dtype from the same
    segment math before the fp32 accumulate. `dropw` is the token-weighted
    MoE capacity-drop sum (aux[1] * count, the loss_sum_count convention;
    0 for dense models)."""
    m = cfg.model
    eps = m.rms_norm_eps
    hd = m.head_dim
    moe = bool(m.num_experts)
    cos, sin = model_rope_tables(m)
    attn_fwd, attn_bwd = _attn_paths(cfg, ctx, cos, sin)
    # flatten by the tensor's OWN leading dims: under sequence parallelism
    # the residual stream is seq-sharded [B, S/tp, H] while the post-gather
    # q/k/v/out are full-sequence — reshaping those by x's dims would
    # silently fold tp x seq into the feature axis
    flat = lambda t: t.reshape(t.shape[0], t.shape[1], -1)  # noqa: E731

    def attn_bwd_flat(qf, kf, vf, outf, lse, doutf):
        r = lambda t: t.reshape(t.shape[0], t.shape[1], -1, hd)  # noqa: E731
        dq, dk, dv = attn_bwd(r(qf), r(kf), r(vf), r(outf), lse, r(doutf))
        return flat(dq), flat(dk), flat(dv)

    bias_keys = [k for k in ("b_q", "b_k", "b_v")
                 if k in params["layers"]]
    moe_keys = (["router", "w_gate", "w_up", "w_down"] if moe
                else ["gate", "up", "down"])

    # ---------------- forward ----------------
    x0, vjp_embed = jax.vjp(
        lambda e: (ctx.embed_lookup(e, ids) if ctx.embed_lookup is not None
                   else e[ids]).astype(compute_dtype(m)),
        params["embedding"])

    def fwd_body(x, lp):
        h1 = rms_norm(ctx.pre(x), lp["input_norm"], eps)
        hf = ctx.f(h1)
        q, k, v = (ctx.qkv_mm or qkv_proj)(hf, lp, hd)
        out, lse = attn_fwd(q, k, v)
        outf = flat(out)
        a = x + _o_exit(ctx, outf, lp["o"], x.dtype)
        if moe:
            mo, aux = _moe_block(a, lp, m, ctx)
            y = a + mo
        else:
            y = a + _mlp_block(a, lp, m, ctx)
            aux = jnp.zeros(2, jnp.float32)
        return y, ((x, flat(q), flat(k), flat(v), outf, lse), aux)

    xL, (saved, aux_layers) = lax.scan(fwd_body, x0, params["layers"])
    aux_sum = jnp.sum(aux_layers, axis=0)  # [2]: (router loss, drop frac)

    # ---------------- head + CE ----------------
    nonlayer = {k: v for k, v in params.items() if k != "layers"}

    def head_fn(x, nl):
        xh = rms_norm(x, nl["final_norm"], eps)
        if ctx.head_ce is not None:
            total, count = ctx.head_ce(xh, head_weight(nl), tgt)
        else:
            from picotron_tpu.ops.losses import cross_entropy_sum_count

            logits = xh @ head_weight(nl).astype(xh.dtype)
            total, count = cross_entropy_sum_count(logits, tgt)
        return total, count

    (total, vjp_head, count) = jax.vjp(head_fn, xL, nonlayer, has_aux=True)
    one = _vary_like(jnp.ones((), jnp.float32), total)
    dxL, g_nonlayer = vjp_head(one)
    count_f = count.astype(jnp.float32)
    if moe:
        # the loss_sum_count fold: reported total = nll + (sum_l aux_l)*count
        # — the router-loss gradient flows per layer through the backward
        # scan's segment VJPs with cotangent 1.0 on the folded scalar.
        total = total + aux_sum[0] * count_f
        dropw = aux_sum[1] * count_f
    else:
        dropw = total * 0.0

    # ---------------- backward layer scan ----------------
    def bwd_body(carry, xs):
        dy, gL = carry
        (x, qf, kf, vf, outf, lse), lp, idx = xs

        # MLP/MoE half: recompute a = x + o-proj (the dots_attn policy's
        # recompute set), derive the block's grads by segment VJP. For MoE
        # the routing recomputes deterministically and the aux-loss fold
        # (aux * count) rides the segment so balance/z grads flow.
        a = x + _o_exit(ctx, outf, lp["o"], x.dtype)

        if moe:
            def seg_mlp(a_, *ws):
                lp2 = dict(lp)
                lp2.update(zip(["post_norm"] + moe_keys, ws))
                mo, aux2 = _moe_block(a_, lp2, m, ctx)
                return a_ + mo, aux2[0] * count_f

            (_, fold_re), vjp_b = jax.vjp(
                seg_mlp, a, lp["post_norm"], *[lp[k] for k in moe_keys])
            d_fold = _vary_like(jnp.ones((), jnp.float32), fold_re)
            da, d_post, *d_ws = vjp_b((dy, d_fold))
        else:
            def seg_mlp(a_, *ws):
                lp2 = dict(lp)
                lp2.update(zip(["post_norm"] + moe_keys, ws))
                return a_ + _mlp_block(a_, lp2, m, ctx)

            _, vjp_b = jax.vjp(
                seg_mlp, a, lp["post_norm"], *[lp[k] for k in moe_keys])
            da, d_post, *d_ws = vjp_b(dy)

        def seg_o(x_, outf_, wo):
            return x_ + _o_exit(ctx, outf_, wo, x_.dtype)

        _, vjp_o = jax.vjp(seg_o, x, outf, lp["o"])
        dx1, doutf, d_o = vjp_o(da)

        dqf, dkf, dvf = attn_bwd_flat(qf, kf, vf, outf, lse, doutf)

        def seg_qkv(x_, w_in, wq, wk, wv, *bs):
            lpq = dict(lp)
            lpq.update(input_norm=w_in, q=wq, k=wk, v=wv,
                       **dict(zip(bias_keys, bs)))
            h1_ = rms_norm(ctx.pre(x_), w_in, eps)
            hf_ = ctx.f(h1_)
            q_, k_, v_ = (ctx.qkv_mm or qkv_proj)(hf_, lpq, hd)
            return flat(q_), flat(k_), flat(v_)

        _, vjp_q = jax.vjp(seg_qkv, x, lp["input_norm"], lp["q"], lp["k"],
                           lp["v"], *[lp[k] for k in bias_keys])
        dx2, d_in, d_q, d_k, d_v, *d_bs = vjp_q((dqf, dkf, dvf))

        gl = dict(input_norm=d_in, q=d_q, k=d_k, v=d_v, o=d_o,
                  post_norm=d_post,
                  **dict(zip(moe_keys, d_ws)),
                  **dict(zip(bias_keys, d_bs)))
        assert set(gl) == set(lp), (sorted(gl), sorted(lp))

        def acc(accl, g):
            cur = lax.dynamic_index_in_dim(accl, idx, 0, keepdims=False)
            return lax.dynamic_update_index_in_dim(
                accl, cur + g.astype(accl.dtype), idx, 0)

        gL = jax.tree.map(acc, gL, gl)
        return (dx1 + dx2, gL), None

    n_layers = jax.tree.leaves(params["layers"])[0].shape[0]
    (dx0, g_layers), _ = lax.scan(
        bwd_body, (dxL, g_acc["layers"]),
        (saved, params["layers"], jnp.arange(n_layers)), reverse=True)

    # ---------------- embedding + non-layer accumulate ----------------
    (g_embed,) = vjp_embed(dx0)
    new_acc = {"layers": g_layers}
    for k in g_acc:
        if k == "layers":
            continue
        g = g_nonlayer[k]
        if k == "embedding":
            g = g + g_embed if g is not None else g_embed
        new_acc[k] = g_acc[k] + g.astype(g_acc[k].dtype)
    return new_acc, total, count, dropw
