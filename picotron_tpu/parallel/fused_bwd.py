"""Fused-accumulation grad engine: a manual VJP over the decoder-layer scan
that adds each layer's weight gradients into the fp32 accumulator IN-SCAN.

Why this exists (PERF.md r5): under gradient accumulation the AD path
materializes every microbatch's full stacked-layer grad tree (the backward
scan's ys output, ~6.5 GB fp32 at SmolLM-1.7B) and then runs whole-tree
`g_acc + grads` adds — measured at 26 ms per microbatch of pure serialized
HBM traffic between the backward and the next forward scan (1.7 s of a 36 s
step at grad-acc 64, all at roofline, none of it overlappable: TPU cores
run one op at a time, and the adds depend on the completed backward-scan
output buffer). This engine instead carries the fp32 accumulator through a
manual backward layer scan and updates one layer's slices per iteration
(`dynamic-update-slice(acc, acc[k] + dW_k)`), so the microbatch grad tree
never exists — the temp write AND the separate add pass disappear.

The backward mirrors exactly the `dots_attn` remat policy's save set
(models/llama.py remat_policy_for): the forward scan saves per layer the
layer input x plus the flash kernel's residuals (q/k/v flat "qkv_out",
out flat "attn_out", "attn_lse"); the backward recomputes the norms, the
o-projection input, and the whole MLP, and reaches the Pallas backward
kernels through `flash_attention_bwd_from_saved` without re-running the
forward kernel. Segment VJPs (`jax.vjp` over the same llama.py building
blocks — qkv_proj, _mlp_block, the ctx.f/g hooks) derive every other
transpose, so TP collectives and activation functions cannot diverge from
the AD engine; parity is pinned by tests/test_fused_bwd.py.

Eligibility (see `fused_bwd_supported`): the single-stage dense path —
pp = cp = 1, no MoE, no sequence parallelism, remat with the dots_attn
policy, flash/sdpa attention. Everything else keeps the AD engine; the
reference has no analogue of either (its per-rank autograd accumulates
into .grad buffers in place, ref: bucket.py:25-31 — an imperative luxury
an SPMD program has to earn back with scan structure).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.config import Config
from picotron_tpu.models.llama import (
    ParallelCtx, _mlp_block, compute_dtype, head_weight, model_rope_tables,
    qkv_proj,
)
from picotron_tpu.ops.flash_attention import (
    flash_attention, flash_attention_bwd_from_saved,
)
from picotron_tpu.ops.rmsnorm import rms_norm


def fused_bwd_supported(cfg: Config) -> bool:
    """True when the fused grad engine covers this config (the dense
    single-stage path whose save set is exactly dots_attn's)."""
    d, m, t = cfg.distributed, cfg.model, cfg.training
    return (d.pp_size == 1 and d.cp_size == 1
            and not d.sequence_parallel
            and not m.num_experts
            and t.remat and t.remat_policy == "dots_attn"
            and m.attn_impl in ("auto", "flash", "reference"))


def _vary_like(x, ref):
    from picotron_tpu import compat
    from picotron_tpu.parallel.pp import _vary_over

    return _vary_over(x, set(compat.vma(ref)))


def fused_micro_grads(params, ids, tgt, g_acc, cfg: Config,
                      ctx: ParallelCtx):
    """One microbatch: returns (g_acc', nll_sum, valid_count) with grads
    accumulated into g_acc (layer leaves in-scan, non-layer leaves by one
    small add). Per-device semantics — runs inside the train step's
    shard_map body like the AD engine it replaces. Numerics match the AD
    engine: per-layer dW emerges in the bf16 param dtype from the same
    segment math before the fp32 accumulate."""
    m = cfg.model
    eps = m.rms_norm_eps
    hd = m.head_dim
    cos, sin = model_rope_tables(m)
    pos = ctx.positions
    use_flash = m.attn_impl in ("auto", "flash")

    def attn_fwd(q, k, v):
        if use_flash:
            return flash_attention(q, k, v, causal=True, rope=(cos, sin),
                                   q_positions=pos, kv_positions=pos,
                                   return_lse=True)
        from picotron_tpu.ops.attention import sdpa_attention
        from picotron_tpu.ops.rope import apply_rope

        qr = apply_rope(q, cos, sin, pos)
        kr = apply_rope(k, cos, sin, pos)
        return sdpa_attention(qr, kr, v, causal=True, q_positions=pos,
                              kv_positions=pos, return_lse=True)

    def attn_bwd(qf, kf, vf, outf, lse, doutf):
        b, s, _ = qf.shape
        r = lambda t: t.reshape(b, s, -1, hd)  # noqa: E731
        if use_flash:
            dq, dk, dv = flash_attention_bwd_from_saved(
                r(qf), r(kf), r(vf), r(outf), lse, r(doutf), causal=True,
                q_positions=pos, kv_positions=pos, rope=(cos, sin))
        else:
            def f(q, k, v):
                out, _ = attn_fwd(q, k, v)
                return out

            _, vjp_fn = jax.vjp(f, r(qf), r(kf), r(vf))
            dq, dk, dv = vjp_fn(r(doutf))
        flat = lambda t: t.reshape(b, s, -1)  # noqa: E731
        return flat(dq), flat(dk), flat(dv)

    bias_keys = [k for k in ("b_q", "b_k", "b_v")
                 if k in params["layers"]]

    # ---------------- forward ----------------
    x0, vjp_embed = jax.vjp(
        lambda e: (ctx.embed_lookup(e, ids) if ctx.embed_lookup is not None
                   else e[ids]).astype(compute_dtype(m)),
        params["embedding"])

    def fwd_body(x, lp):
        b, s, _ = x.shape
        h1 = rms_norm(x, lp["input_norm"], eps)
        hf = ctx.f(h1)
        q, k, v = qkv_proj(hf, lp, hd)
        out, lse = attn_fwd(q, k, v)
        outf = out.reshape(b, s, -1)
        a = x + ctx.g(outf @ lp["o"].astype(x.dtype))
        y = a + _mlp_block(a, lp, m, ctx)
        flat = lambda t: t.reshape(b, s, -1)  # noqa: E731
        return y, (x, flat(q), flat(k), flat(v), outf, lse)

    xL, saved = lax.scan(fwd_body, x0, params["layers"])

    # ---------------- head + CE ----------------
    nonlayer = {k: v for k, v in params.items() if k != "layers"}

    def head_fn(x, nl):
        xh = rms_norm(x, nl["final_norm"], eps)
        if ctx.head_ce is not None:
            total, count = ctx.head_ce(xh, head_weight(nl), tgt)
        else:
            from picotron_tpu.ops.losses import cross_entropy_sum_count

            logits = xh @ head_weight(nl).astype(xh.dtype)
            total, count = cross_entropy_sum_count(logits, tgt)
        return total, count

    (total, vjp_head, count) = jax.vjp(head_fn, xL, nonlayer, has_aux=True)
    one = _vary_like(jnp.ones((), jnp.float32), total)
    dxL, g_nonlayer = vjp_head(one)

    # ---------------- backward layer scan ----------------
    def bwd_body(carry, xs):
        dy, gL = carry
        (x, qf, kf, vf, outf, lse), lp, idx = xs
        b, s, _ = x.shape

        # MLP half: recompute a = x + o-proj (the dots_attn policy's
        # recompute set), derive the MLP/post-norm grads by segment VJP
        a = x + ctx.g(outf @ lp["o"].astype(x.dtype))

        def seg_mlp(a_, w_post, wg, wu, wd):
            lp2 = dict(lp)
            lp2.update(post_norm=w_post, gate=wg, up=wu, down=wd)
            return a_ + _mlp_block(a_, lp2, m, ctx)

        _, vjp_b = jax.vjp(seg_mlp, a, lp["post_norm"], lp["gate"],
                           lp["up"], lp["down"])
        da, d_post, d_gate, d_up, d_down = vjp_b(dy)

        def seg_o(x_, outf_, wo):
            return x_ + ctx.g(outf_ @ wo.astype(x_.dtype))

        _, vjp_o = jax.vjp(seg_o, x, outf, lp["o"])
        dx1, doutf, d_o = vjp_o(da)

        dqf, dkf, dvf = attn_bwd(qf, kf, vf, outf, lse, doutf)

        def seg_qkv(x_, w_in, wq, wk, wv, *bs):
            lpq = dict(lp)
            lpq.update(input_norm=w_in, q=wq, k=wk, v=wv,
                       **dict(zip(bias_keys, bs)))
            h1_ = rms_norm(x_, w_in, eps)
            hf_ = ctx.f(h1_)
            q_, k_, v_ = qkv_proj(hf_, lpq, hd)
            flat = lambda t: t.reshape(b, s, -1)  # noqa: E731
            return flat(q_), flat(k_), flat(v_)

        _, vjp_q = jax.vjp(seg_qkv, x, lp["input_norm"], lp["q"], lp["k"],
                           lp["v"], *[lp[k] for k in bias_keys])
        dx2, d_in, d_q, d_k, d_v, *d_bs = vjp_q((dqf, dkf, dvf))

        gl = dict(input_norm=d_in, q=d_q, k=d_k, v=d_v, o=d_o,
                  post_norm=d_post, gate=d_gate, up=d_up, down=d_down,
                  **dict(zip(bias_keys, d_bs)))
        assert set(gl) == set(lp), (sorted(gl), sorted(lp))

        def acc(accl, g):
            cur = lax.dynamic_index_in_dim(accl, idx, 0, keepdims=False)
            return lax.dynamic_update_index_in_dim(
                accl, cur + g.astype(accl.dtype), idx, 0)

        gL = jax.tree.map(acc, gL, gl)
        return (dx1 + dx2, gL), None

    n_layers = jax.tree.leaves(params["layers"])[0].shape[0]
    (dx0, g_layers), _ = lax.scan(
        bwd_body, (dxL, g_acc["layers"]),
        (saved, params["layers"], jnp.arange(n_layers)), reverse=True)

    # ---------------- embedding + non-layer accumulate ----------------
    (g_embed,) = vjp_embed(dx0)
    new_acc = {"layers": g_layers}
    for k in g_acc:
        if k == "layers":
            continue
        g = g_nonlayer[k]
        if k == "embedding":
            g = g + g_embed if g is not None else g_embed
        new_acc[k] = g_acc[k] + g.astype(g_acc[k].dtype)
    return new_acc, total, count
