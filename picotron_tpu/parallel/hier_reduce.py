"""Runtime hierarchical dp gradient reduction across the slice cut.

Multi-slice jobs join TPU slices over DCN — a network orders of magnitude
slower than ICI. PR 16's static auditor (analysis/boundary.py) classifies
the traced schedule against the cut and its presence rules DEMAND the
hierarchical decomposition of every crossing reduction; this module is the
runtime half that actually emits it. A flat `psum(g, data_axes)` whose dp
axis carries a slice granule becomes:

    reduce-scatter over the intra-slice data axes (ep/cp, then the
      per-slice dp factor)                    — wide legs, pure ICI
    all-reduce over the dp slice granule      — one shard per slice, DCN
    all-gather back in reverse order          — wide legs, pure ICI

so the DCN link carries 1/m of the gradient bytes (m = the per-slice
width of the fused data axes) instead of the full tree — the standard
hierarchical algorithm the cost model prices (`CostModel.dcn_secs`) and
the MPMD-pipeline paper (arxiv 2412.14374) assumes between slices.

XLA *can* discover this decomposition itself on real hybrid meshes, but
nothing guarantees it; emitting it explicitly makes the schedule the
auditor's `hier_intra_scatter`/`hier_dcn_cohort` rules check a property
of the program, not of a compiler mood. Numerics: identical sums in a
different association order — bit-exact on integer-valued grads, ~1e-7
relative on float ones (the documented tolerance the parity twin in
tests/test_boundary.py pins).

Group math mirrors mesh._split_axes_over_dcn: the slice granule g_dp is
the OUTER factor of dp, so dp index = outer * inner + i with
inner = dp_size // g_dp. Intra-slice dp cohorts are the contiguous
runs [o*inner, (o+1)*inner); the DCN leg pairs equal inner offsets
across granules (one member per slice — the cohort-1 groups the
boundary auditor classifies as the declared DCN traffic).

Both grad engines exit through this module: the AD and fused engines via
api._data_axes_psum, the MPMD stage programs via mpmd._sub_data_psum.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from picotron_tpu import compat
from picotron_tpu.config import Config, parse_dcn_axes


def dp_granule(cfg: Config) -> tuple[int, int]:
    """(g_dp, inner): the slice granule dp carries under the house rule
    and the per-slice dp width (dp_size == g_dp * inner)."""
    d = cfg.distributed
    if d.slices <= 1:
        return 1, d.dp_size
    from picotron_tpu.mesh import _split_axes_over_dcn

    grid = (d.dp_size, d.pp_size, d.ep_size, d.cp_size, d.tp_size)
    dcn_shape, per_slice = _split_axes_over_dcn(grid, d.slices)
    return dcn_shape[0], per_slice[0]


def use_hier_dp(cfg: Config) -> bool:
    """Resolve distributed.hier_dp_reduce: hierarchical iff the knob
    allows it AND dp both is declared DCN-tolerant and physically
    carries a slice granule ('auto' and 'on' agree here — 'on' merely
    refuses at config validation when the layout cannot qualify)."""
    d = cfg.distributed
    if d.hier_dp_reduce == "off" or d.slices <= 1:
        return False
    if "dp" not in parse_dcn_axes(d.dcn_axes):
        return False
    g_dp, _ = dp_granule(cfg)
    return g_dp > 1


def _dp_groups(g_dp: int, inner: int) -> tuple[list, list]:
    """(intra-slice, cross-slice) axis_index_groups over the dp axis."""
    intra = [[o * inner + i for i in range(inner)] for o in range(g_dp)]
    cross = [[o * inner + i for o in range(g_dp)] for i in range(inner)]
    return intra, cross


def _varying(x, axis: str):
    """Re-mark `x` varying over `axis` after a grouped collective (which
    the vma type system treats as axis-invariant even though groups
    narrower than the axis leave values group-dependent) — the same
    re-marking discipline as parallel/tp_strategies.py."""
    if axis in compat.vma(x):
        return x
    return compat.pcast(x, (axis,), to="varying")


def hier_axes_psum(x, axes: tuple, cfg: Config):
    """`lax.psum(x, axes)` (with "dp" in `axes`) emitted as the
    hierarchical schedule described in the module docstring. Exact
    same sum, association order aside."""
    d = cfg.distributed
    g_dp, inner = dp_granule(cfg)
    sizes = {"dp": d.dp_size, "ep": d.ep_size, "cp": d.cp_size}
    intra_axes = [a for a in axes if a != "dp" and sizes[a] > 1]
    m = inner * math.prod(sizes[a] for a in intra_axes)
    if m <= 1:
        # no intra-slice width to scatter over: the flat psum IS the
        # shard-per-slice DCN leg (and the auditor's m_expected == 1
        # skips the presence rule accordingly)
        return lax.psum(x, axes)
    intra_dp, cross_dp = _dp_groups(g_dp, inner)
    shape, size = x.shape, x.size
    v = x.reshape(-1)
    pad = (-size) % m
    if pad:
        # zero padding is exact under summation; sliced back off below
        v = jnp.pad(v, (0, pad))
    for a in intra_axes:
        # one collective per fused intra axis (<= 3), deliberate unroll
        v = lax.psum_scatter(v, a, scatter_dimension=0, tiled=True)  # shardcheck: ok
    if inner > 1:
        v = _varying(
            lax.psum_scatter(v, "dp", scatter_dimension=0, tiled=True,
                             axis_index_groups=intra_dp), "dp")
    v = lax.psum(v, "dp", axis_index_groups=cross_dp)
    if inner > 1:
        v = _varying(v, "dp")
        v = lax.all_gather(v, "dp", axis=0, tiled=True,
                           axis_index_groups=intra_dp)
    for a in reversed(intra_axes):
        v = lax.all_gather(v, a, axis=0, tiled=True)  # shardcheck: ok
    if pad:
        v = lax.slice_in_dim(v, 0, size)
    return v.reshape(shape)
