"""Per-layer-class TP partitioning strategies + deferred activation sync.

The fixed Megatron pattern (parallel/tp.py) pays one synchronous collective
per block half on the critical path: the row-parallel exit psum (or the SP
reduce-scatter/all-gather pair). Two relaxations, both selected per config
and threaded through the ParallelCtx hooks so the AD and fused grad engines
emit identical collectives:

**Adaptive per-layer partitioning** (ATP, arxiv 2301.08658). Each layer
class (attn-qkv / attn-o / mlp-up / mlp-down / head) carries a strategy in
{col, row, 2d}; `distributed.tp_strategy` names a preset or an explicit
per-class spec, and "adaptive" resolves the per-class argmin against the
ICI cost model (analysis/cost_model.py price_tp_strategy). The weight
STORAGE layout never changes — all strategies reuse the 1D megatron shards
from parallel/sharding.py (column shards for qkv/gate/up, row shards for
o/down, re-sharded per class for "row") and express the alternative
partitionings as different collective schedules over those shards:

- **col/row (megatron)**: the default f/g pair; no hooks installed, the
  block code path is byte-identical to before this module existed.
- **row-first**: qkv/up contract a per-rank SLICE of the replicated input
  against input-sharded weights and psum the partial projections; o/down
  are column-parallel, so the block exit becomes a feature all-gather —
  V·(n-1)/n bytes instead of the psum's 2·V·(n-1)/n — at the price of
  tp-replicated attention (every rank holds all heads). Honest about the
  replication: the cost model prices it and row-first loses at today's
  shapes; it exists as a searchable point, not a recommendation.
- **2d**: tp factors into tp_x x tp_y (rank r = ix*tp_y + iy, iy-minor so
  tp_y subgroups are contiguous = innermost ICI links). Column matmuls run
  exactly as megatron (full contraction, 1/tp of the output features, no
  collective) and an all-gather within the tp_y subgroup assembles the
  1/tp_x feature block; attention runs with heads/tp_x (replicated tp_y
  ways). Row matmuls all-gather the WEIGHT rows within the tp_y subgroup
  and contract the full feature block, so the exit psum shrinks to the
  tp_x subgroup — activation bytes over tp/tp_y ranks instead of tp, at
  the price of tp_y-replicated row-matmul flops plus a small weight
  gather. On a torus the subgroup psum also rides shorter rings.

**Deferred activation sync** (partially-synchronized-activation TP, arxiv
2506.19645). `distributed.tp_sync="deferred"` replaces the megatron exit
psum with a reduce-scatter over the sequence whose gather half is hoisted
into the NEXT block's entry (`ParallelCtx.pre`, applied to the block input
before the norm): the residual stream stays seq-sharded [*, S/tp, H]
between blocks and the entry all-gather's first consumer is the block's
own norm+qkv chain, so XLA's latency-hiding scheduler can overlap it with
the preceding block's tail compute instead of stalling on a synchronous
psum. Numerics are exact (RMSNorm is per-token; same reduce tree as SP),
pinned by fp32 parity twins against the sync path and the loss-pinned
dryrun patterns (`sp-deferred`) in __graft_entry__.py, and the shardflow
provenance rules (analysis/dataflow.py) prove no implicit reshard.

Everything here runs inside shard_map with the 'tp' axis in scope; the
subgroup collectives use `axis_index_groups` over the single named axis
(the PR-13 mesh-attention submesh idiom — the submesh never becomes a mesh
axis, so dp/cp/ep composition is untouched).
"""

from __future__ import annotations

from functools import partial

from jax import lax
from jax.ad_checkpoint import checkpoint_name

from picotron_tpu import compat
from picotron_tpu.config import (
    Config, resolved_tp_mesh, resolved_tp_strategy,
)
from picotron_tpu.parallel.tp import (
    sp_gather_seq, sp_scatter_seq, vocab_parallel_embed,
)


def _identity(x):
    return x


def tp_subgroups(tp_x: int, tp_y: int):
    """(ty_groups, tx_groups) over the single named 'tp' axis for the
    iy-minor rank layout r = ix*tp_y + iy.

    ty_groups: tp_x subgroups of tp_y contiguous ranks (fixed ix) — the
    feature/weight all-gathers run within these, landing on the innermost
    ICI links. tx_groups: tp_y subgroups of tp_x strided ranks (fixed iy)
    — the shrunken exit psum runs within these.
    """
    ty_groups = [[ix * tp_y + iy for iy in range(tp_y)]
                 for ix in range(tp_x)]
    tx_groups = [[ix * tp_y + iy for ix in range(tp_x)]
                 for iy in range(tp_y)]
    return ty_groups, tx_groups


def _varying(x, axis: str = "tp"):
    """Type x as varying over `axis` (identity on values). Strategy exits
    whose collectives run over subgroups (2d) or all-gathers (row) leave
    the residual replicated in VALUE but the vma type differs per exit
    kind; pinning every strategy exit (and the embedding entry) to the
    varying type keeps the layer-scan carry type stable across mixed
    per-class strategies."""
    if axis in compat.vma(x):
        return x
    return compat.pcast(x, (axis,), to="varying")


# ---------------------------------------------------------------------------
# 2d hooks — tp = tp_x x tp_y over the 1D megatron shards
# ---------------------------------------------------------------------------


def qkv_mm_2d(h, lp, d: int, *, ty_groups, axis: str = "tp"):
    """Column-parallel qkv (megatron compute, 1/tp of the features) + a
    tp_y-subgroup all-gather assembling the 1/tp_x head block. Mirrors
    qkv_proj's contract: [B,S,H] -> ([B,S,Hq/tp_x,D], kv..), flat
    projections checkpoint-named "qkv_out" AFTER the gather (the gathered
    flats are what attention consumes and the remat policies must save)."""
    dt = h.dtype
    b, s, _ = h.shape

    def col_gather(w):
        y = h @ w.astype(dt)
        if len(ty_groups[0]) > 1:
            y = lax.all_gather(y, axis, axis=-1, tiled=True,
                               axis_index_groups=ty_groups)
        return checkpoint_name(y, "qkv_out")

    q, k, v = col_gather(lp["q"]), col_gather(lp["k"]), col_gather(lp["v"])
    return (q.reshape(b, s, -1, d), k.reshape(b, s, -1, d),
            v.reshape(b, s, -1, d))


def o_mm_2d(outf, w, *, ty_groups, tx_groups, axis: str = "tp"):
    """Row matmul over the tp_y-gathered weight rows + a tp_x-subgroup exit
    psum. outf [B,S,q_out/tp_x] (the 2d attention output, flat); w is the
    megatron row shard [q_out/tp, H] — its tp_y-subgroup gather is the
    1/tp_x row block matching outf's features."""
    wg = w.astype(outf.dtype)
    if len(ty_groups[0]) > 1:
        wg = lax.all_gather(wg, axis, axis=0, tiled=True,
                            axis_index_groups=ty_groups)
    part = checkpoint_name(outf @ wg, "attn_proj_out")
    return _varying(lax.psum(part, axis, axis_index_groups=tx_groups), axis)


def mlp_mm_2d(h, lp, cfg, *, ty_groups, tx_groups, axis: str = "tp"):
    """The full 2d MLP after the entry norm: column gate/up (megatron
    compute), the activation product gathered ONCE within the tp_y
    subgroup (elementwise, so act(gate)*up commutes with the gather),
    then the row down-projection against tp_y-gathered weight rows with a
    tp_x-subgroup exit psum."""
    from picotron_tpu.models.llama import mlp_act

    dt = h.dtype
    gate = checkpoint_name(h @ lp["gate"].astype(dt), "mlp_gate")
    up = checkpoint_name(h @ lp["up"].astype(dt), "mlp_up")
    inter = mlp_act(cfg)(gate) * up
    wd = lp["down"].astype(dt)
    if len(ty_groups[0]) > 1:
        inter = lax.all_gather(inter, axis, axis=-1, tiled=True,
                               axis_index_groups=ty_groups)
        wd = lax.all_gather(wd, axis, axis=0, tiled=True,
                            axis_index_groups=ty_groups)
    return _varying(lax.psum(inter @ wd, axis, axis_index_groups=tx_groups),
                    axis)


# ---------------------------------------------------------------------------
# row-first hooks — input-sharded entry, column-parallel exit
# ---------------------------------------------------------------------------


def _slice_features(x, n: int, axis: str = "tp"):
    """This rank's 1/n slab of the replicated feature dim (the row-parallel
    contraction input). The slice's transpose (dynamic-update into zeros)
    plus the varying->invariant boundary psum reassembles the full-feature
    cotangent, exactly megatron's f-backward."""
    chunk = x.shape[-1] // n
    idx = lax.axis_index(axis)
    return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=x.ndim - 1)


def qkv_mm_row(h, lp, d: int, *, tp: int, axis: str = "tp"):
    """Row-parallel qkv: each rank contracts its feature slab against its
    input-sharded weight [H/tp, q_out] and the psum assembles the FULL
    projections — attention then runs tp-replicated (all heads on every
    rank; the cost model charges the replication)."""
    dt = h.dtype
    b, s, _ = h.shape
    hs = _slice_features(h, tp, axis)

    def row_psum(w):
        y = lax.psum(hs @ w.astype(dt), axis)
        return checkpoint_name(y, "qkv_out")

    q, k, v = row_psum(lp["q"]), row_psum(lp["k"]), row_psum(lp["v"])
    return (q.reshape(b, s, -1, d), k.reshape(b, s, -1, d),
            v.reshape(b, s, -1, d))


def o_mm_row(outf, w, *, axis: str = "tp"):
    """Column-parallel o: [B,S,q_out] @ [q_out, H/tp] then a feature
    all-gather — half the exit bytes of the psum it replaces."""
    part = checkpoint_name(outf @ w.astype(outf.dtype), "attn_proj_out")
    return _varying(lax.all_gather(part, axis, axis=-1, tiled=True), axis)


def mlp_mm_row(h, lp, cfg, *, tp: int, axis: str = "tp"):
    """Row-parallel gate/up (two entry psums) + column-parallel down with
    the feature all-gather exit."""
    from picotron_tpu.models.llama import mlp_act

    dt = h.dtype
    hs = _slice_features(h, tp, axis)
    gate = checkpoint_name(lax.psum(hs @ lp["gate"].astype(dt), axis),
                           "mlp_gate")
    up = checkpoint_name(lax.psum(hs @ lp["up"].astype(dt), axis), "mlp_up")
    part = (mlp_act(cfg)(gate) * up) @ lp["down"].astype(dt)
    return _varying(lax.all_gather(part, axis, axis=-1, tiled=True), axis)


# ---------------------------------------------------------------------------
# hook assembly
# ---------------------------------------------------------------------------


def uses_strategy_hooks(cfg: Config) -> bool:
    """True when this config installs any non-megatron hook (strategy or
    deferred sync) — the audit/pricing dispatch key."""
    d = cfg.distributed
    return d.tp_size > 1 and (d.tp_strategy != "megatron"
                              or d.tp_sync == "deferred")


def tp_strategy_hooks(cfg: Config, ce=None) -> dict:
    """ParallelCtx hook overrides for this config's TP strategy and sync
    mode; {} when the config runs plain megatron (sync), so the default
    (and SP) paths are untouched.

    `ce` is the vocab-parallel head-CE callable `(x, head, tgt) ->
    (nll_sum, count)` the deferred head hook composes with its gather
    (make_parallel_ctx passes its chunk-size-bound partial)."""
    d = cfg.distributed
    tp = d.tp_size
    if not uses_strategy_hooks(cfg):
        return {}

    if d.tp_sync == "deferred":
        # Megatron collectives, rescheduled: exit reduce-scatter over the
        # sequence, gather hoisted to the next block's entry (pre). The
        # residual stays seq-sharded; the norm runs AFTER the gather
        # (full-sequence, per-token — numerics identical to sync), so the
        # entry all-gather heads the block's compute chain where XLA can
        # overlap it. Composes with sequence_parallel (the "sp-deferred"
        # pattern): same collectives, the SP f/g placement replaced by
        # the pre/g placement.
        hooks = dict(
            pre=sp_gather_seq,
            f=_identity,
            g=sp_scatter_seq,
            embed_lookup=partial(vocab_parallel_embed, axis="tp",
                                 scatter_seq=True),
            head_in=sp_gather_seq,
            seq_shard=tp,
            # the local/merge CE split cannot host the seq gather inside a
            # divergent branch (same constraint as SP); pp is gated off so
            # nothing consumes it, but keep the fields honest
            head_ce_local=None,
            head_ce_merge=None,
        )
        if ce is not None:
            hooks["head_ce"] = lambda x, head, tgt: ce(
                sp_gather_seq(x), head, tgt)
        return hooks

    spec = resolved_tp_strategy(cfg)
    hooks = {}
    if spec["qkv"] == "2d":
        tp_x, tp_y = resolved_tp_mesh(cfg)
        ty_g, tx_g = tp_subgroups(tp_x, tp_y)
        hooks["qkv_mm"] = partial(qkv_mm_2d, ty_groups=ty_g)
        hooks["o_mm"] = partial(o_mm_2d, ty_groups=ty_g, tx_groups=tx_g)
    elif spec["qkv"] == "row":
        hooks["qkv_mm"] = partial(qkv_mm_row, tp=tp)
        hooks["o_mm"] = o_mm_row
    if spec["up"] == "2d":
        tp_x, tp_y = resolved_tp_mesh(cfg)
        ty_g, tx_g = tp_subgroups(tp_x, tp_y)
        hooks["mlp_mm"] = partial(mlp_mm_2d, ty_groups=ty_g,
                                  tx_groups=tx_g)
    elif spec["up"] == "row":
        hooks["mlp_mm"] = partial(mlp_mm_row, tp=tp)

    if hooks:
        # Strategy exits leave the residual tp-varying (subgroup psums and
        # all-gathers don't erase the varying type the way the full-axis
        # psum does); pin the embedding entry to the same type so the
        # layer-scan carry is stable from layer 0.
        embed = partial(vocab_parallel_embed, axis="tp")
        hooks["embed_lookup"] = lambda w, ids: _varying(embed(w, ids))
    return hooks
