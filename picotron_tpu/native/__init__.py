"""Native (C++) components, loaded via ctypes with pure-Python fallbacks.

The only native code the reference runs in its data path is the HF fast
tokenizer; its concat-and-chunk grouping loop is Python (ref:
picotron/data.py:57-100). Here the grouping loop is `BlockPacker`, a C++
streaming packer compiled on first use (g++ is part of the toolchain; no
pybind11 — plain C ABI + ctypes). If compilation is impossible the
`PyBlockPacker` fallback provides identical behavior.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_THIS_DIR, "packer.cpp")
_LIB = os.path.join(_THIS_DIR, "libpacker.so")

_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _ensure_built() -> Optional[ctypes.CDLL]:
    """Compile packer.cpp -> libpacker.so if missing or stale; load it."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    try:
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _LIB + ".tmp"],
                check=True, capture_output=True, timeout=120)
            os.replace(_LIB + ".tmp", _LIB)
        lib = ctypes.CDLL(_LIB)
        lib.packer_new.restype = ctypes.c_void_p
        lib.packer_new.argtypes = [ctypes.c_int64]
        lib.packer_free.argtypes = [ctypes.c_void_p]
        lib.packer_feed.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_int32),
                                    ctypes.c_int64]
        lib.packer_num_ready.restype = ctypes.c_int64
        lib.packer_num_ready.argtypes = [ctypes.c_void_p]
        lib.packer_carry_len.restype = ctypes.c_int64
        lib.packer_carry_len.argtypes = [ctypes.c_void_p]
        lib.packer_take.restype = ctypes.c_int64
        lib.packer_take.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_int32),
                                    ctypes.c_int64]
        _lib = lib
        return _lib
    except Exception:
        _build_failed = True
        return None


class BlockPacker:
    """Streaming fixed-size token-block packer (C++ backed).

    feed() token-id arrays of any length; take() returns completed
    [n, block_size] int32 blocks. The partial tail carries across feeds, so
    document streams pack losslessly across batch boundaries.
    """

    def __init__(self, block_size: int):
        if block_size <= 0:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.block_size = block_size
        lib = _ensure_built()
        if lib is None:
            raise RuntimeError(
                "native packer unavailable (g++ build failed); use "
                "PyBlockPacker")
        self._lib = lib
        self._h = lib.packer_new(block_size)

    def feed(self, tokens) -> None:
        arr = np.ascontiguousarray(tokens, dtype=np.int32)
        if arr.size == 0:
            return
        self._lib.packer_feed(
            self._h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            arr.size)

    @property
    def num_ready(self) -> int:
        return self._lib.packer_num_ready(self._h)

    @property
    def carry_len(self) -> int:
        return self._lib.packer_carry_len(self._h)

    def take(self, max_blocks: Optional[int] = None) -> np.ndarray:
        n = self.num_ready
        if max_blocks is not None:
            n = min(n, max_blocks)
        out = np.empty((n, self.block_size), dtype=np.int32)
        if n:
            got = self._lib.packer_take(
                self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
            assert got == n
        return out

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.packer_free(h)
            self._h = None


class PyBlockPacker:
    """Pure-numpy fallback with BlockPacker's exact contract."""

    def __init__(self, block_size: int):
        if block_size <= 0:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.block_size = block_size
        self._carry = np.empty((0,), dtype=np.int32)
        self._blocks: list[np.ndarray] = []

    def feed(self, tokens) -> None:
        arr = np.ascontiguousarray(tokens, dtype=np.int32).ravel()
        buf = np.concatenate([self._carry, arr]) if self._carry.size else arr
        n = buf.size // self.block_size
        if n:
            self._blocks.append(
                buf[:n * self.block_size].reshape(n, self.block_size).copy())
        self._carry = buf[n * self.block_size:].copy()

    @property
    def num_ready(self) -> int:
        return sum(b.shape[0] for b in self._blocks)

    @property
    def carry_len(self) -> int:
        return int(self._carry.size)

    def take(self, max_blocks: Optional[int] = None) -> np.ndarray:
        avail = np.concatenate(self._blocks) if self._blocks else np.empty(
            (0, self.block_size), dtype=np.int32)
        n = avail.shape[0] if max_blocks is None else min(avail.shape[0],
                                                          max_blocks)
        out = avail[:n]
        rest = avail[n:]
        self._blocks = [rest] if rest.size else []
        return out


def make_packer(block_size: int):
    """BlockPacker if the native library builds/loads, else PyBlockPacker."""
    try:
        return BlockPacker(block_size)
    except (RuntimeError, OSError):
        return PyBlockPacker(block_size)
