// Streaming token-block packer — the native hot loop of the data pipeline.
//
// The reference's dataloader concatenates tokenized documents and chunks them
// into fixed (seq_len+1)-token blocks inside a Python dataset.map callback
// (ref: picotron/data.py:57-100, `tokenizer_group_text`); its native
// performance there comes from the HF fast-tokenizer Rust core. This is the
// equivalent native component on our side: a C++ packer that accepts
// token-id buffers of arbitrary length and emits fixed-size blocks, carrying
// the remainder across calls (so no tokens are lost at feed boundaries —
// an improvement over per-map-batch tail dropping).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image). All
// buffers are int32 token ids; the Python wrapper owns numpy conversion.
//
// Build: g++ -O3 -shared -fPIC packer.cpp -o libpacker.so
// (done automatically by picotron_tpu/native/__init__.py).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Packer {
  int64_t block_size;
  // Completed blocks, stored back-to-back (ready_count * block_size ids),
  // plus the carry of the current partially-filled block.
  std::vector<int32_t> ready;
  std::vector<int32_t> carry;
};

}  // namespace

extern "C" {

void* packer_new(int64_t block_size) {
  if (block_size <= 0) return nullptr;
  auto* p = new Packer();
  p->block_size = block_size;
  p->carry.reserve(static_cast<size_t>(block_size));
  return p;
}

void packer_free(void* handle) { delete static_cast<Packer*>(handle); }

// Feed `n` token ids; completed blocks accumulate internally.
void packer_feed(void* handle, const int32_t* tokens, int64_t n) {
  auto* p = static_cast<Packer*>(handle);
  const int64_t bs = p->block_size;
  int64_t i = 0;

  // Top up the carry first.
  if (!p->carry.empty()) {
    const int64_t need = bs - static_cast<int64_t>(p->carry.size());
    const int64_t take = n < need ? n : need;
    p->carry.insert(p->carry.end(), tokens, tokens + take);
    i = take;
    if (static_cast<int64_t>(p->carry.size()) == bs) {
      p->ready.insert(p->ready.end(), p->carry.begin(), p->carry.end());
      p->carry.clear();
    }
  }

  // Bulk-copy whole blocks straight from the input.
  const int64_t whole = (n - i) / bs;
  if (whole > 0) {
    const size_t old = p->ready.size();
    p->ready.resize(old + static_cast<size_t>(whole * bs));
    std::memcpy(p->ready.data() + old, tokens + i,
                static_cast<size_t>(whole * bs) * sizeof(int32_t));
    i += whole * bs;
  }

  // Remainder becomes the new carry.
  if (i < n) p->carry.insert(p->carry.end(), tokens + i, tokens + n);
}

int64_t packer_num_ready(void* handle) {
  auto* p = static_cast<Packer*>(handle);
  return static_cast<int64_t>(p->ready.size()) / p->block_size;
}

int64_t packer_carry_len(void* handle) {
  return static_cast<int64_t>(static_cast<Packer*>(handle)->carry.size());
}

// Move up to `max_blocks` completed blocks into `out` (caller-allocated,
// max_blocks * block_size int32s). Returns the number of blocks written.
int64_t packer_take(void* handle, int32_t* out, int64_t max_blocks) {
  auto* p = static_cast<Packer*>(handle);
  const int64_t bs = p->block_size;
  const int64_t have = static_cast<int64_t>(p->ready.size()) / bs;
  const int64_t n = have < max_blocks ? have : max_blocks;
  if (n > 0) {
    std::memcpy(out, p->ready.data(),
                static_cast<size_t>(n * bs) * sizeof(int32_t));
    p->ready.erase(p->ready.begin(), p->ready.begin() + n * bs);
  }
  return n;
}

}  // extern "C"
