from picotron_tpu.models.llama import (  # noqa: F401
    ParallelCtx,
    init_params,
    embed,
    run_layers,
    final_hidden,
    logits_from_hidden,
    forward,
    loss_fn,
)
