"""Llama-family decoder-only model, written as pure functions over a param
pytree (capability parity with ref: picotron/model.py:227-272).

Architecture: Embedding -> N x (RMSNorm -> GQA-Attention -> residual ->
RMSNorm -> SwiGLU-MLP -> residual) -> final RMSNorm -> untied LM head
(ref: model.py:204-209, 265-272).

TPU-first design decisions (vs the reference's nn.Module tree):

- **Stacked layer params.** All decoder layers live in one pytree with a
  leading layer axis, so the layer loop is a `lax.scan` — one traced layer
  body, O(1) compile time in depth, and the pipeline-parallel stage slice is
  literally `tree_map(lambda x: x[stage_lo:stage_hi], layers)`.
- **Parallelism is injected, not hard-coded.** The model never reads env vars
  (the reference dispatches attention through `CONTEXT_PARALLEL`/`FLASH_ATTEN`
  env flags, ref: model.py:148-158). Instead a `ParallelCtx` carries the
  attention implementation and the TP/CP collective hooks; the single-device
  defaults are identities, and shard_map-level code swaps in psum/ppermute
  versions. Head counts are derived from the *local* weight shapes, so the
  same forward runs unsharded or TP-sharded unchanged.
- **fp32 master params, bf16 compute.** Params are stored fp32 and cast to
  the compute dtype at use; autodiff then naturally yields fp32 gradients
  (the reference gets this with a separate fp32 `main_grad` buffer system,
  ref: data_parallel.py:66-144).
- **Init matches the reference exactly** (ref: model.py:110-120, 173-182,
  221-222, 48-49): linear weights ~ U(±sqrt(1/fan_in)), embedding ~ N(0,1),
  norm weights = 1, untied head.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from picotron_tpu.config import ModelConfig
from picotron_tpu.ops.attention import sdpa_attention
from picotron_tpu.ops.losses import cross_entropy, cross_entropy_sum_count
from picotron_tpu.ops.rmsnorm import rms_norm
from picotron_tpu.ops.rope import apply_rope, rope_tables


def model_rope_tables(cfg, max_len=None):
    """RoPE tables for a model config, honoring cfg.rope_scaling
    (Llama-3.1/3.2). All model-level paths must build tables through this
    helper so scaling cannot be silently dropped on one path."""
    return rope_tables(max_len or cfg.max_position_embeddings, cfg.head_dim,
                       cfg.rope_theta, rope_scaling=cfg.rope_scaling_dict)

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Parallel context — how parallelism plugs into the model
# ---------------------------------------------------------------------------


def _identity(x):
    return x


def _default_attn(q, k, v, positions, rope):
    # q/k arrive unrotated: each attention impl owns RoPE so the flash path
    # can rotate inside its kernels (parallel/api.py) while reference paths
    # use the jnp rotation.
    q = apply_rope(q, *rope, positions)
    k = apply_rope(k, *rope, positions)
    return sdpa_attention(q, k, v, causal=True,
                          q_positions=positions, kv_positions=positions)


@dataclass(frozen=True)
class ParallelCtx:
    """Hooks that parallel wrappers override; defaults are single-device.

    f / g are Megatron's column-parallel entry / row-parallel exit collectives
    (ref: tp_communications.py:19-49): `f` = identity fwd / psum bwd, applied
    to activations entering column-parallel matmuls; `g` = psum fwd / identity
    bwd, applied to row-parallel matmul outputs.
    """

    # attention impl: (q, k, v, positions) -> out, all [B, S, H_local, D]
    attn: Callable = _default_attn
    # TP collectives
    f: Callable = _identity
    g: Callable = _identity
    # block-entry hook, applied to the residual-stream input BEFORE the
    # norm (identity everywhere except deferred TP sync, where it is the
    # hoisted gather half of the previous block's exit reduce-scatter —
    # parallel/tp_strategies.py)
    pre: Callable = _identity
    # per-layer-class TP strategy overrides (parallel/tp_strategies.py):
    # qkv_mm replaces qkv_proj ((h, lp, head_dim) -> (q, k, v) reshaped to
    # heads), o_mm replaces the o-projection + exit collective
    # ((out_flat, w_o) -> block output), mlp_mm replaces the MLP matmuls +
    # exit collective after the entry norm ((h, lp, cfg) -> block output).
    # None = the megatron path as written in this file. The fused grad
    # engine reaches all three through the same call sites / segment VJPs.
    qkv_mm: Optional[Callable] = None
    o_mm: Optional[Callable] = None
    mlp_mm: Optional[Callable] = None
    # head-entry hook for the logits path: deferred TP sync keeps f as the
    # identity (the gather moved to `pre`) but the head still needs the
    # full sequence — None falls back to f (SP and every sync path)
    head_in: Optional[Callable] = None
    # embedding lookup (vocab-parallel TP overrides this)
    embed_lookup: Optional[Callable] = None
    # fused head+CE returning (nll_sum, valid_count) (vocab-parallel TP
    # overrides to avoid full-logit gather)
    head_ce: Optional[Callable] = None
    # collective-free/merge split of head_ce for the pipeline engines' gated
    # last-stage scoring (parallel/tp.py vocab_parallel_ce_local_stats /
    # _merge); None when the split is unavailable (sequence parallelism —
    # its seq gather cannot live inside a divergent branch) and the engines
    # fall back to uniform masked scoring
    head_ce_local: Optional[Callable] = None
    head_ce_merge: Optional[Callable] = None
    # logits gather for eval under TP
    gather_logits: Callable = _identity
    # global positions of this shard's tokens [S_local] (context parallelism;
    # None = 0..S-1)
    positions: Optional[jnp.ndarray] = None
    # factor by which the residual stream's sequence dim is sharded relative
    # to the input ids (sequence parallelism: tp_size; otherwise 1). Pipeline
    # boundary buffers are sized S_local / seq_shard.
    seq_shard: int = 1
    # mesh axis for MoE expert parallelism ("ep" inside the composed step);
    # None = no all_to_all (single device, or outside shard_map)
    moe_ep_axis: Optional[str] = None
    # mesh axes to pmean router statistics over (layout-exact global aux;
    # config.router_aux_global) — None = per-device statistics
    moe_stat_axes: Optional[tuple] = None
    # makes the MoE aux-loss scalar tp-INVARIANT under sequence parallelism
    # (every tp rank computes it from the same gathered tokens, but the
    # gather's output is typed tp-varying; a pmean re-establishes the
    # replication so the loss fold stays tp-clean)
    moe_aux_sync: Callable = _identity
    # gradient checkpointing over decoder layers
    remat: bool = False
    # "full" | "dots" (save matmul outputs, recompute elementwise only)
    remat_policy: str = "dots"
    # (n_slots) -> float32[n_slots] mask of REAL (non-pad) layer slots in
    # this device's stacked-layer slice. Uneven-PP padding adds all-zero
    # identity layers (pp_layer_placement); their router statistics must not
    # enter the MoE aux loss / drop metric, and the mask is derived from the
    # STATIC placement (stage index + remainder rule), not from sniffing
    # router weights — a legitimately zero-initialized router would
    # otherwise lose its balance/z gradients silently (ADVICE r3). None =
    # every slot is real.
    layer_is_real: Optional[Callable] = None


DEFAULT_CTX = ParallelCtx()


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def _uniform_fan_in(key, fan_in: int, shape) -> jnp.ndarray:
    bound = (1.0 / fan_in) ** 0.5
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound)


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Full (unsharded) parameter pytree, fp32.

    Layer weights are stacked on a leading layer axis. Matmul weights are
    stored [in_features, out_features] (x @ w convention).
    """
    h = cfg.hidden_size
    i = cfg.intermediate_size
    v = cfg.vocab_size
    nl = cfg.num_hidden_layers
    d = cfg.head_dim
    q_out = cfg.num_attention_heads * d
    kv_out = cfg.num_key_value_heads * d

    keys = jax.random.split(key, 14)

    def stacked(k, fan_in, shape):
        ks = jax.random.split(k, nl)
        return jnp.stack([_uniform_fan_in(ks[j], fan_in, shape) for j in range(nl)])

    layers = {
        "input_norm": jnp.ones((nl, h), jnp.float32),
        "q": stacked(keys[1], h, (h, q_out)),
        "k": stacked(keys[2], h, (h, kv_out)),
        "v": stacked(keys[3], h, (h, kv_out)),
        "o": stacked(keys[4], q_out, (q_out, h)),
        "post_norm": jnp.ones((nl, h), jnp.float32),
    }
    if cfg.attention_bias:
        # Qwen2-style qkv bias (zero-init, the HF convention)
        layers.update({
            "b_q": jnp.zeros((nl, q_out), jnp.float32),
            "b_k": jnp.zeros((nl, kv_out), jnp.float32),
            "b_v": jnp.zeros((nl, kv_out), jnp.float32),
        })
    if cfg.num_experts:
        e, f = cfg.num_experts, cfg.expert_ffn_size
        layers.update({
            # router + per-layer expert banks [L, E, ...] (ops/moe.py)
            "router": stacked(keys[9], h, (h, e)),
            "w_gate": stacked(keys[5], h, (e, h, f)),
            "w_up": stacked(keys[6], h, (e, h, f)),
            "w_down": stacked(keys[7], f, (e, f, h)),
        })
    else:
        layers.update({
            "gate": stacked(keys[5], h, (h, i)),
            "up": stacked(keys[6], h, (h, i)),
            "down": stacked(keys[7], i, (i, h)),
        })

    params = {
        "embedding": jax.random.normal(keys[0], (v, h), jnp.float32),
        "layers": layers,
        "final_norm": jnp.ones((h,), jnp.float32),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _uniform_fan_in(keys[8], h, (h, v))
    return params


def head_weight(params: Params) -> jnp.ndarray:
    # The LM-head matrix [H, V(/tp)]: the separate lm_head when the model
    # unties (the Llama family), else the transposed embedding (Qwen2-style
    # tying; gradients flow to the embedding through both uses, and under
    # TP the vocab-sharded [V/tp, H] embedding shard transposes to exactly
    # the head's [H, V/tp] layout).
    w = params.get("lm_head")
    return w if w is not None else params["embedding"].T


def param_count(params: Params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


# ---------------------------------------------------------------------------
# Uneven pipeline layer distribution (ref: pipeline_parallel.py:42-51)
# ---------------------------------------------------------------------------


def pp_layer_placement(num_layers: int, pp: int):
    """(padded_size, slot_index[num_layers]) for an uneven layer split.

    The stacked layer axis is padded to pp * ceil(L/pp) so P('pp') divides
    evenly; stage k holds L//pp (+1 for the first L%pp stages — remainder to
    early stages, the reference's distribute_layers rule) real layers in its
    leading slots. Pad slots hold all-zero layer params, which make the
    decoder layer an *exact identity with exactly-zero gradients*: the
    residual passes through, every projection output is 0, and every pad
    param's grad is 0 (each flows through a zero activation or zero weight),
    so Adam(+wd) keeps pads at zero forever. No masking needed anywhere.
    """
    import numpy as np

    per = -(-num_layers // pp)  # ceil
    counts = [num_layers // pp + (1 if k < num_layers % pp else 0)
              for k in range(pp)]
    slots = np.concatenate([
        np.arange(k * per, k * per + counts[k]) for k in range(pp)
    ]).astype(np.int32)
    return per * pp, slots


def pad_layers_for_pp(params: Params, num_layers: int, pp: int) -> Params:
    """Scatter the canonical [L]-stacked layer tree into its [Lp] padded
    layout (identity when L % pp == 0)."""
    padded, slots = pp_layer_placement(num_layers, pp)
    if padded == num_layers:
        return params

    def pad(x):
        out = jnp.zeros((padded,) + x.shape[1:], x.dtype)
        return out.at[slots].set(x)

    return {**params, "layers": jax.tree.map(pad, params["layers"])}


def unpad_layers(params: Params, num_layers: int, pp: int) -> Params:
    """Inverse of pad_layers_for_pp: gather back the canonical [L] stack."""
    padded, slots = pp_layer_placement(num_layers, pp)
    if padded == num_layers:
        return params
    return {**params,
            "layers": jax.tree.map(lambda x: x[slots], params["layers"])}


# ---------------------------------------------------------------------------
# Forward pieces (granular so PP schedules can compose them)
# ---------------------------------------------------------------------------


def compute_dtype(cfg: ModelConfig):
    """Activation/compute dtype for this model config (params stay fp32)."""
    return jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32


def embed(params: Params, input_ids: jnp.ndarray, cfg: ModelConfig,
          ctx: ParallelCtx = DEFAULT_CTX) -> jnp.ndarray:
    """Token embedding -> [B, S, H] in compute dtype."""
    w = params["embedding"]
    if ctx.embed_lookup is not None:
        x = ctx.embed_lookup(w, input_ids)
    else:
        x = w[input_ids]
    return x.astype(compute_dtype(cfg))


def qkv_proj(h, lp, d: int):
    """Shared q/k/v projection (+ optional Qwen2 bias, tp-sharded with its
    output features) -> ([B,S,Hq,D], [B,S,Hkv,D], [B,S,Hkv,D]); local head
    counts come from the (possibly TP-sharded) weight shapes. One
    implementation for the training block AND the KV-cache decode path
    (generate.py) so attention-input changes cannot silently diverge."""
    dt = h.dtype
    b, s, _ = h.shape
    q = h @ lp["q"].astype(dt)
    k = h @ lp["k"].astype(dt)
    v = h @ lp["v"].astype(dt)
    if "b_q" in lp:
        q = q + lp["b_q"].astype(dt)
        k = k + lp["b_k"].astype(dt)
        v = v + lp["b_v"].astype(dt)
    # checkpoint-name the FLAT [B, S, H*D] projections, BEFORE the head
    # reshape: saved activations inherit the flat matmul layout, whose
    # (8, 128)-tiled minor dim is H*D. Naming the reshaped [B, S, H, 64]
    # form instead makes the remat policies store tensors whose 64-wide
    # minor dim tiles to 128 lanes — a 2x HBM pad on every saved q/k/v
    # (measured ~1.5 GB at SmolLM-1.7B mbs 2; PERF.md r4).
    q = checkpoint_name(q, "qkv_out")
    k = checkpoint_name(k, "qkv_out")
    v = checkpoint_name(v, "qkv_out")
    return (q.reshape(b, s, -1, d), k.reshape(b, s, -1, d),
            v.reshape(b, s, -1, d))


def _attention_block(x, lp, cfg: ModelConfig, ctx: ParallelCtx, cos, sin):
    """RMSNorm -> qkv -> RoPE -> attention -> out_proj (ref: model.py:122-162)."""
    dt = x.dtype
    d = cfg.head_dim

    h = rms_norm(ctx.pre(x), lp["input_norm"], cfg.rms_norm_eps)
    h = ctx.f(h)  # column-parallel entry: identity fwd / psum bwd; under
    # sequence parallelism an all_gather that restores the full sequence
    b, s, _ = h.shape
    # qkv_proj checkpoint-names the flat projections ("qkv_out"): the
    # "dots_attn" policy saves the attention-side dots (the flash VJP's
    # inputs) while the MLP recomputes — the memory/flops midpoint between
    # "dots" and "full" (the MLP's gate/up activations are ~2/3 of a
    # layer's saved bytes but its matmuls only ~+7% of step flops)
    q, k, v = (ctx.qkv_mm or qkv_proj)(h, lp, d)
    n_q = q.shape[2]

    # K/V stay unexpanded (n_kv heads) — attention impls handle GQA so the
    # CP ring permutes and flash streams the small K/V. RoPE is applied by
    # the impl (in-kernel on the flash path), so q/k pass through raw.
    out = ctx.attn(q, k, v, ctx.positions, (cos, sin))  # [B, S, n_q, D]
    # attn_out/attn_lse are checkpoint_name'd inside each attention impl
    # (flash VJP fwd rule / sdpa), so the "dots" remat policy saves the
    # kernel residuals exactly once and backward never re-runs the forward.
    out = out.reshape(b, s, n_q * d)
    if ctx.o_mm is not None:
        return ctx.o_mm(out, lp["o"])
    out = out @ lp["o"].astype(dt)
    out = checkpoint_name(out, "attn_proj_out")
    return ctx.g(out)  # row-parallel exit: psum-over-tp fwd / identity bwd


def mlp_act(cfg: ModelConfig):
    """Gated-MLP activation on the gate branch: SwiGLU (silu, the Llama
    lineage, ref: model.py:184-186), exact-erf GeGLU ("gelu" — what
    transformers' ACT2FN "gelu" means), or tanh-approx GeGLU ("gelu_tanh",
    the Gemma-style variant) — shared by the dense MLP, the MoE expert
    bank, and the decode path so they cannot diverge."""
    if cfg.hidden_act == "silu":
        return jax.nn.silu
    return partial(jax.nn.gelu, approximate=cfg.hidden_act == "gelu_tanh")


def _mlp_block(x, lp, cfg: ModelConfig, ctx: ParallelCtx):
    """RMSNorm -> gated MLP (ref: model.py:184-186)."""
    dt = x.dtype
    h = rms_norm(ctx.pre(x), lp["post_norm"], cfg.rms_norm_eps)
    if ctx.mlp_mm is not None:
        return ctx.mlp_mm(h, lp, cfg)
    h = ctx.f(h)
    gate = checkpoint_name(h @ lp["gate"].astype(dt), "mlp_gate")
    up = checkpoint_name(h @ lp["up"].astype(dt), "mlp_up")
    out = (mlp_act(cfg)(gate) * up) @ lp["down"].astype(dt)
    return ctx.g(out)


def _moe_block(x, lp, cfg: ModelConfig, ctx: ParallelCtx, is_real=1.0):
    """RMSNorm -> top-k routed expert SwiGLU bank (beyond the reference;
    ops/moe.py). Returns (out, aux [2])."""
    from picotron_tpu.ops.moe import moe_mlp

    h = rms_norm(ctx.pre(x), lp["post_norm"], cfg.rms_norm_eps)
    h = ctx.f(h)
    out, aux, drop = moe_mlp(
        h, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
        num_experts=cfg.num_experts,
        top_k=cfg.num_experts_per_token,
        capacity_factor=cfg.capacity_factor,
        act=mlp_act(cfg),
        ep_axis=ctx.moe_ep_axis,
        router_aux_coef=cfg.router_aux_coef,
        router_z_coef=cfg.router_z_coef,
        stat_axes=ctx.moe_stat_axes,
    )
    # Zero-padded PP layer slots (pad_layers_for_pp) must not contribute
    # router statistics: their all-zero router yields uniform logits whose
    # z-loss (log(E)^2 per token) and tie-broken top-k capacity overflow
    # would pollute the loss and the drop metric (code review r3). `is_real`
    # comes from the static placement (ctx.layer_is_real via run_layers),
    # not from the weights (ADVICE r3).
    return ctx.g(out), ctx.moe_aux_sync(jnp.stack([aux, drop]) * is_real)


def decoder_layer(x, lp, cfg: ModelConfig, ctx: ParallelCtx, cos, sin,
                  is_real=1.0):
    """Returns (x, aux [2]) — aux[0] is the pre-weighted router loss
    (balance + z, 0 for dense models), aux[1] the capacity drop fraction
    (observability; stop_gradient-free but weightless in the loss).
    `is_real` masks the aux of zero-padded PP layer slots (see
    ParallelCtx.layer_is_real)."""
    x = x + _attention_block(x, lp, cfg, ctx, cos, sin)
    if cfg.num_experts:
        mlp_out, aux = _moe_block(x, lp, cfg, ctx, is_real)
    else:
        mlp_out, aux = _mlp_block(x, lp, cfg, ctx), jnp.zeros(2, jnp.float32)
    return x + mlp_out, aux


def remat_policy_for(name: str):
    """jax.checkpoint policy for a config remat_policy name.

    "dots" saves matmul outputs + the named attention output, so only cheap
    elementwise work is recomputed in backward; "full" (None) recomputes
    everything. Shared by the layer scan here and the pipeline tick scan
    (parallel/pp.py) so both paths honor the same config knob.
    """
    if name in ("dots", "dots_norms"):
        # attn_lse rides along with attn_out (named inside the flash VJP's
        # fwd rule, ops/flash_attention.py) so the kernel's residuals are
        # fully saved and backward never re-runs the forward kernel.
        # "dots_norms" additionally saves the RMSNorm outputs — backward
        # skips the fp32 norm recompute at ~2 extra saved activations per
        # layer of HBM (measured slower on v5e; PERF.md).
        names = ("attn_out", "attn_lse")
        if name == "dots_norms":
            names += ("norm_out",)
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(*names),
        )
    if name == "dots_attn":
        # Save only the flash kernel's inputs and residuals (qkv
        # projections, out, lse) and recompute everything else in backward
        # — the MLP (its gate/up activations are ~2/3 of a layer's saved
        # bytes but its matmuls only ~+7% of step FLOPs) and the
        # o-projection (one matmul consuming the SAVED attn_out). The
        # policy that fits full-depth SmolLM-1.7B beside
        # optimizer_offload's fp32 grad tree on one v5e chip (PERF.md r4).
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_lse", "qkv_out")
    if name == "dots_lean":
        # "dots" minus the o-projection and down-projection outputs (each
        # is one matmul whose inputs ARE saved — attn_out and gate/up —
        # so recompute costs ~+2% step FLOPs for ~0.4 GB less saved HBM
        # at SmolLM-1.7B mbs 1). All saves are the flat named forms, so
        # none carry the 64-lane tile padding (PERF.md r4).
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_lse", "qkv_out", "mlp_gate", "mlp_up")
    if name == "dots_offload":
        # "dots" memory shape with the saved activations parked in pinned
        # HOST memory instead of HBM (offloaded on the forward, fetched in
        # backward): near-zero device activation residency for 2x the
        # activation bytes over PCIe per microbatch. Measured on v5e in
        # PERF.md round 4 — the PCIe cost exceeds the recompute it avoids
        # at these shapes; kept as a knob for shapes where it flips
        # (long-sequence activations >> PCIe budget is the wrong side; big
        # grad-accum with small activations the right one).
        # attn_lse stays device-saved: it is tiny ([B,H,S] vs the [B,S,H*D]
        # tensors) and offloading it crashes libtpu's host-offload
        # legalizer (host_offload_utils.cc "reduce has 2 operands" check —
        # the lse feeds a variadic reduce in the flash VJP)
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=["attn_lse"],
            names_which_can_be_offloaded=[
                "attn_out", "qkv_out", "attn_proj_out",
                "mlp_gate", "mlp_up"],
            offload_src="device", offload_dst="pinned_host")
    return None


def run_layers(layer_params: Params, x: jnp.ndarray, cfg: ModelConfig,
               ctx: ParallelCtx = DEFAULT_CTX,
               cos: jnp.ndarray | None = None,
               sin: jnp.ndarray | None = None):
    """Scan a stacked layer pytree over x. Works on any contiguous stage
    slice, which is exactly what pipeline parallelism feeds it.

    Returns (x, aux [2]) — aux[0] the summed pre-weighted MoE router loss
    over the scanned layers, aux[1] the summed capacity drop fraction
    (both 0 for dense models)."""
    if cos is None:
        cos, sin = model_rope_tables(cfg)

    def body(h, xs):
        lp, real = xs
        h, aux = decoder_layer(h, lp, cfg, ctx, cos, sin, real)
        # aux rides the scan's stacked outputs (not the carry: its varying
        # mesh axes differ from x's, which would unstabilize the carry type)
        return h, aux

    n_slots = jax.tree.leaves(layer_params)[0].shape[0]
    real = (ctx.layer_is_real(n_slots) if ctx.layer_is_real is not None
            else jnp.ones((n_slots,), jnp.float32))
    if ctx.remat:
        body = jax.checkpoint(body, policy=remat_policy_for(ctx.remat_policy))
    x, aux_per_layer = jax.lax.scan(body, x, (layer_params, real))  # [L, 2]
    return x, jnp.sum(aux_per_layer, axis=0)


def final_hidden(params: Params, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps)


def logits_from_hidden(params: Params, x: jnp.ndarray, cfg: ModelConfig,
                       ctx: ParallelCtx = DEFAULT_CTX) -> jnp.ndarray:
    # Under sequence parallelism x arrives seq-sharded; the column-parallel
    # entry hook re-gathers the sequence before the vocab-sharded head.
    # Deferred TP sync keeps f as the identity (the gather lives in `pre`)
    # and supplies head_in instead (identity on every other path).
    x = (ctx.head_in or ctx.f)(x)
    logits = x @ head_weight(params).astype(x.dtype)
    return ctx.gather_logits(logits)


# ---------------------------------------------------------------------------
# Convenience compositions
# ---------------------------------------------------------------------------


def forward(params: Params, input_ids: jnp.ndarray, cfg: ModelConfig,
            ctx: ParallelCtx = DEFAULT_CTX) -> jnp.ndarray:
    """input_ids [B, S] -> logits [B, S, V] (full vocab; eval/debug path)."""
    cos, sin = model_rope_tables(cfg)
    x = embed(params, input_ids, cfg, ctx)
    x, _ = run_layers(params["layers"], x, cfg, ctx, cos, sin)
    x = final_hidden(params, x, cfg)
    return logits_from_hidden(params, x, cfg, ctx)


def loss_sum_count(params: Params, input_ids: jnp.ndarray, targets: jnp.ndarray,
                   cfg: ModelConfig, ctx: ParallelCtx = DEFAULT_CTX):
    """(sum of per-token NLL, valid-token count) — the reduction pieces, so
    data-parallel shards can psum both and divide once (a per-shard mean +
    unweighted pmean would mis-weight shards with different IGNORE_INDEX
    counts).

    Under TP, `ctx.head_ce` computes the pieces against vocab-sharded logits
    without materializing the full-vocab gather.

    For MoE models the (pre-weighted, ops/moe.py) router loss is folded in
    as `nll_sum + aux * count`, so the downstream `total / count` division
    yields `ce_mean + aux` — the reported loss includes the router terms
    (Mixtral convention) and their gradient flows with no extra plumbing
    through the dp/cp/pp reductions. The third return is an extras dict of
    token-weighted observability sums ({"moe_drop_weighted"} for MoE, {}
    for dense) that ride the same psum path; the step normalizes them.
    """
    cos, sin = model_rope_tables(cfg)
    x = embed(params, input_ids, cfg, ctx)
    x, aux = run_layers(params["layers"], x, cfg, ctx, cos, sin)
    x = final_hidden(params, x, cfg)
    if ctx.head_ce is not None:
        total, count = ctx.head_ce(x, head_weight(params), targets)
    else:
        logits = x @ head_weight(params).astype(x.dtype)
        total, count = cross_entropy_sum_count(logits, targets)
    extras = {}
    if cfg.num_experts:
        total = total + aux[0] * count
        extras["moe_drop_weighted"] = aux[1] * count
    return total, count, extras


def loss_fn(params: Params, input_ids: jnp.ndarray, targets: jnp.ndarray,
            cfg: ModelConfig, ctx: ParallelCtx = DEFAULT_CTX) -> jnp.ndarray:
    """Token-mean cross-entropy training loss (ref: train.py:43-49)."""
    total, count, _ = loss_sum_count(params, input_ids, targets, cfg, ctx)
    return total / jnp.maximum(count, 1)
