"""Verified checkpoint lineage: integrity manifests, retention, preflight.

`CheckpointManager` (checkpoint.py) historically equated *finalized* with
*valid*: once Orbax's commit marker existed, restore trusted the bytes
unconditionally. A bit-flipped shard, a truncated array file, or a torn
`meta.json` on the newest step killed the run — or worse, resumed it
silently wrong. This package closes that gap:

- **manifest** — a commit manifest written as the LAST act of every save
  (atomic tmp+rename): per-payload-file content digests and byte sizes of
  everything under the step directory, plus the source topology. A step is
  *verified* when every manifest entry matches the bytes on disk;
  "finalized => trust it" becomes "finalized AND verified => trust it".
  Verification is pure reads, so it runs at restore time (and in
  `tools/ckpt_doctor.py`) without touching the step path.
- **retention** — the pure `retention_plan` policy behind
  `checkpoint.keep_last` / `keep_every` GC: prune old steps after each
  durable commit, provably never the newest retained window, a
  keep_every anchor, or the last verified step.
- **preflight** — fail-fast save-dir validation at trainer startup
  (writable? headroom for one checkpoint, estimated from param+optimizer
  bytes?) so a doomed `save_dir` dies before pod time is committed, not
  at the first save.

The consumers: checkpoint.CheckpointManager (manifest commit,
`latest_valid_step`, GC), train.py (preflight, lineage-fallback restore),
resilience/chaos.py (the `ckpt_corrupt_*` fault kinds mutate committed
bytes for exactly this machinery to catch), tools/ckpt_doctor.py (the
offline fsck).
"""

from picotron_tpu.ckpt_integrity.manifest import (
    MANIFEST_NAME, VerifyResult, atomic_write_text, build_manifest,
    file_digest, rmtree, verify_step_dir, write_manifest,
)
from picotron_tpu.ckpt_integrity.preflight import (
    checkpoint_nbytes, preflight_save_dir,
)
from picotron_tpu.ckpt_integrity.retention import retention_plan

__all__ = [
    "MANIFEST_NAME",
    "VerifyResult",
    "atomic_write_text",
    "build_manifest",
    "checkpoint_nbytes",
    "file_digest",
    "preflight_save_dir",
    "retention_plan",
    "rmtree",
    "verify_step_dir",
    "write_manifest",
]
