"""Retention policy: which checkpoint steps survive GC.

Pure set arithmetic, no I/O — `CheckpointManager.gc` and
`tools/ckpt_doctor.py --gc` both call this, so in-process pruning and the
offline tool can never disagree about what a policy keeps.
"""

from __future__ import annotations

from typing import Iterable


def retention_plan(steps: Iterable[int], keep_last: int = 0,
                   keep_every: int = 0,
                   protect: Iterable[int] = ()) -> tuple[list, list]:
    """(keep, delete) over `steps` under the retention policy.

    - ``keep_last`` — the N newest steps always survive. 0 disables GC
      entirely (everything is kept; the pre-lineage behavior).
    - ``keep_every`` — steps divisible by this survive forever (sparse
      long-horizon anchors under an aggressive keep_last). 0 disables.
    - ``protect`` — steps that must survive regardless of policy. The
      caller passes at least the last *verified* step: a retention sweep
      must never delete the only checkpoint restore could fall back to,
      even when keep_last=1 and the newest step is corrupt.

    Both outputs are sorted ascending and partition the input set.
    """
    steps = sorted(set(int(s) for s in steps))
    if keep_last <= 0:
        return steps, []
    keep = set(steps[-keep_last:])
    if keep_every > 0:
        keep.update(s for s in steps if s % keep_every == 0)
    keep.update(s for s in protect if s in set(steps))
    delete = [s for s in steps if s not in keep]
    return sorted(keep), delete
