"""Commit manifests: content-addressed integrity for a checkpoint step dir.

A manifest is a JSON sidecar (`manifest.json`, written tmp+rename as the
last act of a save) recording, for every file under `step_<n>/` at commit
time, its byte size and a content digest. Orbax's own finalization marker
proves the *write protocol* completed; the manifest proves the *bytes*
that landed are the bytes that were staged — a later bit flip, truncation,
or torn metadata file fails verification instead of poisoning restore.

Digest choice: xxh64 when the `xxhash` wheel is present (the TPU image
bakes it in; ~GB/s, negligible next to the disk read), else stdlib
`zlib.crc32`. The algo is recorded in the manifest, so a store written
under one and read under the other still verifies sizes and fails loudly
on the digest rather than silently passing.

Everything here is epath-aware (Orbax's own path layer) so `gs://` stores
get the same treatment as posix — including the tmp+rename commit, which
on GCS degrades to copy+delete but keeps the invariant that a reader
never observes a half-written manifest under its final name.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, field
from typing import Optional

MANIFEST_NAME = "manifest.json"
MANIFEST_FORMAT = "picotron-ckpt-manifest"
MANIFEST_VERSION = 1

_CHUNK = 1 << 20  # 1 MiB read chunks: streaming, never whole-file in RAM


def _epath(path: str):
    """epath.Path when etils is importable (URL-store support), else None —
    the same arrangement as checkpoint._isdir."""
    try:
        from etils import epath

        return epath.Path(path)
    except ImportError:
        return None


def _open_rb(path: str):
    p = _epath(path)
    return p.open("rb") if p is not None else open(path, "rb")


def digest_algo() -> str:
    try:
        import xxhash  # noqa: F401

        return "xxh64"
    except ImportError:
        return "crc32"


def file_digest(path: str, algo: Optional[str] = None) -> tuple[str, int]:
    """(hexdigest, byte_size) of one file, streaming."""
    algo = algo or digest_algo()
    size = 0
    if algo == "xxh64":
        import xxhash

        h = xxhash.xxh64()
        with _open_rb(path) as f:
            while chunk := f.read(_CHUNK):
                size += len(chunk)
                h.update(chunk)
        return h.hexdigest(), size
    if algo == "crc32":
        crc = 0
        with _open_rb(path) as f:
            while chunk := f.read(_CHUNK):
                size += len(chunk)
                crc = zlib.crc32(chunk, crc)
        return f"{crc & 0xFFFFFFFF:08x}", size
    raise ValueError(f"unknown digest algo {algo!r} (xxh64/crc32)")


def _walk_files(root: str) -> list[str]:
    """Relative (posix-style) paths of every regular file under `root`,
    sorted for a deterministic manifest. Skips the manifest itself and
    in-flight `*.tmp*` names (our own atomic-write staging)."""
    rels: list[str] = []
    ep = _epath(root)
    if ep is not None and "://" in root:
        stack = [ep]
        base = str(ep)
        while stack:
            d = stack.pop()
            for child in d.iterdir():
                if child.is_dir():
                    stack.append(child)
                else:
                    rels.append(os.path.relpath(str(child), base))
    else:
        for dirpath, _dirs, files in os.walk(root):
            for f in files:
                rels.append(
                    os.path.relpath(os.path.join(dirpath, f), root))
    rels = [r.replace(os.sep, "/") for r in rels]
    return sorted(r for r in rels
                  if r != MANIFEST_NAME and ".tmp" not in os.path.basename(r))


def build_manifest(step_dir: str, *, step: int,
                   topology: Optional[dict] = None) -> dict:
    """Hash every committed file under `step_dir` into a manifest dict.
    Runs AFTER the Orbax write is durable (checkpoint._commit) and off the
    step path — the training loop never waits on it."""
    algo = digest_algo()
    files: dict[str, dict] = {}
    total = 0
    for rel in _walk_files(step_dir):
        digest, size = file_digest(os.path.join(step_dir, rel), algo)
        files[rel] = {"bytes": size, "digest": digest}
        total += size
    return {
        "format": MANIFEST_FORMAT,
        "version": MANIFEST_VERSION,
        "step": int(step),
        "algo": algo,
        "file_count": len(files),
        "total_bytes": total,
        "topology": dict(topology or {}),
        "files": files,
    }


def atomic_write_text(path: str, text: str) -> None:
    """Write `text` to `path` via tmp-file + rename, so a crash mid-write
    leaves either the old content or nothing under the final name — never
    a torn file (the meta.json / manifest commit primitive). epath-aware
    for gs:// (rename there is copy+delete; the half-written tmp name is
    still never the final name)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    ep = _epath(tmp)
    if ep is not None and "://" in path:
        ep.write_text(text)
        ep.rename(_epath(path))
        return
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_manifest(step_dir: str, manifest: dict) -> str:
    path = os.path.join(step_dir, MANIFEST_NAME)
    atomic_write_text(path, json.dumps(manifest, indent=1, sort_keys=True))
    return path


def rmtree(path: str) -> None:
    """Recursive delete, epath-first so gs:// step dirs GC too."""
    ep = _epath(path)
    if ep is not None and "://" in path:
        ep.rmtree()
        return
    import shutil

    shutil.rmtree(path)


@dataclass
class VerifyResult:
    """Per-step verification verdict.

    status: "verified" (manifest present, every entry matches),
    "legacy" (no manifest — a pre-lineage checkpoint; meta.json parsed, so
    it stays restorable), or "corrupt" (manifest/meta torn, a listed file
    missing, or bytes/digest mismatch — `failures` names each culprit).
    """

    status: str
    failures: list = field(default_factory=list)
    manifest: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return self.status in ("verified", "legacy")


def _check_meta(step_dir: str, failures: list) -> None:
    """meta.json must parse — the restore path reads it before Orbax ever
    runs, so a torn JSON there poisons resume even when the arrays are
    fine."""
    meta_path = os.path.join(step_dir, "meta.json")
    try:
        with _open_rb(meta_path) as f:
            json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        failures.append("meta.json: missing")
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        failures.append(f"meta.json: torn/invalid JSON ({e})")


def verify_step_dir(step_dir: str, deep: bool = True) -> VerifyResult:
    """Verify one committed step dir against its manifest.

    `deep=False` checks existence + byte sizes only (catches truncation
    and deletion for the cost of a stat walk); `deep=True` additionally
    re-digests every file (catches bit flips). Durability (Orbax
    finalization) is the caller's concern — this judges bytes, not the
    commit protocol.
    """
    man_path = os.path.join(step_dir, MANIFEST_NAME)
    failures: list[str] = []
    try:
        with _open_rb(man_path) as f:
            manifest = json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        # Pre-lineage checkpoint: no manifest was ever written. Durable +
        # parseable meta.json keeps it restorable (upgrades must not
        # orphan existing save_dirs), but it can never rank "verified".
        _check_meta(step_dir, failures)
        return VerifyResult("corrupt" if failures else "legacy", failures)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        return VerifyResult(
            "corrupt", [f"{MANIFEST_NAME}: torn/invalid JSON ({e})"])
    if not isinstance(manifest.get("files"), dict):
        return VerifyResult(
            "corrupt", [f"{MANIFEST_NAME}: malformed (no files map)"],
            manifest)

    algo = manifest.get("algo", "crc32")
    for rel, want in sorted(manifest["files"].items()):
        path = os.path.join(step_dir, rel)
        try:
            if deep:
                digest, size = file_digest(path, algo)
            else:
                ep = _epath(path)
                size = (ep.stat().length if ep is not None and "://" in path
                        else os.path.getsize(path))
                digest = None
        except FileNotFoundError:
            failures.append(f"{rel}: missing")
            continue
        except OSError as e:
            failures.append(f"{rel}: unreadable ({e})")
            continue
        if size != want.get("bytes"):
            failures.append(
                f"{rel}: size {size} != manifest {want.get('bytes')}")
        elif digest is not None and digest != want.get("digest"):
            failures.append(
                f"{rel}: {algo} digest {digest} != manifest "
                f"{want.get('digest')}")
    _check_meta(step_dir, failures)
    return VerifyResult("corrupt" if failures else "verified", failures,
                        manifest)
