"""Checkpoint save-dir preflight: fail at startup, not at the first save.

The trainer's first periodic save can land hours into a run; an
unwritable `save_dir` (typo'd path, read-only mount, a file where the
directory should be) or a nearly-full disk turns that into dead pod time
plus a lost run. This probe runs next to the shardcheck preflight in
train.main — seconds before any compile — and raises with the story.
"""

from __future__ import annotations

import os

from picotron_tpu.config import Config, num_params


def checkpoint_nbytes(cfg: Config) -> int:
    """Estimated on-disk bytes of ONE training checkpoint: fp32 master
    params + both Adam moments (at their configured dtype) + the bf16
    compute copy when optimizer_offload stores one. Orbax adds only
    per-array metadata on top, so this is a tight lower bound — exactly
    what the headroom check needs."""
    n = num_params(cfg.model)
    moment_bytes = 2 if cfg.training.adam_moments_dtype == "bfloat16" else 4
    total = 4 * n + 2 * moment_bytes * n
    if cfg.training.optimizer_offload:
        total += 2 * n  # the device-resident bf16 copy is saved as params
    return total


def preflight_save_dir(cfg: Config) -> int:
    """Validate that `checkpoint.save_dir` can take one checkpoint;
    returns the estimated bytes per checkpoint. Raises RuntimeError with
    a fix-it message when the directory cannot be created/written or the
    filesystem lacks headroom (estimate + 10% slack, x(keep_last or 1)
    retained steps). URL stores (gs://) skip the local probes — quota
    there is the provider's concern and statvfs does not exist."""
    save_dir = cfg.checkpoint.save_dir
    est = checkpoint_nbytes(cfg)
    if "://" in save_dir:
        return est
    try:
        os.makedirs(save_dir, exist_ok=True)
    except OSError as e:
        raise RuntimeError(
            f"checkpoint preflight: save_dir {save_dir!r} cannot be "
            f"created ({e}); fix checkpoint.save_dir before committing "
            f"pod time") from e
    probe = os.path.join(save_dir, f".picotron_writecheck.{os.getpid()}")
    try:
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
    except OSError as e:
        raise RuntimeError(
            f"checkpoint preflight: save_dir {save_dir!r} is not writable "
            f"({e}); the first save would die after the run warmed up"
        ) from e
    import shutil

    retained = max(1, cfg.checkpoint.keep_last)
    need = int(est * 1.1) * retained
    free = shutil.disk_usage(save_dir).free
    if free < need:
        raise RuntimeError(
            f"checkpoint preflight: save_dir {save_dir!r} has "
            f"{free / 1e9:.2f} GB free but one checkpoint is "
            f"~{est / 1e9:.2f} GB ({retained} retained step(s) + 10% "
            f"slack = {need / 1e9:.2f} GB needed); free space or lower "
            f"checkpoint.keep_last")
    return est
