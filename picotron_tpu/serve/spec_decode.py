"""Speculative multi-token decode inside the serving decode scan.

Self-drafting n-gram speculation (prompt lookup, in the spirit of
"Inference with Reference" / vLLM's ngram speculator): each slot keeps a
small rolling window of its own recent tokens on device; per decode
iteration the drafter finds the most recent earlier occurrence of the
trailing bigram inside that window and proposes the `draft_len` tokens
that followed it. One [S, 1 + draft_len] forward pass then plays both
roles at once — it IS the next-token pass the non-speculative scan would
have run (column 0 consumes the real last token), and it verifies the
draft columns for free. The target token is sampled at EVERY position
with the same (request id, token index) key fold as the non-speculative
path, and the longest draft prefix whose tokens match the targets is
accepted.

Because acceptance only decides HOW MANY of the target-sampled tokens
one iteration emits — never WHICH tokens — the emitted stream is
bit-identical to non-speculative decode at any temperature, under any
accept/reject pattern, preemption, or slot reshuffle. The tests pin
this.

Rejected-draft K/V writes are left in place deliberately: the next
iteration (and the next dispatch) always re-writes positions starting at
the first unconfirmed slot before anything reads them, and the causal
mask (`arange(s_max) <= q_pos`) screens positions beyond the query — the
same argument that makes stale slots safe in the contiguous cache.

The whole verify-accept loop runs as ONE jitted program per engine
lifetime (a lax.scan of `decode_interval` iterations), preserving the
compile-once discipline the variant prover audits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from picotron_tpu.config import ModelConfig
from picotron_tpu.generate import _decode_layers
from picotron_tpu.models.llama import compute_dtype, final_hidden, head_weight
from picotron_tpu.serve.engine import _fold_keys
from picotron_tpu.serve.paged_cache import PagedKVCache

# Drafter constants (static — baked into the compiled program).
NGRAM_K = 2    # trailing gram length the drafter matches on
CTX_W = 32     # per-slot rolling context window the drafter searches

# -1 pads empty context slots; real token ids are >= 0, so padding can
# never match a gram and the drafter falls back to repeat-last-token.
CTX_PAD = -1


def max_draft_len() -> int:
    """Largest draft_len the [CTX_W]-wide context can source a
    continuation for (needs >= 1 candidate gram start)."""
    return CTX_W - NGRAM_K


def context_rows(states, slots, num_slots: int):
    """Host-side [num_slots, CTX_W] int32 context buffer for the drafter:
    per live slot, the last CTX_W tokens of prompt + generated,
    left-padded with CTX_PAD. `states[s]` must have .req.prompt and
    .generated for every s in `slots`."""
    import numpy as np

    ctx = np.full((num_slots, CTX_W), CTX_PAD, np.int32)
    for s in slots:
        st = states[s]
        toks = list(st.req.prompt) + list(st.generated)
        tail = toks[-CTX_W:]
        if tail:
            ctx[s, -len(tail):] = tail
    return ctx


def _ngram_draft(ctx, last_tok, draft_len: int):
    """[S, draft_len] draft per slot by prompt lookup: match the trailing
    NGRAM_K-gram of ctx (newest token = last column) against every
    earlier window, take the LAST (most recent) match, and propose the
    tokens that followed it. Slots with no match repeat their last token
    — a draft is only a guess, correctness never depends on it."""
    s, w = ctx.shape
    tail = ctx[:, w - NGRAM_K:]                              # [S, k]
    n_cand = w - NGRAM_K - draft_len + 1
    starts = jnp.arange(n_cand)                              # [n_cand]
    gram_idx = starts[:, None] + jnp.arange(NGRAM_K)[None, :]
    grams = ctx[:, gram_idx]                                 # [S, n_cand, k]
    ok = ((grams >= 0).all(-1)
          & (grams == tail[:, None, :]).all(-1))             # [S, n_cand]
    has = ok.any(-1)
    best = jnp.argmax(jnp.where(ok, starts + 1, 0), axis=-1)
    cont = best[:, None] + NGRAM_K + jnp.arange(draft_len)[None, :]
    draft = jnp.take_along_axis(ctx, cont, axis=1)
    return jnp.where(has[:, None], draft, last_tok[:, None])


def _spec_decode_step_impl(params, k, v, tables, toks, positions, rids,
                           tidx, ctx, base_key, cos, sin,
                           cfg: ModelConfig, temperature: float,
                           top_k: int, interval: int, eos_token_id,
                           draft_len: int):
    """`interval` speculative decode iterations over all slots in ONE
    dispatch. Shapes mirror engine._decode_step_impl with two additions:
    ctx [S, CTX_W] (drafter window) and the ragged outputs — each
    iteration emits between 1 and 1 + draft_len tokens per slot, so
    tokens come back as [S, interval, 1 + draft_len] plus a per-iteration
    valid count [S, interval]; columns past the count are padding the
    host skips. Returns (tokens, n_valid, last, positions, tidx, ctx,
    k, v) — the trailing carries feed the steady-state fast path exactly
    like the non-speculative program."""
    live = positions >= 0
    d1 = draft_len + 1
    offs = jnp.arange(d1)[None, :]                           # [1, 1+d]

    def one(carry, _):
        toks, positions, tidx, ctx, cache, done = carry
        draft = _ngram_draft(ctx, toks, draft_len)           # [S, d]
        seq = jnp.concatenate([toks[:, None], draft], 1)     # [S, 1+d]
        pos = jnp.where(live[:, None], positions[:, None] + offs, -1)
        x = params["embedding"][seq].astype(compute_dtype(cfg))
        x, cache = _decode_layers(params, x, cache, pos, cfg, cos, sin)
        hf = final_hidden(params, x, cfg)                    # [S, 1+d, H]
        logits = (hf @ head_weight(params).astype(hf.dtype)
                  ).astype(jnp.float32)                      # [S, 1+d, V]
        if temperature == 0.0:
            tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            lg = logits / temperature
            if top_k > 0:
                kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
                lg = jnp.where(lg < kth, -jnp.inf, lg)
            # column j's token, if emitted, is output token tidx + j —
            # key it exactly as the non-speculative step would
            keys = jax.vmap(_fold_keys, in_axes=(None, None, 0),
                            out_axes=1)(base_key, rids, (tidx[:, None]
                                                         + offs).T)
            tgt = jax.vmap(jax.vmap(
                lambda l, key: jax.random.categorical(key, l)
            ))(lg, keys).astype(jnp.int32)
        if eos_token_id is not None:
            tgt = jnp.where(done[:, None], eos_token_id, tgt)
        # accept the longest draft prefix matching the targets: draft
        # column j (= seq column j+1) is confirmed iff it equals the
        # target sampled after consuming seq[:, :j+1]
        acc = jnp.cumprod((seq[:, 1:] == tgt[:, :draft_len])
                          .astype(jnp.int32), axis=1)        # [S, d]
        n_acc = acc.sum(axis=1)                              # [S]
        n_emit = n_acc + 1
        if eos_token_id is not None:
            # an EOS inside the emitted window finishes the slot; its
            # remaining iterations emit forced EOS like the non-spec scan
            emitted = offs < n_emit[:, None]
            done = done | ((tgt == eos_token_id) & emitted).any(axis=1)
        new_last = jnp.take_along_axis(tgt, n_acc[:, None], axis=1)[:, 0]
        step = jnp.where(live, n_emit, 0)
        positions = positions + step
        tidx = tidx + step
        # roll the drafter window: drop `step` oldest, append the
        # emitted targets (columns >= n_emit of tgt never enter — the
        # gather below stops at combined column CTX_W + step - 1)
        combined = jnp.concatenate([ctx, tgt], axis=1)       # [S, W+1+d]
        idx = step[:, None] + jnp.arange(ctx.shape[1])[None, :]
        ctx = jnp.take_along_axis(combined, idx, axis=1)
        return ((new_last, positions, tidx, ctx, cache, done),
                (tgt, jnp.where(live, n_emit, 0)))

    cache = PagedKVCache(k, v, tables)
    done = jnp.zeros(toks.shape, bool)
    (last, positions, tidx, ctx, cache, _), (toks_all, n_all) = \
        jax.lax.scan(one, (toks, positions, tidx, ctx, cache, done),
                     None, length=interval)
    # scan stacks along axis 0: [interval, S, ...] -> slot-major
    return (toks_all.transpose(1, 0, 2), n_all.T, last, positions, tidx,
            ctx, cache.k, cache.v)


_SPEC_JITS: dict = {}


def get_spec_jit(donate: bool):
    """Jitted speculative decode step, cached module-level like
    engine._get_jits so repeated engine construction shares one compile
    cache. Donation off-CPU only (CPU ignores it with a warning)."""
    if donate not in _SPEC_JITS:
        dargs = (1, 2) if donate else ()
        _SPEC_JITS[donate] = jax.jit(
            _spec_decode_step_impl, donate_argnums=dargs,
            static_argnames=("cfg", "temperature", "top_k", "interval",
                             "eos_token_id", "draft_len"))
    return _SPEC_JITS[donate]
