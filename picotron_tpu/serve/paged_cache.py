"""Block/paged KV cache for the serving decode path.

The offline `generate.KVCache` pays `batch x max_length` HBM for every
sequence — at serving batch sizes with ragged request lengths most of
that is stranded (a 40-token reply in a 4096-slot row wastes 99% of it).
The paged cache instead allocates fixed-size BLOCKS from one shared pool
and maps each decode slot's logical positions onto physical blocks
through a per-slot block table (the vLLM arrangement, kept deliberately
static-shaped for XLA):

- ``k``/``v``: ``[L, num_blocks, block_size, Hkv, D]`` — the pool.
  Persistent cache HBM scales with ``num_blocks`` actually provisioned,
  not with ``slots x max_length`` (pinned by the pool-accounting test).
- ``tables``: ``[B, max_blocks]`` int32, logical block -> physical block.
  ``num_blocks`` itself is the UNMAPPED sentinel: scatter writes at the
  sentinel drop (``mode="drop"``), gathers clamp into the pool and the
  clamped garbage is masked by the causal mask before anything reads it.

Writes use the same advanced-indexing scatter for decode (one token per
slot, each at its own position) and chunked prefill (a contiguous span of
one slot); positions < 0 (chunk padding) are routed to the sentinel. The
attention view gathers a slot's blocks back into logical order, so
`generate._cached_attention` runs on it unchanged — slot j of the
gathered view holds the token at position j, exactly like the contiguous
cache, which is what makes paged-vs-contiguous greedy parity a
structural property rather than a numerical accident.

`BlockPool` is the host-side allocator: free-list alloc/free with
all-or-nothing semantics and peak accounting, so the scheduler can make
admission/preemption decisions and tests can assert no block leaks
across a full trace.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from picotron_tpu.config import ModelConfig
from picotron_tpu.models.llama import compute_dtype


class PagedKVCache(NamedTuple):
    """Pool-backed cache; same interface as `generate.KVCache`
    (num_layers / write / layer_view) so `generate._decode_layers` is
    cache-agnostic."""

    k: jnp.ndarray       # [L, num_blocks, block_size, Hkv, D]
    v: jnp.ndarray       # [L, num_blocks, block_size, Hkv, D]
    tables: jnp.ndarray  # [B, max_blocks] int32; num_blocks = unmapped

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    def write(self, li, k_new, v_new, q_pos) -> "PagedKVCache":
        """Scatter K/V [B, s, Hkv, D] into each token's (physical block,
        offset) slot of layer li. q_pos: [s] batch-shared or [B, s]
        per-slot global positions; positions < 0, positions beyond the
        table's capacity, and unmapped table entries all resolve to the
        out-of-bounds sentinel and are DROPPED by the scatter."""
        bs = self.block_size
        if q_pos.ndim == 1:
            q_pos = jnp.broadcast_to(q_pos[None, :],
                                     (k_new.shape[0], q_pos.shape[0]))
        blk = jnp.maximum(q_pos, 0) // bs                       # [B, s]
        idx = jnp.minimum(blk, self.tables.shape[1] - 1)
        phys = jnp.take_along_axis(self.tables, idx, axis=1)    # [B, s]
        ok = (q_pos >= 0) & (blk < self.tables.shape[1])
        phys = jnp.where(ok, phys, self.num_blocks)
        off = jnp.maximum(q_pos, 0) % bs
        k = self.k.at[li, phys, off].set(k_new, mode="drop")
        v = self.v.at[li, phys, off].set(v_new, mode="drop")
        return self._replace(k=k, v=v)

    def layer_view(self, li):
        """Gather layer li's blocks back into logical order:
        ([B, max_blocks * block_size, Hkv, D], same) — slot j holds the
        token at position j, identically to the contiguous cache, so the
        shared attention math applies unchanged. Unmapped table entries
        clamp to the last pool block; whatever stale K/V they surface sits
        beyond every live q position and is causally masked. This view is
        a per-layer TRANSIENT inside the layer scan (capacity-sized
        activation), not persistent cache memory."""
        kl = self.k[li]  # [num_blocks, block_size, Hkv, D]
        vl = self.v[li]
        b, mb = self.tables.shape
        shape = (b, mb * self.block_size) + kl.shape[2:]
        return (kl[self.tables].reshape(shape),
                vl[self.tables].reshape(shape))


def init_paged_cache(cfg: ModelConfig, num_blocks: int, block_size: int,
                     num_slots: int, max_blocks: int) -> PagedKVCache:
    """Zeroed pool + all-unmapped tables. Pool memory is
    L * num_blocks * block_size * Hkv * D * 2 tensors — sized by the
    blocks provisioned, independent of num_slots * max_length."""
    shape = (cfg.num_hidden_layers, num_blocks, block_size,
             cfg.num_key_value_heads, cfg.head_dim)
    dt = compute_dtype(cfg)
    tables = jnp.full((num_slots, max_blocks), num_blocks, jnp.int32)
    return PagedKVCache(jnp.zeros(shape, dt), jnp.zeros(shape, dt), tables)


class BlockPool:
    """Host-side free-list allocator over the physical blocks.

    All-or-nothing `alloc(n)` (a partially-allocated sequence could never
    run and would strand blocks), LIFO reuse (freshly-freed blocks are the
    ones whose stale contents the causal mask already screens), and peak
    accounting for the pool-utilization telemetry."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, -1, -1))
        self.peak_in_use = 0

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.num_blocks - len(self._free)

    def alloc(self, n: int) -> Optional[list]:
        """n physical block ids, or None (and no state change) when the
        pool cannot cover all n."""
        if n < 0:
            raise ValueError(f"alloc count must be >= 0, got {n}")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return out

    def free(self, blocks) -> None:
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(f"freeing unknown block {b}")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)
