"""Fleet serving: a supervisor fronting N engine replicas, built
robustness-first — engine death, hung dispatches, and overload bursts
are routine, chaos-tested events, not crashes.

`FleetSupervisor` owns N `ServeEngine` / `DisaggServeEngine` replicas,
each pinned to its own device (round-robin over `jax.devices()` — on
CPU the conftest's simulated devices, so the tests exercise REAL
multi-engine placement) with its own KV block pool and its OWN copy of
the params, but the SAME base sampling key. Requests flow through a
fleet-global FIFO: arrivals route to the least-loaded live engine;
everything after that is the single-engine machinery unchanged.

Four robustness mechanisms, layered on the PR-7 scheduler invariants:

- **Health + hang detection.** Every dispatch heartbeats the
  `resilience/watchdog.py` machinery with a phase naming the live
  ``serve engine=K dispatch=decode|prefill``, so a hung dispatch is
  reported as THAT dispatch. The supervisor arms its own watchdog
  (``watchdog_timeout``) with postmortem reason ``serve_hang`` — a
  stall dumps the flightdeck window and exits 77 for the supervisor
  wrapper, exactly like a wedged training collective.
- **Failover re-dispatch.** `kill_engine(k)` (or the chaos kind
  ``engine_dead@REQ``) marks a replica dead ABRUPTLY: its pool, cache,
  and device state are discarded wholesale — nothing graceful, the
  in-process analogue of SIGKILLing the replica. Its in-flight requests
  (generated tokens intact) requeue at the FRONT of the survivors'
  queues and recompute via the preemption path. Sampling keys fold
  (request id, token index), so the re-dispatched continuation is
  bit-identical at any temperature to a fault-free run — the parity pin
  of every failover test. Survivor pools must show zero leaked blocks.
- **Deadline admission + load shedding.** Requests carry `deadline_ms`
  (or inherit `serve.deadline_ms`); a request still queued when its
  wait exceeds the deadline is SHED at the admission attempt —
  rejected, `serve_shed` event, queue seconds booked to the `shed`
  ledger category (badput), excluded from goodput. The decision runs on
  the fleet's VIRTUAL trace clock (`tick_s` per fleet iteration), so
  the shed set is a deterministic function of the trace — pinned by the
  overload tests, order-invariant like the PR-7 sampling tests.
- **Graceful drain.** `drain(k)` stops routing to one engine, lets its
  residents finish (bounded by `serve.drain_grace_s` on the trace
  clock, after which they are re-dispatched to survivors), then retires
  it with a `serve_drain` event and an empty pool — the redeploy /
  autoscale primitive.

Chaos: the fleet loop fires the request-indexed points ``serve_route``
(per routed request: ``engine_dead@REQ``, ``shed_storm@REQ``) and
``serve_dispatch`` (per resident request per decode dispatch:
``engine_dead@REQ``, ``decode_hang@REQ~SECS``); `tools/chaos.py
--scenario serve_engine_dead / serve_overload` drive the end-to-end
recovery scenarios via ``bench.py --serve --fleet N --chaos``.
"""

from __future__ import annotations

import time
from typing import Optional

import jax

from picotron_tpu.config import ModelConfig, ServeConfig
from picotron_tpu.resilience import chaos, watchdog
from picotron_tpu.resilience.watchdog import Watchdog
from picotron_tpu.serve.disagg import DisaggServeEngine
from picotron_tpu.serve.engine import ServeEngine
from picotron_tpu.serve.scheduler import Request
from picotron_tpu.telemetry import Telemetry


class FleetSupervisor:
    """Route requests across N engine replicas; survive the loss of
    N - 1 of them. Drives engines through their public step() with a
    virtual trace clock (`tick_s` seconds per fleet iteration), so
    every routing, shedding, and failover decision is a deterministic
    function of the trace — the property all the parity tests lean on."""

    def __init__(self, params, model_cfg: ModelConfig,
                 serve_cfg: Optional[ServeConfig] = None, *,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 telemetry: Optional[Telemetry] = None,
                 tick_s: float = 0.001, watchdog_timeout: float = 0.0,
                 watchdog_on_timeout=None):
        scfg = serve_cfg or ServeConfig()
        scfg.validate()
        if scfg.speculator != "off":
            raise ValueError(
                "serve.fleet_size > 1 does not support speculative decode "
                "(serve.speculator != 'off'): the drafter's context is "
                "engine-local and is not carried across failover "
                "re-dispatch; set serve.speculator='off' or "
                "serve.fleet_size=1")
        self.scfg = scfg
        self.n = max(int(scfg.fleet_size), 1)
        self.tick_s = float(tick_s)

        self._owns_telemetry = telemetry is None
        self.telemetry = telemetry or Telemetry(sinks=[])

        # Per-replica placement: engine k lives wholly on device
        # k % len(devices) — its params copy, KV pool, rope tables, and
        # key all committed there, so "discard the engine" is a real
        # statement about device state, not bookkeeping. tp-sharded
        # (NamedSharding) params collapse every replica onto the shared
        # mesh — the fleet still routes, only physical separation goes.
        from jax.sharding import NamedSharding, SingleDeviceSharding
        mesh_sharded = any(
            isinstance(getattr(leaf, "sharding", None), NamedSharding)
            for leaf in jax.tree.leaves(params))
        devices = jax.devices()
        self.engines: list = []
        for k in range(self.n):
            if scfg.disagg:
                import dataclasses
                dev_d = (2 * k) % len(devices)
                dev_p = (2 * k + 1) % len(devices)
                ecfg = dataclasses.replace(
                    scfg, decode_device=dev_d, prefill_device=dev_p)
                eng = DisaggServeEngine(
                    params, model_cfg, ecfg, eos_token_id=eos_token_id,
                    temperature=temperature, top_k=top_k, seed=seed,
                    telemetry=self.telemetry, engine_id=k)
            else:
                dev = devices[k % len(devices)]
                # re-commit even already-committed params: a replica must
                # hold its OWN copy on its OWN device or failover would
                # discard state it shares with survivors
                p_k = (params if mesh_sharded
                       else jax.device_put(params, SingleDeviceSharding(dev)))
                eng = ServeEngine(
                    p_k, model_cfg, scfg, eos_token_id=eos_token_id,
                    temperature=temperature, top_k=top_k, seed=seed,
                    telemetry=self.telemetry,
                    device=None if mesh_sharded else dev, engine_id=k)
            self.engines.append(eng)

        self.alive = [True] * self.n
        self.draining: dict = {}   # engine -> drain start (trace clock)
        self.drained: list = []    # engines retired via drain
        self.pending: list = []    # fleet queue: RequestStates, FIFO by
        #                            (arrival, id) — kept sorted so
        #                            submission order cannot matter
        self.shed_results: list = []
        self.n_shed_fleet = 0
        self.n_redispatched = 0
        self.n_engines_dead = 0
        self._next_auto_id = 0
        self.now = 0.0             # virtual trace clock
        self.summary: Optional[dict] = None

        self.watchdog = (Watchdog(watchdog_timeout,
                                  on_timeout=watchdog_on_timeout,
                                  reason="serve_hang")
                         if watchdog_timeout > 0 else None)

    # -- intake ------------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               req_id: Optional[int] = None, arrival: float = 0.0,
               deadline_ms: Optional[float] = None) -> int:
        """Enqueue one request fleet-wide. Request ids are FLEET-global
        — they seed the sampling-key fold, so a request must keep its id
        across engines (that is the whole failover-parity mechanism).
        `deadline_ms` defaults to serve.deadline_ms when unset (0 = no
        deadline)."""
        if req_id is None:
            req_id = self._next_auto_id
        self._next_auto_id = max(self._next_auto_id, req_id + 1)
        if deadline_ms is None and self.scfg.deadline_ms > 0:
            deadline_ms = self.scfg.deadline_ms
        req = Request(req_id, tuple(prompt), max_new_tokens, arrival,
                      deadline_ms)
        # capacity validation through a live scheduler (same limits on
        # every replica): submit appends a fresh RequestState after the
        # never-servable checks, which we pop straight into the fleet
        # queue — one validation code path, zero duplication
        ref = self.engines[0].sched
        ref.submit(req)
        st = ref.queue.pop()
        self.pending.append(st)
        self.pending.sort(key=lambda s: (s.req.arrival, s.req.id))
        return req_id

    # -- engine lifecycle --------------------------------------------------

    def _routable(self) -> list:
        return [k for k in range((self.n))
                if self.alive[k] and k not in self.draining]

    def _survivors(self) -> list:
        return self._routable() or [k for k in range(self.n)
                                    if self.alive[k]]

    def _load(self, k: int) -> int:
        s = self.engines[k].sched
        n = len(s.queue) + sum(x is not None for x in s.slots)
        n += sum(x is not None for x in getattr(s, "pslots", ()))
        return n

    def _displace(self, k: int, free_blocks: bool) -> list:
        """Pull every in-flight request out of engine k, oldest-admitted
        first, queued requests behind them, reset for recompute. With
        free_blocks (graceful drain) the blocks return to the engine's
        pool; without (abrupt death) the pool is discarded wholesale —
        freeing into a dead engine's pool would only launder the leak
        accounting the tests pin on SURVIVOR pools."""
        eng = self.engines[k]
        sched = eng.sched
        resident = []  # (state, owning pool) — disagg pslot blocks live
        #                in the prefill pool, decode-slot blocks in pool
        for i, s in enumerate(sched.slots):
            if s is not None:
                resident.append((s, eng.pool))
                sched.slots[i] = None
        pslots = getattr(sched, "pslots", None)
        if pslots is not None:
            for i, s in enumerate(pslots):
                if s is not None:
                    resident.append((s, eng.pool_p))
                    pslots[i] = None
        resident.sort(key=lambda sp: sp[0].admit_seq)
        if free_blocks:
            for st, pool in resident:
                if st.blocks:
                    pool.free(st.blocks)
        sts = [sp[0] for sp in resident] + list(sched.queue)
        sched.queue.clear()
        for st in sts:
            st.blocks = []
            st.n_prefilled = 0
            st.prefill_ids = ()
        eng._decode_state = None
        return sts

    def _redispatch(self, sts: list, survivors: list, from_engine: int,
                    now: float) -> int:
        """Requeue displaced requests at the FRONT of the survivors'
        queues (round-robin, relative order preserved): they carry their
        generated tokens and recompute via the preemption path, so the
        continuation is bit-identical — arrival priority and token
        stream both survive the engine that did not."""
        if not survivors:
            raise RuntimeError(
                "fleet: no surviving engines to re-dispatch onto — the "
                "whole fleet is dead")
        per: dict = {k: [] for k in survivors}
        for i, st in enumerate(sts):
            per[survivors[i % len(survivors)]].append(st)
        for k, lst in per.items():
            if not lst:
                continue
            # extendleft(reversed(...)) puts lst[0] leftmost: oldest at
            # the very front, exactly the preemption requeue discipline
            self.engines[k].sched.queue.extendleft(reversed(lst))
            for st in lst:
                self.n_redispatched += 1
                self.telemetry.emit(
                    "serve_redispatch", id=st.req.id,
                    from_engine=from_engine, to_engine=k,
                    tokens=len(st.generated))
        return len(sts)

    def kill_engine(self, k: int, cause: str = "dead") -> int:
        """Abrupt replica death (the SIGKILL analogue): state discarded
        wholesale, in-flight requests re-dispatched onto survivors.
        Returns the number of requests re-dispatched."""
        if not self.alive[k]:
            return 0
        self.alive[k] = False
        self.draining.pop(k, None)
        self.n_engines_dead += 1
        sts = self._displace(k, free_blocks=False)
        self.telemetry.emit("serve_engine_dead", engine=k, cause=cause,
                            inflight=len(sts))
        flight = getattr(self.telemetry, "flight", None)
        if flight is not None:
            flight.dump("serve_engine_dead", engine=k, cause=cause,
                        inflight=len(sts))
        if not any(self.alive):
            raise RuntimeError(
                f"fleet: engine {k} died ({cause}) and no replicas "
                f"survive — nothing left to re-dispatch "
                f"{len(sts)} in-flight request(s) onto")
        if sts:
            self._redispatch(sts, self._survivors(), k, now=self.now)
        return len(sts)

    def drain(self, k: int) -> None:
        """Stop routing new work to engine k; let residents finish
        (bounded by serve.drain_grace_s on the trace clock, then they
        re-dispatch to survivors); the engine retires once empty. The
        redeploy/autoscale primitive."""
        if not self.alive[k]:
            raise ValueError(f"fleet: engine {k} is not alive")
        others = [j for j in range(self.n)
                  if j != k and self.alive[j] and j not in self.draining]
        if not others:
            raise ValueError(
                f"fleet: cannot drain engine {k} — it is the last "
                f"routable replica")
        self.draining.setdefault(k, self.now)

    def _drain_tick(self, now: float) -> None:
        for k in list(self.draining):
            eng = self.engines[k]
            start = self.draining[k]
            moved = 0
            if eng.sched.has_work():
                if now - start <= self.scfg.drain_grace_s:
                    continue  # still inside the grace window
                sts = self._displace(k, free_blocks=True)
                moved = self._redispatch(sts, self._survivors(), k, now)
            # empty (or forcibly emptied): retire
            self.draining.pop(k)
            self.alive[k] = False
            self.drained.append(k)
            self.telemetry.emit(
                "serve_drain", engine=k, redispatched=moved,
                drain_s=round(now - start, 6),
                pool_in_use=eng.pool.in_use)

    # -- routing -----------------------------------------------------------

    def _shed(self, st, now: float, forced: bool = False) -> None:
        wait = max(now - st.req.arrival, 0.0)
        self.n_shed_fleet += 1
        self.shed_results.append(
            {"id": st.req.id, "prompt_len": len(st.req.prompt),
             "queue_wait_s": wait, "deadline_ms": st.req.deadline_ms,
             "shed": True})
        self.telemetry.emit("serve_shed", category="shed", secs=wait,
                            id=st.req.id, deadline_ms=st.req.deadline_ms,
                            queue_wait_s=round(wait, 6), forced=forced)

    def _route_pending(self, now: float) -> None:
        """Send fleet-queued requests to the least-loaded routable
        engine (ties break on the lowest id — deterministic), head of
        line first. Heads past their deadline shed here; the rest of
        the deadline policy lives in each engine's scheduler, on the
        same virtual clock."""
        while self.pending:
            st = self.pending[0]
            dl = st.req.deadline_ms
            if dl is not None and (now - st.req.arrival) * 1e3 > dl:
                self.pending.pop(0)
                self._shed(st, now)
                continue
            cands = self._routable()
            if not cands:
                if not any(self.alive):
                    raise RuntimeError(
                        "fleet: requests pending but every engine is dead")
                break
            k = min(cands, key=lambda j: (self._load(j), j))
            try:
                chaos.fire("serve_route", st.req.id, engine=k)
            except chaos.ChaosEngineDead:
                self.kill_engine(k, cause="chaos engine_dead")
                continue  # head stays; re-route to a survivor next pass
            except chaos.ChaosShed:
                self.pending.pop(0)
                self._shed(st, now, forced=True)
                continue
            self.pending.pop(0)
            self.engines[k].sched.queue.append(st)

    def _step_engine(self, k: int, now: float) -> bool:
        eng = self.engines[k]
        if not eng.sched.has_work():
            return False
        if watchdog.active():
            watchdog.touch(f"serve engine={k} dispatch=decode")
        if chaos.controller().active:
            try:
                for s in eng.sched.slots:
                    if s is not None:
                        chaos.fire("serve_dispatch", s.req.id, engine=k)
            except chaos.ChaosEngineDead:
                self.kill_engine(k, cause="chaos engine_dead")
                return False
        return eng.step(now)

    # -- the fleet loop ----------------------------------------------------

    def has_work(self) -> bool:
        return bool(self.pending) or any(
            self.alive[k] and self.engines[k].sched.has_work()
            for k in range(self.n))

    def tick(self, now: Optional[float] = None) -> bool:
        """One fleet iteration: route, step every live engine, progress
        drains, advance the virtual clock by tick_s."""
        if now is not None:
            self.now = now
        self._route_pending(self.now)
        worked = False
        for k in range(self.n):
            if self.alive[k]:
                worked = self._step_engine(k, self.now) or worked
        self._drain_tick(self.now)
        self.now += self.tick_s
        return worked

    def run(self, requests=(), max_ticks: int = 2_000_000) -> list:
        """Drive a whole trace of (prompt, max_new_tokens[, arrival[,
        deadline_ms]]) tuples against the virtual clock. Returns result
        dicts for every request that FINISHED, sorted by id; shed
        requests land in `self.shed_results`."""
        arrivals = sorted((tuple(r) for r in requests),
                          key=lambda r: r[2] if len(r) > 2 else 0.0)
        wall_t0 = time.perf_counter()
        if self.watchdog is not None:
            self.watchdog.start()
        ticks = 0
        try:
            while arrivals or self.has_work() or self.draining:
                while arrivals and (arrivals[0][2] if len(arrivals[0]) > 2
                                    else 0.0) <= self.now:
                    r = arrivals.pop(0)
                    self.submit(r[0], r[1],
                                arrival=r[2] if len(r) > 2 else 0.0,
                                deadline_ms=r[3] if len(r) > 3 else None)
                if (arrivals and not self.has_work()
                        and not self.draining):
                    # idle: jump the virtual clock to the next arrival
                    self.now = max(self.now,
                                   arrivals[0][2] if len(arrivals[0]) > 2
                                   else 0.0)
                    continue
                self.tick()
                ticks += 1
                if ticks > max_ticks:
                    raise RuntimeError(
                        f"fleet: no convergence after {max_ticks} ticks "
                        f"— a request cannot finish (wedged engine?)")
        finally:
            if self.watchdog is not None:
                self.watchdog.stop()
        self._emit_summary(time.perf_counter() - wall_t0)
        return self.results

    # -- results / summary -------------------------------------------------

    @property
    def results(self) -> list:
        out = []
        for eng in self.engines:
            out.extend(eng.results)
        return sorted(out, key=lambda r: r["id"])

    @property
    def all_shed(self) -> list:
        out = list(self.shed_results)
        for eng in self.engines:
            out.extend(eng.shed_results)
        return sorted(out, key=lambda r: r["id"])

    def leaked_blocks(self) -> int:
        """Blocks still held across every LIVING pool after a drained
        trace — dead engines' pools were discarded wholesale and do not
        count (that is the failover contract). Must be zero."""
        total = 0
        for k, eng in enumerate(self.engines):
            if not self.alive[k] and k not in self.drained:
                continue  # died abruptly: pool discarded, not leaked
            total += eng.pool.in_use
            pool_p = getattr(eng, "pool_p", None)
            if pool_p is not None:
                total += pool_p.in_use
        return total

    def _emit_summary(self, wall: float) -> None:
        reg = self.telemetry.registry
        ttft = reg.histogram("serve/ttft")
        qw = reg.histogram("serve/queue_wait")
        results = self.results
        shed = self.all_shed
        per_engine = []
        for k, eng in enumerate(self.engines):
            per_engine.append({
                "engine": k,
                "alive": self.alive[k],
                "drained": k in self.drained,
                "requests": len(eng.results),
                "shed": eng.sched.n_shed,
                "decode_steps": eng.stats["decode_steps"],
                "preemptions": eng.sched.n_preempted,
                "pool_in_use": eng.pool.in_use,
                "pool_peak_utilization": round(
                    eng.pool.peak_in_use / eng.num_blocks, 4),
            })
        self.summary = {
            "fleet_size": self.n,
            "requests": len(results),
            "shed": len(shed),
            "redispatched": self.n_redispatched,
            "engines_dead": self.n_engines_dead,
            "drains": len(self.drained),
            "leaked_blocks": self.leaked_blocks(),
            "output_tokens": sum(r["output_tokens"] for r in results),
            "wall_s": round(wall, 6),
            "ttft_p50_s": ttft.p50, "ttft_p95_s": ttft.p95,
            "queue_wait_p50_s": qw.p50, "queue_wait_p95_s": qw.p95,
            "decode_steps": sum(e.stats["decode_steps"]
                                for e in self.engines),
            "decode_compiles": sum(e.stats["decode_compiles"]
                                   for e in self.engines),
            "preemptions": sum(e.sched.n_preempted for e in self.engines),
            "per_engine": per_engine,
        }
        self.telemetry.emit("serve_summary", **self.summary)

    def close(self) -> None:
        if self._owns_telemetry:
            self.telemetry.close()
