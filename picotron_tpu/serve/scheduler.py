"""Continuous-batching scheduler: request queue, slot lifecycle,
block-budgeted admission and preemption. Pure host logic — no jax — so
every policy decision is unit-testable without touching a device.

Lifecycle: submitted requests wait in a FIFO queue; admission takes the
HEAD request whenever a decode slot is free AND the block pool can cover
its whole prefix (head-of-line, no skipping — a short request can never
starve a long one that arrived first). An admitted request prefills in
chunks (the engine interleaves one chunk per decode step so a long
prompt cannot stall in-flight decodes), then decodes one token per
engine step until EOS or its token budget retires it — the slot and its
blocks return to the pool and the next queued request is admitted into
the still-running decode batch. That refill is the whole point of
continuous batching: finished slots stop idling until the batch drains.

Preemption: decode allocates blocks lazily (one whenever a sequence
crosses a block boundary). When the pool is empty the YOUNGEST live
request is preempted — its blocks are freed and it is requeued at the
FRONT with its generated tokens folded into the prefill prefix
(vLLM-style recompute: no tokens are lost, and because sampling keys are
derived from (request id, token index) the continuation is
token-identical to an uninterrupted run). Preempting youngest-first
means the oldest request always makes progress, so the system cannot
livelock; a single request that cannot fit the pool alone is a
configuration error and raises.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class Request:
    """One generation request. `arrival` is seconds on the trace clock
    (bench.py --serve replays synthetic arrival times against it).
    `deadline_ms`, when set, is an ADMISSION deadline: a request still
    queued once its wait exceeds it is shed (rejected, never run) rather
    than admitted late — the load-shedding contract that keeps an
    overload burst from degrading every admitted request's TTFT. None =
    wait forever (the pre-fleet behavior)."""

    id: int
    prompt: tuple
    max_new_tokens: int
    arrival: float = 0.0
    deadline_ms: Optional[float] = None

    def __post_init__(self):
        object.__setattr__(self, "prompt", tuple(int(t) for t in self.prompt))
        if not self.prompt:
            raise ValueError(f"request {self.id}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.id}: max_new_tokens must be >= 1")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                f"request {self.id}: deadline_ms must be > 0 (None = no "
                f"deadline), got {self.deadline_ms}")


@dataclass
class RequestState:
    """Queue/slot-resident mutable state. `generated` survives preemption
    (recompute folds it into the next prefill prefix)."""

    req: Request
    generated: list = field(default_factory=list)
    prefill_ids: tuple = ()   # snapshot at admission: prompt + generated
    n_prefilled: int = 0
    blocks: list = field(default_factory=list)
    admit_seq: int = -1
    t_admit: Optional[float] = None
    t_first_token: Optional[float] = None
    n_preempted: int = 0

    @property
    def prefilling(self) -> bool:
        return self.n_prefilled < len(self.prefill_ids)

    @property
    def write_pos(self) -> int:
        """Global position of the newest generated token (where the next
        decode step writes its K/V)."""
        return len(self.req.prompt) + len(self.generated) - 1

    @property
    def last_token(self) -> int:
        return self.generated[-1]


def blocks_for(n_tokens: int, block_size: int) -> int:
    return -(-n_tokens // block_size)


class Scheduler:
    def __init__(self, num_slots: int, pool, block_size: int,
                 max_blocks: int):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = num_slots
        self.pool = pool
        self.block_size = block_size
        self.max_blocks = max_blocks
        self.queue: deque = deque()
        self.slots: list = [None] * num_slots
        self._admit_seq = 0
        self.n_admitted = 0
        self.n_preempted = 0
        self.n_retired = 0
        self.n_shed = 0
        self.n_cancelled = 0
        # shed-but-not-yet-reported states; the engine drains this after
        # each admit() and emits the serve_shed telemetry per entry
        self.shed: list = []

    # -- intake ------------------------------------------------------------

    def submit(self, req: Request) -> None:
        """Reject-at-submit anything that could NEVER run: a request whose
        full prefix + budget exceeds per-slot capacity or the whole pool
        would otherwise deadlock admission forever."""
        need = blocks_for(len(req.prompt) + req.max_new_tokens,
                          self.block_size)
        if need > self.max_blocks:
            raise ValueError(
                f"request {req.id}: {len(req.prompt)} prompt + "
                f"{req.max_new_tokens} new tokens needs {need} blocks, "
                f"over the per-slot table capacity ({self.max_blocks}); "
                f"raise serve.max_model_len")
        if need > self.pool.num_blocks:
            raise ValueError(
                f"request {req.id}: needs {need} blocks but the whole "
                f"pool holds {self.pool.num_blocks}; raise "
                f"serve.num_blocks")
        self.queue.append(RequestState(req))

    def has_work(self) -> bool:
        return bool(self.queue) or any(s is not None for s in self.slots)

    # -- admission ---------------------------------------------------------

    def _shed_expired_head(self, now: float) -> bool:
        """Deadline admission: a head whose queue-wait already exceeds
        its deadline is REJECTED (popped into `self.shed`, never run) —
        decided here, at the admission attempt, so the shed set is a
        pure function of the trace clock and the queue order (no wall
        time, no races: the determinism the overload tests pin). Only
        the head is examined — head-of-line FIFO discipline holds for
        shedding exactly as it does for admission."""
        st = self.queue[0]
        dl = st.req.deadline_ms
        if dl is None or (now - st.req.arrival) * 1e3 <= dl:
            return False
        self.queue.popleft()
        self.shed.append(st)
        self.n_shed += 1
        return True

    def drain_shed(self) -> list:
        out, self.shed = self.shed, []
        return out

    def admit(self, now: float = 0.0) -> list:
        """Head-of-line FIFO admission while a slot is free and the pool
        covers the head's whole prefill prefix. Returns the (slot_index,
        RequestState) pairs admitted this call. Heads past their
        deadline are shed (even when every slot is busy — the queue must
        not back up behind the already-dead)."""
        out = []
        while self.queue:
            if self._shed_expired_head(now):
                continue
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free:
                break
            st = self.queue[0]
            st.prefill_ids = st.req.prompt + tuple(st.generated)
            blocks = self.pool.alloc(
                blocks_for(len(st.prefill_ids), self.block_size))
            if blocks is None:
                break
            self.queue.popleft()
            st.blocks = blocks
            st.n_prefilled = 0
            st.admit_seq = self._admit_seq
            st.t_admit = now
            self._admit_seq += 1
            self.n_admitted += 1
            slot = free[0]
            self.slots[slot] = st
            out.append((slot, st))
        return out

    # -- prefill -----------------------------------------------------------

    def prefill_slots(self) -> list:
        """Every slot still prefilling, oldest-admitted first — the
        engine batches one chunk of each into a single dispatch per
        iteration."""
        cands = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                 if s is not None and s.prefilling]
        return [i for _, i in sorted(cands)]

    def note_prefilled(self, slot: int, n_tokens: int) -> None:
        st = self.slots[slot]
        st.n_prefilled = min(st.n_prefilled + n_tokens,
                             len(st.prefill_ids))

    # -- decode ------------------------------------------------------------

    def decode_ready(self) -> list:
        """Slot indices with a completed prefill (>= 1 generated token)
        and budget left, oldest-admitted first — the order block
        allocation (and therefore preemption pressure) is applied in."""
        cands = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                 if s is not None and not s.prefilling and s.generated]
        return [i for _, i in sorted(cands)]

    def ensure_block(self, slot: int, horizon: int = 1):
        """Make sure the blocks holding positions write_pos ..
        write_pos + horizon - 1 (the K/V slots the next decode dispatch
        writes — horizon = the engine's decode interval) are mapped,
        preempting youngest-first until the allocation fits. Returns
        (ok, preempted_slot_indices); ok=False means this slot itself was
        the youngest and got preempted — skip its decode this round."""
        preempted = []
        st = self.slots[slot]
        # clamp to table capacity: interval padding past a request's
        # budget may point beyond max_model_len — those writes sentinel-
        # drop in the cache, and must not demand unallocatable blocks
        need_upto = min(blocks_for(st.write_pos + horizon, self.block_size),
                        self.max_blocks)
        while len(st.blocks) < need_upto:
            got = self.pool.alloc(1)
            if got is not None:
                st.blocks.extend(got)
                continue
            live = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                    if s is not None]
            if len(live) <= 1:
                raise RuntimeError(
                    f"block pool exhausted with a single live request "
                    f"(id {st.req.id}): serve.num_blocks "
                    f"({self.pool.num_blocks}) cannot hold one sequence; "
                    f"raise it")
            victim = max(live)[1]  # youngest admitted
            preempted.append(victim)
            self._preempt(victim)
            if victim == slot:
                return False, preempted
        return True, preempted

    def _preempt(self, slot: int) -> None:
        st = self.slots[slot]
        self.pool.free(st.blocks)
        st.blocks = []
        st.n_prefilled = 0
        st.prefill_ids = ()
        st.n_preempted += 1
        self.slots[slot] = None
        self.queue.appendleft(st)  # front: it keeps its arrival priority
        self.n_preempted += 1

    # -- cancellation ------------------------------------------------------

    def cancel(self, request_id: int):
        """Abandon a request wherever it lives — decode slot or queue —
        freeing any blocks it holds straight back to the pool (the
        no-leak contract: before this existed the only way to drop a
        request was engine teardown). Returns ("slot", index, state) or
        ("queue", None, state), or None when the id is unknown (already
        retired, shed, or never submitted)."""
        for i, s in enumerate(self.slots):
            if s is not None and s.req.id == request_id:
                self.pool.free(s.blocks)
                s.blocks = []
                self.slots[i] = None
                self.n_cancelled += 1
                return "slot", i, s
        for s in list(self.queue):
            if s.req.id == request_id:
                self.queue.remove(s)
                if s.blocks:  # queued states hold no blocks; defensive
                    self.pool.free(s.blocks)
                    s.blocks = []
                self.n_cancelled += 1
                return "queue", None, s
        return None

    # -- retirement --------------------------------------------------------

    def should_retire(self, slot: int, eos_token_id: Optional[int]) -> bool:
        st = self.slots[slot]
        return (len(st.generated) >= st.req.max_new_tokens
                or (eos_token_id is not None
                    and st.last_token == eos_token_id))

    def retire(self, slot: int) -> RequestState:
        st = self.slots[slot]
        self.pool.free(st.blocks)
        st.blocks = []
        self.slots[slot] = None
        self.n_retired += 1
        return st


class DisaggScheduler:
    """Scheduler for the disaggregated engine (serve/disagg.py): a
    PREFILL slot set backed by its own block pool, a DECODE slot set
    backed by another, and a handoff boundary between them.

    Lifecycle: queue -> prefill slot (chunked prefill against the
    prefill pool) -> handoff-ready (prefill complete, first token
    sampled) -> handoff (decode-pool blocks allocated, K/V copied by the
    engine, prefill blocks + slot freed) -> decode slot -> retirement.
    Admission is the same head-of-line FIFO as the colocated scheduler,
    but budgeted against the PREFILL pool and gated on a free PREFILL
    slot — which is the whole point: a burst of long prompts saturates
    the prefill side and leaves decode slots untouched.

    Preemption stays youngest-first ACROSS the handoff boundary: decode
    block growth preempts the youngest decode resident (as before), and
    a handoff candidate that cannot get a decode slot/blocks may preempt
    decode residents STRICTLY YOUNGER than itself — so the oldest
    request always makes progress whether it is decoding or waiting at
    the boundary, and the no-livelock argument carries over. Preempted
    requests requeue at the front and recompute through the prefill pool
    (generated tokens fold into the prefix; the (request id, token
    index) key fold keeps the continuation token-identical)."""

    def __init__(self, prefill_slots: int, decode_slots: int,
                 prefill_pool, decode_pool, block_size: int,
                 max_blocks: int):
        if prefill_slots < 1 or decode_slots < 1:
            raise ValueError(
                f"prefill_slots and decode_slots must be >= 1, got "
                f"{prefill_slots}/{decode_slots}")
        self.num_pslots = prefill_slots
        self.num_slots = decode_slots
        self.prefill_pool = prefill_pool
        self.pool = decode_pool  # name-compatible with Scheduler users
        self.block_size = block_size
        self.max_blocks = max_blocks
        self.queue: deque = deque()
        self.pslots: list = [None] * prefill_slots
        self.slots: list = [None] * decode_slots
        self._admit_seq = 0
        self.n_admitted = 0
        self.n_preempted = 0
        self.n_retired = 0
        self.n_handoffs = 0
        self.n_shed = 0
        self.n_cancelled = 0
        self.shed: list = []

    # deadline shedding is a queue-head policy, identical on both sides
    # of the disaggregation split — share the colocated implementation
    _shed_expired_head = Scheduler._shed_expired_head
    drain_shed = Scheduler.drain_shed

    def cancel(self, request_id: int):
        """Scheduler.cancel plus the prefill side: a request caught
        mid-prefill frees back to the PREFILL pool."""
        for i, s in enumerate(self.pslots):
            if s is not None and s.req.id == request_id:
                self.prefill_pool.free(s.blocks)
                s.blocks = []
                self.pslots[i] = None
                self.n_cancelled += 1
                return "pslot", i, s
        return Scheduler.cancel(self, request_id)

    # -- intake ------------------------------------------------------------

    def submit(self, req: Request) -> None:
        """Reject anything that could NEVER be served: the prefill pool
        must hold the whole prefix, the decode pool the prefix plus the
        token budget (each bounded by per-slot table capacity)."""
        prefix = blocks_for(len(req.prompt) + req.max_new_tokens - 1,
                            self.block_size)
        need = blocks_for(len(req.prompt) + req.max_new_tokens,
                          self.block_size)
        if need > self.max_blocks:
            raise ValueError(
                f"request {req.id}: {len(req.prompt)} prompt + "
                f"{req.max_new_tokens} new tokens needs {need} blocks, "
                f"over the per-slot table capacity ({self.max_blocks}); "
                f"raise serve.max_model_len")
        if prefix > self.prefill_pool.num_blocks:
            raise ValueError(
                f"request {req.id}: prefix needs {prefix} blocks but the "
                f"prefill pool holds {self.prefill_pool.num_blocks}; "
                f"raise serve.prefill_num_blocks")
        if need > self.pool.num_blocks:
            raise ValueError(
                f"request {req.id}: needs {need} blocks but the decode "
                f"pool holds {self.pool.num_blocks}; raise "
                f"serve.num_blocks")
        self.queue.append(RequestState(req))

    def has_work(self) -> bool:
        return (bool(self.queue)
                or any(s is not None for s in self.pslots)
                or any(s is not None for s in self.slots))

    # -- admission (into the prefill pool) ---------------------------------

    def admit(self, now: float = 0.0) -> list:
        """Head-of-line FIFO into free PREFILL slots while the prefill
        pool covers the head's whole prefill prefix (prompt + any
        recompute tokens, + 1 growth block for the sampled first token
        when the prefix ends block-aligned)."""
        out = []
        while self.queue:
            if self._shed_expired_head(now):
                continue
            free = [i for i, s in enumerate(self.pslots) if s is None]
            if not free:
                break
            st = self.queue[0]
            st.prefill_ids = st.req.prompt + tuple(st.generated)
            # the final chunk samples the first token, whose K/V lands
            # at position len(prefill_ids) on the NEXT dispatch — but
            # the handoff must carry every written position, so size to
            # the prefix only; the first generated token's write happens
            # decode-side after handoff
            blocks = self.prefill_pool.alloc(
                blocks_for(len(st.prefill_ids), self.block_size))
            if blocks is None:
                break
            self.queue.popleft()
            st.blocks = blocks
            st.n_prefilled = 0
            st.admit_seq = self._admit_seq
            st.t_admit = now
            self._admit_seq += 1
            self.n_admitted += 1
            slot = free[0]
            self.pslots[slot] = st
            out.append((slot, st))
        return out

    # -- prefill -----------------------------------------------------------

    def prefill_slots(self) -> list:
        cands = [(s.admit_seq, i) for i, s in enumerate(self.pslots)
                 if s is not None and s.prefilling]
        return [i for _, i in sorted(cands)]

    def note_prefilled(self, slot: int, n_tokens: int) -> None:
        st = self.pslots[slot]
        st.n_prefilled = min(st.n_prefilled + n_tokens,
                             len(st.prefill_ids))

    def retire_prefill(self, slot: int) -> RequestState:
        """Retire straight out of the prefill pool — a request whose
        FIRST token already hits EOS or exhausts its budget never needs
        a decode slot (or a handoff)."""
        st = self.pslots[slot]
        self.prefill_pool.free(st.blocks)
        st.blocks = []
        self.pslots[slot] = None
        self.n_retired += 1
        return st

    # -- handoff boundary --------------------------------------------------

    def handoff_ready(self) -> list:
        """Prefill-slot indices whose prefill is complete and first token
        sampled, oldest-admitted first — the order handoffs are
        attempted (and therefore the order decode-slot pressure is
        applied in)."""
        cands = [(s.admit_seq, i) for i, s in enumerate(self.pslots)
                 if s is not None and not s.prefilling and s.generated]
        return [i for _, i in sorted(cands)]

    def handoff(self, pslot: int):
        """Move the request in prefill slot `pslot` across the boundary:
        allocate decode-pool blocks for its prefix, free the prefill
        side, install it in a decode slot. May preempt decode residents
        STRICTLY YOUNGER than the candidate (youngest first) to make
        room. Returns (decode_slot, src_blocks, dst_blocks, preempted)
        — src/dst are the physical block ids the engine must copy K/V
        between — or None when the candidate must keep waiting (it is
        the youngest, so someone older is making progress)."""
        st = self.pslots[pslot]
        need = blocks_for(len(st.prefill_ids), self.block_size)
        preempted = []

        def free_slot():
            return next((i for i, s in enumerate(self.slots)
                         if s is None), None)

        def try_alloc():
            return (self.pool.alloc(need)
                    if free_slot() is not None else None)

        dst = try_alloc()
        while dst is None:
            younger = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                       if s is not None and s.admit_seq > st.admit_seq]
            if not younger:
                return None
            victim = max(younger)[1]
            preempted.append(victim)
            self._preempt_decode(victim)
            dst = try_alloc()
        src = list(st.blocks)
        self.prefill_pool.free(st.blocks)
        st.blocks = dst
        self.pslots[pslot] = None
        dslot = free_slot()
        self.slots[dslot] = st
        self.n_handoffs += 1
        return dslot, src, dst, preempted

    # -- decode ------------------------------------------------------------

    def decode_ready(self) -> list:
        cands = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                 if s is not None and s.generated]
        return [i for _, i in sorted(cands)]

    def ensure_block(self, slot: int, horizon: int = 1):
        """Identical policy to Scheduler.ensure_block, over the decode
        pool and decode residents only (prefill residents are never
        preempted by decode growth — their pool is separate, which is
        the isolation the split exists to provide)."""
        preempted = []
        st = self.slots[slot]
        need_upto = min(blocks_for(st.write_pos + horizon,
                                   self.block_size), self.max_blocks)
        while len(st.blocks) < need_upto:
            got = self.pool.alloc(1)
            if got is not None:
                st.blocks.extend(got)
                continue
            live = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                    if s is not None]
            if len(live) <= 1:
                raise RuntimeError(
                    f"decode block pool exhausted with a single live "
                    f"request (id {st.req.id}): serve.num_blocks "
                    f"({self.pool.num_blocks}) cannot hold one "
                    f"sequence; raise it")
            victim = max(live)[1]
            preempted.append(victim)
            self._preempt_decode(victim)
            if victim == slot:
                return False, preempted
        return True, preempted

    def _preempt_decode(self, slot: int) -> None:
        st = self.slots[slot]
        self.pool.free(st.blocks)
        st.blocks = []
        st.n_prefilled = 0
        st.prefill_ids = ()
        st.n_preempted += 1
        self.slots[slot] = None
        self.queue.appendleft(st)
        self.n_preempted += 1

    # -- retirement --------------------------------------------------------

    def should_retire(self, slot: int, eos_token_id: Optional[int],
                      pslot: bool = False) -> bool:
        st = (self.pslots if pslot else self.slots)[slot]
        return (len(st.generated) >= st.req.max_new_tokens
                or (eos_token_id is not None
                    and st.last_token == eos_token_id))

    def retire(self, slot: int) -> RequestState:
        st = self.slots[slot]
        self.pool.free(st.blocks)
        st.blocks = []
        self.slots[slot] = None
        self.n_retired += 1
        return st
