"""Serving stack: continuous batching + paged KV cache on the decode
path — the "millions of users, heavy traffic" half of the north star.

- `paged_cache`: block-pool KV cache (fixed-size blocks, per-slot block
  tables, memory ~ blocks allocated, not batch x max_length) behind the
  same interface as the offline contiguous `generate.KVCache`.
- `scheduler`: FIFO admission into a fixed decode-slot batch, chunked
  prefill, youngest-first preemption with recompute, retirement — pure
  host logic.
- `engine`: the driver — two jitted device programs (one decode step,
  one prefill chunk; each compiled exactly once per serving lifetime)
  plus telemetry (queue_wait/prefill/decode in the GoodputLedger, TTFT /
  per-token latency histograms, serve_request/serve_summary JSONL).

Prefill and decode are separate programs on purpose: the planned MPMD
executor (ROADMAP) can disaggregate them across chips without touching
this layer.
"""

from picotron_tpu.serve.engine import ServeEngine
from picotron_tpu.serve.paged_cache import (
    BlockPool, PagedKVCache, init_paged_cache,
)
from picotron_tpu.serve.scheduler import Request, Scheduler, blocks_for

__all__ = [
    "BlockPool",
    "PagedKVCache",
    "Request",
    "Scheduler",
    "ServeEngine",
    "blocks_for",
    "init_paged_cache",
]
