"""Serving stack: continuous batching + paged KV cache on the decode
path — the "millions of users, heavy traffic" half of the north star.

- `paged_cache`: block-pool KV cache (fixed-size blocks, per-slot block
  tables, memory ~ blocks allocated, not batch x max_length) behind the
  same interface as the offline contiguous `generate.KVCache`.
- `scheduler`: FIFO admission into a fixed decode-slot batch, chunked
  prefill, youngest-first preemption with recompute, retirement — pure
  host logic. `DisaggScheduler` splits the slot set in two with a
  handoff boundary between the pools.
- `engine`: the colocated driver — two jitted device programs (one
  decode step, one prefill chunk; each compiled exactly once per
  serving lifetime) plus telemetry (queue_wait/prefill/decode in the
  GoodputLedger, TTFT/TPOT/per-token latency histograms,
  serve_request/serve_summary JSONL).
- `disagg`: the disaggregated driver — prefill and decode as separately
  PLACED pools over their own block pools, paged-KV block handoff via
  explicit `device_put` (the MPMD ring-buffer discipline), so prefill
  bursts cannot stall decode dispatches.
- `spec_decode`: speculative multi-token decode (self-drafting n-gram
  speculator, verify-and-accept in one dispatch) for either engine;
  token-identical to non-speculative decode by construction.
- `fleet`: `FleetSupervisor` — N engine replicas behind one queue, with
  failover re-dispatch (bit-identical continuations), deadline load
  shedding, hang detection, and graceful drain.
"""

from picotron_tpu.serve.disagg import DisaggServeEngine
from picotron_tpu.serve.engine import ServeEngine
from picotron_tpu.serve.fleet import FleetSupervisor
from picotron_tpu.serve.paged_cache import (
    BlockPool, PagedKVCache, init_paged_cache,
)
from picotron_tpu.serve.scheduler import (
    DisaggScheduler, Request, Scheduler, blocks_for,
)

__all__ = [
    "BlockPool",
    "DisaggScheduler",
    "DisaggServeEngine",
    "FleetSupervisor",
    "PagedKVCache",
    "Request",
    "Scheduler",
    "ServeEngine",
    "blocks_for",
    "init_paged_cache",
]
