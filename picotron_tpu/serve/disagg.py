"""Disaggregated serving: prefill and decode as separately placed pools.

Under heavy mixed traffic the colocated engine has one structural
weakness: admission couples a request's PREFILL to a DECODE slot, so a
burst of long prompts occupies decode slots with chunked prefill work
and the in-flight decode batch starves — the classic TTFT/TPOT SLO
killer. This module splits the two phases into independent pools, in the
MPMD spirit of parallel/mpmd.py (arxiv 2412.14374): each pool is its own
separately PLACED jitted program over its own paged-KV block pool, and
finished prefixes cross the boundary through an explicit
``jax.device_put`` handoff — the same transfer_guard-clean ring-buffer
discipline the pipeline executor uses for boundary activations.

- **Prefill pool**: `prefill_slots` slots over `prefill_num_blocks`
  blocks on `prefill_device`, running the SAME `_prefill_chunk_impl`
  program as the colocated engine (chunked, batched over mid-prefill
  slots). Admission is budgeted against THIS pool only.
- **Decode pool**: `decode_slots` slots over `num_blocks` blocks on
  `decode_device`, running the same decode program (speculative or not)
  via the `ServeEngine._decode_tick` it inherits. Long-prompt bursts
  cannot touch it: `bench.py --serve --disagg` measures the max
  consecutive decode-stall ticks collapsing vs colocated.
- **Handoff**: a jitted block gather on the prefill device ->
  `jax.device_put` of the staging buffer to the decode placement (the
  ONLY inter-pool transfer, always explicit) -> a jitted sentinel-drop
  scatter into the decode pool. Index vectors are fixed [max_blocks]
  wide (padding gathers garbage that the scatter's sentinel drops), so
  both programs compile exactly once per engine lifetime — proven
  statically by `analysis/variants.prove_disagg_programs` and priced by
  `analysis/cost_model.price_kv_handoff`.

Token parity: the device programs, the paged-cache layout, and the
(request id, token index) sampling-key fold are all shared with the
colocated engine, so disaggregated output is bit-identical to colocated
(and to the offline sampler) on any trace, including under preemption —
a pinned test invariant, not an aspiration.

When params arrive tp-sharded (NamedSharding), both pools degrade to
the shared mesh placement: the pools and the handoff still exist (the
device_put becomes a same-sharding copy), only the physical separation
collapses. CPU tests use the 8 simulated devices from conftest to
exercise REAL cross-device handoff.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from picotron_tpu.config import ModelConfig, ServeConfig
from picotron_tpu.models.llama import model_rope_tables
from picotron_tpu.resilience import watchdog
from picotron_tpu.serve.engine import ServeEngine, _get_jits
from picotron_tpu.serve.paged_cache import BlockPool, init_paged_cache
from picotron_tpu.serve.scheduler import DisaggScheduler, blocks_for
from picotron_tpu.telemetry import Telemetry


# ---------------------------------------------------------------------------
# Handoff device programs (module-level: one jit cache for all engines)
# ---------------------------------------------------------------------------


def _gather_blocks_impl(k, v, idx):
    """Pull the handed-off sequence's blocks out of the prefill pool
    into a dense staging buffer: k/v [L, N_p, bs, Hkv, D], idx
    [max_blocks] physical block ids (0-padded past the sequence's
    blocks — the padding rows carry garbage the scatter side drops).
    Runs ON the prefill placement; the returned buffer is what crosses
    the pool boundary via device_put."""
    return k[:, idx], v[:, idx]


def _scatter_blocks_impl(k, v, buf_k, buf_v, idx):
    """Scatter the staging buffer into the decode pool's blocks: idx
    [max_blocks] destination block ids, sentinel (= N_d) past the
    sequence's blocks so padding rows DROP — the same sentinel
    discipline as the paged cache's write path. Runs ON the decode
    placement."""
    return (k.at[:, idx].set(buf_k, mode="drop"),
            v.at[:, idx].set(buf_v, mode="drop"))


_HANDOFF_JITS: dict = {}


def _get_handoff_jits(donate: bool):
    if donate not in _HANDOFF_JITS:
        _HANDOFF_JITS[donate] = (
            jax.jit(_gather_blocks_impl),
            jax.jit(_scatter_blocks_impl,
                    donate_argnums=(0, 1) if donate else ()),
        )
    return _HANDOFF_JITS[donate]


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class DisaggServeEngine(ServeEngine):
    """Same public surface as ServeEngine (submit / step / run / summary
    / results / close — bench and the tests drive either through one
    code path), backed by two pools. Inherits the decode tick, the
    retirement/telemetry plumbing, and the trace driver; owns admission
    -> prefill -> handoff."""

    def __init__(self, params, model_cfg: ModelConfig,
                 serve_cfg: Optional[ServeConfig] = None, *,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 telemetry: Optional[Telemetry] = None,
                 engine_id: int = 0):
        scfg = serve_cfg or ServeConfig()
        scfg.validate()
        if model_cfg.num_experts:
            raise ValueError(
                "serving does not support MoE models (num_experts > 0): "
                "chunked prefill feeds each chunk through per-call "
                "capacity-bounded expert dispatch, so routing — and "
                "therefore tokens — depends on the chunking; parity with "
                "the offline sampler cannot be guaranteed. Serve dense "
                "models only.")
        self.cfg = model_cfg
        self.scfg = scfg
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)

        self.max_len = scfg.max_model_len or model_cfg.max_position_embeddings
        self.block_size = scfg.block_size
        self.max_blocks = blocks_for(self.max_len, self.block_size)
        self.num_slots = scfg.decode_slots
        self.num_blocks = (scfg.num_blocks
                           or scfg.decode_slots * self.max_blocks)
        self.num_pslots = scfg.prefill_slots or scfg.decode_slots
        self.pnum_blocks = (scfg.prefill_num_blocks
                            or self.num_pslots * self.max_blocks)

        self.speculate = scfg.speculator == "ngram"
        self.draft_len = scfg.draft_len if self.speculate else 0
        if self.speculate:
            from picotron_tpu.serve import spec_decode
            if self.draft_len > spec_decode.max_draft_len():
                raise ValueError(
                    f"serve.draft_len ({self.draft_len}) exceeds the "
                    f"drafter's context window: max "
                    f"{spec_decode.max_draft_len()}")

        # ---- placement: one sharding per pool, everything committed up
        # front (the colocated engine's variant discipline, doubled).
        # tp-sharded params pin both pools to the mesh; otherwise each
        # pool gets its own device, defaulting to distinct devices when
        # the backend has more than one.
        from jax.sharding import (
            NamedSharding, PartitionSpec, SingleDeviceSharding,
        )
        mesh_sh = None
        for leaf in jax.tree.leaves(params):
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding):
                mesh_sh = NamedSharding(sh.mesh, PartitionSpec())
                kv_sh = NamedSharding(
                    sh.mesh,
                    PartitionSpec(None, None, None, "tp", None)
                    if dict(zip(sh.mesh.axis_names,
                                sh.mesh.devices.shape)).get("tp", 1) > 1
                    else PartitionSpec())
                break
        if mesh_sh is not None:
            self._sh_p = self._sh_d = self._rep_sh = mesh_sh
            kv_sh_p = kv_sh_d = kv_sh
        else:
            devices = jax.devices()
            d_idx = scfg.decode_device if scfg.decode_device >= 0 else 0
            p_idx = (scfg.prefill_device if scfg.prefill_device >= 0
                     else (1 if len(devices) > 1 else 0))
            for name, idx in (("decode_device", d_idx),
                              ("prefill_device", p_idx)):
                if idx >= len(devices):
                    raise ValueError(
                        f"serve.{name} = {idx} but only {len(devices)} "
                        f"device(s) are visible")
            self._sh_d = SingleDeviceSharding(devices[d_idx])
            self._sh_p = SingleDeviceSharding(devices[p_idx])
            self._rep_sh = self._sh_d  # decode-side alias _decode_tick uses
            kv_sh_p, kv_sh_d = self._sh_p, self._sh_d

        # per-pool params (weight replication is the standard disagg
        # cost; with a shared mesh the "copy" is the same array — only
        # uncommitted leaves get committed, tp shardings stay untouched)
        put_p = partial(jax.device_put, device=self._sh_p)
        put_d = partial(jax.device_put, device=self._sh_d)
        if mesh_sh is not None:
            self.params_p = self.params = jax.tree.map(
                lambda x: x if getattr(x, "committed", True)
                else jax.device_put(x, mesh_sh), params)
        else:
            self.params_p = jax.tree.map(put_p, params)
            self.params = (self.params_p if self._sh_p == self._sh_d
                           else jax.tree.map(put_d, params))

        cos, sin = model_rope_tables(model_cfg, max_len=self.max_len)
        self.cos, self.sin = put_d(cos), put_d(sin)
        self.cos_p, self.sin_p = put_p(cos), put_p(sin)
        self.base_key = put_d(jax.random.key(seed))
        self.base_key_p = put_p(jax.random.key(seed))

        dcache = init_paged_cache(model_cfg, self.num_blocks,
                                  self.block_size, self.num_slots,
                                  self.max_blocks)
        self._k = jax.device_put(dcache.k, kv_sh_d)
        self._v = jax.device_put(dcache.v, kv_sh_d)
        pcache = init_paged_cache(model_cfg, self.pnum_blocks,
                                  self.block_size, self.num_pslots,
                                  self.max_blocks)
        self._k_p = jax.device_put(pcache.k, kv_sh_p)
        self._v_p = jax.device_put(pcache.v, kv_sh_p)

        # host table mirrors, one per pool; sentinel = each pool's size
        self._tables = np.full((self.num_slots, self.max_blocks),
                               self.num_blocks, np.int32)
        self._tables_p = np.full((self.num_pslots, self.max_blocks),
                                 self.pnum_blocks, np.int32)
        self.pool = BlockPool(self.num_blocks)
        self.pool_p = BlockPool(self.pnum_blocks)
        self.sched = DisaggScheduler(self.num_pslots, self.num_slots,
                                     self.pool_p, self.pool,
                                     self.block_size, self.max_blocks)

        self._owns_telemetry = telemetry is None
        self.telemetry = telemetry or Telemetry(sinks=[])
        donate = jax.default_backend() != "cpu"
        self._decode_jit, self._prefill_jit = _get_jits(donate)
        if self.speculate:
            from picotron_tpu.serve.spec_decode import get_spec_jit
            self._decode_jit = get_spec_jit(donate)
        self._gather_jit, self._scatter_jit = _get_handoff_jits(donate)

        self._t0 = time.perf_counter()
        self.engine_id = int(engine_id)
        self._decode_state: Optional[dict] = None
        self.results: list = []
        self.shed_results: list = []
        self.stats = {
            "decode_steps": 0, "decode_compiles": 0,
            "prefill_chunks": 0, "occupancy_sum": 0.0,
            "prefill_occupancy_sum": 0.0, "prefill_ticks": 0,
            "output_tokens": 0, "prefill_tokens": 0,
            "draft_tokens": 0, "accepted_draft_tokens": 0,
            "decode_stall_ticks_max": 0, "cancelled": 0,
            "handoffs": 0, "handoff_s": 0.0, "handoff_blocks": 0,
        }
        self._stall_streak = 0
        self._next_auto_id = 0

        try:
            from picotron_tpu.analysis.variants import check_engine_feed

            self.variant_report = check_engine_feed(self)
            for f in self.variant_report.warnings():
                self.telemetry.emit("variant_hazard", category="serve",
                                    path=f.path, message=f.message)
        except Exception:  # analysis is best-effort at serve time
            self.variant_report = None

    # -- prefill-pool table mirror ----------------------------------------

    def _sync_ptable(self, pslot: int) -> None:
        st = self.sched.pslots[pslot]
        row = np.full((self.max_blocks,), self.pnum_blocks, np.int32)
        if st is not None and st.blocks:
            row[:len(st.blocks)] = st.blocks
        self._tables_p[pslot] = row

    # -- handoff -----------------------------------------------------------

    def _copy_blocks(self, src: list, dst: list) -> None:
        """Carry one sequence's K/V across the pool boundary: gather on
        the prefill placement, ONE explicit device_put of the staging
        buffer, sentinel-drop scatter on the decode placement. Fixed
        [max_blocks] index shapes keep both programs compile-once."""
        idx_src = np.zeros((self.max_blocks,), np.int32)
        idx_src[:len(src)] = src
        idx_dst = np.full((self.max_blocks,), self.num_blocks, np.int32)
        idx_dst[:len(dst)] = dst
        buf_k, buf_v = self._gather_jit(
            self._k_p, self._v_p,
            jax.device_put(idx_src, self._sh_p))
        buf_k, buf_v = jax.device_put((buf_k, buf_v), self._sh_d)
        self._k, self._v = self._scatter_jit(
            self._k, self._v, buf_k, buf_v,
            jax.device_put(idx_dst, self._sh_d))

    # -- one engine iteration ---------------------------------------------

    def step(self, now: Optional[float] = None) -> bool:
        """Admit into the prefill pool; run ONE batched prefill chunk on
        the prefill placement; hand finished prefixes across the
        boundary; run ONE decode dispatch on the decode placement.
        Returns whether any device work ran."""
        if now is None:
            now = time.perf_counter() - self._t0
        reg = self.telemetry.registry

        for pslot, st in self.sched.admit(now):
            self._sync_ptable(pslot)
            wait = max(now - st.req.arrival, 0.0)
            self.telemetry.emit("phase", phase="queue_wait",
                                category="queue_wait", secs=wait,
                                id=st.req.id)
            reg.histogram("serve/queue_wait").observe(wait)
        for st in self.sched.drain_shed():
            self._emit_shed(st, now)

        worked = False

        # ---- prefill chunks, batched over the PREFILL pool's slots
        pslots = self.sched.prefill_slots()
        if pslots:
            c = self.scfg.prefill_chunk
            ids = np.zeros((self.num_pslots, c), np.int32)
            start = np.zeros((self.num_pslots,), np.int32)
            nval = np.zeros((self.num_pslots,), np.int32)
            rids = np.zeros((self.num_pslots,), np.int32)
            tidx = np.zeros((self.num_pslots,), np.int32)
            finals = []
            for s in pslots:
                st = self.sched.pslots[s]
                chunk = st.prefill_ids[st.n_prefilled:st.n_prefilled + c]
                ids[s, :len(chunk)] = chunk
                start[s] = st.n_prefilled
                nval[s] = len(chunk)
                rids[s] = st.req.id
                tidx[s] = len(st.generated)
                if st.n_prefilled + len(chunk) >= len(st.prefill_ids):
                    finals.append(s)
            up = partial(jax.device_put, device=self._sh_p)
            self._drain_compile()
            if watchdog.active():
                watchdog.touch(
                    f"serve engine={self.engine_id} dispatch=prefill")
            t0 = time.perf_counter()
            self._k_p, self._v_p, toks_d = self._prefill_jit(
                self.params_p, self._k_p, self._v_p, up(self._tables_p),
                up(ids), up(start), up(nval), up(rids), up(tidx),
                self.base_key_p, self.cos_p, self.sin_p, cfg=self.cfg,
                temperature=self.temperature, top_k=self.top_k)
            toks = np.asarray(toks_d) if finals else None
            dt = time.perf_counter() - t0
            dt -= min(self._drain_compile(), dt)
            n_prefilled = int(nval.sum())
            self.telemetry.emit("phase", phase="prefill",
                                category="prefill", secs=dt,
                                tokens=n_prefilled, pool="prefill",
                                ids=[int(rids[s]) for s in pslots])
            for s in pslots:
                self.sched.note_prefilled(s, int(nval[s]))
            self.stats["prefill_chunks"] += len(pslots)
            self.stats["prefill_tokens"] += n_prefilled
            for s in finals:
                st = self.sched.pslots[s]
                st.generated.append(int(toks[s]))
                self.stats["output_tokens"] += 1
                if st.t_first_token is None:
                    st.t_first_token = now + dt
                    ttft = max(st.t_first_token - st.req.arrival, 0.0)
                    reg.histogram("serve/ttft").observe(ttft)
                if self.sched.should_retire(s, self.eos_token_id,
                                            pslot=True):
                    # first token already finishes it: retire straight
                    # from the prefill pool, no handoff needed
                    st = self.sched.retire_prefill(s)
                    self._sync_ptable(s)
                    self._emit_retired(st, now + dt)
            worked = True
        self.stats["prefill_ticks"] += 1
        self.stats["prefill_occupancy_sum"] += (
            sum(s is not None for s in self.sched.pslots)
            / self.num_pslots)

        # ---- handoff: oldest finished prefixes cross the boundary
        for pslot in self.sched.handoff_ready():
            got = self.sched.handoff(pslot)
            if got is None:
                break  # youngest everywhere — wait for decode capacity
            dslot, src, dst, preempted = got
            t0 = time.perf_counter()
            self._copy_blocks(src, dst)
            dt = time.perf_counter() - t0
            dt -= min(self._drain_compile(), dt)
            self._sync_ptable(pslot)
            for p in preempted:
                self._sync_table(p)
            self._sync_table(dslot)
            self.stats["handoffs"] += 1
            self.stats["handoff_s"] += dt
            self.stats["handoff_blocks"] += len(src)
            self.telemetry.emit("phase", phase="handoff",
                                category="handoff", secs=dt,
                                id=self.sched.slots[dslot].req.id,
                                blocks=len(src))
            worked = True

        # ---- decode dispatch on the decode pool (inherited — operates
        # on the decode-side context and the scheduler's decode half)
        decode_ran = self._decode_tick(now, reg)
        worked = worked or decode_ran
        if decode_ran:
            self._stall_streak = 0
        elif self.sched.has_work():
            self._stall_streak += 1
            self.stats["decode_stall_ticks_max"] = max(
                self.stats["decode_stall_ticks_max"], self._stall_streak)
        return worked

    # -- summary -----------------------------------------------------------

    def _summary_dict(self, wall: float) -> dict:
        pticks = max(self.stats["prefill_ticks"], 1)
        return dict(
            super()._summary_dict(wall),
            disagg=True,
            prefill_slots=self.num_pslots,
            prefill_num_blocks=self.pnum_blocks,
            prefill_slot_occupancy=round(
                self.stats["prefill_occupancy_sum"] / pticks, 4),
            prefill_pool_peak_utilization=round(
                self.pool_p.peak_in_use / self.pnum_blocks, 4),
            handoffs=self.stats["handoffs"],
            handoff_s=round(self.stats["handoff_s"], 6),
            handoff_blocks=self.stats["handoff_blocks"],
        )
