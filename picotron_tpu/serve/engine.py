"""Serving engine: continuous batching over a paged KV cache.

The host loop owns the scheduler (admission / chunked prefill /
preemption / retirement, serve/scheduler.py) and drives exactly TWO
jitted device programs, each compiled once for the whole serving
lifetime:

- ``decode step``: a fixed batch of `decode_slots` slots, one token per
  slot per call. Slot count is the static shape; which request occupies
  which slot, every slot's position, and the block tables are ordinary
  device DATA, so requests enter and leave mid-flight without a
  recompile (asserted via CompileWatch in tests — one decode compile
  across a multi-request trace). Idle/prefilling slots ride along at
  position -1: their q-rows compute masked garbage that is discarded and
  their K/V writes resolve to the sentinel block and drop.
- ``prefill chunk``: `prefill_chunk` tokens of ONE slot's prompt,
  interleaved one chunk per engine iteration so a long prompt never
  stalls the in-flight decode batch. The final (padded) chunk returns
  the last valid position's logits — the request's first token (TTFT).

Both run `generate._decode_layers` against `PagedKVCache` — the same
layer math as the offline contiguous path, which is what makes greedy
token parity between the two cache implementations a pinned test
invariant. tp-sharded params from `generate.place_for_decode` work
unchanged: the programs are pure GSPMD, XLA propagates the shardings
through the block pool and inserts the collectives.

Sampling keys derive from (request id, token index), so tokens are
independent of slot assignment, arrival interleaving, and preemption —
the ragged-batch-invariance property the tests pin.

With ``serve.speculator = "ngram"`` the decode program is swapped for
the speculative verify-and-accept scan (serve/spec_decode.py): each
dispatch still compiles once and still covers `decode_interval`
iterations, but every iteration forwards 1 + draft_len candidate tokens
and emits between 1 and 1 + draft_len of them. The same key fold keys
every candidate position, so speculative output is bit-identical to the
non-speculative stream at any temperature — acceptance only changes how
fast the stream advances. MoE models are rejected at engine
construction: chunked prefill routes tokens through per-call
capacity-bounded expert dispatch, so routing depends on the chunking
and parity with the offline sampler cannot be guaranteed (the PR-7
KNOWN, now a hard error).

Observability rides the existing telemetry machinery: the GoodputLedger
books queue_wait / prefill / decode (compile time drained out exactly
via CompileWatch), per-request TTFT and per-token latency land in the
registry histograms and as ``serve_request`` / ``serve_summary`` JSONL
events, and tools/telemetry_report.py renders the serving view
(p50/p95 TTFT, tok/s, slot occupancy, pool utilization).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from picotron_tpu.config import ModelConfig, ServeConfig
from picotron_tpu.generate import _decode_layers, _logits_last
from picotron_tpu.resilience import watchdog
from picotron_tpu.models.llama import (
    compute_dtype, final_hidden, head_weight, model_rope_tables,
)
from picotron_tpu.serve.paged_cache import (
    BlockPool, PagedKVCache, init_paged_cache,
)
from picotron_tpu.serve.scheduler import Request, Scheduler, blocks_for
from picotron_tpu.telemetry import Telemetry


# ---------------------------------------------------------------------------
# Device programs (module-level so every engine shares one jit cache)
# ---------------------------------------------------------------------------


def _fold_keys(base_key, rids, tidx):
    """[S] sampling keys from (request id, token index) — slot/order
    independent, so continuous batching and preemption replay cannot
    perturb sampled tokens."""
    return jax.vmap(
        lambda r, t: jax.random.fold_in(jax.random.fold_in(base_key, r), t)
    )(rids, tidx)


def _decode_step_impl(params, k, v, tables, toks, positions, rids, tidx,
                      base_key, cos, sin, cfg: ModelConfig,
                      temperature: float, top_k: int, interval: int,
                      eos_token_id):
    """`interval` decode steps over all slots inside ONE dispatch (a
    lax.scan — amortizes per-dispatch host overhead over interval tokens
    per slot; the same reason offline generate scans its whole decode).
    toks/positions/rids/tidx: [S]; positions < 0 = idle slot (output
    ignored, write dropped). Slots that emit EOS mid-interval are forced
    to keep emitting EOS — identical semantics to generate.py's scan —
    and the host truncates + retires them at dispatch end. Returns
    (tokens [S, interval], next positions, next tidx, k, v); the
    position/index outputs feed the steady-state fast path straight back
    in, so an unchanged slot roster costs zero host->device uploads
    (measured ~2x the whole dispatch on the CPU tiny-model bench)."""
    live = positions >= 0

    def one(carry, _):
        toks, positions, tidx, cache, done = carry
        x = params["embedding"][toks[:, None]].astype(compute_dtype(cfg))
        x, cache = _decode_layers(params, x, cache, positions[:, None],
                                  cfg, cos, sin)
        logits = _logits_last(params, x, cfg)  # [S, V] fp32
        if temperature == 0.0:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            lg = logits / temperature
            if top_k > 0:
                kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
                lg = jnp.where(lg < kth, -jnp.inf, lg)
            keys = _fold_keys(base_key, rids, tidx)
            nxt = jax.vmap(
                lambda l, key: jax.random.categorical(key, l)
            )(lg, keys).astype(jnp.int32)
        if eos_token_id is not None:
            nxt = jnp.where(done, eos_token_id, nxt)
            done = done | (nxt == eos_token_id)
        positions = jnp.where(live, positions + 1, positions)
        tidx = jnp.where(live, tidx + 1, tidx)
        return (nxt, positions, tidx, cache, done), nxt

    cache = PagedKVCache(k, v, tables)
    done = jnp.zeros(toks.shape, bool)
    (last, positions, tidx, cache, _), toks_all = jax.lax.scan(
        one, (toks, positions, tidx, cache, done), None, length=interval)
    return toks_all.T, last, positions, tidx, cache.k, cache.v


def _prefill_chunk_impl(params, k, v, table_rows, chunk_ids, start_pos,
                        n_valid, rids, tidx, base_key, cos, sin,
                        cfg: ModelConfig, temperature: float, top_k: int):
    """Prefill the next chunk of EVERY mid-prefill slot in one dispatch:
    chunk_ids [S, C] (padded), start_pos/n_valid/rids/tidx [S],
    table_rows [S, max_blocks]. Rows with n_valid = 0 are idle slots
    riding along (all positions -1: writes sentinel-drop, outputs
    discarded); padded positions inside a live row behave the same.
    Batching matters: a per-slot prefill dispatch measured ~2x the
    static sampler's batched prompt pass on the CPU bench — one [S, C]
    program closes that. Samples each row's next token off its last
    valid position's logits with the same (request id, token index) key
    derivation as the decode step — one sampling law everywhere.
    Returns (k, v, tokens [S])."""
    s, c = chunk_ids.shape
    t = jnp.arange(c)[None, :]
    pos = jnp.where(t < n_valid[:, None], start_pos[:, None] + t, -1)
    cache = PagedKVCache(k, v, table_rows)
    x = params["embedding"][chunk_ids].astype(compute_dtype(cfg))
    x, cache = _decode_layers(params, x, cache, pos, cfg, cos, sin)
    last = jnp.maximum(n_valid - 1, 0)  # [S]
    h_last = jnp.take_along_axis(x, last[:, None, None], axis=1)  # [S,1,H]
    hf = final_hidden(params, h_last, cfg)
    logits = (hf @ head_weight(params).astype(hf.dtype))[:, 0]
    logits = logits.astype(jnp.float32)  # [S, V]
    if temperature == 0.0:
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        lg = logits / temperature
        if top_k > 0:
            kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        keys = _fold_keys(base_key, rids, tidx)
        toks = jax.vmap(
            lambda l, key: jax.random.categorical(key, l)
        )(lg, keys).astype(jnp.int32)
    return cache.k, cache.v, toks


_JITS: dict = {}


def _get_jits(donate: bool):
    """Jitted (decode, prefill) pair, shared across engines so repeated
    engine construction (tests, bench baseline+serve in one process)
    reuses the compile cache. Cache donation is only requested off-CPU —
    the CPU backend ignores donation with a warning per call site."""
    if donate not in _JITS:
        dargs = (1, 2) if donate else ()
        _JITS[donate] = (
            jax.jit(_decode_step_impl, donate_argnums=dargs,
                    static_argnames=("cfg", "temperature", "top_k",
                                     "interval", "eos_token_id")),
            jax.jit(_prefill_chunk_impl, donate_argnums=dargs,
                    static_argnames=("cfg", "temperature", "top_k")),
        )
    return _JITS[donate]


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class ServeEngine:
    def __init__(self, params, model_cfg: ModelConfig,
                 serve_cfg: Optional[ServeConfig] = None, *,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 telemetry: Optional[Telemetry] = None,
                 device=None, engine_id: int = 0):
        scfg = serve_cfg or ServeConfig()
        scfg.validate()
        if model_cfg.num_experts:
            raise ValueError(
                "serving does not support MoE models (num_experts > 0): "
                "chunked prefill feeds each chunk through per-call "
                "capacity-bounded expert dispatch, so routing — and "
                "therefore tokens — depends on the chunking; parity with "
                "the offline sampler cannot be guaranteed. Serve dense "
                "models only.")
        self.params = params
        self.cfg = model_cfg
        self.scfg = scfg
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.base_key = jax.random.key(seed)

        self.max_len = scfg.max_model_len or model_cfg.max_position_embeddings
        self.block_size = scfg.block_size
        self.max_blocks = blocks_for(self.max_len, self.block_size)
        self.num_blocks = (scfg.num_blocks
                           or scfg.decode_slots * self.max_blocks)
        self.num_slots = scfg.decode_slots

        self.speculate = scfg.speculator == "ngram"
        self.draft_len = scfg.draft_len if self.speculate else 0
        if self.speculate:
            from picotron_tpu.serve import spec_decode
            if self.draft_len > spec_decode.max_draft_len():
                raise ValueError(
                    f"serve.draft_len ({self.draft_len}) exceeds the "
                    f"drafter's context window: max "
                    f"{spec_decode.max_draft_len()}")

        self.cos, self.sin = model_rope_tables(model_cfg,
                                               max_len=self.max_len)
        cache = init_paged_cache(model_cfg, self.num_blocks,
                                 self.block_size, self.num_slots,
                                 self.max_blocks)
        self._k, self._v = cache.k, cache.v

        # Sharding discipline: every decode/prefill input keeps ONE
        # explicit sharding for the engine's whole lifetime. Committed
        # and uncommitted arrays key DIFFERENT jit variants, and
        # commitment spreads through outputs — one committed argument
        # (e.g. place_for_decode'd params) cascades into k/v and then
        # every upload, minting fresh 0.6 s recompiles mid-trace (caught
        # on the CPU bench). Committing everything up front collapses the
        # variant space to exactly one per program. With tp > 1 the KV
        # pool is pinned over the kv-head axis — the layout GSPMD picks
        # for TP attention.
        from jax.sharding import NamedSharding, PartitionSpec
        self._rep_sh = None
        for leaf in jax.tree.leaves(params):
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding):
                mesh = sh.mesh
                self._rep_sh = NamedSharding(mesh, PartitionSpec())
                kv_sh = NamedSharding(
                    mesh,
                    PartitionSpec(None, None, None, "tp", None)
                    if dict(zip(mesh.axis_names,
                                mesh.devices.shape)).get("tp", 1) > 1
                    else PartitionSpec())
                break
        if self._rep_sh is None:
            # `device` pins the whole engine (params, KV pool, rope
            # tables, key) to ONE device — the fleet's per-replica
            # placement: N engines on N distinct (simulated) devices,
            # each a self-contained replica whose state can be discarded
            # wholesale on failover.
            dev = device if device is not None else jax.devices()[0]
            self._rep_sh = jax.sharding.SingleDeviceSharding(dev)
            kv_sh = self._rep_sh
        self._k = jax.device_put(self._k, kv_sh)
        self._v = jax.device_put(self._v, kv_sh)
        self.cos = jax.device_put(self.cos, self._rep_sh)
        self.sin = jax.device_put(self.sin, self._rep_sh)
        self.base_key = jax.device_put(self.base_key, self._rep_sh)
        # ... and the params themselves: raw init_params / checkpoint
        # loads hand over uncommitted arrays, the one hole the variant
        # prover (analysis/variants.check_engine_feed) found in this
        # discipline — an uncommitted re-feed of the same shapes would
        # mint a second executable. Already-committed leaves (e.g.
        # place_for_decode output) pass through untouched.
        self.params = jax.tree.map(
            lambda x: x if getattr(x, "committed", True)
            else jax.device_put(x, self._rep_sh), self.params)
        # host mirror of the device block tables; sentinel = num_blocks
        self._tables = np.full((self.num_slots, self.max_blocks),
                               self.num_blocks, np.int32)
        self.pool = BlockPool(self.num_blocks)
        self.sched = Scheduler(self.num_slots, self.pool, self.block_size,
                               self.max_blocks)

        self._owns_telemetry = telemetry is None
        self.telemetry = telemetry or Telemetry(sinks=[])
        self._decode_jit, self._prefill_jit = _get_jits(
            jax.default_backend() != "cpu")
        if self.speculate:
            from picotron_tpu.serve.spec_decode import get_spec_jit
            self._decode_jit = get_spec_jit(jax.default_backend() != "cpu")

        self._t0 = time.perf_counter()  # trace clock zero (run() resets)
        self.engine_id = int(engine_id)  # fleet replica index (0 = solo)
        # steady-state decode fast path: device-resident step inputs,
        # valid while the slot roster and block tables are unchanged
        self._decode_state: Optional[dict] = None
        self.results: list = []
        self.shed_results: list = []
        self.stats = {
            "decode_steps": 0, "decode_compiles": 0,
            "prefill_chunks": 0, "occupancy_sum": 0.0,
            "output_tokens": 0, "prefill_tokens": 0,
            "draft_tokens": 0, "accepted_draft_tokens": 0,
            "decode_stall_ticks_max": 0, "cancelled": 0,
        }
        self._stall_streak = 0  # consecutive ticks: work queued, no decode
        self._next_auto_id = 0

        # Static variant-prover check over the feed the engine just built
        # (analysis/variants.py): every persistent leaf must be committed,
        # or the first decode after an uncommitted re-feed mints a second
        # executable for the same shapes. Advisory — findings go to
        # telemetry, never raise; the runtime CompileWatch twin
        # (stats["decode_compiles"]) remains the ground truth.
        try:
            from picotron_tpu.analysis.variants import check_engine_feed

            self.variant_report = check_engine_feed(self)
            for f in self.variant_report.warnings():
                self.telemetry.emit("variant_hazard", category="serve",
                                    path=f.path, message=f.message)
        except Exception:  # analysis is best-effort at serve time
            self.variant_report = None

    # -- intake ------------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               req_id: Optional[int] = None, arrival: float = 0.0,
               deadline_ms: Optional[float] = None) -> int:
        if req_id is None:
            req_id = self._next_auto_id
        self._next_auto_id = max(self._next_auto_id, req_id + 1)
        self.sched.submit(Request(req_id, tuple(prompt), max_new_tokens,
                                  arrival, deadline_ms))
        return req_id

    def cancel(self, request_id: int) -> bool:
        """Abandon a request mid-generation (client hung up, upstream
        timeout): its blocks go straight back to the pool and its slot
        frees for the next admission — no result is recorded, nothing
        leaks until teardown. Returns False for an unknown id (already
        retired, shed, or never submitted)."""
        got = self.sched.cancel(request_id)
        if got is None:
            return False
        where, idx, st = got
        if where == "slot":
            self._sync_table(idx)
        elif where == "pslot":  # disagg prefill side
            self._sync_ptable(idx)
        self.stats["cancelled"] += 1
        self.telemetry.emit("serve_cancel", id=request_id, where=where,
                            tokens=len(st.generated))
        return True

    # -- helpers -----------------------------------------------------------

    def _sync_table(self, slot: int) -> None:
        st = self.sched.slots[slot]
        row = np.full((self.max_blocks,), self.num_blocks, np.int32)
        if st is not None and st.blocks:
            row[:len(st.blocks)] = st.blocks
        self._tables[slot] = row
        self._decode_state = None  # roster/table changed: slow path next

    def _drain_compile(self) -> float:
        n, secs = self.telemetry.compile_watch.drain()
        if n:
            self.telemetry.emit("compile", category="compile", secs=secs,
                                compiles=n)
        return secs if n else 0.0

    def _emit_retired(self, st, now: float) -> dict:
        req = st.req
        ttft = (st.t_first_token - req.arrival
                if st.t_first_token is not None else None)
        # TPOT: mean inter-token time AFTER the first token — the decode
        # SLO, as distinct from TTFT (the prefill/queueing SLO)
        tpot = None
        if st.t_first_token is not None and len(st.generated) > 1:
            tpot = (max(now - st.t_first_token, 0.0)
                    / (len(st.generated) - 1))
            self.telemetry.registry.histogram("serve/tpot").observe(tpot)
        res = {
            "id": req.id,
            "prompt_len": len(req.prompt),
            "tokens": list(st.generated),
            "output_tokens": len(st.generated),
            "queue_wait_s": max((st.t_admit or 0.0) - req.arrival, 0.0),
            "ttft_s": ttft,
            "latency_s": max(now - req.arrival, 0.0),
            "tpot_s": tpot,
            "n_preempted": st.n_preempted,
        }
        self.results.append(res)
        self.telemetry.emit(
            "serve_request",
            id=req.id, prompt_tokens=res["prompt_len"],
            output_tokens=res["output_tokens"],
            queue_wait_s=round(res["queue_wait_s"], 6),
            ttft_s=round(ttft, 6) if ttft is not None else None,
            latency_s=round(res["latency_s"], 6),
            tpot_s=round(tpot, 6) if tpot is not None else None,
            preempted=st.n_preempted, engine=self.engine_id)
        return res

    def _emit_shed(self, st, now: float) -> dict:
        """Report one deadline-shed request: the queue seconds it burned
        book to the `shed` ledger category (pure badput — the wait
        bought nothing, the request never ran) and it lands in
        `shed_results`, never `results` — shed requests are excluded
        from goodput and throughput by construction."""
        wait = max(now - st.req.arrival, 0.0)
        res = {"id": st.req.id, "prompt_len": len(st.req.prompt),
               "queue_wait_s": wait, "deadline_ms": st.req.deadline_ms,
               "shed": True}
        self.shed_results.append(res)
        self.telemetry.emit("serve_shed", category="shed", secs=wait,
                            id=st.req.id, deadline_ms=st.req.deadline_ms,
                            queue_wait_s=round(wait, 6),
                            engine=self.engine_id)
        return res

    # -- one engine iteration ---------------------------------------------

    def step(self, now: Optional[float] = None) -> bool:
        """Admit; run ONE prefill chunk (if any prompt is mid-prefill);
        run ONE decode step over the slot batch; retire. Returns whether
        any device work ran."""
        if now is None:
            now = time.perf_counter() - self._t0
        reg = self.telemetry.registry

        for slot, st in self.sched.admit(now):
            self._sync_table(slot)
            wait = max(now - st.req.arrival, 0.0)
            # "phase" events carry (category, secs) so a post-hoc sum of
            # the JSONL reproduces the in-process ledger, exactly like the
            # training stream's phase events
            self.telemetry.emit("phase", phase="queue_wait",
                                category="queue_wait", secs=wait,
                                id=st.req.id)
            reg.histogram("serve/queue_wait").observe(wait)
        for st in self.sched.drain_shed():
            self._emit_shed(st, now)

        worked = False

        # ---- one prefill chunk per mid-prefill slot, batched into a
        # single dispatch and interleaved with the decode step
        pslots = self.sched.prefill_slots()
        if pslots:
            c = self.scfg.prefill_chunk
            ids = np.zeros((self.num_slots, c), np.int32)
            start = np.zeros((self.num_slots,), np.int32)
            nval = np.zeros((self.num_slots,), np.int32)
            rids = np.zeros((self.num_slots,), np.int32)
            tidx = np.zeros((self.num_slots,), np.int32)
            finals = []
            for s in pslots:
                st = self.sched.slots[s]
                chunk = st.prefill_ids[st.n_prefilled:st.n_prefilled + c]
                ids[s, :len(chunk)] = chunk
                start[s] = st.n_prefilled
                nval[s] = len(chunk)
                rids[s] = st.req.id
                tidx[s] = len(st.generated)
                if st.n_prefilled + len(chunk) >= len(st.prefill_ids):
                    finals.append(s)
            up = partial(jax.device_put, device=self._rep_sh)
            self._drain_compile()
            if watchdog.active():
                # a hang inside this dispatch is reported as THIS
                # dispatch, not a bare stack dump (satellite of the
                # fleet's serve_hang detection; also arms bench --serve)
                watchdog.touch(
                    f"serve engine={self.engine_id} dispatch=prefill")
            t0 = time.perf_counter()
            self._k, self._v, toks_d = self._prefill_jit(
                self.params, self._k, self._v, up(self._tables), up(ids),
                up(start), up(nval), up(rids), up(tidx), self.base_key,
                self.cos, self.sin, cfg=self.cfg,
                temperature=self.temperature, top_k=self.top_k)
            toks = np.asarray(toks_d) if finals else None
            dt = time.perf_counter() - t0
            dt -= min(self._drain_compile(), dt)
            n_prefilled = int(nval.sum())
            self.telemetry.emit("phase", phase="prefill",
                                category="prefill", secs=dt,
                                tokens=n_prefilled,
                                ids=[int(rids[s]) for s in pslots])
            for s in pslots:
                self.sched.note_prefilled(s, int(nval[s]))
            self.stats["prefill_chunks"] += len(pslots)
            self.stats["prefill_tokens"] += n_prefilled
            for s in finals:
                st = self.sched.slots[s]
                st.generated.append(int(toks[s]))
                self.stats["output_tokens"] += 1
                if st.t_first_token is None:
                    st.t_first_token = now + dt
                    ttft = max(st.t_first_token - st.req.arrival, 0.0)
                    reg.histogram("serve/ttft").observe(ttft)
                if self.sched.should_retire(s, self.eos_token_id):
                    st = self.sched.retire(s)
                    self._sync_table(s)
                    self._emit_retired(st, now + dt)
            worked = True

        # ---- one decode step over every slot with a live sequence
        decode_ran = self._decode_tick(now, reg)
        worked = worked or decode_ran
        # max consecutive ticks with work in the system but no decode
        # dispatch — the TTFT/TPOT SLO killer the disaggregated engine
        # exists to eliminate (bench.py --serve --disagg compares this)
        if decode_ran:
            self._stall_streak = 0
        elif self.sched.has_work():
            self._stall_streak += 1
            self.stats["decode_stall_ticks_max"] = max(
                self.stats["decode_stall_ticks_max"], self._stall_streak)
        return worked

    def _decode_tick(self, now: float, reg) -> bool:
        """One decode dispatch over every decode-ready slot. Operates
        purely through the scheduler's decode interface plus the
        decode-side device context (self.params/_k/_v/cos/sin/base_key/
        _rep_sh), so the disaggregated engine reuses it verbatim against
        its decode pool. Returns whether a dispatch ran."""
        ready = self.sched.decode_ready()
        if ready:
            active = []
            dropped: set = set()
            interval = self.scfg.decode_interval
            # a speculative iteration can advance a slot by up to
            # 1 + draft_len positions, so the write horizon (and the
            # block allocation backing it) scales with it
            span = interval * (1 + self.draft_len)
            for s in ready:
                if s in dropped:
                    continue
                st = self.sched.slots[s]
                horizon = min(span,
                              st.req.max_new_tokens - len(st.generated))
                n_before = len(st.blocks)
                ok, preempted = self.sched.ensure_block(s, horizon)
                dropped.update(preempted)
                for p in preempted:
                    self._sync_table(p)
                if ok:
                    if len(self.sched.slots[s].blocks) != n_before:
                        self._sync_table(s)
                    active.append(s)
            # a later ensure_block can preempt a slot already activated
            # (it was younger than the one needing the block)
            active = [s for s in active if s not in dropped]
            if active:
                ds = self._decode_state
                if ds is None or ds["active"] != active:
                    # slow path: roster changed — rebuild inputs on host,
                    # uploaded with the shardings earlier calls produced
                    # so the rebuild cannot mint a new jit variant
                    toks = np.zeros((self.num_slots,), np.int32)
                    positions = np.full((self.num_slots,), -1, np.int32)
                    rids = np.zeros((self.num_slots,), np.int32)
                    tidx = np.zeros((self.num_slots,), np.int32)
                    for s in active:
                        st = self.sched.slots[s]
                        toks[s] = st.last_token
                        positions[s] = st.write_pos
                        rids[s] = st.req.id
                        tidx[s] = len(st.generated)
                    up = partial(jax.device_put, device=self._rep_sh)
                    ds = {"active": list(active),
                          "tables": up(self._tables),
                          "toks": up(toks),
                          "positions": up(positions),
                          "rids": up(rids),
                          "tidx": up(tidx)}
                    if self.speculate:
                        from picotron_tpu.serve.spec_decode import (
                            context_rows,
                        )
                        ds["ctx"] = up(context_rows(
                            self.sched.slots, active, self.num_slots))
                self._drain_compile()
                if watchdog.active():
                    watchdog.touch(
                        f"serve engine={self.engine_id} dispatch=decode")
                t0 = time.perf_counter()
                nval = None
                if self.speculate:
                    (toks_d, nval_d, last_d, pos_d, tidx_d, ctx_d,
                     self._k, self._v) = self._decode_jit(
                        self.params, self._k, self._v,
                        ds["tables"], ds["toks"], ds["positions"],
                        ds["rids"], ds["tidx"], ds["ctx"], self.base_key,
                        self.cos, self.sin, cfg=self.cfg,
                        temperature=self.temperature, top_k=self.top_k,
                        interval=interval,
                        eos_token_id=self.eos_token_id,
                        draft_len=self.draft_len)
                    nxt = np.asarray(toks_d)   # [S, interval, 1+d]
                    nval = np.asarray(nval_d)  # [S, interval]
                    state = dict(ds, toks=last_d, positions=pos_d,
                                 tidx=tidx_d, ctx=ctx_d)
                else:
                    toks_d, last_d, pos_d, tidx_d, self._k, self._v = \
                        self._decode_jit(
                            self.params, self._k, self._v,
                            ds["tables"], ds["toks"], ds["positions"],
                            ds["rids"], ds["tidx"], self.base_key,
                            self.cos, self.sin, cfg=self.cfg,
                            temperature=self.temperature,
                            top_k=self.top_k, interval=interval,
                            eos_token_id=self.eos_token_id)
                    nxt = np.asarray(toks_d)  # [S, interval]
                    state = dict(ds, toks=last_d, positions=pos_d,
                                 tidx=tidx_d)
                # feed outputs forward; any roster/table change below
                # nulls this via _sync_table
                self._decode_state = state
                dt = time.perf_counter() - t0
                csecs = self._drain_compile()
                if csecs:
                    self.stats["decode_compiles"] += 1
                dt -= min(csecs, dt)
                # Request ids snapshotted before the retire loop below
                # frees slots — tags the decode phase event (and its
                # flightdeck span) with the requests it advanced.
                dec_ids = [self.sched.slots[s].req.id for s in active]
                n_tokens = 0
                for s in active:
                    st = self.sched.slots[s]
                    retired = False
                    for t in range(interval):
                        if retired:
                            break
                        if self.speculate:
                            emit = [int(x)
                                    for x in nxt[s, t, :int(nval[s, t])]]
                            self.stats["draft_tokens"] += self.draft_len
                            self.stats["accepted_draft_tokens"] += (
                                len(emit) - 1)
                        else:
                            emit = [int(nxt[s, t])]
                        for tok in emit:
                            st.generated.append(tok)
                            n_tokens += 1
                            if self.sched.should_retire(
                                    s, self.eos_token_id):
                                # tokens past EOS/budget are padding
                                rst = self.sched.retire(s)
                                self._sync_table(s)
                                self._emit_retired(rst, now + dt)
                                retired = True
                                break
                self.telemetry.emit("phase", phase="decode",
                                    category="decode", secs=dt,
                                    tokens=n_tokens, ids=dec_ids)
                reg.histogram("serve/token_latency").observe(
                    dt / max(n_tokens if self.speculate
                             else len(active) * interval, 1))
                self.stats["decode_steps"] += 1
                self.stats["occupancy_sum"] += len(active) / self.num_slots
                self.stats["output_tokens"] += n_tokens
                reg.gauge("serve/slot_occupancy").set(
                    len(active) / self.num_slots)
                reg.gauge("serve/pool_utilization").set(
                    self.pool.in_use / self.num_blocks)
                return True
        return False

    # -- trace driver ------------------------------------------------------

    def run(self, requests=(), watchdog_timeout: float = 0.0) -> list:
        """Drive a whole trace: submit each (prompt, max_new_tokens[,
        arrival[, deadline_ms]]) when its arrival time passes on the
        trace clock, loop engine steps until queue and slots drain.
        Returns per-request result dicts sorted by request id (shed
        requests are in `self.shed_results`, not here).

        watchdog_timeout > 0 arms a resilience watchdog for the trace:
        every dispatch heartbeats with a phase naming this engine and
        dispatch kind, so a wedged device call is reported as `serve
        engine=K dispatch=decode` — flightdeck postmortem reason
        `serve_hang`, then exit 77 for the supervisor (same contract as
        a hung training collective)."""
        wd = None
        if watchdog_timeout > 0:
            from picotron_tpu.resilience.watchdog import Watchdog
            wd = Watchdog(watchdog_timeout, reason="serve_hang")
            wd.start()
        try:
            pending = sorted(requests,
                             key=lambda r: r[2] if len(r) > 2 else 0.0)
            self._t0 = t0 = time.perf_counter()
            while pending or self.sched.has_work():
                now = time.perf_counter() - t0
                while pending and (pending[0][2] if len(pending[0]) > 2
                                   else 0.0) <= now:
                    r = pending.pop(0)
                    self.submit(r[0], r[1],
                                arrival=r[2] if len(r) > 2 else 0.0,
                                deadline_ms=r[3] if len(r) > 3 else None)
                if not self.sched.has_work():
                    time.sleep(min(max(pending[0][2] - now, 0.0), 0.01))
                    continue
                self.step(now)
        finally:
            if wd is not None:
                wd.stop()
        self._emit_summary(time.perf_counter() - t0)
        return sorted(self.results, key=lambda r: r["id"])

    def _emit_summary(self, wall: float) -> None:
        self.summary = self._summary_dict(wall)
        self.telemetry.emit("serve_summary", **self.summary)

    def _summary_dict(self, wall: float) -> dict:
        reg = self.telemetry.registry
        ttft = reg.histogram("serve/ttft")
        lat = reg.histogram("serve/token_latency")
        qw = reg.histogram("serve/queue_wait")
        tpot = reg.histogram("serve/tpot")
        steps = max(self.stats["decode_steps"], 1)
        drafted = self.stats["draft_tokens"]
        return {
            "requests": len(self.results),
            "output_tokens": sum(r["output_tokens"] for r in self.results),
            "wall_s": round(wall, 6),
            "tokens_per_sec": round(
                sum(r["output_tokens"] for r in self.results)
                / max(wall, 1e-9), 2),
            "ttft_p50_s": ttft.p50, "ttft_p95_s": ttft.p95,
            "token_latency_p50_s": lat.p50, "token_latency_p95_s": lat.p95,
            "tpot_p50_s": tpot.p50, "tpot_p95_s": tpot.p95,
            "queue_wait_p50_s": qw.p50, "queue_wait_p95_s": qw.p95,
            "slot_occupancy": round(self.stats["occupancy_sum"] / steps, 4),
            "pool_peak_utilization": round(
                self.pool.peak_in_use / self.num_blocks, 4),
            "decode_steps": self.stats["decode_steps"],
            "decode_compiles": self.stats["decode_compiles"],
            "prefill_chunks": self.stats["prefill_chunks"],
            "decode_stall_ticks_max":
                self.stats["decode_stall_ticks_max"],
            "speculator": self.scfg.speculator,
            "draft_len": self.draft_len,
            "draft_tokens": drafted,
            "accepted_draft_tokens": self.stats["accepted_draft_tokens"],
            "acceptance_rate": (
                round(self.stats["accepted_draft_tokens"] / drafted, 4)
                if drafted else None),
            "preemptions": self.sched.n_preempted,
            "shed": self.sched.n_shed,
            "cancelled": self.stats["cancelled"],
            "slots": self.num_slots,
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
        }

    def close(self) -> None:
        if self._owns_telemetry:
            self.telemetry.close()
