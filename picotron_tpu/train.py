"""Training driver — `python -m picotron_tpu.train --config cfg.json`.

Parity with the reference's train.py (ref: train.py:57-281), single-controller:
load config -> initialize the (possibly multi-host) runtime -> build mesh,
dataloader, sharded train state (fresh, HF-bootstrapped, or resumed) -> step
loop with per-step tokens/s / MFU / memory logging -> periodic checkpointing.

What disappears relative to the reference: torchrun rank choreography, the
rank-0 config/tokenizer broadcasts (ref: train.py:152-165, data.py:23-32),
device placement flags, and the env-var dispatch channel — one process per
host runs ordinary Python and every collective lives inside the jitted step.

What the reference's loop lacks entirely: runtime fault tolerance. The step
loop here is wired through picotron_tpu/resilience — SIGTERM/SIGINT land as
a finished step + emergency checkpoint + exit 75 (auto_resume recovers
losslessly), divergence guards answer NaN/spike steps with skip / rollback /
abort, checkpoint and dataset I/O retry with backoff, and a watchdog turns a
hung step or stalled producer into a stack dump + exit 77 instead of a
silently burning reservation. All of it is testable on CPU via the chaos
harness (PICOTRON_CHAOS / resilience.chaos; tools/chaos.py runs whole
fault-recovery scenarios). See README "Fault tolerance".

Observability: the loop reports through picotron_tpu/telemetry — the
frozen stdout line, a per-host telemetry.jsonl event stream (step-phase
timings, goodput/badput ledger, resilience events, exact compile time),
and a rollback-safe wandb adapter; tools/telemetry_report.py summarizes a
stream post-hoc. See README "Observability".
"""

from __future__ import annotations

import argparse
import os
import time

import jax

from picotron_tpu.checkpoint import CheckpointManager, load_hf_safetensors
from picotron_tpu.config import Config, load_config, num_params
from picotron_tpu.models.llama import pad_layers_for_pp
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.mesh import MeshEnv, multihost_initialize
from picotron_tpu.parallel.api import (
    init_sharded_state, install_params, make_train_step,
)
from picotron_tpu.resilience import (
    EXIT_DIVERGED, EXIT_PREEMPTED, DivergenceGuard, GuardAction,
    PreemptionHandler, Watchdog, chaos, elastic,
)
from picotron_tpu.telemetry import Telemetry, bus as telemetry_bus
from picotron_tpu.train_step import TrainState
from picotron_tpu.utils import (
    StepTimer, device_memory_gb, device_peak_flops, human_format,
    is_logging_host, log_print, mfu, training_log_line,
)


def build_state(cfg: Config, menv: MeshEnv, tel: Telemetry = None) \
        -> tuple[TrainState, int, int, dict, str]:
    """(state, start_step, trained_tokens, ckpt_meta, resumed_from) — fresh
    init, HF weights, or resume, in the reference's precedence (ref:
    train.py:174-215: materialize weights, then load_checkpoint overrides).
    `resumed_from` is the checkpoint directory the state came from ("" when
    fresh): with auto_resume and no explicit load_path, the newest durable
    checkpoint in save_dir wins — preemption recovery."""
    state = init_sharded_state(cfg, menv, jax.random.key(cfg.training.seed))

    if cfg.checkpoint.init_from_hf:
        params = load_hf_safetensors(cfg.checkpoint.init_from_hf, cfg.model)
        params = pad_layers_for_pp(params, cfg.model.num_hidden_layers,
                                   cfg.distributed.pp_size)
        # install_params respects the optimizer-offload layout (pinned-host
        # master + bf16 device copy) as well as the standard fp32 layout
        state = install_params(cfg, menv, state, params)
        log_print(f"initialized weights from {cfg.checkpoint.init_from_hf}")

    load_dir = cfg.checkpoint.load_path
    mgr = None
    if not load_dir and cfg.checkpoint.auto_resume:
        probe = CheckpointManager(cfg, menv)
        # Durable AND manifest-verified: a bit-flipped/truncated newest
        # checkpoint makes the probe (and restore below) walk down the
        # lineage to the last known-good step — emitting ckpt_corrupt —
        # instead of resuming silently wrong.
        if probe.latest_valid_step() is not None:
            load_dir = probe.directory
            mgr = probe  # same dir — reuse, don't build a second manager
            log_print(f"auto_resume: found checkpoints in {load_dir}")

    if load_dir:
        if mgr is None:
            mgr = CheckpointManager(cfg, menv, directory=load_dir)
        # An elastic restore across a topology change is booked under the
        # `resize` goodput category, not `restore`, so shrink/grow cost is
        # measured apart from plain resumes. The phase name must be chosen
        # before the phase opens, so probe the newest valid step's source
        # topology up front (cheap manifest read; restore re-checks it
        # authoritatively).
        phase_name = "restore"
        if cfg.checkpoint.elastic:
            probe_step = mgr.latest_valid_step()
            if probe_step is not None:
                saved = elastic.saved_topology(mgr._step_dir(probe_step))
                here = elastic.topology_from_distributed(cfg.distributed)
                if elastic.topology_mismatch(saved, here):
                    phase_name = "resize"
        if tel is not None:
            with tel.phases.phase(phase_name):
                state, meta = mgr.restore(state)
        else:
            state, meta = mgr.restore(state)
        tokens = meta.get("trained_tokens", 0)
        resize = meta.get("elastic_resize")
        if resize:
            if tel is not None:
                tel.emit("elastic_resize", step=int(state.step),
                         **{k: resize[k] for k in ("from", "to", "axes")})
            log_print(
                f"elastic resize: restored step {int(state.step)} saved "
                f"at [{elastic.describe_topology(resize['from'])}] into "
                f"[{elastic.describe_topology(resize['to'])}] "
                f"(axes: {', '.join(resize['axes'])}; global batch "
                f"{cfg.global_batch_size} unchanged)")
        log_print(f"resumed from {load_dir} at step "
                  f"{int(state.step)} ({human_format(tokens)} tokens)")
        return state, int(state.step), tokens, meta, load_dir
    return state, 0, 0, {}, ""


def _emergency_checkpoint(cfg, menv, ckpt_mgr, state, trained_tokens, dl,
                          saved_steps):
    """Preemption landed: make the in-flight progress durable inside the
    grace window. Builds a manager on the spot when periodic saving was
    off — an emergency save must not depend on save_frequency."""
    mgr = ckpt_mgr if ckpt_mgr is not None else CheckpointManager(cfg, menv)
    step = int(state.step)
    if step not in saved_steps:
        path = mgr.save(state, trained_tokens, dataloader_state=dl.state)
        saved_steps.add(step)
        log_print(f"emergency checkpoint -> {path}")
    mgr.wait_until_finished()
    return mgr


def _rollback(ckpt_mgr, state, dl, step, trained_tokens, why):
    """Divergence-guard rollback: restore the last known-good checkpoint
    (durable AND manifest-verified — a corrupt newest step is skipped
    down the lineage, ckpt_integrity) and reposition the dataloader to
    the cursor AFTER the poison batch, so the resumed steps skip the data
    range that tripped the guard. Returns the restored (state, step,
    trained_tokens); escalates to EXIT_DIVERGED when there is nothing
    valid to roll back to."""
    if ckpt_mgr is None or ckpt_mgr.latest_valid_step() is None:
        log_print(f"[guard {step:06d}] {why}; rollback requested but no "
                  f"valid checkpoint exists — aborting "
                  f"(exit {EXIT_DIVERGED})")
        raise SystemExit(EXIT_DIVERGED)
    skip_to = dl.state  # position after the poison batch
    ckpt_mgr.wait_until_finished()
    state, meta = ckpt_mgr.restore(state)
    restored = int(state.step)
    dl.reset(skip_to)
    tokens = int(meta.get("trained_tokens", 0))
    log_print(f"[guard {step:06d}] {why}; rolled back to step {restored} "
              f"(skipping poisoned data through "
              f"epoch {skip_to['epoch']} cursor {skip_to['cursor']}); "
              f"was {human_format(trained_tokens)} tokens, "
              f"now {human_format(tokens)}")
    return state, restored, tokens


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="picotron-tpu trainer")
    ap.add_argument("--config", required=True, help="config JSON path "
                    "(reference-schema compatible)")
    args = ap.parse_args(argv)

    cfg = load_config(args.config)
    if cfg.distributed.use_cpu:
        # The reference's --use_cpu path (gloo + FLASH_ATTEN=0, ref:
        # create_config.py:64-66): run the full parallel layout on simulated
        # host devices. Must happen before any backend-initializing jax call.
        # Under the multi-process launcher contract each process provisions
        # only its share of the world's devices (the 2-process integration
        # test runs exactly this path). launcher_contract() validates the
        # PICOTRON_* vars as a unit, so a stale partial contract fails here
        # rather than as a confusing mesh-oversubscription error.
        from picotron_tpu.mesh import force_host_device_count, launcher_contract

        contract = launcher_contract()
        n_proc = contract[1] if contract else 1
        world = cfg.distributed.world_size
        if world % n_proc != 0:
            raise ValueError(
                f"world_size {world} not divisible by "
                f"PICOTRON_NUM_PROCESSES={n_proc}")
        # exact under a multi-process contract: an inherited XLA_FLAGS count
        # would otherwise over-provision every process (code review r3)
        force_host_device_count(world // n_proc, exact=n_proc > 1)
        jax.config.update("jax_platforms", "cpu")
    multihost_initialize()
    menv = MeshEnv.from_config(cfg)
    t = cfg.training

    # Fail-fast static pre-flight (tools/shardcheck.py is the full pass):
    # spec lint + donation/recompile hazards catch a mis-authored
    # PartitionSpec or a lost donation BEFORE any pod time is committed —
    # every one of these previously surfaced as a partitioner error or an
    # OOM at step 1 of a real run. Costs one extra abstract trace of the
    # step (seconds, vs the compile that follows anyway); set
    # PICOTRON_PREFLIGHT=0 to skip.
    from picotron_tpu.analysis import preflight

    if os.environ.get("PICOTRON_PREFLIGHT", "1") != "0":
        pre = preflight(cfg, menv)  # raises ShardcheckError with the report
        log_print(f"shardcheck preflight: ok "
                  f"({len(pre.warnings())} warning(s))")
        # Surface the sharding-dataflow audit verbatim: an implicit
        # (GSPMD-minted) reshard or an unproven jit entry is a perf smell
        # the operator should see at startup, each with the spec fix named
        # (analysis/dataflow.py, analysis/variants.py).
        for f in pre.warnings():
            if f.check in ("provenance", "variants"):
                log_print(f"shardcheck preflight WARNING: {f.render()}")
        prov = pre.info.get("provenance", {})
        if prov.get("sites") is not None:
            log_print(
                f"shardflow: {prov['ops_attributed']}/"
                f"{prov['ops_effective']} collective(s) attributed, "
                f"{prov['implicit_ops']} implicit, "
                f"{prov['boundary_reshards']} predicted reshard(s)")
        ts = pre.info.get("variants", {}).get("train_step", {})
        if ts.get("proven"):
            log_print("shardflow: train step proven compile-once "
                      f"({ts['leaves']} abstract leaves, 1 signature)")
        bnd = pre.info.get("boundary", {})
        if bnd.get("audited"):
            # slicecheck (analysis/boundary.py): the preflight raised above
            # on any ICI-only axis straddling the DCN cut, so reaching
            # here means every crossing collective is a declared one
            log_print(f"slicecheck: {bnd['slices']} slices, cut on "
                      f"[{bnd['cut_axes']}] — {bnd['boundary']} declared "
                      f"boundary op(s) over [{bnd['dcn_axes']}], "
                      f"{bnd['intra']} intra-slice, 0 violating "
                      f"({bnd['dcn_bytes']} B/step across DCN)")
        if cfg.checkpoint.save_frequency > 0:
            # Same fail-fast contract for the checkpoint store: an
            # unwritable save_dir or a disk without headroom for one
            # checkpoint must die here, not at the first periodic save
            # hours in (picotron_tpu/ckpt_integrity.preflight).
            from picotron_tpu.ckpt_integrity import preflight_save_dir

            est = preflight_save_dir(cfg)  # raises RuntimeError w/ story
            log_print(f"checkpoint preflight: ok ({cfg.checkpoint.save_dir}"
                      f", ~{est / 1e9:.2f} GB/checkpoint)")
        if (cfg.distributed.world_size > 1
                and os.environ.get("PICOTRON_COST_PREFLIGHT", "1") != "0"):
            # Advisory layout check (analysis/cost_model + planner): pure
            # arithmetic, milliseconds even at pod scale. Warn — never
            # fail — when the chosen layout is predicted >= 20% slower
            # than the planner's best at the same chip count, with the
            # overrides line that would close the gap. Threshold via
            # PICOTRON_COST_GAP (fraction); PICOTRON_COST_PREFLIGHT=0
            # disables.
            from picotron_tpu.analysis.cost_model import CostModel
            from picotron_tpu.analysis.planner import planner_gap

            cm = CostModel(jax.devices()[0].device_kind)
            cur, best, gap = planner_gap(cfg, cm)
            gap_bar = float(os.environ.get("PICOTRON_COST_GAP", "0.2"))
            log_print(f"cost preflight [{cm.gen.name}]: predicted "
                      f"{cur.total_s * 1e3:.4g} ms/step "
                      f"({cur.exposed_comm_s * 1e3:.4g} ms exposed comm)")
            if best is not None and gap >= gap_bar:
                log_print(
                    f"cost preflight WARNING: this layout is predicted "
                    f"{gap * 100:.0f}% slower than the planner's best at "
                    f"{cfg.distributed.world_size} chips "
                    f"({best.label}, {best.cost.total_s * 1e3:.4g} "
                    f"ms/step). To adopt it: {best.overrides_line()}")
                if (cfg.pipeline.executor == "spmd"
                        and cfg.distributed.pp_size > 1):
                    # When just flipping the executor (same layout)
                    # closes a material share of the gap, say so — it is
                    # a one-knob change, vs the full relayout above.
                    import dataclasses as _dc

                    from picotron_tpu.config import PipelineConfig

                    try:
                        twin = _dc.replace(
                            cfg, pipeline=PipelineConfig(executor="mpmd"))
                        twin.validate()
                        closed = cur.total_s - cm.predict(twin).total_s
                        gap_s = cur.total_s - best.cost.total_s
                        if gap_s > 0 and closed >= 0.2 * gap_s:
                            log_print(
                                f"cost preflight: pipeline.executor=mpmd "
                                f"alone (same layout) is predicted to "
                                f"close {closed / gap_s * 100:.0f}% of "
                                f"that gap — --override "
                                f"pipeline.executor=mpmd")
                    except (ValueError, KeyError):
                        pass  # layout can't host mpmd (offload/sp/MoE)

    n_chips = menv.world_size
    n_params = num_params(cfg.model)
    peak = device_peak_flops()
    log_print(
        f"model {cfg.model.name}: {human_format(n_params)} params | "
        f"mesh dp={menv.dp} pp={menv.pp} ep={menv.ep} cp={menv.cp} tp={menv.tp} "
        f"({n_chips} chips, {jax.devices()[0].device_kind}) | "
        f"global batch {cfg.global_batch_size} x seq {t.seq_length} = "
        f"{human_format(cfg.tokens_per_step)} tokens/step"
    )

    # Structured telemetry (picotron_tpu/telemetry; README
    # "Observability"): metrics registry + sinks (the frozen stdout line,
    # the per-host telemetry.jsonl next to the checkpoints, wandb), the
    # step-phase timer that doubles as the watchdog heartbeat source, the
    # goodput/badput ledger, and exact compile-time accounting. Installed
    # on the bus BEFORE the dataloader/state build so restore retries and
    # chaos events are captured from the first second.
    tel = telemetry_bus.install(Telemetry.from_config(cfg))
    if tel.jsonl_path:
        log_print(f"telemetry -> {tel.jsonl_path}")
    if tel.trace_path:
        log_print(f"flightdeck trace -> {tel.trace_path}")
    if cfg.distributed.pp_size > 1:
        # Book the analytic fill/drain share of every step into the
        # pp_bubble ledger category (both executors — the schedule table
        # implies the fraction either way), and let the MPMD executor's
        # sampled per-stage tick timings (PICOTRON_PP_TICK_SAMPLE) feed
        # the section/pp_stage* histograms the telemetry report reads.
        from picotron_tpu.parallel import mpmd

        tel.set_pp_bubble_fraction(mpmd.pipeline_bubble_fraction(cfg))
        log_print(f"pipeline: executor={cfg.pipeline.executor} "
                  f"schedule={cfg.pipeline.schedule} "
                  f"v={cfg.pipeline.interleave} — predicted bubble "
                  f"{tel.pp_bubble_fraction * 100:.1f}% of step wall")
        if cfg.pipeline.executor == "mpmd":
            def _stage_times(timings, _step, _tel=tel):
                for g, secs in sorted(timings.items()):
                    for s in secs:
                        _tel.observe_section(f"pp_stage{g}", s)

            mpmd.on_stage_times = _stage_times

    dl = MicroBatchDataLoader(cfg, menv)
    (state, start_step, trained_tokens, ckpt_meta,
     resumed_from) = build_state(cfg, menv, tel)
    tel.ledger.resume_from(start_step)
    if start_step > 0:
        # Fast-forward the dataloader so resume does not replay consumed
        # data (ADVICE r1). Checkpoints record the exact position; for ones
        # that predate that, derive it from the step count and the
        # tail-dropping epoch arithmetic.
        dl_state = ckpt_meta.get("dataloader")
        if dl_state is None:
            steps_per_epoch = max(1, len(dl.source) // cfg.global_batch_size)
            dl_state = {
                "epoch": start_step // steps_per_epoch,
                "cursor": (start_step % steps_per_epoch) * cfg.global_batch_size,
            }
        dl.set_state(dl_state)
    step_fn = make_train_step(cfg, menv)
    eval_batches = eval_fn = None
    if t.eval_frequency > 0:
        from picotron_tpu.data import build_eval_source
        from picotron_tpu.parallel.api import make_eval_step

        # Materialize a FIXED validation set once: every eval (and every
        # resumed run) scores the same batches, so the val_loss curve
        # reflects the model, not which slice of the split got sampled
        # (code review r3).
        eval_dl = MicroBatchDataLoader(cfg, menv,
                                       source=build_eval_source(cfg))
        eval_batches = [next(eval_dl) for _ in range(t.eval_steps)]
        eval_dl.close()
        eval_fn = make_eval_step(cfg, menv)
    ckpt_mgr = (CheckpointManager(cfg, menv)
                if cfg.checkpoint.save_frequency > 0 else None)

    wandb_run = None
    if cfg.logging.use_wandb and is_logging_host():
        try:
            import wandb
            wandb_run = wandb.init(project=cfg.logging.project_name,
                                   name=cfg.logging.run_name,
                                   config=cfg.to_json_dict())
            # The sink logs against a monotonic event counter with the
            # training step as a field (+ define_metric'd step axis):
            # wandb silently drops non-monotonic step= calls, which used
            # to erase every point after a guard rollback.
            tel.attach_wandb(wandb_run)
        except Exception as e:  # wandb optional; zero-egress pods have none
            log_print(f"wandb unavailable ({e}); continuing without")

    # Two stop conditions, whichever bites first: the step budget and the
    # token budget (ref: the config's max_tokens field).
    total_steps = t.total_train_steps
    if t.max_tokens is not None:
        remaining = max(0, t.max_tokens - trained_tokens)
        total_steps = min(total_steps,
                          start_step + -(-remaining // cfg.tokens_per_step))

    # Runtime resilience (picotron_tpu/resilience; README "Fault
    # tolerance"). Chaos installs LAST so the eval batches materialized
    # above cannot consume a data event meant for the training stream.
    rcfg = cfg.resilience
    ctrl = chaos.install(rcfg.chaos)
    if ctrl.active:
        log_print(f"chaos: {ctrl.describe()}")
    # The poisoned twin compiles lazily on first use; built only when the
    # chaos spec names a nan_grad event (injection must happen inside the
    # jitted step — see make_train_step).
    poison_step_fn = (make_train_step(cfg, menv, inject_nan=True)
                      if ctrl.has_nan_grad() else None)
    guard = (DivergenceGuard.from_config(rcfg)
             if rcfg.guard_policy != "off" else None)
    preempt = PreemptionHandler()
    watchdog = Watchdog(rcfg.watchdog_timeout)
    # One clock for liveness and timing: every phase entry below beats the
    # watchdog AND times the section for the goodput ledger.
    tel.attach_watchdog(watchdog)
    ph = tel.phases

    timer = StepTimer()
    last_logged_step = start_step
    # Steps whose checkpoint already exists in the SAVE directory: the loaded
    # step counts only when the resume source IS the save dir (explicit
    # load_path there, or auto_resume) — resuming from elsewhere must still
    # write a final save into save_dir.
    resumed_in_place = (
        resumed_from
        and os.path.abspath(resumed_from)
        == os.path.abspath(cfg.checkpoint.save_dir))
    saved_steps = {start_step} if resumed_in_place else set()
    prof = cfg.logging  # trace capture window (config.py LoggingConfig)
    tracing = False
    exit_code = None
    # A while loop, not a range: the rollback path rewinds `step` to the
    # restored checkpoint and the loop re-trains from there.
    step = start_step
    try:
        preempt.install()
        while step < total_steps:
            step += 1
            chaos.fire("step_begin", step=step)
            if (prof.profile_dir
                    and step - start_step == prof.profile_start_step):
                jax.profiler.start_trace(prof.profile_dir)
                tracing = True
            with ph.phase("data", step):
                batch = next(dl)
            with ph.phase("step", step):
                use_poison = (poison_step_fn is not None
                              and ctrl.poison_step(step))
                state, metrics = (poison_step_fn if use_poison
                                  else step_fn)(state, batch)
            trained_tokens += cfg.tokens_per_step
            if not watchdog.started:
                # Arm only after the first step completes: step 1 includes
                # XLA compilation, whose duration no sane timeout covers.
                watchdog.start()
            if (tracing and step - start_step
                    >= prof.profile_start_step + prof.profile_num_steps - 1):
                jax.block_until_ready(metrics)
                jax.profiler.stop_trace()
                tracing = False
                log_print(f"profiler trace -> {prof.profile_dir}")

            want_log = (step % cfg.logging.log_frequency == 0
                        or step == total_steps)
            fmetrics = None
            if guard is not None or want_log:
                with ph.phase("sync", step):
                    fmetrics = {k: float(v) for k, v in
                                jax.block_until_ready(metrics).items()}
            if guard is not None:
                action, why = guard.observe(
                    step, fmetrics["loss"],
                    grad_norm=fmetrics.get("grad_norm"),
                    nonfinite=fmetrics.get("nonfinite"))
                if action is not GuardAction.OK:
                    tel.emit("guard", action=action.value, step=step,
                             why=why)
                if action is GuardAction.ABORT:
                    log_print(f"[guard {step:06d}] {why}; aborting "
                              f"(exit {EXIT_DIVERGED})")
                    if tel.flight is not None:
                        tel.flight.dump("divergence_abort", step=step,
                                        why=why)
                    exit_code = EXIT_DIVERGED
                    break
                if action is GuardAction.SKIP:
                    if "spike" in why:
                        # Spikes are detected host-side AFTER the update
                        # applied; under 'skip' they can only be
                        # quarantined from the guard window.
                        log_print(f"[guard {step:06d}] {why}; quarantined "
                                  f"from the spike window (update already "
                                  f"applied — policy 'rollback' undoes it)")
                    else:
                        log_print(f"[guard {step:06d}] {why}; batch skipped "
                                  f"(update suppressed in-step, optimizer "
                                  f"state preserved)")
                elif action is GuardAction.ROLLBACK:
                    bad_step = step
                    if tel.flight is not None:
                        # Dump BEFORE restoring: the window still holds
                        # the diverging steps, and _rollback can itself
                        # exit (no valid checkpoint -> EXIT_DIVERGED).
                        tel.flight.dump("rollback", step=bad_step,
                                        why=why)
                    with ph.phase("rollback", step):
                        state, step, trained_tokens = _rollback(
                            ckpt_mgr, state, dl, step, trained_tokens, why)
                    # Steps (restored, bad_step] now re-run at-or-below
                    # the ledger's high-water mark -> booked as replay.
                    tel.emit("rollback", step=bad_step, restored=step,
                             why=why)
                    saved_steps.add(step)
                    last_logged_step = step
                    timer.lap()  # restart the throughput window
                    continue

            if want_log:
                loss = fmetrics.pop("loss")
                fmetrics.pop("nonfinite", None)  # guard plumbing, not a metric
                # Floor the wall-clock window: a ~0 s lap (resume-heavy
                # tests, clock quantization) must never print inf
                # tokens/s or inf MFU (mirrors PR 1's decode-timing guard).
                dt = max(timer.lap(), 1e-9)
                steps_in_window = step - last_logged_step
                last_logged_step = step
                tokens_per_sec = cfg.tokens_per_step * steps_in_window / dt
                mfu_frac = mfu(tokens_per_sec, cfg.model, t.seq_length,
                               n_chips, peak)
                mem_gb = device_memory_gb()
                line = training_log_line(
                    step, loss, tokens_per_sec, tokens_per_sec / n_chips,
                    mfu_frac, trained_tokens, mem_gb,
                    extras=fmetrics)
                # One record, every sink: stdout gets the preformatted
                # line byte-identically (the extract_metrics contract);
                # JSONL/wandb get the structured fields.
                tel.record_step(
                    step, line, loss=loss, tokens_per_sec=tokens_per_sec,
                    tokens_per_sec_per_chip=tokens_per_sec / n_chips,
                    mfu=mfu_frac, trained_tokens=trained_tokens,
                    memory_gb=mem_gb, **fmetrics)

            if eval_fn is not None and (step % t.eval_frequency == 0
                                        or step == total_steps):
                with ph.phase("eval", step):
                    # max(1, ...) guards the division alongside config.py's
                    # eval_steps >= 1 validation (defense in depth: a custom
                    # driver could hand-build a Config bypassing validate()).
                    val = (sum(float(eval_fn(state.params, b))
                               for b in eval_batches)
                           / max(1, len(eval_batches)))
                tel.record_eval(step, val,
                                f"[eval  {step:06d}] val_loss: {val:.4f} "
                                f"({t.eval_steps} batches)")

            if (ckpt_mgr is not None
                    and step % cfg.checkpoint.save_frequency == 0):
                with ph.phase("save", step):
                    path = ckpt_mgr.save(state, trained_tokens,
                                         dataloader_state=dl.state)
                saved_steps.add(step)
                log_print(f"saved checkpoint -> {path}")

            if preempt.triggered:
                # The in-flight step finished above; make it durable and
                # hand control back to the supervisor with the distinct
                # exit code auto_resume pairs with.
                with ph.phase("preempt-save", step):
                    ckpt_mgr = _emergency_checkpoint(
                        cfg, menv, ckpt_mgr, state, trained_tokens, dl,
                        saved_steps)
                tel.emit("preempted", step=step)
                if tel.flight is not None:
                    tel.flight.dump("preempted", step=step)
                log_print(f"preempted at step {step}; state is durable — "
                          f"exiting {EXIT_PREEMPTED} for auto_resume")
                exit_code = EXIT_PREEMPTED
                break

        if exit_code is None:
            # Final save, unless this run already wrote this exact step (a
            # resumed run whose budget was met trains zero steps; re-saving
            # the loaded step into its existing directory would make Orbax
            # fail an otherwise-clean exit). Tracked in-process so a stale
            # same-numbered checkpoint from an earlier run into the same
            # save_dir cannot suppress the save.
            if ckpt_mgr is not None and int(state.step) not in saved_steps:
                with ph.phase("save", int(state.step)):
                    ckpt_mgr.save(state, trained_tokens,
                                  dataloader_state=dl.state)
    except SystemExit:
        raise  # deliberate exits (rollback-without-ckpt) dumped above
    except BaseException as e:  # noqa: BLE001
        # Unhandled crash: leave the last-K-steps window next to the
        # checkpoints before the teardown below runs.
        if tel.flight is not None:
            tel.flight.dump("exception", step=step, error=repr(e))
        raise
    finally:
        # Always-run teardown: a mid-run crash must not leak the producer
        # thread, a half-written async checkpoint, an open trace, or a
        # dangling wandb run. Each step is fenced so one failing cleanup
        # cannot mask the original exception (or the other cleanups).
        watchdog.stop()
        preempt.uninstall()
        if tracing:
            try:
                jax.profiler.stop_trace()
                log_print(f"profiler trace -> {prof.profile_dir}")
            except Exception as e:  # noqa: BLE001
                log_print(f"profiler stop failed during shutdown: {e!r}")
        if ckpt_mgr is not None:
            # Async saves overlap training; the process must not exit
            # before the last one is durable.
            try:
                ckpt_mgr.wait_until_finished()
            except Exception as e:  # noqa: BLE001
                log_print(f"checkpoint finalization failed during "
                          f"shutdown: {e!r}")
        try:
            dl.close()
        except Exception as e:  # noqa: BLE001
            log_print(f"dataloader close failed during shutdown: {e!r}")
        # Writes the run_summary event (goodput ledger + metric snapshot),
        # closes the JSONL stream, finishes wandb (WandbSink.close), and
        # uninstalls the bus so a crashed run cannot leak a sink into the
        # next in-process run (tests).
        try:
            tel.close()
        except Exception as e:  # noqa: BLE001
            log_print(f"telemetry close failed during shutdown: {e!r}")
    if exit_code is not None:
        raise SystemExit(exit_code)
    log_print("training done")


if __name__ == "__main__":
    main()
