"""Pluggable telemetry sinks: stdout (frozen format), JSONL, wandb.

Every sink receives the same event dicts from the Telemetry facade and
serializes what it cares about:

- ``StdoutSink`` — the existing per-step console line. Its format is a
  de-facto API (tools/extract_metrics.py regex-parses it, same contract
  the reference has between train.py prints and its extract_metrics);
  the line arrives PREFORMATTED by utils.training_log_line so routing
  through telemetry cannot perturb a byte of it.
- ``JsonlSink`` — one JSON object per line, append-mode (a supervised
  restart into the same save_dir continues the same stream — that is how
  tools/telemetry_report.py sees replayed steps across restarts). Flushed
  per event: the interesting events are exactly the ones right before a
  crash/exit. Thread-safe (the watchdog/retry threads emit too).
- ``WandbSink`` — the wandb adapter. wandb silently DROPS log(step=...)
  calls whose step is lower than one already logged, so every point after
  a divergence-guard rollback would vanish from the dashboard. The sink
  therefore logs against its own monotonic event counter and carries the
  training step as an ordinary field, additionally `define_metric`-ing
  "step" as the x-axis where the wandb version supports it — both halves
  of the fix, so charts stay step-indexed AND post-rollback points
  survive.
"""

from __future__ import annotations

import json
import sys
import threading
from typing import Optional


class Sink:
    def emit(self, event: dict) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


class StdoutSink(Sink):
    """Prints preformatted console lines (events carrying a "line" field)
    from the logging host only — the same process gate utils.log_print
    applies, passed in so this module stays jax-free."""

    def __init__(self, is_primary: bool = True):
        self.is_primary = is_primary

    def emit(self, event: dict) -> None:
        line = event.get("line")
        if line is not None and self.is_primary:
            print(line)
            sys.stdout.flush()


class JsonlSink(Sink):
    """Append-mode JSONL writer with optional size-capped rotation.

    With ``max_bytes`` set, a stream that outgrows the cap is rotated
    once: the current file becomes ``<path>.1`` (replacing any previous
    rotation) and a fresh segment starts at ``<path>``. Readers that
    care about the whole saga (tools/telemetry_report.py,
    tools/extract_metrics.py — cross-restart replay counting needs
    event ORDER) read ``<path>.1`` first, then ``<path>``; see
    ``jsonl_segments``. Rotation happens on event boundaries, so no
    line is ever split across segments.
    """

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._f = open(path, "a")

    def emit(self, event: dict) -> None:
        # "line" is stdout presentation, not data — the structured fields
        # carry strictly more information.
        rec = {k: v for k, v in event.items() if k != "line"}
        with self._lock:
            if self._f.closed:
                return
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
            if self.max_bytes and self._f.tell() >= self.max_bytes:
                self._rotate()

    def _rotate(self) -> None:
        import os

        self._f.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass  # rotation is best-effort; keep appending in place
        self._f = open(self.path, "a")

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


# Event kinds a wandb dashboard wants as chart points; everything else
# (phase timings, chaos/retry bookkeeping) stays in the JSONL stream.
_WANDB_KINDS = ("step", "eval")


class WandbSink(Sink):
    def __init__(self, run):
        self.run = run
        self._seq = 0  # monotonic wandb step axis; never rewinds
        try:
            # Preferred fix where available: make the "step" FIELD the
            # x-axis for every metric, so charts read in training steps.
            run.define_metric("step")
            run.define_metric("*", step_metric="step")
        except Exception:  # noqa: BLE001 — older wandb / fake runs
            pass

    def emit(self, event: dict) -> None:
        if event.get("kind") not in _WANDB_KINDS:
            return
        data = {k: v for k, v in event.items()
                if k not in ("kind", "ts", "line") and v is not None}
        self._seq += 1
        self.run.log(data, step=self._seq)

    def close(self) -> None:
        try:
            self.run.finish()
        except Exception as e:  # noqa: BLE001 — mirror train.py's old fence
            print(f"wandb finish failed during shutdown: {e!r}",
                  file=sys.stderr)


def jsonl_segments(path: str) -> list:
    """Existing segments of a possibly-rotated JSONL stream, oldest
    first (``<path>.1`` then ``<path>``) — the read order that keeps
    cross-restart replay counting correct after rotation."""
    import os

    return [p for p in (path + ".1", path) if os.path.exists(p)]


def telemetry_jsonl_path(cfg, process_index: int = 0) -> Optional[str]:
    """Resolve the per-host JSONL path for a run config, or None when
    disabled. Process 0 owns the canonical `telemetry.jsonl` (next to the
    checkpoints, so run artifacts travel together); other hosts of a
    multi-process run write `telemetry.p<idx>.jsonl` beside it."""
    import os

    lg = cfg.logging
    if not lg.telemetry_jsonl:
        return None
    base = lg.telemetry_dir or cfg.checkpoint.save_dir
    os.makedirs(base, exist_ok=True)
    name = ("telemetry.jsonl" if process_index == 0
            else f"telemetry.p{process_index}.jsonl")
    return os.path.join(base, name)
