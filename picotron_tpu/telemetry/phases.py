"""Step-phase timer: one clock for timing AND watchdog liveness.

PR 2 interleaved `watchdog.beat(phase, step)` calls with ad-hoc wall-clock
reads; the two could drift (a new loop section timed but never beating, or
beating but invisible to timing). The phase timer is the single source:
entering a phase beats the watchdog with that phase name, leaving it hands
the measured duration to a callback (the Telemetry facade books it into
the histogram registry + goodput ledger and emits the JSONL phase event).
A section that exists for the timer therefore cannot be missed by the
watchdog, and vice versa.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable, Optional


class PhaseTimer:
    def __init__(self, on_phase: Callable[[str, float, Optional[int]], None],
                 watchdog=None,
                 on_enter: Optional[Callable[[str, Optional[int]], None]]
                 = None,
                 on_section: Optional[
                     Callable[[str, float, Optional[int]], None]] = None):
        self._on_phase = on_phase
        self._on_enter = on_enter
        self._on_section = on_section
        self.watchdog = watchdog

    @contextmanager
    def phase(self, name: str, step: Optional[int] = None):
        """Time one loop section. Beats the watchdog on ENTRY (the beat
        must land before the potentially-hanging work, not after) and
        books the duration on exit — including the exceptional exit, so a
        phase that dies mid-flight still accounts for the time it burned
        before the exception unwound. `on_enter` fires before the clock
        starts (the facade uses it to drain compile time that accrued
        OUTSIDE any phase, so it cannot be mis-attributed to this one)."""
        if self.watchdog is not None:
            self.watchdog.beat(name, step)
        if self._on_enter is not None:
            self._on_enter(name, step)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._on_phase(name, time.perf_counter() - t0, step)

    @contextmanager
    def section(self, name: str, step: Optional[int] = None):
        """Time a sub-span INSIDE a phase (a pipeline stage's ticks, a
        loss post-process). Sections feed the histogram registry only:
        no watchdog beat (the enclosing phase already armed it) and no
        ledger booking (their wall is part of the enclosing phase — a
        second booking would double-count the same seconds)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if self._on_section is not None:
                self._on_section(name, time.perf_counter() - t0, step)
