"""Recompile detection via jax.monitoring: exact compile-time accounting
plus a tripwire for unexpected re-jits of the train step.

XLA compilation is invisible to wall-clock phase timing (it just makes
step 1 — or, worse, a silently-recompiling step N — slow). jax.monitoring
publishes `/jax/core/compile/backend_compile_duration` for every backend
compile, so a registered listener measures compile time EXACTLY instead of
guessing from step-time outliers. The Telemetry facade drains the
accumulator at every phase boundary: the drained seconds are booked to the
`compile` goodput category (subtracted from the enclosing phase), and any
compile observed in a "step" phase after the first flags an unexpected
recompile — the classic symptoms being a shape-dtype drift or a weak-type
mismatch that shardcheck's hazard pass exists to catch statically.

jax.monitoring has no per-listener unregister (only a global clear), so
one module-level listener registers lazily on first install and routes to
whichever watch is currently active; inactive = zero overhead beyond a
None check.
"""

from __future__ import annotations

import threading

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_active: "CompileWatch | None" = None
_registered = False
_register_lock = threading.Lock()


def _listener(name: str, secs: float, **kw) -> None:
    watch = _active
    if watch is not None and name == _COMPILE_EVENT:
        watch._record(secs)


def _ensure_registered() -> bool:
    global _registered
    with _register_lock:
        if _registered:
            return True
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(_listener)
        except Exception:  # noqa: BLE001 — jax too old / stripped build
            return False
        _registered = True
        return True


class CompileWatch:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0
        self._secs = 0.0
        self.total_count = 0
        self.total_secs = 0.0
        self.supported = False

    def install(self) -> "CompileWatch":
        global _active
        self.supported = _ensure_registered()
        _active = self
        return self

    def uninstall(self) -> None:
        global _active
        if _active is self:
            _active = None

    def _record(self, secs: float) -> None:
        with self._lock:
            self._count += 1
            self._secs += secs
            self.total_count += 1
            self.total_secs += secs

    def drain(self) -> tuple[int, float]:
        """(compiles, seconds) since the previous drain — called at each
        phase boundary so compile time lands in the phase it occurred in."""
        with self._lock:
            out = (self._count, self._secs)
            self._count = 0
            self._secs = 0.0
        return out
