"""Goodput/badput ledger: where did the wall-clock actually go?

Every timed second of the run is booked to exactly one category:

- ``compute``      — the jitted train step doing productive work. The ONLY
                     goodput category: goodput% = compute / accounted.
- ``compile``      — XLA compilation (measured exactly via the
                     jax.monitoring backend-compile hook, subtracted from
                     whichever phase it occurred inside).
- ``replay``       — re-training steps at-or-below the high-water mark:
                     after a divergence-guard rollback those steps ran
                     before, so their time buys back lost ground, not new
                     progress.
- ``restore``      — checkpoint restore (rollback or resume).
- ``resize``       — an elastic restore: resuming a checkpoint saved at a
                     different topology (resilience/elastic.py), booked
                     apart from plain restores so shrink/grow cost is
                     measured, not guessed.
- ``ckpt_io``      — periodic checkpoint saves.
- ``preempt``      — preemption drain: the emergency save between SIGTERM
                     and exit 75.
- ``retry_backoff``— sleeps between I/O retry attempts.
- ``data_wait``    — the step loop blocked on the data producer (covers
                     injected/real data stalls).
- ``host_sync``    — device->host metric fetch for guards/logging.
- ``pp_bubble``    — the pipeline-parallel bubble share of the step
                     phase: fill/drain ticks where stages sit idle.
                     Analytic (schedule-table fraction from
                     parallel/mpmd.py, both executors), carved out of
                     the step's compute so goodput%% reflects that a
                     pp run's devices are not busy wall-to-wall.
- ``eval``         — validation passes.
- ``other``        — anything booked without a better class.
- ``prefill`` / ``decode`` / ``queue_wait`` — serving streams only
                     (picotron_tpu/serve): the engine's two jitted
                     programs (both goodput — tokens leaving the system)
                     and time requests sat queued before admission.
- ``handoff``      — disaggregated serving only (serve/disagg.py): the
                     prefill->decode KV-block transfer across the pool
                     boundary. Transport overhead, NOT goodput — the
                     number the cost model's price_kv_handoff predicts
                     and the decode pool must never wait on.
- ``shed``         — serving only (serve/fleet.py deadline admission):
                     queue seconds burned by requests REJECTED because
                     their wait already exceeded their deadline. Pure
                     badput — the time bought nothing, the request never
                     ran — booked apart from queue_wait (which admitted
                     requests recover by finishing) so an overload run's
                     report shows exactly what the load shedder threw
                     away.

The per-phase -> category mapping is shared with tools/telemetry_report.py
(PHASE_CATEGORY) so in-process booking and post-hoc JSONL analysis can
never disagree. Badput sources that KILL the process mid-phase (watchdog
stall, hard crash) never complete a phase, so their time shows up in the
report's `unaccounted` bucket (wall - accounted) plus the explicit
watchdog/stall events — the ledger only books what it observed end-to-end.
"""

from __future__ import annotations

# Training streams book "compute" only; serving streams (picotron_tpu/
# serve) book "prefill" and "decode" — both are the serving engine's
# productive device work. The two kinds of stream never book each
# other's categories, so adding the serving pair leaves every training
# report's goodput % untouched.
GOODPUT_CATEGORIES = ("compute", "prefill", "decode")

# Step-loop phase name -> ledger category. "step" is special-cased in
# book_phase (compute vs replay vs compile split); everything else maps
# statically. Shared with tools/telemetry_report.py.
PHASE_CATEGORY = {
    "data": "data_wait",
    "step": "compute",
    "sync": "host_sync",
    "eval": "eval",
    "save": "ckpt_io",
    "rollback": "restore",
    "restore": "restore",
    # elastic restore across a topology change (resilience/elastic.py):
    # train.py books the restore phase as "resize" when the checkpoint's
    # source topology differs from the run's mesh
    "resize": "resize",
    "preempt-save": "preempt",
}

CATEGORIES = (
    "compute", "compile", "replay", "restore", "resize", "ckpt_io",
    "preempt",
    "retry_backoff", "data_wait", "host_sync", "pp_bubble", "eval",
    "other",
    # serving (picotron_tpu/serve): device time in the two jitted
    # programs (goodput), the admission-latency badput, the
    # disaggregated engines' cross-pool KV transfer (badput: transport),
    # and queue seconds thrown away by deadline load shedding (badput)
    "prefill", "decode", "queue_wait", "handoff", "shed",
)


class GoodputLedger:
    def __init__(self) -> None:
        self.seconds: dict[str, float] = {}
        # Highest step whose "step" phase completed: a later booking at or
        # below it is re-training after a rollback -> replay, not compute.
        self.high_water_step = 0

    def book(self, category: str, secs: float) -> None:
        if secs <= 0:
            return
        if category not in CATEGORIES:
            category = "other"
        self.seconds[category] = self.seconds.get(category, 0.0) + secs

    def book_phase(self, phase: str, secs: float, step=None,
                   compile_secs: float = 0.0,
                   bubble_secs: float = 0.0) -> str:
        """Book one completed phase; returns the category the NON-compile
        remainder was booked under (what the phase event should carry).
        `compile_secs` is the exactly-measured XLA compile time that
        occurred inside this phase (recompile.CompileWatch) — booked as
        `compile` and subtracted, so step 1's wall does not masquerade as
        productive compute. `bubble_secs` is the pipeline-bubble share of
        a step phase (fraction × step wall, from the schedule table) —
        carved out of `compute` into `pp_bubble` so a pp run's goodput%%
        reflects the fill/drain idle time. Only compute is carved:
        a replayed step is already badput wall-to-wall."""
        compile_secs = min(max(compile_secs, 0.0), max(secs, 0.0))
        if compile_secs:
            self.book("compile", compile_secs)
            secs -= compile_secs
        category = PHASE_CATEGORY.get(phase, "other")
        if phase == "step" and step is not None:
            if step <= self.high_water_step:
                category = "replay"
            else:
                self.high_water_step = step
        if category == "compute":
            bubble_secs = min(max(bubble_secs, 0.0), max(secs, 0.0))
            if bubble_secs:
                self.book("pp_bubble", bubble_secs)
                secs -= bubble_secs
        self.book(category, secs)
        return category

    def resume_from(self, step: int) -> None:
        """Seed the high-water mark on an in-process restore (build_state
        resume): the restored step count is ground already covered."""
        self.high_water_step = max(self.high_water_step, int(step))

    @property
    def accounted(self) -> float:
        return sum(self.seconds.values())

    @property
    def goodput_seconds(self) -> float:
        return sum(self.seconds.get(c, 0.0) for c in GOODPUT_CATEGORIES)

    def goodput_fraction(self):
        total = self.accounted
        return (self.goodput_seconds / total) if total > 0 else None

    def summary(self) -> dict:
        frac = self.goodput_fraction()
        return {
            "accounted_seconds": round(self.accounted, 6),
            "goodput_seconds": round(self.goodput_seconds, 6),
            "goodput_pct": (round(100.0 * frac, 2)
                            if frac is not None else None),
            "seconds_by_category": {
                k: round(v, 6) for k, v in sorted(self.seconds.items())},
            "high_water_step": self.high_water_step,
        }
