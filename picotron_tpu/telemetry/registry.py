"""Metrics registry: counters, gauges, and windowed histograms.

The in-process metric store every sink reads from. Three instrument kinds,
deliberately tiny (this is a trainer, not a metrics platform):

- ``Counter`` — monotonically increasing event count (guard trips, retries,
  recompiles).
- ``Gauge`` — last-written scalar (trained_tokens, memory_gb).
- ``Histogram`` — distribution over a bounded retention window with
  p50/p95 percentiles (step time, per-phase durations). The window is the
  last `window` observations: for step-time triage the *recent*
  distribution is the one that matters (a straggler 40k steps ago should
  not dilute today's p95), and it bounds memory for million-step runs.
  Lifetime count/sum/min/max are kept exactly alongside.

All mutation is a single attribute assignment or deque append — atomic
under the GIL — so instruments can be fed from the retry/watchdog threads
without locks (same argument as Watchdog.beat).
"""

from __future__ import annotations

from collections import deque
from typing import Optional


class Counter:
    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    def __init__(self) -> None:
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    def __init__(self, window: int = 4096) -> None:
        self._window: deque[float] = deque(maxlen=window)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        self._window.append(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 100], over the retention window (nearest-rank on the
        sorted window — the conventional definition; no interpolation so
        every reported percentile is an actually-observed value)."""
        if not self._window:
            return None
        xs = sorted(self._window)
        # nearest-rank: ceil(q/100 * n), 1-based; clamp for q=0
        rank = max(1, -(-int(q * len(xs)) // 100)) if q > 0 else 1
        return xs[min(rank, len(xs)) - 1]

    @property
    def p50(self) -> Optional[float]:
        return self.percentile(50)

    @property
    def p95(self) -> Optional[float]:
        return self.percentile(95)


class MetricsRegistry:
    """Named instrument factory: `registry.counter("events/retry").inc()`.
    Instruments are created on first touch and live for the process."""

    def __init__(self) -> None:
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        return self._gauges.setdefault(name, Gauge())

    def histogram(self, name: str, window: int = 4096) -> Histogram:
        return self._histograms.setdefault(name, Histogram(window))

    def snapshot(self) -> dict:
        """Plain-dict view of every instrument — what the run_summary
        event and bench.py serialize."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, c in sorted(self._counters.items()):
            out["counters"][name] = c.value
        for name, g in sorted(self._gauges.items()):
            out["gauges"][name] = g.value
        for name, h in sorted(self._histograms.items()):
            out["histograms"][name] = {
                "count": h.count, "sum": round(h.sum, 6),
                "min": h.min, "max": h.max, "mean": h.mean,
                "p50": h.p50, "p95": h.p95,
            }
        return out
