"""Flight recorder: last-K-steps window dumped on abnormal exits.

A bounded in-memory ring of the most recent steps — per-step phase
timings, the step record's metrics, and (when a tracer is attached) the
step's spans — plus a deque of recent non-phase bus events. ``dump()``
serializes the window to ``flightdeck_postmortem.json`` in the run
directory, atomically, and never raises: it is called from the paths a
run dies on (watchdog ``os._exit(77)``, divergence abort/rollback,
preemption exit 75, the train loop's unhandled-exception path, sentinel
auto-dump) where a second failure must not mask the first.

The top-level ``step`` of a dump is the fault step as reported by the
caller (falling back to the last step the recorder saw) — the number a
chaos scenario asserts against its injected fault step.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

POSTMORTEM_NAME = "flightdeck_postmortem.json"

# Per-step span cap inside the ring: an MPMD step is O(ticks) spans and
# the postmortem must stay readable, not exhaustive.
_MAX_SPANS_PER_STEP = 512
# Fields stripped from recorded bus events: rendered console lines are
# bulk, not signal, in a postmortem.
_EVENT_DROP_FIELDS = ("line",)


class FlightRecorder:
    def __init__(self, dirpath: str, max_steps: int = 8,
                 max_events: int = 64, tracer=None):
        self.path = os.path.join(dirpath, POSTMORTEM_NAME)
        self.max_steps = int(max_steps)
        self.tracer = tracer
        self._ring: deque[dict] = deque(maxlen=self.max_steps)
        self._events: deque[dict] = deque(maxlen=int(max_events))
        self._phases: dict[str, float] = {}
        self._step: int | None = None
        self._mark = tracer.mark() if tracer is not None else 0
        self.dumps = 0

    # -- feeding (facade hooks) --------------------------------------

    def on_phase(self, phase: str, secs: float,
                 step: int | None = None) -> None:
        """Accumulate one phase timing into the in-flight step record."""
        self._phases[phase] = self._phases.get(phase, 0.0) + float(secs)
        if step is not None:
            self._step = int(step)

    def on_event(self, kind: str, fields: dict) -> None:
        """Remember a non-phase bus event (chaos, guard, rollback,
        preemption, watchdog, recompile, ...) in the recent-events
        deque."""
        ev = {"kind": kind}
        for k, v in fields.items():
            if k not in _EVENT_DROP_FIELDS:
                ev[k] = v
        self._events.append(ev)

    def on_step(self, step: int, fields: dict | None = None) -> None:
        """Close the in-flight step record and push it onto the ring."""
        rec: dict = {"step": int(step), "phases": {
            k: round(v, 6) for k, v in self._phases.items()}}
        if fields:
            rec["metrics"] = {
                k: v for k, v in fields.items()
                if k not in _EVENT_DROP_FIELDS
                and isinstance(v, (int, float, str))}
        if self.tracer is not None:
            spans = self.tracer.since(self._mark)
            if len(spans) > _MAX_SPANS_PER_STEP:
                rec["spans_dropped"] = len(spans) - _MAX_SPANS_PER_STEP
                spans = spans[-_MAX_SPANS_PER_STEP:]
            rec["spans"] = spans
            self._mark = self.tracer.mark()
        self._ring.append(rec)
        self._phases = {}
        self._step = int(step)

    # -- dumping -----------------------------------------------------

    def last_step(self) -> int | None:
        """Most recent step the recorder saw (in-flight or completed)."""
        if self._step is not None:
            return self._step
        if self._ring:
            return self._ring[-1]["step"]
        return None

    def snapshot(self, reason: str, step: int | None = None,
                 **extra) -> dict:
        steps = list(self._ring)
        if self._phases:  # the step that was in flight when we died
            partial: dict = {
                "step": self._step, "partial": True,
                "phases": {k: round(v, 6)
                           for k, v in self._phases.items()}}
            if self.tracer is not None:
                spans = self.tracer.since(self._mark)
                partial["spans"] = spans[-_MAX_SPANS_PER_STEP:]
            steps.append(partial)
        doc = {
            "reason": reason,
            "ts": time.time(),
            "step": step if step is not None else self.last_step(),
            "steps": steps,
            "recent_events": list(self._events),
        }
        if extra:
            doc["extra"] = extra
        return doc

    def dump(self, reason: str, step: int | None = None,
             **extra) -> str | None:
        """Write the postmortem; best-effort, returns the path or None.

        Multiple dumps overwrite (last writer wins): a rollback followed
        by a later fatal exit should leave the *later* window.
        """
        try:
            doc = self.snapshot(reason, step=step, **extra)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
            self.dumps += 1
            return self.path
        except Exception:
            return None
