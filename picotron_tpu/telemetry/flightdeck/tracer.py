"""Span tracer: an in-process Chrome-trace/Perfetto timeline.

One ``SpanTracer`` per process records complete spans (``ph="X"``) and
instant events (``ph="i"``) into a bounded in-memory list, exported as
the Chrome trace-event JSON format (the ``{"traceEvents": [...]}``
container Perfetto and chrome://tracing both load). Timestamps are
microseconds on the tracer's own monotonic clock, zeroed at
construction, so one export is one self-consistent timeline.

Thread-lane (``tid``) convention, kept stable so traces from different
runs line up:

* 0            train-loop phases (data/step/sync/eval/save/...)
* 1            serving request lifecycle (queue_wait/prefill/handoff/
               decode spans, tagged with request ids)
* 2            sentinel / flightdeck bookkeeping instants
* 100 + stage  MPMD pipeline stage lanes (one per local stage), carrying
               the per-op tick spans named ``stage/tick/op/mb`` — the
               same coordinates the watchdog's last-touch string uses.

The tracer is deliberately dumb: no nesting model, no flow events. A
span is one dict append under a lock; the disabled path (tracer absent)
is a single ``is not None`` check at every call site and allocates
nothing.
"""

from __future__ import annotations

import json
import os
import threading
import time

TID_TRAIN = 0
TID_SERVE = 1
TID_SENTINEL = 2
TID_PP_BASE = 100

_THREAD_NAMES = {
    TID_TRAIN: "train",
    TID_SERVE: "serve",
    TID_SENTINEL: "flightdeck",
}


class SpanTracer:
    """Bounded in-memory trace-event recorder.

    ``max_events`` caps memory on long runs: past the cap new events are
    counted in ``dropped`` instead of recorded (the export notes the
    drop count so a truncated trace is never mistaken for a quiet one).
    """

    def __init__(self, pid: int = 0, clock=time.perf_counter,
                 max_events: int = 500_000):
        self.pid = int(pid)
        self.clock = clock
        self._t0 = clock()
        self._events: list[dict] = []
        self._meta: dict[int, dict] = {}
        self._lock = threading.Lock()
        self.max_events = int(max_events)
        self.dropped = 0

    # -- recording ---------------------------------------------------

    def now(self) -> float:
        """Current time on the tracer's clock (seconds)."""
        return self.clock()

    def complete(self, name: str, tid: int = TID_TRAIN,
                 start_s: float | None = None, dur_s: float = 0.0,
                 **args) -> None:
        """Record a complete span (``ph="X"``).

        ``start_s`` is on the tracer's clock domain (``tracer.now()``);
        when None the span is back-dated ``dur_s`` seconds from now —
        the natural call shape for "phase just finished, took `secs`"
        hooks that only learn the duration after the fact.
        """
        if start_s is None:
            start_s = self.clock() - dur_s
        ev = {"name": name, "ph": "X", "pid": self.pid, "tid": int(tid),
              "ts": (start_s - self._t0) * 1e6,
              "dur": max(dur_s, 0.0) * 1e6}
        if args:
            ev["args"] = args
        self._push(tid, ev)

    def instant(self, name: str, tid: int = TID_TRAIN, **args) -> None:
        """Record an instant event (``ph="i"``, process scope)."""
        ev = {"name": name, "ph": "i", "s": "p", "pid": self.pid,
              "tid": int(tid), "ts": (self.clock() - self._t0) * 1e6}
        if args:
            ev["args"] = args
        self._push(tid, ev)

    def counter(self, name: str, tid: int = TID_SENTINEL,
                **series) -> None:
        """Record a counter sample (``ph="C"``)."""
        self._push(tid, {"name": name, "ph": "C", "pid": self.pid,
                         "tid": int(tid),
                         "ts": (self.clock() - self._t0) * 1e6,
                         "args": dict(series)})

    def thread_name(self, tid: int, name: str) -> None:
        """Label a lane (metadata event, emitted first in the export)."""
        with self._lock:
            self._meta[int(tid)] = {
                "name": "thread_name", "ph": "M", "pid": self.pid,
                "tid": int(tid), "ts": 0, "args": {"name": name}}

    def _push(self, tid: int, ev: dict) -> None:
        with self._lock:
            if int(tid) not in self._meta:
                label = _THREAD_NAMES.get(int(tid))
                if label is None and int(tid) >= TID_PP_BASE:
                    label = f"pp_stage{int(tid) - TID_PP_BASE}"
                if label is not None:
                    self._meta[int(tid)] = {
                        "name": "thread_name", "ph": "M",
                        "pid": self.pid, "tid": int(tid), "ts": 0,
                        "args": {"name": label}}
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    # -- snapshots (flight recorder) ---------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def mark(self) -> int:
        """Watermark for ``since`` — events recorded so far."""
        with self._lock:
            return len(self._events)

    def since(self, mark: int) -> list[dict]:
        """Copy of events recorded after a ``mark()`` watermark."""
        with self._lock:
            return list(self._events[mark:])

    # -- export ------------------------------------------------------

    def to_json(self) -> dict:
        """Chrome-trace document: metadata lanes first, spans sorted by
        timestamp (Perfetto tolerates unsorted input; the validator and
        humans prefer not to)."""
        with self._lock:
            meta = [self._meta[t] for t in sorted(self._meta)]
            events = sorted(self._events, key=lambda e: e["ts"])
            dropped = self.dropped
        doc = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
        if dropped:
            doc["otherData"] = {"dropped_events": dropped}
        return doc

    def export(self, path: str) -> str:
        """Atomically write the trace JSON; returns the path."""
        doc = self.to_json()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path
