"""flightdeck — span tracing, crash flight recorder, drift sentinel.

Three composable observability pieces that ride on the telemetry facade
(picotron_tpu/telemetry):

* ``SpanTracer`` (tracer.py): a low-overhead span timeline exported as
  Chrome-trace/Perfetto JSON. PhaseTimer phases, MPMD schedule ticks,
  serve request lifecycles, and resilience events all land on one
  timeline.
* ``FlightRecorder`` (flight.py): a bounded ring of the last N steps'
  phase timings + metrics + spans plus recent bus events, dumped to
  ``flightdeck_postmortem.json`` on every abnormal exit path.
* ``DriftSentinel`` (sentinel.py): an online monitor of step time,
  sync-phase share vs the cost model's predicted exposed comm, and
  data-wait share; a sustained breach emits one ``sentinel_alert``
  event and auto-dumps the flight recorder.

All three are *nullable attributes* on the Telemetry facade
(``tel.tracer`` / ``tel.flight`` / ``tel.sentinel``): when a piece is
not installed the hot-path hooks are a single ``is not None`` check —
no span objects, no dict churn, nothing allocated.
"""

from __future__ import annotations

from .flight import FlightRecorder
from .sentinel import DriftSentinel
from .tracer import (
    SpanTracer,
    TID_PP_BASE,
    TID_SENTINEL,
    TID_SERVE,
    TID_TRAIN,
)

__all__ = [
    "SpanTracer",
    "FlightRecorder",
    "DriftSentinel",
    "TID_TRAIN",
    "TID_SERVE",
    "TID_SENTINEL",
    "TID_PP_BASE",
    "install",
]


def install(tel, cfg=None, *, process_index: int = 0) -> None:
    """Attach flightdeck pieces to a Telemetry facade per its config.

    Policy (all overridable by constructing the pieces directly):

    * tracer  — only when ``logging.trace_dir`` is set (span recording
      costs a dict append per phase/tick; opt-in).
    * flight  — whenever the run has a directory to dump into
      (``logging.telemetry_dir`` or ``checkpoint.save_dir``) and
      ``logging.flight_steps > 0``; on by default so abnormal exits
      always leave a postmortem.
    * sentinel — only when ``logging.sentinel`` is true; seeded with the
      ICI cost model's prediction for the active config when that
      prediction is computable (pure arithmetic, no devices touched).
    """
    if cfg is None:
        return
    lg = getattr(cfg, "logging", None)
    if lg is None:
        return

    trace_dir = getattr(lg, "trace_dir", None)
    if trace_dir:
        import os

        os.makedirs(trace_dir, exist_ok=True)
        tel.tracer = SpanTracer(pid=process_index)
        tel.trace_path = os.path.join(
            trace_dir,
            "trace.json" if process_index == 0
            else f"trace.p{process_index}.json")

    flight_steps = int(getattr(lg, "flight_steps", 8) or 0)
    dump_dir = (getattr(lg, "telemetry_dir", None)
                or getattr(getattr(cfg, "checkpoint", None),
                           "save_dir", None))
    if flight_steps > 0 and dump_dir:
        import os

        os.makedirs(dump_dir, exist_ok=True)
        tel.flight = FlightRecorder(dump_dir, max_steps=flight_steps,
                                    tracer=tel.tracer)

    if getattr(lg, "sentinel", False):
        predicted = None
        try:
            from picotron_tpu.analysis.cost_model import CostModel

            sc = CostModel().predict(cfg)
            predicted = {"total_s": sc.total_s,
                         "exposed_comm_s": sc.exposed_comm_s}
        except Exception:
            predicted = None  # sentinel still watches rolling baselines
        tel.sentinel = DriftSentinel(
            window=int(getattr(lg, "sentinel_window", 32)),
            zscore=float(getattr(lg, "sentinel_zscore", 4.0)),
            ratio=float(getattr(lg, "sentinel_ratio", 1.5)),
            patience=int(getattr(lg, "sentinel_patience", 3)),
            predicted=predicted)
