"""Drift sentinel: online step-time/comm/data-wait regression watch.

Fed each step's phase timings by the telemetry facade, the sentinel
tracks three quantities:

* ``step_time``       — step + sync wall seconds, vs its own rolling
                        median (p50/p95 kept for reporting),
* ``sync_share``      — sync / step_time, vs the ICI cost model's
                        predicted exposed-comm share for the active
                        config when a prediction was supplied (the PR-6
                        predicted-vs-measured gap, watched online),
                        falling back to its rolling median otherwise,
* ``data_wait_share`` — data / (data + step_time), vs rolling median.

A quantity breaches when it exceeds ``ratio`` x its baseline (and, when
the rolling window has variance, ``zscore`` sigmas above it — the
z-test suppresses ratio trips on noisy-but-wide baselines; a flat
baseline falls through on ratio alone). ``patience`` consecutive
breaches of the same quantity raise one alert; the sentinel then
latches — a drifting run produces exactly one ``sentinel_alert``, not
one per step. Breaching samples are kept out of the rolling window so a
sustained regression cannot vote itself into the baseline before the
patience runs out.
"""

from __future__ import annotations

from collections import deque

_ROLLING = ("step_time", "sync_share", "data_wait_share")
# Share baselines below this are noise floors, not baselines — a ratio
# against ~0 would trip on the first nonzero sample.
_MIN_SHARE_BASELINE = 1e-3


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _pctile(xs: list[float], q: float) -> float:
    s = sorted(xs)
    rank = max(1, -(-int(q * len(s)) // 100)) if q > 0 else 1
    return s[min(rank, len(s)) - 1]


def _std(xs: list[float]) -> float:
    n = len(xs)
    if n < 2:
        return 0.0
    m = sum(xs) / n
    return (sum((x - m) ** 2 for x in xs) / (n - 1)) ** 0.5


class DriftSentinel:
    def __init__(self, window: int = 32, zscore: float = 4.0,
                 ratio: float = 1.5, patience: int = 3,
                 predicted: dict | None = None):
        self.window = max(int(window), 4)
        self.zscore = float(zscore)
        self.ratio = float(ratio)
        self.patience = max(int(patience), 1)
        self.predicted = predicted
        self.warmup = max(4, self.window // 4)
        self._hist: dict[str, deque] = {
            q: deque(maxlen=self.window) for q in _ROLLING}
        self._cur: dict[str, float] = {}
        self._streak: dict[str, int] = {q: 0 for q in _ROLLING}
        self.alerted = False
        self.alerts: list[dict] = []

    # -- feeding -----------------------------------------------------

    def observe_phase(self, phase: str, secs: float) -> None:
        if phase in ("data", "step", "sync"):
            self._cur[phase] = self._cur.get(phase, 0.0) + float(secs)

    def predicted_sync_share(self) -> float | None:
        p = self.predicted
        if not p:
            return None
        total = float(p.get("total_s") or 0.0)
        exposed = float(p.get("exposed_comm_s") or 0.0)
        if total <= 0.0:
            return None
        return exposed / total

    # -- judging -----------------------------------------------------

    def on_step(self, step: int) -> dict | None:
        """Fold the accumulated phases into the rolling windows; returns
        an alert dict exactly once when a sustained breach is found."""
        cur, self._cur = self._cur, {}
        step_s = cur.get("step", 0.0) + cur.get("sync", 0.0)
        if step_s <= 0.0:
            return None  # eval-only / phaseless iteration
        data_s = cur.get("data", 0.0)
        values = {
            "step_time": step_s,
            "sync_share": cur.get("sync", 0.0) / step_s,
            "data_wait_share": data_s / (data_s + step_s),
        }

        alert = None
        for q, val in values.items():
            hist = self._hist[q]
            baseline, z = self._baseline(q, hist)
            breach = self._is_breach(q, val, baseline, z)
            if breach:
                self._streak[q] += 1
            else:
                self._streak[q] = 0
                hist.append(val)
                continue
            if (self._streak[q] >= self.patience and not self.alerted
                    and alert is None):
                alert = {
                    "quantity": q,
                    "value": round(val, 6),
                    "baseline": round(baseline, 6),
                    "ratio": round(val / baseline, 4),
                    "streak": self._streak[q],
                    "step": int(step),
                    "window": len(hist),
                    "step_time_p50_s": round(
                        _pctile(list(self._hist["step_time"]), 50), 6)
                    if self._hist["step_time"] else None,
                    "step_time_p95_s": round(
                        _pctile(list(self._hist["step_time"]), 95), 6)
                    if self._hist["step_time"] else None,
                }
        if alert is not None:
            self.alerted = True
            self.alerts.append(alert)
        return alert

    def _baseline(self, q: str, hist: deque) -> tuple[float, float]:
        """(baseline, z-denominator std). Predicted baseline for
        sync_share when available; rolling median otherwise (0.0 while
        the window is still warming up — never judged)."""
        if q == "sync_share":
            pred = self.predicted_sync_share()
            if pred is not None:
                return pred, 0.0
        if len(hist) < self.warmup:
            return 0.0, 0.0
        xs = list(hist)
        return _median(xs), _std(xs)

    def _is_breach(self, q: str, val: float, baseline: float,
                   std: float) -> bool:
        if baseline <= 0.0:
            return False
        if q != "step_time" and baseline < _MIN_SHARE_BASELINE:
            return False
        if val < self.ratio * baseline:
            return False
        if std > 0.0 and (val - baseline) / std < self.zscore:
            return False
        return True

    # -- reporting ---------------------------------------------------

    def stats(self) -> dict:
        xs = list(self._hist["step_time"])
        out: dict = {"alerts": len(self.alerts),
                     "window": len(xs)}
        if xs:
            out["step_time_p50_s"] = round(_pctile(xs, 50), 6)
            out["step_time_p95_s"] = round(_pctile(xs, 95), 6)
        pred = self.predicted_sync_share()
        if pred is not None:
            out["predicted_sync_share"] = round(pred, 6)
        return out
