"""Module-level event bus — how library code reaches telemetry without
plumbing.

Same arrangement as resilience.chaos: checkpoint/data/resilience code
calls `bus.emit(...)` unconditionally; with no Telemetry installed (unit
tests, library use) the call is a None check and nothing else. train.main
installs the run's Telemetry, after which every emitted event reaches the
sinks and — when it carries `category` + `secs` — the goodput ledger.

Events from background threads (watchdog fire, retry backoff) are safe:
the JSONL sink locks, and ledger booking is a dict add under the GIL.
"""

from __future__ import annotations

_active = None


def install(telemetry):
    """Make `telemetry` the process-wide event target (None uninstalls).
    Returns it for chaining."""
    global _active
    _active = telemetry
    return telemetry


def active():
    return _active


def emit(kind: str, *, category: str | None = None,
         secs: float | None = None, **fields) -> None:
    """Emit one event. `category` + `secs` additionally book the time into
    the goodput ledger (e.g. retry backoff sleeps); bare events are
    record-only (chaos firings, guard trips, preemption signals)."""
    t = _active
    if t is not None:
        t.emit(kind, category=category, secs=secs, **fields)
