"""Structured telemetry: registry, sinks, phase timing, goodput ledger.

The observability substrate the training driver, bench harness, and
resilience machinery report through. One `Telemetry` facade owns:

- a `MetricsRegistry` (counters / gauges / p50-p95 histograms),
- the sink fan-out — stdout (the frozen log-line format
  tools/extract_metrics.py parses), a per-host `telemetry.jsonl` event
  stream next to the checkpoints, and the wandb adapter (rollback-safe:
  monotonic event counter, step as a field),
- a `PhaseTimer` that wraps the step loop's sections AND is the
  watchdog's heartbeat source — timing and liveness share one clock,
- a `GoodputLedger` classifying every accounted second (compute vs
  compile / ckpt I/O / restore+replay / preemption drain / retry backoff
  / data stall / ...), fed by the phases and by events the resilience
  modules emit through `telemetry.bus`,
- a `CompileWatch` (jax.monitoring) that measures XLA compile time
  exactly and flags unexpected re-jits of the step.

Post-hoc: `tools/telemetry_report.py` summarizes a JSONL stream (goodput
%, phase breakdown, event counts) for run triage; the per-phase category
mapping is shared so in-process and post-hoc accounting agree.

JSONL schema (one object per line; `ts` = time.time()):

  {"ts", "kind": "phase", "phase", "step", "secs", "category"}
  {"ts", "kind": "step",  "step", "loss", "tokens_per_sec",
   "tokens_per_sec_per_chip", "mfu", "trained_tokens", "memory_gb", ...}
  {"ts", "kind": "eval",  "step", "val_loss"}
  {"ts", "kind": <event>, ...}        # retry / chaos / guard / preempt /
                                      # recompile / watchdog_timeout ...
  {"ts", "kind": "run_summary", "goodput": {...}, "metrics": {...}}
"""

from __future__ import annotations

import time
from typing import Optional

from picotron_tpu.telemetry import bus
from picotron_tpu.telemetry.flightdeck.tracer import TID_SERVE, TID_TRAIN
from picotron_tpu.telemetry.goodput import (
    CATEGORIES, GOODPUT_CATEGORIES, PHASE_CATEGORY, GoodputLedger,
)
from picotron_tpu.telemetry.phases import PhaseTimer
from picotron_tpu.telemetry.recompile import CompileWatch
from picotron_tpu.telemetry.registry import (
    Counter, Gauge, Histogram, MetricsRegistry,
)
from picotron_tpu.telemetry.sinks import (
    JsonlSink, Sink, StdoutSink, WandbSink, telemetry_jsonl_path,
)

__all__ = [
    "CATEGORIES",
    "GOODPUT_CATEGORIES",
    "PHASE_CATEGORY",
    "CompileWatch",
    "Counter",
    "Gauge",
    "GoodputLedger",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "PhaseTimer",
    "Sink",
    "StdoutSink",
    "Telemetry",
    "WandbSink",
    "bus",
    "telemetry_jsonl_path",
]

# Serve-engine request-lifecycle phases: traced on the serve lane with
# their request ids rather than the train lane.
_SERVE_PHASES = frozenset(("queue_wait", "prefill", "decode", "handoff"))
# Resilience/fault event kinds rendered as trace instants so a timeline
# shows the fault next to the phase it interrupted.
_INSTANT_KINDS = frozenset((
    "chaos", "guard", "rollback", "preempted", "preempt_signal",
    "watchdog_timeout", "elastic_resize", "recompile", "retry",
    "sentinel_alert", "slice_lost"))


class Telemetry:
    """Facade wiring registry + sinks + phases + ledger + compile watch.

    Constructed once per run (train.main / bench), installed on the bus so
    library code reaches it, closed in the driver's teardown (writes the
    run_summary event). Sinks may be attached late (wandb initializes
    after the config banner; the watchdog after the resilience block) —
    everything else works from the first emitted event.
    """

    def __init__(self, sinks: Optional[list] = None, watchdog=None,
                 compile_watch: Optional[CompileWatch] = None):
        self.registry = MetricsRegistry()
        self.ledger = GoodputLedger()
        self.sinks: list = list(sinks or [])
        self.compile_watch = (compile_watch if compile_watch is not None
                              else CompileWatch().install())
        self.phases = PhaseTimer(self._phase_done, watchdog=watchdog,
                                 on_enter=self._phase_enter,
                                 on_section=self._section_done)
        self._step_phases_done = 0
        # Analytic pipeline-bubble share of each step phase (from the
        # schedule table, parallel/mpmd.pipeline_bubble_fraction) —
        # installed by the driver once per run, 0.0 when pp is off.
        self.pp_bubble_fraction = 0.0
        # flightdeck attachments (telemetry/flightdeck): all nullable —
        # the hot-path hooks below are a single `is not None` check when
        # a piece is absent, allocating nothing.
        self.tracer = None          # SpanTracer
        self.flight = None          # FlightRecorder
        self.sentinel = None        # DriftSentinel
        self.trace_path = None      # where close() exports the trace
        self._closed = False
        # Anchor the stream's wall-clock: compiles/setup before the first
        # phase would otherwise make the report's `accounted` exceed its
        # observed `wall`.
        self._fan_out({"ts": time.time(), "kind": "run_start"})

    # -- construction ------------------------------------------------------

    @classmethod
    def from_config(cls, cfg, watchdog=None) -> "Telemetry":
        import jax  # local: keep the package importable without a backend

        is_primary = jax.process_index() == 0
        sinks: list = [StdoutSink(is_primary=is_primary)]
        path = telemetry_jsonl_path(cfg, jax.process_index())
        if path is not None:
            max_mb = float(getattr(cfg.logging, "telemetry_max_mb", 0.0)
                           or 0.0)
            sinks.append(JsonlSink(
                path,
                max_bytes=int(max_mb * 1e6) if max_mb > 0 else None))
        tel = cls(sinks=sinks, watchdog=watchdog)
        from picotron_tpu.telemetry import flightdeck

        flightdeck.install(tel, cfg,
                           process_index=jax.process_index())
        return tel

    def attach_watchdog(self, watchdog) -> None:
        self.phases.watchdog = watchdog

    def set_pp_bubble_fraction(self, fraction: float) -> None:
        """Install the analytic pipeline-bubble share (schedule-table
        fraction of each step's wall spent in fill/drain idle). Every
        subsequent step phase carves this share of its compute into the
        `pp_bubble` ledger category."""
        self.pp_bubble_fraction = min(max(float(fraction), 0.0), 1.0)

    def attach_wandb(self, run) -> "WandbSink":
        sink = WandbSink(run)
        self.sinks.append(sink)
        return sink

    @property
    def jsonl_path(self) -> Optional[str]:
        for s in self.sinks:
            if isinstance(s, JsonlSink):
                return s.path
        return None

    # -- event plumbing ----------------------------------------------------

    def emit(self, kind: str, *, category: Optional[str] = None,
             secs: Optional[float] = None, book: bool = True,
             **fields) -> None:
        """Emit one event. `category` + `secs` book the time into the
        goodput ledger unless `book=False` (phase events arrive already
        booked by book_phase — re-booking would double-count)."""
        self.registry.counter(f"events/{kind}").inc()
        if book and category is not None and secs is not None:
            self.ledger.book(category, secs)
        event = {"ts": time.time(), "kind": kind, **fields}
        if category is not None:
            event["category"] = category
        if secs is not None:
            event["secs"] = round(secs, 6)
        self._fan_out(event)
        if self.tracer is not None:
            self._trace_event(kind, secs, fields)
        if self.flight is not None:
            if kind == "phase":
                self.flight.on_phase(fields.get("phase") or "?",
                                     secs or 0.0,
                                     step=fields.get("step"))
            elif kind not in ("compile", "pp_bubble"):
                self.flight.on_event(kind, fields)
        if self.sentinel is not None and kind == "phase" \
                and isinstance(secs, (int, float)):
            self.sentinel.observe_phase(fields.get("phase") or "", secs)

    def _trace_event(self, kind: str, secs, fields: dict) -> None:
        """Route one bus event onto the span timeline: phase events
        become complete spans (serve request phases on the serve lane,
        tagged with their request ids; everything else on the train
        lane), resilience/fault kinds become instants."""
        tr = self.tracer
        if kind == "phase":
            if not isinstance(secs, (int, float)):
                return
            phase = fields.get("phase") or "?"
            args = {k: fields[k] for k in ("id", "ids", "tokens", "step")
                    if fields.get(k) is not None}
            tid = TID_SERVE if phase in _SERVE_PHASES else TID_TRAIN
            tr.complete(phase, tid=tid, dur_s=secs, **args)
        elif kind == "compile" and isinstance(secs, (int, float)):
            args = ({"step": fields["step"]}
                    if fields.get("step") is not None else {})
            tr.complete("compile", tid=TID_TRAIN, dur_s=secs, **args)
        elif kind in _INSTANT_KINDS:
            args = {k: v for k, v in fields.items()
                    if isinstance(v, (int, float, str, bool))}
            tr.instant(kind, tid=TID_TRAIN, **args)

    def _fan_out(self, event: dict) -> None:
        for sink in self.sinks:
            try:
                sink.emit(event)
            except Exception:  # noqa: BLE001 — a sick sink must not kill a step
                pass

    def _phase_enter(self, name: str, step) -> None:
        """Drain compiles that accrued OUTSIDE any phase (jit init /
        warm-up between loop sections) before this phase's clock starts —
        left in the accumulator they would be drained at this phase's END
        and clamped against its wall, silently eating the phase (the
        sigterm-resume restore was booked as 0 this way)."""
        n_compiles, compile_secs = self.compile_watch.drain()
        if n_compiles:
            self.registry.counter("compile/count").inc(n_compiles)
            self.emit("compile", category="compile", secs=compile_secs,
                      phase=None, step=step, compiles=n_compiles)

    def _phase_done(self, name: str, secs: float, step) -> None:
        """PhaseTimer callback: drain exact compile time, book the ledger,
        feed the histograms, emit the phase event(s). The phase event's
        `secs` carries the NON-compile remainder and the compile share
        rides its own category="compile" event, so a post-hoc sum of
        (category, secs) pairs over the JSONL reproduces the ledger."""
        n_compiles, compile_secs = self.compile_watch.drain()
        compile_secs = min(compile_secs, max(secs, 0.0))
        bubble_secs = 0.0
        if name == "step" and self.pp_bubble_fraction > 0.0:
            bubble_secs = self.pp_bubble_fraction * max(
                secs - compile_secs, 0.0)
        category = self.ledger.book_phase(name, secs, step=step,
                                          compile_secs=compile_secs,
                                          bubble_secs=bubble_secs)
        if category != "compute":
            bubble_secs = 0.0  # ledger carves compute only (replay etc.)
        self.registry.histogram(f"phase/{name}").observe(secs)
        if n_compiles:
            self.registry.counter("compile/count").inc(n_compiles)
            self.emit("compile", category="compile", secs=compile_secs,
                      book=False, phase=name, step=step,
                      compiles=n_compiles)
            if name == "step" and self._step_phases_done > 0:
                # Re-jit of an already-compiled step: shape/dtype/weak-type
                # drift — exactly what analysis/hazards.py lints statically.
                self.registry.counter("compile/unexpected_recompiles").inc(
                    n_compiles)
                self.emit("recompile", step=step, compiles=n_compiles,
                          compile_secs=round(compile_secs, 6))
        if name == "step":
            self._step_phases_done += 1
        if bubble_secs > 0.0:
            # the bubble share rides its own category="pp_bubble" event
            # (like compile) so the JSONL (category, secs) sum still
            # reproduces the ledger exactly
            self.emit("pp_bubble", category="pp_bubble", secs=bubble_secs,
                      book=False, phase=name, step=step)
        self.emit("phase", category=category,
                  secs=secs - compile_secs - bubble_secs,
                  book=False, phase=name, step=step)

    def _section_done(self, name: str, secs: float, step) -> None:
        """PhaseTimer section callback: histogram only (see
        PhaseTimer.section for why sections never touch the ledger)."""
        self.registry.histogram(f"section/{name}").observe(secs)

    def observe_section(self, name: str, secs: float) -> None:
        """Record an externally-measured section duration (e.g. the MPMD
        executor's per-stage tick times, timed inside the schedule walker
        where a context manager cannot reach)."""
        self.registry.histogram(f"section/{name}").observe(secs)

    # -- step / eval records ----------------------------------------------

    def record_step(self, step: int, line: str, **fields) -> None:
        """One training-log record: the preformatted console `line` goes to
        stdout byte-identically; the structured fields go to JSONL/wandb."""
        self._fan_out({"ts": time.time(), "kind": "step", "step": step,
                       "line": line, **fields})
        if self.flight is not None:
            self.flight.on_step(step, fields)
        if self.sentinel is not None:
            alert = self.sentinel.on_step(step)
            if alert is not None:
                self.emit("sentinel_alert", **alert)
                if self.flight is not None:
                    self.flight.dump("sentinel_alert",
                                     step=alert.get("step", step),
                                     alert=alert)

    def record_eval(self, step: int, val_loss: float, line: str) -> None:
        self._fan_out({"ts": time.time(), "kind": "eval", "step": step,
                       "val_loss": val_loss, "line": line})

    # -- teardown ----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        summary = {"ts": time.time(), "kind": "run_summary",
                   "goodput": self.ledger.summary(),
                   "metrics": self.registry.snapshot()}
        if self.sentinel is not None:
            summary["sentinel"] = self.sentinel.stats()
        self._fan_out(summary)
        if self.tracer is not None and self.trace_path:
            try:
                self.tracer.export(self.trace_path)
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        self.compile_watch.uninstall()
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:  # noqa: BLE001
                pass
        if bus.active() is self:
            bus.install(None)
