"""Cross-entropy losses.

`cross_entropy`: the plain full-vocab loss (ref: train.py:49 and
pipeline_parallel.py:102-104 use F.cross_entropy over flattened logits).
Computed in fp32 with an ignore_index mask matching torch's default semantics
(mean over non-ignored tokens).

The vocab-parallel variant (no full-logit materialization — an improvement
over the reference's TP gather, ref: tensor_parallel.py:50) lives in
picotron_tpu/parallel/tp.py next to the TP collectives it needs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100


def cross_entropy_sum_count(logits: jnp.ndarray, targets: jnp.ndarray):
    """(sum of per-token NLL, number of non-ignored tokens) — the reduction
    pieces, so data-parallel shards can psum both and divide once (a per-shard
    mean followed by an unweighted pmean would mis-weight shards whose
    IGNORE_INDEX counts differ).

    logits: [..., vocab] (any float dtype; upcast to fp32)
    targets: [...] int labels, IGNORE_INDEX entries excluded.
    """
    logits = logits.astype(jnp.float32)
    valid = targets != IGNORE_INDEX
    safe_targets = jnp.where(valid, targets, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(
        logits, safe_targets[..., None], axis=-1
    ).squeeze(-1)
    nll = jnp.where(valid, logz - label_logit, 0.0)
    return jnp.sum(nll), jnp.sum(valid)


def cross_entropy(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Token-mean cross entropy over the non-ignored tokens."""
    total, count = cross_entropy_sum_count(logits, targets)
    return total / jnp.maximum(count, 1)
