"""Reference attention path: plain jnp scaled-dot-product attention with GQA.

This is the TPU analogue of the reference's SDPA fallback
(ref: picotron/model.py:155-158) and doubles as the ground truth that the
Pallas flash kernel and the context-parallel ring are tested against
(the reference tests TP the same way, against an unsharded nn.Linear).

Softmax statistics are computed in fp32 regardless of input dtype. The
log-sum-exp can be returned so the context-parallel ring can merge partial
results across K/V blocks (ref: context_parallel.py:112-128 keeps LSE for the
same reason).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """GQA: expand kv heads to match query heads.

    x: [batch, seq, kv_heads, head_dim] -> [batch, seq, kv_heads*n_rep, head_dim]
    (ref: model.py:142-143 uses repeat_interleave on the head axis).
    """
    if n_rep == 1:
        return x
    b, s, kv, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, kv, n_rep, d)).reshape(b, s, kv * n_rep, d)


def sdpa_attention_bwd_from_saved(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    out: jnp.ndarray,
    lse: jnp.ndarray,
    dout: jnp.ndarray,
    *,
    causal: bool = True,
    q_positions: jnp.ndarray | None = None,
    kv_positions: jnp.ndarray | None = None,
    sm_scale: float | None = None,
):
    """(dq, dk, dv) from the forward's saved (out, lse) — the flash-attn-2
    backward identity in plain jnp, the reference twin of the Pallas
    backward kernels (ops/flash_attention.py `_bwd`):

        p  = exp(s - lse)            (GLOBALLY normalized probabilities)
        dv = pᵀ @ dout
        ds = p * (dout @ vᵀ - delta),  delta = rowsum(dout * out)
        dq = ds @ k * scale,  dk = dsᵀ @ q * scale

    Because `p` is normalized by the *saved* lse (not a recomputed local
    one), calling this on one K/V block of a larger attention — with the
    block's positions and the GLOBAL (out, lse, dout) — yields exactly that
    block's additive contribution to the global gradients. That property is
    what the context-parallel ring backward sums over visiting blocks
    (ops/ring_attention.py ring_attention_bwd_from_saved); it does NOT hold
    for AD of a per-block forward, which normalizes by the block-local lse.

    Shapes follow sdpa_attention: q/out/dout [B, Sq, Hq, D]; k/v
    [B, Sk, Hkv, D] (GQA unexpanded — the group's query-head grads sum into
    the kv head); lse [B, Hq, Sq] fp32. Rows with no visible keys
    (lse = -inf) contribute zero everywhere.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n_rep = h // k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    kx = repeat_kv(k, n_rep)
    vx = repeat_kv(v, n_rep)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, kx,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        qp = q_positions if q_positions is not None else jnp.arange(sq)
        kp = kv_positions if kv_positions is not None else jnp.arange(sk)
        mask = qp[:, None] >= kp[None, :]
        scores = jnp.where(mask[None, None, :, :], scores, -1e30)
    lse_f = lse.astype(jnp.float32)[..., None]        # [B, H, Sq, 1]
    # exp(-1e30 - lse) underflows to exactly 0 for masked entries; a row
    # with lse = -inf (no visible keys anywhere) must also contribute 0.
    p = jnp.exp(scores - jnp.maximum(lse_f, -1e30))
    p = jnp.where(jnp.isinf(lse_f) & (lse_f < 0), 0.0, p)

    do32 = dout.astype(jnp.float32)
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)  # [B, Sq, Hq]
    delta = jnp.transpose(delta, (0, 2, 1))[..., None]        # [B, H, Sq, 1]
    dv_x = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", do32, vx.astype(jnp.float32))
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds,
                    kx.astype(jnp.float32)) * sm_scale
    dk_x = jnp.einsum("bhqk,bqhd->bkhd", ds,
                      q.astype(jnp.float32)) * sm_scale
    if n_rep > 1:
        dk_x = dk_x.reshape(b, sk, h // n_rep, n_rep, d).sum(axis=3)
        dv_x = dv_x.reshape(b, sk, h // n_rep, n_rep, d).sum(axis=3)
    return (dq.astype(q.dtype), dk_x.astype(k.dtype), dv_x.astype(v.dtype))


def sdpa_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    q_positions: jnp.ndarray | None = None,
    kv_positions: jnp.ndarray | None = None,
    return_lse: bool = False,
    sm_scale: float | None = None,
):
    """Scaled dot-product attention.

    q: [batch, q_len, q_heads, head_dim]
    k, v: [batch, kv_len, kv_heads, head_dim] — kv_heads may be smaller than
        q_heads (GQA); the expansion happens here, NOT in the caller, so
        parallel implementations (CP ring, flash kernel) can move/stream the
        small unexpanded K/V.
    q_positions/kv_positions: optional global position vectors; the causal
        mask is `q_pos >= kv_pos`, which generalizes to context-parallel
        shards where local index != global position.

    Returns out [batch, q_len, q_heads, head_dim] (and lse
    [batch, q_heads, q_len] fp32 if return_lse).
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if k.shape[2] != h:
        k = repeat_kv(k, h // k.shape[2])
        v = repeat_kv(v, h // v.shape[2])
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)

    # [B, H, Sq, Sk] in fp32 for stable softmax
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * sm_scale

    if causal:
        qp = q_positions if q_positions is not None else jnp.arange(sq)
        kp = kv_positions if kv_positions is not None else jnp.arange(sk)
        mask = qp[:, None] >= kp[None, :]  # [Sq, Sk]
        scores = jnp.where(mask[None, None, :, :], scores, -jnp.inf)

    m = jnp.max(scores, axis=-1, keepdims=True)
    # Fully-masked rows (non-square blocks in the CP ring) have m = -inf and
    # l = 0; they must produce out = 0 with lse = -inf so the ring's LSE merge
    # assigns them zero weight — not NaN from 0/0 or exp(-inf - -inf).
    m_safe = jnp.maximum(m, -1e30)
    p = jnp.exp(scores - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhqk,bkhd->bqhd", (p / l_safe).astype(v.dtype), v,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    # Named so the "dots" remat policy saves the attention output on this
    # reference path too (the flash path names its outputs inside the VJP
    # fwd rule — ops/flash_attention.py — so each impl names exactly once).
    out = checkpoint_name(out, "attn_out")
    if return_lse:
        lse = jnp.where(l == 0.0, -jnp.inf, m_safe + jnp.log(l_safe)).squeeze(-1)
        return out, checkpoint_name(lse, "attn_lse")  # lse: [B, H, Sq] fp32
    return out
