"""Mixture-of-experts layer: top-k router + capacity-bounded dispatch +
expert parallelism over the 'ep' mesh axis.

Beyond the reference (SURVEY §2.2 marks EP/MoE absent) — designed TPU-first:

- **Static shapes** (GShard-style capacity): every expert processes exactly
  `capacity` token slots per device; overflow tokens are dropped from the
  expert path (their residual stream passes through unchanged — top-k
  combine just contributes 0), underflow slots compute on zeros. XLA sees
  one fixed [E, C, H] einsum program, no data-dependent shapes.
- **Routing** (Mixtral-style): softmax over the top-k router logits, so the
  k gates sum to 1 per token. The load-balancing aux loss is the standard
  Switch/Mixtral `E * sum_e(frac_tokens_e * mean_router_prob_e)`.
- **Expert parallelism**: the expert bank [E, ...] is sharded over 'ep'
  (parallel/sharding.py). Dispatch builds per-device [E, C, H] slots, an
  `all_to_all` over 'ep' regroups them to [E/ep, ep*C, H] so each device
  runs only its experts over every device's slots, and a reverse
  `all_to_all` brings expert outputs home. With ep = 1 (or outside
  shard_map) both collectives are skipped and the math is identical.
- **TP composes**: the expert ffn dim is sharded over 'tp' like the dense
  MLP's; the caller's row-parallel exit hook psums the partial outputs.

The dispatch/combine uses scatter/gather by slot index (computed with one
[N*k, E] cumsum), not the [N, E, C] one-hot einsum of the original GShard —
the one-hot dispatch tensor is O(N*E*C) memory, which at train shapes
(N = 6k tokens) dwarfs the activations; slot scatter is O(N*k + E*C).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class Routing(NamedTuple):
    """Per-token routing decisions (all leading dim N = flattened tokens)."""

    expert_idx: jnp.ndarray   # [N, k] int32 — chosen expert per assignment
    gate: jnp.ndarray         # [N, k] fp32 — combine weight (top-k softmax)
    slot: jnp.ndarray         # [N, k] int32 — slot within the expert's
    #                           capacity buffer; >= capacity means dropped
    aux_loss: jnp.ndarray     # [] fp32 — load-balancing loss (unweighted)
    z_loss: jnp.ndarray       # [] fp32 — router z-loss (unweighted)


def route_topk(logits: jnp.ndarray, k: int,
               stat_axes: Optional[tuple] = None) -> Routing:
    """Top-k routing with slots assigned in token order.

    logits: [N, E] fp32 router outputs. Slot assignment is deterministic in
    token order (first-come priority); the CALLER drops assignments whose
    slot lands beyond its capacity (moe_mlp's `keep = slot < cap`).

    `stat_axes` names mesh axes to pmean the aux statistics over (must be
    inside shard_map): the balance loss's f/P and the z-loss token mean then
    describe the GLOBAL batch, making the losses layout-exact — a per-device
    statistic differs across dp/cp/ep layouts by O(shard variance) (VERDICT
    r2 weak #4). None keeps per-device statistics.

    z-loss (ST-MoE, Zoph et al. 2022 eq. 5): mean(logsumexp(logits)^2) —
    penalizes router logit drift; returned unweighted, the caller applies
    its coefficient.
    """
    n, e = logits.shape
    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                       # [N, E]
    top_p, top_i = lax.top_k(probs, k)                            # [N, k]
    # Mixtral renormalizes the k selected probabilities to sum to 1.
    gate = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    # slot_in_expert: for assignment (token t, choice j) -> how many earlier
    # assignments went to the same expert. Flatten [N, k] in token-major
    # order, one-hot over E, exclusive cumsum down the assignment axis.
    flat_e = top_i.reshape(-1)                                    # [N*k]
    onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.int32)           # [N*k, E]
    prior = jnp.cumsum(onehot, axis=0) - onehot                   # exclusive
    slot = jnp.take_along_axis(prior, flat_e[:, None], axis=1)[:, 0]
    slot = slot.reshape(n, k)

    def stat_mean(v):
        return lax.pmean(v, stat_axes) if stat_axes else v

    # Load-balancing aux (Switch eq. 4 / Mixtral): E * sum_e f_e * P_e where
    # f_e = fraction of assignments routed to e, P_e = mean router prob.
    # Equal-sized token shards make pmean-of-means the exact global mean.
    f = stat_mean(
        jnp.mean(jax.nn.one_hot(top_i, e, dtype=jnp.float32), axis=(0, 1)))
    p = stat_mean(jnp.mean(probs, axis=0))
    aux = e * jnp.sum(f * p)

    z = jax.nn.logsumexp(logits, axis=-1)                         # [N]
    z_loss = stat_mean(jnp.mean(z * z))

    return Routing(top_i.astype(jnp.int32), gate, slot.astype(jnp.int32),
                   aux, z_loss)


def _swiglu_experts(slots: jnp.ndarray, w_gate, w_up, w_down,
                    act=jax.nn.silu) -> jnp.ndarray:
    """Batched gated MLP over expert slots: [E_local, C', H] with weight
    banks [E_local, H, F] / [E_local, F, H]. bf16 MXU matmuls, fp32
    accumulation folded by XLA; mirrors the dense _mlp_block math (`act`
    is models.llama.mlp_act's choice — silu or gelu)."""
    dt = slots.dtype
    g = jnp.einsum("ech,ehf->ecf", slots, w_gate.astype(dt))
    u = jnp.einsum("ech,ehf->ecf", slots, w_up.astype(dt))
    return jnp.einsum("ecf,efh->ech", act(g) * u, w_down.astype(dt))


def moe_mlp(
    x: jnp.ndarray,
    router_w: jnp.ndarray,
    w_gate: jnp.ndarray,
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,
    *,
    num_experts: int,
    top_k: int,
    capacity_factor: float = 1.25,
    act=jax.nn.silu,
    ep_axis: Optional[str] = None,
    router_aux_coef: float = 0.0,
    router_z_coef: float = 0.0,
    stat_axes: Optional[tuple] = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """MoE feed-forward. x: [B, S, H]; router_w: [H, E]; expert banks
    [E_local, H, F] / [E_local, F, H] (E_local = E/ep under expert
    parallelism — the bank arrives pre-sharded inside shard_map).

    Returns (out [B, S, H] — partial over tp like the dense down-proj,
    aux [] — the PRE-WEIGHTED router loss `aux_coef * balance +
    z_coef * z`, drop_frac [] — fraction of routing assignments dropped by
    the capacity bound, an observability scalar the train log reports;
    capacity drops are otherwise silent). `ep_axis` names the mesh axis for
    the all_to_all pair; None = no expert parallelism (single device, or
    ep = 1). `stat_axes` makes the router statistics global (route_topk).

    Recompute contract: every op here is a deterministic function of
    (x, weights) — fp32 router logits, top_k, the slot cumsum, the
    capacity bound — so re-running this block on the same inputs
    reproduces the forward's routing bit-identically. Both remat (the AD
    engine under the dots/dots_attn policies) and the fused grad engine's
    backward segment VJP (parallel/fused_bwd.py) rely on that: they
    recompute the whole expert block from the saved layer input instead
    of saving the [E, C, H] dispatch buffers, and a nondeterministic
    tie-break here would silently diverge their gradients.
    """
    b, s, h = x.shape
    n = b * s
    e = num_experts
    ep = lax.psum(1, ep_axis) if ep_axis is not None else 1
    e_local = w_gate.shape[0]
    assert e_local * ep == e, (e_local, ep, e)
    # Per-device capacity per expert, padded to a lane-friendly multiple.
    cap = int(capacity_factor * top_k * n / e) + 1
    cap = -(-cap // 8) * 8

    flat = x.reshape(n, h)
    logits = (flat.astype(jnp.float32)
              @ router_w.astype(jnp.float32))                     # [N, E] fp32
    r = route_topk(logits, top_k, stat_axes=stat_axes)
    aux = router_aux_coef * r.aux_loss + router_z_coef * r.z_loss

    # ---- dispatch: scatter assignments into [E, cap, H] slot buffers ----
    keep = r.slot < cap                                           # [N, k]
    drop_frac = 1.0 - jnp.mean(keep.astype(jnp.float32))
    eidx = r.expert_idx.reshape(-1)                               # [N*k]
    sidx = jnp.where(keep, r.slot, cap - 1).reshape(-1)
    kflat = keep.reshape(-1)
    tok = jnp.repeat(jnp.arange(n), top_k)                        # [N*k]
    buf = jnp.zeros((e, cap, h), x.dtype)
    buf = buf.at[eidx, sidx].add(
        flat[tok] * kflat[:, None].astype(x.dtype), mode="drop")

    # ---- expert parallelism: regroup slots so each device runs only its
    # local experts over every ep-peer's slots ----
    if ep_axis is not None and ep > 1:
        # [E, cap, H] -> split E into (ep, E_local) -> all_to_all: trade the
        # ep groups so this device holds [E_local, ep*cap, H].
        buf = buf.reshape(ep, e_local, cap, h)
        buf = lax.all_to_all(buf, ep_axis, split_axis=0, concat_axis=0,
                             tiled=False)                         # [ep, El, cap, H]
        buf = jnp.moveaxis(buf, 0, 1).reshape(e_local, ep * cap, h)

    out_slots = _swiglu_experts(buf, w_gate, w_up, w_down, act=act)

    if ep_axis is not None and ep > 1:
        out_slots = out_slots.reshape(e_local, ep, cap, h)
        out_slots = jnp.moveaxis(out_slots, 1, 0)                 # [ep, El, cap, H]
        out_slots = lax.all_to_all(out_slots, ep_axis, split_axis=0,
                                   concat_axis=0, tiled=False)
        out_slots = out_slots.reshape(e, cap, h)

    # ---- combine: gather each assignment's slot, weight by its gate.
    # tok is arange(n) repeated k times in order, so the "scatter-add back
    # to tokens" is just a dense sum over the k assignment column ----
    picked = out_slots[eidx, sidx]                                # [N*k, H]
    w = (r.gate.reshape(-1) * kflat).astype(x.dtype)[:, None]
    out = (picked * w).reshape(n, top_k, h).sum(axis=1)
    return out.reshape(b, s, h), aux, drop_frac
