"""RMSNorm with fp32 statistics.

Reference semantics (ref: picotron/model.py:67-86): compute variance in fp32,
normalize, scale by a learned weight, return in the input dtype. On TPU a
plain jnp implementation fuses into surrounding ops under XLA, playing the
role of the reference's Triton kernel (ref: model.py:39-65) with zero custom
code; a Pallas variant is unnecessary (bandwidth-bound op, XLA emits an
optimal fusion).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    variance = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(variance + eps)
    # named so remat policies can opt into saving the normed output
    # ("dots_norms" trades ~2 activations/layer of HBM for skipping the
    # norm recompute in backward); a name alone changes nothing.
    return checkpoint_name((weight.astype(jnp.float32) * normed).astype(dtype),
                           "norm_out")
