"""Rotary position embeddings.

Matches the reference semantics (ref: picotron/model.py:12-31): non-interleaved
"rotate-half" RoPE with HF-compatible frequencies, tables computed in fp32 and
cast to the compute dtype at application time. One table pair serves all
layers (the reference recomputes identical tables per layer,
ref: model.py:199 — a pure waste we drop).

For context parallelism each cp shard applies the table rows of its own
contiguous sequence slice (ref: context_parallel.py:189-195); callers pass the
global positions of their local tokens instead of slicing tables by hand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def llama3_scale_freqs(inv_freq: jnp.ndarray, factor: float = 8.0,
                       low_freq_factor: float = 1.0,
                       high_freq_factor: float = 4.0,
                       original_max_position: int = 8192) -> jnp.ndarray:
    """Llama-3.1-style RoPE frequency scaling (the `rope_scaling:
    {"rope_type": "llama3"}` of Llama-3.1/3.2 HF configs): long-wavelength
    frequencies are divided by `factor` (context extension), short
    wavelengths are kept, and the band between `high_freq_factor` and
    `low_freq_factor` wavelengths-per-original-context interpolates
    smoothly between the two."""
    wavelen = 2.0 * jnp.pi / inv_freq
    low_wl = original_max_position / low_freq_factor
    high_wl = original_max_position / high_freq_factor
    # smooth factor in [0, 1]: 1 at high-frequency end, 0 at low-frequency
    smooth = (original_max_position / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor)
    smooth = jnp.clip(smooth, 0.0, 1.0)
    scaled = jnp.where(
        wavelen > low_wl, inv_freq / factor,
        jnp.where(wavelen < high_wl, inv_freq,
                  (1 - smooth) * inv_freq / factor + smooth * inv_freq))
    return scaled


def rope_tables(max_seq_len: int, head_dim: int, base: float = 10000.0,
                rope_scaling: dict | None = None):
    """Precompute cos/sin tables, shape [max_seq_len, head_dim // 2], fp32.

    `rope_scaling`: optional HF-style dict; supported `rope_type`s:
    "llama3" (Llama-3.1/3.2 frequency banding) and "linear" (positions
    divided by `factor`)."""
    assert head_dim % 2 == 0, "head_dim must be even for RoPE"
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (base ** exponent)  # [head_dim/2]
    if rope_scaling:
        kind = rope_scaling.get("rope_type", rope_scaling.get("type"))
        if kind == "llama3":
            inv_freq = llama3_scale_freqs(
                inv_freq,
                factor=rope_scaling.get("factor", 8.0),
                low_freq_factor=rope_scaling.get("low_freq_factor", 1.0),
                high_freq_factor=rope_scaling.get("high_freq_factor", 4.0),
                original_max_position=rope_scaling.get(
                    "original_max_position_embeddings", 8192))
        elif kind == "linear":
            inv_freq = inv_freq / rope_scaling.get("factor", 1.0)
        else:
            raise ValueError(
                f"unsupported rope_scaling type {kind!r} (supported: "
                f"'llama3', 'linear')")
    positions = jnp.arange(max_seq_len, dtype=jnp.float32)[:, None]  # [S, 1]
    angles = positions * inv_freq[None, :]  # [S, head_dim/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               positions: jnp.ndarray | None = None) -> jnp.ndarray:
    """Apply rotate-half RoPE.

    x:    [batch, seq, heads, head_dim]
    cos/sin: [max_seq, head_dim/2] tables from `rope_tables`
    positions: optional [seq] global positions of the local tokens (for CP
        shards); defaults to 0..seq-1.

    Equivalent to the reference's `x * cos + rotate_half(x) * sin` with
    `cos/sin` repeated (1,2) (ref: model.py:12-19,31) — written on the
    half-tables directly so no materialized repeat is needed.
    """
    seq_len = x.shape[1]
    if positions is None:
        if seq_len > cos.shape[0]:
            raise ValueError(
                f"sequence length {seq_len} exceeds the RoPE table length "
                f"{cos.shape[0]} (max_position_embeddings)"
            )
        c = cos[:seq_len]
        s = sin[:seq_len]
    else:
        # Bounds-check when positions are concrete (tracers — e.g. computed
        # from axis_index inside shard_map — can't be checked at trace time;
        # out-of-range gathers would silently clamp). The max itself can
        # come back traced even for a concrete `positions` when this runs
        # under an outer trace (a scan body closing over constant
        # positions), so concreteness is probed by attempting the int()
        # conversion — the public spelling (jax.errors) of the old
        # `isinstance(..., jax.core.Tracer)` checks, whose semi-private
        # namespace the shardcheck source lint forbids (ADVICE r5).
        try:
            pmax = int(jnp.max(positions))
        except jax.errors.ConcretizationTypeError:
            pmax = None  # traced: checkable only at runtime
        if pmax is not None and pmax >= cos.shape[0]:
            raise ValueError(
                f"position {pmax} exceeds the RoPE table length "
                f"{cos.shape[0]}")
        c = cos[positions]
        s = sin[positions]
    c = c[None, :, None, :]  # [1, S, 1, D/2]
    s = s[None, :, None, :]
    half = x.shape[-1] // 2
    x1 = x[..., :half]
    x2 = x[..., half:]
    # (x1, x2) * repeat(cos,2) + (-x2, x1) * repeat(sin,2)
    out1 = x1 * c - x2 * s
    out2 = x2 * c + x1 * s
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
