"""Ring attention: context-parallel causal attention over a 'cp' mesh axis.

TPU-native equivalent of the reference's ring attention
(ref: picotron/context_parallel/context_parallel.py:17-110 +
cp_communications.py): K/V blocks rotate around the cp ring while each device
computes blockwise attention of its local queries against the visiting block,
merging partial results with online-softmax LSE updates
(ref: context_parallel.py:157-187).

Design differences from the reference, all deliberate:

- **`lax.ppermute` instead of batched isend/irecv.** The ring neighbors the
  reference derives from its process-group singleton
  (ref: process_group_manager.py:43-44) are just the cp axis ordering; XLA
  lowers the ppermute to an ICI collective-permute and its latency-hiding
  scheduler overlaps it with the blockwise attention compute — the manual
  comm/compute overlap the reference codes by hand (ref:
  context_parallel.py:30-45).
- **No custom backward on the AD path.** The reference hand-writes a
  110-line autograd Function whose backward runs a second ring for dK/dV
  accumulators (ref: context_parallel.py:54-110) because torch cannot
  differentiate through its P2P calls. JAX transposes `ppermute` natively
  (the transpose is the inverse permutation), so reverse-mode AD derives
  exactly that dK/dV ring for free. The fused grad engine — which never
  re-runs the forward — instead enters through
  `ring_attention_bwd_from_saved`: `return_lse=True` saves the globally
  merged LSE, and the backward is a second forward ring whose per-block
  grads (normalized by the saved LSE) are exactly additive, with dK/dV
  accumulators traveling the ring alongside their blocks.
- **GQA-aware**: the unexpanded K/V heads travel the ring (smaller transfers);
  head expansion happens inside the blockwise kernel.
- **Positions are explicit.** Causality across blocks is decided by global
  token positions, so the same code is correct for any sequence layout.
  The default layout is the reference's contiguous split
  (ref: data.py:105-109), whose known causal load imbalance
  (SURVEY.md §3.4) is inherent to the layout, not to this kernel.

Block-skip note: a visiting block that is entirely in the causal future
(min kv position > max q position) skips the whole blockwise kernel via
`lax.cond` — the per-rank skip the reference does with Python control flow
(`step <= rank`, ref: context_parallel.py:36). The branch is exact: a fully
masked block would have contributed (out=0, lse=-inf), which is precisely
what the skip branch returns, so layouts are bit-compatible with
full compute. Under the default zigzag layout every block pair is partially
visible and the branch never fires (work is balanced by construction);
under `cp_layout: "contiguous"` rank r skips cp-1-r of its cp visiting
blocks, halving the layout's average wasted FLOPs. The branch body is
collective-free (a pure kernel call), which keeps the divergent cond
SPMD-sound — see parallel/pp.py's branch rules.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from picotron_tpu.ops.attention import sdpa_attention


def _merge(out_acc, lse_acc, out_blk, lse_blk):
    """Online-softmax merge of two partial attention results.

    out: [B, S, H, D] fp32, lse: [B, H, S] fp32 (-inf where no keys attended).
    Numerically-stable log-space merge — same role as the reference's
    sigmoid/logsigmoid update (ref: context_parallel.py:157-187).
    """
    m = jnp.maximum(lse_acc, lse_blk)
    # Guard fully-masked rows (m = -inf): exp(-inf - -inf) would be NaN.
    m_safe = jnp.where(jnp.isinf(m) & (m < 0), 0.0, m)
    w_acc = jnp.exp(lse_acc - m_safe)  # 0 where lse_acc = -inf
    w_blk = jnp.exp(lse_blk - m_safe)
    denom = w_acc + w_blk
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    # Renormalize so out stays the *normalized* attention over every block
    # seen so far (invariant: out = sum_i out_i * exp(lse_i - lse_total)).
    wa = w_acc / denom_safe
    wb = w_blk / denom_safe
    # [B, H, S] -> [B, S, H, 1] to weight the outputs
    out = (out_acc * jnp.transpose(wa, (0, 2, 1))[..., None]
           + out_blk * jnp.transpose(wb, (0, 2, 1))[..., None])
    lse = m_safe + jnp.log(denom_safe)
    lse = jnp.where(denom == 0.0, -jnp.inf, lse)
    return out, lse


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis: str = "cp",
    q_positions: jnp.ndarray | None = None,
    attn_block=None,
    return_lse: bool = False,
) -> jnp.ndarray:
    """Causal ring attention over the named mesh axis `axis`.

    Must be called inside shard_map with `axis` in scope. Each device holds
    the contiguous sequence shard of its cp index:

      q:    [B, S_local, Hq, D]
      k, v: [B, S_local, Hkv, D]   (Hkv <= Hq, GQA unexpanded)

    q_positions: optional [S_local] global positions of the local tokens;
        defaults to the contiguous layout `cp_index * S_local + arange`.
    attn_block: blockwise attention implementation with the signature of
        `sdpa_attention(..., return_lse=True)`; defaults to the jnp reference
        path (the Pallas flash kernel slots in here).
    return_lse: also return the GLOBALLY merged log-sum-exp
        [B, Hq, S_local] fp32 — the per-shard statistic the fused grad
        engine saves so `ring_attention_bwd_from_saved` can run the
        backward ring without re-running the forward.

    Returns [B, S_local, Hq, D] in q.dtype (and the merged lse when
    `return_lse`).
    """
    n = lax.psum(1, axis)  # static axis size
    s_local = q.shape[1]
    my = lax.axis_index(axis)
    if q_positions is None:
        q_positions = my * s_local + jnp.arange(s_local)
    if attn_block is None:
        attn_block = partial(sdpa_attention, return_lse=True)

    b, _, h, d = q.shape
    out_acc = jnp.zeros((b, s_local, h, d), jnp.float32)
    lse_acc = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)

    # Send K/V to the next cp index, receive from the previous — after step t
    # this device holds the block originating at cp index (my - t) mod n
    # (ref: cp_communications.py:22-36 builds the same ring). The position
    # vector travels the ring WITH its K/V block, so any sequence layout
    # (contiguous, zigzag, ...) masks correctly without this function knowing
    # the layout — each block's positions are simply its owner's q_positions.
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    kv_positions = q_positions

    q_max = jnp.max(q_positions)

    for step in range(n):
        # Whole-block causal skip: blocks entirely in the future contribute
        # exactly (out=0, lse=-inf). The skip branch anchors its constants
        # on zero-weighted elements of the compute branch's operands so the
        # branches agree on varying type without pcast (whose transpose
        # would put a psum inside the divergent backward branch — the
        # rendezvous-deadlock hazard documented in parallel/pp.py).
        kv_pos = kv_positions

        def compute(opnds, kv_pos=kv_pos):
            q_, k_, v_ = opnds
            ob, lb = attn_block(q_, k_, v_, causal=True,
                                q_positions=q_positions,
                                kv_positions=kv_pos)
            return ob.astype(jnp.float32), lb.astype(jnp.float32)

        def skip(opnds):
            q_, k_, v_ = opnds
            a = (q_.ravel()[0] + k_.ravel()[0]
                 + v_.ravel()[0]).astype(jnp.float32) * 0.0
            return (jnp.zeros((b, s_local, h, d), jnp.float32) + a,
                    jnp.full((b, h, s_local), -jnp.inf, jnp.float32) + a)

        fully_masked = jnp.min(kv_pos) > q_max
        out_blk, lse_blk = lax.cond(fully_masked, skip, compute, (q, k, v))
        out_acc, lse_acc = _merge(out_acc, lse_acc, out_blk, lse_blk)
        if step != n - 1:
            # deliberate unroll: ring attention IS one ppermute per hop
            k = lax.ppermute(k, axis, fwd_perm)  # shardcheck: ok
            v = lax.ppermute(v, axis, fwd_perm)  # shardcheck: ok
            kv_positions = lax.ppermute(  # shardcheck: ok
                kv_positions, axis, fwd_perm)

    if return_lse:
        return out_acc.astype(q.dtype), lse_acc
    return out_acc.astype(q.dtype)


def ring_attention_bwd_from_saved(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    out: jnp.ndarray,
    lse: jnp.ndarray,
    dout: jnp.ndarray,
    axis: str = "cp",
    q_positions: jnp.ndarray | None = None,
    sm_scale: float | None = None,
    block_bwd=None,
):
    """(dq, dk, dv) for the causal K/V ring from the forward's saved
    (out, lse) — the manual-VJP entry for the fused grad engine
    (parallel/fused_bwd.py), mirroring `flash_attention_bwd_from_saved`.

    The forward ring's saved statistics make the backward a SECOND forward
    ring, not a transpose of the first: because the saved lse is the
    globally merged one, each visiting block's grads computed against it
    (p = exp(s - lse_global); delta = rowsum(dout·out) global) are that
    block's exact additive contribution to the global gradients — the
    structure the reference hand-writes as its 110-line backward ring
    (ref: context_parallel.py:54-110) and that Mesh-Attention (arxiv
    2512.20968) exploits for communication-efficient distributed backward.
    dQ accumulates locally; each visiting block's dK/dV accumulators travel
    the ring WITH their block (the same forward `ppermute` permutation) and
    a final ppermute delivers them home after the full circle.

    Shapes follow `ring_attention`: q/out/dout [B, S_local, Hq, D], k/v
    [B, S_local, Hkv, D], lse [B, Hq, S_local] fp32 (the `return_lse`
    form). q/k arrive in the same (pre-rotated) form the forward ring
    consumed. `block_bwd` has `flash_attention_bwd_from_saved`'s signature
    (the default; the sdpa twin runs on non-TPU backends). Fully-future
    visiting blocks skip their kernel via the same collective-free
    `lax.cond` as the forward — their contribution is exactly zero.
    """
    from picotron_tpu.ops.flash_attention import flash_attention_bwd_from_saved

    n = lax.psum(1, axis)
    s_local = q.shape[1]
    my = lax.axis_index(axis)
    if q_positions is None:
        q_positions = my * s_local + jnp.arange(s_local)
    if block_bwd is None:
        block_bwd = flash_attention_bwd_from_saved

    dq_acc = jnp.zeros(q.shape, jnp.float32)
    dk_acc = jnp.zeros(k.shape, jnp.float32)
    dv_acc = jnp.zeros(v.shape, jnp.float32)
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    kv_positions = q_positions
    q_max = jnp.max(q_positions)

    for step in range(n):
        kv_pos = kv_positions

        def compute(opnds, kv_pos=kv_pos):
            q_, k_, v_ = opnds
            dq_b, dk_b, dv_b = block_bwd(
                q_, k_, v_, out, lse, dout, causal=True,
                q_positions=q_positions, kv_positions=kv_pos,
                sm_scale=sm_scale)
            return (dq_b.astype(jnp.float32), dk_b.astype(jnp.float32),
                    dv_b.astype(jnp.float32))

        def skip(opnds):
            q_, k_, v_ = opnds
            a = (q_.ravel()[0] + k_.ravel()[0]
                 + v_.ravel()[0]).astype(jnp.float32) * 0.0
            return (jnp.zeros(q_.shape, jnp.float32) + a,
                    jnp.zeros(k_.shape, jnp.float32) + a,
                    jnp.zeros(v_.shape, jnp.float32) + a)

        fully_masked = jnp.min(kv_pos) > q_max
        dq_b, dk_b, dv_b = lax.cond(fully_masked, skip, compute, (q, k, v))
        dq_acc = dq_acc + dq_b
        dk_acc = dk_acc + dk_b
        dv_acc = dv_acc + dv_b
        if step != n - 1:
            # deliberate unroll: one K/V + dK/dV rotation per ring hop
            k = lax.ppermute(k, axis, fwd_perm)  # shardcheck: ok
            v = lax.ppermute(v, axis, fwd_perm)  # shardcheck: ok
            kv_positions = lax.ppermute(  # shardcheck: ok
                kv_positions, axis, fwd_perm)
            dk_acc = lax.ppermute(dk_acc, axis, fwd_perm)  # shardcheck: ok
            dv_acc = lax.ppermute(dv_acc, axis, fwd_perm)  # shardcheck: ok
    # After n-1 rotations this device holds block (my+1) mod n and its
    # accumulated grads; one more forward hop delivers every block's dK/dV
    # back to its owner (n hops total = the identity permutation).
    dk_acc = lax.ppermute(dk_acc, axis, fwd_perm)
    dv_acc = lax.ppermute(dv_acc, axis, fwd_perm)
    return (dq_acc.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))
