"""Core numerical ops: RoPE, RMSNorm, attention implementations, losses.

TPU-native replacements for the reference's imported CUDA/Triton kernels
(SURVEY.md §2.3): flash-attn -> Pallas flash attention, Triton RMSNorm ->
jnp RMSNorm (XLA fuses it), fused rotary -> jnp rotary fused by XLA.
"""

from picotron_tpu.ops.rope import rope_tables, apply_rope  # noqa: F401
from picotron_tpu.ops.rmsnorm import rms_norm  # noqa: F401
from picotron_tpu.ops.attention import sdpa_attention  # noqa: F401
from picotron_tpu.ops.flash_attention import flash_attention  # noqa: F401
from picotron_tpu.ops.ring_attention import ring_attention  # noqa: F401
from picotron_tpu.ops.losses import cross_entropy, cross_entropy_sum_count  # noqa: F401
