"""Ulysses (DeepSpeed-style) all-to-all sequence parallelism.

An alternative context-parallel schedule to the K/V ring
(ops/ring_attention.py): instead of rotating K/V blocks around the 'cp'
ring for cp steps, ONE all_to_all pair per attention call trades the
sequence sharding for a head sharding —

    q/k/v [B, S/cp, H, D]  --all_to_all-->  [B, S, H/cp, D]

so each device runs ordinary full-sequence attention (the Pallas flash
kernel, fused RoPE and all) over its head subset, and the output rides the
reverse all_to_all home. Communication volume per call is 2x activations
(vs the ring's (cp-1)/cp x K/V per step but cp steps), and the attention
itself needs no LSE merging or causal-step bookkeeping.

Positions travel via an all_gather so any sequence layout works — with the
zigzag CP layout the gathered sequence is position-permuted and the
position-masked flash kernel handles it unchanged.

Constraint: local head counts (after TP) must be divisible by cp — q AND kv
heads (GQA); config.validate enforces it. The ring has no such constraint,
which is why both schedules exist (`attn_impl: "ring" | "ulysses"`).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax


def _scatter_heads(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """[B, S_local, H, D] -> [B, S, H/cp, D]: split heads over `axis`,
    concatenate the sequence shards (in device order)."""
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def _gather_heads(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Inverse of _scatter_heads: [B, S, H/cp, D] -> [B, S_local, H, D]."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis: str = "cp",
    q_positions: Optional[jnp.ndarray] = None,
    attn_fn: Callable,
    rope=None,
    seq_sort=None,
    full_positions=None,
    positions_static: bool = False,
) -> jnp.ndarray:
    """Full-sequence attention over seq-sharded q/k/v [B, S_local, H, D].

    attn_fn(q, k, v, causal=True, q_positions=..., kv_positions=..., \
            rope=...) runs the per-device attention (flash_attention — gets
    the fused-RoPE path; its position-based causal mask handles any
    gathered sequence order).

    seq_sort: optional static [S] permutation sorting the GATHERED sequence
    by global position. Under the zigzag cp layout the gathered order is
    position-interleaved, which would leave almost every attention tile
    position-straddling (defeating the flash kernel's unmasked fast path
    and block skipping); the layout permutation is known at trace time, so
    sorting costs two static gathers and restores ring-free full-sequence
    attention on a monotone sequence. The caller (parallel/api.py) derives
    it from the configured cp layout.

    full_positions: optional static [S] global positions of the gathered
    (device-order) sequence — when the layout is known at trace time
    (parallel/api.py passes it), this skips a per-call all_gather of
    positions in the jitted hot path.

    positions_static: caller's declaration that `full_positions` is a
    trace-time constant (a numpy array, not a traced value). The caller
    knows this statically — parallel/api.py derives the layout from the
    config — so no runtime tracer-probing is needed here (the old
    `isinstance(..., jax.core.Tracer)` probe leaned on a semi-private
    namespace; ADVICE r5 / the shardcheck source lint forbids it).
    """
    s_local = q.shape[1]
    if full_positions is not None:
        pos_full = jnp.asarray(full_positions)
    else:
        if q_positions is None:
            # this shard's contiguous slice of the global sequence (same
            # default as ring_attention)
            q_positions = lax.axis_index(axis) * s_local + jnp.arange(s_local)
        # positions of the gathered sequence, in the same device-order the
        # all_to_all concatenates shards
        pos_full = lax.all_gather(q_positions, axis, axis=0, tiled=True)

    qh = _scatter_heads(q, axis)
    kh = _scatter_heads(k, axis)
    vh = _scatter_heads(v, axis)
    if seq_sort is not None:
        inv = jnp.argsort(jnp.asarray(seq_sort))
        pos_full = pos_full[seq_sort]
        qh, kh, vh = (x[:, seq_sort] for x in (qh, kh, vh))
    # When the (possibly sorted) gathered positions are STATICALLY the
    # plain 0..S-1 — contiguous layout, or zigzag restored by seq_sort —
    # hand the kernel positions=None so its static-causal fast path fires
    # (program-id block classes + DMA-free skipped tiles; this is the
    # long-sequence path where that ~20% kernel overhead matters most,
    # code review r5). Decidable only for trace-time-known positions,
    # which the caller declares via `positions_static`.
    pos_arg = pos_full
    if full_positions is not None and positions_static:
        import numpy as np

        fp = np.asarray(full_positions)
        if seq_sort is not None:
            fp = fp[np.asarray(seq_sort)]
        if np.array_equal(fp, np.arange(fp.shape[0])):
            pos_arg = None
    kwargs = {} if rope is None else {"rope": rope}
    out = attn_fn(qh, kh, vh, causal=True, q_positions=pos_arg,
                  kv_positions=pos_arg, **kwargs)
    if seq_sort is not None:
        out = out[:, inv]
    return _gather_heads(out, axis)
