"""Ulysses (DeepSpeed-style) all-to-all sequence parallelism.

An alternative context-parallel schedule to the K/V ring
(ops/ring_attention.py): instead of rotating K/V blocks around the 'cp'
ring for cp steps, ONE all_to_all pair per attention call trades the
sequence sharding for a head sharding —

    q/k/v [B, S/cp, H, D]  --all_to_all-->  [B, S, H/cp, D]

so each device runs ordinary full-sequence attention (the Pallas flash
kernel, fused RoPE and all) over its head subset, and the output rides the
reverse all_to_all home. Communication volume per call is 2x activations
(vs the ring's (cp-1)/cp x K/V per step but cp steps), and the attention
itself needs no LSE merging or causal-step bookkeeping.

Positions travel via an all_gather so any sequence layout works — with the
zigzag CP layout the gathered sequence is position-permuted and the
position-masked flash kernel handles it unchanged.

Constraint: local head counts (after TP) must be divisible by cp — q AND kv
heads (GQA); config.validate enforces it. The ring has no such constraint,
which is why both schedules exist (`attn_impl: "ring" | "ulysses"`).

The fused grad engine enters through `ulysses_attention_bwd_from_saved`:
the forward (`return_lse=True`) saves the INNER-domain LSE, and the
backward replays the identical all_to_all pair in both directions around
the flash bwd-from-saved kernel — the forward kernel never re-runs.
`ulysses_static_layout` is the single source of the gathered-sequence
layout both directions share.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax


def ulysses_static_layout(cfg):
    """(full_positions, seq_sort) for the GATHERED sequence, as trace-time
    numpy constants derived from the config's cp layout — the single source
    both the forward wiring (parallel/api.py) and the fused grad engine
    (parallel/fused_bwd.py) build their Ulysses calls from, so the two
    paths cannot disagree about the gathered order. full_positions is the
    dataloader's layout permutation (arange when contiguous); seq_sort is
    the static argsort restoring a monotone sequence (None when already
    monotone), which re-enables the flash kernel's static-causal fast
    path."""
    import numpy as np

    from picotron_tpu.data import cp_sequence_permutation

    layout_perm = cp_sequence_permutation(cfg)
    full_pos = (np.asarray(layout_perm) if layout_perm is not None
                else np.arange(cfg.training.seq_length))
    seq_sort = np.argsort(full_pos) if layout_perm is not None else None
    return full_pos, seq_sort


def _scatter_heads(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """[B, S_local, H, D] -> [B, S, H/cp, D]: split heads over `axis`,
    concatenate the sequence shards (in device order)."""
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def _gather_heads(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Inverse of _scatter_heads: [B, S, H/cp, D] -> [B, S_local, H, D]."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis: str = "cp",
    q_positions: Optional[jnp.ndarray] = None,
    attn_fn: Callable,
    rope=None,
    seq_sort=None,
    full_positions=None,
    positions_static: bool = False,
    return_lse: bool = False,
) -> jnp.ndarray:
    """Full-sequence attention over seq-sharded q/k/v [B, S_local, H, D].

    attn_fn(q, k, v, causal=True, q_positions=..., kv_positions=..., \
            rope=...) runs the per-device attention (flash_attention — gets
    the fused-RoPE path; its position-based causal mask handles any
    gathered sequence order).

    seq_sort: optional static [S] permutation sorting the GATHERED sequence
    by global position. Under the zigzag cp layout the gathered order is
    position-interleaved, which would leave almost every attention tile
    position-straddling (defeating the flash kernel's unmasked fast path
    and block skipping); the layout permutation is known at trace time, so
    sorting costs two static gathers and restores ring-free full-sequence
    attention on a monotone sequence. The caller (parallel/api.py) derives
    it from the configured cp layout.

    full_positions: optional static [S] global positions of the gathered
    (device-order) sequence — when the layout is known at trace time
    (parallel/api.py passes it), this skips a per-call all_gather of
    positions in the jitted hot path.

    positions_static: caller's declaration that `full_positions` is a
    trace-time constant (a numpy array, not a traced value). The caller
    knows this statically — parallel/api.py derives the layout from the
    config — so no runtime tracer-probing is needed here (the old
    `isinstance(..., jax.core.Tracer)` probe leaned on a semi-private
    namespace; ADVICE r5 / the shardcheck source lint forbids it).

    return_lse: also return the inner attention's log-sum-exp
    [B, H_local, S] fp32, in the INNER (head-sharded, seq_sort-ed) domain —
    the save the fused grad engine pairs with
    `ulysses_attention_bwd_from_saved`.
    """
    pos_arg, inv = _inner_positions(q.shape[1], axis, q_positions, seq_sort,
                                    full_positions, positions_static)
    qh = _scatter_heads(q, axis)
    kh = _scatter_heads(k, axis)
    vh = _scatter_heads(v, axis)
    if seq_sort is not None:
        qh, kh, vh = (x[:, seq_sort] for x in (qh, kh, vh))
    kwargs = {} if rope is None else {"rope": rope}
    if return_lse:
        out, lse = attn_fn(qh, kh, vh, causal=True, q_positions=pos_arg,
                           kv_positions=pos_arg, return_lse=True, **kwargs)
    else:
        out = attn_fn(qh, kh, vh, causal=True, q_positions=pos_arg,
                      kv_positions=pos_arg, **kwargs)
    if seq_sort is not None:
        out = out[:, inv]
    out = _gather_heads(out, axis)
    # lse stays in the INNER (head-sharded, sorted, full-sequence) domain
    # [B, H_local, S] fp32: the backward re-derives the inner q/k/v/out by
    # re-running the exact all_to_all + sort permutations (bit-exact), so
    # the lse never needs un/re-sorting round trips.
    return (out, lse) if return_lse else out


def _inner_positions(s_local: int, axis: str, q_positions, seq_sort,
                     full_positions, positions_static: bool):
    """(pos_arg, inv) for the inner full-sequence attention: the gathered
    (and seq_sort-ed) position vector — or None when it is STATICALLY the
    plain 0..S-1 (contiguous layout, or zigzag restored by seq_sort), so
    the kernel's static-causal fast path fires (program-id block classes +
    DMA-free skipped tiles; the long-sequence path where that ~20% kernel
    overhead matters most, code review r5). Static-ness is decidable only
    for trace-time-known positions, which the caller declares via
    `positions_static`. `inv` is the static un-sort permutation (None when
    no sort)."""
    if full_positions is not None:
        pos_full = jnp.asarray(full_positions)
    else:
        if q_positions is None:
            # this shard's contiguous slice of the global sequence (same
            # default as ring_attention)
            q_positions = lax.axis_index(axis) * s_local + jnp.arange(s_local)
        # positions of the gathered sequence, in the same device-order the
        # all_to_all concatenates shards
        pos_full = lax.all_gather(q_positions, axis, axis=0, tiled=True)
    inv = None
    if seq_sort is not None:
        inv = jnp.argsort(jnp.asarray(seq_sort))
        pos_full = pos_full[seq_sort]
    pos_arg = pos_full
    if full_positions is not None and positions_static:
        import numpy as np

        fp = np.asarray(full_positions)
        if seq_sort is not None:
            fp = fp[np.asarray(seq_sort)]
        if np.array_equal(fp, np.arange(fp.shape[0])):
            pos_arg = None
    return pos_arg, inv


def ulysses_attention_bwd_from_saved(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    out: jnp.ndarray,
    lse: jnp.ndarray,
    dout: jnp.ndarray,
    *,
    axis: str = "cp",
    q_positions: Optional[jnp.ndarray] = None,
    attn_bwd: Optional[Callable] = None,
    rope=None,
    seq_sort=None,
    full_positions=None,
    positions_static: bool = False,
    sm_scale: Optional[float] = None,
):
    """(dq, dk, dv) for Ulysses attention from the forward's saved
    (out, lse) — the manual-VJP entry for the fused grad engine
    (parallel/fused_bwd.py), mirroring `flash_attention_bwd_from_saved`.

    The backward reuses the forward's all_to_all pair in both directions:
    q/k/v/out/dout (outer domain, [B, S_local, H, D]) scatter to the inner
    head-sharded full-sequence domain, `attn_bwd` (the flash
    bwd-from-saved; sdpa twin on non-TPU) runs there against the saved
    inner-domain lse [B, H_local, S] — never re-running the forward kernel
    — and the grads ride the reverse all_to_all home. seq_sort/
    full_positions/positions_static follow `ulysses_attention`'s contract
    and MUST match the forward call's values (both sides derive them from
    `ulysses_static_layout`).
    """
    from picotron_tpu.ops.flash_attention import flash_attention_bwd_from_saved

    if attn_bwd is None:
        attn_bwd = flash_attention_bwd_from_saved
    pos_arg, inv = _inner_positions(q.shape[1], axis, q_positions, seq_sort,
                                    full_positions, positions_static)
    qh = _scatter_heads(q, axis)
    kh = _scatter_heads(k, axis)
    vh = _scatter_heads(v, axis)
    oh = _scatter_heads(out, axis)
    doh = _scatter_heads(dout, axis)
    if seq_sort is not None:
        qh, kh, vh, oh, doh = (x[:, seq_sort]
                               for x in (qh, kh, vh, oh, doh))
    kwargs = {} if rope is None else {"rope": rope}
    dqh, dkh, dvh = attn_bwd(qh, kh, vh, oh, lse, doh, causal=True,
                             q_positions=pos_arg, kv_positions=pos_arg,
                             sm_scale=sm_scale, **kwargs)
    if seq_sort is not None:
        dqh, dkh, dvh = (x[:, inv] for x in (dqh, dkh, dvh))
    return (_gather_heads(dqh, axis), _gather_heads(dkh, axis),
            _gather_heads(dvh, axis))
