"""Mesh attention: 2D-mesh context parallelism (cp = cp_x x cp_y).

The third context-parallel schedule, after the K/V ring
(ops/ring_attention.py) and Ulysses (ops/ulysses.py). Mesh-Attention
(arxiv 2512.20968) factors the cp axis into a 2D submesh and runs a
different collective along each factor; TASP (arxiv 2509.26541) shows the
right factorization is a property of the physical topology — which is why
the cost model (analysis/cost_model.py) prices factorizations from the
per-generation ICI descriptors and the planner enumerates them.

Schedule, per attention call:

1. **Head scatter over the inner cp_y factor.** One Ulysses-style
   all_to_all restricted to each row's cp_y-device subgroup
   (`axis_index_groups` over the single named cp axis — the submesh never
   becomes a real mesh axis, so nothing else in the stack changes):

       q/k/v [B, S/cp, H, D]  ->  [B, S/cp_x, H/cp_y, D]

   Each device now holds its ROW's combined sequence block on a head
   subset.
2. **K/V ring over the outer cp_x factor.** Row blocks rotate between
   corresponding devices of adjacent rows (`ppermute` with row-wise pairs),
   merging partials with the same online-softmax LSE update as the ring.
3. The output rides the reverse all_to_all home.

Why this beats both parents at large cp: the per-hop ring volume is
IDENTICAL to ring attention's (the row block has cp_y x the sequence on
1/cp_y the heads), but there are only cp_x-1 hops instead of cp-1 — the
serial latency chain shrinks by the factor cp_y, paid for with one
all_to_all pair whose subgroup spans only cp_y devices (contiguous on the
cp axis, so it lands on the innermost — fastest — ICI links that
`mesh_utils` assigns to later mesh axes). And the Ulysses head-divisibility
constraint relaxes from cp to cp_y.

Degenerate factorizations are exact: cp_y=1 IS the ring schedule (the
all_to_all pair is elided, not lowered as a size-1 group), cp_x=1 IS
Ulysses (no ring hops). Both are legal `cp_mesh` values; the planner
prices all three flavors and picks.

The fused grad engine enters through `mesh_attention_bwd_from_saved`: the
forward (`return_lse=True`) saves the ROW-domain LSE (head-sharded,
row-gathered — the analogue of Ulysses' inner-domain save), and the
backward replays the identical all_to_all scatter around a second forward
ring whose per-block grads — normalized by the saved LSE — are exactly
additive, with dK/dV accumulators traveling the row ring alongside their
blocks (the PR-3 contract shared by all three flavors).

Positions are explicit and travel with their blocks, so any sequence
layout (contiguous, zigzag) masks correctly; the row block's positions are
one small subgroup all_gather of the per-device position vector.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp
from jax import lax

from picotron_tpu.ops.attention import sdpa_attention
from picotron_tpu.ops.ring_attention import _merge


def mesh_groups(cp_x: int, cp_y: int):
    """(row_groups, ring_perm) over the single named cp axis for the
    row-major cp_x x cp_y factorization: device cp-index i sits at
    (row x, col y) = (i // cp_y, i % cp_y).

    row_groups: the cp_y-device subgroups the head-scatter all_to_all and
    the position all_gather run within — contiguous index ranges, so on
    hardware they land on the innermost ICI links of the cp axis.
    ring_perm: the (src, dst) pairs rotating row blocks to the next row's
    corresponding device (the outer-factor ring).
    """
    row_groups = [[x * cp_y + y for y in range(cp_y)] for x in range(cp_x)]
    ring_perm = [(x * cp_y + y, ((x + 1) % cp_x) * cp_y + y)
                 for x in range(cp_x) for y in range(cp_y)]
    return row_groups, ring_perm


def _scatter_heads(x: jnp.ndarray, axis: str, groups) -> jnp.ndarray:
    """[B, S_local, H, D] -> [B, S_local*cp_y, H/cp_y, D] within each row
    subgroup (sequence shards concatenate in subgroup order)."""
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True,
                          axis_index_groups=groups)


def _gather_heads(x: jnp.ndarray, axis: str, groups) -> jnp.ndarray:
    """Inverse of _scatter_heads: [B, S_row, H/cp_y, D] -> [B, S_local, H, D]."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True,
                          axis_index_groups=groups)


def _check_factorization(axis: str, cp_x: int, cp_y: int) -> None:
    n = lax.psum(1, axis)  # static axis size
    if cp_x * cp_y != n:
        raise ValueError(
            f"cp_mesh {cp_x}x{cp_y} does not factor the '{axis}' axis size "
            f"{n} (config.validate should have caught this)")


def _row_inputs(tensors, axis, groups, cp_y, q_positions):
    """Scatter `tensors` into the row domain and gather the row's position
    vector; the cp_y=1 degenerate elides the collectives entirely so the
    lowering is bit-identical to the plain ring schedule."""
    if cp_y == 1:
        return tensors, q_positions
    row = [_scatter_heads(t, axis, groups) for t in tensors]
    row_pos = lax.all_gather(q_positions, axis, axis=0, tiled=True,
                             axis_index_groups=groups)
    return row, row_pos


def mesh_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis: str = "cp",
    cp_mesh: tuple[int, int],
    q_positions: jnp.ndarray | None = None,
    attn_block=None,
    return_lse: bool = False,
):
    """Causal 2D-mesh attention over the named mesh axis `axis`.

    Must be called inside shard_map with `axis` in scope and q/k already
    RoPE-rotated (same contract as ring_attention — rotation commutes with
    the head split, so pre-rotating keeps positions single-sourced in the
    caller). Each device holds the sequence shard of its cp index:

      q:    [B, S_local, Hq, D]
      k, v: [B, S_local, Hkv, D]   (Hkv <= Hq, GQA unexpanded)

    cp_mesh: the STATIC (cp_x, cp_y) factorization; cp_x * cp_y must equal
        the axis size. Hq and Hkv must be divisible by cp_y
        (config.validate enforces both from the config).
    q_positions: optional [S_local] global positions of the local tokens;
        defaults to the contiguous layout (same as ring_attention).
    attn_block: blockwise attention with `sdpa_attention(...,
        return_lse=True)`'s signature (the Pallas flash kernel slots in).
    return_lse: also return the merged log-sum-exp [B, Hq/cp_y, S_row]
        fp32 in the ROW domain (head-sharded, row-gathered) — the save
        `mesh_attention_bwd_from_saved` consumes. The backward re-derives
        the row-domain q/k/v/out by replaying the exact all_to_all, so the
        lse never needs un/re-scattering round trips (the Ulysses
        inner-domain convention).

    Returns [B, S_local, Hq, D] in q.dtype (+ the row-domain lse when
    `return_lse`).
    """
    cp_x, cp_y = cp_mesh
    _check_factorization(axis, cp_x, cp_y)
    s_local = q.shape[1]
    if q_positions is None:
        q_positions = lax.axis_index(axis) * s_local + jnp.arange(s_local)
    if attn_block is None:
        attn_block = partial(sdpa_attention, return_lse=True)
    groups, ring_perm = mesh_groups(cp_x, cp_y)

    (qh, kh, vh), row_pos = _row_inputs((q, k, v), axis, groups, cp_y,
                                        q_positions)
    b, s_row, h, d = qh.shape
    out_acc = jnp.zeros((b, s_row, h, d), jnp.float32)
    lse_acc = jnp.full((b, h, s_row), -jnp.inf, jnp.float32)
    kv_positions = row_pos
    q_max = jnp.max(row_pos)

    for step in range(cp_x):
        # Whole-block causal skip, same collective-free lax.cond contract
        # as ring_attention (a fully-future row contributes exactly
        # (out=0, lse=-inf), which is what the skip branch returns).
        kv_pos = kv_positions

        def compute(opnds, kv_pos=kv_pos):
            q_, k_, v_ = opnds
            ob, lb = attn_block(q_, k_, v_, causal=True,
                                q_positions=row_pos, kv_positions=kv_pos)
            return ob.astype(jnp.float32), lb.astype(jnp.float32)

        def skip(opnds):
            q_, k_, v_ = opnds
            a = (q_.ravel()[0] + k_.ravel()[0]
                 + v_.ravel()[0]).astype(jnp.float32) * 0.0
            return (jnp.zeros((b, s_row, h, d), jnp.float32) + a,
                    jnp.full((b, h, s_row), -jnp.inf, jnp.float32) + a)

        fully_masked = jnp.min(kv_pos) > q_max
        out_blk, lse_blk = lax.cond(fully_masked, skip, compute,
                                    (qh, kh, vh))
        out_acc, lse_acc = _merge(out_acc, lse_acc, out_blk, lse_blk)
        if step != cp_x - 1:
            # deliberate unroll: one row-block rotation per outer-ring hop
            kh = lax.ppermute(kh, axis, ring_perm)  # shardcheck: ok
            vh = lax.ppermute(vh, axis, ring_perm)  # shardcheck: ok
            kv_positions = lax.ppermute(  # shardcheck: ok
                kv_positions, axis, ring_perm)

    out = out_acc.astype(q.dtype)
    if cp_y > 1:
        out = _gather_heads(out, axis, groups)
    return (out, lse_acc) if return_lse else out


def mesh_attention_bwd_from_saved(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    out: jnp.ndarray,
    lse: jnp.ndarray,
    dout: jnp.ndarray,
    *,
    axis: str = "cp",
    cp_mesh: tuple[int, int],
    q_positions: jnp.ndarray | None = None,
    sm_scale: float | None = None,
    block_bwd=None,
):
    """(dq, dk, dv) for 2D-mesh attention from the forward's saved
    (out, lse) — the manual-VJP entry for the fused grad engine
    (parallel/fused_bwd.py), completing the PR-3 contract for the third
    flavor.

    q/k/v/out/dout arrive in the OUTER domain [B, S_local, H, D] (out is
    the forward's gathered-home return); lse is the forward's saved
    ROW-domain statistic [B, Hq/cp_y, S_row] fp32. The backward replays
    the forward's head scatter on all five operands, then runs a second
    forward ring over cp_x: each visiting row block's grads — computed by
    `block_bwd` against the globally-merged saved lse — are its exact
    additive contribution (the sdpa_attention_bwd_from_saved block
    property), dQ accumulates locally, dK/dV accumulators travel the row
    ring WITH their blocks, a final hop delivers them home (cp_x hops =
    the row ring's identity), and the reverse all_to_all returns all
    three grads to the outer domain.
    """
    from picotron_tpu.ops.flash_attention import flash_attention_bwd_from_saved

    cp_x, cp_y = cp_mesh
    _check_factorization(axis, cp_x, cp_y)
    s_local = q.shape[1]
    if q_positions is None:
        q_positions = lax.axis_index(axis) * s_local + jnp.arange(s_local)
    if block_bwd is None:
        block_bwd = flash_attention_bwd_from_saved
    groups, ring_perm = mesh_groups(cp_x, cp_y)

    (qh, kh, vh, oh, doh), row_pos = _row_inputs(
        (q, k, v, out, dout), axis, groups, cp_y, q_positions)
    dq_acc = jnp.zeros(qh.shape, jnp.float32)
    dk_acc = jnp.zeros(kh.shape, jnp.float32)
    dv_acc = jnp.zeros(vh.shape, jnp.float32)
    kv_positions = row_pos
    q_max = jnp.max(row_pos)

    for step in range(cp_x):
        kv_pos = kv_positions

        def compute(opnds, kv_pos=kv_pos):
            q_, k_, v_ = opnds
            dq_b, dk_b, dv_b = block_bwd(
                q_, k_, v_, oh, lse, doh, causal=True,
                q_positions=row_pos, kv_positions=kv_pos,
                sm_scale=sm_scale)
            return (dq_b.astype(jnp.float32), dk_b.astype(jnp.float32),
                    dv_b.astype(jnp.float32))

        def skip(opnds):
            q_, k_, v_ = opnds
            a = (q_.ravel()[0] + k_.ravel()[0]
                 + v_.ravel()[0]).astype(jnp.float32) * 0.0
            return (jnp.zeros(q_.shape, jnp.float32) + a,
                    jnp.zeros(k_.shape, jnp.float32) + a,
                    jnp.zeros(v_.shape, jnp.float32) + a)

        fully_masked = jnp.min(kv_pos) > q_max
        dq_b, dk_b, dv_b = lax.cond(fully_masked, skip, compute,
                                    (qh, kh, vh))
        dq_acc = dq_acc + dq_b
        dk_acc = dk_acc + dk_b
        dv_acc = dv_acc + dv_b
        if step != cp_x - 1:
            # deliberate unroll: one row-block + dK/dV rotation per hop
            kh = lax.ppermute(kh, axis, ring_perm)  # shardcheck: ok
            vh = lax.ppermute(vh, axis, ring_perm)  # shardcheck: ok
            kv_positions = lax.ppermute(  # shardcheck: ok
                kv_positions, axis, ring_perm)
            dk_acc = lax.ppermute(dk_acc, axis, ring_perm)  # shardcheck: ok
            dv_acc = lax.ppermute(dv_acc, axis, ring_perm)  # shardcheck: ok
    if cp_x > 1:
        # one more hop delivers every row block's dK/dV back to its owner
        dk_acc = lax.ppermute(dk_acc, axis, ring_perm)
        dv_acc = lax.ppermute(dv_acc, axis, ring_perm)

    dq = dq_acc.astype(q.dtype)
    dk = dk_acc.astype(k.dtype)
    dv = dv_acc.astype(v.dtype)
    if cp_y > 1:
        dq = _gather_heads(dq, axis, groups)
        dk = _gather_heads(dk, axis, groups)
        dv = _gather_heads(dv, axis, groups)
    return dq, dk, dv
