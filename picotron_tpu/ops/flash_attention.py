"""Pallas flash attention for TPU: blockwise causal attention with LSE export.

TPU-native replacement for the reference's imported flash-attn CUDA kernel
(ref: picotron/model.py:7,33-37,152-154 calls flash_attn_func; SURVEY.md §2.3
row 1 requires a first-class equivalent). Same contract as
`ops.attention.sdpa_attention` — including `return_lse` — so it slots into
`ParallelCtx.attn` directly and into the context-parallel ring as the
per-block kernel (ref: the CP ring's pure-torch blockwise math + TODOs
wishing for flash, context_parallel.py:22-23,112-155).

Design:
- Inputs [B, S, H, D] are viewed [B, H, S, D]; the grid runs one program per
  (batch, q-head, q-block). K/V for the whole (cp-local) sequence sit in
  VMEM; the kernel loops KV blocks with online-softmax (m, l, acc) updates —
  the standard flash recurrence.
- **GQA in the index map**: the K/V BlockSpecs map q-head h to kv-head
  h // (Hq // Hkv), so grouped heads never materialize (the reference
  repeat_interleaves K/V to full Hq first, model.py:142-143).
- **Masking by explicit positions**, not block indices: the causal mask is
  `q_pos >= kv_pos` on position vectors, so context-parallel shards (local
  index != global position) and future zigzag layouts reuse the same kernel.
  Blocks that are entirely masked skip their matmuls via `pl.when`.
- **Custom VJP with Pallas backward kernels**: dq via a q-block-parallel
  kernel, dk/dv via a kv-block-parallel kernel, both recomputing P from the
  saved LSE (flash-attn 2's backward structure; no S x S materialization).

Numerics: fp32 accumulation for scores/softmax/output regardless of input
dtype, matching sdpa_attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30


def _pick_block(s: int, preferred: int) -> int:
    b = min(preferred, s)
    while s % b != 0:
        b //= 2
    return max(b, 1)


def _out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct whose `vma` is the union of the operands' varying
    mesh axes — required for pallas_call under shard_map(check_vma=True)
    (the CP ring runs this kernel on 'cp'-varying blocks)."""
    vma = frozenset()
    for x in operands:
        vma = vma | getattr(jax.typeof(x), "vma", frozenset())
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(kmin_ref, qpos_ref, kpos_ref, q_ref, k_ref, v_ref, o_ref,
                lse_ref, *, sm_scale: float, block_k: int, causal: bool):
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale          # [BQ, D]
    bq = q.shape[0]
    sk = k_ref.shape[2]
    qpos = qpos_ref[0]                                       # [BQ]
    num_kv = sk // block_k

    m = jnp.full((bq,), _NEG_INF, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)
    acc = jnp.zeros((bq, q.shape[1]), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        kpos = kpos_ref[0, pl.ds(j * block_k, block_k)]      # [BK]

        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [BQ, BK]
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask, s, _NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)                            # exp(-inf-(-inf))
        alpha = jnp.where(m <= _NEG_INF, 0.0, alpha)          # guarded to 0
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(m_new[:, None] <= _NEG_INF, 0.0, p)
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l = l * alpha + jnp.sum(p, axis=-1)
        return m_new, l, acc

    if causal:
        # Skip blocks with no unmasked entry. Per-block position minima come
        # from SMEM (kmin_ref) — Mosaic cannot prove lane alignment for a
        # dynamic scalar load from the VMEM position vector.
        q_hi = jnp.max(qpos)

        def guarded(j, carry):
            k_lo = kmin_ref[0, j]
            return jax.lax.cond(q_hi >= k_lo, lambda c: body(j, c),
                                lambda c: c, carry)

        m, l, acc = jax.lax.fori_loop(0, num_kv, guarded, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m, l, acc))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # True -inf for fully-masked rows — the CP ring's LSE merge keys on
    # isinf, matching sdpa_attention's convention.
    lse = jnp.where(l == 0.0, -jnp.inf, m + jnp.log(l_safe))
    lse_ref[0, 0] = lse.astype(jnp.float32)[:, None]


def _fwd(q4, k4, v4, qpos, kpos, sm_scale, causal, block_q, block_k,
         interpret):
    """q4 [B,Hq,Sq,D]; k4/v4 [B,Hkv,Sk,D]; qpos [1,Sq]; kpos [1,Sk]."""
    b, hq, sq, d = q4.shape
    hkv, sk = k4.shape[1], k4.shape[2]
    n_rep = hq // hkv
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)

    grid = (b, hq, sq // bq)
    kmin = kpos.reshape(1, sk // bk, bk).min(axis=-1)  # [1, num_kv_blocks]
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_k=bk, causal=causal)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                  # kmin
            pl.BlockSpec((1, bq), lambda bi, hi, qi: (0, qi)),      # qpos
            pl.BlockSpec((1, sk), lambda bi, hi, qi: (0, 0)),       # kpos
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d),
                         lambda bi, hi, qi, n_rep=n_rep: (bi, hi // n_rep, 0, 0)),
            pl.BlockSpec((1, 1, sk, d),
                         lambda bi, hi, qi, n_rep=n_rep: (bi, hi // n_rep, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            _out_struct((b, hq, sq, d), q4.dtype, q4, k4, v4, qpos, kpos),
            _out_struct((b, hq, sq, 1), jnp.float32, q4, k4, v4, qpos, kpos),
        ],
        interpret=interpret,
    )(kmin, qpos, kpos, q4, k4, v4)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels (flash-attn 2 structure: recompute P from saved LSE)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(kmin_ref, qpos_ref, kpos_ref, q_ref, k_ref, v_ref,
                   do_ref, lse_ref, delta_ref, dq_ref, *, sm_scale: float,
                   block_k: int, causal: bool):
    q = q_ref[0, 0].astype(jnp.float32)                      # [BQ, D]
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]                                # [BQ]
    delta = delta_ref[0, 0, :, 0]                            # [BQ]
    qpos = qpos_ref[0]
    bq = q.shape[0]
    sk = k_ref.shape[2]
    num_kv = sk // block_k

    dq = jnp.zeros_like(q)

    def body(j, dq):
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        kpos = kpos_ref[0, pl.ds(j * block_k, block_k)]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(lse[:, None] <= _NEG_INF, 0.0, p)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        q_hi = jnp.max(qpos)

        def guarded(j, dq):
            k_lo = kmin_ref[0, j]
            return jax.lax.cond(q_hi >= k_lo, lambda c: body(j, c),
                                lambda c: c, dq)

        dq = jax.lax.fori_loop(0, num_kv, guarded, dq)
    else:
        dq = jax.lax.fori_loop(0, num_kv, body, dq)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(qmax_ref, qpos_ref, kpos_ref, q_ref, k_ref, v_ref,
                    do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *,
                    sm_scale: float, block_q: int, causal: bool):
    k_blk = k_ref[0, 0].astype(jnp.float32)                  # [BK, D]
    v_blk = v_ref[0, 0].astype(jnp.float32)
    kpos = kpos_ref[0]                                       # [BK]
    sq = q_ref.shape[2]
    bk = k_blk.shape[0]
    num_q = sq // block_q

    dk = jnp.zeros_like(k_blk)
    dv = jnp.zeros_like(v_blk)

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[0, 0, pl.ds(i * block_q, block_q)].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(i * block_q, block_q)].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q), 0]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q), 0]
        qpos = qpos_ref[0, pl.ds(i * block_q, block_q)]
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale    # [BQ, BK]
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(lse[:, None] <= _NEG_INF, 0.0, p)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        k_lo = jnp.min(kpos)

        def guarded(i, carry):
            q_hi = qmax_ref[0, i]
            return jax.lax.cond(q_hi >= k_lo, lambda c: body(i, c),
                                lambda c: c, carry)

        dk, dv = jax.lax.fori_loop(0, num_q, guarded, (dk, dv))
    else:
        dk, dv = jax.lax.fori_loop(0, num_q, body, (dk, dv))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(q4, k4, v4, o4, lse, do4, dlse, qpos, kpos, sm_scale, causal,
         block_q, block_k, interpret):
    b, hq, sq, d = q4.shape
    hkv, sk = k4.shape[1], k4.shape[2]
    n_rep = hq // hkv
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)

    # delta = rowsum(do * o) [B, Hq, Sq] (flash-attn 2's D term). The LSE
    # cotangent folds in here: dL/ds_ij = p_ij * (dp_ij - delta_i + dlse_i)
    # because dlse_i/ds_ij = p_ij — so shipping (delta - dlse) to the kernels
    # handles out- and lse-cotangents in one pass (the CP ring's LSE merge
    # differentiates through both).
    delta = jnp.sum(do4.astype(jnp.float32) * o4.astype(jnp.float32),
                    axis=-1, keepdims=True)
    delta = delta - dlse.astype(jnp.float32)

    kmin = kpos.reshape(1, sk // bk, bk).min(axis=-1)
    qmax = qpos.reshape(1, sq // bq, bq).max(axis=-1)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, block_k=bk,
                          causal=causal),
        grid=(b, hq, sq // bq),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq), lambda bi, hi, qi: (0, qi)),
            pl.BlockSpec((1, sk), lambda bi, hi, qi: (0, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d),
                         lambda bi, hi, qi, n_rep=n_rep: (bi, hi // n_rep, 0, 0)),
            pl.BlockSpec((1, 1, sk, d),
                         lambda bi, hi, qi, n_rep=n_rep: (bi, hi // n_rep, 0, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=_out_struct((b, hq, sq, d), q4.dtype,
                              q4, k4, v4, do4, lse, delta, qpos, kpos),
        interpret=interpret,
    )(kmin, qpos, kpos, q4, k4, v4, do4, lse, delta)

    # dk/dv over full query heads, then sum grouped heads for GQA.
    dk_full, dv_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, block_q=bq,
                          causal=causal),
        grid=(b, hq, sk // bk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, sq), lambda bi, hi, ki: (0, 0)),
            pl.BlockSpec((1, bk), lambda bi, hi, ki: (0, ki)),
            pl.BlockSpec((1, 1, sq, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0)),
            pl.BlockSpec((1, 1, sq, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sq, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sq, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            _out_struct((b, hq, sk, d), q4.dtype,
                        q4, k4, v4, do4, lse, delta, qpos, kpos),
            _out_struct((b, hq, sk, d), q4.dtype,
                        q4, k4, v4, do4, lse, delta, qpos, kpos),
        ],
        interpret=interpret,
    )(qmax, qpos, kpos, q4, k4, v4, do4, lse, delta)

    if n_rep > 1:
        dk = dk_full.reshape(b, hkv, n_rep, sk, d).sum(axis=2)
        dv = dv_full.reshape(b, hkv, n_rep, sk, d).sum(axis=2)
    else:
        dk, dv = dk_full, dv_full
    return dq, dk.astype(k4.dtype), dv.astype(v4.dtype)


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_core(q4, k4, v4, qpos, kpos, sm_scale, causal, block_q, block_k,
                interpret):
    return _fwd(q4, k4, v4, qpos, kpos, sm_scale, causal, block_q, block_k,
                interpret)


def _flash_core_fwd(q4, k4, v4, qpos, kpos, sm_scale, causal, block_q,
                    block_k, interpret):
    out, lse = _fwd(q4, k4, v4, qpos, kpos, sm_scale, causal, block_q,
                    block_k, interpret)
    return (out, lse), (q4, k4, v4, out, lse, qpos, kpos)


def _flash_core_bwd(sm_scale, causal, block_q, block_k, interpret, res, cts):
    q4, k4, v4, out, lse, qpos, kpos = res
    do4, dlse = cts
    dq, dk, dv = _bwd(q4, k4, v4, out, lse, do4, dlse, qpos, kpos, sm_scale,
                      causal, block_q, block_k, interpret)
    return dq, dk, dv, None, None


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    q_positions: Optional[jnp.ndarray] = None,
    kv_positions: Optional[jnp.ndarray] = None,
    return_lse: bool = False,
    sm_scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
):
    """Drop-in flash counterpart of `sdpa_attention` (same shapes/semantics):
    q [B, Sq, Hq, D]; k/v [B, Sk, Hkv, D] (GQA unexpanded); optional global
    position vectors for CP shards. Returns out (and fp32 lse [B, Hq, Sq]).

    Backend dispatch: on TPU the Pallas kernels run compiled. On other
    backends (the simulated-mesh test platform) the mathematically identical
    jnp path runs instead — Pallas interpreter mode does not compose with
    shard_map's varying-axis checking, and tests/test_flash_attention.py
    pins kernel==jnp equivalence in interpreter mode directly. Pass
    `interpret=True` to force the Pallas interpreter (kernel unit tests).
    """
    b, sq, hq, d = q.shape
    sk = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    if interpret is None and jax.default_backend() != "tpu":
        from picotron_tpu.ops.attention import sdpa_attention

        return sdpa_attention(
            q, k, v, causal=causal, q_positions=q_positions,
            kv_positions=kv_positions, return_lse=return_lse,
            sm_scale=sm_scale)
    interpret = bool(interpret)
    qpos = (q_positions if q_positions is not None else jnp.arange(sq))
    kpos = (kv_positions if kv_positions is not None else jnp.arange(sk))
    qpos = qpos.astype(jnp.int32).reshape(1, sq)
    kpos = kpos.astype(jnp.int32).reshape(1, sk)

    q4 = jnp.swapaxes(q, 1, 2)
    k4 = jnp.swapaxes(k, 1, 2)
    v4 = jnp.swapaxes(v, 1, 2)

    out, lse = _flash_core(q4, k4, v4, qpos, kpos, sm_scale, causal, block_q,
                           block_k, interpret)
    out = jnp.swapaxes(out, 1, 2)
    if return_lse:
        # LSE is the *scaled-score* logsumexp, same convention as
        # sdpa_attention (which also applies sm_scale before the softmax).
        # Kernels carry it [B, Hq, Sq, 1] (TPU block-shape constraint);
        # drop the trailing dim at the boundary.
        return out, lse[..., 0]
    return out
