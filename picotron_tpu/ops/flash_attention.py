"""Pallas flash attention for TPU: blockwise causal attention with LSE export.

TPU-native replacement for the reference's imported flash-attn CUDA kernel
(ref: picotron/model.py:7,33-37,152-154 calls flash_attn_func; SURVEY.md §2.3
row 1 requires a first-class equivalent). Same contract as
`ops.attention.sdpa_attention` — including `return_lse` — so it slots into
`ParallelCtx.attn` directly and into the context-parallel ring as the
per-block kernel (ref: the CP ring's pure-torch blockwise math + TODOs
wishing for flash, context_parallel.py:22-23,112-155).

Design:
- Inputs [B, S, H, D] are viewed [B, H, S, D]; the KV dimension is a *grid
  dimension*, not a kernel-internal loop: grid (batch, q-head, q-block,
  kv-block) with online-softmax (m, l, acc) carries in VMEM scratch across
  the sequential kv dimension. Only one K/V block is VMEM-resident per step,
  so per-shard sequence length is bounded by HBM, not VMEM — the
  long-context regime CP exists for (16k+ per shard) compiles and runs.
- **GQA in the index map**: the K/V BlockSpecs map q-head h to kv-head
  h // (Hq // Hkv), so grouped heads never materialize (the reference
  repeat_interleaves K/V to full Hq first, model.py:142-143).
- **Masking by explicit positions**, not block indices: the causal mask is
  `q_pos >= kv_pos` on position vectors, so context-parallel shards (local
  index != global position) and the zigzag layout reuse the same kernel.
  Blocks that are entirely masked skip their matmuls via `pl.when`.
- **Custom VJP with Pallas backward kernels**: dq via a q-block-parallel
  kernel, dk/dv via a kv-block-parallel kernel, both recomputing P from the
  saved LSE (flash-attn 2's backward structure; no S x S materialization).
  The dkv grid is (batch, KV-head, kv-block): under GQA the group's query
  heads are accumulated *inside* the program (an inner sequential grid
  dimension), not materialized per-q-head and summed after.

Numerics: fp32 accumulation for scores/softmax/output regardless of input
dtype, matching sdpa_attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed TPUCompilerParams -> CompilerParams across pallas releases
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

# Swept on v5e at seq 2048 (B3 H32 D64): 1024x1024 runs 4x faster than
# 256x256 — the kernel is VPU/overhead-bound, not MXU-bound, so fewer,
# larger programs win. VMEM (fp32 [BQ, BK] score block) caps growth: 2048^2
# exceeds the 16 MB scoped-vmem budget.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30


def _pick_block(s: int, preferred: int) -> int:
    b = min(preferred, s)
    while s % b != 0:
        b //= 2
    return max(b, 1)


def _kv_eff(qi, ki, bq: int, bk: int):
    """Clamp a kv-block index to the last block visible from q-block qi
    under contiguous causal positions (static_causal index maps): skipped
    tiles re-address the previous iteration's blocks, so Mosaic elides
    their DMAs entirely."""
    return jnp.minimum(ki, (qi * bq + bq - 1) // bk)


def _q_eff(qi, ki, bq: int, bk: int, num_q: int):
    """Clamp a q-block index to the first block that can see kv-block ki
    (the dkv kernel's mirror of _kv_eff). The upper clamp matters when
    sk > sq: the last kv blocks see no q block at all, and an unclamped
    index would address past the q array (code review r5)."""
    return jnp.minimum(jnp.maximum(qi, (ki * bk) // bq), num_q - 1)


def _static_block_classes(qi, ki, bq: int, bk: int):
    """(visible, full) block classes as integer functions of the program
    ids — the static_causal twin of the kernels' position-based
    `max(qpos) >= min(kpos)` / `min(qpos) >= max(kpos)` tests, shared by
    all three kernels so the class boundaries cannot desynchronize."""
    visible = qi * bq + bq - 1 >= ki * bk
    full = qi * bq >= ki * bk + bk - 1
    return visible, full


def _rot_tables(cos, sin, pos, dtype=jnp.float32):
    """Gather the half tables [maxS, d/2] at `pos` [1, S] and lay them out
    full-width for the in-kernel rotate-half:

        rot(x)     = x * C + roll(x, d/2) * S,   C = [cos|cos], S = [-sin|sin]
        rot_inv(y) = y * C + roll(y, d/2) * (-S)

    (roll moves the upper half down: roll(x)[: d/2] = x2, matching the HF
    rotate_half convention rot(x) = x*cos_full + [-x2|x1]*sin_full.)"""
    c = cos[pos[0]].astype(dtype)                    # [S, d/2]
    s = sin[pos[0]].astype(dtype)
    C = jnp.concatenate([c, c], axis=-1)[None]       # [1, S, d]
    S = jnp.concatenate([-s, s], axis=-1)[None]
    return C, S


def _rot(x, c_ref, s_ref, sign: float):
    """Rotate an [N, d] tile with full-width tables from `_rot_tables`;
    sign=+1 applies RoPE, sign=-1 its inverse (transpose). fp32 math, result
    cast back to x.dtype so the MXU stays on the bf16 path."""
    xf = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    rolled = jnp.concatenate([xf[:, half:], xf[:, :half]], axis=-1)
    out = xf * c_ref[0] + rolled * (sign * s_ref[0])
    return out.astype(x.dtype)


def _out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct whose `vma` is the union of the operands' varying
    mesh axes — required for pallas_call under shard_map(check_vma=True)
    (the CP ring runs this kernel on 'cp'-varying blocks)."""
    from picotron_tpu import compat

    vma = frozenset()
    for x in operands:
        vma = vma | compat.vma(x)
    if not compat.HAS_VMA:  # pre-vma ShapeDtypeStruct has no vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(*refs, sm_scale: float, causal: bool, num_kv: int,
                fused_rope: bool, static_causal: bool = False,
                block_q: int = 0, block_k: int = 0):
    if fused_rope:
        (qpos_ref, kpos_ref, cq_ref, sq_ref, ck_ref, sk_ref,
         q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
         qrot_ref) = refs
    else:
        (qpos_ref, kpos_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_ref, l_ref, acc_ref) = refs
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)
        if fused_rope:
            # q is constant across the sequential kv dim — rotate once per
            # q-block chain, not once per kv block (the rotation lands on
            # the VPU, this kernel's bottleneck unit).
            qrot_ref[...] = _rot(q_ref[0, 0], cq_ref, sq_ref, 1.0)

    qpos = qpos_ref[0]                                       # [BQ]
    kpos = kpos_ref[0]                                       # [BK]
    if static_causal:
        # Contiguous-positions fast path: the block classes are integer
        # functions of the program ids, and the index maps re-point every
        # skipped tile's kv-side blocks at the previous (visible) blocks,
        # so skipped programs trigger NO new DMAs — measured ~1.4 us per
        # skipped program otherwise, ~20% of the whole kernel at seq 16k
        # where nearly half the rectangular grid is below the causal
        # diagonal (PERF.md r5).
        qi = pl.program_id(2)
        visible, full = _static_block_classes(qi, ki, block_q, block_k)
    elif causal:
        # Three block classes: fully masked (skip entirely), fully visible
        # (no mask / no -inf guards — the common case, ~(num_kv-1)/2 of the
        # grid), and diagonal-straddling (masked path). Splitting the paths
        # removes 4+ VPU passes over [BQ, BK] from the common case; the
        # softmax VPU work, not the MXU matmuls, bounds this kernel at D=64.
        visible = jnp.max(qpos) >= jnp.min(kpos)
        full = jnp.min(qpos) >= jnp.max(kpos)
    else:
        visible = ki >= 0
        full = visible

    def _tile(masked: bool):
        # Matmuls keep the input dtype (bf16 on the fast MXU path) with fp32
        # accumulation via preferred_element_type; only the softmax math runs
        # in fp32. Casting inputs to fp32 before the dot would put the MXU in
        # fp32 mode (~8x slower on MXU).
        if fused_rope:
            q = qrot_ref[...]                                # [BQ, D]
            k_blk = _rot(k_ref[0, 0], ck_ref, sk_ref, 1.0)
        else:
            q = q_ref[0, 0]                                  # [BQ, D]
            k_blk = k_ref[0, 0]                              # [BK, D]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [BQ, BK] fp32
        if sm_scale != 1.0:  # the public wrapper pre-scales q; this is the
            s = s * sm_scale  # fallback for direct _fwd/_bwd callers
        if masked:
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[...][:, 0]                            # [BQ]
        l_prev = l_ref[...][:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)                      # exp(-inf-(-inf))
        alpha = jnp.where(m_prev <= _NEG_INF, 0.0, alpha)    # guarded to 0
        p = jnp.exp(s - m_new[:, None])
        if masked:
            # a fully-masked row has m_new = -inf; exp(-inf - -inf) = nan
            p = jnp.where(m_new[:, None] <= _NEG_INF, 0.0, p)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(full)
    def _compute_full():
        _tile(masked=False)

    if causal:
        @pl.when(visible & ~full)
        def _compute_masked():
            _tile(masked=True)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        m = m_ref[...][:, 0]
        l = l_ref[...][:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        # True -inf for fully-masked rows — the CP ring's LSE merge keys on
        # isinf, matching sdpa_attention's convention.
        lse = jnp.where(l == 0.0, -jnp.inf, m + jnp.log(l_safe))
        lse_ref[0, 0] = lse.astype(jnp.float32)[:, None]


def _fwd(q4, k4, v4, qpos, kpos, rope, sm_scale, causal, block_q, block_k,
         interpret, static_causal=False):
    """q4 [B,Hq,Sq,D]; k4/v4 [B,Hkv,Sk,D]; qpos [1,Sq]; kpos [1,Sk];
    rope = None or (cos, sin) half tables [maxS, D/2] applied in-kernel.
    static_causal: positions are known to be plain 0..S-1 — skipped tiles
    use program-id block classes and DMA-free index maps (_kv_eff)."""
    b, hq, sq, d = q4.shape
    hkv, sk = k4.shape[1], k4.shape[2]
    n_rep = hq // hkv
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    num_kv = sk // bk

    def keff(qi, ki):
        # last kv block any row of q-block qi can see; skipped tiles
        # re-load it (same block as the previous iteration -> no DMA)
        return _kv_eff(qi, ki, bq, bk) if static_causal else ki

    rope_args, rope_specs = [], []
    if rope is not None:
        cq, sq_t = _rot_tables(*rope, qpos)
        ck, sk_t = _rot_tables(*rope, kpos)
        rope_args = [cq, sq_t, ck, sk_t]
        rope_specs = [
            pl.BlockSpec((1, bq, d), lambda bi, hi, qi, ki: (0, qi, 0)),
            pl.BlockSpec((1, bq, d), lambda bi, hi, qi, ki: (0, qi, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda bi, hi, qi, ki: (0, keff(qi, ki), 0)),
            pl.BlockSpec((1, bk, d),
                         lambda bi, hi, qi, ki: (0, keff(qi, ki), 0)),
        ]

    grid = (b, hq, sq // bq, num_kv)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, num_kv=num_kv,
        fused_rope=rope is not None, static_causal=static_causal,
        block_q=bq, block_k=bk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq), lambda bi, hi, qi, ki: (0, qi)),  # qpos
            pl.BlockSpec((1, bk),
                         lambda bi, hi, qi, ki: (0, keff(qi, ki))),  # kpos
            *rope_specs,
            pl.BlockSpec((1, 1, bq, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hi, qi, ki, n_rep=n_rep:
                         (bi, hi // n_rep, keff(qi, ki), 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hi, qi, ki, n_rep=n_rep:
                         (bi, hi // n_rep, keff(qi, ki), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            _out_struct((b, hq, sq, d), q4.dtype, q4, k4, v4, qpos, kpos,
                        *rope_args),
            _out_struct((b, hq, sq, 1), jnp.float32, q4, k4, v4, qpos, kpos,
                        *rope_args),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # m (broadcast over lanes)
            pltpu.VMEM((bq, 128), jnp.float32),   # l
            pltpu.VMEM((bq, d), jnp.float32),     # acc
        ] + ([pltpu.VMEM((bq, d), q4.dtype)]      # rotated q, reused per ki
             if rope is not None else []),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qpos, kpos, *rope_args, q4, k4, v4)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels (flash-attn 2 structure: recompute P from saved LSE)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(*refs, sm_scale: float, causal: bool, num_kv: int,
                   fused_rope: bool, static_causal: bool = False,
                   block_q: int = 0, block_k: int = 0):
    if fused_rope:
        (qpos_ref, kpos_ref, cq_ref, sq_ref, ck_ref, sk_ref,
         q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc_ref, qrot_ref) = refs
    else:
        (qpos_ref, kpos_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
         delta_ref, dq_ref, dq_acc_ref) = refs
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)
        if fused_rope:
            qrot_ref[...] = _rot(q_ref[0, 0], cq_ref, sq_ref, 1.0)

    qpos = qpos_ref[0]
    kpos = kpos_ref[0]
    if static_causal:
        # program-id block classes + DMA-free skipped tiles (_kv_eff) —
        # see _fwd_kernel's static_causal note
        qi = pl.program_id(2)
        visible, full = _static_block_classes(qi, ki, block_q, block_k)
    elif causal:
        visible = jnp.max(qpos) >= jnp.min(kpos)
        full = jnp.min(qpos) >= jnp.max(kpos)
    else:
        visible = ki >= 0
        full = visible

    def _tile(masked: bool):
        # bf16 MXU matmuls with fp32 accumulation (see _fwd_kernel note).
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]                            # [BQ]
        delta = delta_ref[0, 0, :, 0]                        # [BQ]
        v_blk = v_ref[0, 0]
        if fused_rope:
            q = qrot_ref[...]                                # [BQ, D]
            k_blk = _rot(k_ref[0, 0], ck_ref, sk_ref, 1.0)
        else:
            q = q_ref[0, 0]                                  # [BQ, D]
            k_blk = k_ref[0, 0]                              # [BK, D]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if sm_scale != 1.0:
            s = s * sm_scale
        if masked:
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if masked:
            p = jnp.where(lse[:, None] <= _NEG_INF, 0.0, p)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        if sm_scale != 1.0:
            ds = ds * sm_scale
        ds = ds.astype(k_blk.dtype)
        dq_acc_ref[...] = dq_acc_ref[...] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(full)
    def _compute_full():
        _tile(masked=False)

    if causal:
        @pl.when(visible & ~full)
        def _compute_masked():
            _tile(masked=True)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        dq = dq_acc_ref[...]
        if fused_rope:
            # dq was accumulated w.r.t. the rotated q; map back through the
            # rotation's transpose (R^T = rotation with negated sin).
            dq = _rot(dq, cq_ref, sq_ref, -1.0)
        dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, sm_scale: float, causal: bool, num_inner: int,
                    fused_rope: bool, static_causal: bool = False,
                    block_q: int = 0, block_k: int = 0, num_q: int = 0):
    if fused_rope:
        (qpos_ref, kpos_ref, cq_ref, sq_ref, ck_ref, sk_ref,
         q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, krot_ref) = refs
    else:
        (qpos_ref, kpos_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
         delta_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref) = refs
    # Inner sequential dim folds (group-head, q-block): the GQA group
    # accumulates into this kv-head's dk/dv inside the program.
    t = pl.program_id(3)

    @pl.when(t == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)
        if fused_rope:
            # k is constant across the inner (group-head x q-block) dim —
            # rotate once per kv-block chain.
            krot_ref[...] = _rot(k_ref[0, 0], ck_ref, sk_ref, 1.0)

    qpos = qpos_ref[0]
    kpos = kpos_ref[0]
    if static_causal:
        # program-id block classes + DMA-free skipped tiles (_q_eff) —
        # see _fwd_kernel's static_causal note
        ki = pl.program_id(2)
        qi = t % num_q
        visible, full = _static_block_classes(qi, ki, block_q, block_k)
    elif causal:
        visible = jnp.max(qpos) >= jnp.min(kpos)
        full = jnp.min(qpos) >= jnp.max(kpos)
    else:
        visible = t >= 0
        full = visible

    def _tile(masked: bool):
        # bf16 MXU matmuls with fp32 accumulation (see _fwd_kernel note).
        v_blk = v_ref[0, 0]
        do = do_ref[0, 0]
        if fused_rope:
            k_blk = krot_ref[...]                            # [BK, D]
            q_blk = _rot(q_ref[0, 0], cq_ref, sq_ref, 1.0)
        else:
            k_blk = k_ref[0, 0]                              # [BK, D]
            q_blk = q_ref[0, 0]                              # [BQ, D]
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [BQ, BK]
        if sm_scale != 1.0:
            s = s * sm_scale
        if masked:
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if masked:
            p = jnp.where(lse[:, None] <= _NEG_INF, 0.0, p)
        p_lo = p.astype(do.dtype)
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            p_lo, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        if sm_scale != 1.0:
            ds = ds * sm_scale
        ds = ds.astype(q_blk.dtype)
        dk_acc_ref[...] = dk_acc_ref[...] + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(full)
    def _compute_full():
        _tile(masked=False)

    if causal:
        @pl.when(visible & ~full)
        def _compute_masked():
            _tile(masked=True)

    @pl.when(t == num_inner - 1)
    def _finalize():
        dk = dk_acc_ref[...]
        if fused_rope:
            dk = _rot(dk, ck_ref, sk_ref, -1.0)  # back through R^T
        dk_ref[0, 0] = dk.astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _bwd(q4, k4, v4, o4, lse, do4, dlse, qpos, kpos, rope, sm_scale, causal,
         block_q, block_k, interpret, static_causal=False):
    b, hq, sq, d = q4.shape
    hkv, sk = k4.shape[1], k4.shape[2]
    n_rep = hq // hkv
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    num_q = sq // bq
    num_kv = sk // bk

    def keff(qi, ki):
        return _kv_eff(qi, ki, bq, bk) if static_causal else ki

    def qeff(qi, ki):
        return _q_eff(qi, ki, bq, bk, num_q) if static_causal else qi

    rope_args = []
    if rope is not None:
        cq, sq_t = _rot_tables(*rope, qpos)
        ck, sk_t = _rot_tables(*rope, kpos)
        rope_args = [cq, sq_t, ck, sk_t]

    def rope_specs(qmap, kmap):
        if rope is None:
            return []
        return [pl.BlockSpec((1, bq, d), qmap), pl.BlockSpec((1, bq, d), qmap),
                pl.BlockSpec((1, bk, d), kmap), pl.BlockSpec((1, bk, d), kmap)]

    # delta = rowsum(do * o) [B, Hq, Sq] (flash-attn 2's D term). The LSE
    # cotangent folds in here: dL/ds_ij = p_ij * (dp_ij - delta_i + dlse_i)
    # because dlse_i/ds_ij = p_ij — so shipping (delta - dlse) to the kernels
    # handles out- and lse-cotangents in one pass (the CP ring's LSE merge
    # differentiates through both).
    delta = jnp.sum(do4.astype(jnp.float32) * o4.astype(jnp.float32),
                    axis=-1, keepdims=True)
    delta = delta - dlse.astype(jnp.float32)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          num_kv=num_kv, fused_rope=rope is not None,
                          static_causal=static_causal, block_q=bq,
                          block_k=bk),
        grid=(b, hq, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, bq), lambda bi, hi, qi, ki: (0, qi)),
            pl.BlockSpec((1, bk),
                         lambda bi, hi, qi, ki: (0, keff(qi, ki))),
            *rope_specs(lambda bi, hi, qi, ki: (0, qi, 0),
                        lambda bi, hi, qi, ki: (0, keff(qi, ki), 0)),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hi, qi, ki, n_rep=n_rep:
                         (bi, hi // n_rep, keff(qi, ki), 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hi, qi, ki, n_rep=n_rep:
                         (bi, hi // n_rep, keff(qi, ki), 0)),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=_out_struct((b, hq, sq, d), q4.dtype,
                              q4, k4, v4, do4, lse, delta, qpos, kpos,
                              *rope_args),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)]
        + ([pltpu.VMEM((bq, d), q4.dtype)] if rope is not None else []),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qpos, kpos, *rope_args, q4, k4, v4, do4, lse, delta)

    # dk/dv: one program per (batch, KV head, kv-block); the inner
    # sequential dim walks the group's query heads x q-blocks, accumulating
    # into scratch — GQA costs no extra memory traffic or post-hoc sum.
    num_inner = n_rep * num_q

    def qhead(hi, t):
        return hi * n_rep + t // num_q

    def qblk(t):
        return t % num_q

    def qbe(ki, t):
        return qeff(qblk(t), ki)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          num_inner=num_inner, fused_rope=rope is not None,
                          static_causal=static_causal, block_q=bq,
                          block_k=bk, num_q=num_q),
        grid=(b, hkv, num_kv, num_inner),
        in_specs=[
            pl.BlockSpec((1, bq), lambda bi, hi, ki, t: (0, qbe(ki, t))),
            pl.BlockSpec((1, bk), lambda bi, hi, ki, t: (0, ki)),
            *rope_specs(lambda bi, hi, ki, t: (0, qbe(ki, t), 0),
                        lambda bi, hi, ki, t: (0, ki, 0)),
            pl.BlockSpec((1, 1, bq, d),
                         lambda bi, hi, ki, t: (bi, qhead(hi, t),
                                                qbe(ki, t), 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki, t: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki, t: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bq, d),
                         lambda bi, hi, ki, t: (bi, qhead(hi, t),
                                                qbe(ki, t), 0)),
            pl.BlockSpec((1, 1, bq, 1),
                         lambda bi, hi, ki, t: (bi, qhead(hi, t),
                                                qbe(ki, t), 0)),
            pl.BlockSpec((1, 1, bq, 1),
                         lambda bi, hi, ki, t: (bi, qhead(hi, t),
                                                qbe(ki, t), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki, t: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki, t: (bi, hi, ki, 0)),
        ],
        out_shape=[
            _out_struct((b, hkv, sk, d), k4.dtype,
                        q4, k4, v4, do4, lse, delta, qpos, kpos, *rope_args),
            _out_struct((b, hkv, sk, d), v4.dtype,
                        q4, k4, v4, do4, lse, delta, qpos, kpos, *rope_args),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ] + ([pltpu.VMEM((bk, d), k4.dtype)]  # rotated k, reused per t
             if rope is not None else []),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qpos, kpos, *rope_args, q4, k4, v4, do4, lse, delta)

    return dq, dk.astype(k4.dtype), dv.astype(v4.dtype)


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def _flash_core(q4, k4, v4, qpos, kpos, rope, sm_scale, causal, block_q,
                block_k, interpret, static_causal):
    return _fwd(q4, k4, v4, qpos, kpos, rope, sm_scale, causal, block_q,
                block_k, interpret, static_causal)


def _flash_core_fwd(q4, k4, v4, qpos, kpos, rope, sm_scale, causal, block_q,
                    block_k, interpret, static_causal):
    out, lse = _fwd(q4, k4, v4, qpos, kpos, rope, sm_scale, causal, block_q,
                    block_k, interpret, static_causal)
    # Residuals carry the *named* values: under jax.checkpoint the "dots"
    # policy (models/llama.py remat_policy_for) saves attn_out/attn_lse, so
    # the backward pass reads them instead of re-running the forward kernel
    # (profiled at ~4% of step time as rematted_computation). The named
    # residual is the FLAT [B, S, H*D] view: saving the 4-D [B, S, H, 64]
    # form would tile the 64-wide minor dim to 128 lanes — a 2x HBM pad on
    # every saved attention output (PERF.md r4); the reshape back is free.
    b, s, h, dd = out.shape
    out_flat = checkpoint_name(out.reshape(b, s, h * dd), "attn_out")
    out = out_flat.reshape(b, s, h, dd)
    lse = checkpoint_name(lse, "attn_lse")
    return (out, lse), (q4, k4, v4, out_flat, lse, qpos, kpos, rope)


def _flash_core_bwd(sm_scale, causal, block_q, block_k, interpret,
                    static_causal, res, cts):
    q4, k4, v4, out_flat, lse, qpos, kpos, rope = res
    do4, dlse = cts
    out = out_flat.reshape(do4.shape)
    dq, dk, dv = _bwd(q4, k4, v4, out, lse, do4, dlse, qpos, kpos, rope,
                      sm_scale, causal, block_q, block_k, interpret,
                      static_causal)
    # rope tables get a zero cotangent (they are precomputed position
    # constants, never trained).
    drope = None if rope is None else jax.tree.map(jnp.zeros_like, rope)
    return dq, dk, dv, None, None, drope


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    q_positions: Optional[jnp.ndarray] = None,
    kv_positions: Optional[jnp.ndarray] = None,
    return_lse: bool = False,
    sm_scale: Optional[float] = None,
    rope: Optional[tuple] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
):
    """Drop-in flash counterpart of `sdpa_attention` (same shapes/semantics):
    q [B, Sq, Hq, D]; k/v [B, Sk, Hkv, D] (GQA unexpanded); optional global
    position vectors for CP shards. Returns out (and fp32 lse [B, Hq, Sq]).

    rope: optional (cos, sin) half tables [maxS, D/2] from ops.rope — when
    given, q/k arrive UNROTATED and rotate-half RoPE is applied inside the
    kernels at q_positions/kv_positions (replacing the reference's separate
    fused-rotary CUDA kernel, ref: model.py:8,136-137, and XLA's layout-heavy
    rotate-half, which profiled at ~7% of a train step).

    Backend dispatch: on TPU the Pallas kernels run compiled. On other
    backends (the simulated-mesh test platform) the mathematically identical
    jnp path runs instead — Pallas interpreter mode does not compose with
    shard_map's varying-axis checking, and tests/test_flash_attention.py
    pins kernel==jnp equivalence in interpreter mode directly. Pass
    `interpret=True` to force the Pallas interpreter (kernel unit tests).
    """
    b, sq, hq, d = q.shape
    sk = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    if interpret is None and jax.default_backend() != "tpu":
        from picotron_tpu.ops.attention import sdpa_attention
        from picotron_tpu.ops.rope import apply_rope

        if rope is not None:
            q = apply_rope(q, *rope, q_positions)
            k = apply_rope(k, *rope, kv_positions)
        return sdpa_attention(
            q, k, v, causal=causal, q_positions=q_positions,
            kv_positions=kv_positions, return_lse=return_lse,
            sm_scale=sm_scale)
    interpret = bool(interpret)
    # Contiguous-causal fast path: positions passed as None mean plain
    # 0..S-1, so block visibility is a static function of the program ids
    # and the kernels elide every below-diagonal tile's DMAs (PERF.md r5:
    # skipped programs measured ~1.4 us each — ~20% of the seq-16k
    # forward kernel). Callers with genuinely permuted layouts (the CP
    # ring/zigzag) pass explicit position arrays and keep the dynamic
    # masking path.
    static_causal = (causal and q_positions is None
                     and kv_positions is None)
    qpos = (q_positions if q_positions is not None else jnp.arange(sq))
    kpos = (kv_positions if kv_positions is not None else jnp.arange(sk))
    qpos = qpos.astype(jnp.int32).reshape(1, sq)
    kpos = kpos.astype(jnp.int32).reshape(1, sk)

    q4 = jnp.swapaxes(q, 1, 2)
    k4 = jnp.swapaxes(k, 1, 2)
    v4 = jnp.swapaxes(v, 1, 2)

    # Fold sm_scale into q once here instead of scaling the [BQ, BK] score
    # block inside every kernel program — one [B,H,S,D] multiply replaces
    # S/BK of them, and for the common d = 4^k the scale 2^-k is exact in
    # bf16. Differentiable, so dq picks up the factor through the VJP chain.
    out, lse = _flash_core(q4 * jnp.asarray(sm_scale, q4.dtype), k4, v4,
                           qpos, kpos, rope, 1.0, causal, block_q,
                           block_k, interpret, static_causal)
    out = jnp.swapaxes(out, 1, 2)
    if return_lse:
        # LSE is the *scaled-score* logsumexp, same convention as
        # sdpa_attention (which also applies sm_scale before the softmax).
        # Kernels carry it [B, Hq, Sq, 1] (TPU block-shape constraint);
        # drop the trailing dim at the boundary.
        return out, lse[..., 0]
    return out


def flash_attention_bwd_from_saved(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    out: jnp.ndarray,
    lse: jnp.ndarray,
    dout: jnp.ndarray,
    *,
    causal: bool = True,
    q_positions: Optional[jnp.ndarray] = None,
    kv_positions: Optional[jnp.ndarray] = None,
    sm_scale: Optional[float] = None,
    rope: Optional[tuple] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
):
    """(dq, dk, dv) from the forward's saved tensors — the manual-VJP entry
    for the fused grad engine (parallel/fused_bwd.py), which saves exactly
    (q, k, v, out, lse) per layer and never re-runs the forward kernel.

    Shapes follow the public `flash_attention`: q [B, Sq, Hq, D] UNROTATED
    and UNSCALED (as produced by qkv_proj — the "qkv_out" save set), out
    [B, Sq, Hq, D], lse [B, Hq, Sq] fp32 (the public return_lse form),
    dout like out. The sm_scale fold and the head-axis swaps happen here,
    mirroring `flash_attention`'s pre-kernel steps, so callers hold only
    the flat matmul-layout tensors. The LSE cotangent is zero by contract
    (training consumes `out` only).

    Contract: the gradients are computed FROM the passed (out, lse) — the
    probabilities are normalized by the saved lse, never a recomputed local
    one. Called on one K/V block of a larger attention with the block's
    positions and the GLOBAL (out, lse, dout), the result is that block's
    additive contribution to the global (dq, dk, dv) — the property the
    context-parallel backwards sum over (ring_attention_bwd_from_saved /
    ulysses_attention_bwd_from_saved). On non-TPU backends the identical
    math runs as plain jnp (ops.attention.sdpa_attention_bwd_from_saved),
    so CPU-mesh parity tests exercise the same structure as the kernels.
    """
    b, sq, hq, d = q.shape
    sk = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    if interpret is None and jax.default_backend() != "tpu":
        from picotron_tpu.ops.attention import sdpa_attention_bwd_from_saved
        from picotron_tpu.ops.rope import apply_rope

        if rope is None:
            return sdpa_attention_bwd_from_saved(
                q, k, v, out, lse, dout, causal=causal,
                q_positions=q_positions, kv_positions=kv_positions,
                sm_scale=sm_scale)
        # q/k arrive unrotated; grads map back through the rotation's
        # transpose — jax.vjp over apply_rope is that transpose exactly.
        (qr, kr), rot_vjp = jax.vjp(
            lambda q_, k_: (apply_rope(q_, *rope, q_positions),
                            apply_rope(k_, *rope, kv_positions)), q, k)
        dqr, dkr, dv = sdpa_attention_bwd_from_saved(
            qr, kr, v, out, lse, dout, causal=causal,
            q_positions=q_positions, kv_positions=kv_positions,
            sm_scale=sm_scale)
        dq, dk = rot_vjp((dqr, dkr))
        return dq, dk, dv
    interpret = bool(interpret)
    static_causal = (causal and q_positions is None
                     and kv_positions is None)
    qpos = (q_positions if q_positions is not None else jnp.arange(sq))
    kpos = (kv_positions if kv_positions is not None else jnp.arange(sk))
    qpos = qpos.astype(jnp.int32).reshape(1, sq)
    kpos = kpos.astype(jnp.int32).reshape(1, sk)
    scale = jnp.asarray(sm_scale, q.dtype)
    q4 = jnp.swapaxes(q, 1, 2) * scale
    k4 = jnp.swapaxes(k, 1, 2)
    v4 = jnp.swapaxes(v, 1, 2)
    o4 = jnp.swapaxes(out, 1, 2)
    do4 = jnp.swapaxes(dout, 1, 2)
    lse4 = lse[..., None]
    dq4, dk4, dv4 = _bwd(q4, k4, v4, o4, lse4, do4, jnp.zeros_like(lse4),
                         qpos, kpos, rope, 1.0, causal, block_q, block_k,
                         interpret, static_causal)
    # chain rule through the q * sm_scale fold
    dq = jnp.swapaxes(dq4, 1, 2) * scale
    return dq, jnp.swapaxes(dk4, 1, 2), jnp.swapaxes(dv4, 1, 2)
