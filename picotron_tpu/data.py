"""Data pipeline: tokenized, chunked micro-batch streams as sharded arrays.

Capability parity with the reference's dataloader (ref: picotron/data.py),
restructured for a single-controller SPMD runtime:

- The reference runs one DataLoader per rank with a `DistributedSampler`
  sharded by dp_rank (ref: data.py:40-45) and a collate function that slices
  each sequence to the local cp rank's contiguous chunk (ref: data.py:102-116).
  Here each process assembles the *global* batch [n_micro, global_batch, seq]
  deterministically (the source is a pure function of (epoch, cursor)) and
  hands it to the mesh under the `P(None, ('dp','ep'), 'cp')` sharding — the
  dp split on the batch dim, the cp split on the sequence dim. Single-process,
  that is one `jax.device_put`; with `jax.process_count() > 1` each process
  contributes only its addressable shards via `jax.make_array_from_callback`
  (the per-rank contract the reference's DistributedSampler implements,
  ref: data.py:40-45 — a plain device_put cannot place data on another
  host's devices).
- Tokenizer broadcast via `broadcast_object_list` (ref: data.py:23-32)
  disappears: one process per host means plain host code.
- `global_batch_size = mbs * grad_acc * dp` and
  `seq_length_per_device = seq_len / cp` keep the reference's batch math
  (ref: data.py:17-20).
- The reference tokenizes with `dataset.map(..., remove_columns)` grouping
  text into fixed `seq_len+1` blocks (ref: data.py:57-100); `tokenize_and_chunk`
  reproduces that contract. A deterministic synthetic stream stands in where
  the environment has no dataset/network (TPU pods frequently run with zero
  egress), and is what tests and benchmarks use.
"""

from __future__ import annotations

import itertools
import os
import queue as queue_mod
import threading
from typing import Any, Iterator, Optional

import jax
import numpy as np

from picotron_tpu.config import Config
from picotron_tpu.resilience import chaos
from picotron_tpu.resilience.retry import RetryPolicy, retry_call


class _ProducerError:
    """Wrapper shipping a prefetch-thread exception through the batch queue."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def cp_sequence_permutation(cfg: Config):
    """Permutation applied to the sequence axis before the P('cp') sharding,
    or None for the identity (contiguous) layout.

    Zigzag: with 2*cp equal chunks, cp shard r receives chunks (r, 2cp-1-r)
    — one early + one late chunk, so causal-attention work is balanced
    around the ring. Token-level semantics are unchanged: the model reads
    true global positions from `parallel.api.make_parallel_ctx`, which must
    agree with this layout (both derive from cfg.distributed.cp_layout).
    """
    d, s = cfg.distributed, cfg.training.seq_length
    if d.cp_size <= 1 or d.cp_layout != "zigzag":
        return None
    half = s // (2 * d.cp_size)
    chunks = []
    for r in range(d.cp_size):
        chunks.append(np.arange(r * half, (r + 1) * half))
        hi = 2 * d.cp_size - 1 - r
        chunks.append(np.arange(hi * half, (hi + 1) * half))
    return np.concatenate(chunks)


# ---------------------------------------------------------------------------
# Tokenize + chunk (ref: data.py:57-100)
# ---------------------------------------------------------------------------


def tokenize_and_chunk(dataset, tokenizer, seq_length: int,
                       text_column: str = "text", num_proc: int = 1):
    """Tokenize `text_column`, concatenate, and chunk into fixed
    `seq_length + 1`-token blocks (one extra token so input/target shifting
    needs no cross-block state) — the reference's `tokenizer_group_text`
    pipeline (ref: data.py:57-100).

    Returns a dataset of {"input_ids": [seq_length + 1]} rows.
    """
    block = seq_length + 1
    # ONE packer per worker process, shared across its map batches: the
    # partial tail carries over, so no tokens are lost at batch boundaries
    # (the reference drops the tail of every map batch, ref: data.py:70-90;
    # under num_proc > 1 each worker carries within its shard). Constructed
    # lazily INSIDE the closure: a ctypes-backed packer captured at closure
    # build time can't be pickled by HF datasets' fingerprinting, and
    # num_proc > 1 on spawn platforms would not inherit it.
    packer_box: list = []

    def tok_group(batch):
        if not packer_box:
            from picotron_tpu.native import make_packer

            packer_box.append(make_packer(block))
        packer = packer_box[0]
        texts = batch[text_column]
        out = tokenizer(texts)["input_ids"]
        packer.feed(np.fromiter(itertools.chain.from_iterable(out),
                                dtype=np.int32))
        return {"input_ids": packer.take().tolist()}

    return dataset.map(
        tok_group,
        batched=True,
        remove_columns=dataset.column_names,
        num_proc=num_proc if num_proc > 1 else None,
    )


# ---------------------------------------------------------------------------
# Batch sources
# ---------------------------------------------------------------------------


class SyntheticSource:
    """Deterministic PRNG token blocks — the zero-egress stand-in for a real
    dataset; same role as the reference's CPU config for cluster-free runs
    (ref: README.md:40-47)."""

    def __init__(self, vocab_size: int, seq_length: int, seed: int = 0,
                 num_samples: Optional[int] = None):
        self.vocab_size = vocab_size
        self.block = seq_length + 1
        self.seed = seed
        # Finite epoch so the infinite-iteration epoch-bump path is exercised
        # (ref: data.py:118-137); effectively unbounded by default.
        self.num_samples = num_samples or 1 << 30

    def __len__(self) -> int:
        return self.num_samples

    def get_rows(self, epoch: int, start: int, n: int) -> np.ndarray:
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, epoch, start]))
        return rng.integers(0, self.vocab_size, (n, self.block), dtype=np.int32)


class DatasetSource:
    """Adapter over a chunked HF dataset (rows of {"input_ids": [block]})."""

    def __init__(self, dataset, shuffle_seed: Optional[int] = None):
        # numpy output format: ds[start:n] then yields one ndarray slice of
        # the arrow buffer instead of nested Python lists — measured ~20x
        # faster get_rows (tools/data_bench.py); the format survives
        # .shuffle()/.flatten_indices() epoch views.
        self.dataset = dataset.with_format("numpy", columns=["input_ids"])
        self.shuffle_seed = shuffle_seed
        self._epoch_cache: tuple[int, Any] | None = None

    def __len__(self) -> int:
        return len(self.dataset)

    def _epoch_view(self, epoch: int):
        if self._epoch_cache is not None and self._epoch_cache[0] == epoch:
            return self._epoch_cache[1]
        ds = self.dataset
        if self.shuffle_seed is not None:
            # New permutation each epoch (the role of DistributedSampler's
            # set_epoch, ref: data.py:131). Deliberately the LAZY shuffle:
            # adding .flatten_indices() was measured (tools/data_bench.py)
            # as a ~6x READ pessimization at cache-resident scale — the
            # re-materialized arrow table slices worse than the indices
            # indirection — while the lazy path reads 37M tokens/s,
            # ~185x an 8-chip host's consumption. Revisit only if a
            # disk-bound corpus (dataset >> RAM) shows the random-read
            # cliff the indirection theoretically implies.
            ds = ds.shuffle(seed=self.shuffle_seed + epoch)
        self._epoch_cache = (epoch, ds)
        return ds

    def get_rows(self, epoch: int, start: int, n: int) -> np.ndarray:
        ds = self._epoch_view(epoch)
        rows = ds[start:start + n]["input_ids"]
        return np.asarray(rows, dtype=np.int32)


def build_eval_source(cfg: Config):
    """Validation batch source (training.eval_frequency > 0): the HF
    dataset's `eval_split` when configured, else a synthetic stream on a
    seed offset disjoint from training's."""
    d = cfg.dataset
    if d.name == "synthetic":
        return SyntheticSource(
            cfg.model.vocab_size, cfg.training.seq_length,
            seed=cfg.training.seed + 104729,  # disjoint PRNG stream
            num_samples=cfg.training.num_samples,
        )
    if d.eval_split is None:
        raise ValueError(
            "training.eval_frequency > 0 with an HF dataset requires "
            "dataset.eval_split (e.g. 'validation')")
    import datasets
    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(
        d.tokenizer_name or cfg.model.name)
    raw = datasets.load_dataset(d.name, d.subset_name, split=d.eval_split)
    chunked = tokenize_and_chunk(raw, tokenizer, cfg.training.seq_length,
                                 d.text_column, d.num_proc)
    return DatasetSource(chunked, shuffle_seed=None)


# ---------------------------------------------------------------------------
# The loader
# ---------------------------------------------------------------------------


class MicroBatchDataLoader:
    """Yields (input_ids, targets) pairs shaped
    [grad_acc, global_batch, seq_length], placed into the mesh's
    P(None, ('dp','ep'), 'cp') sharding (process-local shards only on
    multi-host runs). Iteration is infinite: exhausting the
    source bumps the epoch and continues (ref: data.py:118-137). The tail of
    each epoch is dropped when len(source) is not a multiple of the global
    batch (up to global_batch - 1 blocks — the reference's drop_last
    behavior, ref: data.py:40-45).

    `dataset.num_workers > 0` enables host-side prefetch: a background
    thread assembles and device_puts up to num_workers batches ahead, so
    host batch assembly overlaps device compute (the role of the
    reference's DataLoader num_workers). `state` / `set_state` expose the
    (epoch, cursor) position for checkpoint resume; set_state must be
    called before the first `next()`.
    """

    def __init__(self, cfg: Config, menv, source=None):
        self.cfg = cfg
        self.menv = menv
        self.global_batch_size = cfg.global_batch_size  # ref: data.py:17
        self.seq_length = cfg.training.seq_length
        self.source = source if source is not None else self._build_source()
        if len(self.source) < self.global_batch_size:
            raise ValueError(
                f"dataset has {len(self.source)} blocks < one step's "
                f"{self.global_batch_size}"
            )
        self.epoch = 0
        self.cursor = 0
        self.sharding = menv.batch_sharding()
        self.cp_perm = cp_sequence_permutation(cfg)
        self._consumed_state = {"epoch": 0, "cursor": 0}
        self._prefetch_depth = cfg.dataset.num_workers
        self._queue = None  # created lazily on first __next__
        self._producer_exc = None  # set once the prefetch thread dies
        # Transient source-I/O retry (resilience config) around batch
        # assembly, in both the sync and prefetch paths.
        self._retry = RetryPolicy.from_config(cfg.resilience)
        # Global batch ordinal (1-based, derived from the data position so
        # it survives resume) — the deterministic key chaos data events
        # fire on: a resumed run past an injected stall does not re-stall.
        self._steps_per_epoch = max(1, len(self.source)
                                    // self.global_batch_size)
        self._batch_index = 0

    # -- resume position (persisted in checkpoint meta; ADVICE r1) --------

    @property
    def state(self) -> dict:
        """Position after the last batch handed out — persist this at
        checkpoint time so resume does not replay consumed data. With
        prefetch enabled this intentionally lags the production cursor by
        the queued (not yet trained-on) batches."""
        return dict(self._consumed_state)

    def set_state(self, st: dict) -> None:
        if self._queue is not None:
            raise RuntimeError("set_state must be called before iteration "
                               "starts (prefetch already running)")
        self.epoch = int(st["epoch"])
        self.cursor = int(st["cursor"])
        self._consumed_state = {"epoch": self.epoch, "cursor": self.cursor}
        self._batch_index = (self.epoch * self._steps_per_epoch
                             + self.cursor // self.global_batch_size)

    def reset(self, st: dict) -> None:
        """Reposition mid-run (the divergence guard's rollback path: jump
        past a poison data range). Stops the prefetch thread and drops any
        queued batches first — they were assembled beyond the old cursor
        and must not leak into the repositioned stream."""
        if self._queue is not None:
            self._stop.set()
            self._thread.join(timeout=10.0)
            # _produce captured this queue/stop pair by argument; a thread
            # still draining a chaos stall can only touch the old pair.
            self._queue = None
            self._producer_exc = None
        self.set_state(st)

    def _build_source(self):
        d = self.cfg.dataset
        if d.name == "synthetic":
            return SyntheticSource(
                self.cfg.model.vocab_size, self.seq_length,
                seed=self.cfg.training.seed,
                num_samples=self.cfg.training.num_samples,
            )
        import datasets  # HF; lazy so synthetic paths never import it

        if os.path.isdir(d.name):
            # File-backed corpus (datasets.save_to_disk layout): either a
            # PRE-CHUNKED table of {"input_ids": [seq+1]} rows (tokenize
            # once offline, train many times — the zero-egress path; also
            # what the 2-process data-determinism test feeds) or raw text
            # to tokenize here.
            ds = datasets.load_from_disk(d.name)
            if isinstance(ds, datasets.DatasetDict):
                # saving a loaded dataset without selecting a split yields
                # a DatasetDict; pick the configured split (its
                # column_names is a per-split dict, so falling through
                # would crash confusingly in the tokenizer path)
                if d.split not in ds:
                    raise ValueError(
                        f"dataset dir {d.name} holds splits "
                        f"{sorted(ds)}; dataset.split={d.split!r} is not "
                        "one of them")
                ds = ds[d.split]
            if "input_ids" in ds.column_names:
                block = len(ds[0]["input_ids"])
                if block != self.seq_length + 1:
                    raise ValueError(
                        f"pre-chunked dataset at {d.name} has blocks of "
                        f"{block} tokens; training.seq_length="
                        f"{self.seq_length} needs {self.seq_length + 1} "
                        f"(input/target shift) — re-chunk the corpus")
                return DatasetSource(ds,
                                     shuffle_seed=self.cfg.training.seed)
            raw = ds
        else:
            raw = datasets.load_dataset(d.name, d.subset_name,
                                        split=d.split)
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(
            d.tokenizer_name or self.cfg.model.name)
        chunked = tokenize_and_chunk(
            raw, tokenizer, self.seq_length, d.text_column, d.num_proc)
        return DatasetSource(chunked, shuffle_seed=self.cfg.training.seed)

    def __iter__(self) -> Iterator:
        return self

    def _assemble_next(self):
        """Produce the next (batch, post_state) at the production cursor.
        Idempotent under retry: the cursor/batch-index advance only after
        the source read succeeds (and the epoch-bump re-check is a no-op
        on re-entry), so a failed attempt re-assembles the same batch."""
        idx = self._batch_index + 1
        chaos.fire("data_produce", step=idx)
        n = self.global_batch_size
        if self.cursor + n > len(self.source):
            self.epoch += 1  # ref: data.py:129-133 epoch bump
            self.cursor = 0
        rows = self.source.get_rows(self.epoch, self.cursor, n)
        self.cursor += n
        self._batch_index = idx
        t = self.cfg.training
        blocks = rows.reshape(
            t.gradient_accumulation_steps,
            t.micro_batch_size * self.cfg.distributed.dp_size
            * self.cfg.distributed.ep_size,
            self.seq_length + 1,
        )
        ids = blocks[..., :-1]
        targets = blocks[..., 1:]
        if self.cp_perm is not None:
            # Reorder the sequence so the contiguous P('cp') shards receive
            # the zigzag chunks; targets were shifted BEFORE permuting, so
            # each token still predicts its true successor.
            ids = ids[..., self.cp_perm]
            targets = targets[..., self.cp_perm]
        batch = (self._put_sharded(ids), self._put_sharded(targets))
        return batch, {"epoch": self.epoch, "cursor": self.cursor}

    def _put_sharded(self, arr: np.ndarray):
        """Hand a host-assembled global array to the mesh. Multi-process,
        `jax.device_put` would have to place shards on non-addressable
        devices and throws; instead every process runs this same code on the
        same (deterministic) global batch and `make_array_from_callback`
        pulls out just the shards its local devices own. Token blocks are
        int32 and small relative to activations, so the redundant host-side
        assembly is cheap and keeps the path layout-agnostic (any
        process->device assignment the runtime picks works)."""
        if jax.process_count() == 1:
            return jax.device_put(arr, self.sharding)
        return jax.make_array_from_callback(
            arr.shape, self.sharding, lambda idx: arr[idx])

    def _assemble_with_retry(self):
        """Batch assembly under the transient-I/O retry policy (OSError
        only — a logic error in the source must still fail fast)."""
        return retry_call(self._assemble_next, policy=self._retry,
                          describe="batch assembly")

    def _produce(self, queue, stop):
        # queue/stop arrive as arguments, not via self: after a reset()
        # the loader starts a fresh pair, and a previous thread still
        # unwinding (e.g. out of a chaos stall) must keep talking to its
        # own — stale — queue rather than feed the repositioned stream.
        while not stop.is_set():
            try:
                item = self._assemble_with_retry()
            except BaseException as e:  # noqa: BLE001 — relayed to consumer
                # A dead producer must not leave the consumer blocked on an
                # empty queue forever; ship the exception as an item and let
                # __next__ re-raise it on the training thread.
                item = _ProducerError(e)
            while not stop.is_set():
                try:
                    queue.put(item, timeout=0.5)
                    break
                except queue_mod.Full:
                    continue
            if isinstance(item, _ProducerError):
                return

    def close(self) -> None:
        if self._queue is not None:
            self._stop.set()

    def __next__(self):
        if self._prefetch_depth > 0:
            if self._queue is None:
                self._queue = queue_mod.Queue(maxsize=self._prefetch_depth)
                self._stop = threading.Event()
                self._thread = threading.Thread(
                    target=self._produce, args=(self._queue, self._stop),
                    daemon=True, name="picotron-data-producer")
                self._thread.start()
            if self._producer_exc is not None:  # producer already dead
                raise RuntimeError(
                    "dataloader prefetch thread died") from self._producer_exc
            got = self._queue.get()
            if isinstance(got, _ProducerError):
                # remember it: the thread has exited, so every later call
                # must fail loudly too instead of blocking on an empty queue
                self._producer_exc = got.exc
                raise RuntimeError(
                    "dataloader prefetch thread died") from got.exc
            batch, post_state = got
        else:
            batch, post_state = self._assemble_with_retry()
        self._consumed_state = post_state
        return batch
