"""Shared utilities: seeds, formatting, MFU accounting, rank-aware printing.

Capability parity with the reference's utils (ref: picotron/utils.py), with the
hardware constants made TPU-native: the reference hardcodes the H100 bf16 peak
(989.5 TFLOP/s, ref: utils.py:42); here peak FLOP/s is looked up per TPU
generation from the device kind, as SURVEY.md §5 prescribes.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Optional

import jax

from picotron_tpu.config import Config, ModelConfig, num_params


# ---------------------------------------------------------------------------
# Hardware peaks
# ---------------------------------------------------------------------------

# Published per-chip bf16 peak FLOP/s by TPU generation.
TPU_PEAK_FLOPS: dict[str, float] = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,  # Trillium
    "v6p": 918e12,
}
# The reference's H100 constant, kept for apples-to-apples MFU comparison
# against its published numbers (ref: utils.py:42).
H100_BF16_PEAK = 989.5e12


def device_peak_flops(device: Optional[jax.Device] = None) -> float:
    """Per-chip bf16 peak FLOP/s for `device` (default: first local device).

    Real device_kind strings use the hardware naming, not the marketing one:
    a v5e reports "TPU v5 lite", a v6e/Trillium "TPU v6 lite", a v5p
    "TPU v5p" (and "TPU v5" alone means v5p). Unknown kinds (e.g. the CPU
    test platform) fall back to the v5e peak so derived MFU stays finite and
    comparable.
    """
    if device is None:
        device = jax.devices()[0]
    kind = device.device_kind.lower()
    if "v6" in kind or "trillium" in kind:
        return TPU_PEAK_FLOPS["v6e"]
    if "v5 lite" in kind or "v5lite" in kind or "v5e" in kind:
        return TPU_PEAK_FLOPS["v5e"]
    if "v5" in kind:  # "TPU v5p" / bare "TPU v5"
        return TPU_PEAK_FLOPS["v5p"]
    for gen in ("v4", "v3", "v2"):
        if gen in kind:
            return TPU_PEAK_FLOPS[gen]
    return TPU_PEAK_FLOPS["v5e"]


# ---------------------------------------------------------------------------
# FLOPs / MFU accounting (ref: utils.py:39-48)
# ---------------------------------------------------------------------------


def flops_per_token(m: ModelConfig, seq_length: int) -> float:
    """Training FLOPs per token: 6N + 12·L·h·s — same formula the reference
    uses so MFU numbers are directly comparable (ref: utils.py:46-47).
    """
    # MoE: only visited experts compute; tied head: the matmul runs anyway
    n = num_params(m, active_only=True, include_tied_head=True)
    return 6.0 * n + 12.0 * m.num_hidden_layers * m.hidden_size * seq_length


def mfu(tokens_per_second: float, m: ModelConfig, seq_length: int,
        num_chips: int, peak_flops_per_chip: Optional[float] = None) -> float:
    """Model FLOPs utilization in [0, 1]."""
    if peak_flops_per_chip is None:
        peak_flops_per_chip = device_peak_flops()
    achieved = tokens_per_second * flops_per_token(m, seq_length)
    return achieved / (peak_flops_per_chip * num_chips)


# ---------------------------------------------------------------------------
# Formatting / logging (ref: utils.py:12-37)
# ---------------------------------------------------------------------------


def human_format(num: float) -> str:
    """1234567 -> '1.23M' (ref: utils.py:27-37)."""
    num = float(f"{num:.3g}")
    magnitude = 0
    while abs(num) >= 1000:
        magnitude += 1
        num /= 1000.0
    suffix = ["", "K", "M", "B", "T", "P"][magnitude]
    return f"{num:f}".rstrip("0").rstrip(".") + suffix


def is_logging_host() -> bool:
    """Single-controller analogue of the reference's wandb-rank gate
    (ref: train.py:101): under JAX only process 0 logs."""
    return jax.process_index() == 0


def log_print(*args, **kwargs) -> None:
    """Print from the logging host only (the reference needs an fcntl file
    lock to serialize per-rank prints, ref: utils.py:12-20; a single
    controller per host makes that a process_index gate)."""
    if is_logging_host():
        print(*args, **kwargs)
        sys.stdout.flush()


class StepTimer:
    """Wall-clock per-step timing for tokens/s (ref: train.py:220,242)."""

    def __init__(self) -> None:
        self._t0 = time.perf_counter()

    def lap(self) -> float:
        now = time.perf_counter()
        dt = now - self._t0
        self._t0 = now
        return dt


def training_log_line(step: int, loss: float, tokens_per_sec: float,
                      tokens_per_sec_per_chip: float, mfu_frac: float,
                      trained_tokens: int, memory_gb: float = 0.0,
                      extras: Optional[dict] = None) -> str:
    """The per-step console line. Format is a de-facto API consumed by the
    metrics harvester (ref: train.py:248-259 <-> extract_metrics.py:55-68);
    tools/extract_metrics.py parses exactly these field names. `extras`
    appends step-metric scalars after the stable fields (e.g. MoE's
    `moe_drop_frac`), so the harvester's prefix parse is unaffected."""
    line = (
        f"[step {step:06d}] loss: {loss:.4f} | "
        f"tokens/s: {human_format(tokens_per_sec)} | "
        f"tokens/s/chip: {human_format(tokens_per_sec_per_chip)} | "
        f"MFU: {100.0 * mfu_frac:.2f}% | "
        f"tokens: {human_format(trained_tokens)} | "
        f"mem: {memory_gb:.1f}GB"
    )
    for k, v in (extras or {}).items():
        line += f" | {k}: {v:.4f}"
    return line


def dump_all_stacks(file=None) -> None:
    """Write every thread's Python stack to `file` (default stderr) — the
    watchdog's post-mortem when a step or the data producer hangs: which
    thread is stuck, and where. Thread names come from threading;
    sys._current_frames also surfaces threads the module does not know."""
    file = file or sys.stderr
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in sys._current_frames().items():
        print(f"--- thread {names.get(ident, '<unknown>')} "
              f"(ident {ident}) ---", file=file)
        traceback.print_stack(frame, file=file)
    file.flush()


def device_memory_gb() -> float:
    """Peak on-device memory in GiB if the backend exposes it (the TPU
    analogue of torch.cuda.memory_reserved, ref: train.py:255). Max over
    this process's local devices — under tp/pp sharding different chips
    peak differently, and the max is the one that OOMs. (Cross-host maxing
    would need a collective; each host logging its own max is the useful
    view since log_print gates to process 0, whose chips are
    representative under SPMD.)"""
    peak = 0.0
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
            if stats and "peak_bytes_in_use" in stats:
                peak = max(peak, stats["peak_bytes_in_use"] / (1024 ** 3))
        except Exception:
            pass
    return peak
