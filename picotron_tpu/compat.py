"""JAX version portability for the typed-shard_map API surface.

The parallel layer is written against the varying-manual-axes ("vma")
shard_map type system (`jax.shard_map`, `lax.pcast`, `jax.typeof(x).vma`).
Older JAX releases (<= 0.4.x) ship shard_map under `jax.experimental` with
the replication-rule checker instead of the vma types. Everything the
composed step needs from the newer API has an exact old-API spelling:

- `jax.shard_map(...)` -> `jax.experimental.shard_map.shard_map(...,
  check_rep=False)`. With the checker off there is no replication typing to
  satisfy. CAVEAT: differentiating THROUGH a `lax.psum` (a psum inside the
  grad closure, e.g. a tp all-reduce in the forward) multiplies the
  cotangent by the axis size on pre-vma JAX — measured 4x on a cp=4 mesh,
  with check_rep=True no better. Grads of a LOCAL loss psummed AFTERWARDS
  (the `_device_grads` pattern in parallel/api.py) are unaffected. The
  parity tests that require grad-through-psum skip on `not HAS_VMA`.
- `lax.pcast(x, axes, to="varying")` exists purely to satisfy the vma type
  system (it is an identity on values); without that type system it IS the
  identity.
- `jax.typeof(x).vma` reads the axes a value varies over. The old API has
  no such record; `vma()` returns the empty set, which is sound everywhere
  the information is used to *add* varying axes (forgetting replication
  knowledge), and the one site that needs the real answer
  (parallel/pp.py sync_sp_partial_grads) guards on `HAS_VMA` explicitly.

Keeping the adaptation in one module means the parallel layer reads as if
the new API were always present, and deleting this file is the entire
migration cost once the fleet's JAX floor catches up.
"""

from __future__ import annotations

import jax
from jax import lax

# The vma type system (pcast/pvary + typeof().vma) arrives together with
# the public jax.shard_map; probe the one knob the code paths branch on.
HAS_VMA = hasattr(lax, "pcast") and hasattr(jax, "typeof")


def shard_map(f, *, mesh, in_specs, out_specs):
    """`jax.shard_map` on new JAX; the experimental spelling (checker off,
    see module docstring) on old."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def vma(x) -> frozenset:
    """Mesh axes `x` varies over — empty on JAX without the vma types
    (sound only where the caller ADDS varying axes; see module docstring)."""
    if HAS_VMA:
        return frozenset(jax.typeof(x).vma)
    return frozenset()


def pcast(x, axes, to="varying"):
    """`lax.pcast` when the vma type system exists; identity otherwise
    (pcast never changes values, only the varying-axes type)."""
    if HAS_VMA:
        return lax.pcast(x, axes, to=to)
    return x


def memory_space_puts():
    """(to_device, to_host) callables for memory-SPACE-only transfers
    inside jit (optimizer offload). New JAX spells this
    `device_put(x, MemorySpace.Device/Host)`; 0.4.x spells it
    `device_put(x, TransferToMemoryKind('device'/'pinned_host'))`."""
    try:
        from jax._src.core import MemorySpace

        return (lambda x: jax.device_put(x, MemorySpace.Device),
                lambda x: jax.device_put(x, MemorySpace.Host))
    except ImportError:
        from jax._src.sharding_impls import TransferToMemoryKind

        return (lambda x: jax.device_put(x, TransferToMemoryKind("device")),
                lambda x: jax.device_put(
                    x, TransferToMemoryKind("pinned_host")))


def require_vma(feature: str) -> None:
    """Fail loudly where correctness (not just typing) depends on reading
    real vma information — silently-wrong gradients are never acceptable."""
    if not HAS_VMA:
        raise RuntimeError(
            f"{feature} requires the varying-manual-axes shard_map type "
            f"system (jax.typeof(...).vma), which this JAX "
            f"({jax.__version__}) predates; upgrade JAX or disable the "
            f"feature")
