"""Checkpointing: sharded train-state save/resume + HF safetensors import.

Capability parity with the reference's checkpoint layer
(ref: picotron/checkpoint.py), upgraded where the TPU stack makes it free:

- **Training state** — the reference writes one `.pth` per (tp_rank, pp_rank)
  with the topology baked into the filename, saved only by dp/cp rank 0, and
  resume asserts the identical parallel layout (ref: checkpoint.py:242-278).
  Here Orbax saves the global arrays once (each host writes its shards), and
  restore takes the *target* sharding — resuming on a different
  DPxPPxCPxTP layout reshards automatically, the "easy win over the
  reference" SURVEY.md §5 calls out. Saved payload matches the reference's:
  model + optimizer + step + trained tokens (ref: checkpoint.py:254-259).
- **HF weight import** — the reference reads only this rank's tensors from
  (sharded or single-file) safetensors, TP-slices them, regex-renames
  safetensors->picotron names, then *discards the values* by re-running
  random init; weights are shape templates only (ref: checkpoint.py:93-101).
  Here `load_hf_safetensors` actually materializes the weights into the
  stacked param pytree (renaming + torch->jax layout transposes), because
  a real framework should fine-tune; `init_params` remains the random
  bootstrap path. Untied lm_head force-creation (ref: checkpoint.py:88-91)
  maps to falling back to the embedding matrix when the file has no
  `lm_head.weight`.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from picotron_tpu.ckpt_integrity import (
    VerifyResult, atomic_write_text, build_manifest, retention_plan,
    rmtree, verify_step_dir, write_manifest,
)
from picotron_tpu.config import Config, ModelConfig
from picotron_tpu.resilience import chaos, elastic
from picotron_tpu.resilience.retry import RetryPolicy, retry_call
from picotron_tpu.telemetry import bus as telemetry_bus
from picotron_tpu.train_step import TrainState


def _isdir(path: str) -> bool:
    """Directory probe through epath (Orbax's own path layer) so
    URL-style stores (gs://) answer correctly — os.path.isdir is always
    False on URL paths, which would classify every remote checkpoint as
    not-durable and silently disable auto-resume (code review r5)."""
    try:
        from etils import epath

        return epath.Path(path).is_dir()
    except ImportError:
        return os.path.isdir(path)


def _listdir(path: str) -> list:
    """Child names of a directory, [] when absent — epath-first for the
    same URL-store reason as _isdir."""
    try:
        from etils import epath

        root = epath.Path(path)
        return [p.name for p in root.iterdir()] if root.is_dir() else []
    except ImportError:
        return os.listdir(path) if os.path.isdir(path) else []


# ---------------------------------------------------------------------------
# Orbax-backed training-state checkpointing
# ---------------------------------------------------------------------------


class CheckpointManager:
    """Save/restore TrainState under `<save_dir>/step_<n>/` (ref:
    checkpoint.py:232-278; the per-(tp,pp)-rank filename scheme collapses to
    one logical global checkpoint).

    Lineage integrity (picotron_tpu/ckpt_integrity): every save ends with a
    commit manifest — per-file content digests of the committed step dir,
    written tmp+rename as the last act, hashed AFTER the async array write
    lands so the step path never waits on it. Restore-side, durability
    (Orbax finalization) is necessary but no longer sufficient:
    `latest_valid_step` walks the lineage newest-first and returns the
    newest step that is durable AND verifies against its manifest, so a
    bit-flipped shard or torn meta.json on the newest step costs a
    fallback (emitting a `ckpt_corrupt` event), not the run. Retention GC
    (`checkpoint.keep_last` / `keep_every`) prunes after each commit,
    never the last verified step.

    Multihost requirement: `save_dir` must be a filesystem shared by every
    host (GCS / NFS — the standard Cloud TPU arrangement, and what Orbax
    itself needs to assemble the sharded array write). meta.json and the
    manifest are written by process 0 and read by all processes on
    restore, which assumes the same shared view."""

    def __init__(self, cfg: Config, menv=None, directory: Optional[str] = None):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.cfg = cfg
        self.menv = menv
        self.directory = os.path.abspath(directory or cfg.checkpoint.save_dir)
        # Post-write commit work (manifest hash + write, chaos hook, GC)
        # runs on this thread for async saves; joined by
        # wait_until_finished so durability still means "manifest too".
        self._commit_thread: Optional[threading.Thread] = None
        # Async by default (SURVEY §5 names async Orbax the TPU-native
        # upgrade over the reference's blocking .pth writes, ref:
        # checkpoint.py:246-260): save() returns once the device->host
        # copies are staged — safe even with donated step buffers, since
        # the staging happens before save() returns — and the disk write
        # proceeds concurrently with the next training steps.
        if cfg.checkpoint.async_save:
            self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        else:
            self._ckptr = ocp.StandardCheckpointer()
        # Flaky-store retry policy (resilience config): save/restore and
        # the durability probe all ride it. The probe variant keeps the
        # attempt budget but caps the delays — latest_step() probes every
        # step dir, and a 30 s backoff per dir would stall resume.
        self._retry = RetryPolicy.from_config(cfg.resilience)
        self._probe_retry = dataclasses.replace(
            self._retry,
            base_delay=min(self._retry.base_delay, 0.2),
            max_delay=min(self._retry.max_delay, 1.0))

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def save(self, state: TrainState, trained_tokens: int = 0,
             dataloader_state: Optional[dict] = None) -> str:
        # At most one save in flight: a still-running previous write must
        # finish before its directory layout is mutated again.
        self._ckptr.wait_until_finished()
        step = int(state.step)
        path = self._step_dir(step)

        def _write():
            # Chaos injection + retry sit around the whole write so a
            # transient store failure (or an injected one) costs a
            # backoff, not the run; force=True makes the re-save of a
            # partially staged attempt idempotent.
            chaos.fire("ckpt_save", step=step)
            self._ckptr.save(
                os.path.join(path, "state"),
                {"params": state.params, "opt_state": state.opt_state,
                 "step": state.step},
                force=True,
            )
            if not self.cfg.checkpoint.async_save:
                self._ckptr.wait_until_finished()
            if jax.process_index() == 0:
                # Orbax coordinates the sharded array write across hosts;
                # the sidecar metadata must be written once, not per-host.
                # Written immediately (even mid-async-write): durability
                # is judged by the finalized `state` dir (latest_step),
                # not by meta.json. tmp+rename so a crash mid-write leaves
                # no torn JSON under the final name to poison restore.
                meta = {
                    "step": step,
                    "trained_tokens": int(trained_tokens),
                    "config": self.cfg.to_json_dict(),
                }
                if dataloader_state is not None:
                    meta["dataloader"] = dataloader_state
                atomic_write_text(os.path.join(path, "meta.json"),
                                  json.dumps(meta, indent=2))

        retry_call(_write, policy=self._retry,
                   describe=f"checkpoint save (step {step})")
        if self.cfg.checkpoint.async_save:
            # The manifest hashes the step dir's committed bytes, so it
            # must run after the async array write lands — on its own
            # thread, off the step path (the whole point of async saves).
            self._commit_thread = threading.Thread(
                target=self._commit, args=(step, path),
                name=f"ckpt-commit-{step}", daemon=False)
            self._commit_thread.start()
        else:
            self._commit(step, path)
        return path

    def _topology(self) -> dict:
        d = self.cfg.distributed
        return {"dp": d.dp_size, "pp": d.pp_size, "ep": d.ep_size,
                "cp": d.cp_size, "tp": d.tp_size,
                "world_size": d.world_size, "slices": d.slices,
                "process_count": jax.process_count()}

    def _commit(self, step: int, path: str) -> None:
        """Last act of a save: wait for the array write to land, then
        write the commit manifest (process 0; the write itself is
        tmp+rename-atomic) and run retention GC. A failure here leaves the
        checkpoint durable-but-legacy (still restorable, never ranked
        "verified") rather than failing the run — reported via the probe
        event, not an exception on the commit thread."""
        try:
            self._ckptr.wait_until_finished()
            if jax.process_index() == 0:
                def _hash_and_write():
                    manifest = build_manifest(
                        path, step=step, topology=self._topology())
                    write_manifest(path, manifest)
                    return manifest

                manifest = retry_call(
                    _hash_and_write, policy=self._probe_retry,
                    describe=f"manifest commit (step {step})")
                telemetry_bus.emit(
                    "ckpt_commit", step=step,
                    files=manifest["file_count"],
                    bytes=manifest["total_bytes"])
                # Corruption chaos mutates the *committed* bytes — the
                # fault the manifest machinery exists to catch.
                chaos.fire("ckpt_committed", step=step, path=path)
                self.gc()
        except Exception as e:  # noqa: BLE001
            self._probe_failed(path, e, what="manifest commit")

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save is durable on disk AND its
        commit manifest is written. Call before process exit (train.py
        does) and before restoring a checkpoint this manager may still be
        writing."""
        self._ckptr.wait_until_finished()
        t = self._commit_thread
        if t is not None and t is not threading.current_thread():
            t.join()
            self._commit_thread = None

    def _is_durable(self, step_dirname: str) -> bool:
        """True when the step's `state` checkpoint is fully committed.
        Orbax's own finalization check covers both commit strategies —
        tmp-dir-plus-atomic-rename on posix and in-place-write-plus-commit-
        marker on GCS-style stores (where the final directory exists while
        the write is still in flight, so a bare isdir test would hand
        restore a torn checkpoint; code review r3)."""
        state_dir = os.path.join(self.directory, step_dirname, "state")
        if not _isdir(state_dir):
            return False
        try:
            # The probe itself retries transient store errors (short
            # backoff) — the general form of the old one-shot
            # _probe_failed: a 2-second GCS blip while listing steps must
            # not hide a durable checkpoint from auto_resume.
            return bool(retry_call(
                self._ocp.utils.is_checkpoint_finalized, state_dir,
                policy=self._probe_retry,
                describe=f"durability probe {step_dirname}"))
        except ValueError as e:
            # "not an Orbax-managed checkpoint path" (older Orbax APIs).
            # json.JSONDecodeError subclasses ValueError, so a torn
            # finalization-metadata file must NOT ride this branch to
            # "durable" (ADVICE r4) — it falls through to the not-durable
            # handler. The durable=True conclusion holds only for LOCAL
            # paths, where Orbax commits by atomic rename (the final
            # `state` dir existing at all means the rename happened);
            # URL-style stores commit via marker files, so absent metadata
            # there means possibly-torn, not durable.
            if isinstance(e, json.JSONDecodeError) or "://" in state_dir:
                return self._probe_failed(state_dir, e)
            return True
        except Exception as e:  # noqa: BLE001
            # Transient metadata read errors (GCS-style stores — exactly
            # the case the finalization check exists for) must NOT classify
            # an in-flight/torn checkpoint as durable (ADVICE r3). Skip it;
            # a genuinely durable step is re-discovered on the next probe.
            return self._probe_failed(state_dir, e)

    @staticmethod
    def _probe_failed(state_dir: str, e: Exception,
                      what: str = "durability probe") -> bool:
        import warnings

        # Routed through the bus as an event (counted by
        # tools/telemetry_report.py) so flaky-store noise is visible in
        # the JSONL stream, not just a stderr warning a supervisor log
        # rotation eats.
        telemetry_bus.emit("ckpt_probe_failed", what=what,
                           path=str(state_dir), error=repr(e))
        warnings.warn(f"checkpoint {what} failed for "
                      f"{state_dir}: {e!r}; treating as not durable")
        return False

    def steps(self) -> list:
        """All step numbers with a step_<n> dir, sorted (durable or not)."""
        return sorted(
            int(m.group(1)) for d in _listdir(self.directory)
            if (m := re.fullmatch(r"step_(\d+)", d)))

    def durable_steps(self) -> list:
        """Step numbers whose `state` checkpoint is fully committed."""
        return [s for s in self.steps()
                if self._is_durable(f"step_{s:08d}")]

    def latest_step(self) -> Optional[int]:
        """Newest *durable* checkpoint step — finalized, but NOT content-
        verified (prefer latest_valid_step, which is). An async save that
        has not committed yet (or a crashed one) is skipped rather than
        handed to restore (see _is_durable)."""
        steps = self.durable_steps()
        return max(steps) if steps else None

    def verify_step(self, step: int, deep: bool = True) -> VerifyResult:
        """Verify step's bytes against its commit manifest (see
        ckpt_integrity.verify_step_dir for the verdict semantics)."""
        return verify_step_dir(self._step_dir(step), deep=deep)

    def _report_corrupt(self, step: int, res: VerifyResult) -> None:
        telemetry_bus.emit("ckpt_corrupt", step=step,
                           failures=list(res.failures[:8]))
        print(f"[ckpt] step {step} failed verification "
              f"({'; '.join(res.failures[:3]) or res.status}); "
              f"falling back to an older checkpoint",
              file=sys.stderr, flush=True)

    def latest_valid_step(self) -> Optional[int]:
        """Newest step that is durable AND verifies against its commit
        manifest — what restore/auto-resume/rollback trust. Walks the
        lineage newest-first; every durable-but-corrupt step it skips on
        the way down emits a `ckpt_corrupt` telemetry event, so a flipped
        bit costs a logged fallback to the last known-good step instead
        of the run."""
        for step in sorted(self.durable_steps(), reverse=True):
            res = self.verify_step(step)
            if res.ok:
                return step
            self._report_corrupt(step, res)
        return None

    def valid_steps(self) -> list:
        """All durable steps that pass verification, sorted — the restore
        menu ckpt_doctor and explicit-step error messages show."""
        return [s for s in self.durable_steps() if self.verify_step(s).ok]

    def gc(self, dry_run: bool = False) -> dict:
        """Retention GC: prune step dirs per checkpoint.keep_last /
        keep_every; returns {"kept": [...], "deleted": [...]}. Runs after
        each durable commit (process 0 only — every other process sees
        the shared store mutate, same as it does for saves; and only
        post-commit, when no host can still be mid-restore: restores
        happen at startup/rollback, strictly before the subsequent save's
        commit). The last *verified* step is protected unconditionally —
        keep_last=1 with a corrupt newest step keeps the fallback alive.
        Only durable steps are candidates: a partially-written dir from a
        concurrent/crashed save is never touched."""
        ck = self.cfg.checkpoint
        if ck.keep_last <= 0:
            return {"kept": self.steps(), "deleted": []}
        durable = self.durable_steps()
        protect = set()
        last_valid = self.latest_valid_step()
        if last_valid is not None:
            protect.add(last_valid)
        keep, delete = retention_plan(durable, keep_last=ck.keep_last,
                                      keep_every=ck.keep_every,
                                      protect=protect)
        if not dry_run and jax.process_index() == 0:
            for s in delete:
                rmtree(self._step_dir(s))
            if delete:
                telemetry_bus.emit("ckpt_gc", deleted=delete, kept=keep)
        return {"kept": keep, "deleted": delete}

    def restore(self, state_template: TrainState,
                step: Optional[int] = None) -> tuple[TrainState, dict]:
        """Restore into the shardings/dtypes of `state_template` (any
        topology — resharding is Orbax's job). Returns (state, meta) where
        meta carries at least trained_tokens, plus the dataloader position
        when the checkpoint recorded one.

        With no explicit step this restores the newest durable AND
        verified checkpoint (latest_valid_step — the lineage-fallback
        path). An explicit step is validated the same way first, so a
        non-durable or corrupt request fails with the list of valid steps
        instead of a raw JSON/Orbax error mid-restore.
        """
        self.wait_until_finished()  # never read our own partial write
        if step is None:
            step = self.latest_valid_step()
            if step is None:
                raise FileNotFoundError(
                    f"no valid checkpoints under {self.directory}")
        else:
            if not self._is_durable(f"step_{step:08d}"):
                raise FileNotFoundError(
                    f"checkpoint step {step} under {self.directory} is "
                    f"missing or not durable (save incomplete/crashed); "
                    f"available valid steps: {self.valid_steps()}")
            res = self.verify_step(step)
            if not res.ok:
                self._report_corrupt(step, res)
                raise FileNotFoundError(
                    f"checkpoint step {step} under {self.directory} "
                    f"failed verification "
                    f"({'; '.join(res.failures[:3])}); available valid "
                    f"steps: {self.valid_steps()}")
        path = self._step_dir(step)

        def _read_meta():
            with open(os.path.join(path, "meta.json")) as f:
                return json.load(f)

        meta = retry_call(_read_meta, policy=self._retry,
                          describe=f"checkpoint meta read (step {step})")
        # Topology compatibility (resilience/elastic.py): a checkpoint
        # saved under a different mesh shape must never resume silently —
        # either hard-fail naming both topologies (elastic off) or
        # validate the constant-global-batch invariant and record the
        # resize (elastic on). Orbax handles the array resharding either
        # way; this guard handles the semantics. Runs before the uneven-PP
        # check so the operator-facing story leads with the topology.
        resize = elastic.check_restore_topology(
            path, meta, self.cfg, step=step, save_dir=self.directory)
        if resize is not None:
            # surfaced to the caller (train.build_state books/emits it);
            # never written back to disk
            meta["elastic_resize"] = resize
        # Checkpoints store the PP-padded layer stack. Even splits are
        # canonical (no padding), so any-topology restore works; an uneven
        # split bakes its pp into the padded shape, which a different pp
        # cannot consume — fail with the story rather than a shape error.
        # This is also the gate behind elastic pp resize: the guard above
        # admits a pp mismatch (checkpoint.elastic), and this check is
        # what restricts it to even splits that share the slot layout.
        src = meta.get("config", {})
        src_m, src_d = src.get("model", {}), src.get("distributed", {})
        if src_m.get("num_hidden_layers") and src_d.get("pp_size"):
            from picotron_tpu.models.llama import pp_layer_placement

            src_padded, src_slots = pp_layer_placement(
                src_m["num_hidden_layers"], src_d["pp_size"])
            dst_padded, dst_slots = pp_layer_placement(
                self.cfg.model.num_hidden_layers,
                self.cfg.distributed.pp_size)
            # Padded sizes alone can collide across pp_sizes (10 layers on
            # pp=3 and pp=4 both pad to 12) while placing real layers in
            # different slots — compare the slot layout itself.
            if src_padded != dst_padded or not np.array_equal(src_slots,
                                                              dst_slots):
                raise ValueError(
                    f"checkpoint was saved with an uneven PP layer split "
                    f"(padded stack {src_padded}, pp={src_d['pp_size']}) "
                    f"whose layer slots differ from this run's (padded "
                    f"stack {dst_padded}, pp="
                    f"{self.cfg.distributed.pp_size}); resume with the "
                    f"same pp_size or use a layer count divisible by both"
                )
        template = {
            "params": state_template.params,
            "opt_state": state_template.opt_state,
            "step": state_template.step,
        }
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if hasattr(x, "sharding") else x,
            template,
        )
        restored = retry_call(
            self._ckptr.restore, os.path.join(path, "state"), abstract,
            policy=self._retry,
            describe=f"checkpoint restore (step {step})")
        # Force every leaf onto the template's sharding: Orbax can hand back
        # differently-placed arrays (e.g. scalar opt-state counters on a
        # single device), which would fail jit's consistent-devices check on
        # the first step after resume.
        restored = jax.tree.map(
            lambda r, t: jax.device_put(r, t.sharding)
            if hasattr(t, "sharding") else r,
            restored, template)
        state = TrainState(params=restored["params"],
                           opt_state=restored["opt_state"],
                           step=restored["step"])
        return state, meta


def restore_params_only(cfg: Config, ckpt_dir: str,
                        step: Optional[int] = None, dtype=None):
    """Restore ONLY the canonical [L]-stacked params from a training
    checkpoint onto the first local device — the inference/export path
    (tools/generate.py, tools/export_hf.py). Skips the Adam moments
    entirely (a partial PyTree restore: ~1/3 the IO and host memory of a
    full-state restore at 7B scale) and unpads the PP layer stack.

    `dtype` overrides the restored leaf dtype (Orbax casts DURING restore,
    so e.g. dtype=jnp.bfloat16 loads a 7B checkpoint in 13.5 GB without
    the 28 GB fp32 tree ever materializing — the single-chip decode path).
    For an optimizer_offload checkpoint the "params" entry is only the
    bf16 compute copy, so this restores the fp32 MASTER from
    opt_state.master instead — tools/export_hf.py must export full
    master precision, not bf16-rounded weights (code review r4)."""
    import orbax.checkpoint as ocp

    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.models.llama import unpad_layers

    menv = MeshEnv.create(dp=1, devices=jax.devices()[:1])
    mgr = CheckpointManager(cfg, menv, directory=ckpt_dir)
    if step is None:
        # Same trust rule as the training restore path: newest durable
        # AND manifest-verified — export/decode must not read a flipped
        # bit any more than resume may.
        step = mgr.latest_valid_step()
        if step is None:
            raise FileNotFoundError(f"no valid checkpoints under {ckpt_dir}")
    from picotron_tpu.parallel.api import abstract_master

    nl, pp = cfg.model.num_hidden_layers, cfg.distributed.pp_size
    abstract = abstract_master(cfg)
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    restore_args = jax.tree.map(
        lambda x: ocp.ArrayRestoreArgs(dtype=dtype or x.dtype,
                                       sharding=sharding),
        abstract)
    if cfg.training.optimizer_offload:
        item = {"opt_state": {"master": abstract}}
        rargs = {"opt_state": {"master": restore_args}}
        pick = lambda r: r["opt_state"]["master"]  # noqa: E731
    else:
        item = {"params": abstract}
        rargs = {"params": restore_args}
        pick = lambda r: r["params"]  # noqa: E731
    # partial_restore (skip tree branches absent from `item`) only exists
    # on newer orbax; older releases spell the same thing as an empty
    # `transforms` dict (the transforms machinery restores exactly the
    # item's keys, each defaulting to its same-path checkpoint value)
    import inspect

    if "partial_restore" in inspect.signature(
            ocp.args.PyTreeRestore).parameters:
        restore_kwargs = {"partial_restore": True}
    else:
        restore_kwargs = {"transforms": {}}
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        restored = ckptr.restore(
            os.path.join(mgr.directory, f"step_{step:08d}", "state"),
            args=ocp.args.PyTreeRestore(
                item=item, restore_args=rargs, **restore_kwargs))
    return unpad_layers(pick(restored), nl, pp), step


# ---------------------------------------------------------------------------
# HF safetensors import (ref: checkpoint.py:50-230)
# ---------------------------------------------------------------------------

# safetensors name -> (our key path, needs_transpose). Torch Linear stores
# [out_features, in_features]; our matmuls are x @ w with [in, out]
# (the reference's regex rename map is checkpoint.py:213-230).
_ATTN_MAP = {
    "self_attn.q_proj.weight": ("q", True),
    "self_attn.k_proj.weight": ("k", True),
    "self_attn.v_proj.weight": ("v", True),
    "self_attn.o_proj.weight": ("o", True),
    "input_layernorm.weight": ("input_norm", False),
    "post_attention_layernorm.weight": ("post_norm", False),
}

_LAYER_MAP = {
    **_ATTN_MAP,
    "mlp.gate_proj.weight": ("gate", True),
    "mlp.up_proj.weight": ("up", True),
    "mlp.down_proj.weight": ("down", True),
}

# Qwen2-style qkv bias (HF stores [out_features]; no transpose).
_BIAS_MAP = {
    "self_attn.q_proj.bias": ("b_q", False),
    "self_attn.k_proj.bias": ("b_k", False),
    "self_attn.v_proj.bias": ("b_v", False),
}

# Mixtral MoE expert naming: block_sparse_moe.experts.<j>.{w1,w2,w3} hold
# gate/down/up projections, block_sparse_moe.gate is the router.
_MOE_EXPERT_MAP = {"w1": "w_gate", "w2": "w_down", "w3": "w_up"}


def _read_safetensors_dir(path: str) -> dict[str, np.ndarray]:
    """Read all tensors from a single-file or index-sharded HF safetensors
    checkpoint directory (ref: checkpoint.py:62-86 handles both layouts)."""
    from safetensors.numpy import load_file

    index_path = os.path.join(path, "model.safetensors.index.json")
    single_path = os.path.join(path, "model.safetensors")
    tensors: dict[str, np.ndarray] = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        for shard in sorted(set(index["weight_map"].values())):
            tensors.update(load_file(os.path.join(path, shard)))
    elif os.path.exists(single_path):
        tensors.update(load_file(single_path))
    else:
        raise FileNotFoundError(
            f"no model.safetensors[.index.json] under {path}")
    return tensors


def load_hf_safetensors(path: str, cfg: ModelConfig,
                        dtype=jnp.float32) -> dict[str, Any]:
    """Materialize an HF Llama-family safetensors checkpoint as our stacked
    param pytree (fp32 master by default)."""
    raw = _read_safetensors_dir(path)
    nl = cfg.num_hidden_layers
    file_layers = {int(mm.group(1)) for k in raw
                   if (mm := re.match(r"model\.layers\.(\d+)\.", k))}
    if file_layers and len(file_layers) != nl:
        # A config expecting FEWER layers than the file holds would
        # otherwise silently truncate the model (more layers fails later
        # with a missing-tensor KeyError, but make both cases explicit).
        raise ValueError(
            f"checkpoint at {path} has {len(file_layers)} layers but the "
            f"config expects num_hidden_layers={nl}; pass a matching model "
            f"config")

    def get(name: str) -> np.ndarray:
        if name not in raw:
            raise KeyError(
                f"tensor {name!r} missing from checkpoint (found "
                f"{len(raw)} tensors)")
        return raw[name].astype(np.float32)

    lmap = dict(_ATTN_MAP if cfg.num_experts else _LAYER_MAP)
    if cfg.attention_bias:
        lmap.update(_BIAS_MAP)
    layers: dict[str, list[np.ndarray]] = {k: [] for k, _ in lmap.values()}
    if cfg.num_experts:
        layers.update({k: [] for k in ("router", "w_gate", "w_up", "w_down")})
    for i in range(nl):
        prefix = f"model.layers.{i}."
        for suffix, (key, transpose) in lmap.items():
            t = get(prefix + suffix)
            layers[key].append(t.T if transpose else t)
        if cfg.num_experts:
            moe = prefix + "block_sparse_moe."
            layers["router"].append(get(moe + "gate.weight").T)  # [H, E]
            for short, key in _MOE_EXPERT_MAP.items():
                bank = [get(f"{moe}experts.{j}.{short}.weight").T
                        for j in range(cfg.num_experts)]
                layers[key].append(np.stack(bank))  # [E, in, out]

    embedding = get("model.embed_tokens.weight")  # [vocab, hidden]
    params = {
        "embedding": jnp.asarray(embedding, dtype),
        "layers": {k: jnp.asarray(np.stack(v), dtype)
                   for k, v in layers.items()},
        "final_norm": jnp.asarray(get("model.norm.weight"), dtype),
    }
    if cfg.tie_word_embeddings:
        # Qwen2-style tying: no lm_head parameter; head_weight() reads the
        # embedding. (A stray lm_head.weight in the file is ignored — HF
        # does the same for tied configs.)
        return params
    if "lm_head.weight" in raw:
        lm_head = get("lm_head.weight").T  # [hidden, vocab]
    else:
        # Tied-head checkpoint loaded as an UNTIED model: untie by copying
        # (ref: checkpoint.py:88-91 force-creates lm_head the same way).
        lm_head = embedding.T.copy()
    params["lm_head"] = jnp.asarray(lm_head, dtype)
    return params


def save_hf_safetensors(params: dict[str, Any], path: str) -> None:
    """Export our param pytree to HF Llama safetensors naming (round-trip of
    `load_hf_safetensors`; the reference has no export path)."""
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    out: dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = np.asarray(params["embedding"])
    out["model.norm.weight"] = np.asarray(params["final_norm"])
    if "lm_head" in params:  # tied models carry no separate head
        out["lm_head.weight"] = np.asarray(params["lm_head"]).T
    layers = params["layers"]
    nl = next(iter(layers.values())).shape[0]
    is_moe = "router" in layers
    lmap = dict(_ATTN_MAP if is_moe else _LAYER_MAP)
    if "b_q" in layers:
        lmap.update(_BIAS_MAP)
    for i in range(nl):
        prefix = f"model.layers.{i}."
        for suffix, (key, transpose) in lmap.items():
            t = np.asarray(layers[key][i])
            out[prefix + suffix] = t.T if transpose else t
        if is_moe:
            moe = prefix + "block_sparse_moe."
            out[moe + "gate.weight"] = np.asarray(layers["router"][i]).T
            for short, key in _MOE_EXPERT_MAP.items():
                bank = np.asarray(layers[key][i])  # [E, in, out]
                for j in range(bank.shape[0]):
                    out[f"{moe}experts.{j}.{short}.weight"] = bank[j].T
    out = {k: np.ascontiguousarray(v) for k, v in out.items()}
    save_file(out, os.path.join(path, "model.safetensors"))
