#!/usr/bin/env python
"""Host data-pipeline microbench: does the loader outpace the chips?

The device side consumes ~7k tokens/s/chip at the full-depth headline (up
to ~25k on the depth-reduced config), i.e. ~56-200k tokens/s for an
8-chip host. This benchmarks the HOST side of the pipeline on a real
on-disk HF-datasets arrow table (built locally — zero egress):

1. epoch-view construction cost (`ds.shuffle(seed).flatten_indices()` as
   the alternative under test),
2. steady-state `DatasetSource.get_rows` + numpy assembly throughput:
   shuffled-lazy (production) vs shuffled+flatten_indices vs unshuffled —
   the numbers behind DatasetSource's choice to keep the lazy shuffle,
3. `tokenize_and_chunk`'s map+pack throughput with a stand-in tokenizer
   (zero egress: no real BPE vocab on disk; the stand-in hashes whitespace
   words — the point is the pipeline around the tokenizer, which is
   one-time preprocessing anyway, not the tokenizer itself).

Usage: python tools/data_bench.py [--blocks 20000] [--seq 2048]
Prints one human-readable line per measurement plus a JSON summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_chunked_dataset(path: str, blocks: int, seq: int):
    import datasets

    rng = np.random.default_rng(0)
    rows = rng.integers(0, 50257, (blocks, seq + 1), dtype=np.int32)
    ds = datasets.Dataset.from_dict({"input_ids": rows.tolist()})
    ds.save_to_disk(path)
    return datasets.load_from_disk(path)  # memory-mapped arrow, like prod


def bench_get_rows(source, blocks: int, seq: int, label: str,
                   batch_rows: int = 64) -> float:
    from picotron_tpu.data import DatasetSource  # noqa: F401 (doc link)

    t0 = time.perf_counter()
    total = 0
    start = 0
    while start + batch_rows <= blocks:
        rows = source.get_rows(0, start, batch_rows)
        total += rows.size
        start += batch_rows
    dt = time.perf_counter() - t0
    rate = total / dt
    print(f"{label}: {rate/1e6:.1f}M tokens/s "
          f"({total/1e6:.1f}M tokens in {dt:.2f}s)")
    return rate


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", type=int, default=20000)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--keep", action="store_true",
                    help="keep the generated dataset dir")
    args = ap.parse_args()

    from picotron_tpu.data import DatasetSource, tokenize_and_chunk

    tmp = tempfile.mkdtemp(prefix="data_bench_")
    out = {}
    try:
        ds = build_chunked_dataset(os.path.join(tmp, "chunked"),
                                   args.blocks, args.seq)

        # 1. once-per-epoch view construction
        t0 = time.perf_counter()
        flat = ds.shuffle(seed=1).flatten_indices()
        out["epoch_view_s"] = time.perf_counter() - t0
        print(f"epoch view (shuffle+flatten_indices, {args.blocks} blocks): "
              f"{out['epoch_view_s']:.2f}s")
        del flat

        # 2. steady-state read throughput: the production path (lazy
        # shuffle) vs the flatten_indices alternative vs unshuffled.
        # flatten_indices was VERDICT r3's suggested fix for the lazy
        # indices mapping's theoretical random-read cliff; measurement
        # showed the OPPOSITE at cache-resident scale (see DatasetSource).
        out["read_lazy_tok_s"] = bench_get_rows(
            DatasetSource(ds, shuffle_seed=1), args.blocks, args.seq,
            "get_rows shuffled lazy (production)")

        class FlatSource(DatasetSource):
            def _epoch_view(self, epoch):
                if self._epoch_cache and self._epoch_cache[0] == epoch:
                    return self._epoch_cache[1]
                v = self.dataset.shuffle(
                    seed=self.shuffle_seed + epoch).flatten_indices()
                self._epoch_cache = (epoch, v)
                return v

        out["read_flat_tok_s"] = bench_get_rows(
            FlatSource(ds, shuffle_seed=1), args.blocks, args.seq,
            "get_rows shuffled+flatten_indices")
        out["read_seq_tok_s"] = bench_get_rows(
            DatasetSource(ds, shuffle_seed=None), args.blocks, args.seq,
            "get_rows unshuffled")

        # 3. preprocessing throughput with a stand-in tokenizer
        import datasets as hfds

        words = [f"w{i:04d}" for i in range(1000)]
        rng = np.random.default_rng(2)
        texts = [" ".join(words[j] for j in rng.integers(0, 1000, 256))
                 for _ in range(2000)]
        raw = hfds.Dataset.from_dict({"text": texts})

        class StandinTokenizer:
            def __call__(self, texts):
                return {"input_ids": [
                    [hash(w) % 50000 for w in t.split()] for t in texts]}

        t0 = time.perf_counter()
        chunked = tokenize_and_chunk(raw, StandinTokenizer(), args.seq)
        dt = time.perf_counter() - t0
        toks = sum(len(r) for r in chunked["input_ids"])
        out["preproc_tok_s"] = toks / dt
        print(f"tokenize_and_chunk (stand-in tokenizer): "
              f"{out['preproc_tok_s']/1e6:.2f}M tokens/s")
    finally:
        if not args.keep:
            shutil.rmtree(tmp, ignore_errors=True)

    # device-side comparison points (PERF.md): full-depth headline ~7k
    # tok/s/chip, depth-reduced peak ~25k tok/s/chip, 8-chip host ~200k
    out["vs_8chip_host_margin"] = round(
        out["read_lazy_tok_s"] / (25_000 * 8), 1)
    print(json.dumps({k: (round(v, 1) if isinstance(v, float) else v)
                      for k, v in out.items()}))


if __name__ == "__main__":
    main()
