#!/usr/bin/env python
"""Experiment config generator — parity with the reference's create_config.py.

Writes `<out_dir>/<exp_name>/config.json` in the (reference-compatible) JSON
schema from CLI flags (ref: create_config.py:78-106), prints the global-batch
math (ref: create_config.py:71-73). Model hyperparameters resolve from the
built-in preset registry instead of a network AutoConfig fetch
(ref: create_config.py:51-55) — TPU pods frequently have zero egress.

Example:
  python tools/create_config.py --exp-name smol-dp4tp2 --out-dir runs \\
      --model SmolLM-1.7B --dp 4 --tp 2 --pp 2 --seq-len 2048 \\
      --mbs 4 --grad-acc 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from picotron_tpu.config import config_from_dict, resolve_preset  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="picotron-tpu config generator")
    p.add_argument("--exp-name", required=True)
    p.add_argument("--out-dir", default="runs")
    # parallel layout (ref: create_config.py --tp/--cp/--dp/--pp)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--cp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1,
                   help="expert parallelism (MoE models only)")
    p.add_argument("--pp-engine", default="1f1b", choices=["1f1b", "afab"])
    p.add_argument("--cp-flavor", default=None,
                   choices=["ring", "ulysses", "mesh"],
                   help="context-parallel attention schedule for cp > 1 "
                        "(default: ring, or whatever --attn-impl names); "
                        "'mesh' factors cp into a 2D submesh — see "
                        "--cp-mesh")
    p.add_argument("--cp-mesh", default=None, metavar="XxY",
                   help="mesh-flavor factorization cp = cp_x * cp_y, e.g. "
                        "'2x4' (default: most-square feasible split; "
                        "cp_y must divide the tp-local head counts)")
    p.add_argument("--sequence-parallel", action="store_true",
                   help="Megatron-SP over the tp axis (seq-sharded "
                        "residual stream between blocks)")
    p.add_argument("--tp-strategy", default=None,
                   choices=["megatron", "row", "2d", "adaptive"],
                   help="per-layer TP partitioning (default: megatron "
                        "column-first; 'adaptive' asks the cost model per "
                        "layer class; per-class specs like "
                        "'qkv=2d,up=col' go straight in config.json)")
    p.add_argument("--tp-mesh", default=None, metavar="XxY",
                   help="2D-strategy factorization tp = tp_x * tp_y, e.g. "
                        "'2x2' (default: most-square feasible split; tp_x "
                        "must divide the head counts)")
    p.add_argument("--tp-sync", default=None,
                   choices=["sync", "deferred"],
                   help="TP activation sync schedule: 'deferred' replaces "
                        "the row-parallel exit all_reduce with a "
                        "reduce_scatter whose gather half is hoisted into "
                        "the next block (megatron strategy, pp=1, dense)")
    p.add_argument("--zero1", action="store_true",
                   help="ZeRO-1: shard Adam moments over dp")
    p.add_argument("--slices", type=int, default=None,
                   help="multislice topology: the pod spans N TPU slices "
                        "joined by DCN; train.py's slicecheck preflight "
                        "then audits every collective against the cut "
                        "(analysis/boundary.py)")
    p.add_argument("--dcn-axes", default=None, metavar="AXES",
                   help="comma-separated mesh axes allowed to cross the "
                        "DCN boundary with --slices > 1 (subset of "
                        "dp,pp; default dp,pp — pick with "
                        "tools/layout_planner.py --slices)")
    # model
    p.add_argument("--model", default="HuggingFaceTB/SmolLM-1.7B")
    p.add_argument("--from-hf-config", default=None, metavar="CONFIG_JSON",
                   help="resolve model hyperparameters from a local HF "
                        "config.json instead of the preset registry — the "
                        "offline AutoConfig: any Llama/Qwen2/Mixtral-"
                        "family model trains without hand-typing its "
                        "architecture (--model then only names the run)")
    p.add_argument("--num-hidden-layers", type=int, default=None,
                   help="override the preset's layer count "
                        "(ref: create_config.py:56-59)")
    p.add_argument("--num-attention-heads", type=int, default=None)
    p.add_argument("--num-key-value-heads", type=int, default=None)
    p.add_argument("--attn-impl", default="auto",
                   choices=["auto", "flash", "reference", "ring",
                            "ulysses", "mesh"])
    p.add_argument("--dtype", default="bfloat16", choices=["bfloat16", "float32"])
    # training (ref: create_config.py --mbs/--grad-acc/--seq-len)
    p.add_argument("--mbs", type=int, default=1)
    p.add_argument("--grad-acc", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--learning-rate", type=float, default=3e-4)
    p.add_argument("--lr-schedule", default="constant",
                   choices=["constant", "cosine", "linear"])
    p.add_argument("--lr-warmup-steps", type=int, default=0)
    p.add_argument("--total-train-steps", type=int, default=200)
    p.add_argument("--eval-frequency", type=int, default=0,
                   help="run a val-loss pass every N steps (0 = off); HF "
                        "datasets need --eval-split")
    p.add_argument("--eval-steps", type=int, default=8)
    p.add_argument("--eval-split", default=None)
    p.add_argument("--no-remat", action="store_true")
    p.add_argument("--remat-policy", default="dots",
                   choices=["full", "dots", "dots_attn", "dots_lean", "dots_norms",
                            "dots_offload"])
    p.add_argument("--adam-moments-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="bf16 halves optimizer-state memory (update math "
                        "stays fp32) — usually required to fit >1B models "
                        "per 16G chip; check with tools/memcheck.py")
    p.add_argument("--optimizer-offload", action="store_true",
                   help="fp32 master + Adam moments in pinned HOST memory "
                        "(the full-depth-on-one-chip lever; pair with "
                        "--grad-acc >= 16 to amortize the PCIe round "
                        "trip; requires bf16 model dtype)")
    p.add_argument("--grad-engine", default="auto",
                   choices=["auto", "ad", "fused"],
                   help="'fused' accumulates per-layer dW in-scan (no "
                        "per-microbatch grad tree; any pp=1 layout incl. "
                        "tp/SP/cp ring|ulysses/MoE/ep, with "
                        "remat_policy=dots_attn — see the README "
                        "eligibility matrix); 'auto' picks it whenever "
                        "supported")
    # dataset
    p.add_argument("--dataset", default="synthetic")
    p.add_argument("--subset", default=None)
    p.add_argument("--split", default="train")
    p.add_argument("--tokenizer", default=None)
    # serving (picotron_tpu/serve: continuous batching + paged KV cache)
    p.add_argument("--serve-slots", type=int, default=None,
                   help="serving decode batch width (writes the `serve` "
                        "config block; picotron_tpu/serve)")
    p.add_argument("--serve-block-size", type=int, default=None,
                   help="tokens per paged-KV-cache block")
    p.add_argument("--serve-num-blocks", type=int, default=None,
                   help="physical blocks in the shared KV pool (0 = "
                        "worst-case auto; set lower to oversubscribe — "
                        "the scheduler preempts youngest-first)")
    p.add_argument("--serve-prefill-chunk", type=int, default=None,
                   help="prompt tokens prefilled per engine iteration")
    p.add_argument("--serve-max-len", type=int, default=None,
                   help="per-sequence serving capacity (0 = the model's "
                        "max_position_embeddings)")
    p.add_argument("--serve-decode-interval", type=int, default=None,
                   help="decode steps scanned per dispatch (amortizes "
                        "host overhead; retirement latency quantizes "
                        "to it)")
    p.add_argument("--serve-disagg", action="store_true",
                   help="disaggregated serving: prefill and decode as "
                        "separately placed pools with paged-KV block "
                        "handoff (picotron_tpu/serve/disagg)")
    p.add_argument("--serve-prefill-slots", type=int, default=None,
                   help="prefill-pool slot count (0 = decode_slots)")
    p.add_argument("--serve-prefill-num-blocks", type=int, default=None,
                   help="prefill-pool KV blocks (0 = worst-case auto)")
    p.add_argument("--serve-prefill-device", type=int, default=None,
                   help="device index for the prefill pool (-1 = auto: "
                        "device 1 when available)")
    p.add_argument("--serve-decode-device", type=int, default=None,
                   help="device index for the decode pool (-1 = auto: "
                        "device 0)")
    p.add_argument("--serve-speculator", default=None,
                   choices=["off", "ngram"],
                   help="speculative decode drafter ('ngram' = "
                        "self-drafting n-gram; token-identical to "
                        "non-speculative decode)")
    p.add_argument("--serve-draft-len", type=int, default=None,
                   help="draft tokens proposed per decode step when the "
                        "speculator is on")
    # checkpoint / logging
    p.add_argument("--save-frequency", type=int, default=0)
    p.add_argument("--auto-resume", action="store_true",
                   help="resume from the newest durable checkpoint in the "
                        "save dir when the job (re)starts — pairs with "
                        "submit_jobs' failure resubmission so preempted "
                        "jobs continue instead of restarting")
    p.add_argument("--download-model", action="store_true",
                   help="snapshot the model's HF safetensors (tools/"
                        "download_model.py; ref: create_config.py:134) and "
                        "set checkpoint.init_from_hf so training starts "
                        "from the pretrained weights")
    p.add_argument("--use-wandb", action="store_true")
    p.add_argument("--use-cpu", action="store_true",
                   help="run the layout on simulated host devices (the "
                        "reference's --use_cpu, ref: create_config.py:64-66)")
    return p


def create_single_config(args) -> str:
    model_overrides = {
        k: v for k, v in dict(
            num_hidden_layers=args.num_hidden_layers,
            num_attention_heads=args.num_attention_heads,
            num_key_value_heads=args.num_key_value_heads,
        ).items() if v is not None
    }
    if getattr(args, "from_hf_config", None):
        # offline long-tail resolution: any Llama-family model outside the
        # preset registry, from its local HF config.json (the reference
        # fetches this over the network via AutoConfig,
        # ref: create_config.py:51-55; zero-egress pods can't)
        from picotron_tpu.config import model_config_from_hf_json

        preset = model_config_from_hf_json(args.from_hf_config)
    else:
        preset = resolve_preset(args.model)
    seq_len = args.seq_len
    if seq_len > preset["max_position_embeddings"]:
        preset["max_position_embeddings"] = seq_len

    raw = {
        "distributed": {
            "tp_size": args.tp, "cp_size": args.cp, "pp_size": args.pp,
            "dp_size": args.dp, "ep_size": args.ep,
            "pp_engine": args.pp_engine,
            "sequence_parallel": args.sequence_parallel,
            "zero1": args.zero1,
            "use_cpu": args.use_cpu,
            **({"cp_flavor": args.cp_flavor} if args.cp_flavor else {}),
            **({"cp_mesh": args.cp_mesh} if args.cp_mesh else {}),
            **({"tp_strategy": args.tp_strategy} if args.tp_strategy else {}),
            **({"tp_mesh": args.tp_mesh} if args.tp_mesh else {}),
            **({"tp_sync": args.tp_sync} if args.tp_sync else {}),
            **({"slices": args.slices} if args.slices else {}),
            **({"dcn_axes": args.dcn_axes} if args.dcn_axes else {}),
        },
        "model": {
            "name": args.model, **preset, **model_overrides,
            "dtype": args.dtype, "attn_impl": args.attn_impl,
        },
        "training": {
            "seq_length": seq_len,
            "micro_batch_size": args.mbs,
            "gradient_accumulation_steps": args.grad_acc,
            "learning_rate": args.learning_rate,
            "lr_schedule": args.lr_schedule,
            "lr_warmup_steps": args.lr_warmup_steps,
            "total_train_steps": args.total_train_steps,
            "eval_frequency": args.eval_frequency,
            "eval_steps": args.eval_steps,
            "adam_moments_dtype": args.adam_moments_dtype,
            "optimizer_offload": args.optimizer_offload,
            "remat": not args.no_remat,
            "remat_policy": args.remat_policy,
            "grad_engine": args.grad_engine,
        },
        "dataset": {
            "name": args.dataset, "subset_name": args.subset,
            "split": args.split, "eval_split": args.eval_split,
            "tokenizer_name": args.tokenizer,
        },
        # save_dir pinned INSIDE the run directory: the dataclass default
        # ("ckpt") is relative, and submit_jobs launches trainers with
        # cwd=REPO_ROOT — checkpoints and telemetry.jsonl from every run
        # would otherwise pile into one shared repo-root ckpt/ (and
        # extract_metrics could never pair a run with its telemetry).
        "checkpoint": {"save_frequency": args.save_frequency,
                       "auto_resume": args.auto_resume,
                       "save_dir": os.path.abspath(os.path.join(
                           args.out_dir, args.exp_name, "ckpt"))},
        "logging": {"use_wandb": args.use_wandb, "run_name": args.exp_name},
    }
    serve = {k: v for k, v in dict(
        decode_slots=args.serve_slots,
        block_size=args.serve_block_size,
        num_blocks=args.serve_num_blocks,
        prefill_chunk=args.serve_prefill_chunk,
        max_model_len=args.serve_max_len,
        decode_interval=args.serve_decode_interval,
        disagg=args.serve_disagg or None,
        prefill_slots=args.serve_prefill_slots,
        prefill_num_blocks=args.serve_prefill_num_blocks,
        prefill_device=args.serve_prefill_device,
        decode_device=args.serve_decode_device,
        speculator=args.serve_speculator,
        draft_len=args.serve_draft_len,
    ).items() if v is not None}
    if serve:
        raw["serve"] = serve
    if getattr(args, "download_model", False):
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from download_model import download

        from picotron_tpu.config import resolve_hf_name

        raw["checkpoint"]["init_from_hf"] = download(
            resolve_hf_name(args.model))
    cfg = config_from_dict(raw)  # validates

    exp_dir = os.path.join(args.out_dir, args.exp_name)
    os.makedirs(exp_dir, exist_ok=True)
    path = os.path.join(exp_dir, "config.json")
    with open(path, "w") as f:
        json.dump(raw, f, indent=2)

    # ref: create_config.py:71-73 prints the same math
    print(f"config -> {path}")
    print(f"  mesh: dp={args.dp} pp={args.pp} ep={args.ep} cp={args.cp} tp={args.tp} "
          f"({cfg.distributed.world_size} chips)")
    dataxes = (f"x dp {args.dp} x ep {args.ep}" if args.ep > 1
               else f"x dp {args.dp}")
    print(f"  global_batch_size = mbs {args.mbs} x grad_acc {args.grad_acc} "
          f"{dataxes} = {cfg.global_batch_size} "
          f"({cfg.tokens_per_step} tokens/step)")
    return path


if __name__ == "__main__":
    create_single_config(build_parser().parse_args())
