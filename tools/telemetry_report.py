#!/usr/bin/env python
"""Post-hoc run triage from a telemetry JSONL stream.

Summarizes a `telemetry.jsonl` (picotron_tpu/telemetry; written next to
the checkpoints by the trainer) into the questions a run post-mortem
actually asks: how many distinct steps trained, where did the wall-clock
go (phase breakdown with p50/p95), what fraction was goodput, what did
the badput consist of (compile / checkpoint I/O / restore + replayed
steps / preemption drain / retry backoff / data stall), and which events
(chaos, guard trips, rollbacks, preemptions, retries, recompiles) fired.

The stream is append-mode across supervised restarts, so one file covers
a whole preempt/kill/resume saga; steps whose compute phase appears more
than once (an in-process rollback already reclassified in the ledger, a
cross-restart replay only visible here) are booked as `replay` badput.

Usage:

  python tools/telemetry_report.py RUN_DIR_OR_JSONL            # text
  python tools/telemetry_report.py run/ --markdown             # PERF.md-style
  python tools/telemetry_report.py run/telemetry.jsonl --json  # machine
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from picotron_tpu.telemetry.goodput import (  # noqa: E402
    GOODPUT_CATEGORIES,
)
from picotron_tpu.telemetry.sinks import jsonl_segments  # noqa: E402


def resolve_path(path: str) -> str:
    """Accept the JSONL itself or a run directory containing one."""
    if os.path.isdir(path):
        cand = os.path.join(path, "telemetry.jsonl")
        if not os.path.exists(cand):
            raise FileNotFoundError(f"no telemetry.jsonl under {path}")
        return cand
    return path


def load_events(path: str) -> list[dict]:
    """Read the stream, including a rotated `.1` segment first when
    logging.telemetry_max_mb rotation left one — event ORDER across
    segments is what keeps cross-restart replay counting correct."""
    events = []
    for seg in jsonl_segments(path) or [path]:
        with open(seg) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn final line of a killed run is expected
                if isinstance(ev, dict):
                    events.append(ev)
    return events


def _pctile(xs: list[float], q: float) -> float:
    """Nearest-rank percentile (same definition as registry.Histogram)."""
    xs = sorted(xs)
    rank = max(1, -(-int(q * len(xs)) // 100)) if q > 0 else 1
    return xs[min(rank, len(xs)) - 1]


def summarize(events: list[dict]) -> dict:
    """Aggregate a stream into {steps, phases, categories, goodput_pct,
    events, training, wall}. Summing the (category, secs) pairs off the
    events reproduces the in-process ledger by construction (the phase
    events carry their resolved category; compile time rides separate
    category="compile" events) — plus the cross-restart replay
    reclassification only the whole stream can see."""
    categories: dict[str, float] = {}
    phases: dict[str, list[float]] = {}
    counts: dict[str, int] = {}
    steps_seen: set[int] = set()
    replayed = 0
    step_rows: list[dict] = []
    eval_rows: list[dict] = []
    serve_reqs: list[dict] = []
    serve_summary: dict | None = None
    run_summary: dict | None = None
    sentinel_alerts: list[dict] = []
    ts = [e["ts"] for e in events if isinstance(e.get("ts"), (int, float))]

    for e in events:
        kind = e.get("kind")
        counts[kind] = counts.get(kind, 0) + 1
        cat, secs = e.get("category"), e.get("secs")
        if kind == "phase":
            phases.setdefault(e.get("phase", "?"), []).append(secs or 0.0)
            step = e.get("step")
            if e.get("phase") == "step" and step is not None:
                if cat in ("compute", "replay") and step in steps_seen:
                    # a step number training twice = lost ground being
                    # re-bought, whichever process it happened in
                    cat = "replay"
                    replayed += 1
                steps_seen.add(step)
        if cat is not None and isinstance(secs, (int, float)):
            categories[cat] = categories.get(cat, 0.0) + secs
        elif kind == "step":
            step_rows.append(e)
        elif kind == "eval":
            eval_rows.append(e)
        elif kind == "bench_step" and isinstance(secs, (int, float)):
            # bench.py --telemetry streams: per-step samples, no phases
            phases.setdefault("bench_step", []).append(secs)
        elif kind == "serve_request":
            serve_reqs.append(e)
        elif kind == "serve_summary":
            serve_summary = e  # last wins (one per engine run)
        elif kind == "run_summary":
            run_summary = e  # last wins (one per process lifetime)
        elif kind == "sentinel_alert":
            sentinel_alerts.append(e)

    accounted = sum(categories.values())
    goodput = sum(categories.get(c, 0.0) for c in GOODPUT_CATEGORIES)
    wall = (max(ts) - min(ts)) if len(ts) >= 2 else 0.0
    out = {
        "steps": {
            "count": len(steps_seen),
            "max": max(steps_seen) if steps_seen else 0,
            "replayed": replayed,
        },
        "phases": {
            name: {
                "count": len(xs),
                "total_s": round(sum(xs), 4),
                "p50_ms": round(_pctile(xs, 50) * 1e3, 2),
                "p95_ms": round(_pctile(xs, 95) * 1e3, 2),
            }
            for name, xs in sorted(phases.items())
        },
        "categories": {k: round(v, 4)
                       for k, v in sorted(categories.items())},
        "goodput_pct": (round(100.0 * goodput / accounted, 2)
                        if accounted > 0 else None),
        "badput_s": round(accounted - goodput, 4),
        "accounted_s": round(accounted, 4),
        "wall_s": round(wall, 4),
        # Time the stream never saw end-to-end: pre-loop setup, the jit
        # warm-up outside phases, and phases killed mid-flight (crash,
        # watchdog os._exit).
        "unaccounted_s": round(max(wall - accounted, 0.0), 4),
        "events": dict(sorted(counts.items())),
    }
    if step_rows:
        losses = [r["loss"] for r in step_rows if "loss" in r]
        tps = [r["tokens_per_sec"] for r in step_rows
               if "tokens_per_sec" in r]
        out["training"] = {
            "records": len(step_rows),
            "final_step": step_rows[-1].get("step"),
            "final_loss": losses[-1] if losses else None,
            "mean_tokens_per_sec": (round(sum(tps) / len(tps), 1)
                                    if tps else None),
            "final_trained_tokens": step_rows[-1].get("trained_tokens"),
        }
    if eval_rows:
        out["training"] = out.get("training", {})
        out["training"]["final_val_loss"] = eval_rows[-1].get("val_loss")
    if serve_reqs or serve_summary:
        out["serving"] = serving_view(serve_reqs, serve_summary, counts)
    # Elastic-resize row: the resize category already sums into the table
    # above (the phase event carries its resolved category); this pairs
    # the seconds with the elastic_resize events so a shrink/grow saga is
    # one row, not a grep.
    n_resize = counts.get("elastic_resize", 0)
    resize_s = categories.get("resize", 0.0)
    if n_resize or resize_s:
        out["resize"] = {"events": n_resize,
                         "seconds": round(resize_s, 4)}
    pp = pipeline_view(categories, run_summary)
    if pp:
        out["pipeline"] = pp
    if sentinel_alerts:
        # Drift-sentinel row (telemetry/flightdeck/sentinel.py): one
        # alert per drifting run — the worst measured/baseline ratio
        # names the quantity to chase.
        worst = max(sentinel_alerts,
                    key=lambda a: a.get("ratio") or 0.0)
        out["sentinel"] = {
            "alerts": len(sentinel_alerts),
            "quantity": worst.get("quantity"),
            "worst_ratio": round(float(worst.get("ratio") or 0.0), 4),
        }
    return out


def pipeline_view(categories: dict[str, float],
                  run_summary: dict | None) -> dict:
    """Pipeline-parallel row: the bubble's share of step wall (the
    pp_bubble category next to the compute/replay it was carved from)
    plus per-stage tick-time percentiles from the run_summary's
    section/pp_stage* histograms (fed by the MPMD executor's sampled
    per-stage timings). Empty dict when the run had no pipeline."""
    view: dict = {}
    bubble = categories.get("pp_bubble", 0.0)
    if bubble > 0.0:
        step_wall = (bubble + categories.get("compute", 0.0)
                     + categories.get("replay", 0.0))
        view["bubble_s"] = round(bubble, 4)
        view["bubble_fraction"] = round(bubble / step_wall, 4) \
            if step_wall > 0 else None
    hists = ((run_summary or {}).get("metrics") or {}).get("histograms",
                                                           {})
    stages = {}
    for name, h in sorted(hists.items()):
        if not name.startswith("section/pp_stage"):
            continue
        stage = name[len("section/"):]
        stages[stage] = {
            "count": h.get("count"),
            "p50_ms": (round(h["p50"] * 1e3, 3)
                       if isinstance(h.get("p50"), (int, float)) else None),
            "p95_ms": (round(h["p95"] * 1e3, 3)
                       if isinstance(h.get("p95"), (int, float)) else None),
        }
    if stages:
        view["stages"] = stages
    return view


def serving_view(reqs: list[dict], summary: dict | None,
                 counts: dict | None = None) -> dict:
    """SLO view of a serving stream: per-request TTFT/queue-wait
    percentiles recomputed from the serve_request events (so the view
    works even on a stream truncated before its serve_summary), plus the
    engine-level aggregates (tok/s, per-token latency, slot occupancy,
    pool utilization) from the serve_summary when present. Fleet runs
    (serve/fleet.py) add shed/redispatch/engine-death counters and
    per-engine rows; on a truncated stream those fall back to counting
    the serve_shed / serve_redispatch events directly."""
    view: dict = {"requests": len(reqs)}
    ttfts = [r["ttft_s"] for r in reqs
             if isinstance(r.get("ttft_s"), (int, float))]
    waits = [r["queue_wait_s"] for r in reqs
             if isinstance(r.get("queue_wait_s"), (int, float))]
    toks = [r["output_tokens"] for r in reqs
            if isinstance(r.get("output_tokens"), (int, float))]
    if ttfts:
        view["ttft_p50_ms"] = round(_pctile(ttfts, 50) * 1e3, 2)
        view["ttft_p95_ms"] = round(_pctile(ttfts, 95) * 1e3, 2)
    if waits:
        view["queue_wait_p50_ms"] = round(_pctile(waits, 50) * 1e3, 2)
        view["queue_wait_p95_ms"] = round(_pctile(waits, 95) * 1e3, 2)
    if toks:
        view["output_tokens"] = int(sum(toks))
    if summary:
        for src, dst, scale in (
                ("tokens_per_sec", "tokens_per_sec", 1),
                ("token_latency_p50_s", "token_latency_p50_ms", 1e3),
                ("token_latency_p95_s", "token_latency_p95_ms", 1e3),
                ("tpot_p50_s", "tpot_p50_ms", 1e3),
                ("tpot_p95_s", "tpot_p95_ms", 1e3),
                ("slot_occupancy", "slot_occupancy", 1),
                ("pool_peak_utilization", "pool_peak_utilization", 1),
                ("decode_steps", "decode_steps", 1),
                ("decode_compiles", "decode_compiles", 1),
                ("preemptions", "preemptions", 1),
                ("decode_stall_ticks_max", "decode_stall_ticks_max", 1),
                # disaggregated engines only (serve/disagg.py)
                ("prefill_slot_occupancy", "prefill_slot_occupancy", 1),
                ("prefill_pool_peak_utilization",
                 "prefill_pool_peak_utilization", 1),
                ("handoffs", "handoffs", 1),
                ("handoff_s", "handoff_s", 1),
                ("handoff_blocks", "handoff_blocks", 1),
                # speculative decode (serve/spec_decode.py)
                ("acceptance_rate", "acceptance_rate", 1),
                ("draft_tokens", "draft_tokens", 1),
                ("accepted_draft_tokens", "accepted_draft_tokens", 1),
                # fleet serving (serve/fleet.py)
                ("fleet_size", "fleet_size", 1),
                ("shed", "shed", 1),
                ("redispatched", "redispatched", 1),
                ("engines_dead", "engines_dead", 1),
                ("drains", "drains", 1),
                ("leaked_blocks", "leaked_blocks", 1),
                ("wall_s", "wall_s", 1)):
            val = summary.get(src)
            if isinstance(val, (int, float)):
                view[dst] = round(val * scale, 4)
        view.setdefault("requests", summary.get("requests"))
        view.setdefault("output_tokens", summary.get("output_tokens"))
        if summary.get("per_engine"):
            view["per_engine"] = summary["per_engine"]
    if counts:
        # stream truncated before the fleet summary: the events still tell
        # the robustness story
        for dst, kind in (("shed", "serve_shed"),
                          ("redispatched", "serve_redispatch"),
                          ("engines_dead", "serve_engine_dead"),
                          ("drains", "serve_drain")):
            if dst not in view and counts.get(kind):
                view[dst] = counts[kind]
    return view


def comm_row(events: list[dict], config_path: str,
             generation: str) -> dict:
    """Predicted vs measured per-step communication time: the ICI cost
    model's exposed-comm prediction for the run's config next to the
    measured sync-phase median from the stream. The drift column is the
    per-run calibration residual — when it grows, refit (see
    picotron_tpu/analysis/calibration.py and the README calibration
    protocol). Pure arithmetic: no devices are touched."""
    from picotron_tpu.analysis.calibration import measured_step_seconds
    from picotron_tpu.analysis.cost_model import CostModel
    from picotron_tpu.config import load_config

    cfg = load_config(config_path)
    cost = CostModel(generation).predict(cfg)
    meas = measured_step_seconds(events) or {}
    # TP-axis traffic split into its exposed vs overlapped halves: the
    # deferred-sync schedule (distributed.tp_sync) only moves time from
    # the first column into the second, so this pair is the row a
    # strategy A/B actually compares.
    tp_terms = [t for t in cost.comm if "tp" in t.axes]
    tp_exposed = sum(t.secs_exposed for t in tp_terms)
    tp_total = sum(t.secs_total for t in tp_terms)
    out = {
        "generation": cost.generation,
        "predicted_comm_ms": round(cost.exposed_comm_s * 1e3, 3),
        "predicted_step_ms": round(cost.total_s * 1e3, 3),
        "predicted_tp_comm_exposed_ms": round(tp_exposed * 1e3, 3),
        "predicted_tp_comm_overlapped_ms": round(
            (tp_total - tp_exposed) * 1e3, 3),
        "measured_sync_p50_ms": (round(meas["sync_s"] * 1e3, 3)
                                 if meas.get("sync_s") is not None
                                 else None),
        "measured_step_p50_ms": (round(meas["step_s"] * 1e3, 3)
                                 if meas.get("step_s") is not None
                                 else None),
    }
    if out["measured_sync_p50_ms"] and out["predicted_comm_ms"]:
        out["comm_drift_pct"] = round(
            100.0 * (out["measured_sync_p50_ms"]
                     / out["predicted_comm_ms"] - 1.0), 1)
    return out


def render(s: dict, markdown: bool = False) -> str:
    lines = []
    gp = s["goodput_pct"]
    hdr = (f"goodput {gp:.2f}%" if gp is not None else "goodput n/a")
    lines.append(
        f"{'## Telemetry report' if markdown else 'telemetry report'} — "
        f"{hdr} | steps {s['steps']['count']} "
        f"(max {s['steps']['max']}, replayed {s['steps']['replayed']}) | "
        f"wall {s['wall_s']:.1f}s "
        f"(accounted {s['accounted_s']:.1f}s, "
        f"unaccounted {s['unaccounted_s']:.1f}s)")
    lines.append("")
    if markdown:
        lines += ["| category | seconds | share |", "|---|---|---|"]
    else:
        lines.append("time by category:")
    total = s["accounted_s"] or 1.0
    for cat, secs in sorted(s["categories"].items(),
                            key=lambda kv: -kv[1]):
        share = 100.0 * secs / total
        if markdown:
            lines.append(f"| {cat} | {secs:.3f} | {share:.1f}% |")
        else:
            lines.append(f"  {cat:14s} {secs:10.3f}s  {share:5.1f}%")
    lines.append("")
    if markdown:
        lines += ["| phase | count | total s | p50 ms | p95 ms |",
                  "|---|---|---|---|---|"]
    else:
        lines.append("phase breakdown:")
    for name, p in s["phases"].items():
        if markdown:
            lines.append(f"| {name} | {p['count']} | {p['total_s']:.3f} | "
                         f"{p['p50_ms']:.2f} | {p['p95_ms']:.2f} |")
        else:
            lines.append(f"  {name:14s} x{p['count']:<6d} "
                         f"{p['total_s']:10.3f}s  p50 {p['p50_ms']:.2f}ms  "
                         f"p95 {p['p95_ms']:.2f}ms")
    lines.append("")
    cm = s.get("comm")
    if cm:
        drift = cm.get("comm_drift_pct")
        # a stream without sync-phase records (e.g. an MPMD run, or a
        # telemetry.jsonl cut before the first optimizer step) has no
        # measured side — render n/a, never a bare None
        sync_p50 = cm.get("measured_sync_p50_ms")
        sync_txt = f"{sync_p50} ms" if sync_p50 is not None else "n/a"
        msg = (f"comm [{cm['generation']}]: predicted "
               f"{cm['predicted_comm_ms']} ms/step exposed "
               f"(of {cm['predicted_step_ms']} ms predicted step) | "
               f"measured sync p50 {sync_txt}"
               + (f" | drift {drift:+.1f}%" if drift is not None else ""))
        lines.append(f"**{msg}**" if markdown else msg)
        if cm.get("predicted_tp_comm_exposed_ms") or \
                cm.get("predicted_tp_comm_overlapped_ms"):
            tp_msg = (f"  tp comm: {cm['predicted_tp_comm_exposed_ms']} "
                      f"ms exposed + "
                      f"{cm['predicted_tp_comm_overlapped_ms']} ms "
                      f"overlapped (deferred sync moves exposed time "
                      f"into the overlapped column)")
            lines.append(tp_msg)
        lines.append("")
    pp = s.get("pipeline")
    if pp:
        frac = pp.get("bubble_fraction")
        msg = "pipeline:"
        if frac is not None:
            msg += (f" bubble {100.0 * frac:.1f}% of step wall "
                    f"({pp['bubble_s']:.3f}s)")
        lines.append(f"**{msg}**" if markdown else msg)
        for stage, st in pp.get("stages", {}).items():
            lines.append(
                f"  {stage:14s} x{st['count'] or 0:<6d} tick p50 "
                f"{st['p50_ms']} ms  p95 {st['p95_ms']} ms")
        lines.append("")
    sv = s.get("serving")
    if sv:
        hdr = "### Serving" if markdown else "serving:"
        lines.append(hdr)
        pair = lambda k: (f"{sv[k]}" if k in sv else "n/a")  # noqa: E731
        lines.append(
            f"  {sv.get('requests', 0)} requests, "
            f"{sv.get('output_tokens', 0)} output tokens @ "
            f"{pair('tokens_per_sec')} tok/s | "
            f"TTFT p50 {pair('ttft_p50_ms')} ms p95 {pair('ttft_p95_ms')} "
            f"ms | token latency p50 {pair('token_latency_p50_ms')} ms "
            f"p95 {pair('token_latency_p95_ms')} ms")
        lines.append(
            f"  queue wait p50 {pair('queue_wait_p50_ms')} ms p95 "
            f"{pair('queue_wait_p95_ms')} ms | slot occupancy "
            f"{pair('slot_occupancy')} | pool peak util "
            f"{pair('pool_peak_utilization')} | decode steps "
            f"{pair('decode_steps')} (compiles {pair('decode_compiles')}) "
            f"| preemptions {pair('preemptions')}")
        if "tpot_p50_ms" in sv or "decode_stall_ticks_max" in sv:
            lines.append(
                f"  TPOT p50 {pair('tpot_p50_ms')} ms p95 "
                f"{pair('tpot_p95_ms')} ms | max decode stall "
                f"{pair('decode_stall_ticks_max')} ticks")
        if "handoffs" in sv or "prefill_slot_occupancy" in sv:
            lines.append(
                f"  disagg: prefill occupancy "
                f"{pair('prefill_slot_occupancy')} (pool peak "
                f"{pair('prefill_pool_peak_utilization')}) | handoffs "
                f"{pair('handoffs')} ({pair('handoff_blocks')} blocks, "
                f"{pair('handoff_s')} s)")
        if "acceptance_rate" in sv or "draft_tokens" in sv:
            lines.append(
                f"  speculative: acceptance {pair('acceptance_rate')} "
                f"({pair('accepted_draft_tokens')}/{pair('draft_tokens')} "
                f"draft tokens accepted)")
        if any(k in sv for k in ("fleet_size", "shed", "redispatched",
                                 "engines_dead", "drains")):
            lines.append(
                f"  fleet: size {pair('fleet_size')} | shed {pair('shed')} "
                f"| redispatched {pair('redispatched')} | engines dead "
                f"{pair('engines_dead')} | drains {pair('drains')} | "
                f"leaked blocks {pair('leaked_blocks')}")
        for pe in sv.get("per_engine", []) or []:
            state = ("drained" if pe.get("drained")
                     else "alive" if pe.get("alive") else "dead")
            lines.append(
                f"    engine {pe.get('engine')}: {state}, "
                f"{pe.get('requests')} requests, shed {pe.get('shed')}, "
                f"{pe.get('decode_steps')} decode steps, preemptions "
                f"{pe.get('preemptions')}, pool in_use "
                f"{pe.get('pool_in_use')} (peak util "
                f"{pe.get('pool_peak_utilization')})")
        lines.append("")
    rz = s.get("resize")
    if rz:
        msg = (f"elastic resize: {rz['events']} topology-change "
               f"restore(s), {rz['seconds']:.3f}s booked as resize")
        lines.append(f"**{msg}**" if markdown else msg)
        lines.append("")
    sn = s.get("sentinel")
    if sn:
        msg = (f"sentinel: {sn['alerts']} alert(s) — worst "
               f"{sn['quantity']} at {sn['worst_ratio']:.2f}x baseline "
               f"(flight recorder auto-dumped; see "
               f"flightdeck_postmortem.json)")
        lines.append(f"**{msg}**" if markdown else msg)
        lines.append("")
    ev = ", ".join(f"{k}={v}" for k, v in s["events"].items())
    lines.append(f"events: {ev}" if not markdown else f"**events:** {ev}")
    tr = s.get("training")
    if tr:
        msg = (f"training: {tr['records']} log records, final step "
               f"{tr['final_step']}, final loss {tr['final_loss']}, "
               f"mean tokens/s {tr['mean_tokens_per_sec']}")
        if tr.get("final_val_loss") is not None:
            msg += f", final val_loss {tr['final_val_loss']}"
        lines.append(f"**{msg}**" if markdown else msg)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize a picotron-tpu telemetry.jsonl stream")
    ap.add_argument("path", help="telemetry.jsonl or a run directory "
                    "containing one")
    ap.add_argument("--markdown", action="store_true",
                    help="emit markdown tables (PERF.md format)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object")
    ap.add_argument("--config", default=None,
                    help="the run's config JSON: adds a `comm` row — the "
                         "ICI cost model's predicted per-step comm time "
                         "next to the measured sync-phase time, so "
                         "calibration drift is visible per run")
    ap.add_argument("--generation", default="v5e",
                    choices=["v4", "v5e", "v5p", "v6e"],
                    help="TPU generation for --config's comm prediction")
    args = ap.parse_args(argv)

    events = load_events(resolve_path(args.path))
    if not events:
        print(f"no events in {args.path}", file=sys.stderr)
        return 1
    s = summarize(events)
    if args.config:
        s["comm"] = comm_row(events, args.config, args.generation)
    try:
        print(json.dumps(s) if args.json else render(s, args.markdown))
    except BrokenPipeError:  # `... | head` is a supported way to read this
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    sys.exit(main())
