#!/usr/bin/env python
"""Log -> CSV benchmark harvester — parity with the reference's
extract_metrics.py.

Walks an experiment directory and harvests each run's per-step metrics:
runs that carry a structured `telemetry.jsonl` (picotron_tpu/telemetry;
written next to the checkpoints) are read from it directly — no parsing
ambiguity, full float precision, plus the goodput % only the event stream
knows — while runs with only a console log fall back to regex-parsing the
per-step line emitted by picotron_tpu.utils.training_log_line (the log
format is a de-facto API, same contract as the reference's train.py print
<-> extract_metrics.py regexes, ref: extract_metrics.py:55-68). Either
way: skip warmup steps, write per-run `metrics.csv` plus a sweep-level
`global_metrics.csv` (ref: extract_metrics.py:91-99,147-195).
Parallel-layout parameters are decoded from directory names like
`dp8_tp2_pp1_cp1` (ref: extract_metrics.py:8-23).
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import re
from statistics import mean

# Matches picotron_tpu.utils.training_log_line output.
LINE_RE = re.compile(
    r"\[step (?P<step>\d+)\] loss: (?P<loss>[\d.]+|-?nan|-?inf) \| "
    r"tokens/s: (?P<tps>[\d.]+[KMBT]?) \| "
    r"tokens/s/chip: (?P<tpsc>[\d.]+[KMBT]?) \| "
    r"MFU: (?P<mfu>[\d.]+)%"
)

NAME_RE = re.compile(r"(dp|tp|pp|cp)(\d+)")

# Optional trailing step metrics appended by training_log_line's `extras`
# (e.g. "| moe_drop_frac: 0.0123"): harvested into mean_<key> columns.
# The value must end the field (lookahead): the stable suffixed fields
# ("tokens: 10K", "mem: 1.0GB") must NOT be scooped up — their numeric
# prefix alone would be wrong (suffix dropped) and meaningless to average.
EXTRA_RE = re.compile(r"\| (?P<key>[a-z_]+): (?P<val>[\d.]+)(?= \||$)")
_EXTRA_SKIP = {"tokens", "mem"}

# Dedicated eval lines ("[eval  000010] val_loss: 5.6021 (8 batches)").
EVAL_RE = re.compile(r"\[eval  (?P<step>\d+)\] val_loss: (?P<val>[\d.]+)")

_SUFFIX = {"K": 1e3, "M": 1e6, "B": 1e9, "T": 1e12}


def parse_human(s: str) -> float:
    """'13.5K' -> 13500.0 (inverse of utils.human_format)."""
    if s and s[-1] in _SUFFIX:
        return float(s[:-1]) * _SUFFIX[s[-1]]
    return float(s)


def decode_run_name(name: str) -> dict:
    """'dp8_tp2_pp1_cp1_...' -> {'dp': 8, 'tp': 2, ...}
    (ref: extract_metrics.py:8-23)."""
    return {k: int(v) for k, v in NAME_RE.findall(name)}


def process_file(path: str, skip_steps: int = 3) -> dict | None:
    """Mean tokens/s/chip and MFU over post-warmup steps
    (ref: extract_metrics.py:83-89 skips the first 3 steps)."""
    rows = []
    val_losses = []
    with open(path) as f:
        for line in f:
            m = LINE_RE.search(line)
            if m:
                row = {
                    "step": int(m.group("step")),
                    "loss": float(m.group("loss")),
                    "tokens_per_sec": parse_human(m.group("tps")),
                    "tokens_per_sec_per_chip": parse_human(m.group("tpsc")),
                    "mfu_pct": float(m.group("mfu")),
                }
                for em in EXTRA_RE.finditer(line[m.end():].rstrip()):
                    if em.group("key") not in _EXTRA_SKIP:
                        row["extra_" + em.group("key")] = float(em.group("val"))
                rows.append(row)
            ev = EVAL_RE.search(line)
            if ev:
                val_losses.append(float(ev.group("val")))
    rows = [r for r in rows if r["step"] > skip_steps]
    if not rows:
        return None
    # A diverged run must be visible in the sweep, not silently dropped —
    # final_loss will read nan/inf.
    return _aggregate_rows(rows, val_losses)


_STABLE_STEP_FIELDS = {"ts", "kind", "step", "loss", "tokens_per_sec",
                       "tokens_per_sec_per_chip", "mfu", "trained_tokens",
                       "memory_gb", "line"}


# serve_summary fields harvested into serve_* CSV columns — the SLO
# numbers a serving sweep compares across runs (latency seconds scaled
# to ms to match the report tool).
_SERVE_FIELDS = (
    ("requests", "serve_requests", 1),
    ("output_tokens", "serve_output_tokens", 1),
    ("tokens_per_sec", "serve_tokens_per_sec", 1),
    ("ttft_p50_s", "serve_ttft_p50_ms", 1e3),
    ("ttft_p95_s", "serve_ttft_p95_ms", 1e3),
    ("tpot_p50_s", "serve_tpot_p50_ms", 1e3),
    ("tpot_p95_s", "serve_tpot_p95_ms", 1e3),
    ("acceptance_rate", "serve_acceptance_rate", 1),
    ("decode_stall_ticks_max", "serve_decode_stall_ticks_max", 1),
    ("handoffs", "serve_handoffs", 1),
    # fleet serving (serve/fleet.py): overload + failover counters
    ("shed", "serve_shed", 1),
    ("redispatched", "serve_redispatch", 1),
    ("engines_dead", "serve_engines_dead", 1),
    ("fleet_size", "serve_fleet_size", 1),
)


def process_telemetry(path: str, skip_steps: int = 3) -> dict | None:
    """The structured twin of process_file: per-step rows from a
    telemetry.jsonl's "step" records (same schema as the regex rows, so
    the aggregation below is shared) + the goodput % from the stream's
    (category, secs) accounting. Replayed step numbers (rollback /
    restart) keep only their LAST record — the one whose update survived
    into the final weights. Serving streams (no step rows, but a
    serve_summary event) yield serve_* columns instead, so a serving
    sweep harvests TTFT/TPOT/acceptance with the same tool."""
    rows_by_step: dict[int, dict] = {}
    val_losses: list[float] = []
    categories: dict[str, float] = {}
    serve_summary: dict | None = None
    sentinel_alerts = 0
    # A size-rotated stream (logging.telemetry_max_mb) keeps its older
    # half in `<path>.1`; read it first so replayed-step bookkeeping
    # (last record wins) sees events in emission order.
    segments = [p for p in (path + ".1", path) if os.path.exists(p)]
    for seg in segments or [path]:
        with open(seg) as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    ev = json.loads(raw)
                except json.JSONDecodeError:
                    continue  # torn tail line of a killed run
                kind = ev.get("kind")
                secs = ev.get("secs")
                if ev.get("category") is not None \
                        and isinstance(secs, (int, float)):
                    categories[ev["category"]] = \
                        categories.get(ev["category"], 0.0) + secs
                if kind == "serve_summary":
                    serve_summary = ev  # last wins (mirrors telemetry_report)
                if kind == "sentinel_alert":
                    sentinel_alerts += 1
                if kind == "step" and "step" in ev:
                    row = {
                        "step": int(ev["step"]),
                        "loss": float(ev.get("loss", float("nan"))),
                        "tokens_per_sec": float(
                            ev.get("tokens_per_sec", 0.0)),
                        "tokens_per_sec_per_chip": float(
                            ev.get("tokens_per_sec_per_chip", 0.0)),
                        "mfu_pct": 100.0 * float(ev.get("mfu", 0.0)),
                    }
                    for k, v in ev.items():
                        if k not in _STABLE_STEP_FIELDS \
                                and isinstance(v, (int, float)):
                            row["extra_" + k] = float(v)
                    rows_by_step[row["step"]] = row
                elif kind == "eval" and "val_loss" in ev:
                    val_losses.append(float(ev["val_loss"]))
    rows = [r for _, r in sorted(rows_by_step.items())
            if r["step"] > skip_steps]
    serve_cols = {}
    if serve_summary:
        for src, dst, scale in _SERVE_FIELDS:
            val = serve_summary.get(src)
            if isinstance(val, (int, float)):
                serve_cols[dst] = round(val * scale, 4)
    if not rows:
        if not serve_cols:
            return None
        return serve_cols  # serving-only stream: no train-step rows
    out = _aggregate_rows(rows, val_losses)
    out.update(serve_cols)
    accounted = sum(categories.values())
    if accounted > 0:
        out["goodput_pct"] = round(
            100.0 * categories.get("compute", 0.0) / accounted, 2)
    # drift-sentinel alert count (telemetry/flightdeck): 0 on a clean
    # run — the column exists either way so sweeps can filter on it
    out["sentinel_alerts"] = sentinel_alerts
    return out


def _aggregate_rows(rows: list[dict], val_losses: list[float]) -> dict:
    """Shared row aggregation (regex and telemetry paths must stay
    column-compatible — global_metrics.csv mixes runs of both kinds)."""
    out = {
        "steps": len(rows),
        "final_loss": rows[-1]["loss"],
        "mean_tokens_per_sec": mean(r["tokens_per_sec"] for r in rows),
        "mean_tokens_per_sec_per_chip": mean(
            r["tokens_per_sec_per_chip"] for r in rows),
        "mean_mfu_pct": mean(r["mfu_pct"] for r in rows),
    }
    extra_keys = {k for r in rows for k in r if k.startswith("extra_")}
    for k in sorted(extra_keys):
        vals = [r[k] for r in rows if k in r]
        out["mean_" + k.removeprefix("extra_")] = mean(vals)
    if val_losses:
        out["final_val_loss"] = val_losses[-1]
    return out


def find_log(run_dir: str) -> str | None:
    for name in ("train.log", "log.txt", "stdout.log"):
        p = os.path.join(run_dir, name)
        if os.path.exists(p):
            return p
    logs = [f for f in os.listdir(run_dir) if f.endswith(".log")]
    return os.path.join(run_dir, logs[0]) if logs else None


def process_run(run_dir: str, skip_steps: int = 3) -> dict | None:
    """telemetry.jsonl when the run has one (checkpoint dir or run root —
    it sits next to the checkpoints), regex over the console log
    otherwise."""
    for sub in ("", "ckpt"):
        tpath = os.path.join(run_dir, sub, "telemetry.jsonl")
        if os.path.exists(tpath):
            stats = process_telemetry(tpath, skip_steps)
            if stats is not None:
                return stats
            break  # present but empty/torn: the log is the fallback
    log = find_log(run_dir)
    return process_file(log, skip_steps) if log else None


def aggregate(exp_dir: str, skip_steps: int = 3) -> list[dict]:
    results = []
    for name in sorted(os.listdir(exp_dir)):
        run_dir = os.path.join(exp_dir, name)
        if not os.path.isdir(run_dir):
            continue
        stats = process_run(run_dir, skip_steps)
        if stats is None:
            continue
        row = {"run": name, **decode_run_name(name), **stats}
        results.append(row)
        # per-run metrics.csv (ref: extract_metrics.py:91-99)
        with open(os.path.join(run_dir, "metrics.csv"), "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=list(row.keys()))
            w.writeheader()
            w.writerow(row)
    return results


def main() -> None:
    ap = argparse.ArgumentParser(description="harvest metrics from run logs")
    ap.add_argument("exp_dir", help="directory of runs (one subdir per run)")
    ap.add_argument("--skip-steps", type=int, default=3,
                    help="warmup steps to exclude (ref default: 3)")
    args = ap.parse_args()

    results = aggregate(args.exp_dir, args.skip_steps)
    if not results:
        print(f"no parsable logs under {args.exp_dir}")
        return
    fields = sorted({k for r in results for k in r}, key=lambda k: (k != "run", k))
    out = os.path.join(args.exp_dir, "global_metrics.csv")
    with open(out, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=fields)
        w.writeheader()
        for r in results:
            w.writerow(r)
    print(f"{len(results)} runs -> {out}")
    for r in results:
        if "mean_tokens_per_sec_per_chip" in r:
            print(f"  {r['run']}: {r['mean_tokens_per_sec_per_chip']:.0f} "
                  f"tok/s/chip, {r['mean_mfu_pct']:.1f}% MFU, "
                  f"loss {r['final_loss']:.3f}")
        else:  # serving-only run (serve_summary, no train steps)
            print(f"  {r['run']}: {r.get('serve_tokens_per_sec', 0)} tok/s, "
                  f"TTFT p50 {r.get('serve_ttft_p50_ms', 'n/a')} ms, "
                  f"acceptance {r.get('serve_acceptance_rate', 'n/a')}")


if __name__ == "__main__":
    main()
