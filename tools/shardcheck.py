#!/usr/bin/env python
"""Will this config's SPMD program do what you think? — static audit, no TPU.

Runs the shardcheck analyzers (picotron_tpu/analysis) for one or more
configs by abstract evaluation on simulated host devices:

- spec lint: PartitionSpec pytree vs param pytree vs mesh, path-level errors
- collective-schedule audit: parse the lowered step's HLO — the grad
  all-reduce over the fused data axes must exist, pipeline ppermutes and
  expert all_to_alls must exist where the layout promises them, and no
  all-gather may exceed the replication byte budget
- donation + recompilation hazards: every TrainState buffer donated; the
  step's output avals identical to its inputs (anything else recompiles
  every step)
- sharding-dataflow audit (--provenance): attribute every lowered
  collective to the source line + state/batch paths that minted it,
  classify each as intended (schedule contract) or implicit
  (GSPMD-minted reshard), and predict boundary reshards with the spec
  fix named
- jit-variant prover (--variants): statically enumerate the abstract
  signatures (shape/dtype/sharding/commitment) reaching each jit entry
  point — train step, serve prefill/decode — and prove compile-once
- slice-boundary audit (--slices N, "slicecheck"): map every lowered
  replica group onto the declared multislice partition and classify it
  intra-slice / boundary / VIOLATING — an ICI-only axis (tp/cp/ep)
  straddling the DCN cut is a named error, and the per-tier byte totals
  are priced by the cost model's dcn tier under --cost
- source lint: no semi-private jax.core, no host callbacks in library
  code, no uncommitted jax.device_put

Usage:

  python tools/shardcheck.py --config runs/smollm17-dp8/config.json
  python tools/shardcheck.py --preset tiny-dense --preset tiny-moe-ep
  python tools/shardcheck.py --all-presets --verbose
  python tools/shardcheck.py --all-presets --provenance --variants --json
  python tools/shardcheck.py --preset tiny-dense --slices 2 --dcn-axes dp

--json emits one machine-readable line per config for every subcommand
(findings + the per-check info dict); a config that cannot trace at all
on this JAX becomes a row with a "fatal" key instead of killing the
sweep.

Exit status 0 iff every config is green. The preset matrix covers the
layouts the test tier exercises (dense/MoE, pp>1, ep>1, offload on/off) on
at most 8 simulated devices, so the whole matrix runs on a laptop.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# (model, distributed kwargs, training kwargs[, pipeline kwargs]) tuples;
# every preset fits the 8 simulated host devices the test tier provisions.
PRESETS: dict[str, tuple] = {
    "tiny-1chip": ("debug-tiny", {}, {}),
    "tiny-dense": ("debug-tiny",
                   dict(dp_size=2, tp_size=2, cp_size=2),
                   dict(gradient_accumulation_steps=2)),
    "tiny-dense-pp": ("debug-tiny",
                      dict(pp_size=2, dp_size=2),
                      dict(gradient_accumulation_steps=2)),
    # the MPMD executor's per-stage programs (parallel/mpmd.py): the
    # --variants prover must certify each stage fwd/bwd jit compiles
    # exactly once across every call the schedule table makes
    "tiny-dense-pp-mpmd": ("debug-tiny",
                           dict(pp_size=2, dp_size=2),
                           dict(gradient_accumulation_steps=2),
                           dict(executor="mpmd")),
    "tiny-moe-ep": ("debug-tiny-moe",
                    dict(ep_size=2, dp_size=2),
                    dict(gradient_accumulation_steps=2)),
    "tiny-dense-offload": ("debug-tiny", {},
                           dict(gradient_accumulation_steps=2,
                                optimizer_offload=True)),
    "tiny-moe-offload": ("debug-tiny-moe", dict(ep_size=2),
                         dict(gradient_accumulation_steps=2,
                              optimizer_offload=True)),
    # the fused grad engine on its widened axes (parallel/fused_bwd.py):
    # the audit must see the same per-axis schedule the AD engine lowers —
    # SP all-gather/reduce-scatter pair, cp4 ring ppermute — from the
    # manual backward scan (collectives.py presence rules)
    "tiny-sp-fused": ("debug-tiny",
                      dict(dp_size=2, tp_size=2, sequence_parallel=True),
                      dict(gradient_accumulation_steps=2,
                           grad_engine="fused",
                           remat_policy="dots_attn")),
    "tiny-cp4-fused": ("debug-tiny", dict(dp_size=2, cp_size=4),
                       dict(gradient_accumulation_steps=2,
                            grad_engine="fused",
                            remat_policy="dots_attn")),
    # the mesh cp flavor's 2D schedule (ops/mesh_attention.py): the audit
    # must see the head-scatter all_to_all on the cp_y subgroup AND the
    # row ring ppermute on the cp_x rows — and no collective widened to
    # the full cp axis (collectives.py mesh presence rule)
    "tiny-cp4-mesh": ("debug-tiny",
                      dict(dp_size=2, cp_size=4, cp_flavor="mesh",
                           cp_mesh="2x2"),
                      dict(gradient_accumulation_steps=2)),
    "tiny-cp4-mesh-fused": ("debug-tiny",
                            dict(dp_size=2, cp_size=4, cp_flavor="mesh",
                                 cp_mesh="2x2"),
                            dict(gradient_accumulation_steps=2,
                                 grad_engine="fused",
                                 remat_policy="dots_attn")),
    # deferred activation sync (parallel/tp_strategies.py): the audit must
    # see the block-exit reduce-scatter AND the gather hoisted into the
    # next block's entry over tp — WITHOUT sequence_parallel set — on both
    # grad engines (collectives.py deferred presence rule), and the
    # provenance audit must attribute every tp collective (no implicit
    # GSPMD reshard from the seq-sharded residual stream)
    "tiny-tp-deferred": ("debug-tiny",
                         dict(dp_size=2, tp_size=2, tp_sync="deferred"),
                         dict(gradient_accumulation_steps=2)),
    "tiny-tp-deferred-fused": ("debug-tiny",
                               dict(dp_size=2, tp_size=2,
                                    tp_sync="deferred"),
                               dict(gradient_accumulation_steps=2,
                                    grad_engine="fused",
                                    remat_policy="dots_attn")),
    # the 2d tp strategy's subgroup schedule (parallel/tp_strategies.py):
    # inner tp_y activation/weight all-gathers + outer tp_x partial-sum
    # all-reduces, audited against the collectives.py 2d presence rule
    # (kv heads raised to 4 so tp=4 keeps GQA divisibility)
    "tiny-tp2d": ("debug-tiny",
                  dict(dp_size=2, tp_size=4, tp_strategy="2d",
                       tp_mesh="2x2"),
                  dict(gradient_accumulation_steps=2),
                  {},
                  dict(num_key_value_heads=4)),
    # slice-boundary audit (analysis/boundary.py): the 8 simulated hosts
    # split into 2 declared "slices"; with dp crossing the cut, every
    # grad all-reduce must classify as a declared boundary crossing and
    # every tp/cp collective must stay intra-slice — zero violations
    "tiny-dense-dp-cross": ("debug-tiny",
                            dict(dp_size=2, tp_size=2, cp_size=2,
                                 slices=2, dcn_axes="dp"),
                            dict(gradient_accumulation_steps=2)),
    # the dp-cross audit again on the FUSED grad engine under remat: the
    # runtime hierarchical dp reduction (parallel/hier_reduce.py) sits at
    # the engine seam, so the in-scan accumulator must still reach the
    # same explicit reduce-scatter / DCN all-reduce / all-gather schedule
    "tiny-dp-cross-fused": ("debug-tiny",
                            dict(dp_size=2, tp_size=2, cp_size=2,
                                 slices=2, dcn_axes="dp"),
                            dict(gradient_accumulation_steps=2,
                                 grad_engine="fused", remat=True,
                                 remat_policy="dots_attn")),
    # same audit with the PIPELINE axis over DCN on the MPMD substrate:
    # stage-boundary ppermutes are the only declared crossers
    "tiny-pp-mpmd-cross": ("debug-tiny",
                           dict(pp_size=2, tp_size=2,
                                slices=2, dcn_axes="pp"),
                           dict(gradient_accumulation_steps=2),
                           dict(executor="mpmd")),
}


def preset_config(name: str):
    from picotron_tpu.config import (
        Config, DistributedConfig, ModelConfig, PipelineConfig,
        TrainingConfig, resolve_preset,
    )

    model, dist_kw, train_kw, *rest = PRESETS[name]
    pipe_kw = rest[0] if rest else {}
    model_kw = rest[1] if len(rest) > 1 else {}
    cfg = Config(
        distributed=DistributedConfig(**dist_kw),
        model=ModelConfig(name=model,
                          **{**resolve_preset(model), **model_kw}),
        training=TrainingConfig(seq_length=64, micro_batch_size=1,
                                **train_kw),
        pipeline=PipelineConfig(**pipe_kw),
    )
    cfg.validate()
    return cfg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="picotron-tpu static SPMD analysis (shardcheck)")
    ap.add_argument("--config", action="append", default=[],
                    help="config JSON path (repeatable)")
    ap.add_argument("--preset", action="append", default=[],
                    choices=sorted(PRESETS),
                    help="built-in tiny config (repeatable)")
    ap.add_argument("--all-presets", action="store_true",
                    help="run the full preset matrix (dense/MoE, pp>1, "
                         "ep>1, offload on/off)")
    ap.add_argument("--checks", default=None,
                    help="comma-separated subset of spec,source,"
                         "collectives,boundary,provenance,variants,"
                         "donation,stability (default: all)")
    ap.add_argument("--provenance", action="store_true",
                    help="focus on the sharding-dataflow audit: collective "
                         "provenance, intended-vs-implicit classification, "
                         "predicted boundary reshards (spec lint still "
                         "runs first)")
    ap.add_argument("--variants", action="store_true",
                    help="focus on the static jit-variant prover: abstract "
                         "signatures reaching each jit entry point, "
                         "compile-once proof (spec lint still runs first)")
    ap.add_argument("--slices", type=int, default=None,
                    help="audit the collective schedule against an "
                         "N-slice multislice partition (overrides the "
                         "config's distributed.slices); a config "
                         "declaring slices > 1 is audited automatically")
    ap.add_argument("--dcn-axes", default=None,
                    help="comma-separated mesh axes allowed to cross the "
                         "DCN cut (subset of dp,pp; overrides the "
                         "config's distributed.dcn_axes)")
    ap.add_argument("--budget-mb", type=float, default=None,
                    help="all-gather replication budget in MiB (default: "
                         "the largest param leaf / activation block)")
    ap.add_argument("--json", action="store_true",
                    help="one JSON line per config instead of the report")
    ap.add_argument("--verbose", action="store_true",
                    help="include info-level findings and summary tables")
    ap.add_argument("--cost", action="store_true",
                    help="price the traced collective schedule with the "
                         "ICI cost model and compare the config against "
                         "the layout planner's best at equal chip count "
                         "(picotron_tpu/analysis/cost_model.py)")
    ap.add_argument("--generation", default="v5e",
                    choices=["v4", "v5e", "v5p", "v6e"],
                    help="TPU generation for --cost (ICI bandwidth, "
                         "topology, HBM)")
    args = ap.parse_args(argv)

    names = list(args.preset) + (sorted(PRESETS) if args.all_presets
                                 else [])
    if not names and not args.config:
        ap.error("nothing to check: pass --config, --preset, or "
                 "--all-presets")

    from picotron_tpu.analysis import ALL_CHECKS, run_shardcheck
    from picotron_tpu.config import load_config

    if args.checks:
        checks = tuple(c.strip() for c in args.checks.split(","))
    elif args.provenance or args.variants:
        checks = ("spec",)
        checks += ("provenance",) if args.provenance else ()
        checks += ("variants",) if args.variants else ()
        checks += ("boundary",) if args.slices else ()
    else:
        checks = ALL_CHECKS
    if args.slices and "boundary" not in checks:
        checks += ("boundary",)
    unknown = set(checks) - set(ALL_CHECKS)
    if unknown:
        ap.error(f"unknown checks {sorted(unknown)}; valid: {ALL_CHECKS}")
    budget = (int(args.budget_mb * 1024 * 1024)
              if args.budget_mb is not None else None)

    targets = [(f"preset:{n}", preset_config(n)) for n in names]
    targets += [(path, load_config(path)) for path in args.config]

    # Simulate the largest topology on host CPUs — must precede the first
    # backend-initializing jax call (same recipe as tools/memcheck.py).
    world = max(cfg.distributed.world_size for _, cfg in targets)
    from picotron_tpu.mesh import force_host_device_count

    if world > 1:
        force_host_device_count(world)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    cost_model = None
    if args.cost:
        from picotron_tpu.analysis.cost_model import CostModel

        cost_model = CostModel(args.generation)

    n_bad = 0
    for label, cfg in targets:
        try:
            rep = run_shardcheck(cfg, checks=checks, budget_bytes=budget,
                                 cost_model=cost_model, slices=args.slices,
                                 dcn_axes=args.dcn_axes)
        except Exception as e:  # layouts this JAX cannot trace (pre-vma)
            n_bad += 1
            if args.json:
                print(json.dumps({
                    "config": label, "ok": False,
                    "fatal": f"{type(e).__name__}: {e}",
                }), flush=True)
            else:
                print(f"== {label} ==")
                print(f"FATAL {type(e).__name__}: {e}", flush=True)
            continue
        cost_row = None
        if cost_model is not None:
            from picotron_tpu.analysis.planner import planner_gap

            cur, best, gap = planner_gap(cfg, cost_model)
            cost_row = {
                "generation": cost_model.gen.name,
                "predicted_step_ms": round(cur.total_s * 1e3, 3),
                "exposed_comm_ms": round(cur.exposed_comm_s * 1e3, 3),
                "planner_best": best.label if best else None,
                "planner_best_step_ms": (round(best.cost.total_s * 1e3, 3)
                                         if best else None),
                "gap_vs_best_pct": round(gap * 100, 1),
            }
        n_bad += 0 if rep.ok() else 1
        if args.json:
            print(json.dumps({
                "config": label,
                "ok": rep.ok(),
                "errors": len(rep.errors()),
                "warnings": len(rep.warnings()),
                "findings": [f.render() for f in rep.findings
                             if f.severity != "info" or args.verbose],
                "info": rep.info,
                **({"cost": cost_row} if cost_row else {}),
            }), flush=True)
        else:
            print(f"== {label} ==")
            print(rep.render(verbose=args.verbose), flush=True)
            prov = rep.info.get("provenance")
            if prov and "sites" in prov:
                print(f"provenance: {prov['sites']} site(s), "
                      f"{prov['ops_attributed']}/{prov['ops_effective']} "
                      f"lowered op(s) attributed "
                      f"({prov['attribution_pct']:.1f}%), "
                      f"{prov['implicit_ops']} implicit, "
                      f"{prov['boundary_reshards']} predicted reshard(s)",
                      flush=True)
                if args.verbose:
                    for src in sorted(prov.get("by_source", {})):
                        row = prov["by_source"][src]
                        roots = ", ".join(row["roots"][:3]) or "<constants>"
                        print(f"  {src}: {row['ops']} "
                              f"{'/'.join(row['kinds'])} <- {roots}",
                              flush=True)
            bnd = rep.info.get("boundary")
            if bnd and bnd.get("audited"):
                from picotron_tpu.analysis.boundary import render_table

                line = (f"boundary: {bnd['slices']} slice(s), dcn axes "
                        f"[{bnd.get('dcn_axes', '')}] — "
                        f"{bnd.get('intra', 0)} intra / "
                        f"{bnd.get('boundary', 0)} boundary / "
                        f"{bnd.get('violating', 0)} violating")
                if "dcn_ms" in bnd:
                    line += (f"; dcn {bnd['dcn_ms']:.3f} ms, intra-slice "
                             f"ici {bnd['ici_ms']:.3f} ms "
                             f"[{bnd['dcn_generation']}]")
                print(line, flush=True)
                if args.verbose:
                    print(render_table(bnd), flush=True)
            var = rep.info.get("variants")
            if var:
                for entry in ("train_step", "mpmd_stages", "serve"):
                    v = var.get(entry) or {}
                    if "proven" in v:
                        state = ("proven compile-once" if v["proven"]
                                 else "NOT proven")
                        detail = (f"{v['programs']} stage program(s)"
                                  if "programs" in v else
                                  f"{v.get('signatures', '?')} abstract "
                                  f"signature(s)")
                        print(f"variants[{v.get('entry', entry)}]: {state} "
                              f"({detail})", flush=True)
                lint = (var.get("mpmd_stages") or {}).get("schedule_lint")
                if lint:
                    state = ("statically proven"
                             if lint["proven"] else "FAILS the lint")
                    print(f"variants[schedule:{lint['kind']}]: table "
                          f"{state} ({lint['ops']} op(s) over "
                          f"{lint['ticks']} tick(s), "
                          f"{lint['problems']} problem(s))", flush=True)
            if cost_row:
                line = (f"cost[{cost_row['generation']}]: predicted step "
                        f"{cost_row['predicted_step_ms']} ms (exposed "
                        f"comm {cost_row['exposed_comm_ms']} ms)")
                if cost_row["planner_best"]:
                    line += (f"; planner best at equal chips: "
                             f"{cost_row['planner_best']} "
                             f"({cost_row['planner_best_step_ms']} ms, "
                             f"this config "
                             f"+{cost_row['gap_vs_best_pct']}%)")
                print(line, flush=True)
    if not args.json:
        status = "green" if n_bad == 0 else f"{n_bad} config(s) with errors"
        print(f"shardcheck: {len(targets)} config(s) checked — {status}")
    return 0 if n_bad == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
