#!/usr/bin/env python
"""Which layout is fastest? — rank 4D layouts by predicted time, on CPU.

Enumerates the dp×tp×pp×cp×ep×{sequence_parallel, zero1, offload} space
— and, wherever pp > 1, the pipeline executor/schedule space on top
({spmd-1f1b, mpmd-1f1b, mpmd-interleaved-vN}) — for a model + chip
count, prunes HBM non-fits, prices the survivors with the ICI-topology
cost model (picotron_tpu/analysis/cost_model.py), and prints a ranked
table with the predicted-fastest config as a ready-to-run overrides
line. No TPU needed — the model is calibrated against the
measured SWEEP/BENCH rows on disk (validate with --validate-sweep).

  python tools/layout_planner.py --chips 8 --model SmolLM-1.7B --seq 2048
  python tools/layout_planner.py --chips 64 --config runs/llama3-8b-4d-v5p64/config.json \
      --generation v5p --markdown
  python tools/layout_planner.py --chips 8 --model debug-tiny --seq 64 \
      --trace 3 --verify-hbm            # re-cost top-3 from traced HLO,
                                        # memcheck-verify the winner
  python tools/layout_planner.py --validate-sweep   # rank agreement vs
                                                    # SWEEP_r03–r05

--trace and --verify-hbm lower/compile on simulated host devices (the
memcheck recipe); expect minutes for multi-billion-parameter configs —
the analytic default answers in milliseconds.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_base_config(args):
    from picotron_tpu.config import (
        Config, ModelConfig, TrainingConfig, load_config, resolve_preset,
    )

    if args.config:
        cfg = load_config(args.config)
        if args.seq:
            cfg = cfg.replace(training=dataclasses.replace(
                cfg.training, seq_length=args.seq))
        return cfg
    preset = resolve_preset(args.model)
    seq = args.seq or 2048
    preset["max_position_embeddings"] = max(
        preset.get("max_position_embeddings", seq), seq)
    if args.layers:
        preset["num_hidden_layers"] = args.layers
    cfg = Config(
        model=ModelConfig(name=args.model, **preset),
        training=TrainingConfig(
            seq_length=seq, micro_batch_size=args.mbs,
            gradient_accumulation_steps=args.grad_acc),
    )
    cfg.validate()
    return cfg


def render_table(points, top, markdown=False):
    rows = []
    for i, p in enumerate(points[:top]):
        d = p.as_dict()
        rows.append((i + 1, d["layout"], d["predicted_step_ms"],
                     d["compute_ms"], d["exposed_comm_ms"],
                     d["bubble_ms"] + d["offload_ms"],
                     d.get("traced_comm_ms", ""),
                     d["hbm_est_gib"],
                     d.get("memcheck_gib", "")))
    hdr = ("rank", "layout", "step_ms", "compute_ms", "comm_ms",
           "bubble+io_ms", "traced_comm_ms", "hbm_est_gib", "memcheck_gib")
    if markdown:
        lines = ["| " + " | ".join(hdr) + " |",
                 "|" + "---|" * len(hdr)]
        lines += ["| " + " | ".join(str(c) for c in r) + " |"
                  for r in rows]
    else:
        w = [max(len(str(x)) for x in [h] + [r[i] for r in rows])
             for i, h in enumerate(hdr)]
        lines = ["  ".join(h.ljust(w[i]) for i, h in enumerate(hdr))]
        lines += ["  ".join(str(c).ljust(w[i]) for i, c in enumerate(r))
                  for r in rows]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="picotron-tpu automatic layout planner (CPU-only)")
    ap.add_argument("--chips", type=int, default=None,
                    help="slice size to plan for (required unless "
                         "--validate-sweep)")
    ap.add_argument("--model", default="SmolLM-1.7B",
                    help="model preset (ignored with --config)")
    ap.add_argument("--config", default=None,
                    help="plan around an existing config JSON (its model/"
                         "batch settings seed the search)")
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--layers", type=int, default=None,
                    help="override the preset's depth")
    ap.add_argument("--mbs", type=int, default=1)
    ap.add_argument("--grad-acc", type=int, default=8,
                    help="grad-accum of the SEED point; the planner holds "
                         "the implied global batch constant across "
                         "layouts")
    ap.add_argument("--generation", default="v5e",
                    choices=["v4", "v5e", "v5p", "v6e"],
                    help="TPU generation: ICI topology, link bandwidth, "
                         "HBM capacity")
    ap.add_argument("--hbm-gib", type=float, default=None,
                    help="override the generation's per-chip HBM capacity")
    ap.add_argument("--slices", type=int, default=None, metavar="N",
                    help="multislice planning: after ranking, price the "
                         "winner's layout split over N slices — one row "
                         "per DCN-tolerant axis (dp/pp) that can absorb "
                         "the slice count, with the intra-slice ICI and "
                         "cross-slice DCN tiers of the hierarchical "
                         "decomposition priced separately "
                         "(analysis/planner.slice_plans)")
    ap.add_argument("--no-flags", action="store_true",
                    help="search only the 5 parallel axes (skip sp/zero1/"
                         "offload toggles)")
    ap.add_argument("--top", type=int, default=10,
                    help="rows to print")
    ap.add_argument("--trace", type=int, default=0, metavar="K",
                    help="re-cost the top K points from their traced "
                         "collective schedules (lowers the step on "
                         "simulated host devices — slow for big models)")
    ap.add_argument("--verify-hbm", action="store_true",
                    help="memcheck-verify the winner (XLA compile-time "
                         "memory breakdown); walks down the ranking until "
                         "a point passes, so the proposal is never a "
                         "config memcheck rejects")
    ap.add_argument("--json", action="store_true",
                    help="one JSON line per ranked point")
    ap.add_argument("--markdown", action="store_true",
                    help="markdown table (PERF.md format)")
    ap.add_argument("--cp-crossover", action="store_true",
                    help="instead of planning, sweep cp degree and print "
                         "each cp flavor's predicted step time per ICI "
                         "generation, with the smallest cp degree where "
                         "the 2D mesh flavor wins (its crossover)")
    ap.add_argument("--cp-degrees", type=int, nargs="*", default=None,
                    metavar="CP", help="cp degrees to sweep with "
                         "--cp-crossover (default 2 4 8 16 32)")
    ap.add_argument("--tp-strategy-table", action="store_true",
                    help="instead of planning, sweep tp degree and print "
                         "each TP strategy x sync-mode's predicted step "
                         "and exposed-comm time per ICI generation, with "
                         "the best 2D factorization and the adaptive "
                         "resolution per degree")
    ap.add_argument("--tp-degrees", type=int, nargs="*", default=None,
                    metavar="TP", help="tp degrees to sweep with "
                         "--tp-strategy-table (default 2 4 8 16)")
    ap.add_argument("--validate-sweep", action="store_true",
                    help="score the cost model's rank agreement against "
                         "the measured SWEEP_r03-r05 rows instead of "
                         "planning")
    ap.add_argument("--fit", action="store_true",
                    help="with --validate-sweep: refit the calibration "
                         "constants from the rows first")
    args = ap.parse_args(argv)

    from picotron_tpu.analysis.cost_model import CostModel

    if args.validate_sweep:
        from picotron_tpu.analysis.calibration import (
            fit_calibration, load_measured_rows, rank_agreement,
        )

        points = load_measured_rows()
        if not points:
            print("no SWEEP_r*.jsonl rows found", file=sys.stderr)
            return 1
        model = CostModel(args.generation)
        if args.fit:
            model = CostModel(args.generation, fit_calibration(points))
        ra = rank_agreement(points, model)
        if args.json:
            print(json.dumps(ra))
        else:
            print(f"rank agreement vs measured sweeps "
                  f"({len(points)} rows):")
            for src, rho in ra["per_round"].items():
                print(f"  {src}: spearman {rho}")
            print(f"  pooled: {ra.get('pooled')}")
            for r in ra["rows"]:
                print(f"    {r['metric']:42s} measured "
                      f"{r['measured_tps_chip']:>9} predicted "
                      f"{r['predicted_tps_chip']:>9} tok/s/chip")
        return 0

    if args.cp_crossover:
        from picotron_tpu.analysis.cost_model import (
            GENERATIONS, cp_crossover, cp_crossover_table,
        )

        base = build_base_config(args)
        degrees = tuple(args.cp_degrees or (2, 4, 8, 16, 32))
        out = []
        for gen in GENERATIONS:
            m = CostModel(gen)
            out.append((gen, cp_crossover_table(m, base, degrees),
                        cp_crossover(m, base, degrees)))
        if args.json:
            for gen, rows, cross in out:
                print(json.dumps({"generation": gen, "rows": rows,
                                  "crossover_cp": cross}), flush=True)
            return 0
        print(f"cp-flavor crossover: {base.model.name} seq "
              f"{base.training.seq_length} (tp={base.distributed.tp_size},"
              f" '-' = flavor infeasible at that degree)")
        hdr = ("gen", "cp", "ring_ms", "ulysses_ms", "mesh_ms",
               "mesh_fact", "winner")
        print("  " + "  ".join(h.rjust(10) for h in hdr))
        for gen, rows, cross in out:
            for r in rows:
                cells = (gen, r["cp"], r["ring_ms"],
                         r.get("ulysses_ms") or "-",
                         r.get("mesh_ms") or "-",
                         r.get("mesh_factorization", "-"), r["winner"])
                print("  " + "  ".join(str(c).rjust(10) for c in cells))
        for gen, _, cross in out:
            print(f"predicted mesh crossover on {gen}: "
                  + (f"cp={cross}" if cross else
                     "never (within swept degrees)"))
        return 0

    if args.tp_strategy_table:
        from picotron_tpu.analysis.cost_model import (
            GENERATIONS, tp_strategy_table,
        )

        base = build_base_config(args)
        degrees = tuple(args.tp_degrees or (2, 4, 8, 16))
        out = [(gen, tp_strategy_table(CostModel(gen), base, degrees))
               for gen in GENERATIONS]
        if args.json:
            for gen, rows in out:
                print(json.dumps({"generation": gen, "rows": rows}),
                      flush=True)
            return 0
        print(f"TP strategy table: {base.model.name} seq "
              f"{base.training.seq_length} ('-' = strategy infeasible at "
              f"that degree; exposed_ms deltas vs megatron-sync)")
        hdr = ("gen", "tp", "megatron_ms", "deferred_ms", "row_ms",
               "2d_ms", "2d_mesh", "defer_dexp", "adaptive", "winner")
        print("  " + "  ".join(h.rjust(11) for h in hdr))
        for gen, rows in out:
            for r in rows:
                cells = (gen, r["tp"], r["megatron_ms"], r["deferred_ms"],
                         r["row_ms"], r.get("2d_ms", "-"),
                         r.get("mesh_factorization", "-"),
                         r["deferred_exposed_delta_ms"],
                         r["adaptive"], r["winner"])
                print("  " + "  ".join(str(c).rjust(11) for c in cells))
        return 0

    if not args.chips:
        ap.error("--chips is required (or use --validate-sweep)")

    from picotron_tpu.analysis.planner import best_point, plan, reprice_traced

    base = build_base_config(args)
    model = CostModel(args.generation)
    cap = args.hbm_gib if args.hbm_gib is not None else model.gen.hbm_gib
    points = plan(base, args.chips, model, flags=not args.no_flags,
                  hbm_gib=cap)
    if not points:
        print(f"no layout of {base.model.name} fits {args.chips}x"
              f"{args.generation} ({cap} GiB HBM) — try --hbm-gib, more "
              f"chips, or a smaller micro-batch", file=sys.stderr)
        return 1

    needs_devices = args.trace > 0 or args.verify_hbm
    if needs_devices:
        from picotron_tpu.mesh import force_host_device_count

        force_host_device_count(args.chips)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.trace > 0:
        points = reprice_traced(points, model, top_k=args.trace)
    winner = best_point(points, verify=args.verify_hbm, hbm_gib=cap,
                        model=model)
    if winner is None:
        print("every candidate failed HBM verification; relax --hbm-gib "
              "or shrink the model/batch", file=sys.stderr)
        return 1

    slice_rows = []
    if args.slices and args.slices > 1:
        from picotron_tpu.analysis.planner import slice_plans

        slice_rows = slice_plans(winner.cfg, model, args.slices)

    if args.json:
        for p in points[:args.top]:
            print(json.dumps(p.as_dict()), flush=True)
        if args.slices and args.slices > 1:
            print(json.dumps({"slice_plans": slice_rows,
                              "winner": winner.label}), flush=True)
    else:
        n_all = len(points)
        print(f"layout planner: {base.model.name} seq "
              f"{base.training.seq_length} on {args.chips}x"
              f"{args.generation} — {n_all} HBM-feasible layouts, top "
              f"{min(args.top, n_all)}:")
        print(render_table(points, args.top, markdown=args.markdown))
        print()
        print(f"predicted fastest: {winner.label} "
              f"({winner.cost.as_dict()['predicted_step_ms']} ms/step, "
              f"{winner.cost.as_dict()['tokens_per_sec_per_chip']} "
              f"tok/s/chip)")
        print(f"  run it: {winner.overrides_line()}")
        if args.slices and args.slices > 1:
            print()
            if not slice_rows:
                print(f"slice planning: no DCN-tolerant axis of "
                      f"{winner.label} can absorb {args.slices} slices "
                      f"(dp and pp must be divisible by the slice count)")
            else:
                print(f"slice planning: {winner.label} over "
                      f"{args.slices} slices "
                      f"[{slice_rows[0]['generation']}]:")
                hdr = ("axis", "crossing_terms", "dcn_bytes", "dcn_ms",
                       "ici_ms", "total_comm_ms")
                print("  " + "  ".join(h.rjust(14) for h in hdr))
                for r in slice_rows:
                    cells = (r["axis"],
                             ",".join(r["crossing_terms"]) or "-",
                             r["dcn_bytes"], r["dcn_ms"], r["ici_ms"],
                             r["total_comm_ms"])
                    print("  " + "  ".join(str(c).rjust(14)
                                           for c in cells))
                best_ax = slice_rows[0]["axis"]
                print(f"  declare it: --override distributed.slices="
                      f"{args.slices} distributed.dcn_axes={best_ax}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
