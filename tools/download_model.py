#!/usr/bin/env python
"""Download a HuggingFace model's safetensors for `checkpoint.init_from_hf`.

TPU-native counterpart of the reference's `download_model` (ref:
picotron/utils.py:100-115, called from create_config.py:134): snapshots only
the weight/config/tokenizer files, then prints the directory to put in the
config's `checkpoint.init_from_hf` field. Unlike the reference, the weights
are actually LOADED as initial values by `load_hf_safetensors`
(picotron_tpu/checkpoint.py), not just used as shape templates.

Zero-egress pods (no outbound network) get a clear actionable error instead
of a hang: pre-download on a connected machine and ship the directory, or
point `init_from_hf` at any local safetensors checkout.

Usage:
    python tools/download_model.py HuggingFaceTB/SmolLM-1.7B [--out DIR]
"""

from __future__ import annotations

import argparse
import os
import sys


def download(model_name: str, out_dir: str | None = None) -> str:
    """Snapshot `model_name`'s safetensors + config + tokenizer into
    `out_dir` (default ./hf_models/<name>); returns the local directory."""
    # Absolute so the path written into checkpoint.init_from_hf keeps
    # working when training launches from a different cwd.
    out_dir = os.path.abspath(
        out_dir or os.path.join("hf_models", model_name.split("/")[-1]))
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:
        raise SystemExit(
            "huggingface_hub is not installed; install it or place the "
            "model's *.safetensors + config.json under a directory and set "
            "checkpoint.init_from_hf to that path."
        ) from e
    try:
        snapshot_download(
            model_name,
            local_dir=out_dir,
            allow_patterns=["*.safetensors", "*.safetensors.index.json",
                            "config.json", "tokenizer*", "*.model"],
        )
    except Exception as e:
        raise SystemExit(
            f"download of {model_name!r} failed ({type(e).__name__}: {e}).\n"
            f"On an air-gapped/zero-egress pod: run this tool on a connected "
            f"machine, copy {out_dir!r} over, and set "
            f"checkpoint.init_from_hf to it."
        ) from e
    return out_dir


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("model", help="HF hub id, e.g. HuggingFaceTB/SmolLM-1.7B")
    ap.add_argument("--out", default=None,
                    help="target directory (default hf_models/<name>)")
    args = ap.parse_args(argv)
    path = download(args.model, args.out)
    print(path)


if __name__ == "__main__":
    sys.exit(main())
