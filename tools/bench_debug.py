import sys, time
sys.path.insert(0, ".")
import jax, jax.numpy as jnp
from picotron_tpu.config import Config, DistributedConfig, ModelConfig, TrainingConfig, resolve_preset
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.parallel.api import init_sharded_state, make_train_step

preset = resolve_preset("SmolLM-360M")
cfg = Config(
    distributed=DistributedConfig(dp_size=1),
    model=ModelConfig(name="SmolLM-360M", **preset),
    training=TrainingConfig(seq_length=2048, micro_batch_size=4, gradient_accumulation_steps=1, remat=True),
)
cfg.validate()
menv = MeshEnv.from_config(cfg)
state = init_sharded_state(cfg, menv, jax.random.key(0))
step = make_train_step(cfg, menv)
toks = jax.random.randint(jax.random.key(1), (1, 4, 2049), 0, cfg.model.vocab_size)
sh = menv.batch_sharding()
batch = (jax.device_put(toks[..., :-1], sh), jax.device_put(toks[..., 1:], sh))

state, loss = step(state, batch)
jax.block_until_ready(state)
print("warm done")
for i in range(5):
    t0 = time.perf_counter()
    state, loss = step(state, batch)
    jax.block_until_ready(state)  # block on the FULL state, not just loss
    dt = time.perf_counter() - t0
    print(f"step {i}: {dt*1e3:.1f}ms  loss={float(loss):.3f}  tok/s={4*2048/dt:.0f}")
