#!/usr/bin/env python
"""Text generation CLI over a trained or imported checkpoint.

The reference is training-only; this is the inspect-what-you-trained path.

  # from an HF safetensors dir (tools/download_model.py or any HF export)
  python tools/generate.py --model SmolLM-360M --hf-dir ./hf_model \\
      --prompt "The capital of France is" --max-new-tokens 32

  # from a framework checkpoint (checkpoint.save_dir of a training run)
  python tools/generate.py --config runs/smoke/config.json \\
      --ckpt-dir ckpt --prompt-ids 12,7,99 --max-new-tokens 16

Zero-egress note: --prompt needs the model's tokenizer (transformers);
--prompt-ids takes raw token ids and needs nothing.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description="picotron-tpu generation")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--hf-dir", help="HF safetensors directory")
    src.add_argument("--ckpt-dir", help="framework checkpoint save_dir")
    ap.add_argument("--model", default=None,
                    help="model preset name (required with --hf-dir)")
    ap.add_argument("--config", default=None,
                    help="training config JSON (required with --ckpt-dir)")
    prompt = ap.add_mutually_exclusive_group(required=True)
    prompt.add_argument("--prompt", help="text (needs the HF tokenizer)")
    prompt.add_argument("--prompt-ids",
                        help="comma-separated raw token ids")
    ap.add_argument("--max-new-tokens", type=int, default=64)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--seed", type=int, default=0)
    # Memory envelope at target scale (a 7B checkpoint): fp32 params are
    # 28 GB and cannot decode on one 16 GB chip. --load-dtype bfloat16
    # restores straight into 13.5 GB (Orbax casts during restore; decode
    # computes in bf16 regardless, so outputs are unchanged); --tp N
    # additionally shards params + KV cache over N chips (~13.5/N GB + a
    # [L, B, S, Hkv/N, D] cache slice per chip).
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel decode over this many chips "
                         "(training TP shardings; GSPMD inserts the "
                         "collectives)")
    ap.add_argument("--load-dtype", default=None,
                    choices=["float32", "bfloat16"],
                    help="dtype to restore checkpoint params in "
                         "(bfloat16 halves load memory; decode computes "
                         "bf16 either way)")
    args = ap.parse_args()

    from picotron_tpu.config import (
        Config, ModelConfig, load_config, resolve_hf_name, resolve_preset,
    )
    from picotron_tpu.generate import generate

    load_dtype = (jnp.bfloat16 if args.load_dtype == "bfloat16"
                  else jnp.float32 if args.load_dtype == "float32" else None)
    if args.hf_dir:
        if not args.model:
            ap.error("--hf-dir needs --model <preset>")
        from picotron_tpu.checkpoint import load_hf_safetensors

        cfg_m = ModelConfig(name=args.model, **resolve_preset(args.model))
        params = load_hf_safetensors(args.hf_dir, cfg_m,
                                     dtype=load_dtype or jnp.float32)
    else:
        if not args.config:
            ap.error("--ckpt-dir needs --config <json>")
        cfg: Config = load_config(args.config)
        cfg_m = cfg.model
        from picotron_tpu.checkpoint import restore_params_only

        params, _ = restore_params_only(cfg, args.ckpt_dir,
                                        dtype=load_dtype)
    if args.tp > 1:
        from picotron_tpu.generate import place_for_decode

        params = place_for_decode(params, cfg_m, tp=args.tp)

    tokenizer = None
    if args.prompt is not None:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(resolve_hf_name(cfg_m.name))
        ids = tokenizer(args.prompt, return_tensors="np")["input_ids"]
    else:
        ids = [[int(t) for t in args.prompt_ids.split(",")]]
    ids = jnp.asarray(ids, jnp.int32)

    eos = (tokenizer.eos_token_id if tokenizer is not None else None)
    out = generate(params, cfg_m, ids, args.max_new_tokens,
                   temperature=args.temperature, top_k=args.top_k,
                   eos_token_id=eos, key=jax.random.key(args.seed))
    out = jax.device_get(out)
    if tokenizer is not None:
        print(tokenizer.decode(out[0], skip_special_tokens=True))
    else:
        print(",".join(str(int(t)) for t in out[0]))


if __name__ == "__main__":
    main()
