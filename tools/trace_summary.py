#!/usr/bin/env python
"""Summarize a jax.profiler trace into a per-op device-time table.

The profiler story (SURVEY §5): `bench.py --profile DIR` or the training
config's `logging.profile_dir` capture an xprof trace; TensorBoard renders
it, but a pod/CI box usually has no browser — this prints the numbers that
matter on stdout:

  python tools/trace_summary.py /tmp/trace [--top 25] [--steps N]

Reads the newest `*.trace.json.gz` under the directory, aggregates TPU
device-side event durations by op name, and prints total ms (optionally
/step with --steps) plus the share of device time. Top-level annotations
(jit_step, the scan whiles, checkpoint/remat regions) appear alongside leaf
fusions — read it hierarchically: `while.*` rows are the layer scans,
`checkpoint.*` rows are remat recompute, `fusion.*`/`*dynamic-update-slice*`
rows are leaf kernels inside them.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys


def load_events(trace_dir: str):
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                  recursive=True),
        key=os.path.getmtime)
    if not paths:
        sys.exit(f"no *.trace.json.gz under {trace_dir} — produce one with "
                 f"`python bench.py --profile {trace_dir}` or a training "
                 f"config's logging.profile_dir")
    with gzip.open(paths[-1]) as f:
        data = json.load(f)
    return data.get("traceEvents", [])


def summarize(events, device_substr: str = "TPU"):
    pids = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name", "")
    device_pids = {p for p, n in pids.items() if device_substr in n}
    if not device_pids:  # CPU-backend traces: fall back to every process
        device_pids = set(pids)
    total_by_name = collections.Counter()
    for e in events:
        if (e.get("ph") == "X" and "dur" in e
                and e.get("pid") in device_pids):
            total_by_name[e["name"]] += e["dur"]
    return total_by_name, {p: pids[p] for p in device_pids}


def main() -> None:
    ap = argparse.ArgumentParser(description="xprof trace op summary")
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--steps", type=int, default=None,
                    help="divide durations by N to report per-step ms")
    ap.add_argument("--device", default="TPU",
                    help="substring selecting device process rows")
    ap.add_argument("--markdown", action="store_true",
                    help="emit the table as GitHub markdown — the format "
                         "PERF.md commits headline-step breakdowns in "
                         "(capture with `bench.py --profile DIR "
                         "--profile-steps 1`, then summarize with "
                         "--steps 1 --markdown)")
    args = ap.parse_args()

    totals, procs = summarize(load_events(args.trace_dir), args.device)
    if not totals:
        sys.exit("no device events found in the trace")
    grand = sum(totals.values())
    div = args.steps or 1
    unit = "ms/step" if args.steps else "ms total"
    if args.markdown:
        print(f"| share | {unit} | op |")
        print("|---|---|---|")
        for name, d in totals.most_common(args.top):
            print(f"| {d / grand * 100:.1f}% | {d / 1e3 / div:.2f} "
                  f"| `{name[:90]}` |")
        return
    print(f"device processes: {sorted(set(procs.values()))}")
    print(f"{'share':>6}  {unit:>12}  op")
    for name, d in totals.most_common(args.top):
        print(f"{d / grand * 100:5.1f}%  {d / 1e3 / div:12.2f}  {name[:90]}")


if __name__ == "__main__":
    main()
