#!/usr/bin/env python
"""Telemetry -> Chrome-trace converter + trace self-checker.

Two modes:

* Convert: turn a `telemetry.jsonl` stream (picotron_tpu/telemetry; the
  per-host event file next to the checkpoints) into Chrome trace-event
  JSON loadable by Perfetto / chrome://tracing. Phase events become
  complete spans — train-loop phases on the train lane, serve request
  phases (queue_wait/prefill/handoff/decode, with their request ids) on
  the serve lane — and resilience events (chaos, guard, rollback,
  preemption, watchdog, resize, recompile, sentinel alerts) become
  instants, so one timeline shows compute, comm phases, and faults
  together. Rotated streams (`telemetry.jsonl.1`, logging.telemetry_max_mb)
  are read oldest-first. Note the in-process flightdeck tracer
  (logging.trace_dir) exports richer traces — per-op MPMD tick spans
  never hit the JSONL — this converter is the post-hoc fallback for
  runs that only kept their telemetry stream.

* Validate (`--validate`): self-check a trace file — monotonic
  timestamps, balanced B/E begin/end events, pid/tid presence and
  type consistency, non-negative X durations — exiting nonzero on any
  violation. Wired as a tier-1 subprocess smoke (tests/test_flightdeck)
  like the shardcheck gates.

Usage:

  python tools/trace_export.py RUN_DIR_OR_JSONL -o trace.json
  python tools/trace_export.py --validate trace.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from picotron_tpu.telemetry import (  # noqa: E402
    _INSTANT_KINDS, _SERVE_PHASES,
)
from picotron_tpu.telemetry.flightdeck.tracer import (  # noqa: E402
    TID_SERVE, TID_TRAIN,
)
from picotron_tpu.telemetry.sinks import jsonl_segments  # noqa: E402

_VALID_PH = frozenset("XBEiICMsnftPNODabevR")


def resolve_jsonl(path: str) -> str:
    if os.path.isdir(path):
        cand = os.path.join(path, "telemetry.jsonl")
        if not os.path.exists(cand):
            raise FileNotFoundError(f"no telemetry.jsonl under {path}")
        return cand
    return path


def load_events(path: str) -> list[dict]:
    """All events of a possibly-rotated stream, oldest segment first."""
    events = []
    for seg in jsonl_segments(path):
        with open(seg) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail of a killed run
                if isinstance(ev, dict):
                    events.append(ev)
    return events


def convert(events: list[dict], pid: int = 0) -> dict:
    """Telemetry events -> Chrome trace document. Wall-clock `ts`
    anchors the timeline (zeroed at the stream's first event)."""
    ts0 = min((e["ts"] for e in events
               if isinstance(e.get("ts"), (int, float))), default=0.0)
    out: list[dict] = [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": TID_TRAIN,
         "ts": 0, "args": {"name": "train"}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": TID_SERVE,
         "ts": 0, "args": {"name": "serve"}},
    ]
    spans: list[dict] = []
    for e in events:
        kind = e.get("kind")
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if kind in ("phase", "compile", "pp_bubble"):
            secs = e.get("secs")
            if not isinstance(secs, (int, float)):
                continue
            phase = e.get("phase") or kind
            tid = TID_SERVE if phase in _SERVE_PHASES else TID_TRAIN
            args = {k: e[k] for k in ("step", "id", "ids", "tokens")
                    if e.get(k) is not None}
            # the phase event is stamped at phase END; back out the start
            spans.append({"name": phase, "ph": "X", "pid": pid,
                          "tid": tid, "ts": (ts - secs - ts0) * 1e6,
                          "dur": max(secs, 0.0) * 1e6,
                          **({"args": args} if args else {})})
        elif kind in _INSTANT_KINDS:
            args = {k: v for k, v in e.items()
                    if k not in ("ts", "kind")
                    and isinstance(v, (int, float, str, bool))}
            spans.append({"name": kind, "ph": "i", "s": "p", "pid": pid,
                          "tid": TID_TRAIN, "ts": (ts - ts0) * 1e6,
                          **({"args": args} if args else {})})
    spans.sort(key=lambda ev: ev["ts"])
    return {"traceEvents": out + spans, "displayTimeUnit": "ms"}


def validate(path: str) -> list[str]:
    """Self-check a Chrome-trace JSON; returns violation strings."""
    errors: list[str] = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"unreadable trace: {e}"]
    events = doc.get("traceEvents") if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        return ["trace has no traceEvents list"]
    last_ts: dict[tuple, float] = {}
    stacks: dict[tuple, list] = {}
    prev_global = None
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _VALID_PH:
            errors.append(f"event {i}: invalid ph {ph!r}")
            continue
        if ph == "M":
            continue
        pid, tid, ts = ev.get("pid"), ev.get("tid"), ev.get("ts")
        if not isinstance(pid, int) or not isinstance(tid, int):
            errors.append(f"event {i} ({ev.get('name')!r}): "
                          f"pid/tid must be integers, got "
                          f"pid={pid!r} tid={tid!r}")
            continue
        if not isinstance(ts, (int, float)):
            errors.append(f"event {i} ({ev.get('name')!r}): missing ts")
            continue
        if prev_global is not None and ts < prev_global - 1e-6:
            errors.append(f"event {i} ({ev.get('name')!r}): ts {ts} "
                          f"not monotonic (prev {prev_global})")
        prev_global = ts
        lane = (pid, tid)
        if ts < last_ts.get(lane, float("-inf")) - 1e-6:
            errors.append(f"event {i}: ts rewinds on lane {lane}")
        last_ts[lane] = ts
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"event {i} ({ev.get('name')!r}): X event "
                              f"needs dur >= 0, got {dur!r}")
        elif ph == "B":
            stacks.setdefault(lane, []).append((i, ev.get("name")))
        elif ph == "E":
            stack = stacks.get(lane) or []
            if not stack:
                errors.append(f"event {i}: E without matching B on "
                              f"lane {lane}")
            else:
                _, bname = stack.pop()
                ename = ev.get("name")
                if ename is not None and ename != bname:
                    errors.append(f"event {i}: E name {ename!r} does "
                                  f"not match open B {bname!r}")
    for lane, stack in stacks.items():
        for i, name in stack:
            errors.append(f"event {i} ({name!r}): B never closed on "
                          f"lane {lane}")
    return errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="telemetry.jsonl -> Chrome trace, or --validate a "
                    "trace file")
    ap.add_argument("path", help="telemetry.jsonl / run dir (convert "
                    "mode) or a trace JSON (--validate)")
    ap.add_argument("-o", "--output", default=None,
                    help="output trace path (convert mode; default "
                         "<input dir>/trace.json)")
    ap.add_argument("--validate", action="store_true",
                    help="self-check a trace file instead of converting")
    ap.add_argument("--pid", type=int, default=0,
                    help="process id to stamp on converted events")
    args = ap.parse_args(argv)

    if args.validate:
        errors = validate(args.path)
        if errors:
            for e in errors[:50]:
                print(f"TRACE VIOLATION: {e}", file=sys.stderr)
            print(f"{len(errors)} violation(s) in {args.path}",
                  file=sys.stderr)
            return 1
        with open(args.path) as f:
            doc = json.load(f)
        events = doc.get("traceEvents") if isinstance(doc, dict) else doc
        lanes = {(e.get("pid"), e.get("tid")) for e in events
                 if e.get("ph") != "M"}
        print(f"OK: {len(events)} events across {len(lanes)} lane(s) "
              f"in {args.path}")
        return 0

    src = resolve_jsonl(args.path)
    events = load_events(src)
    if not events:
        print(f"no events in {src}", file=sys.stderr)
        return 1
    doc = convert(events, pid=args.pid)
    out = args.output or os.path.join(os.path.dirname(src) or ".",
                                      "trace.json")
    with open(out, "w") as f:
        json.dump(doc, f)
    n = len(doc["traceEvents"])
    print(f"{n} trace events -> {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
