#!/usr/bin/env python
"""Does the trainer actually survive the failure you fear? — scenario runs.

Each named scenario runs a short CPU training job under a chaos spec
(picotron_tpu/resilience/chaos.py), plays external supervisor (restart on
the resilience exit codes, with the fault disabled on restart — the way a
real resubmission does not re-live a preemption), and verifies recovery:
the run must reach EXIT 0 within the restart budget, its log must show the
resilience mechanism actually engaged, and the final checkpoint's step and
trained_tokens must MATCH a fault-free baseline run of the same config —
i.e. the failure cost retries/restarts, not training progress.

Scenarios (the runtime-failure matrix README "Fault tolerance" documents):

  sigterm       preemption mid-run -> emergency ckpt + exit 75 -> resume
  ckpt_io       transient checkpoint-write I/O errors -> absorbed by retry
  nan_skip      NaN gradients, guard_policy=skip -> batch dropped in-step
  nan_rollback  NaN gradients, guard_policy=rollback -> restore + skip data
  data_stall    stuck data producer -> watchdog exit 77 -> resume
  ckpt_corrupt_bitflip
                newest committed checkpoint bit-flipped on disk, then
                SIGKILL -> restart falls back to the prior verified step
                (manifest verification + lineage walk); ckpt_doctor must
                flag exactly the injected-corrupt step
  dp_resize     elastic scale-out: dp=2 run SIGKILLed mid-training,
                re-stamped to dp=1 offline (tools/elastic_resize.py),
                killed again, then restored into a dp=4 mesh via
                checkpoint.elastic — constant global batch throughout,
                final step/tokens AND the per-step loss trajectory must
                match the fault-free dp=2 baseline, and the resize
                seconds must land in the `resize` goodput category
  pp_resize     elastic pipeline resize: pp=2 MPMD run SIGKILLed,
                re-stamped to pp=1 offline (tools/elastic_resize.py
                --pp), killed again, then restored into a pp=2 MPMD
                mesh via checkpoint.elastic — same loss-parity /
                resize-booking bar as dp_resize, plus the PR-9 prover
                pins every rebuilt stage program compiles exactly once
  slice_lost    whole-slice loss on a 2-slice job running the
                hierarchical dp gradient reduction: slice_lost@3 kills
                the pod with the lost slice named in the log, the store
                is re-stamped single-slice offline (tools/
                elastic_resize.py --slices 1), and the surviving chips
                finish at dp=1 via checkpoint.elastic — final
                step/tokens and per-step losses match the single-slice
                baseline, resize booked to the goodput ledger
  mpmd_sigterm  mid-schedule faults on the MPMD executor: SIGTERM at a
                named (stage, tick, op) drains the schedule walk to the
                step boundary (emergency ckpt, exit 75, zero replayed
                steps on resume); a forced mid-schedule hang is
                watchdog-reported naming the live (stage, tick, op)
  serve_engine_dead
                kill 1 of 2 serving replicas mid-burst (chaos
                engine_dead@REQ in the fleet dispatch loop): the
                survivor finishes EVERY request with tokens
                bit-identical to a fault-free single-engine oracle
                (temperature > 0 — the sampling-key fold is the
                mechanism), zero leaked blocks on the survivor pool, a
                serve_engine_dead postmortem, deterministic on repeat
  serve_overload
                burst a 1-slot engine with deadline'd requests: the
                shed set is a deterministic function of the trace
                (virtual clock), admitted requests' tokens match the
                no-deadline run bit-for-bit, every admitted queue wait
                respects the deadline, and the shed seconds land in
                the telemetry ledger's `shed` (badput) category

Usage:

  python tools/chaos.py --list
  python tools/chaos.py --scenario sigterm
  python tools/chaos.py --all          # exit 0 iff every scenario recovers

Long by design (each scenario is several full trainer subprocesses);
the test tier marks these `slow`.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Callable, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from picotron_tpu.resilience import (  # noqa: E402
    EXIT_PREEMPTED, EXIT_WATCHDOG,
)

STEPS = 6  # total_train_steps for every scenario (fault lands mid-run)


@dataclass
class Scenario:
    chaos: str                      # resilience.chaos spec for the first run
    marker: str                     # log regex proving the mechanism engaged
    note: str                       # one-line human description
    expect_exits: tuple = ()        # nonzero exits the supervisor restarts on
    max_restarts: int = 0           # restart budget (0 = must recover in-run)
    overrides: dict = field(default_factory=dict)  # config section updates
    # Assertion over save_dir right after the FIRST trainer exit (the
    # faulted state, before any supervised restart repairs it) — returns
    # an error string or None. The corruption scenario inspects the
    # really-corrupted store with ckpt_doctor here.
    check_after_fault: Optional[Callable] = None


SCENARIOS: dict[str, Scenario] = {
    "sigterm": Scenario(
        chaos=f"sigterm@{STEPS // 2}",
        expect_exits=(EXIT_PREEMPTED,),
        max_restarts=2,
        marker=r"emergency checkpoint ->",
        note="preemption mid-run: finish step, emergency ckpt, exit "
             f"{EXIT_PREEMPTED}, auto_resume",
        check_after_fault=lambda save_dir: _postmortem_matches(
            save_dir, reason="preempted", fault_step=STEPS // 2),
    ),
    "ckpt_io": Scenario(
        # Two injected write failures at the step-2 save; the default
        # 3-attempt retry absorbs them with no restart.
        chaos="ckpt_io@2x2",
        marker=r"\[retry\] checkpoint save",
        note="transient checkpoint-write I/O errors absorbed by "
             "retry-with-backoff",
    ),
    "nan_skip": Scenario(
        chaos=f"nan_grad@{STEPS // 2}",
        overrides={"resilience": {"guard_policy": "skip"}},
        marker=r"batch skipped",
        note="NaN gradients dropped in-step (optimizer state preserved), "
             "run continues",
    ),
    "nan_rollback": Scenario(
        chaos=f"nan_grad@{STEPS - 2}",
        overrides={"resilience": {"guard_policy": "rollback"}},
        marker=r"rolled back to step",
        note="NaN gradients: restore last durable ckpt, skip the poison "
             "data range, re-train",
        check_after_fault=lambda save_dir: _postmortem_matches(
            save_dir, reason="rollback", fault_step=STEPS - 2),
    ),
    "data_stall": Scenario(
        # Producer sleeps far longer than the watchdog timeout; the
        # watchdog dumps stacks and exits for the supervisor to restart.
        chaos=f"data_stall@{STEPS // 2}~120",
        expect_exits=(EXIT_WATCHDOG,),
        max_restarts=2,
        overrides={"dataset": {"num_workers": 2},
                   "resilience": {"watchdog_timeout": 5.0}},
        marker=r"\[watchdog\] no progress",
        note="stalled data producer: watchdog stack-dump + exit "
             f"{EXIT_WATCHDOG}, supervisor restart, auto_resume",
        check_after_fault=lambda save_dir: _postmortem_matches(
            save_dir, reason="watchdog", fault_step=STEPS // 2),
    ),
    "ckpt_corrupt_bitflip": Scenario(
        # The step-4 periodic save commits (manifest written), a byte in
        # its largest array payload is flipped on disk, then SIGKILL at
        # step 5 — a hard crash with a poisoned newest checkpoint. The
        # restart must NOT trust "finalized": verification fails step 4,
        # the lineage walk falls back to the verified step-2 save, and
        # the re-trained run still lands on the baseline's exact final
        # step/tokens. Saves are synchronous here so the commit (and the
        # corruption riding it) is ordered strictly before the kill.
        chaos=f"ckpt_corrupt_bitflip@{STEPS - 2},kill@{STEPS - 1}",
        expect_exits=(-signal.SIGKILL,),
        max_restarts=2,
        overrides={"checkpoint": {"async_save": False}},
        marker=r"failed verification",
        note="newest committed checkpoint bit-flipped, then SIGKILL: "
             "restart verifies, falls back to the prior verified step, "
             "re-trains to the baseline's final step",
        check_after_fault=lambda save_dir: _doctor_flags_exactly(
            save_dir, corrupt_step=STEPS - 2),
    ),
}


def run_dp_resize(workdir: str, verbose: bool = False) -> bool:
    """Elastic scale-out scenario — three topologies, one training run.

    Doesn't fit the Scenario dataclass (every leg needs its own config),
    so it is a custom runner registered next to SCENARIOS:

      baseline  dp=2 mbs=2 ga=1, fault-free, steps 1-6
      leg 1     dp=2, SIGKILL at step-3 begin (save @2 committed first)
      re-stamp  tools/elastic_resize.py --dp 1 rewrites the store offline
      leg 2     dp=1 mbs=2 ga=2, elastic OFF (the re-stamped store now IS
                dp=1), SIGKILL at step-5 begin (save @4 committed first)
      leg 3     dp=4 mbs=1 ga=1, checkpoint.elastic=true — the runtime
                resize path restores the dp=1-stamped step 4 into a dp=4
                mesh, trains to completion

    Global batch is 4 in every leg (2x2x1 = 2x1x2 = 1x4x1), so the loss
    trajectory is the baseline's modulo fp32 reduction order — compared
    per-step with tight tolerances. The resize must be booked: `resize`
    seconds and an `elastic_resize` event in the telemetry stream."""
    import numpy as np

    fail = lambda msg: (print(f"[chaos-cli] dp_resize: FAIL — {msg}"),  # noqa: E731
                        False)[1]

    def leg_config(ckpt_dir: str, *, dp: int, mbs: int, ga: int,
                   chaos_spec: str = "", elastic: bool = False) -> dict:
        cfg = scenario_config(os.path.dirname(ckpt_dir), chaos_spec,
                              {"checkpoint": {"async_save": False}})
        cfg["distributed"]["dp_size"] = dp
        cfg["training"]["micro_batch_size"] = mbs
        cfg["training"]["gradient_accumulation_steps"] = ga
        cfg["checkpoint"]["save_dir"] = ckpt_dir
        if elastic:
            cfg["checkpoint"]["elastic"] = True
        return cfg

    def run_leg(cfg: dict, cfg_name: str, leg_dir: str) -> int:
        cfg_path = os.path.join(leg_dir, cfg_name)
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        return _run_trainer(cfg_path, os.path.join(leg_dir, "run.log"), {})

    def step_losses(jsonl_path: str) -> dict:
        losses = {}
        with open(jsonl_path) as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn final line of a killed leg
                if ev.get("kind") == "step" and "loss" in ev:
                    losses[ev["step"]] = ev["loss"]  # last wins (replay)
        return losses

    # Fault-free dp=2 baseline: the trajectory every leg must stay on.
    base_dir = os.path.join(workdir, "baseline")
    os.makedirs(base_dir, exist_ok=True)
    base_ckpt = os.path.join(base_dir, "ckpt")
    rc = run_leg(leg_config(base_ckpt, dp=2, mbs=2, ga=1),
                 "config.json", base_dir)
    if rc != 0:
        return fail(f"baseline run exited {rc}")
    base_meta = _final_meta(base_ckpt)

    fault_dir = os.path.join(workdir, "fault")
    os.makedirs(fault_dir, exist_ok=True)
    ckpt_dir = os.path.join(fault_dir, "ckpt")

    # Leg 1: dp=2, killed at step-3 begin; the sync save @2 is durable.
    rc = run_leg(leg_config(ckpt_dir, dp=2, mbs=2, ga=1,
                            chaos_spec=f"kill@{STEPS // 2}"),
                 "config_dp2.json", fault_dir)
    if rc != -signal.SIGKILL:
        return fail(f"leg 1 (dp=2) exited {rc}, expected "
                    f"{-signal.SIGKILL} (SIGKILL)")

    # Offline re-stamp: the store becomes a dp=1 checkpoint (constant
    # global batch -> mbs 2 x ga 2), manifest re-committed.
    resize_log = os.path.join(fault_dir, "resize.log")
    with open(resize_log, "ab") as log:
        rc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "elastic_resize.py"),
             ckpt_dir, "--dp", "1"],
            stdout=log, stderr=subprocess.STDOUT, timeout=120).returncode
    if rc != 0:
        return fail(f"tools/elastic_resize.py --dp 1 exited {rc} "
                    f"(see {resize_log})")

    # Leg 2: dp=1, elastic OFF — restoring the re-stamped store must need
    # no special config. Killed at step-5 begin; sync save @4 durable.
    rc = run_leg(leg_config(ckpt_dir, dp=1, mbs=2, ga=2,
                            chaos_spec=f"kill@{STEPS - 1}"),
                 "config_dp1.json", fault_dir)
    if rc != -signal.SIGKILL:
        return fail(f"leg 2 (dp=1) exited {rc}, expected "
                    f"{-signal.SIGKILL} (SIGKILL)")

    # Leg 3: dp=4 with checkpoint.elastic — the runtime resize path
    # restores the dp=1-stamped step 4 into a dp=4 mesh and finishes.
    rc = run_leg(leg_config(ckpt_dir, dp=4, mbs=1, ga=1, elastic=True),
                 "config_dp4.json", fault_dir)
    if rc != 0:
        return fail(f"leg 3 (dp=4, elastic) exited {rc}, expected 0")

    with open(os.path.join(fault_dir, "run.log")) as f:
        log_text = f.read()
    if verbose:
        print(log_text)
    if not re.search(r"elastic resize:", log_text):
        return fail("marker /elastic resize:/ absent from the leg-3 log")

    meta = _final_meta(ckpt_dir)
    for key in ("step", "trained_tokens"):
        if meta[key] != base_meta[key]:
            return fail(f"final {key} {meta[key]} != fault-free baseline "
                        f"{base_meta[key]}")

    # Loss-trajectory parity: same global batch, same data order -> the
    # only legitimate difference across dp=2/1/4 is fp32 reduction order.
    base_losses = step_losses(os.path.join(base_ckpt, "telemetry.jsonl"))
    fault_losses = step_losses(os.path.join(ckpt_dir, "telemetry.jsonl"))
    if set(fault_losses) != set(base_losses):
        return fail(f"step sets differ: fault {sorted(fault_losses)} vs "
                    f"baseline {sorted(base_losses)}")
    steps = sorted(base_losses)
    bl = np.array([base_losses[s] for s in steps])
    fl = np.array([fault_losses[s] for s in steps])
    if not np.allclose(fl, bl, rtol=1e-3, atol=1e-4):
        return fail(f"loss trajectory diverged from baseline: "
                    f"{list(zip(steps, fl.tolist(), bl.tolist()))}")

    # The resize must be booked, not just survived.
    import telemetry_report

    summary = telemetry_report.summarize(telemetry_report.load_events(
        os.path.join(ckpt_dir, "telemetry.jsonl")))
    if summary["categories"].get("resize", 0.0) <= 0.0:
        return fail(f"no `resize` seconds in the goodput categories "
                    f"({summary['categories']})")
    if not summary.get("resize", {}).get("events"):
        return fail("no elastic_resize event in the telemetry stream")

    print(f"[chaos-cli] dp_resize: OK — dp 2->1 (offline re-stamp) ->4 "
          f"(runtime elastic), final step {meta['step']} / "
          f"{meta['trained_tokens']} tokens and loss trajectory match "
          f"baseline; resize booked "
          f"{summary['categories']['resize']:.3f}s")
    return True


def run_pp_resize(workdir: str, verbose: bool = False) -> bool:
    """Elastic PIPELINE resize — the dp_resize story on the pp axis.

    pp does not enter the global batch (mbs x ga x dp x ep), so every leg
    keeps mbs=2 ga=2 dp=1 untouched; what changes is the stage layout:

      baseline  pp=2 MPMD (per-stage programs), fault-free, steps 1-6
      leg 1     pp=2 MPMD, SIGKILL at step-3 begin (sync save @2 durable)
      re-stamp  tools/elastic_resize.py --pp 1 rewrites the store offline
                (even split: debug-tiny's 4 layers pad identically at
                pp=1 and pp=2, so the stack is shared — metadata only)
      leg 2     pp=1, the plain SPMD executor (config forbids MPMD at
                pp=1), elastic OFF — the re-stamped store simply IS a
                pp=1 checkpoint. SIGKILL at step-5 begin (save @4)
      leg 3     pp=2 MPMD again, checkpoint.elastic=true — the runtime
                elastic path restores the pp=1-stamped step 4 into a
                pp=2 mesh; the executor rebuilds stage programs and the
                schedule table from config and trains to completion

    Same acceptance bar as dp_resize (per-step loss parity vs baseline,
    final step/tokens equal, resize seconds + event booked) plus the
    MPMD-specific pin: the PR-9 prover re-proves the rebuilt pp=2 stage
    programs compile exactly once after the resize."""
    import numpy as np

    fail = lambda msg: (print(f"[chaos-cli] pp_resize: FAIL — {msg}"),  # noqa: E731
                        False)[1]

    def leg_config(ckpt_dir: str, *, pp: int, chaos_spec: str = "",
                   elastic: bool = False) -> dict:
        cfg = scenario_config(os.path.dirname(ckpt_dir), chaos_spec,
                              {"checkpoint": {"async_save": False}})
        cfg["distributed"].update(dp_size=1, tp_size=1, pp_size=pp)
        cfg["training"]["micro_batch_size"] = 2
        cfg["training"]["gradient_accumulation_steps"] = 2
        if pp > 1:
            cfg["pipeline"] = {"executor": "mpmd"}
        cfg["checkpoint"]["save_dir"] = ckpt_dir
        if elastic:
            cfg["checkpoint"]["elastic"] = True
        return cfg

    def run_leg(cfg: dict, cfg_name: str, leg_dir: str) -> tuple[int, str]:
        cfg_path = os.path.join(leg_dir, cfg_name)
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        return (_run_trainer(cfg_path, os.path.join(leg_dir, "run.log"),
                             {}), cfg_path)

    # Fault-free pp=2 MPMD baseline: the trajectory every leg must hold.
    base_dir = os.path.join(workdir, "baseline")
    os.makedirs(base_dir, exist_ok=True)
    base_ckpt = os.path.join(base_dir, "ckpt")
    rc, _ = run_leg(leg_config(base_ckpt, pp=2), "config.json", base_dir)
    if rc != 0:
        return fail(f"baseline run (pp=2 mpmd) exited {rc}")
    base_meta = _final_meta(base_ckpt)

    fault_dir = os.path.join(workdir, "fault")
    os.makedirs(fault_dir, exist_ok=True)
    ckpt_dir = os.path.join(fault_dir, "ckpt")

    # Leg 1: pp=2 MPMD, killed at step-3 begin; the sync save @2 durable.
    rc, _ = run_leg(leg_config(ckpt_dir, pp=2,
                               chaos_spec=f"kill@{STEPS // 2}"),
                    "config_pp2.json", fault_dir)
    if rc != -signal.SIGKILL:
        return fail(f"leg 1 (pp=2) exited {rc}, expected "
                    f"{-signal.SIGKILL} (SIGKILL)")

    # Offline re-stamp: the store becomes a pp=1 checkpoint. Pure-pp, so
    # the batch plan is untouched; the tool verifies the padded layer
    # stacks match before mutating anything.
    resize_log = os.path.join(fault_dir, "resize.log")
    with open(resize_log, "ab") as log:
        rc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "elastic_resize.py"),
             ckpt_dir, "--pp", "1"],
            stdout=log, stderr=subprocess.STDOUT, timeout=120).returncode
    if rc != 0:
        return fail(f"tools/elastic_resize.py --pp 1 exited {rc} "
                    f"(see {resize_log})")

    # Leg 2: pp=1 (SPMD — the executor fence requires pp>=2 for MPMD),
    # elastic OFF: the re-stamped store needs no special config. Killed
    # at step-5 begin; sync save @4 durable.
    rc, _ = run_leg(leg_config(ckpt_dir, pp=1,
                               chaos_spec=f"kill@{STEPS - 1}"),
                    "config_pp1.json", fault_dir)
    if rc != -signal.SIGKILL:
        return fail(f"leg 2 (pp=1) exited {rc}, expected "
                    f"{-signal.SIGKILL} (SIGKILL)")

    # Leg 3: pp=2 MPMD with checkpoint.elastic — the runtime elastic path
    # restores the pp=1-stamped step 4 into a pp=2 mesh; stage programs
    # and the schedule table rebuild from config at startup.
    rc, cfg3_path = run_leg(leg_config(ckpt_dir, pp=2, elastic=True),
                            "config_pp2_elastic.json", fault_dir)
    if rc != 0:
        return fail(f"leg 3 (pp=2, elastic) exited {rc}, expected 0")

    with open(os.path.join(fault_dir, "run.log")) as f:
        log_text = f.read()
    if verbose:
        print(log_text)
    if not re.search(r"elastic resize:", log_text):
        return fail("marker /elastic resize:/ absent from the leg-3 log")

    meta = _final_meta(ckpt_dir)
    for key in ("step", "trained_tokens"):
        if meta[key] != base_meta[key]:
            return fail(f"final {key} {meta[key]} != fault-free baseline "
                        f"{base_meta[key]}")

    # Loss-trajectory parity: identical global batch and data order; the
    # only legitimate pp=2-MPMD / pp=1-SPMD difference is fp32 reduction
    # order (the parity bar test_mpmd pins much tighter per-executor).
    def step_losses(jsonl_path: str) -> dict:
        losses = {}
        with open(jsonl_path) as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn final line of a killed leg
                if ev.get("kind") == "step" and "loss" in ev:
                    losses[ev["step"]] = ev["loss"]  # last wins (replay)
        return losses

    base_losses = step_losses(os.path.join(base_ckpt, "telemetry.jsonl"))
    fault_losses = step_losses(os.path.join(ckpt_dir, "telemetry.jsonl"))
    if set(fault_losses) != set(base_losses):
        return fail(f"step sets differ: fault {sorted(fault_losses)} vs "
                    f"baseline {sorted(base_losses)}")
    steps = sorted(base_losses)
    bl = np.array([base_losses[s] for s in steps])
    fl = np.array([fault_losses[s] for s in steps])
    if not np.allclose(fl, bl, rtol=1e-3, atol=1e-4):
        return fail(f"loss trajectory diverged from baseline: "
                    f"{list(zip(steps, fl.tolist(), bl.tolist()))}")

    # The resize must be booked, not just survived.
    import telemetry_report

    summary = telemetry_report.summarize(telemetry_report.load_events(
        os.path.join(ckpt_dir, "telemetry.jsonl")))
    if summary["categories"].get("resize", 0.0) <= 0.0:
        return fail(f"no `resize` seconds in the goodput categories "
                    f"({summary['categories']})")
    if not summary.get("resize", {}).get("events"):
        return fail("no elastic_resize event in the telemetry stream")

    # Compile-once pin on the REBUILT stages: re-prove leg 3's config
    # (the post-resize pp=2 MPMD layout) in a fresh process — every stage
    # program must compile exactly once. 2 stages x fwd/bwd = 4 programs.
    prover = ("import json, sys\n"
              "from picotron_tpu.config import load_config\n"
              "from picotron_tpu.analysis.variants import "
              "prove_mpmd_stages\n"
              "rep = prove_mpmd_stages(load_config(sys.argv[1]))\n"
              "print('PROVE ' + json.dumps(rep.info['variants']))\n"
              "sys.exit(0 if rep.ok() else 1)\n")
    env = dict(os.environ)
    for k in ("PICOTRON_COORDINATOR", "PICOTRON_NUM_PROCESSES",
              "PICOTRON_PROCESS_ID"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    res = subprocess.run([sys.executable, "-c", prover, cfg3_path],
                         capture_output=True, text=True, timeout=600,
                         env=env)
    lines = [ln for ln in res.stdout.splitlines()
             if ln.startswith("PROVE ")]
    if res.returncode != 0 or not lines:
        return fail(f"post-resize stage prover exited {res.returncode}: "
                    f"{res.stdout[-500:]}{res.stderr[-500:]}")
    variants = json.loads(lines[-1][len("PROVE "):])
    if not variants.get("proven") or variants.get("programs") != 4:
        return fail(f"post-resize stages not proven compile-once: "
                    f"{variants}")

    print(f"[chaos-cli] pp_resize: OK — pp 2->1 (offline re-stamp) ->2 "
          f"(runtime elastic, MPMD rebuild), final step {meta['step']} / "
          f"{meta['trained_tokens']} tokens and loss trajectory match "
          f"baseline; resize booked "
          f"{summary['categories']['resize']:.3f}s; "
          f"{variants['programs']} rebuilt stage programs proven "
          f"compile-once")
    return True


def run_mpmd_sigterm(workdir: str, verbose: bool = False) -> bool:
    """Mid-schedule fault hardening on the MPMD executor — two legs.

    SIGTERM leg: `sigterm@3#2` lands the signal INSIDE the schedule walk
    at a named (stage, tick, op) of step 3 — the hardest place to die,
    with boundary buffers live and gradients half-accumulated. The
    record-only preemption handler means the walk drains to the step
    boundary, the emergency checkpoint persists a CLEAN step-3 state,
    exit 75, and the supervised restart resumes with ZERO replayed steps
    (telemetry stream is the witness).

    Hang leg: `hang@4~120#1` wedges the walk at tick 1 of step 4 for far
    longer than the watchdog timeout. The per-op heartbeat means the
    watchdog names the live (stage, tick, op) in its report — not a bare
    stack dump — then exits 77 for the supervisor; the restart resumes
    from the last periodic save (steps ARE replayed here: the hang, by
    design, persists nothing) and finishes at the baseline's step."""
    fail = lambda msg: (print(f"[chaos-cli] mpmd_sigterm: FAIL — {msg}"),  # noqa: E731
                        False)[1]

    def leg_config(ckpt_dir: str, chaos_spec: str,
                   overrides: dict) -> dict:
        cfg = scenario_config(os.path.dirname(ckpt_dir), chaos_spec,
                              {"checkpoint": {"async_save": False},
                               **overrides})
        cfg["distributed"].update(dp_size=1, tp_size=1, pp_size=2)
        cfg["training"]["micro_batch_size"] = 2
        cfg["training"]["gradient_accumulation_steps"] = 2
        cfg["pipeline"] = {"executor": "mpmd"}
        cfg["checkpoint"]["save_dir"] = ckpt_dir
        return cfg

    def run_leg(cfg: dict, leg_dir: str, extra_env: dict) -> int:
        cfg_path = os.path.join(leg_dir, "config.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        return _run_trainer(cfg_path, os.path.join(leg_dir, "run.log"),
                            extra_env)

    # Fault-free pp=2 MPMD baseline.
    base_dir = os.path.join(workdir, "baseline")
    os.makedirs(base_dir, exist_ok=True)
    base_ckpt = os.path.join(base_dir, "ckpt")
    rc = run_leg(leg_config(base_ckpt, "", {}), base_dir, {})
    if rc != 0:
        return fail(f"baseline run (pp=2 mpmd) exited {rc}")
    base_meta = _final_meta(base_ckpt)

    # ---- SIGTERM mid-schedule ------------------------------------------
    st_dir = os.path.join(workdir, "sigterm")
    os.makedirs(st_dir, exist_ok=True)
    st_ckpt = os.path.join(st_dir, "ckpt")
    st_cfg = leg_config(st_ckpt, f"sigterm@{STEPS // 2}#2", {})
    rc = run_leg(st_cfg, st_dir, {})
    if rc != EXIT_PREEMPTED:
        return fail(f"sigterm leg exited {rc}, expected {EXIT_PREEMPTED}")
    # Restart with injection disabled — the resubmission does not re-live
    # the preemption.
    rc = run_leg(st_cfg, st_dir, {"PICOTRON_CHAOS": ""})
    if rc != 0:
        return fail(f"sigterm-leg restart exited {rc}, expected 0")

    with open(os.path.join(st_dir, "run.log")) as f:
        st_log = f.read()
    if verbose:
        print(st_log)
    # The fault must really have landed mid-schedule, at the named tick.
    if not re.search(r"firing sigterm at schedule_tick step "
                     rf"{STEPS // 2} \(stage=\d+ tick=2 op=\w+", st_log):
        return fail("no mid-schedule sigterm firing (schedule_tick with "
                    "stage/tick/op) in the sigterm-leg log")
    if not re.search(r"emergency checkpoint ->", st_log):
        return fail("marker /emergency checkpoint ->/ absent — the drain "
                    "to the step boundary did not persist durable state")

    meta = _final_meta(st_ckpt)
    for key in ("step", "trained_tokens"):
        if meta[key] != base_meta[key]:
            return fail(f"sigterm leg final {key} {meta[key]} != baseline "
                        f"{base_meta[key]}")

    # Lossless resume: the emergency checkpoint carried the full step-3
    # state, so NO step number appears twice in the telemetry stream.
    import telemetry_report

    summary = telemetry_report.summarize(telemetry_report.load_events(
        os.path.join(st_ckpt, "telemetry.jsonl")))
    st = summary.get("steps") or {}
    if st.get("count") != STEPS or st.get("max") != STEPS:
        return fail(f"sigterm leg trained steps {st}, expected "
                    f"count=max={STEPS}")
    if st.get("replayed"):
        return fail(f"sigterm leg replayed {st['replayed']} step(s) — the "
                    f"mid-schedule preemption was supposed to drain to "
                    f"the boundary and lose nothing")

    # ---- forced hang mid-schedule --------------------------------------
    hg_dir = os.path.join(workdir, "hang")
    os.makedirs(hg_dir, exist_ok=True)
    hg_ckpt = os.path.join(hg_dir, "ckpt")
    hg_cfg = leg_config(
        hg_ckpt, f"hang@{STEPS - 2}~120#1",
        {"resilience": {"watchdog_timeout": 5.0}})
    rc = run_leg(hg_cfg, hg_dir, {})
    if rc != EXIT_WATCHDOG:
        return fail(f"hang leg exited {rc}, expected {EXIT_WATCHDOG}")
    rc = run_leg(hg_cfg, hg_dir, {"PICOTRON_CHAOS": ""})
    if rc != 0:
        return fail(f"hang-leg restart exited {rc}, expected 0")

    with open(os.path.join(hg_dir, "run.log")) as f:
        hg_log = f.read()
    if verbose:
        print(hg_log)
    # The watchdog report must NAME the wedged op, not just dump stacks.
    m = re.search(r"\[watchdog\] no progress .* last "
                  r"phase='pp_schedule stage=\d+ tick=\d+ op=\w+ mb=\d+'",
                  hg_log)
    if not m:
        return fail("watchdog report does not name the live "
                    "(stage, tick, op) — /pp_schedule stage=/ phase "
                    "absent from the hang-leg log")
    meta = _final_meta(hg_ckpt)
    for key in ("step", "trained_tokens"):
        if meta[key] != base_meta[key]:
            return fail(f"hang leg final {key} {meta[key]} != baseline "
                        f"{base_meta[key]}")

    print(f"[chaos-cli] mpmd_sigterm: OK — mid-schedule SIGTERM drained "
          f"to the step boundary (exit {EXIT_PREEMPTED}, 0 replayed "
          f"steps) and mid-schedule hang was watchdog-named "
          f"({m.group(0).split('last ')[-1]}); both legs finished at "
          f"baseline step {base_meta['step']}")
    return True


def run_slice_lost(workdir: str, verbose: bool = False) -> bool:
    """Whole-slice loss on a 2-slice job — THE failure mode multi-slice
    adds over a single pod. Custom runner (per-leg configs + an offline
    CLI step), registered next to SCENARIOS:

      baseline  dp=2 tp=2, single slice, fault-free, steps 1-6
      leg 1     dp=2 tp=2 slices=2 dcn_axes=dp — the hierarchical dp
                gradient reduction is live — slice_lost@3: SIGKILL with
                the slice named in the log; the sync save @2 is durable
                and records slices=2 in its manifest topology
      re-stamp  tools/elastic_resize.py --slices 1 rewrites the store as
                single-slice (placement metadata only; dp untouched)
      leg 2     dp=1 tp=2 (one surviving slice's worth of chips) with
                checkpoint.elastic=true: the dp 2->1 mismatch rides the
                runtime resize path at constant global batch, is booked
                to the `resize` goodput category, and trains to done

    Final step/tokens and the per-step loss trajectory must match the
    fault-free baseline — fp32 reduction order is the only legitimate
    difference (the hierarchical schedule reassociates the dp sum; the
    documented ~1e-7 band of parallel/hier_reduce.py sits far inside the
    rtol=1e-3 house tolerance)."""
    import numpy as np

    from picotron_tpu.resilience import elastic

    fail = lambda msg: (print(f"[chaos-cli] slice_lost: FAIL — {msg}"),  # noqa: E731
                        False)[1]

    def leg_config(ckpt_dir: str, *, dp: int, mbs: int, ga: int,
                   slices: int = 1, chaos_spec: str = "",
                   elastic_on: bool = False) -> dict:
        cfg = scenario_config(os.path.dirname(ckpt_dir), chaos_spec,
                              {"checkpoint": {"async_save": False}})
        cfg["distributed"]["dp_size"] = dp
        cfg["distributed"]["slices"] = slices
        if slices > 1:
            cfg["distributed"]["dcn_axes"] = "dp"
        cfg["training"]["micro_batch_size"] = mbs
        cfg["training"]["gradient_accumulation_steps"] = ga
        cfg["checkpoint"]["save_dir"] = ckpt_dir
        if elastic_on:
            cfg["checkpoint"]["elastic"] = True
        return cfg

    def run_leg(cfg: dict, cfg_name: str, leg_dir: str) -> int:
        cfg_path = os.path.join(leg_dir, cfg_name)
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        return _run_trainer(cfg_path, os.path.join(leg_dir, "run.log"), {})

    def step_losses(jsonl_path: str) -> dict:
        losses = {}
        with open(jsonl_path) as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn final line of a killed leg
                if ev.get("kind") == "step" and "loss" in ev:
                    losses[ev["step"]] = ev["loss"]  # last wins (replay)
        return losses

    def newest_step_dir(ckpt_dir: str) -> str:
        steps = sorted(
            int(m.group(1)) for d in os.listdir(ckpt_dir)
            if (m := re.fullmatch(r"step_(\d+)", d))
            and os.path.isdir(os.path.join(ckpt_dir, d, "state")))
        return os.path.join(ckpt_dir, f"step_{steps[-1]:08d}")

    # Fault-free single-slice baseline: the trajectory to stay on.
    base_dir = os.path.join(workdir, "baseline")
    os.makedirs(base_dir, exist_ok=True)
    base_ckpt = os.path.join(base_dir, "ckpt")
    rc = run_leg(leg_config(base_ckpt, dp=2, mbs=2, ga=1),
                 "config.json", base_dir)
    if rc != 0:
        return fail(f"baseline run exited {rc}")
    base_meta = _final_meta(base_ckpt)

    fault_dir = os.path.join(workdir, "fault")
    os.makedirs(fault_dir, exist_ok=True)
    ckpt_dir = os.path.join(fault_dir, "ckpt")

    # Leg 1: 2-slice run with the hierarchical dp reduction live, a
    # whole slice lost at step-3 begin; the sync save @2 is durable.
    rc = run_leg(leg_config(ckpt_dir, dp=2, mbs=2, ga=1, slices=2,
                            chaos_spec=f"slice_lost@{STEPS // 2}"),
                 "config_slices2.json", fault_dir)
    if rc != -signal.SIGKILL:
        return fail(f"leg 1 (slices=2) exited {rc}, expected "
                    f"{-signal.SIGKILL} (SIGKILL)")
    with open(os.path.join(fault_dir, "run.log")) as f:
        leg1_log = f.read()
    if "slice_lost: the slice hosting process" not in leg1_log:
        return fail("slice_lost firing (with the lost slice named) "
                    "absent from the leg-1 log")
    saved = elastic.saved_topology(newest_step_dir(ckpt_dir)) or {}
    if saved.get("slices") != 2:
        return fail(f"durable save records topology {saved}, expected "
                    f"slices=2 in its manifest")

    # Offline re-stamp: single-slice store (the survivors' shape).
    resize_log = os.path.join(fault_dir, "resize.log")
    with open(resize_log, "ab") as log:
        rc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "elastic_resize.py"),
             ckpt_dir, "--slices", "1"],
            stdout=log, stderr=subprocess.STDOUT, timeout=120).returncode
    if rc != 0:
        return fail(f"tools/elastic_resize.py --slices 1 exited {rc} "
                    f"(see {resize_log})")
    saved = elastic.saved_topology(newest_step_dir(ckpt_dir)) or {}
    if saved.get("slices", 1) != 1:
        return fail(f"re-stamped store still records {saved}")

    # Leg 2: one slice's worth of chips (dp=1), checkpoint.elastic — the
    # dp 2->1 mismatch reshards at restore time, booked as `resize`.
    rc = run_leg(leg_config(ckpt_dir, dp=1, mbs=2, ga=2, elastic_on=True),
                 "config_dp1.json", fault_dir)
    if rc != 0:
        return fail(f"leg 2 (dp=1, elastic) exited {rc}, expected 0")

    with open(os.path.join(fault_dir, "run.log")) as f:
        log_text = f.read()
    if verbose:
        print(log_text)
    if not re.search(r"elastic resize:", log_text):
        return fail("marker /elastic resize:/ absent from the leg-2 log")

    meta = _final_meta(ckpt_dir)
    for key in ("step", "trained_tokens"):
        if meta[key] != base_meta[key]:
            return fail(f"final {key} {meta[key]} != fault-free baseline "
                        f"{base_meta[key]}")

    base_losses = step_losses(os.path.join(base_ckpt, "telemetry.jsonl"))
    fault_losses = step_losses(os.path.join(ckpt_dir, "telemetry.jsonl"))
    if set(fault_losses) != set(base_losses):
        return fail(f"step sets differ: fault {sorted(fault_losses)} vs "
                    f"baseline {sorted(base_losses)}")
    steps = sorted(base_losses)
    bl = np.array([base_losses[s] for s in steps])
    fl = np.array([fault_losses[s] for s in steps])
    if not np.allclose(fl, bl, rtol=1e-3, atol=1e-4):
        return fail(f"loss trajectory diverged from baseline: "
                    f"{list(zip(steps, fl.tolist(), bl.tolist()))}")

    import telemetry_report

    summary = telemetry_report.summarize(telemetry_report.load_events(
        os.path.join(ckpt_dir, "telemetry.jsonl")))
    if summary["categories"].get("resize", 0.0) <= 0.0:
        return fail(f"no `resize` seconds in the goodput categories "
                    f"({summary['categories']})")
    if not summary.get("resize", {}).get("events"):
        return fail("no elastic_resize event in the telemetry stream")

    print(f"[chaos-cli] slice_lost: OK — 2-slice run lost a slice, "
          f"re-stamped --slices 1, finished at dp=1 via runtime elastic; "
          f"final step {meta['step']} / {meta['trained_tokens']} tokens "
          f"and loss trajectory match baseline; resize booked "
          f"{summary['categories']['resize']:.3f}s")
    return True


def _doctor_flags_exactly(save_dir: str, corrupt_step: int):
    """tools/ckpt_doctor.py over the faulted store must flag exactly the
    injected-corrupt step and pass the rest (the fsck half of the
    corruption acceptance criteria)."""
    import ckpt_doctor

    rows = ckpt_doctor.scan(save_dir)
    bad = [r["step"] for r in rows if r["verdict"] == "corrupt"]
    good = [r["step"] for r in rows
            if r["verdict"] in ("verified", "legacy")]
    if bad != [corrupt_step]:
        return (f"ckpt_doctor flagged corrupt steps {bad}, expected "
                f"exactly [{corrupt_step}] (rows: {rows})")
    if not good:
        return f"ckpt_doctor found no restorable step besides the corrupt one"
    return None


def _run_bench_fleet(leg_dir: str, extra_args: list,
                     telemetry: str | None = None) -> dict:
    """One `bench.py --serve --fleet` leg in a subprocess (2 simulated
    CPU devices, so replicas really live on distinct devices); returns
    the bench JSON row."""
    os.makedirs(leg_dir, exist_ok=True)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PICOTRON_PREFLIGHT"] = "0"
    env.pop("PICOTRON_CHAOS", None)  # the leg's --chaos is the only fault
    cmd = [sys.executable,
           os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "bench.py"),
           "--serve", "--model", "debug-tiny", "--prompt-len", "16",
           "--max-new-tokens", "8", "--serve-slots", "3", "--block-size",
           "4", "--prefill-chunk", "4", "--serve-temperature", "0.7",
           "--serve-seed", "7"] + extra_args
    if telemetry:
        cmd += ["--telemetry", telemetry]
    log_path = os.path.join(leg_dir, "run.log")
    with open(log_path, "ab") as log:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, stderr=log,
                              env=env, timeout=600)
    with open(log_path, "ab") as log:
        log.write(proc.stdout)
    if proc.returncode != 0:
        raise RuntimeError(f"bench fleet leg exited {proc.returncode} "
                           f"(log: {log_path})")
    for line in reversed(proc.stdout.decode().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"no JSON row in bench output (log: {log_path})")


def run_serve_engine_dead(workdir: str, verbose: bool = False) -> bool:
    """Engine failover under load — the serving half of the fault matrix.

    Oracle leg: fleet of 1, no faults, temperature 0.7. Fault leg: fleet
    of 2 with `engine_dead@2` fired in the dispatch loop — engine killed
    abruptly (state discarded wholesale) while requests are resident.
    The survivor must finish EVERY request with per-request token
    digests IDENTICAL to the oracle's (the (request id, token index)
    sampling-key fold makes re-dispatched continuations bit-exact at any
    temperature), show zero leaked blocks, and leave a
    serve_engine_dead flightdeck postmortem. A repeat of the fault leg
    must reproduce the digests exactly — recovery is deterministic, not
    merely successful."""
    fail = lambda msg: (print(f"[chaos-cli] serve_engine_dead: FAIL — "  # noqa: E731
                              f"{msg}"), False)[1]
    n_req = 8
    common = ["--requests", str(n_req)]

    oracle = _run_bench_fleet(os.path.join(workdir, "oracle"),
                              common + ["--fleet", "1"])
    tel_dir = os.path.join(workdir, "fault")
    fault = _run_bench_fleet(
        tel_dir, common + ["--fleet", "2", "--chaos", "engine_dead@2"],
        telemetry=os.path.join(tel_dir, "telemetry.jsonl"))
    if verbose:
        print(json.dumps(oracle), "\n", json.dumps(fault))

    if fault["engines_dead"] != 1:
        return fail(f"engines_dead {fault['engines_dead']} != 1 — the "
                    f"chaos kill did not land")
    if fault["completed"] != n_req or fault["shed"]:
        return fail(f"survivor finished {fault['completed']}/{n_req} "
                    f"(shed {fault['shed']}) — every request must "
                    f"complete on the surviving engine")
    if fault["redispatched"] < 1:
        return fail("no requests were re-dispatched — the engine died "
                    "with nothing in flight, so the scenario proved "
                    "nothing")
    if fault["request_digests"] != oracle["request_digests"]:
        bad = [k for k, v in oracle["request_digests"].items()
               if fault["request_digests"].get(k) != v]
        return fail(f"token parity broken after failover for request(s) "
                    f"{bad} — re-dispatched continuations must be "
                    f"bit-identical to the fault-free oracle")
    if fault["leaked_blocks"]:
        return fail(f"{fault['leaked_blocks']} leaked block(s) on "
                    f"survivor pools after the trace drained")

    pm_path = os.path.join(tel_dir, "flightdeck_postmortem.json")
    if not os.path.exists(pm_path):
        return fail(f"no flightdeck postmortem at {pm_path}")
    with open(pm_path) as f:
        pm = json.load(f)
    if pm.get("reason") != "serve_engine_dead":
        return fail(f"postmortem reason {pm.get('reason')!r} != "
                    f"'serve_engine_dead'")

    repeat = _run_bench_fleet(
        os.path.join(workdir, "repeat"),
        common + ["--fleet", "2", "--chaos", "engine_dead@2"])
    if repeat["request_digests"] != fault["request_digests"] \
            or repeat["redispatched"] != fault["redispatched"]:
        return fail("fault leg is not deterministic across repeats "
                    "(digests or redispatch count changed)")

    dead_engine = (pm.get("extra") or {}).get("engine")
    print(f"[chaos-cli] serve_engine_dead: OK — engine killed mid-burst "
          f"(postmortem engine {dead_engine}), survivor finished "
          f"{fault['completed']}/{n_req} requests bit-identical to the "
          f"single-engine oracle ({fault['redispatched']} re-dispatched), "
          f"0 leaked blocks, deterministic on repeat")
    return True


def run_serve_overload(workdir: str, verbose: bool = False) -> bool:
    """Deadline load shedding under a saturation burst.

    Both legs run a 1-slot engine on the same all-at-t=0 burst (10
    requests into one decode slot — a 10x overload). The no-deadline leg
    serves everything late; the deadline leg sheds the requests whose
    VIRTUAL-clock queue wait exceeds --deadline-ms. Pins: the shed set
    is non-empty and identical across repeats (the shed decision is a
    pure function of the trace), admitted requests' token digests match
    the no-deadline leg bit-for-bit (shedding neighbors must not perturb
    sampling), every admitted queue wait respects the deadline (the
    graceful-degradation SLO), and the shed seconds are booked to the
    telemetry ledger's `shed` category, rendered by telemetry_report."""
    fail = lambda msg: (print(f"[chaos-cli] serve_overload: FAIL — "  # noqa: E731
                              f"{msg}"), False)[1]
    n_req = 10
    deadline_ms = 6.0
    burst = ["--requests", str(n_req), "--serve-slots", "1",
             "--rate", "0"]

    unloaded = _run_bench_fleet(os.path.join(workdir, "no_deadline"),
                                burst + ["--fleet", "1"])
    tel_dir = os.path.join(workdir, "deadline")
    tel_path = os.path.join(tel_dir, "telemetry.jsonl")
    shedleg = _run_bench_fleet(
        tel_dir,
        burst + ["--fleet", "1", "--deadline-ms", str(deadline_ms)],
        telemetry=tel_path)
    if verbose:
        print(json.dumps(unloaded), "\n", json.dumps(shedleg))

    if not shedleg["shed"]:
        return fail("burst shed nothing — the overload never tripped "
                    "the deadline, scenario proves nothing")
    if shedleg["completed"] + shedleg["shed"] != n_req:
        return fail(f"completed {shedleg['completed']} + shed "
                    f"{shedleg['shed']} != {n_req} submitted")
    admitted = {k: v for k, v in shedleg["request_digests"].items()}
    mismatch = [k for k, v in admitted.items()
                if unloaded["request_digests"].get(k) != v]
    if mismatch:
        return fail(f"admitted request(s) {mismatch} decoded different "
                    f"tokens than the no-deadline leg — shedding "
                    f"neighbors must not perturb sampling")
    qw95 = shedleg["queue_wait_p95_ms"]
    if qw95 is None or qw95 > deadline_ms + 1e-6:
        return fail(f"admitted queue wait p95 {qw95} ms exceeds the "
                    f"{deadline_ms} ms deadline — admission let an "
                    f"expired request through")

    repeat = _run_bench_fleet(
        os.path.join(workdir, "repeat"),
        burst + ["--fleet", "1", "--deadline-ms", str(deadline_ms)])
    if repeat["shed_ids"] != shedleg["shed_ids"] \
            or repeat["request_digests"] != shedleg["request_digests"]:
        return fail(f"shed set not deterministic: {shedleg['shed_ids']} "
                    f"vs {repeat['shed_ids']} on repeat")

    import telemetry_report

    summary = telemetry_report.summarize(
        telemetry_report.load_events(tel_path))
    shed_s = (summary.get("categories") or {}).get("shed", 0.0)
    if not shed_s > 0.0:
        return fail("no seconds booked to the `shed` ledger category in "
                    "the telemetry stream")
    sv = summary.get("serving") or {}
    if sv.get("shed") != shedleg["shed"]:
        return fail(f"telemetry_report serving view shed {sv.get('shed')} "
                    f"!= bench row {shedleg['shed']}")
    if "shed" not in telemetry_report.render(summary):
        return fail("telemetry_report render does not show the shed row")

    print(f"[chaos-cli] serve_overload: OK — burst shed "
          f"{shedleg['shed']}/{n_req} deterministically "
          f"(ids {shedleg['shed_ids']}), admitted tokens bit-identical "
          f"to the no-deadline leg, queue wait p95 {qw95} ms <= "
          f"{deadline_ms} ms deadline, {round(shed_s, 4)}s booked to "
          f"`shed`")
    return True


def _postmortem_matches(save_dir: str, reason: str, fault_step: int):
    """The flightdeck flight recorder (telemetry/flightdeck/flight.py)
    must have left a postmortem dump next to the checkpoints whose
    reason and last recorded step match the injected fault — the
    abnormal-exit half of the flightdeck acceptance criteria."""
    path = os.path.join(save_dir, "flightdeck_postmortem.json")
    if not os.path.exists(path):
        return f"no flightdeck_postmortem.json under {save_dir}"
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return f"unreadable postmortem {path}: {e}"
    if doc.get("reason") != reason:
        return (f"postmortem reason {doc.get('reason')!r} != expected "
                f"{reason!r}")
    if doc.get("step") != fault_step:
        return (f"postmortem last recorded step {doc.get('step')!r} != "
                f"fault step {fault_step}")
    if not doc.get("steps"):
        return "postmortem carries an empty last-K-steps window"
    return None


def scenario_config(workdir: str, chaos_spec: str,
                    overrides: dict) -> dict:
    cfg = {
        "distributed": {"dp_size": 2, "tp_size": 2, "use_cpu": True},
        "model": {"name": "debug-tiny", "dtype": "float32"},
        "training": {"total_train_steps": STEPS, "seq_length": 32,
                     "micro_batch_size": 2,
                     "gradient_accumulation_steps": 1,
                     "remat": False, "seed": 5},
        "dataset": {"name": "synthetic", "num_workers": 0},
        "checkpoint": {"save_dir": os.path.join(workdir, "ckpt"),
                       "save_frequency": 2, "auto_resume": True},
        "logging": {"log_frequency": 1},
        "resilience": {"chaos": chaos_spec,
                       "retry_base_delay": 0.05, "retry_max_delay": 0.2},
    }
    for section, vals in overrides.items():
        cfg.setdefault(section, {}).update(vals)
    return cfg


def _run_trainer(cfg_path: str, log_path: str, extra_env: dict) -> int:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # trainer provisions its own device count
    for k in ("PICOTRON_COORDINATOR", "PICOTRON_NUM_PROCESSES",
              "PICOTRON_PROCESS_ID"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PICOTRON_PREFLIGHT"] = "0"  # scenario wall-time, not shardcheck's
    env.update(extra_env)
    with open(log_path, "ab") as log:
        return subprocess.run(
            [sys.executable, "-m", "picotron_tpu.train",
             "--config", cfg_path],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            timeout=600).returncode


def _final_meta(save_dir: str) -> dict:
    """meta.json of the newest step dir that has a committed state dir.
    The runs verified here exited 0, so the last save is finalized."""
    steps = sorted(
        int(m.group(1)) for d in os.listdir(save_dir)
        if (m := re.fullmatch(r"step_(\d+)", d))
        and os.path.isdir(os.path.join(save_dir, d, "state")))
    if not steps:
        raise FileNotFoundError(f"no checkpoints under {save_dir}")
    with open(os.path.join(save_dir, f"step_{steps[-1]:08d}",
                           "meta.json")) as f:
        return json.load(f)


def run_scenario(name: str, workdir: str, verbose: bool = False) -> bool:
    sc = SCENARIOS[name]
    fail = lambda msg: (print(f"[chaos-cli] {name}: FAIL — {msg}"),  # noqa: E731
                        False)[1]

    # Fault-free baseline: what "no training progress lost" means.
    base_dir = os.path.join(workdir, "baseline")
    os.makedirs(base_dir, exist_ok=True)
    base_cfg = scenario_config(base_dir, "", sc.overrides)
    base_path = os.path.join(base_dir, "config.json")
    with open(base_path, "w") as f:
        json.dump(base_cfg, f)
    rc = _run_trainer(base_path, os.path.join(base_dir, "run.log"), {})
    if rc != 0:
        return fail(f"baseline run exited {rc}")
    base_meta = _final_meta(base_cfg["checkpoint"]["save_dir"])

    # Fault run under supervision.
    fault_dir = os.path.join(workdir, "fault")
    os.makedirs(fault_dir, exist_ok=True)
    cfg = scenario_config(fault_dir, sc.chaos, sc.overrides)
    cfg_path = os.path.join(fault_dir, "config.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    log_path = os.path.join(fault_dir, "run.log")
    exits = []
    for attempt in range(sc.max_restarts + 1):
        # Restarts disable injection via the env override — a resubmitted
        # job does not re-live the environmental fault.
        extra = {} if attempt == 0 else {"PICOTRON_CHAOS": ""}
        rc = _run_trainer(cfg_path, log_path, extra)
        exits.append(rc)
        if attempt == 0 and sc.check_after_fault is not None:
            # Inspect the faulted store BEFORE any restart repairs it
            # (e.g. ckpt_doctor over the really-corrupted lineage).
            err = sc.check_after_fault(cfg["checkpoint"]["save_dir"])
            if err:
                return fail(err)
        if rc == 0:
            break
        if rc not in sc.expect_exits:
            return fail(f"unexpected exit {rc} (allowed: 0 or "
                        f"{sc.expect_exits}); exits so far {exits}")
    if exits[-1] != 0:
        return fail(f"did not recover within {sc.max_restarts} restarts "
                    f"(exits {exits})")

    with open(log_path) as f:
        log_text = f.read()
    if verbose:
        print(log_text)
    if not re.search(sc.marker, log_text):
        return fail(f"recovery marker /{sc.marker}/ absent from {log_path}")
    meta = _final_meta(cfg["checkpoint"]["save_dir"])
    for key in ("step", "trained_tokens"):
        if meta[key] != base_meta[key]:
            return fail(f"final {key} {meta[key]} != fault-free baseline "
                        f"{base_meta[key]}")
    print(f"[chaos-cli] {name}: OK — exits {exits}, final step "
          f"{meta['step']} / {meta['trained_tokens']} tokens match "
          f"baseline")
    return True


# Scenarios with bespoke runners (multiple per-leg configs, offline CLI
# steps): registered next to the Scenario table so --list/--scenario/--all
# treat them uniformly.
CUSTOM_SCENARIOS: dict[str, tuple[Callable, str]] = {
    "dp_resize": (run_dp_resize,
                  "elastic scale-out: SIGKILL a dp=2 run, re-stamp to "
                  "dp=1 offline, SIGKILL again, finish at dp=4 via "
                  "checkpoint.elastic; loss-trajectory parity vs the "
                  "dp=2 baseline, resize seconds booked"),
    "pp_resize": (run_pp_resize,
                  "elastic pipeline resize: SIGKILL a pp=2 MPMD run, "
                  "re-stamp to pp=1 offline (--pp), SIGKILL again, "
                  "finish at pp=2 via checkpoint.elastic; loss parity "
                  "vs the pp=2 baseline, resize booked, rebuilt stage "
                  "programs proven compile-once"),
    "slice_lost": (run_slice_lost,
                   "whole-slice loss on a 2-slice job (hierarchical dp "
                   "grads live): slice_lost@3 SIGKILLs with the slice "
                   "named, tools/elastic_resize.py --slices 1 re-stamps "
                   "the store, the survivors finish at dp=1 via "
                   "checkpoint.elastic; loss parity vs the single-slice "
                   "baseline, resize booked"),
    "mpmd_sigterm": (run_mpmd_sigterm,
                     "mid-schedule MPMD faults: SIGTERM at a named "
                     "(stage, tick, op) drains to the step boundary "
                     "(exit 75, zero replayed steps on resume); forced "
                     "hang is watchdog-reported naming the live op"),
    "serve_engine_dead": (run_serve_engine_dead,
                          "kill 1 of 2 serving replicas mid-burst: the "
                          "survivor finishes every request bit-identical "
                          "to the single-engine oracle (temp 0.7), zero "
                          "leaked blocks, serve_engine_dead postmortem, "
                          "deterministic on repeat"),
    "serve_overload": (run_serve_overload,
                       "deadline shedding under a 10x burst: "
                       "deterministic shed set, admitted tokens match "
                       "the no-deadline leg bit-for-bit, queue wait p95 "
                       "within the deadline, shed seconds booked to the "
                       "`shed` ledger category"),
}


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description="picotron-tpu fault-recovery scenario runner")
    ap.add_argument("--scenario", action="append", default=[],
                    choices=sorted(set(SCENARIOS) | set(CUSTOM_SCENARIOS)),
                    help="scenario to run (repeatable)")
    ap.add_argument("--all", action="store_true",
                    help="run every scenario")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    ap.add_argument("--workdir", default=None,
                    help="scratch directory (default: a fresh tempdir)")
    ap.add_argument("--verbose", action="store_true",
                    help="print the fault run's log")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list:
        for name, sc in SCENARIOS.items():
            print(f"{name:14s} chaos={sc.chaos!r:24s} {sc.note}")
        for name, (_fn, note) in CUSTOM_SCENARIOS.items():
            print(f"{name:14s} chaos={'custom':26s} {note}")
        return 0
    names = sorted(set(args.scenario)) if args.scenario else []
    if args.all:
        names = sorted(set(SCENARIOS) | set(CUSTOM_SCENARIOS))
    if not names:
        build_parser().error("pick --scenario NAME (repeatable), --all, "
                             "or --list")
    workdir = args.workdir
    if workdir is None:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="picotron-chaos-")
    ok = True
    for name in names:
        sub = os.path.join(workdir, name)
        os.makedirs(sub, exist_ok=True)
        if name in CUSTOM_SCENARIOS:
            ok &= CUSTOM_SCENARIOS[name][0](sub, verbose=args.verbose)
        else:
            ok &= run_scenario(name, sub, verbose=args.verbose)
    print(f"[chaos-cli] {'all scenarios recovered' if ok else 'FAILURES'} "
          f"(workdir {workdir})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
