#!/usr/bin/env python
"""Experiment job scheduler — parity with the reference's submit_slurm_jobs.py.

Walks an experiment directory (one subdir per run, each holding a
`config.json` from tools/create_config.py) and drives each job through the
reference's `status.txt` state machine INIT -> PENDING -> RUNNING ->
{COMPLETED, FAIL, OOM, TIMEOUT} (ref: submit_slurm_jobs.py:8-16,25-53), with
`--only fail|oom|timeout|pending|init` re-filtering and resubmission
(ref: submit_slurm_jobs.py:157-172) and a status table printer
(ref: submit_slurm_jobs.py:116-147).

Launchers:
- `--launcher local` (default): runs each job as a subprocess on this host,
  tees output to train.log, and classifies the outcome by exit code + log
  grep — the reference does its post-mortem classification the same way
  (OutOfMemoryError / illegal memory access / Timeout greps,
  ref: template/base_job.slurm:82-94; on TPU the OOM signature is XLA's
  RESOURCE_EXHAUSTED).
- `--launcher slurm`: renders a batch script per job (one process per TPU
  host; `jax.distributed.initialize` picks up the SLURM environment, see
  picotron_tpu.mesh.multihost_initialize) and submits via sbatch with
  optional `--dependency afterany` chaining (ref: submit_slurm_jobs.py:104-113).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

# ref: submit_slurm_jobs.py:8-16
STATUSES = ("init", "pending", "running", "completed", "fail", "oom", "timeout")

OOM_PATTERNS = ("RESOURCE_EXHAUSTED", "Out of memory", "OutOfMemoryError")
TIMEOUT_PATTERNS = ("DEADLINE_EXCEEDED", "Timeout", "timed out")

# The grep alternations are rendered from the same pattern constants the
# local launcher classifies with, so both launchers agree on oom/timeout.
SLURM_TEMPLATE = """#!/bin/bash
#SBATCH --job-name={name}
#SBATCH --nodes={nodes}
#SBATCH --ntasks-per-node=1
#SBATCH --output={run_dir}/train.log
#SBATCH --time={time_limit}
cd "{repo_root}" || {{ echo fail > "{run_dir}/status.txt"; exit 1; }}
echo running > {run_dir}/status.txt
srun python -m picotron_tpu.train --config {run_dir}/config.json
code=$?
if [ $code -eq 0 ]; then echo completed > {run_dir}/status.txt
elif grep -qE '{oom_re}' {run_dir}/train.log; then echo oom > {run_dir}/status.txt
elif grep -qE '{timeout_re}' {run_dir}/train.log; then echo timeout > {run_dir}/status.txt
else echo fail > {run_dir}/status.txt
fi
"""


class Job:
    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        self.name = os.path.basename(run_dir.rstrip("/"))
        self.config = os.path.join(run_dir, "config.json")
        self.status_file = os.path.join(run_dir, "status.txt")
        if not os.path.exists(self.status_file):
            self.set_status("init")

    @property
    def status(self) -> str:
        try:
            with open(self.status_file) as f:
                s = f.read().strip().lower()
            return s if s in STATUSES else "init"
        except OSError:
            return "init"

    def set_status(self, s: str) -> None:
        with open(self.status_file, "w") as f:
            f.write(s + "\n")

    def classify(self, returncode: int) -> str:
        """Exit-code + log-grep post-mortem (ref: base_job.slurm:82-94)."""
        if returncode == 0:
            return "completed"
        log_path = os.path.join(self.run_dir, "train.log")
        try:
            with open(log_path, errors="replace") as f:
                f.seek(max(0, os.path.getsize(log_path) - 50_000))
                tail = f.read()
        except OSError:
            tail = ""
        if any(p in tail for p in OOM_PATTERNS):
            return "oom"
        if any(p in tail for p in TIMEOUT_PATTERNS):
            return "timeout"
        return "fail"


def discover_jobs(exp_dir: str) -> list[Job]:
    jobs = []
    for name in sorted(os.listdir(exp_dir)):
        run_dir = os.path.join(exp_dir, name)
        if os.path.isdir(run_dir) and os.path.exists(
                os.path.join(run_dir, "config.json")):
            jobs.append(Job(run_dir))
    return jobs


def run_local(job: Job, timeout: float | None) -> str:
    job.set_status("running")
    log_path = os.path.join(job.run_dir, "train.log")
    t0 = time.time()
    with open(log_path, "w") as log:
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "picotron_tpu.train",
                 "--config", job.config],
                stdout=log, stderr=subprocess.STDOUT,
                cwd=REPO_ROOT, timeout=timeout,
            )
            status = job.classify(proc.returncode)
        except subprocess.TimeoutExpired:
            status = "timeout"
    job.set_status(status)
    print(f"  {job.name}: {status} ({time.time() - t0:.0f}s)")
    return status


def render_slurm(job: Job, nodes: int, time_limit: str) -> str:
    """Render the job's batch script to <run_dir>/job.slurm and return the
    path (ref: submit_slurm_jobs.py:68-103 renders from its jinja template
    the same way; here the grep alternations come from the exact pattern
    constants the local launcher classifies with)."""
    script = os.path.join(job.run_dir, "job.slurm")
    with open(script, "w") as f:
        f.write(SLURM_TEMPLATE.format(
            name=job.name, nodes=nodes, run_dir=os.path.abspath(job.run_dir),
            time_limit=time_limit, repo_root=REPO_ROOT,
            oom_re="|".join(OOM_PATTERNS),
            timeout_re="|".join(TIMEOUT_PATTERNS)))
    return script


def submit_slurm(job: Job, nodes: int, time_limit: str,
                 depend_on: str | None) -> str | None:
    script = render_slurm(job, nodes, time_limit)
    cmd = ["sbatch", "--parsable"]
    if depend_on:
        cmd.append(f"--dependency=afterany:{depend_on}")  # ref: :104-113
    cmd.append(script)
    out = subprocess.run(cmd, capture_output=True, text=True)
    if out.returncode != 0:
        print(f"  {job.name}: sbatch failed: {out.stderr.strip()}")
        job.set_status("fail")
        return None
    job.set_status("pending")
    job_id = out.stdout.strip().split(";")[0]
    print(f"  {job.name}: submitted as {job_id}")
    return job_id


def watch_queue(exp_dir: str, job_ids: dict[str, str], interval: float = 30.0,
                max_polls: int | None = None) -> None:
    """Poll squeue and flip each submitted job's status.txt PENDING ->
    RUNNING the moment SLURM starts it (the reference runs this from a
    background poller inside the batch script, ref: base_job.slurm:16-32;
    here it is the submitter's loop, which also covers jobs that die before
    their script's first line — those leave the queue without ever writing
    'running', and the poll marks them 'fail'). Returns when every watched
    job has left the queue."""
    watched = dict(job_ids)  # name -> slurm job id
    polls = 0
    consecutive_failures = 0
    while watched and (max_polls is None or polls < max_polls):
        out = subprocess.run(
            ["squeue", "--noheader", "--format=%i %T",
             "--jobs", ",".join(watched.values())],
            capture_output=True, text=True)
        if out.returncode != 0:
            # transient slurmctld hiccup: an empty answer here must NOT be
            # read as "every job left the queue" (that would mark pending
            # jobs fail); skip the poll and retry — but a PERSISTENT
            # failure (e.g. "Invalid job id": the jobs completed and
            # slurmctld purged them past MinJobAge) must not loop forever:
            # give up after a few polls and leave status.txt to the
            # scripts' own epilogues (code review r4)
            consecutive_failures += 1
            if consecutive_failures >= 5:
                print(f"  watch: squeue failing persistently "
                      f"({out.stderr.strip()[:120]}); stopping the watcher "
                      f"for {sorted(watched)}")
                return
            polls += 1
            time.sleep(interval)
            continue
        consecutive_failures = 0
        states = {}
        for line in out.stdout.splitlines():
            parts = line.split()
            if len(parts) >= 2:
                states[parts[0]] = parts[1]
        for name, jid in list(watched.items()):
            job = Job(os.path.join(exp_dir, name))
            st = states.get(jid)
            if st == "RUNNING" and job.status == "pending":
                job.set_status("running")
            elif st is None:
                # left the queue: the script's epilogue normally wrote the
                # terminal status; a job killed before its first line never
                # did — 'pending' with no queue entry means it never
                # started, don't leave it pending forever
                if job.status == "pending":
                    job.set_status("fail")
                del watched[name]
        polls += 1
        if watched:
            time.sleep(interval)


def print_table(jobs: list[Job]) -> None:
    """ref: submit_slurm_jobs.py:116-147."""
    counts: dict[str, int] = {}
    width = max((len(j.name) for j in jobs), default=4)
    print(f"{'run'.ljust(width)}  status")
    for j in jobs:
        s = j.status
        counts[s] = counts.get(s, 0) + 1
        print(f"{j.name.ljust(width)}  {s}")
    print("--")
    print("  ".join(f"{k}:{v}" for k, v in sorted(counts.items())))


def main() -> None:
    ap = argparse.ArgumentParser(description="picotron-tpu job scheduler")
    ap.add_argument("exp_dir")
    ap.add_argument("--launcher", choices=["local", "slurm"], default="local")
    ap.add_argument("--only", choices=list(STATUSES), default=None,
                    help="resubmit only jobs currently in this status "
                         "(ref: submit_slurm_jobs.py --only)")
    ap.add_argument("--status", action="store_true",
                    help="print the status table and exit")
    ap.add_argument("--nodes", type=int, default=1)
    ap.add_argument("--time-limit", default="02:00:00")
    ap.add_argument("--job-timeout", type=float, default=None,
                    help="per-job wall-clock limit for the local launcher (s)")
    ap.add_argument("--chain", action="store_true",
                    help="chain slurm jobs with --dependency=afterany")
    ap.add_argument("--dry-run", action="store_true",
                    help="slurm launcher only: render each job's batch "
                         "script to <run_dir>/job.slurm and print it "
                         "WITHOUT submitting (no sbatch call, status.txt "
                         "untouched) — inspect exactly what would run")
    ap.add_argument("--watch", action="store_true",
                    help="slurm launcher only: after submitting, poll "
                         "squeue and flip status.txt pending -> running "
                         "as jobs start (jobs that die before their first "
                         "script line are marked fail; ref: "
                         "base_job.slurm:16-32's background poller)")
    ap.add_argument("--watch-interval", type=float, default=30.0)
    args = ap.parse_args()
    if args.dry_run and args.launcher != "slurm":
        ap.error("--dry-run renders sbatch scripts; use with "
                 "--launcher slurm")

    jobs = discover_jobs(args.exp_dir)
    if not jobs:
        print(f"no runs with config.json under {args.exp_dir}")
        return
    if args.status:
        print_table(jobs)
        return

    if args.only:
        jobs = [j for j in jobs if j.status == args.only]
    else:
        # default: everything not already completed or in flight
        jobs = [j for j in jobs if j.status in ("init", "fail", "oom", "timeout")]
    print(f"{len(jobs)} job(s) to run")

    prev_id = None
    submitted: dict[str, str] = {}
    for job in jobs:
        if args.launcher == "local":
            run_local(job, args.job_timeout)
        elif args.dry_run:
            script = render_slurm(job, args.nodes, args.time_limit)
            print(f"  {job.name}: rendered {script}")
            with open(script) as f:
                print("    | " + f.read().rstrip().replace("\n", "\n    | "))
        else:
            new_id = submit_slurm(job, args.nodes, args.time_limit,
                                  prev_id if args.chain else None)
            if new_id is not None:
                # A failed submission keeps the previous anchor so later
                # jobs stay chained (serialized) rather than all starting
                # concurrently.
                prev_id = new_id
                submitted[job.name] = new_id

    if args.watch and submitted:
        watch_queue(args.exp_dir, submitted, interval=args.watch_interval)
    print_table(discover_jobs(args.exp_dir))


if __name__ == "__main__":
    main()
