#!/usr/bin/env python
"""Real-TPU smoke test for the Pallas flash-attention kernels.

Runs the compiled (non-interpret) kernels on the local chip and checks
forward/backward against the jnp reference, then prints timings. The pytest
suite covers the same kernels in interpreter mode on CPU; this script is the
on-hardware check (run it plainly: `python tools/flash_smoke.py`).
"""

import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from picotron_tpu.ops.attention import sdpa_attention  # noqa: E402
from picotron_tpu.ops.flash_attention import flash_attention  # noqa: E402


def main():
    print("backend:", jax.default_backend(), jax.devices()[0].device_kind)
    ks = jax.random.split(jax.random.key(0), 3)
    b, s, hq, hkv, d = 2, 2048, 16, 4, 64
    q = jax.random.normal(ks[0], (b, s, hq, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, hkv, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, hkv, d), jnp.bfloat16)

    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                interpret=False))
    r = jax.jit(lambda q, k, v: sdpa_attention(q, k, v, causal=True))
    got = jax.block_until_ready(f(q, k, v)).astype(jnp.float32)
    want = jax.block_until_ready(r(q, k, v)).astype(jnp.float32)
    print("fwd maxdiff:", float(jnp.abs(got - want).max()))

    def floss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=False).astype(jnp.float32) ** 2)

    def rloss(q, k, v):
        return jnp.sum(sdpa_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)

    gf = jax.jit(jax.grad(floss, (0, 1, 2)))
    gr = jax.jit(jax.grad(rloss, (0, 1, 2)))
    a = jax.block_until_ready(gf(q, k, v))
    b_ = jax.block_until_ready(gr(q, k, v))
    for x, y, n in zip(a, b_, "qkv"):
        print(f"d{n} maxdiff:",
              float(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32)).max()))

    # fused RoPE (rotation inside the kernels) vs jnp rotate + plain kernel
    from picotron_tpu.ops.rope import apply_rope, rope_tables
    cos, sin = rope_tables(s, d)

    def fused(q, k, v):
        return flash_attention(q, k, v, causal=True, rope=(cos, sin),
                               interpret=False).astype(jnp.float32)

    def unfused(q, k, v):
        return flash_attention(apply_rope(q, cos, sin),
                               apply_rope(k, cos, sin), v, causal=True,
                               interpret=False).astype(jnp.float32)

    got = jax.block_until_ready(jax.jit(fused)(q, k, v))
    want = jax.block_until_ready(jax.jit(unfused)(q, k, v))
    print("fused-rope fwd maxdiff:", float(jnp.abs(got - want).max()))
    gfr = jax.jit(jax.grad(lambda *a: jnp.sum(fused(*a) ** 2), (0, 1, 2)))
    gur = jax.jit(jax.grad(lambda *a: jnp.sum(unfused(*a) ** 2), (0, 1, 2)))
    for x, y, n in zip(jax.block_until_ready(gfr(q, k, v)),
                       jax.block_until_ready(gur(q, k, v)), "qkv"):
        print(f"fused-rope d{n} maxdiff:",
              float(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32)).max()))

    def timeit(fn, n=20):
        jax.block_until_ready(fn(q, k, v))
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(q, k, v)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / n * 1e3

    print(f"flash fwd {timeit(f):.2f}ms  sdpa fwd {timeit(r):.2f}ms")
    print(f"flash fwd+bwd {timeit(gf):.2f}ms  sdpa fwd+bwd {timeit(gr):.2f}ms")


if __name__ == "__main__":
    main()
