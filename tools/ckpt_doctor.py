#!/usr/bin/env python
"""fsck for a checkpoint save_dir: verify the full lineage, optionally GC.

Walks every `step_<n>` directory and reports a per-step verdict:

  verified     durable (Orbax-finalized) and every file matches the commit
               manifest (bytes + content digest)
  legacy       durable, restorable, but predates commit manifests (no
               integrity claim beyond "meta.json parses")
  corrupt      manifest/meta torn, a listed file missing, or bytes/digest
               mismatch — the failing leaf/file is named
  not-durable  the save never finalized (crashed/in-flight async write)

Exit code: 0 when no step is corrupt, 1 otherwise — scriptable as a
post-incident check or a cron'd store audit.

Usage:

  python tools/ckpt_doctor.py SAVE_DIR                # table
  python tools/ckpt_doctor.py SAVE_DIR --json         # machine-readable
  python tools/ckpt_doctor.py SAVE_DIR --markdown     # paste into a report
  python tools/ckpt_doctor.py SAVE_DIR --shallow      # sizes only, no hashing
  python tools/ckpt_doctor.py SAVE_DIR --gc --keep-last 3 --dry-run
  python tools/ckpt_doctor.py SAVE_DIR --gc --keep-last 3 --keep-every 1000

GC applies the same retention policy the trainer's in-loop GC uses
(picotron_tpu/ckpt_integrity.retention_plan) and the same protection: the
last verified step survives regardless of --keep-last.

The topology column is the routing surface for elastic re-stamps: a step
rewritten by `tools/elastic_resize.py` (dp, pp and/or slices) reports its
NEW topology here — the store simply is that shape afterwards — so "which
pp does this checkpoint restore at" is answered by this table, not by the
config that originally trained it. Multi-slice checkpoints carry a
`slicesN` suffix (and a `slices` field in --json): after a slice loss,
the table shows which steps already restore at the surviving count.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from picotron_tpu.checkpoint import CheckpointManager  # noqa: E402
from picotron_tpu.config import CheckpointConfig, Config  # noqa: E402
from picotron_tpu.resilience import elastic  # noqa: E402


def _manager(save_dir: str, keep_last: int = 0,
             keep_every: int = 0) -> CheckpointManager:
    cfg = Config(checkpoint=CheckpointConfig(
        save_dir=save_dir, keep_last=keep_last, keep_every=keep_every))
    return CheckpointManager(cfg, directory=save_dir)


def scan(save_dir: str, deep: bool = True,
         only_step=None) -> list[dict]:
    """Per-step verdict rows, oldest first. `deep=False` skips content
    hashing (size/existence checks only — fast triage on huge stores)."""
    mgr = _manager(save_dir)
    rows = []
    for step in mgr.steps():
        if only_step is not None and step != only_step:
            continue
        durable = mgr._is_durable(f"step_{step:08d}")
        res = mgr.verify_step(step, deep=deep)
        if res.status == "corrupt":
            verdict = "corrupt"
        elif not durable:
            verdict = "not-durable"
        else:
            verdict = res.status  # verified | legacy
        man = res.manifest or {}
        rows.append({
            "step": step,
            "verdict": verdict,
            "durable": durable,
            "files": man.get("file_count"),
            "bytes": man.get("total_bytes"),
            "algo": man.get("algo"),
            # source topology the step was saved under (manifest field,
            # meta.json fallback for legacy steps) — what an operator
            # must know before attempting an elastic resize
            "topology": elastic.saved_topology(mgr._step_dir(step)),
            "failures": list(res.failures),
        })
    return rows


def render(rows: list[dict], save_dir: str, markdown: bool = False) -> str:
    lines = []
    if markdown:
        lines.append(f"## ckpt_doctor — `{save_dir}`")
        lines.append("")
        lines.append("| step | verdict | topology | files | bytes | "
                     "failures |")
        lines.append("|---:|---|---|---:|---:|---|")
        for r in rows:
            fails = "; ".join(r["failures"][:3]) or ""
            topo = (elastic.describe_topology(r["topology"])
                    if r.get("topology") else "-")
            lines.append(f"| {r['step']} | {r['verdict']} | {topo} | "
                         f"{r['files'] or ''} | {r['bytes'] or ''} | "
                         f"{fails} |")
    else:
        lines.append(f"[ckpt_doctor] {save_dir}: {len(rows)} step dir(s)")
        for r in rows:
            topo = (elastic.describe_topology(r["topology"])
                    if r.get("topology") else "-")
            extra = (f"  ({r['files']} files, {r['bytes']} bytes, "
                     f"{r['algo']})" if r["files"] is not None else "")
            lines.append(f"  step {r['step']:>8d}  {r['verdict']:<11s} "
                         f"[{topo}]{extra}")
            for f in r["failures"][:5]:
                lines.append(f"           !! {f}")
            if len(r["failures"]) > 5:
                lines.append(f"           .. and "
                             f"{len(r['failures']) - 5} more")
    n_corrupt = sum(r["verdict"] == "corrupt" for r in rows)
    valid = [r["step"] for r in rows if r["verdict"] in ("verified",
                                                         "legacy")]
    tail = (f"{n_corrupt} corrupt, {len(valid)} restorable"
            + (f", latest valid step {max(valid)}" if valid else ""))
    lines.append(f"**{tail}**" if markdown else f"[ckpt_doctor] {tail}")
    return "\n".join(lines)


def run_gc(save_dir: str, keep_last: int, keep_every: int,
           dry_run: bool) -> dict:
    mgr = _manager(save_dir, keep_last=keep_last, keep_every=keep_every)
    return mgr.gc(dry_run=dry_run)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description="verify a checkpoint save_dir's lineage; optional GC")
    ap.add_argument("save_dir", help="checkpoint directory "
                    "(contains step_<n> subdirs)")
    ap.add_argument("--step", type=int, default=None,
                    help="check only this step")
    ap.add_argument("--shallow", action="store_true",
                    help="existence+size checks only (skip content hashing)")
    fmt = ap.add_mutually_exclusive_group()
    fmt.add_argument("--json", action="store_true", help="JSON report")
    fmt.add_argument("--markdown", action="store_true",
                     help="markdown report")
    ap.add_argument("--gc", action="store_true",
                    help="apply the retention policy after the scan")
    ap.add_argument("--keep-last", type=int, default=3,
                    help="GC: newest steps to keep (default 3)")
    ap.add_argument("--keep-every", type=int, default=0,
                    help="GC: additionally keep steps divisible by this")
    ap.add_argument("--dry-run", action="store_true",
                    help="GC: report the plan, delete nothing")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not os.path.isdir(args.save_dir) and "://" not in args.save_dir:
        print(f"[ckpt_doctor] no such directory: {args.save_dir}",
              file=sys.stderr)
        return 2
    rows = scan(args.save_dir, deep=not args.shallow, only_step=args.step)
    gc_result = None
    if args.gc:
        if args.keep_last < 1:
            build_parser().error("--gc needs --keep-last >= 1")
        gc_result = run_gc(args.save_dir, args.keep_last, args.keep_every,
                           args.dry_run)
        if not args.dry_run:  # re-scan: the report shows what survived
            rows = [r for r in rows if r["step"] in gc_result["kept"]]
    if args.json:
        print(json.dumps({"save_dir": args.save_dir, "steps": rows,
                          "gc": gc_result}, indent=2))
    else:
        print(render(rows, args.save_dir, markdown=args.markdown))
        if gc_result is not None:
            verb = "would delete" if args.dry_run else "deleted"
            print(f"[ckpt_doctor] gc: kept {gc_result['kept']}, {verb} "
                  f"{gc_result['deleted']}")
    return 1 if any(r["verdict"] == "corrupt" for r in rows) else 0


if __name__ == "__main__":
    sys.exit(main())
